"""Whole-step training compilation: fwd + bwd + optimizer in ONE XLA program.

The reference composes thunder-compiled fwd/bwd with torch autograd and a
separate optimizer step, then optionally wraps regions in CUDA graphs
(thunder/transforms/cudagraph.py:229) to kill dispatch overhead. On TPU the
idiomatic equivalent is stronger: the generated forward and backward callables
are pure-jax, so the full step — prologue-validated forward, backward,
optimizer update — is traced into a single ``jax.jit`` program with buffer
donation on params/optimizer state. XLA then schedules the whole step with
one dispatch and no host round-trips."""
from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .nn.module import Module, ThunderModule, structure_epoch
from .observability import events as _obs
from .observability import flight_recorder as _obs_flight
from .observability import memory_watch as _obs_mem
from .observability import metrics as _obs_metrics
from .observability import runtime as _obs_runtime
from .observability import telemetry as _obs_tel
from .optim import global_norm as _global_norm
from .robustness import faults as _rb_faults


def _stable_val(v, depth: int = 0) -> str:
    """Deterministic string for a config value: simple types repr directly,
    containers recurse, other objects render as type + their own stable
    attrs (NEVER the default repr — it embeds addresses and would make
    cache keys miss every process; silently dropping attrs is worse: two
    semantically different configs would collide on the same key)."""
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return repr(v)
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_stable_val(e, depth + 1) for e in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(f"{k!r}:{_stable_val(val, depth + 1)}"
                              for k, val in sorted(v.items(), key=lambda kv: str(kv[0]))) + "}"
    if depth >= 3:
        return f"<{type(v).__name__}>"
    try:
        attrs = vars(v)
    except TypeError:
        # dtype-like singletons print stably (e.g. "dtypes.bfloat16")
        return f"{type(v).__name__}:{v!s}"
    return (f"{type(v).__name__}(" +
            ",".join(f"{k}={_stable_val(val, depth + 1)}"
                     for k, val in sorted(attrs.items())) + ")")


def _safe_repr(obj) -> str:
    """Deterministic config repr for cache keys (see _stable_val)."""
    return _stable_val(obj)


def _aot_fallback_errors() -> tuple:
    """Exception types a stale/mismatched AOT-deserialized executable raises:
    argument-spec mismatches surface as TypeError/ValueError from the jax
    Compiled call layer, ABI/runtime mismatches as XlaRuntimeError. Anything
    else (a genuine bug) must propagate, not silently retrace."""
    errs: list[type] = [TypeError, ValueError]
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        errs.append(XlaRuntimeError)
    except Exception:
        errs.append(RuntimeError)
    return tuple(errs)


_AOT_FALLBACK_ERRORS = _aot_fallback_errors()

# shared reusable no-op span for disabled-observability hot paths
_NULL_SPAN = contextlib.nullcontext()


class _CompiledWithFallback:
    """A serialized-executable step that transparently falls back to the
    retrace path (the jax.jit fn) if inputs stop matching the compiled
    shapes — AOT warm starts must never change semantics. The fallback is
    never silent: it warns and emits a reason-coded recompile event, since
    a persistently-failing executable would otherwise mask every runtime
    error as a recompile."""

    def __init__(self, compiled, jit_fn_factory):
        self._compiled = compiled
        self._factory = jit_fn_factory
        self._jit_fn = None

    def __call__(self, *args):
        if self._compiled is not None:
            try:
                return self._compiled(*args)
            except _AOT_FALLBACK_ERRORS as e:
                import warnings

                self._compiled = None
                warnings.warn(
                    f"AOT-cached executable failed at run time "
                    f"({type(e).__name__}: {e}); falling back to the retrace "
                    f"path. Delete the TT_AOT_CACHE_DIR entry if this "
                    f"persists.", stacklevel=2)
                _obs_metrics.record_recompile(
                    _obs_metrics.REASON_FALLBACK,
                    error=f"{type(e).__name__}: {e}"[:300])
        if self._jit_fn is None:
            self._jit_fn = self._factory()
        return self._jit_fn(*args)


class TrainStep:
    """step(*batch) -> loss; updates module parameters in place.

    loss_module: a Module whose forward(*batch) returns a scalar loss.
    """

    def __init__(self, loss_module, optimizer, *, donate: bool = True, mesh_plan=None,
                 guard=None, slo=None, buckets=None, bucket_pad=None,
                 bucket_axis: int = 1):
        from . import jit as _jit

        if isinstance(loss_module, Module):
            loss_module = _jit(loss_module)
        if not isinstance(loss_module, ThunderModule):
            raise TypeError("TrainStep expects a Module or ThunderModule computing a scalar loss")
        self.tmodule = loss_module
        self.optimizer = optimizer
        self.donate = donate
        self.mesh_plan = mesh_plan  # set by parallel transforms for sharded steps
        # robustness layer: a StepGuard changes the traced program (finite
        # gate + grad-norm metric), so it is fixed at construction; the
        # CheckpointManager attaches itself via manager.attach(step)
        self._guard = guard
        # live telemetry: an SLOPolicy (observability/slo.py) gets a
        # sliding-window monitor over step wall time and tokens/s (via
        # policy.tokens_per_step); breaches land on the bus reason-coded.
        # Without one the per-step cost is a single `is None` test.
        self.slo_monitor = None
        if slo is not None:
            from .observability.slo import SLOMonitor

            if slo.min_tokens_per_s is not None and not slo.tokens_per_step:
                # a training step has no per-request token count; without
                # tokens_per_step the throughput target would silently never
                # be evaluated — the operator would believe it enforced
                raise ValueError(
                    "SLOPolicy(min_tokens_per_s=...) on a TrainStep needs "
                    "tokens_per_step=<batch tokens per step> to compute "
                    "throughput")
            self.slo_monitor = SLOMonitor(slo, source="training")
        # bucketed lowering (compile_service/buckets.py): with a BucketLadder
        # attached, batch args pad along `bucket_axis` to the next rung
        # before dispatch, so every length in a bucket shares ONE compiled
        # (and one stored) artifact — the trainer-side collapse of the
        # serving engine's prompt buckets. bucket_pad maps positional index
        # (or kwarg name) -> fill value; causal-LM targets use -100 so
        # ltorch.cross_entropy masks padded positions out of loss AND grads.
        self.buckets = buckets
        self.bucket_pad = dict(bucket_pad or {})
        self.bucket_axis = bucket_axis
        self._jitted: Optional[Callable] = None
        self.opt_state = None
        self._step_count = 0
        # steady-state dispatch fast path: the param split (an O(model) tree
        # walk + requires_grad filter) is cached under the module structure
        # epoch; _split_walks counts full walks for regression tests
        self._split_cache = None
        self._split_walks = 0
        self._mode_epoch = None
        # built programs are mode-specific (train/eval flips change the traced
        # program — BatchNorm/Dropout branches — without changing any input
        # metadata); key the whole compiled-program set on the module-mode
        # tuple so a flip selects/rebuilds instead of silently running stale
        self._mode_cache: dict = {}
        self._active_mode = self._mode_key()

    # every compiled artifact + trace-derived metadata that depends on the
    # module's train/eval mode (the FSDP param gather is shape-only and is
    # deliberately NOT mode-keyed)
    _MODE_STATE_ATTRS = (
        "_jitted", "_vag", "_effect_keys", "_micro_jitted", "_jitted_with_acc_fn",
        "_vag_nosync", "_micro_dist_jitted", "_fold_dist_jitted", "_vag_full",
        "_micro_fsdp_jitted", "_fold_fsdp_jitted",
    )

    def _mode_key(self):
        extra = getattr(self.tmodule._cfn._cd.fn, "__cache_extra__", None)
        return extra() if extra is not None else None

    def _sync_mode(self):
        # train()/eval() (and any structural mutation) bump the module
        # structure epoch, so an unchanged epoch proves the mode tuple is
        # unchanged — steady state skips the O(model) mode-tuple walk
        epoch = structure_epoch()
        if epoch == self._mode_epoch:
            return
        key = self._mode_key()
        if key == self._active_mode:
            self._mode_epoch = epoch
            return
        # consume the epoch only AFTER the swap succeeds: if the error below
        # raises, the next call must re-check and raise again rather than
        # early-return and silently run the stale-mode program
        if self._grad_acc is not None:
            raise RuntimeError(
                "module train/eval mode changed in the middle of a no_sync "
                "gradient-accumulation window; finish the window (a syncing "
                "step) before flipping the mode")
        self._mode_cache[self._active_mode] = {
            a: getattr(self, a, None) for a in self._MODE_STATE_ATTRS}
        stash = self._mode_cache.get(key) or {a: None for a in self._MODE_STATE_ATTRS}
        for a, v in stash.items():
            setattr(self, a, v)
        self._active_mode = key
        self._mode_epoch = epoch

    def _make_vag(self, *, sync_loss: bool = True):
        """Build a ThunderValueAndGrad over the (optionally distributed)
        traced step. sync_loss=False skips the cross-replica loss all-reduce,
        so gradients stay per-replica partial — the no_sync program variant."""
        from .transforms.autodiff import ThunderValueAndGrad

        plan = getattr(self.tmodule, "_dist_plan", None)
        inner = self.tmodule._cfn._cd.fn

        if plan is None:
            traced = inner
        else:
            from .ops import ltorch
            from .parallel import prims as dist_prims
            from .parallel.transforms import apply_param_collectives

            def traced(params: dict, args: tuple, kwargs: dict):
                import contextlib

                from .parallel.context_parallel import seq_parallel_tracing

                seq_axes = tuple(getattr(plan, "seq_axes", ()))
                cp_ctx = (
                    seq_parallel_tracing(seq_axes[0], plan.world_size(seq_axes[0]))
                    if seq_axes else contextlib.nullcontext()
                )
                full_params = apply_param_collectives(params, plan)
                with cp_ctx:
                    local_loss = inner(full_params, args, kwargs)
                if sync_loss and plan.loss_axes:
                    s = dist_prims.all_reduce(local_loss, plan.loss_axes)
                    return ltorch.div(s, float(plan.loss_world_size))
                return local_loss

            traced.__name__ = f"dist_{getattr(inner, '__name__', 'step')}"

        # Frozen (requires_grad=False) params ride as a separate non-donated,
        # non-differentiated arg so LoRA/quantized base weights stay untouched.
        def traced_split(tparams: dict, frozen: dict, args: tuple, kwargs: dict):
            return traced({**frozen, **tparams}, args, kwargs)

        traced_split.__name__ = getattr(traced, "__name__", "step")

        # argnums=0: the trainable params dict is arg 0 of the traced wrapper;
        # inside the jitted step params are raw arrays, so positional marking
        # is required. donated_argnums mirrors the jax.jit donation of the
        # whole step (params donated when self.donate) so the trace carries
        # the annotation the alias analysis verifies under TT_CHECK_TRACES
        vag = ThunderValueAndGrad(traced_split, argnums=0,
                                  transforms=self.tmodule._cfn._transforms,
                                  donated_argnums=(0,) if self.donate else None,
                                  check_traces=getattr(self.tmodule._cfn,
                                                       "_check_traces", False))
        vag._effects_consumer_attached = True  # TrainStep consumes pending effects
        return vag

    def _build(self, batch_args, batch_kwargs):
        plan = getattr(self.tmodule, "_dist_plan", None)
        optimizer = self.optimizer
        guard = self._guard
        if guard is not None and plan is not None:
            # host-side policy decisions must come from an ALL-HOST verdict
            # (see the psum in raw_step below); mark the guard so after_step
            # records the distributed agreement counters
            guard.mark_distributed()
        check_gnorm = guard is not None and guard.policy.check_grad_norm
        vag = self._make_vag(sync_loss=True)
        self._vag = vag

        train_step = self

        def raw_step(tparam_arrays: dict, frozen_arrays: dict, opt_state, args, kwargs):
            # named phases: HLO traced under these scopes carries the phase
            # name in its op metadata, so device profiles of the ONE fused
            # step program can still attribute time to fwd+bwd vs the
            # optimizer (the registered fusion regions nest inside tt_fwd_bwd)
            with _obs_runtime.fusion_scope("tt_fwd_bwd"):
                loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
            param_grads = grads[0][0]
            with _obs_runtime.fusion_scope("tt_optimizer"):
                new_params, new_state = optimizer.update(tparam_arrays, param_grads, opt_state)
            gmetrics = None
            if guard is not None:
                # in-program health gate: a non-finite loss/grad-norm step
                # must leave params AND optimizer state untouched. This has
                # to happen inside the program — under buffer donation the
                # old arrays no longer exist anywhere the host could reach
                # by the time it observes the loss.
                if check_gnorm:
                    gnorm = (_dist_global_norm(param_grads, plan)
                             if plan is not None else _global_norm(param_grads))
                else:
                    gnorm = jnp.zeros((), jnp.float32)
                finite = jnp.isfinite(loss)
                if check_gnorm:
                    finite = jnp.logical_and(finite, jnp.isfinite(gnorm))
                if plan is not None:
                    # distributed verdict — "one psum away" (ROADMAP #1):
                    # a NaN in ANY shard (one host's batch, one param shard's
                    # grads) must gate the update on EVERY device, or the
                    # replicas diverge and every later step is garbage. One
                    # psum of the local badness over ALL mesh axes turns the
                    # local flag into the all-host agreement.
                    axes = tuple(plan.mesh.axis_names)
                    axes = axes if len(axes) > 1 else axes[0]
                    bad = jax.lax.psum(
                        jnp.where(finite, 0, 1).astype(jnp.int32), axes)
                    finite = bad == 0
                new_params = {k: jnp.where(finite, v, tparam_arrays[k])
                              for k, v in new_params.items()}
                new_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new_state, opt_state)
                gmetrics = (finite, gnorm)
            pending = vag.consume_pending_effects()
            if pending is not None:
                # epilogue values (buffer mutations) ride out as jit outputs;
                # __call__ replays them onto the module after the step
                train_step._effect_keys = pending[0]
                effects = pending[1]
            else:
                train_step._effect_keys = None
                effects = ()
            if guard is not None:
                return loss, new_params, new_state, effects, gmetrics
            return loss, new_params, new_state, effects

        # attribution hierarchy for device profiles: the whole-step program
        # is named (its HLO module becomes jit_tt_train_step — the join
        # that works on backends whose per-op events drop scope metadata),
        # and the phase scopes above are registered one level finer so
        # optimizer/collective time that no fusion region claims still has
        # a bucket. Fusion regions themselves register at level 0.
        from .observability import profiler as _obs_profiler

        raw_step.__name__ = "tt_train_step"
        _obs_profiler.register_region("tt_fwd_bwd", executor="trainstep", level=1)
        _obs_profiler.register_region("tt_optimizer", executor="trainstep", level=1)
        _obs_profiler.register_region("tt_train_step", executor="trainstep", level=2)

        donate = (0, 2) if self.donate else ()
        if plan is None:
            self._jitted = jax.jit(raw_step, donate_argnums=donate)
        else:
            def raw_step_dist(*a, **kw):
                out = raw_step(*a, **kw)
                if out[3]:
                    raise NotImplementedError(
                        "buffer mutations (e.g. BatchNorm running stats) inside a "
                        "distributed TrainStep are not supported yet — stats would "
                        "need a cross-replica mean; freeze the buffers (module.eval()) "
                        "or train without a mesh plan")
                return out

            self._jitted = _shard_mapped_step(raw_step_dist, plan, self.tmodule, self.opt_state,
                                              batch_args, batch_kwargs, donate,
                                              guarded=guard is not None)

    # -- AOT executable cache (utils/aot_cache.py): warm process start
    # deserializes the compiled whole-step program — no trace, no lowering,
    # no XLA compile. Single-chip effect-free steps only (distributed plans
    # go through shard_map; buffer-mutating steps carry module references).

    def _aot_key(self, tparam_arrays, frozen_arrays, args, kwargs) -> str:
        from .utils import aot_cache

        extra = "|".join([
            _safe_repr(self.optimizer),
            repr(self._active_mode),
            repr(self.donate),
            # a guard changes the traced program (finite gate + metric
            # outputs): a guarded and an unguarded step must never share an
            # AOT entry
            self._guard.program_key() if self._guard is not None else "noguard",
            # a bucketed step's artifact serves a LENGTH RANGE: the ladder
            # identity keys it so a different ladder (different rungs, so
            # different padded shapes could coincide) never shares an entry
            self.buckets.key_fields() if self.buckets is not None else "nobuckets",
            # overlap compiler options (parallel/overlap.py) change the
            # compiled executable without changing any input metadata: a
            # config flip must MISS the cache, never reuse a non-overlapped
            # program under an overlap-requested step (or vice versa)
            getattr(self, "_overlap_key", "nooverlap"),
            "|".join(_safe_repr(t) for t in getattr(self.tmodule._cfn, "_transforms", ())),
        ])
        inputs = (tparam_arrays, frozen_arrays, self.opt_state, args, kwargs)
        return aot_cache.step_key(inputs=inputs, extra=extra)

    def _model_digest(self) -> str:
        """Digest of the model's computation (module tree + forward sources):
        editing a forward must invalidate AOT warm starts even though the
        input shape/dtype spec — the base key — is unchanged."""
        from .utils import aot_cache

        if self._model_digest_cached is None:
            self._model_digest_cached = aot_cache.module_digest(self.tmodule.module)
        return self._model_digest_cached

    _model_digest_cached = None

    def _try_aot(self, tparam_arrays, frozen_arrays, args, kwargs) -> bool:
        from .utils import aot_cache

        if not aot_cache.enabled() or getattr(self.tmodule, "_dist_plan", None) is not None:
            return False
        base = self._aot_key(tparam_arrays, frozen_arrays, args, kwargs)
        loaded, outcome = aot_cache.load_keyed(base, self._model_digest())
        if outcome == "stale":
            # an executable for these exact inputs exists but the model code
            # changed underneath it: the cold trace that follows is forced
            _obs_metrics.record_recompile(_obs_metrics.REASON_STALE_KEY,
                                          key=base[:12])
        if loaded is None:
            return False
        train_step = self

        def rebuild():
            train_step._jitted = None
            train_step._build(args, kwargs)
            return train_step._jitted

        self._effect_keys = None
        self._jitted = _CompiledWithFallback(loaded, rebuild)
        return True

    def _maybe_save_aot(self, tparam_arrays, frozen_arrays, args, kwargs) -> None:
        from .utils import aot_cache

        if not aot_cache.enabled() or getattr(self.tmodule, "_dist_plan", None) is not None:
            return
        jit_fn = self._jitted
        try:
            lowered = jit_fn.lower(tparam_arrays, frozen_arrays, self.opt_state, args, kwargs)
            if getattr(self, "_effect_keys", None) is not None:
                return  # buffer-mutation epilogues carry module refs: not cacheable
            compiled = lowered.compile()
            aot_cache.save_keyed(self._aot_key(tparam_arrays, frozen_arrays, args, kwargs),
                                 self._model_digest(), compiled)
        except Exception:
            return
        # reuse the compiled program directly (the separate AOT lower/compile
        # does not populate jax.jit's dispatch cache; without this the first
        # call would trace the whole step a second time)
        self._jitted = _CompiledWithFallback(compiled, lambda: jit_fn)

    def _bucketize(self, args, kwargs):
        """Pad batch leaves to the attached BucketLadder's next rung (no-op
        without a ladder, zero copies when lengths already sit on a rung).
        Every length in a bucket then dispatches through the SAME cache key
        — steady-state recompiles across a (batch, seq) sweep stay at zero,
        and the stored whole-step artifact serves the whole range."""
        if self.buckets is None:
            return args, kwargs
        from .compile_service.buckets import pad_to_bucket

        for a in args:
            shape = getattr(a, "shape", None)
            if shape is not None and len(shape) > self.bucket_axis:
                # ladder traffic stats (MRU order, per-rung hits) — the
                # same bookkeeping the serving engine records per prefill
                self.buckets.touch(int(shape[self.bucket_axis]))
                break
        args, kwargs = pad_to_bucket(args, kwargs, self.buckets,
                                     axis=self.bucket_axis,
                                     pad_values=self.bucket_pad)
        return args, kwargs

    def _split_params(self):
        self._split_walks += 1
        params = self.tmodule.get_parameters()
        trainable = {k: p for k, p in params.items() if getattr(p, "requires_grad", True)}
        frozen = {k: p for k, p in params.items() if k not in trainable}
        # buffers (running stats etc.) ride as frozen inputs so they are not
        # baked into the program as constants
        getb = getattr(self.tmodule, "get_buffers", None)
        if callable(getb):
            frozen.update(getb())
        return trainable, frozen

    def _split_arrays(self):
        """(tparam_arrays, frozen_arrays, trainable_pairs) with the split
        STRUCTURE cached under the module structure epoch. Steady-state steps
        do no module-tree walk and no requires_grad filtering — only direct
        ``.data`` reads off cached Parameter references (params/buffer values
        may change between steps; the key sets and grad partition cannot
        without bumping the epoch). trainable_pairs is the write-back list
        for ``new_params``."""
        epoch = structure_epoch()
        cache = self._split_cache
        if cache is None or cache[0] != epoch:
            params = self.tmodule.get_parameters()
            self._split_walks += 1
            t_pairs = tuple((k, p) for k, p in params.items()
                            if getattr(p, "requires_grad", True))
            tset = {k for k, _ in t_pairs}
            f_pairs = tuple((k, p) for k, p in params.items() if k not in tset)
            # buffers are re-read from their owning module each step: effect
            # replay rebinds _buffers[name] to a NEW array, so caching the
            # value (rather than the owner+name slot) would serve stale stats
            b_triples = ()
            if callable(getattr(self.tmodule, "get_buffers", None)):
                b_triples = tuple(self.tmodule.module.named_buffer_slots())
            cache = self._split_cache = (epoch, t_pairs, f_pairs, b_triples)
        _, t_pairs, f_pairs, b_triples = cache
        tparam_arrays = {k: p.data for k, p in t_pairs}
        frozen_arrays = {k: getattr(p, "data", p) for k, p in f_pairs}
        for k, m, bn in b_triples:
            frozen_arrays[k] = m._buffers[bn]
        return tparam_arrays, frozen_arrays, t_pairs

    # set by CheckpointManager.attach(); None keeps the per-step cost at one
    # attribute read (same discipline as the disabled observability bus)
    _ckpt_manager = None

    @property
    def step_count(self) -> int:
        """Completed optimizer steps; checkpoint/restore round-trips it."""
        return self._step_count

    def _dispatch(self, *jit_args):
        """Invoke the compiled step, with bounded retry-with-backoff for
        transient runtime errors when the guard asks for it (generalizing
        the one-shot rebuild in _CompiledWithFallback, which stays the
        first line of defense for stale AOT executables)."""
        g = self._guard
        step_idx = self._step_count
        if g is None or g.policy.retry_transient <= 0:
            if _rb_faults.active():
                # `die` kills the process mid-step (host-death injection) —
                # deliberately OUTSIDE any retry loop: a dead host does not
                # retry, its peers discover it through the runtime; `oom`
                # likewise — an exhausted allocator does not recover on the
                # next attempt, the post-mortem path owns it
                _rb_faults.maybe_die(step_idx)
                _rb_faults.maybe_oom(step_idx)
                _rb_faults.maybe_raise("transient", step_idx)
            return self._jitted(*jit_args)

        def attempt():
            # the injection point sits INSIDE the retry loop so an armed
            # `transient@N*k` fault fails the first k attempts of step N
            if _rb_faults.active():
                _rb_faults.maybe_raise("transient", step_idx)
            return self._jitted(*jit_args)

        if _rb_faults.active():
            _rb_faults.maybe_die(step_idx)
            _rb_faults.maybe_oom(step_idx)

        return g.run_with_retry(attempt, step=step_idx)

    def __call__(self, *args, **kwargs):
        # one enabled() read gates ALL per-step observability: disabled mode
        # (the default) must do zero event-bus work on the dispatch path.
        # `sampled` additionally applies TT_OBS_SAMPLE to the per-step
        # records (span + host_overhead) — the flight recorder stays
        # unsampled so its p99/spike detection keeps every step.
        obs_on = _obs.enabled()
        slo_mon = self.slo_monitor
        t_host = time.perf_counter_ns() if (obs_on or slo_mon is not None) else 0
        sampled = obs_on and _obs_runtime.step_sampled("train_step")
        self._sync_mode()
        if getattr(self.tmodule, "_no_sync_active", False):
            return self.micro_step(*args, **kwargs)
        args, kwargs = self._bucketize(args, kwargs)
        # fault-injection seam (TT_FAULT): with no plan armed this is one
        # module-global read — the same zero-work contract as the bus
        step_idx = self._step_count
        if _rb_faults.active():
            # `slow` stalls the host at the step boundary (straggler
            # injection for the fleet detector) before any device work
            _rb_faults.maybe_sleep(step_idx)
            args, kwargs = _rb_faults.maybe_poison(args, kwargs, step_idx)
        tparam_arrays, frozen_arrays, t_pairs = self._split_arrays()
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(tparam_arrays)
        was_built = self._jitted is not None
        if not was_built:
            if obs_on and self._step_count > 0:
                # a mid-run (re)build is a compile no cache served: record it
                # so the flight recorder's spike triage can name the cause
                _obs_metrics.record_recompile(_obs_metrics.REASON_CACHE_MISS,
                                              fn="train_step", step=self._step_count)
            if not self._try_aot(tparam_arrays, frozen_arrays, args, kwargs):
                self._build(args, kwargs)
                self._maybe_save_aot(tparam_arrays, frozen_arrays, args, kwargs)
        self.last_batch = (args, kwargs)  # for memory_analysis/harnesses
        if sampled and was_built:
            # host dispatch overhead of a steady-state step: everything
            # between call entry and handing off to the jitted program
            # (mode check, cached split, array-dict build). Opt-in: with the
            # bus disabled this whole block is one boolean test.
            _obs.event("host_overhead", fn="train_step", step=self._step_count,
                       us=round((time.perf_counter_ns() - t_host) / 1e3, 2))
        gmetrics = None
        if self._grad_acc is not None:
            # final (syncing) step of a no_sync accumulation window: fold the
            # accumulated local grads in before the optimizer update
            plan = getattr(self.tmodule, "_dist_plan", None)
            if plan is not None:
                loss, new_params, self.opt_state = self._fold_dist(
                    plan, tparam_arrays, frozen_arrays, self.opt_state, self._grad_acc, args, kwargs)
            else:
                loss, new_params, self.opt_state = self._jitted_with_acc(
                    tparam_arrays, frozen_arrays, self.opt_state, self._grad_acc, args, kwargs)
            self._grad_acc = None
        else:
            # host-side step latency (opt-in; dispatch is async so this is
            # submission latency unless the caller reads the loss value).
            # Gated on the obs_on read from call entry: the disabled-mode
            # steady-state path must not call into the observability layer
            try:
                with _obs.span("train_step") if sampled else _NULL_SPAN:
                    out = self._dispatch(
                        tparam_arrays, frozen_arrays, self.opt_state, args, kwargs)
            except BaseException as e:
                # RESOURCE_EXHAUSTED through dispatch: dump the forensic
                # bundle (live-array census, watermark ring, budget
                # estimate) BEFORE re-raising — the step is already dead,
                # the only question is whether the crash is legible
                _obs_mem.maybe_post_mortem(e, step=step_idx, source="train")
                # a step that dies while the FLEET is draining (a preempted
                # peer stopped stepping, so this host's collective had no
                # counterparty) is the drain arriving, not a crash: finalize
                # the preemption from the last completed step instead of
                # surfacing a dead-collective error. Zero cost on healthy
                # failures without a manager; with one, the KV read happens
                # only on this (already exceptional) path.
                mgr = self._ckpt_manager
                if mgr is not None and (mgr.preempted or mgr._peer_preempted()):
                    mgr._finalize_preempt(self)  # raises Preempted
                raise
            if self._guard is not None:
                loss, new_params, self.opt_state, effects, gmetrics = out
            else:
                loss, new_params, self.opt_state, effects = out
                gmetrics = None
            if effects and getattr(self, "_effect_keys", None):
                # epilogue: replay traced buffer mutations (running stats).
                # Under a guard, a non-finite step must not replay either:
                # the effect values were computed from the NaN forward, and
                # poisoned running stats / amax histories would corrupt
                # every later step the param gate just protected. The
                # bool() sync is one the guard's after_step pays anyway.
                if gmetrics is None or bool(gmetrics[0]):
                    for (owner, name), v in zip(self._effect_keys, effects):
                        owner._buffers[name] = v
        for k, p in t_pairs:
            p.data = new_params[k]
        self._step_count += 1
        if obs_on or slo_mon is not None:
            wall_ms = (time.perf_counter_ns() - t_host) / 1e6
            if obs_on:
                # flight recorder: every step's wall time (submission latency
                # + any synchronous compile) feeds the bounded ring; spikes
                # cross-reference the bus's recent recompile/stall events.
                # The streaming histogram is equally unsampled: online
                # step-time percentiles must cover every step.
                _obs_flight.record_step(wall_ms, step=self._step_count,
                                        fn="train_step")
                _obs_tel.observe("train.step_ms", wall_ms)
                # HBM watermark sample at the step boundary (mem.* gauges +
                # watermark ring); gated on the same obs_on read
                _obs_mem.on_step(self._step_count, source="train")
            if slo_mon is not None:
                slo_mon.observe_step(wall_ms)
        if gmetrics is not None:
            # host half of the guard: one device sync, then policy
            # (raise / skip-with-budget / rollback via the manager)
            self._guard.after_step(self, loss, gmetrics)
        if _rb_faults.active():
            _rb_faults.maybe_preempt(step_idx)
        mgr = self._ckpt_manager
        if mgr is not None:
            # periodic save / preemption drain; idle cost is an Event read
            # plus an int modulo (see CheckpointManager.on_step)
            mgr.on_step(self)
        return loss

    # -- gradient accumulation (reference ThunderModule.no_sync,
    # thunder/core/module.py:341 + skip_data_parallel_grad_sync) --
    _grad_acc = None
    _micro_jitted = None
    _jitted_with_acc_fn = None

    def micro_step(self, *args, **kwargs):
        """Accumulate local gradients without the cross-replica sync or the
        optimizer update; a following regular step folds them in.

        Under a distributed plan (pure-DDP/replicate) the per-replica partial
        gradients ride in a device-axis-sharded accumulator, so a K-step
        window costs ONE all-reduce instead of K (reference no_sync +
        _sync_grads, thunder/distributed/__init__.py:36,118)."""
        if self._guard is not None:
            # the window's fold step applies the optimizer update through a
            # separate program with no finite gate — silently un-guarding
            # the only updating step of a window would fake NaN protection
            raise NotImplementedError(
                "step guards are not supported inside no_sync gradient-"
                "accumulation windows yet; step without no_sync, or drop "
                "the guard")
        self._sync_mode()
        args, kwargs = self._bucketize(args, kwargs)
        plan = getattr(self.tmodule, "_dist_plan", None)
        if plan is not None:
            return self._micro_step_dist(plan, args, kwargs)
        tparam_arrays, frozen_arrays, _ = self._split_arrays()
        if self._jitted is None:
            if self.opt_state is None:
                self.opt_state = self.optimizer.init(tparam_arrays)
            self._build(args, kwargs)
        if self._micro_jitted is None:
            vag = self._vag

            def micro(tparam_arrays, frozen_arrays, acc, args, kwargs):
                loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
                if vag.consume_pending_effects():
                    raise NotImplementedError(
                        "buffer mutations are not supported inside no_sync "
                        "accumulation windows yet; freeze the buffers (eval()) "
                        "or step without no_sync")
                g = grads[0][0]
                new_acc = g if acc is None else {k: acc[k] + g[k] for k in g}
                return loss, new_acc

            self._micro_jitted = jax.jit(micro, donate_argnums=(2,) if self.donate else ())
        with _obs_runtime.step_span("micro_step") if _obs.enabled() else _NULL_SPAN:
            loss, self._grad_acc = self._micro_jitted(tparam_arrays, frozen_arrays, self._grad_acc, args, kwargs)
        return loss

    # -- distributed no_sync (pure-DDP and DDP/FSDP plans) --
    _vag_nosync = None
    _micro_dist_jitted = None
    _fold_dist_jitted = None
    _acc_mode = None  # 'ddp' (partial grads) | 'fsdp' (full grads, cached gather)
    _vag_full = None
    _gather_jitted = None
    _full_cache = None
    _micro_fsdp_jitted = None
    _fold_fsdp_jitted = None

    @staticmethod
    def _nosync_mode(plan) -> str:
        kinds = {st.kind for sts in plan.param_strategies.values() for st in sts}
        if kinds <= {"replicate"}:
            return "ddp"
        if kinds <= {"replicate", "shard0"} and not getattr(plan, "seq_axes", ()):
            return "fsdp"
        raise NotImplementedError(
            "no_sync supports DDP (replicate) and FSDP (shard0) plans; "
            "TP/CP gradients synchronize per micro-batch inherently")

    def _dist_specs(self, plan, trainable, frozen, batch_args, batch_kwargs):
        from jax.sharding import PartitionSpec as P

        param_specs, frozen_specs, args_specs, kwargs_specs = _dist_in_specs(
            plan, trainable, frozen, batch_args, batch_kwargs)
        acc_specs = {k: P(plan.loss_axis_name, *([None] * v.ndim)) for k, v in trainable.items()}
        return param_specs, frozen_specs, acc_specs, args_specs, kwargs_specs

    def _micro_step_dist(self, plan, args, kwargs):
        self._acc_mode = self._nosync_mode(plan)
        if self._acc_mode == "fsdp":
            return self._micro_step_fsdp(plan, args, kwargs)
        # epoch-cached split: K micro-steps per window must not pay K walks
        tparam_arrays, frozen_arrays, _ = self._split_arrays()
        if self._jitted is None:
            if self.opt_state is None:
                self.opt_state = self.optimizer.init(tparam_arrays)
            self._build(args, kwargs)
        if self._vag_nosync is None:
            self._vag_nosync = self._make_vag(sync_loss=False)
        if self._grad_acc is None:
            # allocate the accumulator already sharded over the device axis
            # (a plain jnp.zeros would materialize world_size x params on one
            # device before resharding — an OOM hazard at scale)
            from jax.sharding import NamedSharding, PartitionSpec as P

            def _sharded_zeros(shape, dtype):
                sh = NamedSharding(plan.mesh, P(plan.loss_axis_name, *([None] * (len(shape) - 1))))
                return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sh)()

            self._grad_acc = {k: _sharded_zeros((plan.loss_world_size,) + tuple(v.shape), v.dtype)
                              for k, v in tparam_arrays.items()}
        if self._micro_dist_jitted is None:
            from jax.sharding import PartitionSpec as P

            vagn = self._vag_nosync
            ndev = plan.loss_world_size
            axes = plan.loss_axis_name

            def micro_raw(tparams, frozen_a, acc, a, kw):
                loss_local, grads = vagn(tparams, frozen_a, a, kw)
                if vagn.consume_pending_effects():
                    raise NotImplementedError(
                        "buffer mutations are not supported in distributed "
                        "no_sync windows; freeze the buffers (eval())")
                g = grads[0][0]
                new_acc = {k: acc[k] + g[k][None] for k in g}
                loss = jax.lax.psum(loss_local, axes) / ndev
                return loss, new_acc

            pspec, fspec, aspec, args_specs, kwargs_specs = self._dist_specs(
                plan, tparam_arrays, frozen_arrays, args, kwargs)
            sm = _shard_map_compat(micro_raw, plan.mesh,
                                   (pspec, fspec, aspec, args_specs, kwargs_specs),
                                   (P(), aspec))
            self._micro_dist_jitted = jax.jit(sm, donate_argnums=(2,) if self.donate else ())
        loss, self._grad_acc = self._micro_dist_jitted(
            tparam_arrays, frozen_arrays, self._grad_acc, args, kwargs)
        return loss

    # -- FSDP no_sync: gather params ONCE per accumulation window, run
    # micro-steps with zero communication on cached full params, fold with a
    # single reduce-scatter (reference FSDP no_sync stashes unsharded grads,
    # thunder/distributed/__init__.py:36 + STASH_GRAD_FOR_FSDP) --

    def _make_vag_full(self):
        """ValueAndGrad over the raw model with FULL params (no collectives)."""
        from .transforms.autodiff import ThunderValueAndGrad

        inner = self.tmodule._cfn._cd.fn

        def traced_full(tfull: dict, frozen_full: dict, args: tuple, kwargs: dict):
            return inner({**frozen_full, **tfull}, args, kwargs)

        traced_full.__name__ = f"nosync_{getattr(inner, '__name__', 'step')}"
        vag = ThunderValueAndGrad(traced_full, argnums=0,
                                  transforms=self.tmodule._cfn._transforms,
                                  check_traces=getattr(self.tmodule._cfn,
                                                       "_check_traces", False))
        vag._effects_consumer_attached = True
        return vag

    def _gather_full(self, plan, tparam_arrays, frozen_arrays):
        """One jitted gather of every sharded param to full (unpadded) form."""
        if self._gather_jitted is None:
            from jax.sharding import PartitionSpec as P

            strategies = plan.param_strategies

            def gather_raw(tparams, frozen_a):
                def full(k, v):
                    for st in strategies.get(k, ()):
                        if st.kind == "shard0":
                            v = jax.lax.all_gather(v, st.axis, tiled=True)
                            if st.orig_dim0 is not None:
                                v = v[: st.orig_dim0]
                    return v

                return ({k: full(k, v) for k, v in tparams.items()},
                        {k: full(k, v) for k, v in frozen_a.items()})

            pspec = {k: plan.param_spec(k, v.ndim) for k, v in tparam_arrays.items()}
            fspec = {k: plan.param_spec(k, v.ndim) for k, v in frozen_arrays.items()}
            out_t = {k: P() for k in tparam_arrays}
            out_f = {k: P() for k in frozen_arrays}
            sm = _shard_map_compat(gather_raw, plan.mesh, (pspec, fspec), (out_t, out_f))
            self._gather_jitted = jax.jit(sm)
        return self._gather_jitted(tparam_arrays, frozen_arrays)

    def _micro_step_fsdp(self, plan, args, kwargs):
        tparam_arrays, frozen_arrays, _ = self._split_arrays()
        if self._jitted is None:
            if self.opt_state is None:
                self.opt_state = self.optimizer.init(tparam_arrays)
            self._build(args, kwargs)
        if self._vag_full is None:
            self._vag_full = self._make_vag_full()
        if self._full_cache is None:
            self._full_cache = self._gather_full(plan, tparam_arrays, frozen_arrays)
        full_t, full_f = self._full_cache
        if self._grad_acc is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            def _sharded_zeros(shape, dtype):
                sh = NamedSharding(plan.mesh, P(plan.loss_axis_name, *([None] * (len(shape) - 1))))
                return jax.jit(lambda: jnp.zeros(shape, dtype), out_shardings=sh)()

            self._grad_acc = {k: _sharded_zeros((plan.loss_world_size,) + tuple(v.shape), v.dtype)
                              for k, v in full_t.items()}
        if self._micro_fsdp_jitted is None:
            from jax.sharding import PartitionSpec as P

            vagf = self._vag_full
            ndev = plan.loss_world_size
            axes = plan.loss_axis_name

            def micro_raw(tfull, ffull, acc, a, kw):
                loss_local, grads = vagf(tfull, ffull, a, kw)
                if vagf.consume_pending_effects():
                    raise NotImplementedError(
                        "buffer mutations are not supported in FSDP no_sync "
                        "windows; freeze the buffers (eval())")
                g = grads[0][0]
                new_acc = {k: acc[k] + g[k][None] for k in g}
                loss = jax.lax.psum(loss_local, axes) / ndev
                return loss, new_acc

            tspec = {k: P() for k in full_t}
            fspec = {k: P() for k in full_f}
            aspec = {k: P(plan.loss_axis_name, *([None] * v.ndim)) for k, v in full_t.items()}
            args_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), args)
            kwargs_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), kwargs)
            sm = _shard_map_compat(micro_raw, plan.mesh,
                                   (tspec, fspec, aspec, args_specs, kwargs_specs),
                                   (P(), aspec))
            self._micro_fsdp_jitted = jax.jit(sm, donate_argnums=(2,) if self.donate else ())
        loss, self._grad_acc = self._micro_fsdp_jitted(full_t, full_f, self._grad_acc, args, kwargs)
        return loss

    def _fold_fsdp(self, plan, tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
        """Final step of an FSDP no_sync window: fresh local full grads + the
        accumulator, ONE reduce-scatter per sharded param, optimizer on
        shards; the cached full params are then invalidated."""
        full_t, full_f = self._full_cache
        if self._fold_fsdp_jitted is None:
            from jax.sharding import PartitionSpec as P

            vagf = self._vag_full
            optimizer = self.optimizer
            ndev = plan.loss_world_size
            axes = plan.loss_axis_name
            strategies = plan.param_strategies

            def shard_grad(k, g, shard_like):
                # full chain: psum over every loss axis the param is NOT
                # sharded on (dp replicas see different batches), then one
                # reduce-scatter over its shard axis
                shard_st = next((st for st in strategies.get(k, ()) if st.kind == "shard0"), None)
                if shard_st is None:
                    return jax.lax.psum(g, axes) / ndev
                other = tuple(a for a in plan.loss_axes if a != shard_st.axis)
                if other:
                    g = jax.lax.psum(g, other if len(other) > 1 else other[0])
                if shard_st.orig_dim0 is not None:
                    pad = shard_like.shape[0] * plan.world_size(shard_st.axis) - shard_st.orig_dim0
                    g = jnp.pad(g, [(0, pad)] + [(0, 0)] * (g.ndim - 1))
                return jax.lax.psum_scatter(g, shard_st.axis, scatter_dimension=0, tiled=True) / ndev

            def fold_raw(tshards, opt_st, tfull, ffull, acc, a, kw):
                loss_local, grads = vagf(tfull, ffull, a, kw)
                vagf.consume_pending_effects()
                g = grads[0][0]
                total = {k: g[k] + acc[k][0] for k in g}
                gshards = {k: shard_grad(k, total[k], tshards[k]) for k in total}
                new_params, new_state = optimizer.update(tshards, gshards, opt_st)
                loss = jax.lax.psum(loss_local, axes) / ndev
                return loss, new_params, new_state

            pspec = {k: plan.param_spec(k, v.ndim) for k, v in tparam_arrays.items()}
            opt_specs = _opt_state_specs(opt_state, pspec)
            tspec = {k: P() for k in full_t}
            fspec = {k: P() for k in full_f}
            aspec = {k: P(plan.loss_axis_name, *([None] * v.ndim)) for k, v in full_t.items()}
            args_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), args)
            kwargs_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), kwargs)
            sm = _shard_map_compat(fold_raw, plan.mesh,
                                   (pspec, opt_specs, tspec, fspec, aspec, args_specs, kwargs_specs),
                                   (P(), pspec, opt_specs))
            self._fold_fsdp_jitted = jax.jit(sm, donate_argnums=(0, 1, 4) if self.donate else ())
        out = self._fold_fsdp_jitted(tparam_arrays, opt_state, full_t, full_f, acc, args, kwargs)
        self._full_cache = None  # params change: next window re-gathers
        return out

    def _fold_dist(self, plan, tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
        """Final step of a distributed no_sync window: ONE all-reduce over
        (fresh local grads + accumulated partials), then the optimizer."""
        if self._acc_mode == "fsdp":
            return self._fold_fsdp(plan, tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs)
        if self._fold_dist_jitted is None:
            from jax.sharding import PartitionSpec as P

            vagn = self._vag_nosync or self._make_vag(sync_loss=False)
            self._vag_nosync = vagn
            optimizer = self.optimizer
            ndev = plan.loss_world_size
            axes = plan.loss_axis_name

            def fold_raw(tparams, frozen_a, opt_st, acc, a, kw):
                loss_local, grads = vagn(tparams, frozen_a, a, kw)
                vagn.consume_pending_effects()
                g = grads[0][0]
                total = {k: jax.lax.psum(g[k] + acc[k][0], axes) / ndev for k in g}
                new_params, new_state = optimizer.update(tparams, total, opt_st)
                loss = jax.lax.psum(loss_local, axes) / ndev
                return loss, new_params, new_state

            pspec, fspec, aspec, args_specs, kwargs_specs = self._dist_specs(
                plan, tparam_arrays, frozen_arrays, args, kwargs)
            opt_specs = _opt_state_specs(opt_state, pspec)
            sm = _shard_map_compat(fold_raw, plan.mesh,
                                   (pspec, fspec, opt_specs, aspec, args_specs, kwargs_specs),
                                   (P(), pspec, opt_specs))
            self._fold_dist_jitted = jax.jit(sm, donate_argnums=(0, 2, 3) if self.donate else ())
        return self._fold_dist_jitted(tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs)

    def _jitted_with_acc(self, tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
        if self._jitted_with_acc_fn is None:
            vag = self._vag
            optimizer = self.optimizer

            def step_acc(tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
                loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
                vag.consume_pending_effects()  # window already rejected effects in micro
                g = grads[0][0]
                total = {k: g[k] + acc[k] for k in g}
                new_params, new_state = optimizer.update(tparam_arrays, total, opt_state)
                return loss, new_params, new_state

            self._jitted_with_acc_fn = jax.jit(step_acc, donate_argnums=(0, 2, 3) if self.donate else ())
        return self._jitted_with_acc_fn(tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs)

    @property
    def compile_stats(self):
        return getattr(self, "_vag", None) and self._vag._cs

    def memory_analysis(self):
        """Compiled-program memory analysis of the last-built step."""
        if self._jitted is None or getattr(self, "last_batch", None) is None:
            return None
        if isinstance(self._jitted, _CompiledWithFallback):
            compiled = self._jitted._compiled
            if compiled is not None:
                return compiled.memory_analysis()
            jitted = self._jitted._jit_fn
            if jitted is None:
                return None
        else:
            jitted = self._jitted
        trainable, frozen = self._split_params()
        tparams = {k: p.data for k, p in trainable.items()}
        fparams = {k: getattr(p, "data", p) for k, p in frozen.items()}
        args, kwargs = self.last_batch
        return jitted.lower(tparams, fparams, self.opt_state, args, kwargs).compile().memory_analysis()


def _dist_global_norm(param_grads: dict, plan):
    """TRUE global gradient norm inside a shard_map'd step: per param, the
    local sum-of-squares is psum'd over exactly the axes that param's grad
    is SHARDED on (shard0/column/row) and counted once over the axes it is
    replicated on — a blanket psum would overcount replicated grads by the
    world size, a bare local norm would understate sharded ones by √shards.
    The result is identical on every device (replicated components are
    equal, psum'd components are collective outputs), so it rides the P()
    out-spec unchanged."""
    strategies = plan.param_strategies
    total = jnp.zeros((), jnp.float32)
    for k, g in param_grads.items():
        ss = jnp.sum(jnp.square(g.astype(jnp.float32)))
        shard_axes = tuple(st.axis for st in strategies.get(k, ())
                           if st.kind in ("shard0", "column", "row"))
        if shard_axes:
            ss = jax.lax.psum(ss, shard_axes if len(shard_axes) > 1
                              else shard_axes[0])
        total = total + ss
    return jnp.sqrt(total)


def _batch_pspec(plan, leaf):
    from jax.sharding import PartitionSpec as P

    ndim = getattr(leaf, "ndim", 0)
    seq_axes = tuple(getattr(plan, "seq_axes", ()))
    if ndim == 0 or (not plan.data_axes and not seq_axes):
        return P()
    first = None
    if plan.data_axes:
        first = plan.data_axes[0] if len(plan.data_axes) == 1 else tuple(plan.data_axes)
    parts = [first]
    if seq_axes and ndim >= 2:
        parts.append(seq_axes[0] if len(seq_axes) == 1 else tuple(seq_axes))
    while len(parts) < ndim:
        parts.append(None)
    return P(*parts)


def _opt_state_specs(opt_state, param_specs: dict):
    from jax.sharding import PartitionSpec as P

    def rec(node):
        if isinstance(node, dict):
            if set(node.keys()) == set(param_specs.keys()):
                return dict(param_specs)
            return {k: rec(v) for k, v in node.items()}
        return P()

    return rec(opt_state)


def _shard_map_compat(fn, mesh, in_specs, out_specs):
    """shard_map across jax API moves: jax.shard_map (new) falls back to
    jax.experimental.shard_map (0.4.x), and the check_vma kwarg falls back
    to its old name check_rep."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax: check_rep
        return sm(fn, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=False)


def _dist_in_specs(plan, trainable, frozen, batch_args, batch_kwargs):
    """PartitionSpecs for (params, frozen, args, kwargs) — the single source
    of sharding rules shared by the synced step and the no_sync variants."""
    param_specs = {k: plan.param_spec(k, v.ndim) for k, v in trainable.items()}
    frozen_specs = {k: plan.param_spec(k, v.ndim) for k, v in frozen.items()}
    args_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), batch_args)
    kwargs_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), batch_kwargs)
    return param_specs, frozen_specs, args_specs, kwargs_specs


def _shard_mapped_step(raw_step, plan, tmodule, opt_state, batch_args, batch_kwargs, donate,
                       *, guarded: bool = False):
    """Wrap the step in shard_map over the plan's mesh: params/opt-state use
    per-param specs, batch leaves shard dim 0 over the data axes, loss comes
    back replicated. XLA lowers the recorded collective prims to ICI
    collectives and overlaps them with compute. A guarded step returns two
    extra outputs — the psum'd finite verdict and the pmax'd grad norm —
    both replicated, so every host's after_step reads the same decision."""
    from jax.sharding import PartitionSpec as P

    all_params = dict(tmodule.get_parameters())
    trainable = {k: p.data for k, p in all_params.items() if getattr(p, "requires_grad", True)}
    getb = getattr(tmodule, "get_buffers", None)
    if callable(getb):
        all_params.update(getb())
    frozen = {k: getattr(p, "data", p) for k, p in all_params.items() if k not in trainable}
    if opt_state is None:
        raise RuntimeError("opt_state must be initialized before building the distributed step")
    if plan.data_axes:
        # loud divisibility check: shard_map's own failure on an uneven
        # batch is an anonymous AssertionError deep in spec matching
        dp_world = 1
        for a in plan.data_axes:
            dp_world *= plan.world_size(a)
        for leaf in jax.tree_util.tree_leaves((batch_args, batch_kwargs)):
            shape = getattr(leaf, "shape", None)
            if shape and shape[0] % dp_world:
                raise ValueError(
                    f"batch dim 0 ({shape[0]}) is not divisible by the "
                    f"data-parallel world size {dp_world} (axes "
                    f"{plan.data_axes}); pad or resize the batch")
    param_specs, frozen_specs, args_specs, kwargs_specs = _dist_in_specs(
        plan, trainable, frozen, batch_args, batch_kwargs)
    opt_specs = _opt_state_specs(opt_state, param_specs)
    out_specs = (P(), param_specs, opt_specs, ())
    if guarded:
        out_specs = out_specs + ((P(), P()),)
    smapped = _shard_map_compat(raw_step, plan.mesh,
                                (param_specs, frozen_specs, opt_specs, args_specs, kwargs_specs),
                                out_specs)
    return jax.jit(smapped, donate_argnums=donate)
