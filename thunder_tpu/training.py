"""Whole-step training compilation: fwd + bwd + optimizer in ONE XLA program.

The reference composes thunder-compiled fwd/bwd with torch autograd and a
separate optimizer step, then optionally wraps regions in CUDA graphs
(thunder/transforms/cudagraph.py:229) to kill dispatch overhead. On TPU the
idiomatic equivalent is stronger: the generated forward and backward callables
are pure-jax, so the full step — prologue-validated forward, backward,
optimizer update — is traced into a single ``jax.jit`` program with buffer
donation on params/optimizer state. XLA then schedules the whole step with
one dispatch and no host round-trips."""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .nn.module import Module, ThunderModule


class TrainStep:
    """step(*batch) -> loss; updates module parameters in place.

    loss_module: a Module whose forward(*batch) returns a scalar loss.
    """

    def __init__(self, loss_module, optimizer, *, donate: bool = True, mesh_plan=None):
        from . import jit as _jit

        if isinstance(loss_module, Module):
            loss_module = _jit(loss_module)
        if not isinstance(loss_module, ThunderModule):
            raise TypeError("TrainStep expects a Module or ThunderModule computing a scalar loss")
        self.tmodule = loss_module
        self.optimizer = optimizer
        self.donate = donate
        self.mesh_plan = mesh_plan  # set by parallel transforms for sharded steps
        self._jitted: Optional[Callable] = None
        self.opt_state = None
        self._step_count = 0

    def _build(self, batch_args, batch_kwargs):
        from .transforms.autodiff import ThunderValueAndGrad

        plan = getattr(self.tmodule, "_dist_plan", None)
        inner = self.tmodule._cfn._cd.fn
        optimizer = self.optimizer

        if plan is None:
            traced = inner
        else:
            from .ops import ltorch
            from .parallel import prims as dist_prims
            from .parallel.transforms import apply_param_collectives

            def traced(params: dict, args: tuple, kwargs: dict):
                import contextlib

                from .parallel.context_parallel import seq_parallel_tracing

                seq_axes = tuple(getattr(plan, "seq_axes", ()))
                cp_ctx = (
                    seq_parallel_tracing(seq_axes[0], plan.world_size(seq_axes[0]))
                    if seq_axes else contextlib.nullcontext()
                )
                full_params = apply_param_collectives(params, plan)
                with cp_ctx:
                    local_loss = inner(full_params, args, kwargs)
                if plan.loss_axes:
                    s = dist_prims.all_reduce(local_loss, plan.loss_axes)
                    return ltorch.div(s, float(plan.loss_world_size))
                return local_loss

            traced.__name__ = f"dist_{getattr(inner, '__name__', 'step')}"

        # Frozen (requires_grad=False) params ride as a separate non-donated,
        # non-differentiated arg so LoRA/quantized base weights stay untouched.
        def traced_split(tparams: dict, frozen: dict, args: tuple, kwargs: dict):
            return traced({**frozen, **tparams}, args, kwargs)

        traced_split.__name__ = getattr(traced, "__name__", "step")

        # argnums=0: the trainable params dict is arg 0 of the traced wrapper;
        # inside the jitted step params are raw arrays, so positional marking
        # is required
        vag = ThunderValueAndGrad(traced_split, argnums=0, transforms=self.tmodule._cfn._transforms)
        self._vag = vag

        def raw_step(tparam_arrays: dict, frozen_arrays: dict, opt_state, args, kwargs):
            loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
            param_grads = grads[0][0]
            new_params, new_state = optimizer.update(tparam_arrays, param_grads, opt_state)
            return loss, new_params, new_state

        donate = (0, 2) if self.donate else ()
        if plan is None:
            self._jitted = jax.jit(raw_step, donate_argnums=donate)
        else:
            self._jitted = _shard_mapped_step(raw_step, plan, self.tmodule, self.opt_state,
                                              batch_args, batch_kwargs, donate)

    def _split_params(self):
        params = self.tmodule.get_parameters()
        trainable = {k: p for k, p in params.items() if getattr(p, "requires_grad", True)}
        frozen = {k: p for k, p in params.items() if k not in trainable}
        return trainable, frozen

    def __call__(self, *args, **kwargs):
        if getattr(self.tmodule, "_no_sync_active", False):
            return self.micro_step(*args, **kwargs)
        trainable, frozen = self._split_params()
        tparam_arrays = {k: p.data for k, p in trainable.items()}
        frozen_arrays = {k: p.data for k, p in frozen.items()}
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(tparam_arrays)
        if self._jitted is None:
            self._build(args, kwargs)
        if self._grad_acc is not None:
            # final (syncing) step of a no_sync accumulation window: fold the
            # accumulated local grads in before the optimizer update
            loss, new_params, self.opt_state = self._jitted_with_acc(
                tparam_arrays, frozen_arrays, self.opt_state, self._grad_acc, args, kwargs)
            self._grad_acc = None
        else:
            loss, new_params, self.opt_state = self._jitted(tparam_arrays, frozen_arrays, self.opt_state, args, kwargs)
        for k, p in trainable.items():
            p.data = new_params[k]
        self._step_count += 1
        return loss

    # -- gradient accumulation (reference ThunderModule.no_sync,
    # thunder/core/module.py:341 + skip_data_parallel_grad_sync) --
    _grad_acc = None
    _micro_jitted = None
    _jitted_with_acc_fn = None

    def micro_step(self, *args, **kwargs):
        """Accumulate local gradients without the cross-replica sync or the
        optimizer update; a following regular step folds them in."""
        if getattr(self.tmodule, "_dist_plan", None) is not None:
            raise NotImplementedError(
                "no_sync/micro_step under a distributed plan needs a "
                "collective-free program variant (planned); accumulate on the "
                "single-program path or sync every step")
        trainable, frozen = self._split_params()
        tparam_arrays = {k: p.data for k, p in trainable.items()}
        frozen_arrays = {k: p.data for k, p in frozen.items()}
        if self._jitted is None:
            if self.opt_state is None:
                self.opt_state = self.optimizer.init(tparam_arrays)
            self._build(args, kwargs)
        if self._micro_jitted is None:
            vag = self._vag

            def micro(tparam_arrays, frozen_arrays, acc, args, kwargs):
                loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
                g = grads[0][0]
                new_acc = g if acc is None else {k: acc[k] + g[k] for k in g}
                return loss, new_acc

            self._micro_jitted = jax.jit(micro, donate_argnums=(2,) if self.donate else ())
        loss, self._grad_acc = self._micro_jitted(tparam_arrays, frozen_arrays, self._grad_acc, args, kwargs)
        return loss

    def _jitted_with_acc(self, tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
        if self._jitted_with_acc_fn is None:
            vag = self._vag
            optimizer = self.optimizer

            def step_acc(tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs):
                loss, grads = vag(tparam_arrays, frozen_arrays, args, kwargs)
                g = grads[0][0]
                total = {k: g[k] + acc[k] for k in g}
                new_params, new_state = optimizer.update(tparam_arrays, total, opt_state)
                return loss, new_params, new_state

            self._jitted_with_acc_fn = jax.jit(step_acc, donate_argnums=(0, 2, 3) if self.donate else ())
        return self._jitted_with_acc_fn(tparam_arrays, frozen_arrays, opt_state, acc, args, kwargs)

    @property
    def compile_stats(self):
        return getattr(self, "_vag", None) and self._vag._cs


def _batch_pspec(plan, leaf):
    from jax.sharding import PartitionSpec as P

    ndim = getattr(leaf, "ndim", 0)
    seq_axes = tuple(getattr(plan, "seq_axes", ()))
    if ndim == 0 or (not plan.data_axes and not seq_axes):
        return P()
    first = None
    if plan.data_axes:
        first = plan.data_axes[0] if len(plan.data_axes) == 1 else tuple(plan.data_axes)
    parts = [first]
    if seq_axes and ndim >= 2:
        parts.append(seq_axes[0] if len(seq_axes) == 1 else tuple(seq_axes))
    while len(parts) < ndim:
        parts.append(None)
    return P(*parts)


def _opt_state_specs(opt_state, param_specs: dict):
    from jax.sharding import PartitionSpec as P

    def rec(node):
        if isinstance(node, dict):
            if set(node.keys()) == set(param_specs.keys()):
                return dict(param_specs)
            return {k: rec(v) for k, v in node.items()}
        return P()

    return rec(opt_state)


def _shard_mapped_step(raw_step, plan, tmodule, opt_state, batch_args, batch_kwargs, donate):
    """Wrap the step in shard_map over the plan's mesh: params/opt-state use
    per-param specs, batch leaves shard dim 0 over the data axes, loss comes
    back replicated. XLA lowers the recorded collective prims to ICI
    collectives and overlaps them with compute."""
    from jax.sharding import PartitionSpec as P

    all_params = tmodule.get_parameters()
    trainable = {k: p.data for k, p in all_params.items() if getattr(p, "requires_grad", True)}
    frozen = {k: p.data for k, p in all_params.items() if k not in trainable}
    param_specs = {k: plan.param_spec(k, v.ndim) for k, v in trainable.items()}
    frozen_specs = {k: plan.param_spec(k, v.ndim) for k, v in frozen.items()}
    if opt_state is None:
        raise RuntimeError("opt_state must be initialized before building the distributed step")
    opt_specs = _opt_state_specs(opt_state, param_specs)
    args_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), batch_args)
    kwargs_specs = jax.tree_util.tree_map(lambda l: _batch_pspec(plan, l), batch_kwargs)
    in_specs = (param_specs, frozen_specs, opt_specs, args_specs, kwargs_specs)
    out_specs = (P(), param_specs, opt_specs)
    try:
        smapped = jax.shard_map(raw_step, mesh=plan.mesh, in_specs=in_specs,
                                out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax: check_rep
        smapped = jax.shard_map(raw_step, mesh=plan.mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=False)
    return jax.jit(smapped, donate_argnums=donate)
