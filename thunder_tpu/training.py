"""Whole-step training compilation: fwd + bwd + optimizer in ONE XLA program.

The reference composes thunder-compiled fwd/bwd with torch autograd and a
separate optimizer step, then optionally wraps regions in CUDA graphs
(thunder/transforms/cudagraph.py:229) to kill dispatch overhead. On TPU the
idiomatic equivalent is stronger: the generated forward and backward callables
are pure-jax, so the full step — prologue-validated forward, backward,
optimizer update — is traced into a single ``jax.jit`` program with buffer
donation on params/optimizer state. XLA then schedules the whole step with
one dispatch and no host round-trips."""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .nn.module import Module, ThunderModule


class TrainStep:
    """step(*batch) -> loss; updates module parameters in place.

    loss_module: a Module whose forward(*batch) returns a scalar loss.
    """

    def __init__(self, loss_module, optimizer, *, donate: bool = True, mesh_plan=None):
        from . import jit as _jit

        if isinstance(loss_module, Module):
            loss_module = _jit(loss_module)
        if not isinstance(loss_module, ThunderModule):
            raise TypeError("TrainStep expects a Module or ThunderModule computing a scalar loss")
        self.tmodule = loss_module
        self.optimizer = optimizer
        self.donate = donate
        self.mesh_plan = mesh_plan  # set by parallel transforms for sharded steps
        self._jitted: Optional[Callable] = None
        self.opt_state = None
        self._step_count = 0

    def _build(self, batch_args, batch_kwargs):
        from .transforms.autodiff import ThunderValueAndGrad

        # argnums=0: the params dict is arg 0 of the traced wrapper; inside the
        # jitted step params are raw arrays, so positional marking is required
        vag = ThunderValueAndGrad(self.tmodule._cfn._cd.fn, argnums=0)
        self._vag = vag
        optimizer = self.optimizer

        def raw_step(param_arrays: dict, opt_state, args, kwargs):
            loss, grads = vag(param_arrays, args, kwargs)
            param_grads = grads[0][0]
            new_params, new_state = optimizer.update(param_arrays, param_grads, opt_state)
            return loss, new_params, new_state

        donate = (0, 1) if self.donate else ()
        self._jitted = jax.jit(raw_step, donate_argnums=donate)

    def __call__(self, *args, **kwargs):
        params = self.tmodule.get_parameters()
        param_arrays = {k: p.data for k, p in params.items()}
        if self.opt_state is None:
            self.opt_state = self.optimizer.init(param_arrays)
        if self._jitted is None:
            self._build(args, kwargs)
        loss, new_params, self.opt_state = self._jitted(param_arrays, self.opt_state, args, kwargs)
        for k, p in params.items():
            p.data = new_params[k]
        self._step_count += 1
        return loss

    @property
    def compile_stats(self):
        return getattr(self, "_vag", None) and self._vag._cs
