"""Auto-catalog extension waves — closing the torch long tail toward the
reference's ~700 auto-registered ops (thunder/torch/default_torch_ops.py:3).

Every entry is a REAL torch-contract name (resolved by the frontend's
qualified-name convention: plain ``<name>`` for ``torch.<name>`` /
``Tensor.<name>`` / ``torch.nn.functional.<name>``, ``fft_<name>`` /
``linalg_<name>`` / ``special_<name>`` for the submodule families) with
torch argument order and semantics, lowered to jax. Shape rules come from
``jax.eval_shape`` (auto_register.register_auto_op); gradients ride the
generic jax.vjp fallback for the differentiable dict.

Deliberately NOT registered (documented, like bincount): ops whose output
shape depends on runtime values (nonzero, unique, masked_select — the
torch interop frontend covers them via the host-eager fallback), sparse
ops, RNG samplers (poisson/binomial: stateless tracing cannot reproduce
torch's generator semantics), and fbgemm/quantized kernels.
"""
from __future__ import annotations

import itertools
import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------------
# helpers (torch semantics on static shapes)
# ---------------------------------------------------------------------------


def _as_strided(a, size, stride, storage_offset=0):
    """Gather-based as_strided over the flattened array (any strides)."""
    flat = jnp.ravel(a)
    idx = jnp.asarray(storage_offset, jnp.int32)
    for d, (sz, st) in enumerate(zip(size, stride)):
        shape = [1] * len(size)
        shape[d] = sz
        idx = idx + (jnp.arange(sz, dtype=jnp.int32) * st).reshape(shape)
    return flat[idx]


def _as_strided_scatter(a, src, size, stride, storage_offset=0):
    flat = jnp.ravel(a)
    idx = jnp.asarray(storage_offset, jnp.int32)
    for d, (sz, st) in enumerate(zip(size, stride)):
        shape = [1] * len(size)
        shape[d] = sz
        idx = idx + (jnp.arange(sz, dtype=jnp.int32) * st).reshape(shape)
    return flat.at[jnp.ravel(idx)].set(jnp.ravel(src)).reshape(a.shape)


def _sum_to_size(a, *size):
    if len(size) == 1 and isinstance(size[0], (tuple, list)):
        size = tuple(size[0])
    lead = a.ndim - len(size)
    out = jnp.sum(a, axis=tuple(range(lead))) if lead > 0 else a
    axes = tuple(i for i, s in enumerate(size) if s == 1 and out.shape[i] != 1)
    if axes:
        out = jnp.sum(out, axis=axes, keepdims=True)
    return out


def _masked_scatter(a, mask, source):
    mask_b = jnp.broadcast_to(mask, a.shape)
    flat_m = jnp.ravel(mask_b)
    pos = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
    src = jnp.ravel(source)
    pos = jnp.clip(pos, 0, max(src.shape[0] - 1, 0))
    return jnp.where(flat_m, src[pos], jnp.ravel(a)).reshape(a.shape)


def _index_fill(a, dim, index, value):
    moved = jnp.moveaxis(a, dim, 0)
    out = moved.at[index].set(value)
    return jnp.moveaxis(out, 0, dim)


def _scatter_nd_along(a, dim, index, src, mode, include_self=True):
    """scatter/scatter_reduce along dim: index has src's shape (torch)."""
    moved = jnp.moveaxis(a, dim, -1)
    idx = jnp.moveaxis(index, dim, -1)
    s = jnp.moveaxis(src, dim, -1) if hasattr(src, "ndim") and getattr(src, "ndim", 0) else src
    lead = moved.shape[:-1]
    R = int(np.prod(lead)) if lead else 1
    flat = moved.reshape(R, moved.shape[-1])
    idx2 = idx.reshape(R, idx.shape[-1])
    rows = jnp.arange(R, dtype=jnp.int32)[:, None]
    if hasattr(s, "ndim") and getattr(s, "ndim", 0):
        s2 = s.reshape(R, s.shape[-1]).astype(flat.dtype)
    else:
        s2 = jnp.full(idx2.shape, s, flat.dtype)
    if mode == "set":
        out = flat.at[rows, idx2].set(s2)
    elif mode == "sum":
        base = flat if include_self else flat.at[rows, idx2].set(0.0)
        out = base.at[rows, idx2].add(s2)
    elif mode == "prod":
        base = flat if include_self else flat.at[rows, idx2].set(1.0)
        out = base.at[rows, idx2].multiply(s2)
    elif mode == "amax":
        base = flat if include_self else flat.at[rows, idx2].set(-jnp.inf)
        out = base.at[rows, idx2].max(s2)
    elif mode == "amin":
        base = flat if include_self else flat.at[rows, idx2].set(jnp.inf)
        out = base.at[rows, idx2].min(s2)
    elif mode == "mean":
        ssum = (flat if include_self else flat.at[rows, idx2].set(0.0)).at[rows, idx2].add(s2)
        ones = jnp.ones_like(s2)
        cnt = (jnp.ones_like(flat) if include_self
               else jnp.ones_like(flat).at[rows, idx2].set(0.0)).at[rows, idx2].add(ones)
        out = ssum / cnt
    else:
        raise NotImplementedError(f"scatter_reduce mode {mode!r}")
    return jnp.moveaxis(out.reshape(*lead, moved.shape[-1]), -1, dim)


def _combinations(a, r=2, with_replacement=False):
    n = a.shape[0]
    gen = itertools.combinations_with_replacement if with_replacement else itertools.combinations
    idx = np.array(list(gen(range(n), r)), np.int32).reshape(-1, r)
    return a[jnp.asarray(idx)]


def _cartesian_prod(*ts):
    grids = jnp.meshgrid(*ts, indexing="ij")
    stacked = jnp.stack([g.ravel() for g in grids], axis=-1)
    return stacked[:, 0] if len(ts) == 1 else stacked


def _constant_pad_nd(a, pad, value=0.0):
    # torch pad format: last dim first, (left, right) pairs
    cfg = [(0, 0)] * a.ndim
    for i in range(len(pad) // 2):
        cfg[a.ndim - 1 - i] = (int(pad[2 * i]), int(pad[2 * i + 1]))
    return jnp.pad(a, cfg, constant_values=value)


def _conv_tbc(a, weight, bias, pad=0):
    # a (T, B, C_in), weight (K, C_in, C_out) -> (T_out, B, C_out)
    x = jnp.transpose(a, (1, 2, 0))  # (B, C_in, T)
    w = jnp.transpose(weight, (2, 1, 0))  # (C_out, C_in, K)
    out = jax.lax.conv_general_dilated(x, w, (1,), [(int(pad), int(pad))],
                                       dimension_numbers=("NCH", "OIH", "NCH"))
    return jnp.transpose(out, (2, 0, 1)) + bias


def _norm_except_dim(v, pow=2, dim=0):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sum(jnp.abs(v) ** pow, axis=axes, keepdims=True) ** (1.0 / pow)


def _unravel_index(indices, shape):
    return tuple(jnp.unravel_index(indices, tuple(shape)))  # torch returns a tuple


def _lu_pieces(a):
    import jax.scipy.linalg as jsl

    p, l, u = jsl.lu(a)
    return p, l, u


def _lu_factor(a):
    import jax.scipy.linalg as jsl

    lu, piv = jsl.lu_factor(a)
    return lu, piv.astype(jnp.int32) + 1  # torch pivots are 1-based


def _lu_solve(b, lu_data, lu_pivots):
    import jax.scipy.linalg as jsl

    return jsl.lu_solve((lu_data, lu_pivots.astype(jnp.int32) - 1), b)


def _lu_unpack(lu_data, lu_pivots, unpack_data=True, unpack_pivots=True):
    m, n = lu_data.shape[-2:]
    k = min(m, n)
    L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
    U = jnp.triu(lu_data[..., :k, :])
    piv = lu_pivots.astype(jnp.int32) - 1

    def swap_seq(piv1d):
        def body(i, p):
            j = piv1d[i]
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)

        return jax.lax.fori_loop(0, piv1d.shape[0], body, jnp.arange(m, dtype=jnp.int32))

    if lu_pivots.ndim == 1:
        perm = swap_seq(piv)
        P = jnp.eye(m, dtype=lu_data.dtype)[:, perm]
    else:
        flat = piv.reshape(-1, piv.shape[-1])
        perms = jax.vmap(swap_seq)(flat)
        P = jax.vmap(lambda p: jnp.eye(m, dtype=lu_data.dtype)[:, p])(perms)
        P = P.reshape(piv.shape[:-1] + (m, m))
    return P, L, U


def _solve_triangular(a, b, upper=True, left=True, unitriangular=False):
    import jax.scipy.linalg as jsl

    # torch broadcasts batch dims; jax requires them to match
    bshape = jnp.broadcast_shapes(a.shape[:-2], b.shape[:-2])
    a = jnp.broadcast_to(a, bshape + a.shape[-2:])
    b = jnp.broadcast_to(b, bshape + b.shape[-2:])
    if not left:  # solve X·A = B  via  Aᵀ·Xᵀ = Bᵀ
        out = jsl.solve_triangular(jnp.swapaxes(a, -2, -1), jnp.swapaxes(b, -2, -1),
                                   lower=upper, unit_diagonal=unitriangular)
        return jnp.swapaxes(out, -2, -1)
    return jsl.solve_triangular(a, b, lower=not upper, unit_diagonal=unitriangular)


def _tensorinv(a, ind=2):
    lead = a.shape[:ind]
    n = int(np.prod(a.shape[ind:]))
    inv = jnp.linalg.inv(a.reshape(int(np.prod(lead)), n))
    return inv.reshape(a.shape[ind:] + lead)


def _poly_recurrence(x, n, init0, init1, rec):
    """Orthogonal-polynomial families via their 3-term recurrence (static n)."""
    n = int(n)
    if n == 0:
        return jnp.broadcast_to(jnp.asarray(init0, x.dtype), x.shape) * jnp.ones_like(x)
    pm1 = jnp.ones_like(x) * init0
    p = init1(x)
    for k in range(1, n):
        pm1, p = p, rec(k, x, p, pm1)
    return p


def chebyshev_t(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: x, lambda k, x, p, pm1: 2 * x * p - pm1)


def chebyshev_u(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: 2 * x, lambda k, x, p, pm1: 2 * x * p - pm1)


def chebyshev_v(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: 2 * x - 1, lambda k, x, p, pm1: 2 * x * p - pm1)


def chebyshev_w(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: 2 * x + 1, lambda k, x, p, pm1: 2 * x * p - pm1)


def hermite_h(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: 2 * x,
                            lambda k, x, p, pm1: 2 * x * p - 2 * k * pm1)


def hermite_he(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: x,
                            lambda k, x, p, pm1: x * p - k * pm1)


def laguerre_l(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: 1 - x,
                            lambda k, x, p, pm1: ((2 * k + 1 - x) * p - k * pm1) / (k + 1))


def legendre_p(x, n):
    return _poly_recurrence(x, n, 1.0, lambda x: x,
                            lambda k, x, p, pm1: ((2 * k + 1) * x * p - k * pm1) / (k + 1))


def _bessel_k0(x):
    """A&S 9.8.5/9.8.6 polynomial approximations (differentiable)."""
    x = jnp.asarray(x, jnp.float32) if jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer) else x
    small = x <= 2.0
    xs = jnp.where(small, x, 2.0)
    t = (xs / 2.0) ** 2
    i0 = jax.scipy.special.i0(xs)
    k0_small = (-jnp.log(xs / 2.0) * i0 - 0.57721566
                + t * (0.42278420 + t * (0.23069756 + t * (0.03488590
                + t * (0.00262698 + t * (0.00010750 + t * 0.00000740))))))
    xl = jnp.where(small, 2.0, x)
    u = 2.0 / xl
    k0_large = (jnp.exp(-xl) / jnp.sqrt(xl)) * (1.25331414 + u * (-0.07832358
                + u * (0.02189568 + u * (-0.01062446 + u * (0.00587872
                + u * (-0.00251540 + u * 0.00053208))))))
    return jnp.where(small, k0_small, k0_large)


def _bessel_k1(x):
    small = x <= 2.0
    xs = jnp.where(small, x, 2.0)
    t = (xs / 2.0) ** 2
    i1 = jax.scipy.special.i1(xs)
    k1_small = (jnp.log(xs / 2.0) * i1 + (1.0 / xs) * (1.0
                + t * (0.15443144 + t * (-0.67278579 + t * (-0.18156897
                + t * (-0.01919402 + t * (-0.00110404 + t * (-0.00004686))))))))
    xl = jnp.where(small, 2.0, x)
    u = 2.0 / xl
    k1_large = (jnp.exp(-xl) / jnp.sqrt(xl)) * (1.25331414 + u * (0.23498619
                + u * (-0.03655620 + u * (0.01504268 + u * (-0.00780353
                + u * (0.00325614 + u * (-0.00068245)))))))
    return jnp.where(small, k1_small, k1_large)


def _bessel_j0(x):
    """J0 via the standard rational/asymptotic split (jax's bessel_jn
    backward recurrence NaNs in f32)."""
    ax = jnp.abs(x)
    xs = jnp.where(ax <= 8.0, ax, 8.0)
    y = xs * xs
    num = (57568490574.0 + y * (-13362590354.0 + y * (651619640.7
           + y * (-11214424.18 + y * (77392.33017 + y * (-184.9052456))))))
    den = (57568490411.0 + y * (1029532985.0 + y * (9494680.718
           + y * (59272.64853 + y * (267.8532712 + y)))))
    small = num / den
    axl = jnp.where(ax <= 8.0, 8.0, ax)
    z = 8.0 / axl
    y2 = z * z
    xx = axl - 0.785398164
    p0 = (1.0 + y2 * (-0.1098628627e-2 + y2 * (0.2734510407e-4
          + y2 * (-0.2073370639e-5 + y2 * 0.2093887211e-6))))
    q0 = (-0.1562499995e-1 + y2 * (0.1430488765e-3 + y2 * (-0.6911147651e-5
          + y2 * (0.7621095161e-6 + y2 * (-0.934935152e-7)))))
    large = jnp.sqrt(0.636619772 / axl) * (jnp.cos(xx) * p0 - z * jnp.sin(xx) * q0)
    return jnp.where(ax <= 8.0, small, large)


def _bessel_j1(x):
    ax = jnp.abs(x)
    xs = jnp.where(ax <= 8.0, ax, 8.0)
    y = xs * xs
    num = xs * (72362614232.0 + y * (-7895059235.0 + y * (242396853.1
          + y * (-2972611.439 + y * (15704.48260 + y * (-30.16036606))))))
    den = (144725228442.0 + y * (2300535178.0 + y * (18583304.74
          + y * (99447.43394 + y * (376.9991397 + y)))))
    small = num / den
    axl = jnp.where(ax <= 8.0, 8.0, ax)
    z = 8.0 / axl
    y2 = z * z
    xx = axl - 2.356194491
    p1 = (1.0 + y2 * (0.183105e-2 + y2 * (-0.3516396496e-4
          + y2 * (0.2457520174e-5 + y2 * (-0.240337019e-6)))))
    q1 = (0.04687499995 + y2 * (-0.2002690873e-3 + y2 * (0.8449199096e-5
          + y2 * (-0.88228987e-6 + y2 * 0.105787412e-6))))
    large = jnp.sqrt(0.636619772 / axl) * (jnp.cos(xx) * p1 - z * jnp.sin(xx) * q1)
    return jnp.sign(x) * jnp.where(ax <= 8.0, small, large)


def _bessel_j(x, v):
    return _bessel_j0(x) if v == 0 else _bessel_j1(x)


def _adaptive_pool_slices(in_size: int, out_size: int):
    """torch adaptive pooling window boundaries (static)."""
    return [(int(math.floor(i * in_size / out_size)),
             int(math.ceil((i + 1) * in_size / out_size))) for i in range(out_size)]


def _adaptive_avg_pool1d(a, output_size):
    out_size = output_size[0] if isinstance(output_size, (tuple, list)) else int(output_size)
    L = a.shape[-1]
    cols = [jnp.mean(a[..., s:e], axis=-1) for s, e in _adaptive_pool_slices(L, out_size)]
    return jnp.stack(cols, axis=-1)


def _adaptive_max_pool1d(a, output_size, return_indices=False):
    out_size = output_size[0] if isinstance(output_size, (tuple, list)) else int(output_size)
    L = a.shape[-1]
    vals, idxs = [], []
    for s, e in _adaptive_pool_slices(L, out_size):
        win = a[..., s:e]
        vals.append(jnp.max(win, axis=-1))
        idxs.append(jnp.argmax(win, axis=-1) + s)
    v = jnp.stack(vals, -1)
    if return_indices:
        return v, jnp.stack(idxs, -1).astype(jnp.int32)
    return v


def _adaptive_avg_pool3d(a, output_size):
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    D, H, W = a.shape[-3:]
    od, oh, ow = (int(o) if o is not None else s for o, s in zip(output_size, (D, H, W)))
    planes = []
    for sd, ed in _adaptive_pool_slices(D, od):
        rows = []
        for sh, eh in _adaptive_pool_slices(H, oh):
            cols = [jnp.mean(a[..., sd:ed, sh:eh, sw:ew], axis=(-3, -2, -1))
                    for sw, ew in _adaptive_pool_slices(W, ow)]
            rows.append(jnp.stack(cols, -1))
        planes.append(jnp.stack(rows, -2))
    return jnp.stack(planes, -3)


def _adaptive_max_pool3d(a, output_size, return_indices=False):
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    D, H, W = a.shape[-3:]
    od, oh, ow = (int(o) if o is not None else s for o, s in zip(output_size, (D, H, W)))
    planes = []
    for sd, ed in _adaptive_pool_slices(D, od):
        rows = []
        for sh, eh in _adaptive_pool_slices(H, oh):
            cols = [jnp.max(a[..., sd:ed, sh:eh, sw:ew], axis=(-3, -2, -1))
                    for sw, ew in _adaptive_pool_slices(W, ow)]
            rows.append(jnp.stack(cols, -1))
        planes.append(jnp.stack(rows, -2))
    out = jnp.stack(planes, -3)
    if return_indices:
        raise NotImplementedError("adaptive_max_pool3d with indices is not supported")
    return out


def _windowed_extrema_pool(a, ndims, kernel_size, stride=None, padding=0, return_indices=False,
                           dilation=1, ceil_mode=False):
    """max_pool{1,2,3}d_with_indices via static window extraction."""
    if ceil_mode:
        raise NotImplementedError("ceil_mode pooling is not supported in the auto catalog")
    ks = (kernel_size,) * ndims if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None or stride == [] else (
        (stride,) * ndims if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * ndims if isinstance(padding, int) else tuple(padding)
    dl = (dilation,) * ndims if isinstance(dilation, int) else tuple(dilation)
    neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
    cfg = [(0, 0)] * (a.ndim - ndims) + [(p, p) for p in pd]
    ap = jnp.pad(a, cfg, constant_values=neg)
    spatial = ap.shape[-ndims:]
    out_sizes = [(spatial[d] - dl[d] * (ks[d] - 1) - 1) // st[d] + 1 for d in range(ndims)]
    # windows: gather one slice per kernel offset (static python loop)
    wins, flat_off = [], []
    for off in itertools.product(*[range(k) for k in ks]):
        sl = [slice(None)] * (a.ndim - ndims)
        for d in range(ndims):
            start = off[d] * dl[d]
            sl.append(slice(start, start + st[d] * (out_sizes[d] - 1) + 1, st[d]))
        wins.append(ap[tuple(sl)])
        flat_off.append(off)
    stack = jnp.stack(wins, axis=0)
    arg = jnp.argmax(stack, axis=0)
    val = jnp.max(stack, axis=0)
    if not return_indices:
        return val
    # recover flat input indices (torch contract: index into the UNpadded input)
    offsets = jnp.asarray(np.array(flat_off, np.int32))  # (n_windows, ndims)
    grids = jnp.meshgrid(*[jnp.arange(o) * s for o, s in zip(out_sizes, st)], indexing="ij")
    pos = [offsets[:, d][arg] * dl[d] + grids[d] - pd[d] for d in range(ndims)]
    in_spatial = a.shape[-ndims:]
    flat = pos[0]
    for d in range(1, ndims):
        flat = flat * in_spatial[d] + pos[d]
    return val, flat.astype(jnp.int64 if False else jnp.int32)


def _max_unpool(a, indices, ndims, kernel_size, stride=None, padding=0, output_size=None):
    if output_size is None:
        ks = (kernel_size,) * ndims if isinstance(kernel_size, int) else tuple(kernel_size)
        st = ks if stride is None or stride == [] else (
            (stride,) * ndims if isinstance(stride, int) else tuple(stride))
        pd = (padding,) * ndims if isinstance(padding, int) else tuple(padding)
        out_spatial = [(a.shape[-ndims + d] - 1) * st[d] - 2 * pd[d] + ks[d] for d in range(ndims)]
    else:
        out_spatial = [int(s) for s in tuple(output_size)[-ndims:]]
    lead = a.shape[:-ndims]
    n = int(np.prod(out_spatial))
    flat_in = a.reshape(lead + (-1,))
    flat_idx = indices.reshape(lead + (-1,)).astype(jnp.int32)
    out = jnp.zeros(lead + (n,), a.dtype)
    R = int(np.prod(lead)) if lead else 1
    o2 = out.reshape(R, n)
    i2 = flat_idx.reshape(R, -1)
    v2 = flat_in.reshape(R, -1)
    o2 = o2.at[jnp.arange(R, dtype=jnp.int32)[:, None], i2].set(v2)
    return o2.reshape(lead + tuple(out_spatial))


def _lp_pool(a, ndims, norm_type, kernel_size, stride=None, ceil_mode=False):
    if ceil_mode:
        raise NotImplementedError("ceil_mode lp_pool is not supported")
    ks = (kernel_size,) * ndims if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else ((stride,) * ndims if isinstance(stride, int) else tuple(stride))
    p = float(norm_type)
    powed = jnp.abs(a) ** p
    window = (1,) * (a.ndim - ndims) + ks
    strides = (1,) * (a.ndim - ndims) + st
    summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, window, strides, "VALID")
    return summed ** (1.0 / p)


def _pdist(a, p=2.0):
    n = a.shape[0]
    iu = np.triu_indices(n, 1)
    diff = a[jnp.asarray(iu[0])] - a[jnp.asarray(iu[1])]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
    return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)


def _bilinear(x1, x2, weight, bias=None):
    out = jnp.einsum("...i,oij,...j->...o", x1, weight, x2)
    return out if bias is None else out + bias


def _ctc_loss(log_probs, targets, input_lengths, target_lengths, blank=0,
              reduction="mean", zero_infinity=False):
    """torch F.ctc_loss((T,N,C) log_probs) via optax.ctc_loss ((N,T,C))."""
    import optax

    lp = jnp.transpose(log_probs, (1, 0, 2))  # (N, T, C)
    N, T, C = lp.shape
    S = targets.shape[-1] if targets.ndim == 2 else int(targets.shape[0])
    tg = targets if targets.ndim == 2 else targets.reshape(N, -1)
    t_arange = jnp.arange(T)[None, :]
    s_arange = jnp.arange(tg.shape[1])[None, :]
    logit_pad = (t_arange >= jnp.asarray(input_lengths).reshape(N, 1)).astype(lp.dtype)
    label_pad = (s_arange >= jnp.asarray(target_lengths).reshape(N, 1)).astype(lp.dtype)
    per_seq = optax.ctc_loss(lp, logit_pad, tg, label_pad, blank_id=blank)
    if zero_infinity:
        per_seq = jnp.where(jnp.isfinite(per_seq), per_seq, 0.0)
    if reduction == "mean":
        # torch divides each sequence loss by its target length before averaging
        return jnp.mean(per_seq / jnp.maximum(jnp.asarray(target_lengths, per_seq.dtype), 1.0))
    if reduction == "sum":
        return jnp.sum(per_seq)
    return per_seq


def _grid_sample(a, grid, mode="bilinear", padding_mode="zeros", align_corners=False):
    """2-D grid_sample, NCHW input + NHW2 grid (torch contract subset)."""
    if a.ndim != 4 or grid.ndim != 4:
        raise NotImplementedError("grid_sample supports 4-D input (NCHW) only")
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(f"grid_sample padding_mode={padding_mode!r}")
    N, C, H, W = a.shape

    def unnorm(coord, size):
        if align_corners:
            return (coord + 1.0) * 0.5 * (size - 1)
        return ((coord + 1.0) * size - 1.0) * 0.5

    gx = unnorm(grid[..., 0], W)
    gy = unnorm(grid[..., 1], H)

    def sample(iy, ix):
        inside = (iy >= 0) & (iy < H) & (ix >= 0) & (ix < W)
        iyc = jnp.clip(iy, 0, H - 1)
        ixc = jnp.clip(ix, 0, W - 1)
        v = a[jnp.arange(N)[:, None, None], :, iyc, ixc]  # (N, Ho, Wo, C)
        if padding_mode == "zeros":
            v = jnp.where(inside[..., None], v, 0.0)
        return v

    if mode == "nearest":
        out = sample(jnp.round(gy).astype(jnp.int32), jnp.round(gx).astype(jnp.int32))
    elif mode == "bilinear":
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (gx - x0)[..., None]
        wy = (gy - y0)[..., None]
        out = (sample(y0, x0) * (1 - wy) * (1 - wx) + sample(y0, x1) * (1 - wy) * wx
               + sample(y1, x0) * wy * (1 - wx) + sample(y1, x1) * wy * wx)
    else:
        raise NotImplementedError(f"grid_sample mode={mode!r}")
    return jnp.transpose(out, (0, 3, 1, 2))


def _affine_grid(theta, size, align_corners=False):
    N, C, H, W = size

    def lin(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys, xs = jnp.meshgrid(lin(H), lin(W), indexing="ij")
    base = jnp.stack([xs, ys, jnp.ones_like(xs)], -1)  # (H, W, 3)
    return jnp.einsum("hwk,nik->nhwi", base, theta)


def _gru_cell(x, hx, w_ih, w_hh, b_ih=None, b_hh=None):
    gi = x @ w_ih.T + (0 if b_ih is None else b_ih)
    gh = hx @ w_hh.T + (0 if b_hh is None else b_hh)
    H = hx.shape[-1]
    ir, iz, in_ = gi[..., :H], gi[..., H:2 * H], gi[..., 2 * H:]
    hr, hz, hn = gh[..., :H], gh[..., H:2 * H], gh[..., 2 * H:]
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return n + z * (hx - n)


def _lstm_cell(x, hidden, w_ih, w_hh, b_ih=None, b_hh=None):
    hx, cx = hidden
    g = x @ w_ih.T + hx @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    H = hx.shape[-1]
    i = jax.nn.sigmoid(g[..., :H])
    f = jax.nn.sigmoid(g[..., H:2 * H])
    c_t = jnp.tanh(g[..., 2 * H:3 * H])
    o = jax.nn.sigmoid(g[..., 3 * H:])
    c = f * cx + i * c_t
    return o * jnp.tanh(c), c


def _rnn_cell(x, hx, w_ih, w_hh, b_ih=None, b_hh=None, fn=jnp.tanh):
    g = x @ w_ih.T + hx @ w_hh.T
    if b_ih is not None:
        g = g + b_ih
    if b_hh is not None:
        g = g + b_hh
    return fn(g)


def _stft(a, n_fft, hop_length=None, win_length=None, window=None, center=True,
          pad_mode="reflect", normalized=False, onesided=True, return_complex=True):
    if not return_complex:
        raise NotImplementedError("stft with return_complex=False is not supported")
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    win = jnp.ones(wl) if window is None else window
    if wl < n_fft:
        pad = (n_fft - wl) // 2
        win = jnp.pad(win, (pad, n_fft - wl - pad))
    x = a if a.ndim == 2 else a[None]
    if center:
        x = jnp.pad(x, ((0, 0), (n_fft // 2, n_fft // 2)),
                    mode="reflect" if pad_mode == "reflect" else "constant")
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop
    starts = np.arange(n_frames) * hop
    frames = jnp.stack([x[:, s:s + n_fft] for s in starts], 1) * win  # (B, F, n_fft)
    spec = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
    spec = jnp.swapaxes(spec, 1, 2)  # (B, freq, frames)
    if normalized:
        spec = spec / math.sqrt(n_fft)  # torch: frame_length**-0.5
    return spec if a.ndim == 2 else spec[0]


def _istft(spec, n_fft, hop_length=None, win_length=None, window=None, center=True,
           normalized=False, onesided=True, length=None, return_complex=False):
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    win = jnp.ones(wl) if window is None else window
    if wl < n_fft:
        pad = (n_fft - wl) // 2
        win = jnp.pad(win, (pad, n_fft - wl - pad))
    x = spec if spec.ndim == 3 else spec[None]
    if normalized:
        x = x * math.sqrt(n_fft)  # inverse of torch's frame_length**-0.5
    frames = jnp.fft.irfft(jnp.swapaxes(x, 1, 2), n=n_fft, axis=-1) if onesided \
        else jnp.real(jnp.fft.ifft(jnp.swapaxes(x, 1, 2), axis=-1))
    frames = frames * win
    n_frames = frames.shape[1]
    T = n_fft + hop * (n_frames - 1)
    out = jnp.zeros((frames.shape[0], T), frames.dtype)
    wsum = jnp.zeros((T,), frames.dtype)
    for i in range(n_frames):
        out = out.at[:, i * hop:i * hop + n_fft].add(frames[:, i])
        wsum = wsum.at[i * hop:i * hop + n_fft].add(win ** 2)
    out = out / jnp.maximum(wsum, 1e-11)
    if center:
        out = out[:, n_fft // 2: T - n_fft // 2]
    if length is not None:
        out = out[:, :length]
    return out if spec.ndim == 3 else out[0]


def _batch_norm_stats(a, eps):
    axes = (0,) + tuple(range(2, a.ndim))
    mean = jnp.mean(a, axes)
    var = jnp.var(a, axes)
    return mean, jax.lax.rsqrt(var + eps)


def _native_layer_norm(a, normalized_shape, weight, bias, eps):
    nd = len(tuple(normalized_shape))
    axes = tuple(range(a.ndim - nd, a.ndim))
    mean = jnp.mean(a, axes, keepdims=True)
    var = jnp.var(a, axes, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    out = (a - mean) * rstd
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out, mean, rstd


def _native_group_norm(a, weight, bias, N, C, HxW, group, eps):
    x = a.reshape(N, group, C // group, -1)
    mean = jnp.mean(x, axis=(2, 3), keepdims=True)
    var = jnp.var(x, axis=(2, 3), keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    out = ((x - mean) * rstd).reshape(a.shape)
    if weight is not None:
        out = out * weight.reshape(1, C, *([1] * (a.ndim - 2)))
    if bias is not None:
        out = out + bias.reshape(1, C, *([1] * (a.ndim - 2)))
    return out, mean.reshape(N, group), rstd.reshape(N, group)


# ---------------------------------------------------------------------------
# wave 6 — differentiable long tail (real torch-contract names)
# ---------------------------------------------------------------------------

EXT_DIFF: dict[str, Callable] = {
    # ---- dtype-cast Tensor methods (Tensor.bfloat16() etc.) ----
    "bfloat16": lambda a: a.astype(jnp.bfloat16),
    "half": lambda a: a.astype(jnp.float16),
    "double": lambda a: a.astype(jnp.float64),
    "cfloat": lambda a: a.astype(jnp.complex64),
    "cdouble": lambda a: a.astype(jnp.complex128),
    "chalf": lambda a: a.astype(jnp.complex64),  # jax has no complex32
    # ---- comparison/elementwise aliases ----
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "less": jnp.less,
    "less_equal": jnp.less_equal,
    "not_equal": jnp.not_equal,
    "clip": lambda a, min=None, max=None: jnp.clip(a, min, max),
    "sgn": lambda a: jnp.where(a == 0, 0, a / jnp.abs(a)) if jnp.iscomplexobj(a) else jnp.sign(a),
    "hypot": jnp.hypot,
    "heaviside": jnp.heaviside,
    "logaddexp": jnp.logaddexp,
    "logaddexp2": jnp.logaddexp2,
    "rsub": lambda a, b, alpha=1.0: b - alpha * a,
    "trapz": lambda y, x=None, dim=-1: jnp.trapezoid(y, x, axis=dim),
    "frac": lambda a: a - jnp.trunc(a),
    "nanmean": lambda a, dim=None, keepdim=False: jnp.nanmean(a, axis=dim, keepdims=keepdim),
    "nansum": lambda a, dim=None, keepdim=False: jnp.nansum(a, axis=dim, keepdims=keepdim),
    "aminmax": lambda a, dim=None, keepdim=False: (
        jnp.min(a, axis=dim, keepdims=keepdim), jnp.max(a, axis=dim, keepdims=keepdim)),
    "dist": lambda a, b, p=2.0: jnp.sum(jnp.abs(a - b) ** p) ** (1.0 / p),
    "absolute": jnp.abs,
    "negative": jnp.negative,
    "swapaxes": lambda a, d0, d1: jnp.swapaxes(a, d0, d1),
    "ravel": jnp.ravel,
    "cummax": lambda a, dim: (jax.lax.cummax(a, axis=dim),
                              _cummax_indices(a, dim)),
    "cumprod": lambda a, dim, dtype=None: jnp.cumprod(
        a if dtype is None else a.astype(dtype), axis=dim),
    "median": lambda a, dim=None, keepdim=False: _median(a, dim, keepdim),
    # ---- linear algebra long tail ----
    "dot": jnp.dot,
    "vdot": jnp.vdot,
    "mv": jnp.matmul,
    "tensordot": lambda a, b, dims=2: jnp.tensordot(a, b, axes=dims),
    "kron": jnp.kron,
    "chain_matmul": lambda *ms: jnp.linalg.multi_dot(ms),
    "matrix_power": jnp.linalg.matrix_power,
    "pinverse": jnp.linalg.pinv,
    "inverse": jnp.linalg.inv,
    "logdet": lambda a: jnp.linalg.slogdet(a)[1],
    "det": jnp.linalg.det,
    "slogdet": jnp.linalg.slogdet,
    "cholesky": lambda a, upper=False: jnp.swapaxes(jnp.conjugate(jnp.linalg.cholesky(a)), -2, -1)
        if upper else jnp.linalg.cholesky(a),
    "qr": lambda a, some=True: jnp.linalg.qr(a, mode="reduced" if some else "complete"),
    # torch.svd returns V (a == U @ diag(S) @ V^H), jax returns Vh
    "svd": lambda a, some=True, compute_uv=True: _torch_svd(a, some)
        if compute_uv else jnp.linalg.svd(a, compute_uv=False),
    "frobenius_norm": lambda a, dim=None, keepdim=False: jnp.sqrt(
        jnp.sum(a * a, axis=tuple(dim) if isinstance(dim, (list, tuple)) else dim,
                keepdims=keepdim)),
    "nuclear_norm": lambda a, keepdim=False: jnp.sum(jnp.linalg.svd(a, compute_uv=False)),
    "norm_except_dim": _norm_except_dim,
    "linalg_cholesky_ex": lambda a, upper=False, check_errors=False: (
        jnp.linalg.cholesky(a), jnp.zeros(a.shape[:-2], jnp.int32)),
    "linalg_inv_ex": lambda a, check_errors=False: (
        jnp.linalg.inv(a), jnp.zeros(a.shape[:-2], jnp.int32)),
    "linalg_solve_ex": lambda a, b, left=True, check_errors=False: (
        jnp.linalg.solve(a, b) if left else jnp.swapaxes(
            jnp.linalg.solve(jnp.swapaxes(a, -2, -1), jnp.swapaxes(b, -2, -1)), -2, -1),
        jnp.zeros(a.shape[:-2], jnp.int32)),
    "linalg_lu": lambda a, pivot=True: _lu_pieces(a),
    "linalg_lu_factor": _lu_factor,
    "linalg_lu_factor_ex": lambda a, pivot=True, check_errors=False: (
        *_lu_factor(a), jnp.zeros(a.shape[:-2], jnp.int32)),
    "linalg_lu_solve": lambda lu, piv, b, left=True, adjoint=False: _lu_solve(b, lu, piv),
    "lu_solve": _lu_solve,  # torch.lu_solve(b, LU_data, LU_pivots)
    "lu_unpack": _lu_unpack,
    "linalg_solve_triangular": lambda a, b, upper=True, left=True, unitriangular=False:
        _solve_triangular(a, b, upper, left, unitriangular),
    "linalg_tensorinv": _tensorinv,
    "linalg_eig": jnp.linalg.eig,
    "linalg_eigvals": jnp.linalg.eigvals,
    "matrix_exp_": jax.scipy.linalg.expm,
    # ---- fft remainder ----
    "fft_hfft": lambda a, n=None, dim=-1, norm=None: jnp.fft.hfft(a, n=n, axis=dim, norm=norm),
    "fft_ihfft": lambda a, n=None, dim=-1, norm=None: jnp.fft.ihfft(a, n=n, axis=dim, norm=norm),
    "fft_rfftn": lambda a, s=None, dim=None, norm=None: jnp.fft.rfftn(a, s=s, axes=dim, norm=norm),
    "fft_irfftn": lambda a, s=None, dim=None, norm=None: jnp.fft.irfftn(a, s=s, axes=dim, norm=norm),
    "fft_fftfreq": lambda n, d=1.0: jnp.fft.fftfreq(n, d),
    "fft_rfftfreq": lambda n, d=1.0: jnp.fft.rfftfreq(n, d),
    # ---- special remainder ----
    "special_modified_bessel_i0": jax.scipy.special.i0,
    "special_modified_bessel_i1": jax.scipy.special.i1,
    "special_modified_bessel_k0": _bessel_k0,
    "special_modified_bessel_k1": _bessel_k1,
    "special_scaled_modified_bessel_k0": lambda x: _bessel_k0(x) * jnp.exp(x),
    "special_scaled_modified_bessel_k1": lambda x: _bessel_k1(x) * jnp.exp(x),
    "special_bessel_j0": lambda x: _bessel_j(x, 0),
    "special_bessel_j1": lambda x: _bessel_j(x, 1),
    "special_spherical_bessel_j0": lambda x: jnp.sinc(x / jnp.pi),
    "special_chebyshev_polynomial_t": chebyshev_t,
    "special_chebyshev_polynomial_u": chebyshev_u,
    "special_chebyshev_polynomial_v": chebyshev_v,
    "special_chebyshev_polynomial_w": chebyshev_w,
    "special_shifted_chebyshev_polynomial_t": lambda x, n: chebyshev_t(2 * x - 1, n),
    "special_shifted_chebyshev_polynomial_u": lambda x, n: chebyshev_u(2 * x - 1, n),
    "special_shifted_chebyshev_polynomial_v": lambda x, n: chebyshev_v(2 * x - 1, n),
    "special_shifted_chebyshev_polynomial_w": lambda x, n: chebyshev_w(2 * x - 1, n),
    "special_hermite_polynomial_h": hermite_h,
    "special_hermite_polynomial_he": hermite_he,
    "special_laguerre_polynomial_l": laguerre_l,
    "special_legendre_polynomial_p": legendre_p,
    # ---- views/copies (functional backend: *_copy == the view op) ----
    "expand_copy": lambda a, size, implicit=False: jnp.broadcast_to(
        a, tuple(a.shape[i - (len(size) - a.ndim)] if s == -1 else s
                 for i, s in enumerate(size))),
    "permute_copy": lambda a, dims: jnp.transpose(a, tuple(dims)),
    "squeeze_copy": lambda a, dim=None: jnp.squeeze(a, dim),
    "unsqueeze_copy": lambda a, dim: jnp.expand_dims(a, dim),
    "transpose_copy": lambda a, dim0, dim1: jnp.swapaxes(a, dim0, dim1),
    "t_copy": lambda a: a.T,
    "view_copy": lambda a, size: jnp.reshape(a, tuple(size)),
    "detach_copy": lambda a: a,
    "diagonal_copy": lambda a, offset=0, dim1=0, dim2=1: jnp.diagonal(a, offset, dim1, dim2),
    "slice_copy": lambda a, dim=0, start=None, end=None, step=1: jax.lax.slice_in_dim(
        a, start or 0, a.shape[dim] if end is None or end > a.shape[dim] else end,
        stride=step, axis=dim),
    "select_copy": lambda a, dim, index: jnp.take(a, index, axis=dim),
    "split_copy": lambda a, split_size, dim=0: tuple(
        jnp.split(a, list(range(split_size, a.shape[dim], split_size)), axis=dim)),
    "split_with_sizes": lambda a, split_sizes, dim=0: tuple(
        jnp.split(a, np.cumsum(split_sizes)[:-1].tolist(), axis=dim)),
    "split_with_sizes_copy": lambda a, split_sizes, dim=0: tuple(
        jnp.split(a, np.cumsum(split_sizes)[:-1].tolist(), axis=dim)),
    "unbind_copy": lambda a, dim=0: tuple(
        jnp.squeeze(x, dim) for x in jnp.split(a, a.shape[dim], axis=dim)),
    "unfold_copy": lambda a, dimension, size, step: _unfold_ext(a, dimension, size, step),
    "view_as_real_copy": lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1),
    "view_as_complex_copy": lambda a: jax.lax.complex(a[..., 0], a[..., 1]),
    "as_strided": _as_strided,
    "as_strided_copy": _as_strided,
    "as_strided_scatter": _as_strided_scatter,
    "narrow": lambda a, dim, start, length: jax.lax.slice_in_dim(a, start, start + length, axis=dim),
    "dsplit": lambda a, sections: tuple(jnp.dsplit(a, sections)),
    "hsplit": lambda a, sections: tuple(jnp.hsplit(a, sections)),
    "vsplit": lambda a, sections: tuple(jnp.vsplit(a, sections)),
    "unsafe_chunk": lambda a, chunks, dim=0: tuple(jnp.array_split(a, chunks, axis=dim)),
    "unsafe_split": lambda a, split_size, dim=0: tuple(
        jnp.split(a, list(range(split_size, a.shape[dim], split_size)), axis=dim)),
    "unsafe_split_with_sizes": lambda a, split_sizes, dim=0: tuple(
        jnp.split(a, np.cumsum(split_sizes)[:-1].tolist(), axis=dim)),
    # ---- construction / combination ----
    "block_diag": lambda *ts: jax.scipy.linalg.block_diag(*ts),
    "broadcast_tensors": lambda *ts: tuple(jnp.broadcast_arrays(*ts)),
    "cartesian_prod": _cartesian_prod,
    "combinations": _combinations,
    "complex": jax.lax.complex,
    "constant_pad_nd": _constant_pad_nd,
    "diag": lambda a, diagonal=0: jnp.diag(a, diagonal),
    "new_zeros": lambda a, size, dtype=None, **kw: jnp.zeros(
        tuple(size) if isinstance(size, (tuple, list)) else (size,), dtype or a.dtype),
    "new_ones": lambda a, size, dtype=None, **kw: jnp.ones(
        tuple(size) if isinstance(size, (tuple, list)) else (size,), dtype or a.dtype),
    "new_full": lambda a, size, fill_value, dtype=None, **kw: jnp.full(
        tuple(size), fill_value, dtype or a.dtype),
    "new_tensor": lambda a, data, dtype=None, **kw: jnp.asarray(data, dtype or a.dtype),
    "reshape_as": lambda a, other: jnp.reshape(a, other.shape),
    "sum_to_size": _sum_to_size,
    "scalar_tensor": lambda s, dtype=None, **kw: jnp.asarray(s, dtype),
    # ---- scatter/index family ----
    "index_fill": _index_fill,
    "masked_scatter": _masked_scatter,
    "put": lambda a, index, source, accumulate=False: (
        jnp.ravel(a).at[index].add(jnp.ravel(source)) if accumulate
        else jnp.ravel(a).at[index].set(jnp.ravel(source))).reshape(a.shape),
    "scatter_reduce": lambda a, dim, index, src, reduce, include_self=True:
        _scatter_nd_along(a, dim, index, src,
                          {"sum": "sum", "prod": "prod", "mean": "mean",
                           "amax": "amax", "amin": "amin"}[reduce], include_self),
    "index_reduce": lambda a, dim, index, source, reduce, include_self=True:
        _index_reduce(a, dim, index, source, reduce, include_self),
    "select_scatter": lambda a, src, dim, index: jnp.moveaxis(
        jnp.moveaxis(a, dim, 0).at[index].set(src), 0, dim),
    "slice_scatter": lambda a, src, dim=0, start=None, end=None, step=1: jnp.moveaxis(
        jnp.moveaxis(a, dim, 0).at[slice(start, end, step)].set(jnp.moveaxis(src, dim, 0)),
        0, dim),
    # ---- nn.functional long tail ----
    "adaptive_avg_pool1d": _adaptive_avg_pool1d,
    "adaptive_max_pool1d": _adaptive_max_pool1d,
    "adaptive_max_pool1d_with_indices": lambda a, output_size: _adaptive_max_pool1d(
        a, output_size, return_indices=True),
    "adaptive_avg_pool3d": _adaptive_avg_pool3d,
    "adaptive_max_pool3d": _adaptive_max_pool3d,
    "max_pool1d_with_indices": lambda a, kernel_size, stride=None, padding=0, dilation=1,
        ceil_mode=False: _windowed_extrema_pool(a, 1, kernel_size, stride, padding, True,
                                                dilation, ceil_mode),
    "max_pool2d_with_indices": lambda a, kernel_size, stride=None, padding=0, dilation=1,
        ceil_mode=False: _windowed_extrema_pool(a, 2, kernel_size, stride, padding, True,
                                                dilation, ceil_mode),
    "max_pool3d_with_indices": lambda a, kernel_size, stride=None, padding=0, dilation=1,
        ceil_mode=False: _windowed_extrema_pool(a, 3, kernel_size, stride, padding, True,
                                                dilation, ceil_mode),
    "max_unpool1d": lambda a, indices, kernel_size, stride=None, padding=0, output_size=None:
        _max_unpool(a, indices, 1, kernel_size, stride, padding, output_size),
    "max_unpool2d": lambda a, indices, kernel_size, stride=None, padding=0, output_size=None:
        _max_unpool(a, indices, 2, kernel_size, stride, padding, output_size),
    "max_unpool3d": lambda a, indices, kernel_size, stride=None, padding=0, output_size=None:
        _max_unpool(a, indices, 3, kernel_size, stride, padding, output_size),
    "lp_pool1d": lambda a, norm_type, kernel_size, stride=None, ceil_mode=False:
        _lp_pool(a, 1, norm_type, kernel_size, stride, ceil_mode),
    "lp_pool3d": lambda a, norm_type, kernel_size, stride=None, ceil_mode=False:
        _lp_pool(a, 3, norm_type, kernel_size, stride, ceil_mode),
    "bilinear": _bilinear,
    "pdist": _pdist,
    "grid_sample": _grid_sample,
    "grid_sampler": lambda a, grid, interpolation_mode, padding_mode, align_corners:
        _grid_sample(a, grid, ["bilinear", "nearest", "bicubic"][interpolation_mode],
                     ["zeros", "border", "reflection"][padding_mode], align_corners),
    "grid_sampler_2d": lambda a, grid, interpolation_mode, padding_mode, align_corners:
        _grid_sample(a, grid, ["bilinear", "nearest", "bicubic"][interpolation_mode],
                     ["zeros", "border", "reflection"][padding_mode], align_corners),
    "affine_grid": _affine_grid,
    "affine_grid_generator": lambda theta, size, align_corners=False: _affine_grid(
        theta, size, align_corners),
    "poisson_nll_loss": lambda input, target, log_input=True, full=False, eps=1e-8,
        reduction="mean": _reduce_ext(
            (jnp.exp(input) - target * input) if log_input
            else (input - target * jnp.log(input + eps)), reduction),
    "multi_margin_loss": lambda input, target, p=1, margin=1.0, weight=None,
        reduction="mean": _multi_margin_loss(input, target, p, margin, weight, reduction),
    "multilabel_margin_loss": lambda input, target, reduction="mean":
        _multilabel_margin_loss(input, target, reduction),
    "triplet_margin_with_distance_loss": lambda anchor, positive, negative,
        distance_function=None, margin=1.0, swap=False, reduction="mean":
        _triplet_margin_distance(anchor, positive, negative, distance_function,
                                 margin, swap, reduction),
    "ctc_loss": _ctc_loss,
    # ---- rnn cells ----
    "gru_cell": _gru_cell,
    "lstm_cell": _lstm_cell,
    "rnn_tanh_cell": lambda x, hx, w_ih, w_hh, b_ih=None, b_hh=None: _rnn_cell(
        x, hx, w_ih, w_hh, b_ih, b_hh, jnp.tanh),
    "rnn_relu_cell": lambda x, hx, w_ih, w_hh, b_ih=None, b_hh=None: _rnn_cell(
        x, hx, w_ih, w_hh, b_ih, b_hh, jax.nn.relu),
    # ---- norm internals (pure subset; the in-place running-stat variants
    # stay on the frontend's functionalized module path) ----
    "batch_norm_stats": _batch_norm_stats,
    "batch_norm_elemt": lambda a, weight, bias, mean, invstd, eps: (
        (a - mean.reshape(1, -1, *([1] * (a.ndim - 2)))) *
        invstd.reshape(1, -1, *([1] * (a.ndim - 2))) *
        (1.0 if weight is None else weight.reshape(1, -1, *([1] * (a.ndim - 2)))) +
        (0.0 if bias is None else bias.reshape(1, -1, *([1] * (a.ndim - 2))))),
    "native_layer_norm": _native_layer_norm,
    "native_group_norm": _native_group_norm,
    "native_channel_shuffle": lambda a, groups: a.reshape(
        a.shape[0], groups, a.shape[1] // groups, *a.shape[2:]).swapaxes(1, 2).reshape(a.shape),
    # ---- signal ----
    "stft": _stft,
    "istft": _istft,
    # ---- misc ----
    "conv_tbc": _conv_tbc,
    "resolve_conj": lambda a: a,
    "resolve_neg": lambda a: a,
}


# overlap with torch semantics needing more code
def _median(a, dim=None, keepdim=False):
    """torch.median: the LOWER middle element (not the numpy average)."""
    if dim is None:
        flat = jnp.ravel(a)
        return jnp.sort(flat)[(flat.shape[0] - 1) // 2]
    k = (a.shape[dim] - 1) // 2
    vals = jnp.take(jnp.sort(a, axis=dim), k, axis=dim)
    idxs = jnp.take(jnp.argsort(a, axis=dim), k, axis=dim).astype(jnp.int32)
    if keepdim:
        vals = jnp.expand_dims(vals, dim)
        idxs = jnp.expand_dims(idxs, dim)
    return vals, idxs


def _torch_svd(a, some=True):
    u, s, vh = jnp.linalg.svd(a, full_matrices=not some)
    return u, s, jnp.conjugate(jnp.swapaxes(vh, -2, -1))


def _unfold_ext(a, dimension, size, step):
    n = (a.shape[dimension] - size) // step + 1
    idx = jnp.arange(n) * step
    moved = jnp.moveaxis(a, dimension, -1)
    windows = jnp.stack([moved[..., int(i):int(i) + size] for i in (np.arange(n) * step)], axis=-2)
    return jnp.moveaxis(windows, (-2, -1), (dimension, a.ndim))


def _cummax_indices(a, dim):
    vals = jax.lax.cummax(a, axis=dim)
    eq = a == vals
    ar = jnp.arange(a.shape[dim]).reshape([-1 if i == (dim % a.ndim) else 1 for i in range(a.ndim)])
    return jax.lax.cummax(jnp.where(eq, ar, 0), axis=dim).astype(jnp.int32)


def _index_reduce(a, dim, index, source, reduce, include_self=True):
    moved = jnp.moveaxis(a, dim, 0)
    src = jnp.moveaxis(source, dim, 0)
    if reduce == "prod":
        base = moved if include_self else moved.at[index].set(1.0)
        out = base.at[index].multiply(src)
    elif reduce == "amax":
        base = moved if include_self else moved.at[index].set(-jnp.inf)
        out = base.at[index].max(src)
    elif reduce == "amin":
        base = moved if include_self else moved.at[index].set(jnp.inf)
        out = base.at[index].min(src)
    elif reduce == "mean":
        ssum = (moved if include_self else moved.at[index].set(0.0)).at[index].add(src)
        cnt = (jnp.ones_like(moved) if include_self
               else jnp.ones_like(moved).at[index].set(0.0)).at[index].add(jnp.ones_like(src))
        out = ssum / cnt
    else:
        raise NotImplementedError(f"index_reduce mode {reduce!r}")
    return jnp.moveaxis(out, 0, dim)


def _reduce_ext(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


def _multi_margin_loss(input, target, p=1, margin=1.0, weight=None, reduction="mean"):
    n, c = input.shape
    picked = jnp.take_along_axis(input, target[:, None], 1)
    m = jnp.maximum(margin - picked + input, 0.0) ** p
    if weight is not None:
        m = m * weight[target][:, None]
    onehot = jax.nn.one_hot(target, c, dtype=bool)
    per = jnp.sum(jnp.where(onehot, 0.0, m), axis=1) / c
    return _reduce_ext(per, reduction)


def _multilabel_margin_loss(input, target, reduction="mean"):
    x = input if input.ndim == 2 else input[None]
    t = target if target.ndim == 2 else target[None]
    n, c = x.shape
    valid = jnp.cumprod(t >= 0, axis=1).astype(bool)
    tc = jnp.where(valid, jnp.clip(t, 0, c - 1), 0)
    # max-scatter: duplicate (row, class) writes must OR, not overwrite
    is_target = jnp.zeros((n, c), jnp.int32).at[
        jnp.arange(n)[:, None], tc].max(valid.astype(jnp.int32)).astype(bool)
    xt = jnp.where(valid, jnp.take_along_axis(x, tc, 1), 0.0)
    diff = jnp.maximum(1.0 - xt[:, :, None] + x[:, None, :], 0.0)  # (n, targets, classes)
    mask = valid[:, :, None] & ~is_target[:, None, :]
    per = jnp.sum(jnp.where(mask, diff, 0.0), axis=(1, 2)) / c
    return _reduce_ext(per if input.ndim == 2 else per[0], reduction)


def _triplet_margin_distance(anchor, positive, negative, distance_function=None,
                             margin=1.0, swap=False, reduction="mean"):
    dist = distance_function or (lambda a, b: jnp.sqrt(jnp.sum((a - b) ** 2, -1) + 1e-12))
    dp = dist(anchor, positive)
    dn = dist(anchor, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce_ext(jnp.maximum(dp - dn + margin, 0.0), reduction)


# ---------------------------------------------------------------------------
# wave 6 — non-differentiable long tail
# ---------------------------------------------------------------------------

EXT_NONDIFF: dict[str, Callable] = {
    "bool": lambda a: a.astype(jnp.bool_),
    "byte": lambda a: a.astype(jnp.uint8),
    "char": lambda a: a.astype(jnp.int8),
    "short": lambda a: a.astype(jnp.int16),
    "int": lambda a: a.astype(jnp.int32),
    "count_nonzero": lambda a, dim=None: jnp.count_nonzero(a, axis=dim),
    "nonzero_static": lambda a, size, fill_value=-1: jnp.stack(
        jnp.nonzero(a, size=size, fill_value=fill_value), -1),
    "histogram": lambda a, bins=100, range=None, weight=None, density=False: (
        jnp.histogram(a, bins=bins, range=range, weights=weight, density=density)[0],
        jnp.histogram(a, bins=bins, range=range, weights=weight, density=density)[1]),
    "unravel_index": _unravel_index,
    "mode": lambda a, dim=-1, keepdim=False: _mode(a, dim, keepdim),
    "is_same_size": lambda a, b: a.shape == b.shape,
}


def _mode(a, dim=-1, keepdim=False):
    # torch.mode: most frequent value along dim (smallest on ties) + index
    s = jnp.sort(a, axis=dim)
    moved = jnp.moveaxis(s, dim, -1)
    n = moved.shape[-1]
    runs = jnp.concatenate([jnp.ones(moved.shape[:-1] + (1,), bool),
                            moved[..., 1:] != moved[..., :-1]], -1)
    run_id = jnp.cumsum(runs, -1)
    counts = jnp.sum(run_id[..., :, None] == run_id[..., None, :], -1)
    best = jnp.argmax(counts, -1)
    val = jnp.take_along_axis(moved, best[..., None], -1)[..., 0]
    orig = jnp.moveaxis(a, dim, -1)
    matches = orig == val[..., None]
    idx = (n - 1) - jnp.argmax(jnp.flip(matches, -1), -1)  # torch: last matching index
    if keepdim:
        val, idx = val[..., None], idx[..., None]
        val = jnp.moveaxis(val, -1, dim)
        idx = jnp.moveaxis(idx, -1, dim)
    return val, idx.astype(jnp.int32)


def register_ext_catalog() -> int:
    from .auto_register import _auto_symbols, register_auto_op

    # wave-6 entries REPLACE earlier same-name registrations: these carry the
    # fuller torch contract (dim/upper/some/... arguments) than the early
    # single-argument versions
    for name, fn in EXT_DIFF.items():
        _auto_symbols.pop(f"auto.{name}", None)
        register_auto_op(name, fn, differentiable=True)
    for name, fn in EXT_NONDIFF.items():
        _auto_symbols.pop(f"auto.{name}", None)
        register_auto_op(name, fn, differentiable=False)
    _register_ext2()
    return len(_auto_symbols)


# ---------------------------------------------------------------------------
# wave 7 — full RNN stacks (lax.scan over time), fft hermitian 2d/nd, misc
# ---------------------------------------------------------------------------


def _rnn_stack(cell, x, h0s, params, has_biases, num_layers, bidirectional,
               batch_first, state_is_tuple=False):
    x = jnp.swapaxes(x, 0, 1) if batch_first else x
    dirs = 2 if bidirectional else 1
    per = 4 if has_biases else 2
    finals = []
    for layer in range(num_layers):
        layer_outs = []
        for d in range(dirs):
            base = (layer * dirs + d) * per
            w_ih, w_hh = params[base], params[base + 1]
            b_ih = params[base + 2] if has_biases else None
            b_hh = params[base + 3] if has_biases else None
            h0 = h0s(layer * dirs + d)
            seq = x if d == 0 else jnp.flip(x, 0)

            def step(h, xt):
                hn = cell(xt, h, w_ih, w_hh, b_ih, b_hh)
                return hn, (hn[0] if state_is_tuple else hn)

            hT, ys = jax.lax.scan(step, h0, seq)
            if d == 1:
                ys = jnp.flip(ys, 0)
            layer_outs.append(ys)
            finals.append(hT)
        x = jnp.concatenate(layer_outs, -1) if dirs == 2 else layer_outs[0]
    out = jnp.swapaxes(x, 0, 1) if batch_first else x
    return out, finals


def _check_rnn_dropout(dropout, train):
    if train and dropout and float(dropout) > 0.0:
        raise NotImplementedError(
            "RNN/GRU/LSTM inter-layer dropout in training mode needs RNG "
            "state the auto catalog does not carry (see the module "
            "docstring's RNG-sampler exclusion); run with dropout=0 or "
            "module.eval()")


def _torch_rnn(cell, input, hx, params, has_biases, num_layers, dropout, train,
               bidirectional, batch_first):
    _check_rnn_dropout(dropout, train)
    out, finals = _rnn_stack(cell, input, lambda i: hx[i], list(params), has_biases,
                             int(num_layers), bool(bidirectional), bool(batch_first))
    return out, jnp.stack(finals, 0)


def _torch_lstm(input, hx, params, has_biases, num_layers, dropout, train,
                bidirectional, batch_first):
    _check_rnn_dropout(dropout, train)
    h0, c0 = hx[0], hx[1]
    out, finals = _rnn_stack(
        lambda x, h, wi, wh, bi, bh: _lstm_cell(x, h, wi, wh, bi, bh),
        input, lambda i: (h0[i], c0[i]), list(params), has_biases,
        int(num_layers), bool(bidirectional), bool(batch_first), state_is_tuple=True)
    return (out, jnp.stack([f[0] for f in finals], 0),
            jnp.stack([f[1] for f in finals], 0))


def _hfft2(a, s=None, dim=(-2, -1), norm=None):
    # hermitian-symmetric input: complex fft over the leading dims FIRST,
    # then the hermitian fft over the last (verified against torch)
    out = a
    for d in dim[:-1]:
        out = jnp.fft.fft(out, axis=d, norm=norm)
    return jnp.fft.hfft(out, n=None if s is None else s[-1], axis=dim[-1], norm=norm)


def _ihfft2(a, s=None, dim=(-2, -1), norm=None):
    out = jnp.fft.ihfft(a, n=None if s is None else s[-1], axis=dim[-1], norm=norm)
    for d in dim[:-1]:
        out = jnp.fft.ifft(out, axis=d, norm=norm)
    return out


def _adaptive_max_pool2d_with_indices(a, output_size):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    H, W = a.shape[-2:]
    oh, ow = (int(o) if o is not None else s for o, s in zip(output_size, (H, W)))
    rows_v, rows_i = [], []
    for sh, eh in _adaptive_pool_slices(H, oh):
        cols_v, cols_i = [], []
        for sw, ew in _adaptive_pool_slices(W, ow):
            win = a[..., sh:eh, sw:ew]
            flat = win.reshape(win.shape[:-2] + (-1,))
            am = jnp.argmax(flat, -1)
            wh = ew - sw
            iy = am // wh + sh
            ix = am % wh + sw
            cols_v.append(jnp.max(flat, -1))
            cols_i.append(iy * W + ix)
        rows_v.append(jnp.stack(cols_v, -1))
        rows_i.append(jnp.stack(cols_i, -1))
    return jnp.stack(rows_v, -2), jnp.stack(rows_i, -2).astype(jnp.int32)


EXT2_DIFF: dict[str, Callable] = {
    "gru": lambda input, hx, params, has_biases, num_layers, dropout, train,
        bidirectional, batch_first: _torch_rnn(_gru_cell, input, hx, params, has_biases,
                                               num_layers, dropout, train, bidirectional,
                                               batch_first),
    "rnn_tanh": lambda input, hx, params, has_biases, num_layers, dropout, train,
        bidirectional, batch_first: _torch_rnn(
            lambda x, h, wi, wh, bi, bh: _rnn_cell(x, h, wi, wh, bi, bh, jnp.tanh),
            input, hx, params, has_biases, num_layers, dropout, train,
            bidirectional, batch_first),
    "rnn_relu": lambda input, hx, params, has_biases, num_layers, dropout, train,
        bidirectional, batch_first: _torch_rnn(
            lambda x, h, wi, wh, bi, bh: _rnn_cell(x, h, wi, wh, bi, bh, jax.nn.relu),
            input, hx, params, has_biases, num_layers, dropout, train,
            bidirectional, batch_first),
    "lstm": _torch_lstm,
    "fft_hfft2": _hfft2,
    "fft_ihfft2": _ihfft2,
    "fft_hfftn": lambda a, s=None, dim=None, norm=None: _hfft2(
        a, s, tuple(dim) if dim is not None else tuple(range(a.ndim)), norm),
    "fft_ihfftn": lambda a, s=None, dim=None, norm=None: _ihfft2(
        a, s, tuple(dim) if dim is not None else tuple(range(a.ndim)), norm),
    "new_empty": lambda a, size, dtype=None, **kw: jnp.zeros(
        tuple(size) if isinstance(size, (tuple, list)) else (size,), dtype or a.dtype),
    "batch_norm_update_stats": lambda a, running_mean, running_var, momentum: (
        (1 - momentum) * running_mean + momentum * jnp.mean(a, (0,) + tuple(range(2, a.ndim))),
        (1 - momentum) * running_var + momentum * jnp.var(
            a, (0,) + tuple(range(2, a.ndim)), ddof=1)),
    "lu": _lu_factor,  # torch.lu / Tensor.lu -> (LU, pivots)
    "adaptive_max_pool2d_with_indices": _adaptive_max_pool2d_with_indices,
}


def _register_ext2():
    from .auto_register import _auto_symbols, register_auto_op

    for name, fn in EXT2_DIFF.items():
        _auto_symbols.pop(f"auto.{name}", None)
        register_auto_op(name, fn, differentiable=True)

    _register_ext3()


# ---------------------------------------------------------------------------
# wave 8 (round 4) — closing the remaining implementable reference names
# (default_torch_ops.py:3): the aten convolution entry point, distributed
# batch-norm internals, window factories, upsample family, fake-quant,
# geqrf/ormqr, low-rank factorizations, and interop-relevant aliases
# ---------------------------------------------------------------------------


def _tup(x, n):
    if isinstance(x, (tuple, list)):
        t = tuple(int(v) for v in x)
        return t * n if len(t) == 1 else t
    return (int(x),) * n


def _convolution(a, w, bias=None, stride=1, padding=0, dilation=1,
                 transposed=False, output_padding=0, groups=1):
    """torch.convolution / aten::convolution — the single entry point every
    torch conv lowers to. Forward and transposed, any spatial rank, groups."""
    nd = a.ndim - 2
    stride = _tup(stride, nd)
    padding = _tup(padding, nd)
    dilation = _tup(dilation, nd)
    output_padding = _tup(output_padding, nd)
    spatial = "DHW"[-nd:] if nd <= 3 else None
    if spatial is None:
        raise NotImplementedError("convolution: >3 spatial dims")
    dn = ("NC" + spatial, "OI" + spatial, "NC" + spatial)
    groups = int(groups)
    if not transposed:
        out = jax.lax.conv_general_dilated(
            a, w, stride, [(p, p) for p in padding], rhs_dilation=dilation,
            feature_group_count=groups, dimension_numbers=dn)
    else:
        # torch transposed-conv weight is (Cin, Cout//g, *k): flip spatial,
        # swap the I/O axes per group, then run a stride-1 conv with
        # lhs_dilation=stride (gradient-of-conv formulation)
        k = w.shape[2:]
        wt = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        cin, coutg = w.shape[0], w.shape[1]
        wt = wt.reshape((groups, cin // groups, coutg) + k)
        wt = jnp.swapaxes(wt, 1, 2).reshape((groups * coutg, cin // groups) + k)
        pads = [(dilation[i] * (k[i] - 1) - padding[i],
                 dilation[i] * (k[i] - 1) - padding[i] + output_padding[i])
                for i in range(nd)]
        out = jax.lax.conv_general_dilated(
            a, wt, (1,) * nd, pads, lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups, dimension_numbers=dn)
    if bias is not None:
        out = out + jnp.reshape(bias, (1, -1) + (1,) * nd)
    return out


def _sdpa(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False,
          scale=None, enable_gqa=False):
    """F.scaled_dot_product_attention contract (pure-jax reference path; the
    Pallas flash kernel claims the ltorch.sdpa symbol on TPU)."""
    if dropout_p and float(dropout_p) > 0.0:
        raise NotImplementedError("sdpa dropout needs RNG state (see module "
                                  "docstring's RNG-sampler exclusion)")
    d = query.shape[-1]
    if enable_gqa and key.shape[-3] != query.shape[-3]:
        rep = query.shape[-3] // key.shape[-3]
        key = jnp.repeat(key, rep, axis=-3)
        value = jnp.repeat(value, rep, axis=-3)
    s = (scale if scale is not None else 1.0 / math.sqrt(d))
    scores = jnp.einsum("...qd,...kd->...qk", query, key) * s
    if is_causal:
        L, S = query.shape[-2], key.shape[-2]
        # torch documents a top-left-aligned causal mask (tril diagonal=0)
        # even when L != S
        causal = jnp.tril(jnp.ones((L, S), bool))
        scores = jnp.where(causal, scores, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, -jnp.inf)
        else:
            scores = scores + attn_mask
    return jnp.einsum("...qk,...kd->...qd", jax.nn.softmax(scores, axis=-1), value)


def _native_batch_norm(a, weight, bias, running_mean, running_var, training,
                       momentum, eps):
    axes = (0,) + tuple(range(2, a.ndim))
    view = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    if training:
        mean = jnp.mean(a, axes)
        var = jnp.var(a, axes)
    else:
        mean, var = running_mean, running_var
    invstd = 1.0 / jnp.sqrt(var + eps)
    out = (a - mean.reshape(view)) * invstd.reshape(view)
    if weight is not None:
        out = out * weight.reshape(view)
    if bias is not None:
        out = out + bias.reshape(view)
    return out, mean, invstd


def _bn_gather_stats_with_counts(a, mean, invstd, running_mean, running_var,
                                 momentum, eps, counts):
    # combine per-replica (world, C) stats into global (C,) mean/invstd
    counts = jnp.asarray(counts, mean.dtype).reshape(-1, 1)
    total = jnp.sum(counts)
    mean_all = jnp.sum(mean * counts, 0) / total
    var_j = 1.0 / (invstd * invstd) - eps          # biased per-replica var
    ex2 = var_j + mean * mean
    var_all = jnp.sum(ex2 * counts, 0) / total - mean_all * mean_all
    return mean_all, 1.0 / jnp.sqrt(var_all + eps)


def _bn_backward_reduce(grad_out, a, mean, invstd, weight, input_g, weight_g, bias_g):
    axes = (0,) + tuple(range(2, a.ndim))
    view = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    sum_dy = jnp.sum(grad_out, axes)
    sum_dy_xmu = jnp.sum(grad_out * (a - mean.reshape(view)), axes)
    grad_weight = sum_dy_xmu * invstd
    return sum_dy, sum_dy_xmu, grad_weight, sum_dy


def _bn_backward_elemt(grad_out, a, mean, invstd, weight, sum_dy, sum_dy_xmu, count):
    view = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    total = jnp.sum(jnp.asarray(count, grad_out.dtype))
    w = weight.reshape(view) if weight is not None else 1.0
    dy_mean = (sum_dy / total).reshape(view)
    proj = ((a - mean.reshape(view)) * (invstd * invstd * sum_dy_xmu / total).reshape(view))
    return (grad_out - dy_mean - proj) * invstd.reshape(view) * w


def _fake_quant_pt(a, scale, zero_point, quant_min, quant_max):
    q = jnp.clip(jnp.round(a / scale) + zero_point, quant_min, quant_max)
    return (q - zero_point) * scale


def _fake_quant_pc(a, scale, zero_point, axis, quant_min, quant_max):
    view = [1] * a.ndim
    view[int(axis)] = -1
    s = jnp.reshape(scale, view)
    zp = jnp.reshape(jnp.asarray(zero_point, a.dtype), view)
    q = jnp.clip(jnp.round(a / s) + zp, quant_min, quant_max)
    return (q - zp) * s


def _window_dtype(dtype):
    if dtype is None:
        return jnp.float32
    name = str(dtype).replace("torch.", "")
    return {"float64": jnp.float64, "double": jnp.float64,
            "bfloat16": jnp.bfloat16, "float16": jnp.float16,
            "half": jnp.float16}.get(name, jnp.float32)


def _window(kind, n, periodic=True, dtype=None, **kw):
    n = int(n)
    dt = _window_dtype(dtype)
    if n == 0:
        return jnp.zeros((0,), dt)
    if n == 1:
        return jnp.ones((1,), dt)
    m = n + 1 if periodic else n
    if kind == "hann":
        w = jnp.hanning(m)
    elif kind == "hamming":
        # torch exposes the generalized-cosine coefficients
        alpha, beta = kw.get("alpha", 0.54), kw.get("beta", 0.46)
        w = alpha - beta * jnp.cos(2 * jnp.pi * jnp.arange(m) / (m - 1))
    elif kind == "blackman":
        w = jnp.blackman(m)
    elif kind == "bartlett":
        w = jnp.bartlett(m)
    else:  # kaiser
        w = jnp.kaiser(m, kw.get("beta", 12.0))
    return jnp.asarray(w[:-1] if periodic else w, dt)


def _scale_to_size(a, scale_factor, nd):
    """torch semantics: output size = floor(input * scale) per spatial dim;
    scale factors stay float (no int truncation)."""
    if isinstance(scale_factor, (tuple, list)):
        sf = tuple(float(v) for v in scale_factor)
        sf = sf * nd if len(sf) == 1 else sf
    else:
        sf = (float(scale_factor),) * nd
    return tuple(int(math.floor(a.shape[2 + i] * sf[i])) for i in range(nd))


def _upsample_nearest(a, size=None, scale_factor=None):
    nd = a.ndim - 2
    if size is None:
        size = _scale_to_size(a, scale_factor, nd)
    else:
        size = _tup(size, nd)
    out = a
    for i in range(nd):
        in_sz, out_sz = a.shape[2 + i], size[i]
        # torch nearest: floor(out_idx * in/out)
        idx = jnp.floor(jnp.arange(out_sz) * (in_sz / out_sz)).astype(jnp.int32)
        out = jnp.take(out, idx, axis=2 + i)
    return out


def _upsample_bilinear(a, size=None, scale_factor=None, align_corners=True):
    # torch's F.upsample_bilinear is align_corners=True
    H, W = a.shape[-2:]
    if size is None:
        size = _scale_to_size(a, scale_factor, 2)
    else:
        size = _tup(size, 2)
    oh, ow = size

    def coords(in_sz, out_sz):
        if align_corners and out_sz > 1:
            return jnp.arange(out_sz) * ((in_sz - 1) / (out_sz - 1))
        return jnp.clip((jnp.arange(out_sz) + 0.5) * (in_sz / out_sz) - 0.5, 0, in_sz - 1)

    ys, xs = coords(H, oh), coords(W, ow)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = (ys - y0).reshape(-1, 1)
    wx = (xs - x0).reshape(1, -1)
    g = lambda yi, xi: a[..., yi, :][..., :, xi]
    top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
    bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
    return top * (1 - wy) + bot * wy


def _geqrf(a):
    """LAPACK-convention compact QR (torch.geqrf): Householder vectors below
    the diagonal, R on and above, plus taus — consumable by
    jax.lax.linalg.householder_product (which IS public, unlike geqrf)."""
    m, n = a.shape[-2:]
    k = min(m, n)
    taus = []
    for j in range(k):
        x = a[..., j:, j]
        alpha = x[..., 0]
        normx = jnp.sqrt(jnp.sum(x * x, -1))
        sign = jnp.where(alpha >= 0, 1.0, -1.0)
        beta = -sign * normx
        safe = jnp.abs(alpha - beta) > 1e-30
        tau = jnp.where(safe, (beta - alpha) / jnp.where(beta == 0, 1.0, beta), 0.0)
        denom = jnp.where(safe, alpha - beta, 1.0)
        v = x / denom[..., None]
        v = v.at[..., 0].set(1.0)
        # apply I - tau v v^T to the trailing block
        block = a[..., j:, j:]
        w = jnp.einsum("...i,...ij->...j", v, block)
        block = block - tau[..., None, None] * v[..., :, None] * w[..., None, :]
        a = a.at[..., j:, j:].set(block)
        # store v below the diagonal of column j (beta lands on the diagonal
        # via the reflection itself)
        a = a.at[..., j + 1:, j].set(v[..., 1:])
        taus.append(tau)
    return a, jnp.stack(taus, -1)


def _ormqr(a, tau, other, left=True, transpose=False):
    q = jax.lax.linalg.householder_product(a, tau)
    qq = jnp.swapaxes(q, -2, -1) if transpose else q
    return qq @ other if left else other @ qq


def _svd_lowrank(a, q=6, niter=2, M=None):
    if M is not None:
        a = a - M
    u, s, vh = jnp.linalg.svd(a, full_matrices=False)
    q = min(int(q), s.shape[-1])
    return u[..., :q], s[..., :q], jnp.swapaxes(vh, -2, -1)[..., :q]


def _pca_lowrank(a, q=None, center=True, niter=2):
    m, n = a.shape[-2:]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    return _svd_lowrank(a, q)


def _adaptive_max_pool3d_with_indices(a, output_size):
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    D, H, W = a.shape[-3:]
    od, oh, ow = (int(o) if o is not None else s
                  for o, s in zip(output_size, (D, H, W)))
    dv, di = [], []
    for sd, ed in _adaptive_pool_slices(D, od):
        hv, hi = [], []
        for sh, eh in _adaptive_pool_slices(H, oh):
            wv, wi = [], []
            for sw, ew in _adaptive_pool_slices(W, ow):
                win = a[..., sd:ed, sh:eh, sw:ew]
                flat = win.reshape(win.shape[:-3] + (-1,))
                am = jnp.argmax(flat, -1)
                wd, wh = eh - sh, ew - sw
                iz = am // (wd * wh) + sd
                iy = (am // wh) % wd + sh
                ix = am % wh + sw
                wv.append(jnp.max(flat, -1))
                wi.append((iz * H + iy) * W + ix)
            hv.append(jnp.stack(wv, -1))
            hi.append(jnp.stack(wi, -1))
        dv.append(jnp.stack(hv, -2))
        di.append(jnp.stack(hi, -2))
    return jnp.stack(dv, -3), jnp.stack(di, -3).astype(jnp.int32)


def _gradient(a, spacing=1, dim=None):
    """torch.gradient: always a flat tuple of per-dim central differences."""
    if dim is None:
        axes = tuple(range(a.ndim))
    elif isinstance(dim, (tuple, list)):
        axes = tuple(int(d) for d in dim)
    else:
        axes = (int(dim),)
    sp = () if spacing == 1 else (spacing,)
    return tuple(jnp.gradient(a, *sp, axis=ax) for ax in axes)


EXT3_DIFF: dict[str, Callable] = {
    "convolution": _convolution,
    "scaled_dot_product_attention": _sdpa,
    "native_batch_norm": _native_batch_norm,
    "native_norm": lambda a, p=2: jnp.sum(jnp.abs(a) ** p) ** (1.0 / p),
    "linalg_matmul": jnp.matmul,
    "linalg_diagonal": lambda A, *, offset=0, dim1=-2, dim2=-1: jnp.diagonal(A, offset, dim1, dim2),
    "special_logit": lambda a, eps=None: jnp.log(
        (c := (jnp.clip(a, eps, 1 - eps) if eps is not None else a)) / (1 - c)),
    "gradient": lambda a, spacing=1, dim=None, edge_order=1: _gradient(a, spacing, dim),
    "fill": lambda a, v: jnp.full_like(a, v),
    "alias_copy": lambda a: a,
    "upsample_nearest": _upsample_nearest,
    "upsample_bilinear": _upsample_bilinear,
    "upsample": lambda a, size=None, scale_factor=None, mode="nearest", align_corners=None: (
        _upsample_nearest(a, size, scale_factor) if mode == "nearest"
        else _upsample_bilinear(a, size, scale_factor, bool(align_corners))),
    "rrelu": lambda a, lower=1/8, upper=1/3, training=False, inplace=False: (
        (_ for _ in ()).throw(NotImplementedError(
            "rrelu training mode samples per-element slopes (RNG exclusion)"))
        if training else jnp.where(a >= 0, a, a * ((lower + upper) / 2.0))),
    "adaptive_max_pool3d_with_indices": _adaptive_max_pool3d_with_indices,
    "adaptive_max_pool3d": lambda a, output_size: _adaptive_max_pool3d_with_indices(a, output_size)[0],
    "batch_norm_backward_reduce": _bn_backward_reduce,
    "batch_norm_backward_elemt": _bn_backward_elemt,
    "linalg_vander": lambda x, N=None: jnp.vander(
        x, int(N) if N is not None else x.shape[-1], increasing=True),
}

EXT3_NONDIFF: dict[str, Callable] = {
    "geqrf": _geqrf,
    "ormqr": _ormqr,
    "svd_lowrank": _svd_lowrank,
    "pca_lowrank": _pca_lowrank,
    "fake_quantize_per_tensor_affine": _fake_quant_pt,
    "fake_quantize_per_channel_affine": _fake_quant_pc,
    "batch_norm_gather_stats": lambda a, mean, invstd, rm, rv, momentum, eps, count: (
        _bn_gather_stats_with_counts(a, mean, invstd, rm, rv, momentum, eps,
                                     jnp.full((mean.shape[0],), count))),
    "batch_norm_gather_stats_with_counts": _bn_gather_stats_with_counts,
    "hann_window": lambda n, periodic=True, dtype=None: _window("hann", n, periodic, dtype),
    "hamming_window": lambda n, periodic=True, alpha=0.54, beta=0.46, dtype=None: _window(
        "hamming", n, periodic, dtype, alpha=alpha, beta=beta),
    "blackman_window": lambda n, periodic=True, dtype=None: _window("blackman", n, periodic, dtype),
    "bartlett_window": lambda n, periodic=True, dtype=None: _window("bartlett", n, periodic, dtype),
    "kaiser_window": lambda n, periodic=True, beta=12.0, dtype=None: _window("kaiser", n, periodic, dtype, beta=beta),
    "histogramdd": lambda a, bins, range=None, weight=None, density=False: (
        (h := jnp.histogramdd(a, bins=bins, range=range, weights=weight, density=density))[0],
        tuple(h[1])),
    "as_tensor": lambda a, dtype=None, device=None: jnp.asarray(a, dtype),
    "asarray": lambda a, dtype=None, device=None, copy=None, requires_grad=False: jnp.asarray(a, dtype),
    "range": lambda start, end, step=1, dtype=None: jnp.arange(start, end + step * 0.5, step,
                                                               dtype=dtype or jnp.float32),
    "empty_strided": lambda size, stride, dtype=None, **kw: jnp.zeros(tuple(size), dtype or jnp.float32),
    "empty_permuted": lambda size, physical_layout, dtype=None, **kw: jnp.zeros(tuple(size), dtype or jnp.float32),
    "cpu": lambda a: a,
    "pin_memory": lambda a, device=None: a,
}


def _register_ext3():
    from .auto_register import _auto_symbols, register_auto_op

    for name, fn in EXT3_DIFF.items():
        _auto_symbols.pop(f"auto.{name}", None)
        register_auto_op(name, fn, differentiable=True)
    for name, fn in EXT3_NONDIFF.items():
        _auto_symbols.pop(f"auto.{name}", None)
        register_auto_op(name, fn, differentiable=False)
