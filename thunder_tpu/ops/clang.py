"""Core operation language: broadcasting, type promotion, indexing.

Counterpart of reference thunder/clang/__init__.py:44 (132 clang ops). These
are plain helper functions (not Symbols) that normalize arguments and call
prims; the torch-like Symbol layer above them (ops/ltorch.py) is what records
into traces as named composite ops."""
from __future__ import annotations

from numbers import Number
from typing import Any, Sequence

from ..core import dtypes, prims
from ..core.baseutils import canonicalize_dim, canonicalize_dims, check
from ..core.proxies import NumberProxy, TensorProxy, pyval


def is_tensor(x) -> bool:
    return isinstance(x, TensorProxy)


def constant(array) -> TensorProxy:
    """Wrap a concrete array (model buffer, rope cache, ...) as a trace-level
    constant tensor. The array is carried out-of-line and becomes an XLA
    constant inside fused regions."""
    return prims.tensor_constant(array)


def _is_concrete_array(x) -> bool:
    return (not isinstance(x, TensorProxy)) and hasattr(x, "shape") and hasattr(x, "dtype") \
        and not isinstance(x, (Number, NumberProxy))


def ensure_proxy(x):
    """Arrays become constant proxies; proxies and numbers pass through."""
    if _is_concrete_array(x):
        return constant(x)
    return x


# ---------------------------------------------------------------------------
# dtype conversion & promotion
# ---------------------------------------------------------------------------


def maybe_convert_to_dtype(a, dtype: dtypes.dtype):
    if isinstance(a, TensorProxy):
        if a.dtype == dtype:
            return a
        return prims.convert_element_type(a, dtype)
    if isinstance(a, (Number, NumberProxy)):
        return dtypes.dtype_to_numbertype(dtype)(pyval(a))
    raise ValueError(f"cannot convert {a} to {dtype}")


def _result_dtype(*args, int_to_float=False) -> dtypes.dtype:
    parts = []
    for a in args:
        if isinstance(a, TensorProxy):
            parts.append(a.dtype)
        elif isinstance(a, (bool,)):
            parts.append(bool)
        elif isinstance(a, int):
            parts.append(int)
        elif isinstance(a, float):
            parts.append(float)
        elif isinstance(a, complex):
            parts.append(complex)
        elif isinstance(a, NumberProxy):
            parts.append(a.python_type)
    d = dtypes.promote_dtypes(*parts)
    if int_to_float and not d.is_inexact:
        d = dtypes.float32
    return d


# ---------------------------------------------------------------------------
# broadcasting
# ---------------------------------------------------------------------------


def compute_broadcast_shape(*shapes) -> tuple:
    shapes = [s for s in shapes if s is not None]
    rank = max(len(s) for s in shapes)
    out = [1] * rank
    for s in shapes:
        off = rank - len(s)
        for i, d in enumerate(s):
            if d != 1:
                check(out[off + i] in (1, d), lambda: f"cannot broadcast shapes {shapes}")
                out[off + i] = d
    return tuple(out)


def maybe_broadcast(*args):
    """Broadcast all tensor args to a common shape (numbers pass through)."""
    shapes = [a.shape for a in args if isinstance(a, TensorProxy)]
    if not shapes:
        return args
    common = compute_broadcast_shape(*shapes)
    out = []
    for a in args:
        if isinstance(a, TensorProxy):
            out.append(expand_to(a, common))
        else:
            out.append(a)
    return tuple(out)


def expand_to(a: TensorProxy, shape: tuple) -> TensorProxy:
    if a.shape == tuple(shape):
        return a
    off = len(shape) - a.ndim
    bdims = tuple(range(off, len(shape)))
    return prims.broadcast_in_dim(a, tuple(shape), bdims)


def _elementwise_binary(prim, a, b, *, int_to_float=False, bool_out=False):
    a, b = ensure_proxy(a), ensure_proxy(b)
    dt = _result_dtype(a, b, int_to_float=int_to_float)
    a, b = maybe_broadcast(a, b)
    if not bool_out:
        a = maybe_convert_to_dtype(a, dt) if isinstance(a, TensorProxy) else a
        b = maybe_convert_to_dtype(b, dt) if isinstance(b, TensorProxy) else b
    else:
        # comparisons: make tensor dtypes agree, output bool
        ta = a.dtype if isinstance(a, TensorProxy) else None
        tb = b.dtype if isinstance(b, TensorProxy) else None
        if ta is not None and tb is not None and ta != tb:
            a = maybe_convert_to_dtype(a, dt)
            b = maybe_convert_to_dtype(b, dt)
    if not isinstance(a, TensorProxy) and not isinstance(b, TensorProxy):
        raise NotImplementedError("number-number ops should be computed statically")
    # NumberProxy operands stay runtime inputs to full (symbolic caching);
    # plain numbers are baked as before
    if not isinstance(a, TensorProxy):
        a = full_like(b, a if isinstance(a, NumberProxy) else pyval(a), dtype=dt if not bool_out else None)
    if not isinstance(b, TensorProxy):
        b = full_like(a, b if isinstance(b, NumberProxy) else pyval(b), dtype=dt if not bool_out else None)
    return prim(a, b)


# elementwise binary wrappers ------------------------------------------------


def add(a, b):
    return _elementwise_binary(prims.add, a, b)


def sub(a, b):
    return _elementwise_binary(prims.sub, a, b)


def mul(a, b):
    return _elementwise_binary(prims.mul, a, b)


def true_divide(a, b):
    return _elementwise_binary(prims.div, a, b, int_to_float=True)


def floor_divide(a, b):
    q = _elementwise_binary(prims.div, a, b)
    if q.dtype.is_float:
        return prims.floor(q)
    return q


def pow_(a, b):
    return _elementwise_binary(prims.pow, a, b)


def remainder(a, b):
    return _elementwise_binary(prims.remainder, a, b)


def fmod(a, b):
    return _elementwise_binary(prims.fmod, a, b)


def maximum(a, b):
    return _elementwise_binary(prims.maximum, a, b)


def minimum(a, b):
    return _elementwise_binary(prims.minimum, a, b)


def atan2(a, b):
    return _elementwise_binary(prims.atan2, a, b, int_to_float=True)


def bitwise_and(a, b):
    return _elementwise_binary(prims.bitwise_and, a, b)


def bitwise_or(a, b):
    return _elementwise_binary(prims.bitwise_or, a, b)


def bitwise_xor(a, b):
    return _elementwise_binary(prims.bitwise_xor, a, b)


def eq(a, b):
    return _elementwise_binary(prims.eq, a, b, bool_out=True)


def ne(a, b):
    return _elementwise_binary(prims.ne, a, b, bool_out=True)


def lt(a, b):
    return _elementwise_binary(prims.lt, a, b, bool_out=True)


def le(a, b):
    return _elementwise_binary(prims.le, a, b, bool_out=True)


def gt(a, b):
    return _elementwise_binary(prims.gt, a, b, bool_out=True)


def ge(a, b):
    return _elementwise_binary(prims.ge, a, b, bool_out=True)


def logical_and(a, b):
    return bitwise_and(to_bool(a), to_bool(b))


def logical_or(a, b):
    return bitwise_or(to_bool(a), to_bool(b))


def to_bool(a):
    if isinstance(a, TensorProxy) and not a.dtype.is_bool:
        return prims.ne(a, full_like(a, 0))
    return a


def where(pred, a, b):
    pred, a, b = ensure_proxy(pred), ensure_proxy(a), ensure_proxy(b)
    dt = _result_dtype(a, b)
    pred, a, b = maybe_broadcast(pred, a, b)
    if isinstance(a, TensorProxy):
        a = maybe_convert_to_dtype(a, dt)
    if isinstance(b, TensorProxy):
        b = maybe_convert_to_dtype(b, dt)
    if not isinstance(a, TensorProxy):
        a = full_like(pred, pyval(a), dtype=dt)
    if not isinstance(b, TensorProxy):
        b = full_like(pred, pyval(b), dtype=dt)
    return prims.where(pred, a, b)


# factories ------------------------------------------------------------------


def full(shape, fill_value, *, device=None, dtype=None):
    return prims.full(tuple(shape), fill_value, device=device, dtype=dtype)


def full_like(a: TensorProxy, fill_value, *, device=None, dtype=None):
    return prims.full(a.shape, fill_value, device=device or a.device, dtype=dtype or a.dtype)


def arange(start, stop=None, step=1, *, device=None, dtype=None):
    if stop is None:
        start, stop = 0, start
    length = max(0, -(-(pyval(stop) - pyval(start)) // pyval(step)))
    if dtype is None:
        if any(isinstance(pyval(x), float) for x in (start, stop, step)):
            dtype = dtypes.float32
        else:
            dtype = dtypes.int64
    return prims.iota(length, start=pyval(start), step=pyval(step), device=device, dtype=dtype)


# shape ops ------------------------------------------------------------------


def reshape(a: TensorProxy, shape) -> TensorProxy:
    shape = tuple(int(pyval(s)) for s in shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape = tuple(a.numel // known if s == -1 else s for s in shape)
    if shape == a.shape:
        return a
    return prims.reshape(a, shape)


def permute(a: TensorProxy, dims) -> TensorProxy:
    dims = canonicalize_dims(a.ndim, tuple(dims))
    if dims == tuple(range(a.ndim)):
        return a
    return prims.transpose(a, dims)


def transpose(a: TensorProxy, dim0: int, dim1: int) -> TensorProxy:
    dim0, dim1 = canonicalize_dim(a.ndim, dim0), canonicalize_dim(a.ndim, dim1)
    perm = list(range(a.ndim))
    perm[dim0], perm[dim1] = perm[dim1], perm[dim0]
    return permute(a, perm)


def matrix_transpose(a: TensorProxy) -> TensorProxy:
    if a.ndim < 2:
        return a
    return transpose(a, -2, -1)


def unsqueeze(a: TensorProxy, dim: int) -> TensorProxy:
    dim = canonicalize_dim(a.ndim + 1, dim)
    shape = a.shape[:dim] + (1,) + a.shape[dim:]
    return prims.reshape(a, shape)


def squeeze(a: TensorProxy, dim=None) -> TensorProxy:
    if dim is None:
        dims = tuple(i for i, s in enumerate(a.shape) if s == 1)
    elif isinstance(dim, (tuple, list)):
        dims = tuple(canonicalize_dim(a.ndim, pyval(d)) for d in dim)
        dims = tuple(d for d in dims if a.shape[d] == 1)
    else:
        dims = (canonicalize_dim(a.ndim, pyval(dim)),)
        if a.shape[dims[0]] != 1:
            return a
    if not dims:
        return a
    return prims.squeeze(a, dims)


def flatten(a: TensorProxy, start_dim=0, end_dim=-1) -> TensorProxy:
    start_dim = canonicalize_dim(a.ndim, start_dim)
    end_dim = canonicalize_dim(a.ndim, end_dim)
    mid = 1
    for s in a.shape[start_dim : end_dim + 1]:
        mid *= s
    shape = a.shape[:start_dim] + (mid,) + a.shape[end_dim + 1 :]
    return reshape(a, shape)


def slice_in_dim(a: TensorProxy, start, stop, dim=0, stride=1) -> TensorProxy:
    dim = canonicalize_dim(a.ndim, dim)
    starts = [0] * a.ndim
    limits = list(a.shape)
    strides = [1] * a.ndim
    starts[dim], limits[dim], strides[dim] = start, stop, stride
    return prims.slice_prim(a, tuple(starts), tuple(limits), tuple(strides))


def split(a: TensorProxy, split_size_or_sections, dim=0):
    dim = canonicalize_dim(a.ndim, dim)
    n = a.shape[dim]
    if isinstance(split_size_or_sections, int):
        sizes = [split_size_or_sections] * (n // split_size_or_sections)
        if n % split_size_or_sections:
            sizes.append(n % split_size_or_sections)
    else:
        sizes = list(split_size_or_sections)
    out, ofs = [], 0
    for s in sizes:
        out.append(slice_in_dim(a, ofs, ofs + s, dim))
        ofs += s
    return tuple(out)


def chunk(a: TensorProxy, chunks: int, dim=0):
    dim = canonicalize_dim(a.ndim, dim)
    size = -(-a.shape[dim] // chunks)
    return split(a, size, dim)


def cat(tensors, dim=0):
    tensors = [ensure_proxy(t) for t in tensors]
    dim = canonicalize_dim(tensors[0].ndim, pyval(dim))
    dt = _result_dtype(*tensors)
    tensors = [maybe_convert_to_dtype(t, dt) for t in tensors]
    return prims.cat(tensors, dim)


def stack(tensors, dim=0):
    tensors = [unsqueeze(t, dim) for t in tensors]
    return cat(tensors, dim)


def expand(a: TensorProxy, shape) -> TensorProxy:
    shape = tuple(int(pyval(s)) for s in shape)
    off = len(shape) - a.ndim
    shape = tuple(a.shape[i - off] if s == -1 else s for i, s in enumerate(shape))
    return expand_to(a, shape)


def flip(a: TensorProxy, dims) -> TensorProxy:
    dims = canonicalize_dims(a.ndim, tuple(dims))
    return prims.flip(a, dims)


def pad(a: TensorProxy, padding_value, padding_config) -> TensorProxy:
    return prims.pad(a, padding_value, tuple(padding_config))


def movedim(a: TensorProxy, source, destination) -> TensorProxy:
    src = [canonicalize_dim(a.ndim, s) for s in (source if isinstance(source, (tuple, list)) else (source,))]
    dst = [canonicalize_dim(a.ndim, d) for d in (destination if isinstance(destination, (tuple, list)) else (destination,))]
    perm = [d for d in range(a.ndim) if d not in src]
    for d, s in sorted(zip(dst, src)):
        perm.insert(d, s)
    return permute(a, perm)


# indexing -------------------------------------------------------------------


def getitem(a: TensorProxy, key):
    """Basic indexing (int/slice/None/Ellipsis/tensor) — the subset models use.
    Python-list index elements (x[[0, 2]] advanced indexing) lower as int
    tensor indices."""
    if not isinstance(key, tuple):
        key = (key,)
    def _lower_list(k):
        if isinstance(k, bool):
            # numpy/torch treat a scalar bool index as a new size-int(k) dim;
            # misrouting through the int branch silently returns row 0/1
            raise NotImplementedError(
                "scalar boolean indexing (x[True]/x[False]) is not supported; "
                "use unsqueeze / an explicit empty slice")
        if not (isinstance(k, list) and k):
            return k
        if all(isinstance(e, bool) for e in k):
            # a bool list is a MASK in torch/numpy — dynamic output shape
            raise NotImplementedError(
                "boolean mask list indexing (x[[True, False]]) has a "
                "data-dependent output shape; use jnp-level masking or "
                "masked_select via the torch interop host fallback")
        if all(isinstance(e, (int, NumberProxy)) and not isinstance(e, bool) for e in k):
            return tensor_from_sequence(k, dtype=dtypes.int32, device=a.device)
        return k

    key = tuple(_lower_list(k) for k in key)
    # expand Ellipsis — identity checks only: `in`/`.index` would run
    # TensorProxy.__eq__ against Ellipsis and bake bogus comparisons
    n_specified = sum(1 for k in key if k is not None and k is not Ellipsis)
    ell = [i for i, k in enumerate(key) if k is Ellipsis]
    if ell:
        i = ell[0]
        key = key[:i] + (slice(None),) * (a.ndim - n_specified) + key[i + 1 :]
    else:
        key = key + (slice(None),) * (a.ndim - n_specified)

    # advanced: single integer-tensor index
    tensor_idxs = [i for i, k in enumerate(key) if isinstance(k, TensorProxy)]
    if tensor_idxs:
        check(len(tensor_idxs) == 1, lambda: "multiple tensor indices not supported yet")
        ti = tensor_idxs[0]
        pre = key[:ti]
        check(all(k == slice(None) for k in pre), lambda: "tensor index after nontrivial basic index unsupported")
        idx = key[ti]
        if idx.dtype.is_bool:
            raise NotImplementedError("boolean mask indexing not supported yet")
        out = prims.take(a, idx, ti)
        rest = key[ti + 1 :]
        check(all(k == slice(None) for k in rest), lambda: "mixed advanced indexing unsupported")
        return out

    starts, limits, strides = [], [], []
    squeeze_dims = []
    unsqueeze_positions = []
    dim = 0
    out_pos = 0
    for k in key:
        if k is None:
            unsqueeze_positions.append(out_pos)
            out_pos += 1
            continue
        if isinstance(k, (int, NumberProxy)):
            kv = canonicalize_dim(a.shape[dim], int(pyval(k))) if a.shape[dim] > 0 else 0
            starts.append(kv)
            limits.append(kv + 1)
            strides.append(1)
            squeeze_dims.append(dim)
            dim += 1
            continue
        if isinstance(k, slice):
            start, stop, step = k.indices(a.shape[dim])
            check(step > 0, lambda: "negative slice steps unsupported")
            starts.append(start)
            limits.append(stop)
            strides.append(step)
            dim += 1
            out_pos += 1
            continue
        raise NotImplementedError(f"unsupported index element {k!r}")
    out = a
    if starts and (tuple(starts) != (0,) * a.ndim or tuple(limits) != a.shape or set(strides) != {1}):
        out = prims.slice_prim(a, tuple(starts), tuple(limits), tuple(strides))
    if squeeze_dims:
        out = prims.squeeze(out, tuple(squeeze_dims))
    for pos in unsqueeze_positions:
        out = unsqueeze(out, pos)
    return out


def take(a, indices, dim):
    return prims.take(a, indices, dim)


def take_along_axis(a, indices, dim):
    dim = canonicalize_dim(a.ndim, dim)
    return prims.take_along_axis(a, indices, dim)


def index_add(a, indices, value, dim):
    return prims.index_add(a, indices, value, canonicalize_dim(a.ndim, dim))


def scatter_add(a, indices, value, dim):
    return prims.scatter_add(a, indices, value, canonicalize_dim(a.ndim, dim))


# reductions -----------------------------------------------------------------


def _reduction_dims(a, dim):
    if dim is None:
        return tuple(range(a.ndim))
    if isinstance(dim, (int, NumberProxy)):
        dim = (int(pyval(dim)),)
    return canonicalize_dims(a.ndim, tuple(int(pyval(d)) for d in dim))


def _maybe_keepdim(out, a, dims, keepdim):
    if not keepdim:
        return out
    shape = tuple(1 if i in dims else s for i, s in enumerate(a.shape))
    return reshape(out, shape)


def sum_(a, dim=None, keepdim=False, *, dtype=None):
    dims = _reduction_dims(a, dim)
    if dtype is None and (a.dtype.is_bool or (a.dtype.is_int and a.dtype.bytes < 8)):
        dtype = dtypes.int64
    out = prims.sum_prim(a, dims, output_dtype=dtypes.to_dtype(dtype) if dtype else None)
    return _maybe_keepdim(out, a, dims, keepdim)


def mean(a, dim=None, keepdim=False, *, dtype=None):
    dims = _reduction_dims(a, dim)
    count = 1
    for d in dims:
        count *= a.shape[d]
    if dtype is None:
        dtype = a.dtype if a.dtype.is_inexact else dtypes.float32
    s = sum_(maybe_convert_to_dtype(a, dtypes.to_dtype(dtype)), dim, keepdim)
    return true_divide(s, count)


def var(a, dim=None, keepdim=False, *, correction=1):
    dims = _reduction_dims(a, dim)
    out = prims.var_prim(a, dims, correction=correction)
    return _maybe_keepdim(out, a, dims, keepdim)


def var_mean(a, dim=None, keepdim=False, *, correction=1):
    return var(a, dim, keepdim, correction=correction), mean(a, dim, keepdim)


def amax(a, dim=None, keepdim=False):
    dims = _reduction_dims(a, dim)
    out = prims.amax(a, dims)
    return _maybe_keepdim(out, a, dims, keepdim)


def amin(a, dim=None, keepdim=False):
    dims = _reduction_dims(a, dim)
    out = prims.amin(a, dims)
    return _maybe_keepdim(out, a, dims, keepdim)


def argmax(a, dim=None, keepdim=False):
    out = prims.argmax(a, dim)
    if dim is not None and keepdim:
        return _maybe_keepdim(out, a, (canonicalize_dim(a.ndim, pyval(dim)),), keepdim)
    return out


def argmin(a, dim=None, keepdim=False):
    out = prims.argmin(a, dim)
    if dim is not None and keepdim:
        return _maybe_keepdim(out, a, (canonicalize_dim(a.ndim, pyval(dim)),), keepdim)
    return out


def prod(a, dim=None, keepdim=False):
    dims = _reduction_dims(a, dim)
    out = prims.prod_prim(a, dims)
    return _maybe_keepdim(out, a, dims, keepdim)


def any_(a, dim=None, keepdim=False):
    dims = _reduction_dims(a, dim)
    out = prims.any_prim(to_bool(a), dims)
    return _maybe_keepdim(out, a, dims, keepdim)


def all_(a, dim=None, keepdim=False):
    return prims.logical_not(any_(prims.logical_not(to_bool(a)), dim, keepdim))


def cumsum(a, dim):
    return prims.cumsum(a, canonicalize_dim(a.ndim, dim))


# ---------------------------------------------------------------------------
# elementwise core-language wrappers (reference clang's elementwise family,
# thunder/clang/__init__.py — thin delegations: normalization/promotion
# happens in the prims metas; kept at clang level so the core language is
# complete without reaching into ltorch)
# ---------------------------------------------------------------------------


def _unary(prim):
    def op(a):
        return prim(ensure_proxy(a))

    op.__name__ = prim.name if hasattr(prim, "name") else getattr(prim, "__name__", "op")
    return op


abs = _unary(prims.abs)  # noqa: A001 — mirrors reference clang naming
acos = _unary(prims.acos)
acosh = _unary(prims.acosh)
asin = _unary(prims.asin)
asinh = _unary(prims.asinh)
atan = _unary(prims.atan)
atanh = _unary(prims.atanh)
ceil = _unary(prims.ceil)
cos = _unary(prims.cos)
cosh = _unary(prims.cosh)
digamma = _unary(prims.digamma)
erf = _unary(prims.erf)
erfc = _unary(prims.erfc)
erfinv = _unary(prims.erfinv)
exp = _unary(prims.exp)
exp2 = _unary(prims.exp2)
expm1 = _unary(prims.expm1)
floor = _unary(prims.floor)
isfinite = _unary(prims.isfinite)
isnan = _unary(prims.isnan)
lgamma = _unary(prims.lgamma)
log = _unary(prims.log)
log10 = _unary(prims.log10)
log1p = _unary(prims.log1p)
log2 = _unary(prims.log2)
logical_not = _unary(prims.logical_not)
neg = _unary(prims.neg)
reciprocal = _unary(prims.reciprocal)
round = _unary(prims.round)  # noqa: A001
rsqrt = _unary(prims.rsqrt)
sign = _unary(prims.sign)
signbit = _unary(prims.signbit)
sin = _unary(prims.sin)
sinh = _unary(prims.sinh)
sqrt = _unary(prims.sqrt)
tan = _unary(prims.tan)
tanh = _unary(prims.tanh)
trunc = _unary(prims.trunc)


def sigmoid(a):
    return prims.reciprocal(add(prims.exp(prims.neg(ensure_proxy(a))), 1.0))


def silu(a):
    a = ensure_proxy(a)
    return mul(a, sigmoid(a))


def pow(a, b):  # noqa: A001
    return _elementwise_binary(prims.pow, a, b)


def copysign(a, b):
    return _elementwise_binary(prims.copysign, a, b)


def nextafter(a, b):
    return _elementwise_binary(prims.nextafter, a, b)


def zeta(a, b):
    from ..ops.auto_register import get_auto_symbol

    return get_auto_symbol("special_zeta")(ensure_proxy(a), ensure_proxy(b))


def logical_xor(a, b):
    return ne(maybe_convert_to_dtype(ensure_proxy(a), dtypes.bool8),
              maybe_convert_to_dtype(ensure_proxy(b), dtypes.bool8))


def bitwise_not(a):
    return prims.bitwise_not(ensure_proxy(a))


def bitwise_left_shift(a, b):
    return prims.shift_left(ensure_proxy(a), ensure_proxy(b))


def bitwise_right_shift(a, b):
    return prims.shift_right(ensure_proxy(a), ensure_proxy(b))


def mod(a, b):
    return _elementwise_binary(prims.remainder, a, b)


def trunc_divide(a, b):
    return trunc(true_divide(a, b))


def lerp(start, end, weight):
    start, end = ensure_proxy(start), ensure_proxy(end)
    return add(start, mul(weight, sub(end, start)))


# ---------------------------------------------------------------------------
# indexing / structure core ops
# ---------------------------------------------------------------------------


def gather(a, indices, dim):
    """take_along_axis semantics (reference clang.gather)."""
    return take_along_axis(a, indices, dim)


def scatter(a, indices, src, dim):
    from . import ltorch

    return ltorch.scatter(a, dim, indices, src)


def index_copy(a, dim, indices, src):
    """Copy rows of src into a at positions `indices` along dim."""
    from . import ltorch

    d = canonicalize_dim(a.ndim, pyval(dim))
    idx_shape = [1] * a.ndim
    idx_shape[d] = -1
    bshape = list(a.shape)
    bshape[d] = indices.shape[0]
    idx = expand(reshape(indices, tuple(idx_shape)), tuple(bshape))
    return ltorch.scatter(a, d, idx, src)


def index_put(a, indices, values, accumulate=False):
    """a[indices] = values (or += with accumulate) — advanced-index write."""
    from . import ltorch

    a = ensure_proxy(a)
    if len(indices) == 1 and not accumulate:
        d = 0
        idx = indices[0]
        bshape = list(a.shape)
        bshape[d] = idx.shape[0]
        idx_shape = [1] * a.ndim
        idx_shape[d] = -1
        full_idx = expand(reshape(idx, tuple(idx_shape)), tuple(bshape))
        src = values if tuple(values.shape) == tuple(bshape) else expand(values, tuple(bshape))
        return ltorch.scatter(a, d, full_idx, src)
    if len(indices) == 1 and accumulate:
        idx = indices[0]
        bshape = list(a.shape)
        bshape[0] = idx.shape[0]
        idx_shape = [1] * a.ndim
        idx_shape[0] = -1
        full_idx = expand(reshape(idx, tuple(idx_shape)), tuple(bshape))
        src = values if tuple(values.shape) == tuple(bshape) else expand(values, tuple(bshape))
        return scatter_add(a, full_idx, src, 0)
    if len(indices) > 1 and all(getattr(i, "ndim", None) == 1 for i in indices):
        # multiple 1-D index vectors over the LEADING dims (the paged-KV
        # write pattern: pool[page_ids, slots] = token_kv): linearize to one
        # flat index over the collapsed leading dims and recurse into the
        # single-index path. Same-length vectors index jointly, numpy-style.
        # Each vector is canonicalized with remainder (Python-modulo
        # semantics) so numpy-style negative indices land in THEIR dim
        # before linearization — a raw -1 in dim d would otherwise address
        # the previous row's last slot.
        n = len(indices)
        check(a.ndim >= n,
              lambda: f"index_put: {n} index tensors over a rank-{a.ndim} input")
        flat = remainder(indices[0], a.shape[0])
        for d in range(1, n):
            flat = flat * a.shape[d] + remainder(indices[d], a.shape[d])
        lead = 1
        for d in range(n):
            lead *= a.shape[d]
        a_flat = reshape(a, (lead,) + tuple(a.shape[n:]))
        out = index_put(a_flat, (flat,), values, accumulate)
        return reshape(out, tuple(a.shape))
    raise NotImplementedError("index_put with multiple >1-D index tensors")


def diagonal(a, offset=0, dim1=0, dim2=1):
    from . import ltorch

    return ltorch.diagonal_op(a, offset, dim1, dim2)


def sort(a, dim=-1, descending=False):
    from . import ltorch

    return ltorch.sort(a, dim, descending)


def topk(a, k, dim=-1):
    from . import ltorch

    return ltorch.topk(a, k, dim)


def unfold(a, dim, size, step):
    """Sliding windows along `dim` (tensor.unfold semantics)."""
    from ..ops.auto_register import get_auto_symbol

    return get_auto_symbol("unfold_dim")(ensure_proxy(a), pyval(dim), pyval(size), pyval(step))


def tensor_from_sequence(seq, *, dtype=None, device=None):
    import numpy as _np

    def conv(x):
        if isinstance(x, NumberProxy):
            return pyval(x)
        if isinstance(x, (list, tuple)):
            return [conv(e) for e in x]
        return x

    arr = _np.asarray(conv(list(seq)))
    if dtype is not None:
        arr = arr.astype(dtypes.to_jax_dtype(dtypes.to_dtype(dtype)))
    elif arr.dtype == _np.float64:
        arr = arr.astype(_np.float32)  # match jax x64-off default
    elif arr.dtype == _np.int64:
        arr = arr.astype(_np.int32)
    return constant(arr)


def empty(shape, *, dtype=dtypes.float32, device=None):
    """Uninitialized-by-contract tensor (implemented as zeros: XLA has no
    uninitialized allocation; the contract is only that values are unread)."""
    return full(tuple(shape), 0, dtype=dtype, device=device)


def uniform(shape, minval=0.0, maxval=1.0, *, dtype=dtypes.float32, device=None, key=None):
    return prims.uniform(tuple(shape), minval, maxval, dtype=dtype, key=key)


def uniform_like(a, minval=0.0, maxval=1.0, *, key=None):
    return prims.uniform(tuple(a.shape), minval, maxval, dtype=a.dtype, key=key)


def real(a):
    from ..ops.auto_register import get_auto_symbol

    return get_auto_symbol("real")(ensure_proxy(a))


def imag(a):
    from ..ops.auto_register import get_auto_symbol

    return get_auto_symbol("imag")(ensure_proxy(a))
