from . import clang, ltorch
