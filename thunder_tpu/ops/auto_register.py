"""Auto-registered fallback ops — the analog of the reference's
thunder/torch/default_torch_ops.py:3 (~700 torch ops registered as opaque
single-op symbols, tagged AUTO_REGISTERED).

Each catalog entry becomes a Symbol whose meta is derived automatically with
``jax.eval_shape`` over the proxies (no hand-written shape rules), whose
execution is the jax function itself (registered on jaxex, so XLA fusion
still applies to surrounding ops), and whose gradient — when the op is
differentiable — rides the generic ``jax.vjp`` fallback in the autodiff
transform. This is how long-tail API surface (fft / linalg / special) is
covered without one-off shape rules."""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.proxies import NumberProxy, Proxy, TensorProxy, pyval
from ..core.symbol import Symbol

AUTO_REGISTERED = "auto_registered"

_auto_symbols: dict[str, Symbol] = {}


class _Slot:
    """Placeholder marking where a tensor spec goes in an otherwise-static
    argument structure (static scalars/axes must NOT pass through eval_shape,
    which would turn them into tracers and break ops with static params)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def _map_structure(x, leaf_fn):
    if _is_namedtuple(x):
        return type(x)(*(_map_structure(e, leaf_fn) for e in x))
    if isinstance(x, (tuple, list)):
        return type(x)(_map_structure(e, leaf_fn) for e in x)
    if isinstance(x, dict):
        return {k: _map_structure(v, leaf_fn) for k, v in x.items()}
    return leaf_fn(x)


def _from_spec(x, device):
    if isinstance(x, jax.ShapeDtypeStruct):
        return TensorProxy(shape=tuple(x.shape), dtype=dtypes.to_dtype(x.dtype), device=device)
    if _is_namedtuple(x):
        # namedtuple results (eigh/qr/svd/slogdet) surface as plain tuples of
        # proxies — trace collections are positional anyway
        return tuple(_from_spec(e, device) for e in x)
    if isinstance(x, (tuple, list)):
        return type(x)(_from_spec(e, device) for e in x)
    return x


def _find_device(args):
    for a in jax.tree_util.tree_leaves(args, is_leaf=lambda x: isinstance(x, Proxy)):
        if isinstance(a, TensorProxy):
            return a.device
    return None


def register_auto_op(name: str, fn: Callable, *, differentiable: bool = True) -> Symbol:
    """Create and register an opaque single-op symbol for a jax callable."""
    sym_id = f"auto.{name}"

    def meta(*args, **kwargs):
        device = _find_device((args, kwargs))
        specs: list[jax.ShapeDtypeStruct] = []

        def to_slot(x):
            if isinstance(x, TensorProxy):
                specs.append(jax.ShapeDtypeStruct(tuple(x.shape), dtypes.to_jax_dtype(x.dtype)))
                return _Slot(len(specs) - 1)
            if isinstance(x, NumberProxy):
                return pyval(x)
            return x

        sub_args = _map_structure(list(args), to_slot)
        sub_kwargs = _map_structure(dict(kwargs), to_slot)

        def call(spec_vals):
            def fill(x):
                return spec_vals[x.i] if isinstance(x, _Slot) else x

            return fn(*_map_structure(sub_args, fill), **_map_structure(sub_kwargs, fill))

        out = jax.eval_shape(call, specs)
        return _from_spec(out, device)

    meta.__name__ = name
    sym = Symbol(name, meta, id=sym_id, module="auto", tags=(AUTO_REGISTERED,))
    _auto_symbols[sym_id] = sym

    from ..executors import jaxex

    jaxex.ex.register_implementation(sym_id, fn)

    if differentiable:
        from ..transforms import autodiff

        autodiff.JAX_VJP_FALLBACK.add(sym_id)
    return sym


def get_auto_symbol(name: str) -> Symbol | None:
    return _auto_symbols.get(f"auto.{name}")


def list_auto_ops() -> list[str]:
    return sorted(s.name for s in _auto_symbols.values())


# ---------------------------------------------------------------------------
# catalog — torch-name : jax impl  (reference default_torch_ops.py families:
# torch.fft.*, torch.linalg.*, torch.special.*, long-tail tensor ops)
# ---------------------------------------------------------------------------

_CATALOG_DIFF: dict[str, Callable] = {
    # fft family (torch.fft.*)
    "fft_fft": lambda a, n=None, dim=-1: jnp.fft.fft(a, n=n, axis=dim),
    "fft_ifft": lambda a, n=None, dim=-1: jnp.fft.ifft(a, n=n, axis=dim),
    "fft_rfft": lambda a, n=None, dim=-1: jnp.fft.rfft(a, n=n, axis=dim),
    "fft_irfft": lambda a, n=None, dim=-1: jnp.fft.irfft(a, n=n, axis=dim),
    "fft_fft2": lambda a: jnp.fft.fft2(a),
    "fft_ifft2": lambda a: jnp.fft.ifft2(a),
    "fft_rfft2": lambda a: jnp.fft.rfft2(a),
    "fft_irfft2": lambda a: jnp.fft.irfft2(a),
    "fft_fftn": lambda a: jnp.fft.fftn(a),
    "fft_ifftn": lambda a: jnp.fft.ifftn(a),
    "fft_fftshift": jnp.fft.fftshift,
    "fft_ifftshift": jnp.fft.ifftshift,
    # linalg family (torch.linalg.*)
    "linalg_inv": jnp.linalg.inv,
    "linalg_pinv": jnp.linalg.pinv,
    "linalg_det": jnp.linalg.det,
    "linalg_slogdet": jnp.linalg.slogdet,
    "linalg_cholesky": jnp.linalg.cholesky,
    "linalg_qr": jnp.linalg.qr,
    "linalg_svd": lambda a, full_matrices=True: jnp.linalg.svd(a, full_matrices=full_matrices),
    "linalg_svdvals": lambda a: jnp.linalg.svd(a, compute_uv=False),
    "linalg_eigh": jnp.linalg.eigh,
    "linalg_eigvalsh": jnp.linalg.eigvalsh,
    "linalg_solve": jnp.linalg.solve,
    "linalg_lstsq": lambda a, b: jnp.linalg.lstsq(a, b)[0],
    "linalg_matrix_rank": jnp.linalg.matrix_rank,
    "linalg_matrix_power": jnp.linalg.matrix_power,
    "linalg_norm": jnp.linalg.norm,
    "linalg_cross": jnp.cross,
    "linalg_tensorsolve": jnp.linalg.tensorsolve,
    "linalg_multi_dot": lambda *mats: jnp.linalg.multi_dot(mats),
    "cholesky_solve": lambda b, L: jax.scipy.linalg.cho_solve((L, True), b),
    "triangular_solve": lambda b, A, upper=True: jax.scipy.linalg.solve_triangular(A, b, lower=not upper),
    # special functions (torch.special.*)
    "special_i0": jax.scipy.special.i0,
    "special_i1": jax.scipy.special.i1,
    "special_i0e": jax.scipy.special.i0e,
    "special_i1e": jax.scipy.special.i1e,
    "special_betainc": jax.scipy.special.betainc,
    "special_gammainc": jax.scipy.special.gammainc,
    "special_gammaincc": jax.scipy.special.gammaincc,
    "special_zeta": jax.scipy.special.zeta,
    "special_ndtr": jax.scipy.special.ndtr,
    "special_ndtri": jax.scipy.special.ndtri,
    "special_entr": jax.scipy.special.entr,
    "special_expit": jax.scipy.special.expit,
    "special_log_ndtr": jax.scipy.special.log_ndtr,
    "special_logsumexp": jax.scipy.special.logsumexp,
    "polygamma": lambda n, a: jax.scipy.special.polygamma(n, a),
    "sinc": jnp.sinc,
    # long-tail tensor ops
    "trace": jnp.trace,
    "flipud": jnp.flipud,
    "fliplr": jnp.fliplr,
    "rot90": lambda a, k=1, dims=(0, 1): jnp.rot90(a, k=k, axes=tuple(dims)),
    "unwrap": jnp.unwrap,
    "cross": lambda a, b, dim=-1: jnp.cross(a, b, axis=dim),
    "renorm": lambda a, p, dim, maxnorm: a * jnp.minimum(
        1.0, maxnorm / jnp.maximum(jnp.linalg.norm(a, ord=p, axis=tuple(
            i for i in range(a.ndim) if i != dim), keepdims=True), 1e-12)),
    "logcumsumexp": lambda a, dim: jax.lax.cumlogsumexp(a, axis=dim),
    "cummin": lambda a, dim: jax.lax.cummin(a, axis=dim),
    "polyval": lambda coeffs, x: jnp.polyval(coeffs, x),
    "lerp": lambda a, b, w: a + w * (b - a),
    "addcmul": lambda a, t1, t2, value=1.0: a + value * t1 * t2,
    "addcdiv": lambda a, t1, t2, value=1.0: a + value * t1 / t2,
    "cov": lambda a: jnp.cov(a),
    "corrcoef": lambda a: jnp.corrcoef(a),
    "vander": lambda x, N=None: jnp.vander(x, N),
    # wave 3 — blas-style composites (torch.addmm family)
    "addmm": lambda inp, m1, m2, beta=1.0, alpha=1.0: beta * inp + alpha * (m1 @ m2),
    "addbmm": lambda inp, b1, b2, beta=1.0, alpha=1.0: beta * inp + alpha * jnp.sum(b1 @ b2, 0),
    "baddbmm": lambda inp, b1, b2, beta=1.0, alpha=1.0: beta * inp + alpha * (b1 @ b2),
    "addmv": lambda inp, m, v, beta=1.0, alpha=1.0: beta * inp + alpha * (m @ v),
    "addr": lambda inp, v1, v2, beta=1.0, alpha=1.0: beta * inp + alpha * jnp.outer(v1, v2),
    "bmm": lambda a, b: a @ b,
    "ger": jnp.outer,
    "inner": jnp.inner,
    "matrix_exp": jax.scipy.linalg.expm,
    "linalg_matrix_exp": jax.scipy.linalg.expm,
    "adjoint": lambda a: jnp.conjugate(jnp.swapaxes(a, -2, -1)),
    "cholesky_inverse": lambda L, upper=False: jnp.linalg.inv(
        (L @ jnp.conjugate(jnp.swapaxes(L, -2, -1))) if not upper
        else (jnp.conjugate(jnp.swapaxes(L, -2, -1)) @ L)),
    "linalg_cond": lambda a, p=None: jnp.linalg.cond(a, p),
    "linalg_vector_norm": lambda a, ord=2, dim=None, keepdim=False: jnp.linalg.norm(
        a, ord=ord, axis=dim, keepdims=keepdim),
    "linalg_matrix_norm": lambda a, ord="fro", dim=(-2, -1), keepdim=False: jnp.linalg.norm(
        a, ord=ord, axis=tuple(dim), keepdims=keepdim),
    "linalg_vecdot": lambda a, b, dim=-1: jnp.sum(jnp.conjugate(a) * b, axis=dim),
    "linalg_householder_product": lambda a, tau: _householder_product(a, tau),
    # complex support
    "real": jnp.real,
    "imag": jnp.imag,
    "conj": jnp.conjugate,
    "conj_physical": jnp.conjugate,
    "angle": jnp.angle,
    "view_as_real": lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1),
    "view_as_complex": lambda a: jax.lax.complex(a[..., 0], a[..., 1]),
    "complex_build": jax.lax.complex,
    "polar": lambda r, theta: jax.lax.complex(r * jnp.cos(theta), r * jnp.sin(theta)),
    # stacking / reshaping long tail
    "dstack": lambda ts: jnp.dstack(ts),
    "hstack": lambda ts: jnp.hstack(ts),
    "vstack": lambda ts: jnp.vstack(ts),
    "column_stack": lambda ts: jnp.column_stack(ts),
    "row_stack": lambda ts: jnp.vstack(ts),
    "atleast_1d": jnp.atleast_1d,
    "atleast_2d": jnp.atleast_2d,
    "atleast_3d": jnp.atleast_3d,
    "swapdims": lambda a, d0, d1: jnp.swapaxes(a, d0, d1),
    "moveaxis": lambda a, s, d: jnp.moveaxis(a, s, d),
    "diag_embed": lambda a, offset=0, dim1=-2, dim2=-1: _diag_embed_dims(a, offset, dim1, dim2),
    "diagflat": lambda a, offset=0: jnp.diagflat(a, offset),
    "diagonal": lambda a, offset=0, dim1=0, dim2=1: jnp.diagonal(a, offset, dim1, dim2),
    "diagonal_scatter": lambda a, src, offset=0, dim1=0, dim2=1: _diagonal_scatter(a, src, offset, dim1, dim2),
    "tril": lambda a, diagonal=0: jnp.tril(a, diagonal),
    "triu": lambda a, diagonal=0: jnp.triu(a, diagonal),
    "narrow_copy": lambda a, dim, start, length: jax.lax.slice_in_dim(a, start, start + length, axis=dim),
    "unfold_dim": lambda a, dim, size, step: _unfold(a, dim, size, step),
    "pixel_shuffle": lambda a, r: _pixel_shuffle(a, r),
    "pixel_unshuffle": lambda a, r: _pixel_unshuffle(a, r),
    "channel_shuffle": lambda a, groups: _channel_shuffle(a, groups),
    # numerical long tail
    "nanmedian": lambda a, dim=None, keepdim=False: jnp.nanmedian(
        a, axis=dim, keepdims=keepdim),
    "nanquantile": lambda a, q, dim=None, keepdim=False: jnp.nanquantile(
        a, q, axis=dim, keepdims=keepdim),
    "quantile": lambda a, q, dim=None, keepdim=False: jnp.quantile(
        a, q, axis=dim, keepdims=keepdim),
    "diff": lambda a, n=1, dim=-1: jnp.diff(a, n=n, axis=dim),
    "trapezoid": lambda y, x=None, dim=-1: jnp.trapezoid(y, x, axis=dim),
    "cumulative_trapezoid": lambda y, x=None, dim=-1: _cumtrapz(y, x, dim),
    "gcd": jnp.gcd,
    "lcm": jnp.lcm,
    "ldexp": jnp.ldexp,
    "frexp": jnp.frexp,
    "nextafter": jnp.nextafter,
    "deg2rad": jnp.deg2rad,
    "rad2deg": jnp.rad2deg,
    "positive": jnp.positive,
    "float_power": jnp.float_power,
    "true_divide_": jnp.true_divide,
    "floor_divide": jnp.floor_divide,
    "fmax": jnp.fmax,
    "fmin": jnp.fmin,
    "logit": lambda a, eps=None: jax.scipy.special.logit(
        jnp.clip(a, eps, 1 - eps) if eps is not None else a),
    "mvlgamma": lambda a, p: jax.scipy.special.multigammaln(a, p),
    "special_multigammaln": lambda a, p: jax.scipy.special.multigammaln(a, p),
    "special_erfcx": lambda a: _erfcx(a),
    "special_xlog1py": jax.scipy.special.xlog1py,
    "special_xlogy": jax.scipy.special.xlogy,
    "special_digamma": jax.scipy.special.digamma,
    "special_psi": jax.scipy.special.digamma,
    "special_erf": jax.scipy.special.erf,
    "special_erfc": jax.scipy.special.erfc,
    "special_erfinv": jax.scipy.special.erfinv,
    "special_exp2": jnp.exp2,
    "special_expm1": jnp.expm1,
    "special_log1p": jnp.log1p,
    "special_sinc": jnp.sinc,
    "special_round": jnp.round,
    "special_gammaln": jax.scipy.special.gammaln,
    "igamma": jax.scipy.special.gammainc,
    "igammac": jax.scipy.special.gammaincc,
    "cosine_similarity": lambda x1, x2, dim=1, eps=1e-8: jnp.sum(x1 * x2, axis=dim) / jnp.maximum(
        jnp.linalg.norm(x1, axis=dim) * jnp.linalg.norm(x2, axis=dim), eps),
    "pairwise_distance": lambda x1, x2, p=2.0, eps=1e-6, keepdim=False: jnp.linalg.norm(
        x1 - x2 + eps, ord=p, axis=-1, keepdims=keepdim),
    "cdist": lambda x1, x2, p=2.0: _cdist(x1, x2, p),
    "normalize_fn": lambda a, p=2.0, dim=1, eps=1e-12: a / jnp.maximum(
        jnp.linalg.norm(a, ord=p, axis=dim, keepdims=True), eps),
    # nn.functional long tail (elementwise activations)
    "elu": lambda a, alpha=1.0: jnp.where(a > 0, a, alpha * jnp.expm1(a)),
    "selu": jax.nn.selu,
    "celu": lambda a, alpha=1.0: jax.nn.celu(a, alpha),
    "glu": lambda a, dim=-1: jax.nn.glu(a, axis=dim),
    "hardswish": jax.nn.hard_swish,
    "hardsigmoid": jax.nn.hard_sigmoid,
    "hardtanh": lambda a, min_val=-1.0, max_val=1.0: jnp.clip(a, min_val, max_val),
    "softsign": jax.nn.soft_sign,
    "tanhshrink": lambda a: a - jnp.tanh(a),
    "hardshrink": lambda a, lambd=0.5: jnp.where(jnp.abs(a) > lambd, a, 0.0),
    "softshrink": lambda a, lambd=0.5: jnp.where(
        a > lambd, a - lambd, jnp.where(a < -lambd, a + lambd, 0.0)),
    "threshold": lambda a, threshold, value: jnp.where(a > threshold, a, value),
    "logsigmoid": jax.nn.log_sigmoid,
    "mish": lambda a: a * jnp.tanh(jax.nn.softplus(a)),
    "softplus": lambda a, beta=1.0, threshold=20.0: jnp.where(
        a * beta > threshold, a, jax.nn.softplus(a * beta) / beta),
    "prelu": lambda a, weight: _prelu(a, weight),
    "rrelu_eval": lambda a, lower=0.125, upper=1.0 / 3: jnp.where(
        a >= 0, a, a * (lower + upper) / 2),
}


def _householder_product(a, tau):
    m, n = a.shape[-2], a.shape[-1]
    q = jnp.eye(m, dtype=a.dtype)
    for i in range(n):
        v = jnp.concatenate([jnp.zeros((i,), a.dtype), jnp.ones((1,), a.dtype), a[i + 1:, i]])
        q = q @ (jnp.eye(m, dtype=a.dtype) - tau[i] * jnp.outer(v, v))
    return q


def _diag_embed_dims(a, offset, dim1, dim2):
    out = _diag_embed(a, offset)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (-2, -1), (d1, d2))
    return out


def _erfcx(a):
    """Scaled complementary error function, overflow-safe: asymptotic series
    1/(x sqrt(pi)) (1 - 1/(2x^2) + 3/(4x^4)) for large positive x."""
    x = a
    direct = jnp.exp(x * x) * jax.scipy.special.erfc(x)
    xs = jnp.where(jnp.abs(x) > 6.0, x, 6.0)  # avoid div-by-small in unused lane
    inv2 = 1.0 / (xs * xs)
    series = (1.0 - 0.5 * inv2 + 0.75 * inv2 * inv2) / (xs * jnp.sqrt(jnp.pi))
    return jnp.where(x > 6.0, series, direct)


def _prelu(a, weight):
    if getattr(weight, "ndim", 0) >= 1 and weight.shape[0] > 1 and a.ndim >= 2:
        # per-channel weight applies along dim 1 (torch semantics)
        weight = weight.reshape((1, -1) + (1,) * (a.ndim - 2))
    return jnp.where(a >= 0, a, weight * a)


def _diag_embed(a, offset=0):
    n = a.shape[-1] + abs(offset)
    base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
    idx = jnp.arange(a.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    return base.at[..., r, c].set(a)


def _diagonal_scatter(a, src, offset, dim1, dim2):
    a_m = jnp.moveaxis(a, (dim1, dim2), (-2, -1))
    idx = jnp.arange(src.shape[-1])
    r = idx + max(-offset, 0)
    c = idx + max(offset, 0)
    out = a_m.at[..., r, c].set(src)
    return jnp.moveaxis(out, (-2, -1), (dim1, dim2))


def _unfold(a, dim, size, step):
    n = (a.shape[dim] - size) // step + 1
    idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
    moved = jnp.moveaxis(a, dim, -1)
    win = moved[..., idx]  # (..., n, size)
    return jnp.moveaxis(win, -2, dim)


def _pixel_shuffle(a, r):
    b, c, h, w = a.shape
    a = a.reshape(b, c // (r * r), r, r, h, w)
    a = a.transpose(0, 1, 4, 2, 5, 3)
    return a.reshape(b, c // (r * r), h * r, w * r)


def _pixel_unshuffle(a, r):
    b, c, h, w = a.shape
    a = a.reshape(b, c, h // r, r, w // r, r)
    a = a.transpose(0, 1, 3, 5, 2, 4)
    return a.reshape(b, c * r * r, h // r, w // r)


def _channel_shuffle(a, groups):
    b, c = a.shape[:2]
    rest = a.shape[2:]
    return a.reshape(b, groups, c // groups, *rest).swapaxes(1, 2).reshape(a.shape)


def _cdist(x1, x2, p):
    d = x1[..., :, None, :] - x2[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
    return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)


def _cumtrapz(y, x, dim):
    import jax.numpy as _j

    yl = jnp.moveaxis(y, dim, -1)
    avg = (yl[..., 1:] + yl[..., :-1]) / 2
    if x is not None:
        dx = jnp.diff(jnp.moveaxis(x, dim, -1) if x.ndim == y.ndim else x)
        avg = avg * dx
    return jnp.moveaxis(jnp.cumsum(avg, -1), -1, dim)

# torch alias families + additional long tail — every name here is a REAL
# torch callable name reachable through _auto_catalog_lookup (plain
# torch.<name> / torch.special.<name> / torch.linalg.<name>) or the frontend
# name-based generic path; no invented identifiers
_CATALOG_DIFF.update({
    "arccos": jnp.arccos,
    "arccosh": jnp.arccosh,
    "arcsin": jnp.arcsin,
    "arcsinh": jnp.arcsinh,
    "arctan": jnp.arctan,
    "arctan2": jnp.arctan2,
    "arctanh": jnp.arctanh,
    "absolute": jnp.abs,
    "negative": jnp.negative,
    "subtract": lambda a, b, alpha=1.0: a - alpha * b,
    "multiply": jnp.multiply,
    "divide": jnp.divide,
    "fix": jnp.trunc,  # torch.fix aliases trunc; jnp.fix is deprecated (JAX 0.10 removal)
    "concat": lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
    "concatenate": lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
    # activations (functional names the frontend resolves by __name__)
    # losses (functional long tail)
    "gaussian_nll_loss": lambda mu, tgt, var, full=False, eps=1e-6, reduction="mean": _reduce(
        0.5 * (jnp.log(jnp.maximum(var, eps)) + (tgt - mu) ** 2 / jnp.maximum(var, eps)),
        reduction),
    # legacy torch.* linalg names
    "pinverse": jnp.linalg.pinv,
    "inverse": jnp.linalg.inv,
    "det": jnp.linalg.det,
    "logdet": lambda a: (lambda sign, logabs: jnp.where(
        sign > 0, logabs, jnp.where(sign == 0, -jnp.inf, jnp.nan)))(
        *jnp.linalg.slogdet(a)),
    "slogdet": jnp.linalg.slogdet,
    "cholesky": jnp.linalg.cholesky,
    "qr": lambda a, some=True: jnp.linalg.qr(a, mode="reduced" if some else "complete"),
    # torch.svd contract: A = U diag(S) V^T -> third output is V, not Vh
    "svd": lambda a, some=True: (lambda u, s2, vh: (u, s2, jnp.swapaxes(vh, -2, -1)))(
        *jnp.linalg.svd(a, full_matrices=not some)),
    "matrix_rank": jnp.linalg.matrix_rank,
    "dist": lambda a, b, p=2.0: jnp.linalg.norm(jnp.ravel(a - b), ord=p),
    "orgqr": lambda a, tau: _householder_product(a, tau),
    "nuclear_norm": lambda a: jnp.sum(jnp.linalg.svd(a, compute_uv=False)),
    "frobenius_norm": lambda a: jnp.linalg.norm(a),
    # reductions & statistics (real torch.* names)
    "std_mean": lambda a, dim=None, correction=1, keepdim=False: (
        jnp.std(a, axis=dim, ddof=correction, keepdims=keepdim),
        jnp.mean(a, axis=dim, keepdims=keepdim)),
    "var_mean": lambda a, dim=None, correction=1, keepdim=False: (
        jnp.var(a, axis=dim, ddof=correction, keepdims=keepdim),
        jnp.mean(a, axis=dim, keepdims=keepdim)),
    "msort": lambda a: jnp.sort(a, axis=0),
    "kthvalue": lambda a, k, dim=-1: (
        jnp.sort(a, axis=dim).take(k - 1, axis=dim),
        jnp.argsort(a, axis=dim).take(k - 1, axis=dim)),
    "take": lambda a, idx: jnp.take(jnp.ravel(a), idx),
    # torch.special extras
    "special_softmax": lambda a, dim=-1: jax.nn.softmax(a, axis=dim),
    "special_log_softmax": lambda a, dim=-1: jax.nn.log_softmax(a, axis=dim),
    "i0": jax.scipy.special.i0,
    "meshgrid": lambda *ts, indexing="ij": jnp.meshgrid(*ts, indexing=indexing),
})


def _reduce(x, reduction):
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


_CATALOG_NONDIFF: dict[str, Callable] = {
    "searchsorted": lambda sorted_seq, values, right=False: jnp.searchsorted(
        sorted_seq, values, side="right" if right else "left"),
    "bucketize": lambda values, boundaries, right=False: jnp.searchsorted(
        boundaries, values, side="right" if right else "left"),
    # torch.bincount's output length depends on max(a) — a dynamic shape XLA
    # cannot express; intentionally NOT registered (like nonzero/unique)
    "histc": lambda a, bins=100, min=0.0, max=0.0: jnp.histogram(
        a, bins=bins, range=(min, max) if (min or max) else None)[0],
    "isclose": jnp.isclose,
    "allclose": jnp.allclose,
    "equal": jnp.array_equal,
    "isin": jnp.isin,
    "isreal": jnp.isreal,
    "tril_indices": lambda row, col, offset=0: jnp.stack(jnp.tril_indices(row, offset, col)),
    "triu_indices": lambda row, col, offset=0: jnp.stack(jnp.triu_indices(row, offset, col)),
    "argwhere_size": lambda a, size: jnp.argwhere(a, size=size),  # static-size variant
    "float_power_int": lambda a, b: jnp.float_power(a, b),
    # nondiff long tail (real torch.* names)
    "isposinf": jnp.isposinf,
    "isneginf": jnp.isneginf,
}


def register_catalog() -> int:
    for name, fn in _CATALOG_DIFF.items():
        if f"auto.{name}" not in _auto_symbols:
            register_auto_op(name, fn, differentiable=True)
    for name, fn in _CATALOG_NONDIFF.items():
        if f"auto.{name}" not in _auto_symbols:
            register_auto_op(name, fn, differentiable=False)
    from .auto_catalog_ext import register_ext_catalog

    register_ext_catalog()
    return len(_auto_symbols)


register_catalog()
