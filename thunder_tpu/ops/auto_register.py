"""Auto-registered fallback ops — the analog of the reference's
thunder/torch/default_torch_ops.py:3 (~700 torch ops registered as opaque
single-op symbols, tagged AUTO_REGISTERED).

Each catalog entry becomes a Symbol whose meta is derived automatically with
``jax.eval_shape`` over the proxies (no hand-written shape rules), whose
execution is the jax function itself (registered on jaxex, so XLA fusion
still applies to surrounding ops), and whose gradient — when the op is
differentiable — rides the generic ``jax.vjp`` fallback in the autodiff
transform. This is how long-tail API surface (fft / linalg / special) is
covered without one-off shape rules."""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.proxies import NumberProxy, Proxy, TensorProxy, pyval
from ..core.symbol import Symbol

AUTO_REGISTERED = "auto_registered"

_auto_symbols: dict[str, Symbol] = {}


class _Slot:
    """Placeholder marking where a tensor spec goes in an otherwise-static
    argument structure (static scalars/axes must NOT pass through eval_shape,
    which would turn them into tracers and break ops with static params)."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def _map_structure(x, leaf_fn):
    if _is_namedtuple(x):
        return type(x)(*(_map_structure(e, leaf_fn) for e in x))
    if isinstance(x, (tuple, list)):
        return type(x)(_map_structure(e, leaf_fn) for e in x)
    if isinstance(x, dict):
        return {k: _map_structure(v, leaf_fn) for k, v in x.items()}
    return leaf_fn(x)


def _from_spec(x, device):
    if isinstance(x, jax.ShapeDtypeStruct):
        return TensorProxy(shape=tuple(x.shape), dtype=dtypes.to_dtype(x.dtype), device=device)
    if _is_namedtuple(x):
        # namedtuple results (eigh/qr/svd/slogdet) surface as plain tuples of
        # proxies — trace collections are positional anyway
        return tuple(_from_spec(e, device) for e in x)
    if isinstance(x, (tuple, list)):
        return type(x)(_from_spec(e, device) for e in x)
    return x


def _find_device(args):
    for a in jax.tree_util.tree_leaves(args, is_leaf=lambda x: isinstance(x, Proxy)):
        if isinstance(a, TensorProxy):
            return a.device
    return None


def register_auto_op(name: str, fn: Callable, *, differentiable: bool = True) -> Symbol:
    """Create and register an opaque single-op symbol for a jax callable."""
    sym_id = f"auto.{name}"

    def meta(*args, **kwargs):
        device = _find_device((args, kwargs))
        specs: list[jax.ShapeDtypeStruct] = []

        def to_slot(x):
            if isinstance(x, TensorProxy):
                specs.append(jax.ShapeDtypeStruct(tuple(x.shape), dtypes.to_jax_dtype(x.dtype)))
                return _Slot(len(specs) - 1)
            if isinstance(x, NumberProxy):
                return pyval(x)
            return x

        sub_args = _map_structure(list(args), to_slot)
        sub_kwargs = _map_structure(dict(kwargs), to_slot)

        def call(spec_vals):
            def fill(x):
                return spec_vals[x.i] if isinstance(x, _Slot) else x

            return fn(*_map_structure(sub_args, fill), **_map_structure(sub_kwargs, fill))

        out = jax.eval_shape(call, specs)
        return _from_spec(out, device)

    meta.__name__ = name
    sym = Symbol(name, meta, id=sym_id, module="auto", tags=(AUTO_REGISTERED,))
    _auto_symbols[sym_id] = sym

    from ..executors import jaxex

    jaxex.ex.register_implementation(sym_id, fn)

    if differentiable:
        from ..transforms import autodiff

        autodiff.JAX_VJP_FALLBACK.add(sym_id)
    return sym


def get_auto_symbol(name: str) -> Symbol | None:
    return _auto_symbols.get(f"auto.{name}")


def list_auto_ops() -> list[str]:
    return sorted(s.name for s in _auto_symbols.values())


# ---------------------------------------------------------------------------
# catalog — torch-name : jax impl  (reference default_torch_ops.py families:
# torch.fft.*, torch.linalg.*, torch.special.*, long-tail tensor ops)
# ---------------------------------------------------------------------------

_CATALOG_DIFF: dict[str, Callable] = {
    # fft family (torch.fft.*)
    "fft_fft": lambda a, n=None, dim=-1: jnp.fft.fft(a, n=n, axis=dim),
    "fft_ifft": lambda a, n=None, dim=-1: jnp.fft.ifft(a, n=n, axis=dim),
    "fft_rfft": lambda a, n=None, dim=-1: jnp.fft.rfft(a, n=n, axis=dim),
    "fft_irfft": lambda a, n=None, dim=-1: jnp.fft.irfft(a, n=n, axis=dim),
    "fft_fft2": lambda a: jnp.fft.fft2(a),
    "fft_ifft2": lambda a: jnp.fft.ifft2(a),
    "fft_rfft2": lambda a: jnp.fft.rfft2(a),
    "fft_irfft2": lambda a: jnp.fft.irfft2(a),
    "fft_fftn": lambda a: jnp.fft.fftn(a),
    "fft_ifftn": lambda a: jnp.fft.ifftn(a),
    "fft_fftshift": jnp.fft.fftshift,
    "fft_ifftshift": jnp.fft.ifftshift,
    # linalg family (torch.linalg.*)
    "linalg_inv": jnp.linalg.inv,
    "linalg_pinv": jnp.linalg.pinv,
    "linalg_det": jnp.linalg.det,
    "linalg_slogdet": jnp.linalg.slogdet,
    "linalg_cholesky": jnp.linalg.cholesky,
    "linalg_qr": jnp.linalg.qr,
    "linalg_svd": lambda a, full_matrices=True: jnp.linalg.svd(a, full_matrices=full_matrices),
    "linalg_svdvals": lambda a: jnp.linalg.svd(a, compute_uv=False),
    "linalg_eigh": jnp.linalg.eigh,
    "linalg_eigvalsh": jnp.linalg.eigvalsh,
    "linalg_solve": jnp.linalg.solve,
    "linalg_lstsq": lambda a, b: jnp.linalg.lstsq(a, b)[0],
    "linalg_matrix_rank": jnp.linalg.matrix_rank,
    "linalg_matrix_power": jnp.linalg.matrix_power,
    "linalg_norm": jnp.linalg.norm,
    "linalg_cross": jnp.cross,
    "linalg_tensorsolve": jnp.linalg.tensorsolve,
    "linalg_multi_dot": lambda *mats: jnp.linalg.multi_dot(mats),
    "cholesky_solve": lambda b, L: jax.scipy.linalg.cho_solve((L, True), b),
    "triangular_solve": lambda b, A, upper=True: jax.scipy.linalg.solve_triangular(A, b, lower=not upper),
    # special functions (torch.special.*)
    "special_i0": jax.scipy.special.i0,
    "special_i1": jax.scipy.special.i1,
    "special_i0e": jax.scipy.special.i0e,
    "special_i1e": jax.scipy.special.i1e,
    "special_betainc": jax.scipy.special.betainc,
    "special_gammainc": jax.scipy.special.gammainc,
    "special_gammaincc": jax.scipy.special.gammaincc,
    "special_zeta": jax.scipy.special.zeta,
    "special_ndtr": jax.scipy.special.ndtr,
    "special_ndtri": jax.scipy.special.ndtri,
    "special_entr": jax.scipy.special.entr,
    "special_expit": jax.scipy.special.expit,
    "special_log_ndtr": jax.scipy.special.log_ndtr,
    "special_logsumexp": jax.scipy.special.logsumexp,
    "polygamma": lambda n, a: jax.scipy.special.polygamma(n, a),
    "sinc": jnp.sinc,
    # long-tail tensor ops
    "trace": jnp.trace,
    "flipud": jnp.flipud,
    "fliplr": jnp.fliplr,
    "rot90": lambda a, k=1, dims=(0, 1): jnp.rot90(a, k=k, axes=tuple(dims)),
    "unwrap": jnp.unwrap,
    "cross": lambda a, b, dim=-1: jnp.cross(a, b, axis=dim),
    "renorm": lambda a, p, dim, maxnorm: a * jnp.minimum(
        1.0, maxnorm / jnp.maximum(jnp.linalg.norm(a, ord=p, axis=tuple(
            i for i in range(a.ndim) if i != dim), keepdims=True), 1e-12)),
    "logcumsumexp": lambda a, dim: jax.lax.cumlogsumexp(a, axis=dim),
    "cummin": lambda a, dim: jax.lax.cummin(a, axis=dim),
    "polyval": lambda coeffs, x: jnp.polyval(coeffs, x),
    "lerp": lambda a, b, w: a + w * (b - a),
    "addcmul": lambda a, t1, t2, value=1.0: a + value * t1 * t2,
    "addcdiv": lambda a, t1, t2, value=1.0: a + value * t1 / t2,
    "cov": lambda a: jnp.cov(a),
    "corrcoef": lambda a: jnp.corrcoef(a),
    "vander": lambda x, N=None: jnp.vander(x, N),
}

_CATALOG_NONDIFF: dict[str, Callable] = {
    "searchsorted": lambda sorted_seq, values, right=False: jnp.searchsorted(
        sorted_seq, values, side="right" if right else "left"),
    "bucketize": lambda values, boundaries, right=False: jnp.searchsorted(
        boundaries, values, side="right" if right else "left"),
    # torch.bincount's output length depends on max(a) — a dynamic shape XLA
    # cannot express; intentionally NOT registered (like nonzero/unique)
    "histc": lambda a, bins=100, min=0.0, max=0.0: jnp.histogram(
        a, bins=bins, range=(min, max) if (min or max) else None)[0],
    "isclose": jnp.isclose,
    "allclose": jnp.allclose,
    "equal": jnp.array_equal,
    "isin": jnp.isin,
    "isreal": jnp.isreal,
    "tril_indices": lambda row, col, offset=0: jnp.stack(jnp.tril_indices(row, offset, col)),
    "triu_indices": lambda row, col, offset=0: jnp.stack(jnp.triu_indices(row, offset, col)),
    "argwhere_size": lambda a, size: jnp.argwhere(a, size=size),  # static-size variant
    "float_power_int": lambda a, b: jnp.float_power(a, b),
}


def register_catalog() -> int:
    for name, fn in _CATALOG_DIFF.items():
        if f"auto.{name}" not in _auto_symbols:
            register_auto_op(name, fn, differentiable=True)
    for name, fn in _CATALOG_NONDIFF.items():
        if f"auto.{name}" not in _auto_symbols:
            register_auto_op(name, fn, differentiable=False)
    return len(_auto_symbols)


register_catalog()
