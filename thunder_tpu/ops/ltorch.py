"""Torch-like operation namespace: the user-facing symbol layer.

Counterpart of reference thunder/torch/__init__.py:153 (~345 @torchsymbol
definitions). Each op here is a composite Symbol whose meta decomposes into
clang helpers → prims, giving the hierarchical bsym IR that executors claim at
whatever level they support (Pallas claims `sdpa`/`cross_entropy`/`rms_norm`
whole; XLA fusion consumes the flattened prims). Tensor methods on TensorProxy
resolve here through the method registry (reference routes via langctx,
thunder/core/langctxs.py)."""
from __future__ import annotations

import builtins
import math
from numbers import Number
from typing import Optional, Sequence

from ..core import dtypes, prims
from ..core.baseutils import canonicalize_dim, check
from ..core.proxies import NumberProxy, TensorProxy, pyval, register_method
from ..core.symbol import OpTags, Symbol
from . import clang

_torch_symbols: dict[str, Symbol] = {}


def torchsymbol(*, name: str, method_names: Sequence[str] = (), id: str | None = None, tags=()):
    """Create a composite Symbol and register tensor methods for it."""

    def decorator(meta):
        sym = Symbol(name, meta, id=id or f"torch.{name}", module="ltorch", tags=tags)
        _torch_symbols[sym.id] = sym
        for m in method_names:
            register_method(m, sym)
        return sym

    return decorator


def get_symbol(id: str) -> Symbol:
    return _torch_symbols[id]


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------


@torchsymbol(name="add", method_names=("add",))
def add(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.add(a, b)


@torchsymbol(name="sub", method_names=("sub",))
def sub(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.sub(a, b)


@torchsymbol(name="mul", method_names=("mul",))
def mul(a, b):
    return clang.mul(a, b)


@torchsymbol(name="div", method_names=("div", "true_divide"))
def div(a, b):
    return clang.true_divide(a, b)


@torchsymbol(name="floor_divide", method_names=("floor_divide",))
def floor_divide(a, b):
    return clang.floor_divide(a, b)


@torchsymbol(name="pow", method_names=("pow",))
def pow(a, b):
    return clang.pow_(a, b)


@torchsymbol(name="remainder", method_names=("remainder",))
def remainder(a, b):
    return clang.remainder(a, b)


@torchsymbol(name="fmod", method_names=("fmod",))
def fmod(a, b):
    return clang.fmod(a, b)


@torchsymbol(name="maximum", method_names=("maximum",))
def maximum(a, b):
    return clang.maximum(a, b)


@torchsymbol(name="minimum", method_names=("minimum",))
def minimum(a, b):
    return clang.minimum(a, b)


@torchsymbol(name="atan2", method_names=("atan2",))
def atan2(a, b):
    return clang.atan2(a, b)


@torchsymbol(name="eq", method_names=("eq",))
def eq(a, b):
    return clang.eq(a, b)


@torchsymbol(name="ne", method_names=("ne",))
def ne(a, b):
    return clang.ne(a, b)


@torchsymbol(name="lt", method_names=("lt",))
def lt(a, b):
    return clang.lt(a, b)


@torchsymbol(name="le", method_names=("le",))
def le(a, b):
    return clang.le(a, b)


@torchsymbol(name="gt", method_names=("gt",))
def gt(a, b):
    return clang.gt(a, b)


@torchsymbol(name="ge", method_names=("ge",))
def ge(a, b):
    return clang.ge(a, b)


@torchsymbol(name="bitwise_and", method_names=("bitwise_and",))
def bitwise_and(a, b):
    return clang.bitwise_and(a, b)


@torchsymbol(name="bitwise_or", method_names=("bitwise_or",))
def bitwise_or(a, b):
    return clang.bitwise_or(a, b)


@torchsymbol(name="bitwise_xor", method_names=("bitwise_xor",))
def bitwise_xor(a, b):
    return clang.bitwise_xor(a, b)


@torchsymbol(name="logical_and", method_names=("logical_and",))
def logical_and(a, b):
    return clang.logical_and(a, b)


@torchsymbol(name="logical_or", method_names=("logical_or",))
def logical_or(a, b):
    return clang.logical_or(a, b)


# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------


def _unary(name, prim, method_names=None, int_to_float=False):
    def meta(a):
        if int_to_float and isinstance(a, TensorProxy) and not a.dtype.is_inexact:
            a = clang.maybe_convert_to_dtype(a, dtypes.float32)
        return prim(a)

    meta.__name__ = name
    sym = Symbol(name, meta, id=f"torch.{name}", module="ltorch")
    _torch_symbols[sym.id] = sym
    for m in method_names or (name,):
        register_method(m, sym)
    return sym


abs = _unary("abs", prims.abs)
neg = _unary("neg", prims.neg)
exp = _unary("exp", prims.exp, int_to_float=True)
exp2 = _unary("exp2", prims.exp2, int_to_float=True)
expm1 = _unary("expm1", prims.expm1, int_to_float=True)
log = _unary("log", prims.log, int_to_float=True)
log1p = _unary("log1p", prims.log1p, int_to_float=True)
log2 = _unary("log2", prims.log2, int_to_float=True)
sqrt = _unary("sqrt", prims.sqrt, int_to_float=True)
rsqrt = _unary("rsqrt", prims.rsqrt, int_to_float=True)
sin = _unary("sin", prims.sin, int_to_float=True)
cos = _unary("cos", prims.cos, int_to_float=True)
tan = _unary("tan", prims.tan, int_to_float=True)
tanh = _unary("tanh", prims.tanh, int_to_float=True)
asin = _unary("asin", prims.asin, int_to_float=True)
acos = _unary("acos", prims.acos, int_to_float=True)
atan = _unary("atan", prims.atan, int_to_float=True)
sinh = _unary("sinh", prims.sinh, int_to_float=True)
cosh = _unary("cosh", prims.cosh, int_to_float=True)
erf = _unary("erf", prims.erf, int_to_float=True)
erfc = _unary("erfc", prims.erfc, int_to_float=True)
floor = _unary("floor", prims.floor)
ceil = _unary("ceil", prims.ceil)
round = _unary("round", prims.round)
trunc = _unary("trunc", prims.trunc)
sign = _unary("sign", prims.sign)
isfinite = _unary("isfinite", prims.isfinite)
isnan = _unary("isnan", prims.isnan)
isinf = _unary("isinf", prims.isinf)
reciprocal = _unary("reciprocal", prims.reciprocal, int_to_float=True)
logical_not = _unary("logical_not", prims.logical_not)
bitwise_not = _unary("bitwise_not", prims.bitwise_not)


@torchsymbol(name="sigmoid", method_names=("sigmoid",))
def sigmoid(a):
    if not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    return clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(a))))


@torchsymbol(name="relu", method_names=("relu",))
def relu(a):
    return clang.maximum(a, 0)


@torchsymbol(name="relu6")
def relu6(a):
    return clang.minimum(clang.maximum(a, 0), 6)


@torchsymbol(name="leaky_relu")
def leaky_relu(a, negative_slope=0.01):
    return clang.where(clang.gt(a, 0), a, clang.mul(a, negative_slope))


@torchsymbol(name="gelu", id="torch.gelu")
def gelu(a, approximate: str = "none"):
    if approximate == "tanh":
        inner = clang.mul(
            math.sqrt(2.0 / math.pi), clang.add(a, clang.mul(0.044715, clang.mul(a, clang.mul(a, a))))
        )
        return clang.mul(clang.mul(0.5, a), clang.add(1.0, prims.tanh(inner)))
    return clang.mul(clang.mul(0.5, a), clang.add(1.0, prims.erf(clang.mul(a, 1.0 / math.sqrt(2.0)))))


@torchsymbol(name="silu")
def silu(a):
    return clang.mul(a, clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(a)))))


@torchsymbol(name="softplus")
def softplus(a, beta=1.0, threshold=20.0):
    scaled = clang.mul(a, beta)
    sp = clang.true_divide(prims.log1p(prims.exp(scaled)), beta)
    return clang.where(clang.gt(scaled, threshold), a, sp)


@torchsymbol(name="mish")
def mish(a):
    return clang.mul(a, prims.tanh(prims.log1p(prims.exp(a))))


@torchsymbol(name="clamp", method_names=("clamp", "clip"))
def clamp(a, min=None, max=None):
    if min is not None:
        a = clang.maximum(a, min)
    if max is not None:
        a = clang.minimum(a, max)
    return a


@torchsymbol(name="masked_fill", method_names=("masked_fill",))
def masked_fill(a, mask, value):
    return clang.where(mask, value, a)


@torchsymbol(name="where")
def where(pred, a, b):
    return clang.where(pred, a, b)


@torchsymbol(name="tril", method_names=("tril",))
def tril(a, diagonal=0):
    rows, cols = a.shape[-2], a.shape[-1]
    r = clang.unsqueeze(prims.iota(rows, dtype=dtypes.int32, device=a.device), 1)
    c = clang.unsqueeze(prims.iota(cols, dtype=dtypes.int32, device=a.device), 0)
    mask = clang.ge(clang.sub(clang.add(r, diagonal), c), 0)
    return clang.where(mask, a, clang.full_like(a, 0))


@torchsymbol(name="triu", method_names=("triu",))
def triu(a, diagonal=0):
    rows, cols = a.shape[-2], a.shape[-1]
    r = clang.unsqueeze(prims.iota(rows, dtype=dtypes.int32, device=a.device), 1)
    c = clang.unsqueeze(prims.iota(cols, dtype=dtypes.int32, device=a.device), 0)
    mask = clang.ge(clang.sub(c, clang.add(r, diagonal)), 0)
    return clang.where(mask, a, clang.full_like(a, 0))


# ---------------------------------------------------------------------------
# dtype/device conversion
# ---------------------------------------------------------------------------


@torchsymbol(name="to", method_names=("to",))
def to(a, dtype_or_device=None, *, dtype=None, device=None):
    from ..core.devices import Device

    if isinstance(dtype_or_device, (dtypes.dtype,)) or dtype_or_device in (float, int, bool):
        dtype = dtype_or_device
    elif dtype_or_device is not None:
        device = dtype_or_device
    out = a
    if dtype is not None and dtypes.to_dtype(dtype) != a.dtype:
        out = prims.convert_element_type(out, dtypes.to_dtype(dtype))
    if device is not None:
        out = prims.device_put(out, device)
    return out


@torchsymbol(name="type_as", method_names=("type_as",))
def type_as(a, b):
    return prims.convert_element_type(a, b.dtype) if a.dtype != b.dtype else a


for _n, _d in (("float", dtypes.float32), ("double", dtypes.float64), ("half", dtypes.float16),
               ("bfloat16", dtypes.bfloat16), ("long", dtypes.int64), ("int", dtypes.int32),
               ("bool", dtypes.bool8)):
    def _mk(dt):
        def meta(a):
            return prims.convert_element_type(a, dt) if a.dtype != dt else a
        return meta
    _s = Symbol(_n, _mk(_d), id=f"torch.{_n}", module="ltorch")
    _torch_symbols[_s.id] = _s
    register_method(_n, _s)


@torchsymbol(name="detach", method_names=("detach",))
def detach(a):
    return prims.stop_gradient(a)


@torchsymbol(name="contiguous", method_names=("contiguous",))
def contiguous(a):
    return a


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------


@torchsymbol(name="full")
def full(shape, fill_value, *, device=None, dtype=None):
    return clang.full(shape, pyval(fill_value), device=device, dtype=dtype)


@torchsymbol(name="zeros")
def zeros(*shape, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.full(shape, 0.0 if dtype is None else 0, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="ones")
def ones(*shape, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.full(shape, 1.0 if dtype is None else 1, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="zeros_like")
def zeros_like(a, *, device=None, dtype=None):
    return clang.full_like(a, 0, device=device, dtype=dtype)


@torchsymbol(name="ones_like")
def ones_like(a, *, device=None, dtype=None):
    return clang.full_like(a, 1, device=device, dtype=dtype)


@torchsymbol(name="full_like")
def full_like(a, fill_value, *, device=None, dtype=None):
    return clang.full_like(a, pyval(fill_value), device=device, dtype=dtype)


@torchsymbol(name="arange")
def arange(start, end=None, step=1, *, device=None, dtype=None):
    return clang.arange(start, end, step, device=device, dtype=dtype)


@torchsymbol(name="linspace")
def linspace(start, end, steps, *, device=None, dtype=None):
    dtype = dtypes.to_dtype(dtype) if dtype else dtypes.float32
    i = prims.iota(steps, dtype=dtypes.float32, device=device)
    step = (pyval(end) - pyval(start)) / builtins.max(1, pyval(steps) - 1)
    return clang.maybe_convert_to_dtype(clang.add(clang.mul(i, step), pyval(start)), dtype)


@torchsymbol(name="one_hot")
def one_hot(a, num_classes):
    c = prims.iota(num_classes, dtype=dtypes.int64 if a.dtype.is_int else a.dtype, device=a.device)
    expanded = clang.unsqueeze(a, -1)
    return clang.maybe_convert_to_dtype(clang.eq(expanded, clang.expand_to(c, expanded.shape[:-1] + (num_classes,))), dtypes.int64)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------


@torchsymbol(name="reshape", method_names=("reshape", "view"))
def reshape(a, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.reshape(a, shape)


@torchsymbol(name="permute", method_names=("permute",))
def permute(a, *dims):
    if len(dims) == 1 and isinstance(dims[0], (tuple, list)):
        dims = tuple(dims[0])
    return clang.permute(a, dims)


@torchsymbol(name="transpose", method_names=("transpose", "swapaxes"))
def transpose(a, dim0, dim1):
    return clang.transpose(a, pyval(dim0), pyval(dim1))


@torchsymbol(name="matrix_transpose", method_names=("matrix_transpose",))
def matrix_transpose(a):
    return clang.matrix_transpose(a)


@torchsymbol(name="t", method_names=("t",))
def t(a):
    check(a.ndim <= 2, lambda: ".t() on >2D tensor")
    return clang.matrix_transpose(a) if a.ndim == 2 else a


@torchsymbol(name="unsqueeze", method_names=("unsqueeze",))
def unsqueeze(a, dim):
    return clang.unsqueeze(a, pyval(dim))


@torchsymbol(name="squeeze", method_names=("squeeze",))
def squeeze(a, dim=None):
    return clang.squeeze(a, dim)


@torchsymbol(name="flatten", method_names=("flatten",))
def flatten(a, start_dim=0, end_dim=-1):
    return clang.flatten(a, pyval(start_dim), pyval(end_dim))


@torchsymbol(name="expand", method_names=("expand",))
def expand(a, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.expand(a, shape)


@torchsymbol(name="cat")
def cat(tensors, dim=0):
    return clang.cat(list(tensors), dim)


@torchsymbol(name="stack")
def stack(tensors, dim=0):
    return clang.stack(list(tensors), dim)


@torchsymbol(name="split", method_names=("split",))
def split(a, split_size_or_sections, dim=0):
    return clang.split(a, split_size_or_sections, pyval(dim))


@torchsymbol(name="chunk", method_names=("chunk",))
def chunk(a, chunks, dim=0):
    return clang.chunk(a, pyval(chunks), pyval(dim))


@torchsymbol(name="flip", method_names=("flip",))
def flip(a, dims):
    return clang.flip(a, dims)


@torchsymbol(name="movedim", method_names=("movedim",))
def movedim(a, source, destination):
    return clang.movedim(a, source, destination)


@torchsymbol(name="repeat", method_names=("repeat",))
def repeat(a, *sizes):
    if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
        sizes = tuple(sizes[0])
    out = a
    # prepend dims
    while out.ndim < len(sizes):
        out = clang.unsqueeze(out, 0)
    tiles = []
    for i, s in enumerate(sizes):
        if s > 1:
            out = clang.cat([out] * s, i)
    return out


@torchsymbol(name="getitem", method_names=("getitem",))
def getitem(a, key):
    return clang.getitem(a, key)


@torchsymbol(name="index_select", method_names=("index_select",))
def index_select(a, dim, index):
    return clang.take(a, index, pyval(dim))


@torchsymbol(name="gather", method_names=("gather",))
def gather(a, dim, index):
    return clang.take_along_axis(a, index, pyval(dim))


@torchsymbol(name="take_along_dim", method_names=("take_along_dim",))
def take_along_dim(a, indices, dim):
    return clang.take_along_axis(a, indices, pyval(dim))


@torchsymbol(name="index_add", method_names=("index_add",))
def index_add(a, dim, index, source):
    return clang.index_add(a, index, source, pyval(dim))


@torchsymbol(name="scatter_add", method_names=("scatter_add",))
def scatter_add(a, dim, index, src):
    return clang.scatter_add(a, index, src, pyval(dim))


@torchsymbol(name="pad", id="torch.nn.functional.pad")
def pad(a, pad_widths, mode="constant", value=0.0):
    """torch.nn.functional.pad with the (last-dim-first) flat pad list."""
    check(mode == "constant", lambda: f"pad mode {mode} unsupported")
    cfg = [(0, 0, 0)] * a.ndim
    pairs = [(pyval(pad_widths[i]), pyval(pad_widths[i + 1])) for i in range(0, len(pad_widths), 2)]
    for i, (lo, hi) in enumerate(pairs):
        cfg[a.ndim - 1 - i] = (lo, hi, 0)
    return clang.pad(a, value, cfg)


@torchsymbol(name="roll", method_names=("roll",))
def roll(a, shifts, dims=None):
    if dims is None:
        flat = clang.reshape(a, (a.numel,))
        out = roll_1d(flat, pyval(shifts))
        return clang.reshape(out, a.shape)
    shifts = (shifts,) if isinstance(shifts, int) else shifts
    dims = (dims,) if isinstance(dims, int) else dims
    out = a
    for s, d in zip(shifts, dims):
        d = canonicalize_dim(out.ndim, d)
        n = out.shape[d]
        s = pyval(s) % builtins.max(1, n)
        if s == 0:
            continue
        left = clang.slice_in_dim(out, n - s, n, d)
        right = clang.slice_in_dim(out, 0, n - s, d)
        out = clang.cat([left, right], d)
    return out


def roll_1d(a, shift):
    n = a.shape[0]
    shift = shift % builtins.max(1, n)
    if shift == 0:
        return a
    return clang.cat([clang.slice_in_dim(a, n - shift, n, 0), clang.slice_in_dim(a, 0, n - shift, 0)], 0)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


@torchsymbol(name="sum", method_names=("sum",))
def sum(a, dim=None, keepdim=False, *, dtype=None):
    return clang.sum_(a, dim, keepdim, dtype=dtype)


@torchsymbol(name="mean", method_names=("mean",))
def mean(a, dim=None, keepdim=False, *, dtype=None):
    return clang.mean(a, dim, keepdim, dtype=dtype)


@torchsymbol(name="var", method_names=("var",))
def var(a, dim=None, keepdim=False, *, correction=1):
    return clang.var(a, dim, keepdim, correction=correction)


@torchsymbol(name="std", method_names=("std",))
def std(a, dim=None, keepdim=False, *, correction=1):
    return prims.sqrt(clang.var(a, dim, keepdim, correction=correction))


@torchsymbol(name="var_mean")
def var_mean(a, dim=None, keepdim=False, *, correction=1):
    return clang.var_mean(a, dim, keepdim, correction=correction)


@torchsymbol(name="amax", method_names=("amax",))
def amax(a, dim=None, keepdim=False):
    return clang.amax(a, dim, keepdim)


@torchsymbol(name="amin", method_names=("amin",))
def amin(a, dim=None, keepdim=False):
    return clang.amin(a, dim, keepdim)


@torchsymbol(name="max", method_names=("max",))
def max(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amax(a, None, False)
    values = clang.amax(a, dim, keepdim)
    indices = clang.argmax(a, dim, keepdim)
    return values, indices


@torchsymbol(name="min", method_names=("min",))
def min(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amin(a, None, False)
    values = clang.amin(a, dim, keepdim)
    indices = clang.argmin(a, dim, keepdim)
    return values, indices


@torchsymbol(name="argmax", method_names=("argmax",))
def argmax(a, dim=None, keepdim=False):
    return clang.argmax(a, dim, keepdim)


@torchsymbol(name="argmin", method_names=("argmin",))
def argmin(a, dim=None, keepdim=False):
    return clang.argmin(a, dim, keepdim)


@torchsymbol(name="prod", method_names=("prod",))
def prod(a, dim=None, keepdim=False):
    return clang.prod(a, dim, keepdim)


@torchsymbol(name="any", method_names=("any",))
def any(a, dim=None, keepdim=False):
    return clang.any_(a, dim, keepdim)


@torchsymbol(name="all", method_names=("all",))
def all(a, dim=None, keepdim=False):
    return clang.all_(a, dim, keepdim)


@torchsymbol(name="cumsum", method_names=("cumsum",))
def cumsum(a, dim):
    return clang.cumsum(a, pyval(dim))


@torchsymbol(name="topk", method_names=("topk",))
def topk(a, k, dim=-1):
    return prims.topk(a, pyval(k), pyval(dim))


@torchsymbol(name="argsort", method_names=("argsort",))
def argsort(a, dim=-1, descending=False):
    return prims.argsort(a, canonicalize_dim(a.ndim, pyval(dim)), descending)


@torchsymbol(name="sort", method_names=("sort",))
def sort(a, dim=-1, descending=False):
    d = canonicalize_dim(a.ndim, pyval(dim))
    return prims.sort(a, d, descending), prims.argsort(a, d, descending)


@torchsymbol(name="softmax", method_names=("softmax",), id="torch.softmax")
def softmax(a, dim=-1, *, dtype=None):
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    elif not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.amax(a, dim, keepdim=True)
    e = prims.exp(clang.sub(a, m))
    return clang.true_divide(e, clang.sum_(e, dim, keepdim=True))


@torchsymbol(name="log_softmax", method_names=("log_softmax",), id="torch.log_softmax")
def log_softmax(a, dim=-1, *, dtype=None):
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    elif not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.amax(a, dim, keepdim=True)
    shifted = clang.sub(a, m)
    lse = prims.log(clang.sum_(prims.exp(shifted), dim, keepdim=True))
    return clang.sub(shifted, lse)


# ---------------------------------------------------------------------------
# linear algebra & NN ops
# ---------------------------------------------------------------------------


@torchsymbol(name="matmul", method_names=("matmul", "mm", "bmm"))
def matmul(a, b):
    return prims.matmul(a, b)


@torchsymbol(name="einsum_bmm", id="torch.einsum_bmm")
def einsum_bmm(a, b):
    return prims.matmul(a, b)


@torchsymbol(name="linear", id="torch.nn.functional.linear")
def linear(a, w, bias=None):
    out = prims.linear(a, w, bias)
    if bias is not None:
        out = clang.add(out, bias)
    return out


@torchsymbol(name="embedding", id="torch.nn.functional.embedding")
def embedding(indices, weight):
    return prims.embedding(indices, weight)


@torchsymbol(name="conv2d", id="torch.nn.functional.conv2d")
def conv2d(a, weight, bias=None, stride=(1, 1), padding=(0, 0), dilation=(1, 1), groups=1):
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    out = prims.convolution(a, weight, None, stride, padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0], 1, 1)))
    return out


@torchsymbol(name="conv1d", id="torch.nn.functional.conv1d")
def conv1d(a, weight, bias=None, stride=(1,), padding=(0,), dilation=(1,), groups=1):
    stride = (stride,) if isinstance(stride, int) else tuple(stride)
    padding = (padding,) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) if isinstance(dilation, int) else tuple(dilation)
    out = prims.convolution(a, weight, None, stride, padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0], 1)))
    return out


@torchsymbol(name="layer_norm", id="torch.nn.functional.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps=1e-5):
    ndims = len(normalized_shape)
    dims = tuple(range(a.ndim - ndims, a.ndim))
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.mean(compute, dims, keepdim=True)
    centered = clang.sub(compute, m)
    v = clang.mean(clang.mul(centered, centered), dims, keepdim=True)
    out = clang.mul(centered, prims.rsqrt(clang.add(v, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    if weight is not None:
        out = clang.mul(out, weight)
    if bias is not None:
        out = clang.add(out, bias)
    return out


@torchsymbol(name="rms_norm", id="torch.nn.functional.rms_norm")
def rms_norm(a, normalized_shape, weight=None, eps=1e-6):
    ndims = len(normalized_shape)
    dims = tuple(range(a.ndim - ndims, a.ndim))
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    ms = clang.mean(clang.mul(compute, compute), dims, keepdim=True)
    out = clang.mul(compute, prims.rsqrt(clang.add(ms, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    if weight is not None:
        out = clang.mul(out, weight)
    return out


@torchsymbol(name="sdpa", id="torch.nn.functional.scaled_dot_product_attention")
def sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None):
    """Scaled dot-product attention (composite; Pallas flash-attention executor
    claims this symbol whole — reference analog: sdpaex/cudnnex claiming,
    thunder/executors/sdpaex.py:1)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kt = clang.matrix_transpose(k)
    scores = clang.mul(prims.matmul(q, kt), scale)
    if is_causal:
        Lq, Lk = q.shape[-2], k.shape[-2]
        r = clang.unsqueeze(prims.iota(Lq, dtype=dtypes.int32, device=q.device), 1)
        c = clang.unsqueeze(prims.iota(Lk, dtype=dtypes.int32, device=q.device), 0)
        causal = clang.ge(clang.add(r, Lk - Lq), c)
        scores = clang.where(causal, scores, float("-inf"))
    if attn_mask is not None:
        if attn_mask.dtype.is_bool:
            scores = clang.where(attn_mask, scores, float("-inf"))
        else:
            scores = clang.add(scores, attn_mask)
    probs = softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, v.dtype)
    return prims.matmul(probs, v)


@torchsymbol(name="cross_entropy", id="torch.nn.functional.cross_entropy")
def cross_entropy(logits, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    """Composite cross-entropy over class dim 1 / last for 2D (logits (N, C)).

    Pallas fused cross-entropy claims this whole (reference analog: apex/triton
    cross-entropy executors, thunder/executors/triton_crossentropy_impl.py)."""
    check(logits.ndim == 2, lambda: "cross_entropy currently expects (N, C) logits")
    lsm = log_softmax(logits, 1)
    n, c = logits.shape
    tgt = clang.unsqueeze(target, 1)
    picked = clang.squeeze(clang.take_along_axis(lsm, tgt, 1), 1)
    nll = prims.neg(picked)
    if label_smoothing > 0.0:
        smooth = prims.neg(clang.mean(lsm, 1))
        nll = clang.add(clang.mul(nll, 1.0 - label_smoothing), clang.mul(smooth, label_smoothing))
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.full_like(nll, 0))
    if reduction == "none":
        return nll
    if reduction == "sum":
        return clang.sum_(nll)
    count = clang.sum_(clang.maybe_convert_to_dtype(valid, nll.dtype))
    return clang.true_divide(clang.sum_(nll), count)


@torchsymbol(name="nll_loss", id="torch.nn.functional.nll_loss")
def nll_loss(log_probs, target, reduction="mean"):
    tgt = clang.unsqueeze(target, 1)
    picked = clang.squeeze(clang.take_along_axis(log_probs, tgt, 1), 1)
    nll = prims.neg(picked)
    if reduction == "none":
        return nll
    if reduction == "sum":
        return clang.sum_(nll)
    return clang.mean(nll)


@torchsymbol(name="mse_loss", id="torch.nn.functional.mse_loss")
def mse_loss(input, target, reduction="mean"):
    d = clang.sub(input, target)
    sq = clang.mul(d, d)
    if reduction == "none":
        return sq
    if reduction == "sum":
        return clang.sum_(sq)
    return clang.mean(sq)


@torchsymbol(name="dropout", id="torch.nn.functional.dropout")
def dropout(a, p=0.5, training=True, *, key=None):
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "dropout in training mode requires an rng key (pass key= or use nn.Module rng plumbing)")
    keep = 1.0 - p
    mask = clang.lt(prims.uniform(a.shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    return clang.mul(clang.where(mask, a, clang.full_like(a, 0)), 1.0 / keep)


@torchsymbol(name="grouped_mm", id="torch.grouped_mm")
def grouped_mm(a, b, group_sizes):
    return prims.grouped_mm(a, b, group_sizes)


@torchsymbol(name="baddbmm", method_names=("baddbmm",))
def baddbmm(input, batch1, batch2, *, beta=1, alpha=1):
    out = prims.matmul(batch1, batch2)
    if pyval(alpha) != 1:
        out = clang.mul(out, alpha)
    if pyval(beta) != 0:
        out = clang.add(out, clang.mul(input, beta) if pyval(beta) != 1 else input)
    return out


@torchsymbol(name="addmm", method_names=("addmm",))
def addmm(input, mat1, mat2, *, beta=1, alpha=1):
    return baddbmm.meta(input, mat1, mat2, beta=beta, alpha=alpha)


@torchsymbol(name="outer", method_names=("outer",))
def outer(a, b):
    return clang.mul(clang.unsqueeze(a, 1), clang.unsqueeze(b, 0))


# normalization helpers used by models ---------------------------------------


@torchsymbol(name="glu", id="torch.nn.functional.glu")
def glu(a, dim=-1):
    x, g = clang.chunk(a, 2, pyval(dim))
    return clang.mul(x, sigmoid.meta(g))


@torchsymbol(name="swiglu", id="thunder_tpu.swiglu")
def swiglu(gate, up):
    return clang.mul(clang.mul(gate, clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(gate))))), up)
