"""Torch-like operation namespace: the user-facing symbol layer.

Counterpart of reference thunder/torch/__init__.py:153 (~345 @torchsymbol
definitions). Each op here is a composite Symbol whose meta decomposes into
clang helpers → prims, giving the hierarchical bsym IR that executors claim at
whatever level they support (Pallas claims `sdpa`/`cross_entropy`/`rms_norm`
whole; XLA fusion consumes the flattened prims). Tensor methods on TensorProxy
resolve here through the method registry (reference routes via langctx,
thunder/core/langctxs.py)."""
from __future__ import annotations

import builtins
import math
from numbers import Number
from typing import Optional, Sequence

from ..core import dtypes, prims
from ..core.baseutils import canonicalize_dim, check
from ..core.proxies import NumberProxy, TensorProxy, pyval, register_method
from ..core.symbol import OpTags, Symbol
from . import clang

_torch_symbols: dict[str, Symbol] = {}


def torchsymbol(*, name: str, method_names: Sequence[str] = (), id: str | None = None, tags=()):
    """Create a composite Symbol and register tensor methods for it."""

    def decorator(meta):
        sym = Symbol(name, meta, id=id or f"torch.{name}", module="ltorch", tags=tags)
        _torch_symbols[sym.id] = sym
        for m in method_names:
            register_method(m, sym)
        return sym

    return decorator


def get_symbol(id: str) -> Symbol:
    return _torch_symbols[id]


# ---------------------------------------------------------------------------
# elementwise binary
# ---------------------------------------------------------------------------


@torchsymbol(name="add", method_names=("add",))
def add(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.add(a, b)


@torchsymbol(name="sub", method_names=("sub",))
def sub(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        b = clang.mul(b, alpha)
    return clang.sub(a, b)


@torchsymbol(name="mul", method_names=("mul",))
def mul(a, b):
    return clang.mul(a, b)


@torchsymbol(name="div", method_names=("div", "true_divide"))
def div(a, b):
    return clang.true_divide(a, b)


@torchsymbol(name="floor_divide", method_names=("floor_divide",))
def floor_divide(a, b):
    return clang.floor_divide(a, b)


@torchsymbol(name="pow", method_names=("pow",))
def pow(a, b):
    return clang.pow_(a, b)


@torchsymbol(name="remainder", method_names=("remainder",))
def remainder(a, b):
    return clang.remainder(a, b)


@torchsymbol(name="fmod", method_names=("fmod",))
def fmod(a, b):
    return clang.fmod(a, b)


@torchsymbol(name="maximum", method_names=("maximum",))
def maximum(a, b):
    return clang.maximum(a, b)


@torchsymbol(name="minimum", method_names=("minimum",))
def minimum(a, b):
    return clang.minimum(a, b)


@torchsymbol(name="atan2", method_names=("atan2",))
def atan2(a, b):
    return clang.atan2(a, b)


@torchsymbol(name="eq", method_names=("eq",))
def eq(a, b):
    return clang.eq(a, b)


@torchsymbol(name="ne", method_names=("ne",))
def ne(a, b):
    return clang.ne(a, b)


@torchsymbol(name="lt", method_names=("lt",))
def lt(a, b):
    return clang.lt(a, b)


@torchsymbol(name="le", method_names=("le",))
def le(a, b):
    return clang.le(a, b)


@torchsymbol(name="gt", method_names=("gt",))
def gt(a, b):
    return clang.gt(a, b)


@torchsymbol(name="ge", method_names=("ge",))
def ge(a, b):
    return clang.ge(a, b)


@torchsymbol(name="bitwise_and", method_names=("bitwise_and",))
def bitwise_and(a, b):
    return clang.bitwise_and(a, b)


@torchsymbol(name="bitwise_or", method_names=("bitwise_or",))
def bitwise_or(a, b):
    return clang.bitwise_or(a, b)


@torchsymbol(name="bitwise_xor", method_names=("bitwise_xor",))
def bitwise_xor(a, b):
    return clang.bitwise_xor(a, b)


@torchsymbol(name="logical_and", method_names=("logical_and",))
def logical_and(a, b):
    return clang.logical_and(a, b)


@torchsymbol(name="logical_or", method_names=("logical_or",))
def logical_or(a, b):
    return clang.logical_or(a, b)


# ---------------------------------------------------------------------------
# elementwise unary
# ---------------------------------------------------------------------------


def _unary(name, prim, method_names=None, int_to_float=False):
    def meta(a):
        if int_to_float and isinstance(a, TensorProxy) and not a.dtype.is_inexact:
            a = clang.maybe_convert_to_dtype(a, dtypes.float32)
        return prim(a)

    meta.__name__ = name
    sym = Symbol(name, meta, id=f"torch.{name}", module="ltorch")
    _torch_symbols[sym.id] = sym
    for m in method_names or (name,):
        register_method(m, sym)
    return sym


abs = _unary("abs", prims.abs)
neg = _unary("neg", prims.neg)
exp = _unary("exp", prims.exp, int_to_float=True)
exp2 = _unary("exp2", prims.exp2, int_to_float=True)
expm1 = _unary("expm1", prims.expm1, int_to_float=True)
log = _unary("log", prims.log, int_to_float=True)
log1p = _unary("log1p", prims.log1p, int_to_float=True)
log2 = _unary("log2", prims.log2, int_to_float=True)
sqrt = _unary("sqrt", prims.sqrt, int_to_float=True)
rsqrt = _unary("rsqrt", prims.rsqrt, int_to_float=True)
sin = _unary("sin", prims.sin, int_to_float=True)
cos = _unary("cos", prims.cos, int_to_float=True)
tan = _unary("tan", prims.tan, int_to_float=True)
tanh = _unary("tanh", prims.tanh, int_to_float=True)
asin = _unary("asin", prims.asin, int_to_float=True)
acos = _unary("acos", prims.acos, int_to_float=True)
atan = _unary("atan", prims.atan, int_to_float=True)
sinh = _unary("sinh", prims.sinh, int_to_float=True)
cosh = _unary("cosh", prims.cosh, int_to_float=True)
erf = _unary("erf", prims.erf, int_to_float=True)
erfc = _unary("erfc", prims.erfc, int_to_float=True)
floor = _unary("floor", prims.floor)
ceil = _unary("ceil", prims.ceil)
round = _unary("round", prims.round)
trunc = _unary("trunc", prims.trunc)
sign = _unary("sign", prims.sign)
isfinite = _unary("isfinite", prims.isfinite)
isnan = _unary("isnan", prims.isnan)
isinf = _unary("isinf", prims.isinf)
reciprocal = _unary("reciprocal", prims.reciprocal, int_to_float=True)
logical_not = _unary("logical_not", prims.logical_not)
bitwise_not = _unary("bitwise_not", prims.bitwise_not)


@torchsymbol(name="sigmoid", method_names=("sigmoid",))
def sigmoid(a):
    if not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    return clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(a))))


@torchsymbol(name="relu", method_names=("relu",))
def relu(a):
    return clang.maximum(a, 0)


@torchsymbol(name="relu6")
def relu6(a):
    return clang.minimum(clang.maximum(a, 0), 6)


@torchsymbol(name="leaky_relu")
def leaky_relu(a, negative_slope=0.01):
    return clang.where(clang.gt(a, 0), a, clang.mul(a, negative_slope))


@torchsymbol(name="gelu", id="torch.gelu")
def gelu(a, approximate: str = "none"):
    if approximate == "tanh":
        inner = clang.mul(
            math.sqrt(2.0 / math.pi), clang.add(a, clang.mul(0.044715, clang.mul(a, clang.mul(a, a))))
        )
        return clang.mul(clang.mul(0.5, a), clang.add(1.0, prims.tanh(inner)))
    return clang.mul(clang.mul(0.5, a), clang.add(1.0, prims.erf(clang.mul(a, 1.0 / math.sqrt(2.0)))))


@torchsymbol(name="silu")
def silu(a):
    return clang.mul(a, clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(a)))))


@torchsymbol(name="softplus")
def softplus(a, beta=1.0, threshold=20.0):
    scaled = clang.mul(a, beta)
    sp = clang.true_divide(prims.log1p(prims.exp(scaled)), beta)
    return clang.where(clang.gt(scaled, threshold), a, sp)


@torchsymbol(name="mish")
def mish(a):
    return clang.mul(a, prims.tanh(prims.log1p(prims.exp(a))))


@torchsymbol(name="clamp", method_names=("clamp", "clip"))
def clamp(a, min=None, max=None):
    check(min is not None or max is not None,
          lambda: "clamp: at least one of min or max must not be None")
    if min is not None:
        a = clang.maximum(a, min)
    if max is not None:
        a = clang.minimum(a, max)
    return a


@torchsymbol(name="masked_fill", method_names=("masked_fill",))
def masked_fill(a, mask, value):
    mdt = dtypes.to_dtype(getattr(mask, "dtype", None))  # proxy OR concrete dtype
    check(mdt is None or mdt.is_bool,
          lambda: f"masked_fill expects a bool mask, got {mdt.name}")
    return clang.where(mask, value, a)


@torchsymbol(name="where")
def where(pred, a, b):
    return clang.where(pred, a, b)


@torchsymbol(name="tril", method_names=("tril",))
def tril(a, diagonal=0):
    check(a.ndim >= 2, lambda: f"tril expects a tensor with at least 2 dims, got {a.ndim}")
    rows, cols = a.shape[-2], a.shape[-1]
    r = clang.unsqueeze(prims.iota(rows, dtype=dtypes.int32, device=a.device), 1)
    c = clang.unsqueeze(prims.iota(cols, dtype=dtypes.int32, device=a.device), 0)
    mask = clang.ge(clang.sub(clang.add(r, diagonal), c), 0)
    return clang.where(mask, a, clang.full_like(a, 0))


@torchsymbol(name="triu", method_names=("triu",))
def triu(a, diagonal=0):
    check(a.ndim >= 2, lambda: f"triu expects a tensor with at least 2 dims, got {a.ndim}")
    rows, cols = a.shape[-2], a.shape[-1]
    r = clang.unsqueeze(prims.iota(rows, dtype=dtypes.int32, device=a.device), 1)
    c = clang.unsqueeze(prims.iota(cols, dtype=dtypes.int32, device=a.device), 0)
    mask = clang.ge(clang.sub(c, clang.add(r, diagonal)), 0)
    return clang.where(mask, a, clang.full_like(a, 0))


# ---------------------------------------------------------------------------
# dtype/device conversion
# ---------------------------------------------------------------------------


@torchsymbol(name="to", method_names=("to",))
def to(a, dtype_or_device=None, *, dtype=None, device=None):
    from ..core.devices import Device

    if isinstance(dtype_or_device, (dtypes.dtype,)) or dtype_or_device in (float, int, bool):
        dtype = dtype_or_device
    elif dtype_or_device is not None:
        device = dtype_or_device
    out = a
    if dtype is not None and dtypes.to_dtype(dtype) != a.dtype:
        out = prims.convert_element_type(out, dtypes.to_dtype(dtype))
    if device is not None:
        out = prims.device_put(out, device)
    return out


@torchsymbol(name="type_as", method_names=("type_as",))
def type_as(a, b):
    return prims.convert_element_type(a, b.dtype) if a.dtype != b.dtype else a


for _n, _d in (("float", dtypes.float32), ("double", dtypes.float64), ("half", dtypes.float16),
               ("bfloat16", dtypes.bfloat16), ("long", dtypes.int64), ("int", dtypes.int32),
               ("bool", dtypes.bool8)):
    def _mk(dt):
        def meta(a):
            return prims.convert_element_type(a, dt) if a.dtype != dt else a
        return meta
    _s = Symbol(_n, _mk(_d), id=f"torch.{_n}", module="ltorch")
    _torch_symbols[_s.id] = _s
    register_method(_n, _s)


@torchsymbol(name="detach", method_names=("detach",))
def detach(a):
    return prims.stop_gradient(a)


@torchsymbol(name="contiguous", method_names=("contiguous",))
def contiguous(a):
    return a


# ---------------------------------------------------------------------------
# factories
# ---------------------------------------------------------------------------


@torchsymbol(name="full")
def full(shape, fill_value, *, device=None, dtype=None):
    return clang.full(shape, pyval(fill_value), device=device, dtype=dtype)


@torchsymbol(name="zeros")
def zeros(*shape, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.full(shape, 0.0 if dtype is None else 0, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="ones")
def ones(*shape, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.full(shape, 1.0 if dtype is None else 1, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="zeros_like")
def zeros_like(a, *, device=None, dtype=None):
    return clang.full_like(a, 0, device=device, dtype=dtype)


@torchsymbol(name="ones_like")
def ones_like(a, *, device=None, dtype=None):
    return clang.full_like(a, 1, device=device, dtype=dtype)


@torchsymbol(name="full_like")
def full_like(a, fill_value, *, device=None, dtype=None):
    return clang.full_like(a, pyval(fill_value), device=device, dtype=dtype)


@torchsymbol(name="arange")
def arange(start, end=None, step=1, *, device=None, dtype=None):
    return clang.arange(start, end, step, device=device, dtype=dtype)


@torchsymbol(name="linspace")
def linspace(start, end, steps, *, device=None, dtype=None):
    dtype = dtypes.to_dtype(dtype) if dtype else dtypes.float32
    i = prims.iota(steps, dtype=dtypes.float32, device=device)
    step = (pyval(end) - pyval(start)) / builtins.max(1, pyval(steps) - 1)
    return clang.maybe_convert_to_dtype(clang.add(clang.mul(i, step), pyval(start)), dtype)


@torchsymbol(name="one_hot")
def one_hot(a, num_classes):
    n = pyval(num_classes)
    if n == -1:
        raise RuntimeError(
            "one_hot: num_classes=-1 (infer from data) needs a data-dependent "
            "output shape XLA cannot express; pass the class count explicitly")
    if n < 1:
        raise RuntimeError(f"one_hot: num_classes must be positive, got {n}")
    c = prims.iota(num_classes, dtype=dtypes.int64 if a.dtype.is_int else a.dtype, device=a.device)
    expanded = clang.unsqueeze(a, -1)
    return clang.maybe_convert_to_dtype(clang.eq(expanded, clang.expand_to(c, expanded.shape[:-1] + (num_classes,))), dtypes.int64)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------


@torchsymbol(name="reshape", method_names=("reshape", "view"))
def reshape(a, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    check(builtins.sum(1 for d in shape if pyval(d) == -1) <= 1,
          lambda: f"reshape can infer (-1) at most one dimension, got {shape}")
    return clang.reshape(a, shape)


@torchsymbol(name="permute", method_names=("permute",))
def permute(a, *dims):
    if len(dims) == 1 and isinstance(dims[0], (tuple, list)):
        dims = tuple(dims[0])
    return clang.permute(a, dims)


@torchsymbol(name="transpose", method_names=("transpose", "swapaxes"))
def transpose(a, dim0, dim1):
    return clang.transpose(a, pyval(dim0), pyval(dim1))


@torchsymbol(name="matrix_transpose", method_names=("matrix_transpose",))
def matrix_transpose(a):
    return clang.matrix_transpose(a)


@torchsymbol(name="t", method_names=("t",))
def t(a):
    check(a.ndim <= 2, lambda: ".t() on >2D tensor")
    return clang.matrix_transpose(a) if a.ndim == 2 else a


@torchsymbol(name="unsqueeze", method_names=("unsqueeze",))
def unsqueeze(a, dim):
    return clang.unsqueeze(a, pyval(dim))


@torchsymbol(name="squeeze", method_names=("squeeze",))
def squeeze(a, dim=None):
    return clang.squeeze(a, dim)


@torchsymbol(name="flatten", method_names=("flatten",))
def flatten(a, start_dim=0, end_dim=-1):
    sd = canonicalize_dim(a.ndim, pyval(start_dim))
    ed = canonicalize_dim(a.ndim, pyval(end_dim))
    check(sd <= ed, lambda: f"flatten: start_dim {sd} must be <= end_dim {ed}")
    return clang.flatten(a, sd, ed)


@torchsymbol(name="expand", method_names=("expand",))
def expand(a, *shape):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.expand(a, shape)


@torchsymbol(name="cat")
def cat(tensors, dim=0):
    tensors = list(tensors)
    check(len(tensors) > 0, lambda: "cat expects at least one tensor")
    canonicalize_dim(tensors[0].ndim, pyval(dim))  # dim-range check
    return clang.cat(tensors, dim)


@torchsymbol(name="stack")
def stack(tensors, dim=0):
    tensors = list(tensors)
    check(len(tensors) > 0, lambda: "stack expects at least one tensor")
    first = tuple(tensors[0].shape)
    for t in tensors[1:]:
        check(tuple(t.shape) == first,
              lambda: f"stack expects tensors of the same shape, got {first} and {tuple(t.shape)}")
    return clang.stack(tensors, dim)


@torchsymbol(name="split", method_names=("split",))
def split(a, split_size_or_sections, dim=0):
    d = canonicalize_dim(a.ndim, pyval(dim))
    if isinstance(split_size_or_sections, (list, tuple)):
        total = builtins.sum(pyval(x) for x in split_size_or_sections)
        check(total == a.shape[d],
              lambda: f"split sizes {split_size_or_sections} must sum to dim {d} size {a.shape[d]}, got {total}")
    return clang.split(a, split_size_or_sections, d)


@torchsymbol(name="chunk", method_names=("chunk",))
def chunk(a, chunks, dim=0):
    check(pyval(chunks) > 0, lambda: f"chunk expects a positive number of chunks, got {chunks}")
    return clang.chunk(a, pyval(chunks), pyval(dim))


@torchsymbol(name="flip", method_names=("flip",))
def flip(a, dims):
    return clang.flip(a, dims)


@torchsymbol(name="movedim", method_names=("movedim",))
def movedim(a, source, destination):
    return clang.movedim(a, source, destination)


@torchsymbol(name="repeat", method_names=("repeat",))
def repeat(a, *sizes):
    if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)):
        sizes = tuple(sizes[0])
    out = a
    # prepend dims
    while out.ndim < len(sizes):
        out = clang.unsqueeze(out, 0)
    tiles = []
    for i, s in enumerate(sizes):
        if s > 1:
            out = clang.cat([out] * s, i)
    return out


@torchsymbol(name="getitem", method_names=("getitem",))
def getitem(a, key):
    return clang.getitem(a, key)


@torchsymbol(name="index_select", method_names=("index_select",))
def index_select(a, dim, index):
    # lowers to the TAKE prim (hand-written grad rule) — a dedicated
    # INDEX_SELECT prim would duplicate it
    check(getattr(index, "ndim", 1) == 1,
          lambda: f"index_select expects a 1-D index vector, got {index.ndim}-D")
    return clang.take(a, index, canonicalize_dim(a.ndim, pyval(dim)))


@torchsymbol(name="gather", method_names=("gather",))
def gather(a, dim, index):
    return clang.take_along_axis(a, index, pyval(dim))


@torchsymbol(name="take_along_dim", method_names=("take_along_dim",))
def take_along_dim(a, indices, dim):
    check(indices.ndim == a.ndim,
          lambda: f"take_along_dim: indices rank {indices.ndim} must match input rank {a.ndim}")
    return clang.take_along_axis(a, indices, pyval(dim))


@torchsymbol(name="index_add", method_names=("index_add",))
def index_add(a, dim, index, source):
    return clang.index_add(a, index, source, pyval(dim))


@torchsymbol(name="scatter_add", method_names=("scatter_add",))
def scatter_add(a, dim, index, src):
    return clang.scatter_add(a, index, src, pyval(dim))


@torchsymbol(name="pad", id="torch.nn.functional.pad")
def pad(a, pad_widths, mode="constant", value=0.0):
    """torch.nn.functional.pad with the (last-dim-first) flat pad list."""
    check(mode == "constant", lambda: f"pad mode {mode} unsupported")
    check(len(pad_widths) % 2 == 0,
          lambda: f"pad expects an even number of pad values (left/right pairs), got {len(pad_widths)}")
    check(len(pad_widths) // 2 <= a.ndim,
          lambda: f"pad: {len(pad_widths)//2} padded dims exceed input rank {a.ndim}")
    cfg = [(0, 0, 0)] * a.ndim
    pairs = [(pyval(pad_widths[i]), pyval(pad_widths[i + 1])) for i in range(0, len(pad_widths), 2)]
    for i, (lo, hi) in enumerate(pairs):
        cfg[a.ndim - 1 - i] = (lo, hi, 0)
    return clang.pad(a, value, cfg)


@torchsymbol(name="roll", method_names=("roll",))
def roll(a, shifts, dims=None):
    if dims is not None and isinstance(shifts, (tuple, list)):
        dlist = (dims,) if isinstance(dims, int) else dims
        check(len(shifts) == len(dlist),
              lambda: f"roll: shifts {shifts} and dims {dlist} must have the same length")
    if dims is None:
        flat = clang.reshape(a, (a.numel,))
        out = roll_1d(flat, pyval(shifts))
        return clang.reshape(out, a.shape)
    shifts = (shifts,) if isinstance(shifts, int) else shifts
    dims = (dims,) if isinstance(dims, int) else dims
    out = a
    for s, d in zip(shifts, dims):
        d = canonicalize_dim(out.ndim, d)
        n = out.shape[d]
        s = pyval(s) % builtins.max(1, n)
        if s == 0:
            continue
        left = clang.slice_in_dim(out, n - s, n, d)
        right = clang.slice_in_dim(out, 0, n - s, d)
        out = clang.cat([left, right], d)
    return out


def roll_1d(a, shift):
    n = a.shape[0]
    shift = shift % builtins.max(1, n)
    if shift == 0:
        return a
    return clang.cat([clang.slice_in_dim(a, n - shift, n, 0), clang.slice_in_dim(a, 0, n - shift, 0)], 0)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


@torchsymbol(name="sum", method_names=("sum",))
def sum(a, dim=None, keepdim=False, *, dtype=None):
    return clang.sum_(a, dim, keepdim, dtype=dtype)


@torchsymbol(name="mean", method_names=("mean",))
def mean(a, dim=None, keepdim=False, *, dtype=None):
    return clang.mean(a, dim, keepdim, dtype=dtype)


@torchsymbol(name="var", method_names=("var",))
def var(a, dim=None, keepdim=False, *, correction=1):
    return clang.var(a, dim, keepdim, correction=correction)


@torchsymbol(name="std", method_names=("std",))
def std(a, dim=None, keepdim=False, *, correction=1):
    return prims.sqrt(clang.var(a, dim, keepdim, correction=correction))


@torchsymbol(name="var_mean")
def var_mean(a, dim=None, keepdim=False, *, correction=1):
    return clang.var_mean(a, dim, keepdim, correction=correction)


@torchsymbol(name="amax", method_names=("amax",))
def amax(a, dim=None, keepdim=False):
    return clang.amax(a, dim, keepdim)


@torchsymbol(name="amin", method_names=("amin",))
def amin(a, dim=None, keepdim=False):
    return clang.amin(a, dim, keepdim)


@torchsymbol(name="max", method_names=("max",))
def max(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amax(a, None, False)
    values = clang.amax(a, dim, keepdim)
    indices = clang.argmax(a, dim, keepdim)
    return values, indices


@torchsymbol(name="min", method_names=("min",))
def min(a, dim=None, keepdim=False):
    if dim is None:
        return clang.amin(a, None, False)
    values = clang.amin(a, dim, keepdim)
    indices = clang.argmin(a, dim, keepdim)
    return values, indices


@torchsymbol(name="argmax", method_names=("argmax",))
def argmax(a, dim=None, keepdim=False):
    return clang.argmax(a, dim, keepdim)


@torchsymbol(name="argmin", method_names=("argmin",))
def argmin(a, dim=None, keepdim=False):
    return clang.argmin(a, dim, keepdim)


@torchsymbol(name="prod", method_names=("prod",))
def prod(a, dim=None, keepdim=False):
    return clang.prod(a, dim, keepdim)


@torchsymbol(name="any", method_names=("any",))
def any(a, dim=None, keepdim=False):
    return clang.any_(a, dim, keepdim)


@torchsymbol(name="all", method_names=("all",))
def all(a, dim=None, keepdim=False):
    return clang.all_(a, dim, keepdim)


@torchsymbol(name="cumsum", method_names=("cumsum",))
def cumsum(a, dim):
    return clang.cumsum(a, pyval(dim))


@torchsymbol(name="topk", method_names=("topk",))
def topk(a, k, dim=-1):
    return prims.topk(a, pyval(k), pyval(dim))


@torchsymbol(name="argsort", method_names=("argsort",))
def argsort(a, dim=-1, descending=False):
    return prims.argsort(a, canonicalize_dim(a.ndim, pyval(dim)), descending)


@torchsymbol(name="sort", method_names=("sort",))
def sort(a, dim=-1, descending=False):
    d = canonicalize_dim(a.ndim, pyval(dim))
    return prims.sort(a, d, descending), prims.argsort(a, d, descending)


@torchsymbol(name="softmax", method_names=("softmax",), id="torch.softmax")
def softmax(a, dim=-1, *, dtype=None):
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    elif not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.amax(a, dim, keepdim=True)
    e = prims.exp(clang.sub(a, m))
    return clang.true_divide(e, clang.sum_(e, dim, keepdim=True))


@torchsymbol(name="log_softmax", method_names=("log_softmax",), id="torch.log_softmax")
def log_softmax(a, dim=-1, *, dtype=None):
    if dtype is not None:
        a = clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    elif not a.dtype.is_inexact:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.amax(a, dim, keepdim=True)
    shifted = clang.sub(a, m)
    lse = prims.log(clang.sum_(prims.exp(shifted), dim, keepdim=True))
    return clang.sub(shifted, lse)


# ---------------------------------------------------------------------------
# linear algebra & NN ops
# ---------------------------------------------------------------------------


@torchsymbol(name="matmul", method_names=("matmul", "mm", "bmm"))
def matmul(a, b):
    return prims.matmul(a, b)


@torchsymbol(name="einsum_bmm", id="torch.einsum_bmm")
def einsum_bmm(a, b):
    return prims.matmul(a, b)


@torchsymbol(name="linear", id="torch.nn.functional.linear")
def linear(a, w, bias=None):
    out = prims.linear(a, w, bias)
    if bias is not None:
        out = clang.add(out, bias)
    return out


@torchsymbol(name="embedding", id="torch.nn.functional.embedding")
def embedding(indices, weight):
    return prims.embedding(indices, weight)


@torchsymbol(name="conv2d", id="torch.nn.functional.conv2d")
def conv2d(a, weight, bias=None, stride=(1, 1), padding=(0, 0), dilation=(1, 1), groups=1):
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    out = prims.convolution(a, weight, None, stride, padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0], 1, 1)))
    return out


@torchsymbol(name="conv1d", id="torch.nn.functional.conv1d")
def conv1d(a, weight, bias=None, stride=(1,), padding=(0,), dilation=(1,), groups=1):
    stride = (stride,) if isinstance(stride, int) else tuple(stride)
    padding = (padding,) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) if isinstance(dilation, int) else tuple(dilation)
    out = prims.convolution(a, weight, None, stride, padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0], 1)))
    return out


@torchsymbol(name="layer_norm", id="torch.nn.functional.layer_norm")
def layer_norm(a, normalized_shape, weight=None, bias=None, eps=1e-5):
    ndims = len(normalized_shape)
    check(ndims <= a.ndim and tuple(int(d) for d in normalized_shape) == tuple(a.shape[a.ndim - ndims:]),
          lambda: f"layer_norm: normalized_shape {tuple(normalized_shape)} must match the trailing dims of {tuple(a.shape)}")
    dims = tuple(range(a.ndim - ndims, a.ndim))
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.mean(compute, dims, keepdim=True)
    centered = clang.sub(compute, m)
    v = clang.mean(clang.mul(centered, centered), dims, keepdim=True)
    out = clang.mul(centered, prims.rsqrt(clang.add(v, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    if weight is not None:
        out = clang.mul(out, weight)
    if bias is not None:
        out = clang.add(out, bias)
    return out


@torchsymbol(name="rms_norm", id="torch.nn.functional.rms_norm")
def rms_norm(a, normalized_shape, weight=None, eps=1e-6):
    ndims = len(normalized_shape)
    dims = tuple(range(a.ndim - ndims, a.ndim))
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    ms = clang.mean(clang.mul(compute, compute), dims, keepdim=True)
    out = clang.mul(compute, prims.rsqrt(clang.add(ms, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    if weight is not None:
        out = clang.mul(out, weight)
    return out


@torchsymbol(name="rope_sdpa", id="thunder.rope_sdpa")
def rope_sdpa(q, k, v, cos, sin, is_causal=True, scale=None):
    """Fused half-split RoPE + scaled-dot-product attention.

    q/k arrive PRE-rope; cos/sin are (T, head_dim) duplicated-half caches.
    The pallas executor claims this whole (rope applied in-kernel, rope VJP
    rotated in-kernel on the dq/dk accumulators — the separate rope
    slice/negate/cat fusions and their backward passes disappear). The
    decomposition below is the unclaimed/CPU path and the grad fallback."""
    hs = q.shape[-1]
    h = hs // 2

    def rope(x):
        x1 = x[..., :h]
        x2 = x[..., h:]
        c = cos[..., :h]
        s_ = sin[..., :h]
        out = cat([x1 * c - x2 * s_, x2 * c + x1 * s_], -1)
        # rope math runs f32 (f32 cos/sin promote), but the attention matmuls
        # must keep the input compute dtype (autocast bf16 would otherwise be
        # silently undone on the unclaimed path)
        return clang.maybe_convert_to_dtype(out, x.dtype)

    return sdpa.meta(rope(q), rope(k), v, is_causal=is_causal, scale=scale,
                     enable_gqa=q.shape[1] != k.shape[1])


@torchsymbol(name="sdpa", id="torch.nn.functional.scaled_dot_product_attention")
def sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False):
    """Scaled dot-product attention (composite; Pallas flash-attention executor
    claims this symbol whole — reference analog: sdpaex/cudnnex claiming,
    thunder/executors/sdpaex.py:1)."""
    check(q.shape[-1] == k.shape[-1],
          lambda: f"sdpa: q head dim {q.shape[-1]} must match k head dim {k.shape[-1]}")
    check(k.shape[-2] == v.shape[-2],
          lambda: f"sdpa: k length {k.shape[-2]} must match v length {v.shape[-2]}")
    if q.ndim == 4 and k.ndim == 4 and q.shape[1] != k.shape[1]:
        check(k.shape[1] == v.shape[1],
              lambda: f"k has {k.shape[1]} heads but v has {v.shape[1]}")
        if k.shape[1] != 1:
            # GQA: replicate k/v head groups to match q (torch enable_gqa=True).
            # Size-1 kv heads need no flag or replication — matmul broadcasting
            # covers them, matching torch's math-path semantics.
            check(enable_gqa, lambda: f"q has {q.shape[1]} heads but k/v have "
                  f"{k.shape[1]}; pass enable_gqa=True for grouped-query attention")
            check(q.shape[1] % k.shape[1] == 0,
                  lambda: f"GQA requires q heads {q.shape[1]} divisible by kv heads {k.shape[1]}")
            k = repeat_interleave(k, q.shape[1] // k.shape[1], 1)
            v = repeat_interleave(v, q.shape[1] // v.shape[1], 1)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kt = clang.matrix_transpose(k)
    scores = clang.mul(prims.matmul(q, kt), scale)
    if is_causal:
        Lq, Lk = q.shape[-2], k.shape[-2]
        r = clang.unsqueeze(prims.iota(Lq, dtype=dtypes.int32, device=q.device), 1)
        c = clang.unsqueeze(prims.iota(Lk, dtype=dtypes.int32, device=q.device), 0)
        # torch documents a top-left-aligned causal mask (tril diagonal=0)
        # even when Lq != Lk
        causal = clang.ge(r, c)
        scores = clang.where(causal, scores, float("-inf"))
    if attn_mask is not None:
        if attn_mask.dtype.is_bool:
            scores = clang.where(attn_mask, scores, float("-inf"))
        else:
            scores = clang.add(scores, attn_mask)
    probs = softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, v.dtype)
    return prims.matmul(probs, v)


@torchsymbol(name="paged_attention", id="thunder.paged_attention")
def paged_attention(q, k_pages, v_pages, page_table, seq_lens, scale=None):
    """Decode-step attention of ONE new token per sequence against a
    block-paged KV pool (vLLM/PagedAttention, SOSP '23).

    q            (B, H, D)           — the current token's query heads
    k_pages/v_pages (P, page_size, Hkv, D) — the shared per-layer page pool
    page_table   (B, n_pages_max) int — per-sequence page ids; entries beyond
                 the sequence's pages point at the reserved null page 0
    seq_lens     (B,) int            — valid tokens per sequence INCLUDING
                 the current one (whose k/v is already written to its page)

    The decomposition below is the pure-jax gather reference path (CPU /
    interpret mode / unclaimed shapes); the pallas executor claims the
    symbol whole with a scalar-prefetch paged decode kernel on TPU
    (executors/pallasex.py:paged_attention_decode)."""
    B, H, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    npm = page_table.shape[1]
    T = npm * ps
    check(H % Hkv == 0,
          lambda: f"paged_attention: q heads {H} not divisible by kv heads {Hkv}")
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    flat = reshape(page_table, (B * npm,))
    k = clang.take(k_pages, flat, 0)  # (B*npm, ps, Hkv, D)
    v = clang.take(v_pages, flat, 0)
    k = permute(reshape(k, (B, T, Hkv, D)), (0, 2, 1, 3))  # (B, Hkv, T, D)
    v = permute(reshape(v, (B, T, Hkv, D)), (0, 2, 1, 3))
    if H != Hkv:
        k = repeat_interleave(k, H // Hkv, 1)
        v = repeat_interleave(v, H // Hkv, 1)
    qe = reshape(q, (B, H, 1, D))
    scores = clang.mul(prims.matmul(qe, clang.matrix_transpose(k)), scale)  # (B, H, 1, T)
    k_pos = reshape(prims.iota(T, dtype=dtypes.int32, device=q.device), (1, 1, 1, T))
    live = clang.lt(k_pos, reshape(seq_lens, (B, 1, 1, 1)))
    scores = clang.where(live, scores, float("-inf"))
    probs = softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, v.dtype)
    return reshape(prims.matmul(probs, v), (B, H, D))


@torchsymbol(name="paged_chunk_attention", id="thunder.paged_chunk_attention")
def paged_chunk_attention(q, k_pages, v_pages, page_table, q_pos, scale=None):
    """Multi-query paged attention: T new tokens per sequence attend the
    block-paged pool with PER-QUERY causal coverage (k_pos <= q_pos[b, t]).

    q            (B, H, T, D)        — T new tokens' query heads per sequence
    k_pages/v_pages (P, page_size, Hkv, D) — the shared per-layer page pool
    page_table   (B, n_pages_max) int — per-sequence page ids; entries beyond
                 the sequence's pages point at the reserved null page 0
    q_pos        (B, T) int          — each query's ABSOLUTE position; it
                 attends keys at positions <= its own (whose k/v, including
                 its own token's, are already written to their pages)

    One symbol serves both new paged multi-token programs (serving/runner.py):
    the CHUNKED-PREFILL chunk (B=1, T=chunk tokens, positions start..start+T)
    and the SPECULATIVE-DECODING verify step (T=k+1 proposed tokens per
    packed sequence). Shared (copy-on-write) page tables need nothing
    special here — shared pages simply repeat across rows of `page_table`.
    This decomposition is the pure-jax gather reference path; the pallas
    executor claims the symbol whole on TPU with a q_pos-prefetch variant of
    the paged decode kernel (executors/pallasex.py:paged_chunk_decode)."""
    B, H, T, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    npm = page_table.shape[1]
    S = npm * ps
    check(H % Hkv == 0,
          lambda: f"paged_chunk_attention: q heads {H} not divisible by kv heads {Hkv}")
    check(tuple(q_pos.shape) == (B, T),
          lambda: f"paged_chunk_attention: q_pos {q_pos.shape} must be (B, T)=({B}, {T})")
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    flat = reshape(page_table, (B * npm,))
    k = clang.take(k_pages, flat, 0)  # (B*npm, ps, Hkv, D)
    v = clang.take(v_pages, flat, 0)
    k = permute(reshape(k, (B, S, Hkv, D)), (0, 2, 1, 3))  # (B, Hkv, S, D)
    v = permute(reshape(v, (B, S, Hkv, D)), (0, 2, 1, 3))
    if H != Hkv:
        k = repeat_interleave(k, H // Hkv, 1)
        v = repeat_interleave(v, H // Hkv, 1)
    scores = clang.mul(prims.matmul(q, clang.matrix_transpose(k)), scale)  # (B, H, T, S)
    k_pos = reshape(prims.iota(S, dtype=dtypes.int32, device=q.device), (1, 1, 1, S))
    live = clang.le(k_pos, reshape(q_pos, (B, 1, T, 1)))
    scores = clang.where(live, scores, float("-inf"))
    probs = softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, v.dtype)
    return prims.matmul(probs, v)  # (B, H, T, D)


@torchsymbol(name="grouped_mlp", id="thunder.grouped_mlp")
def grouped_mlp(bins, w_gate, w_up, w_down, group_sizes):
    """Grouped/ragged SwiGLU expert MLP over capacity-packed token bins
    (Switch-Transformer/Mixtral-style capacity routing).

    bins         (E, cap, D) — per-expert token bins; rows at index >=
                 group_sizes[e] are padding and MUST be zero-filled (the
                 dispatch scatter guarantees this), so SwiGLU maps them to
                 exactly zero on every road
    w_gate/w_up  (E, D, H)   — per-expert gate/up projections
    w_down       (E, H, D)   — per-expert down projection
    group_sizes  (E,) int    — valid rows per bin; the grouped kernel skips
                 MXU work for wholly-padding bin blocks, the decomposition
                 ignores it (zero rows already produce zero outputs)

    The decomposition below is the pure-jax batched-matmul reference path
    (CPU / interpret mode / unclaimed shapes); the pallas executor claims
    the symbol whole on TPU with a (expert, bin-block) grid kernel whose
    MXU matmuls touch only each expert's own bin
    (executors/pallasex.py:grouped_mlp_fused)."""
    check(bins.ndim == 3, lambda: f"grouped_mlp: bins must be (E, cap, D), got {bins.shape}")
    E, cap, D = bins.shape
    check(tuple(w_gate.shape) == (E, D, w_gate.shape[-1]),
          lambda: f"grouped_mlp: w_gate {w_gate.shape} must be (E={E}, D={D}, H)")
    H = w_gate.shape[-1]
    check(tuple(w_up.shape) == (E, D, H),
          lambda: f"grouped_mlp: w_up {w_up.shape} must be ({E}, {D}, {H})")
    check(tuple(w_down.shape) == (E, H, D),
          lambda: f"grouped_mlp: w_down {w_down.shape} must be ({E}, {H}, {D})")
    check(tuple(group_sizes.shape) == (E,),
          lambda: f"grouped_mlp: group_sizes {group_sizes.shape} must be (E={E},)")
    g = prims.matmul(bins, w_gate)   # (E, cap, H)
    u = prims.matmul(bins, w_up)
    h = silu(g) * u
    return prims.matmul(h, w_down)   # (E, cap, D)


@torchsymbol(name="cross_entropy", id="torch.nn.functional.cross_entropy")
def cross_entropy(logits, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    """Composite cross-entropy over class dim 1 / last for 2D (logits (N, C)).

    Pallas fused cross-entropy claims this whole (reference analog: apex/triton
    cross-entropy executors, thunder/executors/triton_crossentropy_impl.py)."""
    check(logits.ndim == 2, lambda: "cross_entropy currently expects (N, C) logits")
    lsm = log_softmax(logits, 1)
    n, c = logits.shape
    tgt = clang.unsqueeze(target, 1)
    picked = clang.squeeze(clang.take_along_axis(lsm, tgt, 1), 1)
    nll = prims.neg(picked)
    if label_smoothing > 0.0:
        smooth = prims.neg(clang.mean(lsm, 1))
        nll = clang.add(clang.mul(nll, 1.0 - label_smoothing), clang.mul(smooth, label_smoothing))
    valid = clang.ne(target, ignore_index)
    nll = clang.where(valid, nll, clang.full_like(nll, 0))
    if reduction == "none":
        return nll
    if reduction == "sum":
        return clang.sum_(nll)
    count = clang.sum_(clang.maybe_convert_to_dtype(valid, nll.dtype))
    return clang.true_divide(clang.sum_(nll), count)


def _register_cross_entropy_grad():
    """Composite-level VJP for cross_entropy: forward saves (logits, lse)
    instead of the full (N, C) log-softmax — for an LM head that residual is
    the single biggest tensor in the step (N=B*T, C=vocab), and the backward
    recomputes softmax from logits in-register. Reference analog: the fused
    cross-entropy executors own their grads (apex/triton,
    thunder/executors/apex_entropyex_impl.py)."""
    from ..transforms.autodiff import VJPResult, register_augmented_forward, register_backward

    @register_augmented_forward("torch.nn.functional.cross_entropy")
    def _xent_aug(logits, target, weight=None, ignore_index=-100, reduction="mean",
                  label_smoothing=0.0):
        if weight is not None or logits.ndim != 2:
            return NotImplemented
        n, c = logits.shape
        lg = clang.maybe_convert_to_dtype(logits, dtypes.float32)
        m = clang.amax(lg, 1, keepdim=True)
        lse = clang.add(prims.log(clang.sum_(prims.exp(clang.sub(lg, m)), 1, keepdim=True)), m)
        tgt2 = clang.unsqueeze(target, 1)
        # gather from the ORIGINAL-dtype logits and upcast the picked values
        # (exact for bf16→f32): a gather consuming lg forces the full f32
        # (N, vocab) convert to materialize as a fusion output — a 1 GB HBM
        # round-trip per step at llama vocab sizes — while the reduction
        # chain over lg alone fuses into one pass
        picked = clang.maybe_convert_to_dtype(
            clang.take_along_axis(logits, tgt2, 1), dtypes.float32)
        nll = clang.squeeze(clang.sub(lse, picked), 1)
        if label_smoothing > 0.0:
            # smooth term: -mean(log_softmax) = lse - mean(logits)
            smooth = clang.sub(clang.squeeze(lse, 1), clang.mean(lg, 1))
            nll = clang.add(clang.mul(nll, 1.0 - label_smoothing),
                            clang.mul(smooth, label_smoothing))
        valid = clang.ne(target, ignore_index)
        nll = clang.where(valid, nll, clang.full_like(nll, 0))
        count = clang.sum_(clang.maybe_convert_to_dtype(valid, dtypes.float32))
        if reduction == "none":
            out = nll
        elif reduction == "sum":
            out = clang.sum_(nll)
        else:
            out = clang.true_divide(clang.sum_(nll), count)
        return VJPResult(out, (logits, target, lse, valid, count,
                               reduction, float(label_smoothing), int(c)))

    @register_backward("torch.nn.functional.cross_entropy")
    def _xent_bwd(logits, target, lse, valid, count, reduction, label_smoothing, c, g):
        lg = clang.maybe_convert_to_dtype(logits, dtypes.float32)
        soft = prims.exp(clang.sub(lg, lse))  # softmax recomputed from lse
        onehot = clang.eq(
            clang.unsqueeze(target, 1),
            clang.unsqueeze(prims.iota(c, dtype=dtypes.int64, device=logits.device), 0))
        onehot_f = clang.maybe_convert_to_dtype(onehot, dtypes.float32)
        if label_smoothing > 0.0:
            target_dist = clang.add(clang.mul(onehot_f, 1.0 - label_smoothing),
                                    label_smoothing / c)
        else:
            target_dist = onehot_f
        dlogits = clang.sub(soft, target_dist)
        valid_f = clang.maybe_convert_to_dtype(valid, dtypes.float32)
        if reduction == "none":
            gi = clang.mul(g, valid_f)
        elif reduction == "sum":
            gi = clang.mul(g, valid_f)
        else:
            gi = clang.mul(clang.true_divide(g, count), valid_f)
        dlogits = clang.mul(dlogits, clang.unsqueeze(gi, 1))
        return (clang.maybe_convert_to_dtype(dlogits, logits.dtype), None)


_register_cross_entropy_grad()


@torchsymbol(name="nll_loss", id="torch.nn.functional.nll_loss")
def nll_loss(log_probs, target, weight=None, ignore_index=-100, reduction="mean"):
    tgt = clang.unsqueeze(target, 1)
    picked = clang.squeeze(clang.take_along_axis(log_probs, tgt, 1), 1)
    nll = prims.neg(picked)
    valid = clang.ne(target, ignore_index)
    if weight is not None:
        # per-sample class weights; torch normalizes the mean by their sum
        safe_tgt = clang.where(valid, target, clang.full_like(target, 0))
        w = clang.take(weight, safe_tgt, 0)
        nll = clang.mul(nll, w)
        denom = clang.sum_(clang.where(valid, w, clang.full_like(w, 0)))
    else:
        denom = clang.sum_(clang.maybe_convert_to_dtype(valid, nll.dtype))
    nll = clang.where(valid, nll, clang.full_like(nll, 0))
    if reduction == "none":
        return nll
    if reduction == "sum":
        return clang.sum_(nll)
    return clang.true_divide(clang.sum_(nll), denom)


@torchsymbol(name="mse_loss", id="torch.nn.functional.mse_loss")
def mse_loss(input, target, reduction="mean"):
    d = clang.sub(input, target)
    sq = clang.mul(d, d)
    if reduction == "none":
        return sq
    if reduction == "sum":
        return clang.sum_(sq)
    return clang.mean(sq)


@torchsymbol(name="dropout", id="torch.nn.functional.dropout")
def dropout(a, p=0.5, training=True, *, key=None):
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "dropout in training mode requires an rng key (pass key= or use nn.Module rng plumbing)")
    keep = 1.0 - p
    mask = clang.lt(prims.uniform(a.shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    return clang.mul(clang.where(mask, a, clang.full_like(a, 0)), 1.0 / keep)


@torchsymbol(name="grouped_mm", id="torch.grouped_mm")
def grouped_mm(a, b, group_sizes):
    return prims.grouped_mm(a, b, group_sizes)


@torchsymbol(name="baddbmm", method_names=("baddbmm",))
def baddbmm(input, batch1, batch2, *, beta=1, alpha=1):
    out = prims.matmul(batch1, batch2)
    if pyval(alpha) != 1:
        out = clang.mul(out, alpha)
    if pyval(beta) != 0:
        out = clang.add(out, clang.mul(input, beta) if pyval(beta) != 1 else input)
    return out


@torchsymbol(name="addmm", method_names=("addmm",))
def addmm(input, mat1, mat2, *, beta=1, alpha=1):
    return baddbmm.meta(input, mat1, mat2, beta=beta, alpha=alpha)


@torchsymbol(name="outer", method_names=("outer",))
def outer(a, b):
    check(a.ndim == 1 and b.ndim == 1,
          lambda: f"outer expects 1D vectors, got {a.ndim}-D and {b.ndim}-D")
    return clang.mul(clang.unsqueeze(a, 1), clang.unsqueeze(b, 0))


# normalization helpers used by models ---------------------------------------


@torchsymbol(name="glu", id="torch.nn.functional.glu")
def glu(a, dim=-1):
    x, g = clang.chunk(a, 2, pyval(dim))
    return clang.mul(x, sigmoid.meta(g))


@torchsymbol(name="swiglu", id="thunder_tpu.swiglu")
def swiglu(gate, up):
    return clang.mul(clang.mul(gate, clang.true_divide(1.0, clang.add(1.0, prims.exp(prims.neg(gate))))), up)


# ---------------------------------------------------------------------------
# widened op surface (reference thunder/torch/__init__.py has ~345 symbols;
# everything below decomposes into the prim set so autodiff + fusion follow)
# ---------------------------------------------------------------------------

log10 = _unary("log10", prims.log10, int_to_float=True)
lgamma = _unary("lgamma", prims.lgamma, int_to_float=True)
digamma = _unary("digamma", prims.digamma, int_to_float=True)
erfinv = _unary("erfinv", prims.erfinv, int_to_float=True)
asinh = _unary("asinh", prims.asinh, int_to_float=True)
acosh = _unary("acosh", prims.acosh, int_to_float=True)
atanh = _unary("atanh", prims.atanh, int_to_float=True)
signbit = _unary("signbit", prims.signbit)


@torchsymbol(name="square", method_names=("square",))
def square(a):
    return clang.mul(a, a)


@torchsymbol(name="frac", method_names=("frac",))
def frac(a):
    return clang.sub(a, prims.trunc(a))


@torchsymbol(name="positive", method_names=("positive",))
def positive(a):
    return a


@torchsymbol(name="rad2deg", method_names=("rad2deg",))
def rad2deg(a):
    return clang.mul(a, 180.0 / math.pi)


@torchsymbol(name="deg2rad", method_names=("deg2rad",))
def deg2rad(a):
    return clang.mul(a, math.pi / 180.0)


@torchsymbol(name="logit")
def logit(a, eps=None):
    if eps is not None:
        a = clang.minimum(clang.maximum(a, eps), 1.0 - eps)
    return prims.log(clang.true_divide(a, clang.sub(1.0, a)))


@torchsymbol(name="nan_to_num", method_names=("nan_to_num",))
def nan_to_num(a, nan=0.0, posinf=None, neginf=None):
    if posinf is None:
        posinf = dtypes.finfo_max(a.dtype)
    if neginf is None:
        neginf = -dtypes.finfo_max(a.dtype)
    out = clang.where(prims.isnan(a), clang.full_like(a, nan), a)
    out = clang.where(clang.eq(a, float("inf")), clang.full_like(a, posinf), out)
    out = clang.where(clang.eq(a, float("-inf")), clang.full_like(a, neginf), out)
    return out


# activation family ----------------------------------------------------------


@torchsymbol(name="hardtanh", id="torch.nn.functional.hardtanh")
def hardtanh(a, min_val=-1.0, max_val=1.0):
    return clang.minimum(clang.maximum(a, min_val), max_val)


@torchsymbol(name="hardswish", id="torch.nn.functional.hardswish")
def hardswish(a):
    return clang.mul(a, clang.true_divide(clang.minimum(clang.maximum(clang.add(a, 3.0), 0.0), 6.0), 6.0))


@torchsymbol(name="hardsigmoid", id="torch.nn.functional.hardsigmoid")
def hardsigmoid(a):
    return clang.true_divide(clang.minimum(clang.maximum(clang.add(a, 3.0), 0.0), 6.0), 6.0)


@torchsymbol(name="hardshrink", id="torch.nn.functional.hardshrink")
def hardshrink(a, lambd=0.5):
    keep = clang.logical_or(clang.gt(a, lambd), clang.lt(a, -lambd))
    return clang.where(keep, a, clang.full_like(a, 0))


@torchsymbol(name="softshrink", id="torch.nn.functional.softshrink")
def softshrink(a, lambd=0.5):
    pos = clang.gt(a, lambd)
    neg = clang.lt(a, -lambd)
    out = clang.where(pos, clang.sub(a, lambd), clang.full_like(a, 0))
    return clang.where(neg, clang.add(a, lambd), out)


@torchsymbol(name="tanhshrink", id="torch.nn.functional.tanhshrink")
def tanhshrink(a):
    return clang.sub(a, prims.tanh(a))


@torchsymbol(name="softsign", id="torch.nn.functional.softsign")
def softsign(a):
    return clang.true_divide(a, clang.add(1.0, prims.abs(a)))


@torchsymbol(name="elu", id="torch.nn.functional.elu")
def elu(a, alpha=1.0):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, prims.expm1(a)))


@torchsymbol(name="selu", id="torch.nn.functional.selu")
def selu(a):
    _alpha = 1.6732632423543772848170429916717
    _scale = 1.0507009873554804934193349852946
    return clang.mul(_scale, clang.where(clang.gt(a, 0), a, clang.mul(_alpha, prims.expm1(a))))


@torchsymbol(name="celu", id="torch.nn.functional.celu")
def celu(a, alpha=1.0):
    return clang.where(clang.gt(a, 0), a, clang.mul(alpha, prims.expm1(clang.true_divide(a, alpha))))


@torchsymbol(name="prelu", id="torch.nn.functional.prelu")
def prelu(a, weight):
    if weight.numel != 1 and a.ndim > 1:
        weight = clang.reshape(weight, (1, weight.shape[0]) + (1,) * (a.ndim - 2))
    return clang.where(clang.gt(a, 0), a, clang.mul(a, weight))


@torchsymbol(name="logsigmoid", id="torch.nn.functional.logsigmoid")
def logsigmoid(a):
    # numerically stable: -softplus(-x)
    neg = prims.neg(a)
    return prims.neg(clang.where(clang.gt(neg, 20.0), neg, prims.log1p(prims.exp(neg))))


@torchsymbol(name="threshold", id="torch.nn.functional.threshold")
def threshold(a, threshold_value, value):
    return clang.where(clang.gt(a, threshold_value), a, clang.full_like(a, pyval(value)))


# binary family --------------------------------------------------------------


@torchsymbol(name="logaddexp", method_names=("logaddexp",))
def logaddexp(a, b):
    m = clang.maximum(a, b)
    out = clang.add(m, prims.log1p(prims.exp(prims.neg(prims.abs(clang.sub(a, b))))))
    # a == b (incl. ±inf where a-b is nan): exact result is a + log(2)
    return clang.where(clang.eq(a, b), clang.add(m, math.log(2.0)), out)


@torchsymbol(name="logaddexp2", method_names=("logaddexp2",))
def logaddexp2(a, b):
    m = clang.maximum(a, b)
    inner = prims.exp2(prims.neg(prims.abs(clang.sub(a, b))))
    out = clang.add(m, clang.true_divide(prims.log1p(inner), math.log(2.0)))
    return clang.where(clang.eq(a, b), clang.add(m, 1.0), out)


@torchsymbol(name="hypot", method_names=("hypot",))
def hypot(a, b):
    return clang._elementwise_binary(prims.hypot, a, b)


@torchsymbol(name="copysign", method_names=("copysign",))
def copysign(a, b):
    return clang._elementwise_binary(prims.copysign, a, b)


@torchsymbol(name="nextafter", method_names=("nextafter",))
def nextafter(a, b):
    return clang._elementwise_binary(prims.nextafter, a, b)


@torchsymbol(name="gcd", method_names=("gcd",))
def gcd(a, b):
    return clang._elementwise_binary(prims.gcd, a, b)


@torchsymbol(name="lcm", method_names=("lcm",))
def lcm(a, b):
    return clang._elementwise_binary(prims.lcm, a, b)


@torchsymbol(name="xlogy", method_names=("xlogy",))
def xlogy(a, b):
    safe = prims.log(clang.where(clang.eq(a, 0), clang.full_like(b, 1.0), b))
    return clang.where(clang.eq(a, 0), clang.full_like(safe, 0.0), clang.mul(a, safe))


@torchsymbol(name="float_power", method_names=("float_power",))
def float_power(a, b):
    a = clang.maybe_convert_to_dtype(a, dtypes.float64 if dtypes.x64_enabled() else dtypes.float32)
    return clang.pow_(a, b)


@torchsymbol(name="fmax", method_names=("fmax",))
def fmax(a, b):
    both = clang.maximum(a, b)
    return clang.where(prims.isnan(clang.ensure_proxy(a) if not isinstance(a, TensorProxy) else a), b, clang.where(prims.isnan(clang.ensure_proxy(b) if not isinstance(b, TensorProxy) else b), a, both))


@torchsymbol(name="fmin", method_names=("fmin",))
def fmin(a, b):
    both = clang.minimum(a, b)
    return clang.where(prims.isnan(clang.ensure_proxy(a) if not isinstance(a, TensorProxy) else a), b, clang.where(prims.isnan(clang.ensure_proxy(b) if not isinstance(b, TensorProxy) else b), a, both))


@torchsymbol(name="heaviside", method_names=("heaviside",))
def heaviside(a, values):
    out = clang.where(clang.gt(a, 0), clang.full_like(a, 1.0), clang.full_like(a, 0.0))
    return clang.where(clang.eq(a, 0), values, out)


@torchsymbol(name="clamp_min", method_names=("clamp_min",))
def clamp_min(a, min):
    return clang.maximum(a, min)


@torchsymbol(name="clamp_max", method_names=("clamp_max",))
def clamp_max(a, max):
    return clang.minimum(a, max)


@torchsymbol(name="rsub", method_names=("rsub",))
def rsub(a, b, *, alpha=None):
    if alpha is not None and pyval(alpha) != 1:
        a = clang.mul(a, alpha)
    return clang.sub(b, a)


@torchsymbol(name="logical_xor", method_names=("logical_xor",))
def logical_xor(a, b):
    return clang.ne(clang.to_bool(a), clang.to_bool(b))


@torchsymbol(name="bitwise_left_shift", method_names=("bitwise_left_shift",))
def bitwise_left_shift(a, b):
    return clang._elementwise_binary(prims.shift_left, a, b)


@torchsymbol(name="bitwise_right_shift", method_names=("bitwise_right_shift",))
def bitwise_right_shift(a, b):
    return clang._elementwise_binary(prims.shift_right, a, b)


# reductions (widened) -------------------------------------------------------


@torchsymbol(name="logsumexp", method_names=("logsumexp",))
def logsumexp(a, dim, keepdim=False):
    m = clang.amax(a, dim, keepdim=True)
    m_stopped = prims.stop_gradient(m)
    s = clang.sum_(prims.exp(clang.sub(a, m_stopped)), dim, keepdim=True)
    out = clang.add(prims.log(s), m_stopped)
    if not keepdim:
        dims = clang._reduction_dims(a, dim)
        out = clang.squeeze(out, dims)
    return out


@torchsymbol(name="softmin", id="torch.nn.functional.softmin")
def softmin(a, dim=-1):
    return softmax.meta(prims.neg(a), dim)


@torchsymbol(name="cumprod", method_names=("cumprod",))
def cumprod(a, dim):
    return prims.cumprod(a, canonicalize_dim(a.ndim, pyval(dim)))


@torchsymbol(name="cummax", method_names=("cummax",))
def cummax(a, dim):
    return prims.cummax(a, canonicalize_dim(a.ndim, pyval(dim)))


@torchsymbol(name="count_nonzero", method_names=("count_nonzero",))
def count_nonzero(a, dim=None):
    nz = clang.ne(a, 0)
    return clang.sum_(clang.maybe_convert_to_dtype(nz, dtypes.int64), dim, False)


@torchsymbol(name="nansum", method_names=("nansum",))
def nansum(a, dim=None, keepdim=False):
    cleaned = clang.where(prims.isnan(a), clang.full_like(a, 0), a)
    return clang.sum_(cleaned, dim, keepdim)


@torchsymbol(name="nanmean", method_names=("nanmean",))
def nanmean(a, dim=None, keepdim=False):
    nan_mask = prims.isnan(a)
    cleaned = clang.where(nan_mask, clang.full_like(a, 0), a)
    total = clang.sum_(cleaned, dim, keepdim)
    count = clang.sum_(clang.maybe_convert_to_dtype(prims.logical_not(nan_mask), a.dtype), dim, keepdim)
    return clang.true_divide(total, count)


@torchsymbol(name="aminmax", method_names=("aminmax",))
def aminmax(a, *, dim=None, keepdim=False):
    return clang.amin(a, dim, keepdim), clang.amax(a, dim, keepdim)


@torchsymbol(name="std_mean")
def std_mean(a, dim=None, keepdim=False, *, correction=1):
    v, m = clang.var_mean(a, dim, keepdim, correction=correction)
    return prims.sqrt(v), m


@torchsymbol(name="median", method_names=("median",))
def median(a, dim=None, keepdim=False):
    """torch.median: global form returns the lower median value."""
    if dim is None:
        flat = clang.reshape(a, (a.numel,))
        s = prims.sort(flat, 0, False)
        return clang.squeeze(clang.slice_in_dim(s, (a.numel - 1) // 2, (a.numel - 1) // 2 + 1, 0), (0,))
    d = canonicalize_dim(a.ndim, pyval(dim))
    n = a.shape[d]
    sv = prims.sort(a, d, False)
    si = prims.argsort(a, d, False)
    values = clang.slice_in_dim(sv, (n - 1) // 2, (n - 1) // 2 + 1, d)
    indices = clang.slice_in_dim(si, (n - 1) // 2, (n - 1) // 2 + 1, d)
    if not keepdim:
        values = clang.squeeze(values, (d,))
        indices = clang.squeeze(indices, (d,))
    return values, clang.maybe_convert_to_dtype(indices, dtypes.int64)


@torchsymbol(name="norm", method_names=("norm",))
def norm(a, p=2, dim=None, keepdim=False):
    p = pyval(p) if not isinstance(p, str) else p
    check(isinstance(p, (int, float)) or p in ("fro", "inf"),
          lambda: f"norm: ord/p must be a number or 'fro'/'inf', got {p!r}")
    if p == "fro" or p == 2:
        return prims.sqrt(clang.sum_(clang.mul(a, a), dim, keepdim))
    if p == "inf" or p == float("inf"):
        return clang.amax(prims.abs(a), dim, keepdim)
    if p == float("-inf"):
        return clang.amin(prims.abs(a), dim, keepdim)
    if p == 1:
        return clang.sum_(prims.abs(a), dim, keepdim)
    powd = clang.pow_(prims.abs(a), p)
    return clang.pow_(clang.sum_(powd, dim, keepdim), 1.0 / p)


@torchsymbol(name="vector_norm", id="torch.linalg.vector_norm")
def vector_norm(a, ord=2, dim=None, keepdim=False):
    return norm.meta(a, ord, dim, keepdim)


# shape ops (widened) --------------------------------------------------------


@torchsymbol(name="narrow", method_names=("narrow",))
def narrow(a, dim, start, length):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    start = pyval(start)
    if start < 0:
        start += a.shape[dim]
    return clang.slice_in_dim(a, start, start + pyval(length), dim)


@torchsymbol(name="select", method_names=("select",))
def select(a, dim, index):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    index = pyval(index)
    if index < 0:
        index += a.shape[dim]
    return clang.squeeze(clang.slice_in_dim(a, index, index + 1, dim), (dim,))


@torchsymbol(name="unbind", method_names=("unbind",))
def unbind(a, dim=0):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    return tuple(select.meta(a, dim, i) for i in builtins.range(a.shape[dim]))


@torchsymbol(name="split_with_sizes", method_names=("split_with_sizes",))
def split_with_sizes(a, split_sizes, dim=0):
    return clang.split(a, [pyval(s) for s in split_sizes], pyval(dim))


@torchsymbol(name="hsplit", method_names=("hsplit",))
def hsplit(a, indices_or_sections):
    d = 0 if a.ndim == 1 else 1
    return _split_by(a, indices_or_sections, d)


@torchsymbol(name="vsplit", method_names=("vsplit",))
def vsplit(a, indices_or_sections):
    return _split_by(a, indices_or_sections, 0)


def _split_by(a, indices_or_sections, dim):
    n = a.shape[dim]
    if isinstance(indices_or_sections, int):
        check(n % indices_or_sections == 0, lambda: f"split {n} into {indices_or_sections}")
        return clang.split(a, n // indices_or_sections, dim)
    pts = [pyval(p) for p in indices_or_sections]
    sizes, prev = [], 0
    for p in pts:
        sizes.append(p - prev)
        prev = p
    sizes.append(n - prev)
    return clang.split(a, sizes, dim)


@torchsymbol(name="tensor_split", method_names=("tensor_split",))
def tensor_split(a, indices_or_sections, dim=0):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    n = a.shape[dim]
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        base, rem = divmod(n, k)
        sizes = [base + (1 if i < rem else 0) for i in builtins.range(k)]
        return clang.split(a, sizes, dim)
    return _split_by(a, indices_or_sections, dim)


@torchsymbol(name="tile", method_names=("tile",))
def tile(a, *dims):
    if len(dims) == 1 and isinstance(dims[0], (tuple, list)):
        dims = tuple(dims[0])
    out = a
    while out.ndim < len(dims):
        out = clang.unsqueeze(out, 0)
    dims = (1,) * (out.ndim - len(dims)) + tuple(pyval(d) for d in dims)
    for i, d in enumerate(dims):
        check(d >= 0, lambda: f"tile: negative repeat {d} for dim {i}")
        if d == 0:
            out = clang.slice_in_dim(out, 0, 0, i)
        elif d > 1:
            out = clang.cat([out] * d, i)
    return out


@torchsymbol(name="broadcast_to", method_names=("broadcast_to",))
def broadcast_to(a, shape):
    return clang.expand(a, tuple(shape))


@torchsymbol(name="expand_as", method_names=("expand_as",))
def expand_as(a, other):
    return clang.expand(a, other.shape)


@torchsymbol(name="repeat_interleave", method_names=("repeat_interleave",))
def repeat_interleave(a, repeats, dim=None):
    check(isinstance(repeats, (int, NumberProxy)), lambda: "repeat_interleave: only int repeats supported (static shapes)")
    r = pyval(repeats)
    check(r >= 0, lambda: f"repeat_interleave: repeats must be non-negative, got {r}")
    if dim is None:
        a = clang.reshape(a, (a.numel,))
        d = 0
    else:
        d = canonicalize_dim(a.ndim, pyval(dim))
    expanded = clang.unsqueeze(a, d + 1)
    tiled = clang.cat([expanded] * r, d + 1)
    new_shape = tuple(s * r if i == d else s for i, s in enumerate(a.shape))
    return clang.reshape(tiled, new_shape)


@torchsymbol(name="diag", method_names=("diag",))
def diag(a, diagonal=0):
    k = pyval(diagonal)
    if a.ndim == 1:
        n = a.shape[0] + builtins.abs(k)
        r = clang.unsqueeze(prims.iota(n, dtype=dtypes.int32, device=a.device), 1)
        c = clang.unsqueeze(prims.iota(n, dtype=dtypes.int32, device=a.device), 0)
        mask = clang.eq(clang.sub(c, r), k)
        # place values: index vector along the diagonal
        src = clang.expand(clang.unsqueeze(a, 0), (n, a.shape[0]))
        idx = clang.sub(c if k >= 0 else r, builtins.abs(k))
        take_idx = clang.maximum(clang.minimum(idx, a.shape[0] - 1), 0)
        vals = clang.take_along_axis(src, clang.expand(take_idx, (n, n)) if take_idx.shape != (n, n) else take_idx, 1)
        zero = clang.full_like(vals, 0)
        return clang.where(mask, vals, zero)
    return diagonal_op.meta(a, offset=k)


@torchsymbol(name="diagonal", method_names=("diagonal",), id="torch.diagonal")
def diagonal_op(a, offset=0, dim1=0, dim2=1):
    d1 = canonicalize_dim(a.ndim, pyval(dim1))
    d2 = canonicalize_dim(a.ndim, pyval(dim2))
    k = pyval(offset)
    n1, n2 = a.shape[d1], a.shape[d2]
    dlen = builtins.max(0, builtins.min(n1, n2 - k) if k >= 0 else builtins.min(n1 + k, n2))
    # move d1,d2 to the end
    order = [i for i in builtins.range(a.ndim) if i not in (d1, d2)] + [d1, d2]
    moved = clang.permute(a, order)
    i = prims.iota(dlen, dtype=dtypes.int32, device=a.device)
    r = clang.add(i, builtins.max(0, -k))
    c = clang.add(i, builtins.max(0, k))
    flat = clang.reshape(moved, moved.shape[:-2] + (n1 * n2,))
    lin = clang.add(clang.mul(r, n2), c)
    lin_b = clang.expand_to(lin, flat.shape[:-1] + (dlen,))
    return clang.take_along_axis(flat, lin_b, flat.ndim - 1)


@torchsymbol(name="diag_embed", method_names=("diag_embed",))
def diag_embed(a, offset=0, dim1=-2, dim2=-1):
    d1, d2 = pyval(dim1), pyval(dim2)
    out_ndim = a.ndim + 1
    for d in (d1, d2):
        if not -out_ndim <= d < out_ndim:
            raise IndexError(f"diag_embed: dim {d} out of range for rank {out_ndim}")
    nd1, nd2 = d1 % out_ndim, d2 % out_ndim
    if nd1 == nd2:
        raise RuntimeError(f"diag_embed: dim1 ({d1}) and dim2 ({d2}) must be distinct")
    k = pyval(offset)
    m = a.shape[-1]
    n = m + builtins.abs(k)
    r = clang.unsqueeze(prims.iota(n, dtype=dtypes.int32, device=a.device), 1)
    c = clang.unsqueeze(prims.iota(n, dtype=dtypes.int32, device=a.device), 0)
    mask = clang.eq(clang.sub(c, r), k)
    idx = clang.maximum(clang.minimum(clang.sub(r if k >= 0 else c, 0), m - 1), 0)
    idx_flat = clang.reshape(clang.expand(idx, (n, n)) if idx.shape != (n, n) else idx, (n * n,))
    gathered = clang.take(a, idx_flat, a.ndim - 1)
    gathered = clang.reshape(gathered, a.shape[:-1] + (n, n))
    mask_b = clang.expand_to(mask, gathered.shape)
    out = clang.where(mask_b, gathered, clang.full_like(gathered, 0))
    if (nd1, nd2) != (out_ndim - 2, out_ndim - 1):
        # torch places the matrix dims at (dim1, dim2); moveaxis the trailing
        # construction dims there
        rest = iter(i for i in range(out_ndim) if i not in (out_ndim - 2, out_ndim - 1))
        perm = [None] * out_ndim
        perm[nd1] = out_ndim - 2
        perm[nd2] = out_ndim - 1
        perm = [next(rest) if p is None else p for p in perm]
        out = clang.permute(out, tuple(perm))
    return out


@torchsymbol(name="meshgrid")
def meshgrid(*tensors, indexing="ij"):
    tensors = list(tensors[0]) if len(tensors) == 1 and isinstance(tensors[0], (tuple, list)) else list(tensors)
    n = len(tensors)
    shape = tuple(t.shape[0] for t in tensors)
    outs = []
    for i, t in enumerate(tensors):
        view = [1] * n
        view[i] = t.shape[0]
        out = clang.expand(clang.reshape(t, tuple(view)), shape)
        outs.append(out)
    if indexing == "xy" and n >= 2:
        outs = [clang.transpose(o, 0, 1) for o in outs]
    return tuple(outs)


@torchsymbol(name="atleast_1d")
def atleast_1d(a):
    return a if a.ndim >= 1 else clang.reshape(a, (1,))


@torchsymbol(name="atleast_2d")
def atleast_2d(a):
    if a.ndim >= 2:
        return a
    if a.ndim == 1:
        return clang.unsqueeze(a, 0)
    return clang.reshape(a, (1, 1))


@torchsymbol(name="atleast_3d")
def atleast_3d(a):
    if a.ndim >= 3:
        return a
    if a.ndim == 2:
        return clang.unsqueeze(a, 2)
    if a.ndim == 1:
        return clang.reshape(a, (1, a.shape[0], 1))
    return clang.reshape(a, (1, 1, 1))


@torchsymbol(name="ravel", method_names=("ravel",))
def ravel(a):
    return clang.reshape(a, (a.numel,))


@torchsymbol(name="unflatten", method_names=("unflatten",))
def unflatten(a, dim, sizes):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    sizes = tuple(pyval(s) for s in sizes)
    if -1 not in sizes:
        prod = 1
        for x in sizes:
            prod *= x
        check(prod == a.shape[dim],
              lambda: f"unflatten: sizes {sizes} (product {prod}) must multiply to dim {dim} size {a.shape[dim]}")
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes = tuple(a.shape[dim] // known if s == -1 else s for s in sizes)
    return clang.reshape(a, a.shape[:dim] + sizes + a.shape[dim + 1 :])


@torchsymbol(name="hstack")
def hstack(tensors):
    tensors = list(tensors)
    if tensors[0].ndim == 1:
        return clang.cat(tensors, 0)
    return clang.cat(tensors, 1)


@torchsymbol(name="vstack")
def vstack(tensors):
    tensors = [clang.unsqueeze(t, 0) if t.ndim == 1 else t for t in tensors]
    return clang.cat(tensors, 0)


@torchsymbol(name="dstack")
def dstack(tensors):
    fixed = []
    for t in tensors:
        if t.ndim == 1:
            t = clang.reshape(t, (1, t.shape[0], 1))
        elif t.ndim == 2:
            t = clang.unsqueeze(t, 2)
        fixed.append(t)
    return clang.cat(fixed, 2)


@torchsymbol(name="column_stack")
def column_stack(tensors):
    fixed = [clang.unsqueeze(t, 1) if t.ndim == 1 else t for t in tensors]
    return clang.cat(fixed, 1)


@torchsymbol(name="select_scatter", method_names=("select_scatter",))
def select_scatter(a, src, dim, index):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    index = pyval(index)
    if index < 0:
        index += a.shape[dim]
    parts = []
    if index > 0:
        parts.append(clang.slice_in_dim(a, 0, index, dim))
    parts.append(clang.unsqueeze(src, dim))
    if index + 1 < a.shape[dim]:
        parts.append(clang.slice_in_dim(a, index + 1, a.shape[dim], dim))
    return clang.cat(parts, dim)


@torchsymbol(name="slice_scatter", method_names=("slice_scatter",))
def slice_scatter(a, src, dim=0, start=None, end=None, step=1):
    dim = canonicalize_dim(a.ndim, pyval(dim))
    n = a.shape[dim]
    start = 0 if start is None else pyval(start)
    end = n if end is None else builtins.min(pyval(end), n)
    check(pyval(step) == 1, lambda: "slice_scatter: step != 1 unsupported")
    parts = []
    if start > 0:
        parts.append(clang.slice_in_dim(a, 0, start, dim))
    parts.append(src)
    if end < n:
        parts.append(clang.slice_in_dim(a, end, n, dim))
    return clang.cat(parts, dim)


@torchsymbol(name="scatter", method_names=("scatter",))
def scatter(a, dim, index, src):
    if isinstance(src, (int, float, NumberProxy)):
        src = clang.full_like(clang.take_along_axis(a, index, pyval(dim)), pyval(src))
    return prims.scatter(a, index, src, canonicalize_dim(a.ndim, pyval(dim)))


# factories (widened) --------------------------------------------------------


@torchsymbol(name="eye")
def eye(n, m=None, *, device=None, dtype=None):
    n = pyval(n)
    m = n if m is None else pyval(m)
    dtype = dtypes.to_dtype(dtype) if dtype else dtypes.float32
    r = clang.unsqueeze(prims.iota(n, dtype=dtypes.int32, device=device), 1)
    c = clang.unsqueeze(prims.iota(m, dtype=dtypes.int32, device=device), 0)
    return clang.maybe_convert_to_dtype(clang.eq(r, c), dtype)


@torchsymbol(name="empty")
def empty(*shape, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return clang.full(shape, 0, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="empty_like")
def empty_like(a, *, device=None, dtype=None):
    return clang.full_like(a, 0, device=device, dtype=dtype)


@torchsymbol(name="rand")
def rand(*shape, key=None, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    check(key is not None, lambda: "rand requires an rng key (key=)")
    return prims.uniform(shape, 0.0, 1.0, key=key, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="randn")
def randn(*shape, key=None, device=None, dtype=None):
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    check(key is not None, lambda: "randn requires an rng key (key=)")
    return prims.normal(shape, 0.0, 1.0, key=key, device=device, dtype=dtype or dtypes.float32)


@torchsymbol(name="randint")
def randint(low, high, shape, *, key=None, device=None, dtype=None):
    check(key is not None, lambda: "randint requires an rng key (key=)")
    return prims.randint(tuple(shape), pyval(low), pyval(high), key=key, device=device, dtype=dtype or dtypes.int32)


@torchsymbol(name="rand_like")
def rand_like(a, *, key=None):
    return prims.uniform(a.shape, 0.0, 1.0, key=key, device=a.device, dtype=a.dtype)


@torchsymbol(name="randn_like")
def randn_like(a, *, key=None):
    return prims.normal(a.shape, 0.0, 1.0, key=key, device=a.device, dtype=a.dtype)


@torchsymbol(name="bernoulli")
def bernoulli(p, *, key=None):
    check(key is not None, lambda: "bernoulli requires an rng key (key=)")
    u = prims.uniform(p.shape, 0.0, 1.0, key=key, device=p.device, dtype=dtypes.float32)
    return clang.maybe_convert_to_dtype(clang.lt(u, p), p.dtype)


@torchsymbol(name="multinomial")
def multinomial(probs, num_samples, *, key=None):
    """Sampling without replacement via the Gumbel top-k trick."""
    check(key is not None, lambda: "multinomial requires an rng key (key=)")
    check(probs.ndim in (1, 2), lambda: "multinomial expects 1D/2D probs")
    u = prims.uniform(probs.shape, 0.0, 1.0, key=key, device=probs.device, dtype=dtypes.float32)
    eps = 1e-10
    gumbel = prims.neg(prims.log(clang.add(prims.neg(prims.log(clang.add(u, eps))), eps)))
    scores = clang.add(prims.log(clang.add(clang.maybe_convert_to_dtype(probs, dtypes.float32), eps)), gumbel)
    _, idx = prims.topk(scores, pyval(num_samples), probs.ndim - 1)
    return clang.maybe_convert_to_dtype(idx, dtypes.int64)


@torchsymbol(name="randperm")
def randperm(n, *, key=None, device=None):
    check(key is not None, lambda: "randperm requires an rng key (key=)")
    u = prims.uniform((pyval(n),), 0.0, 1.0, key=key, device=device, dtype=dtypes.float32)
    return clang.maybe_convert_to_dtype(prims.argsort(u, 0, False), dtypes.int64)


@torchsymbol(name="logspace")
def logspace(start, end, steps, base=10.0, *, device=None, dtype=None):
    lin = linspace.meta(start, end, steps, device=device, dtype=dtypes.float32)
    out = clang.pow_(float(pyval(base)), lin)
    return clang.maybe_convert_to_dtype(out, dtypes.to_dtype(dtype) if dtype else dtypes.float32)


@torchsymbol(name="scalar_tensor")
def scalar_tensor(value, *, device=None, dtype=None):
    return clang.full((), pyval(value), device=device, dtype=dtype or dtypes.to_dtype(type(pyval(value))))


@torchsymbol(name="clone", method_names=("clone",))
def clone(a):
    return a


# matmul family (widened) ----------------------------------------------------


@torchsymbol(name="mm")
def mm(a, b):
    check(a.ndim == 2 and b.ndim == 2, lambda: "mm expects 2D tensors")
    return prims.matmul(a, b)


@torchsymbol(name="bmm")
def bmm(a, b):
    check(a.ndim == 3 and b.ndim == 3, lambda: "bmm expects 3D tensors")
    check(a.shape[0] == b.shape[0],
          lambda: f"bmm: batch sizes must match, got {a.shape[0]} and {b.shape[0]}")
    check(a.shape[2] == b.shape[1],
          lambda: f"bmm: cannot contract {tuple(a.shape)} with {tuple(b.shape)}")
    return prims.matmul(a, b)


@torchsymbol(name="mv", method_names=("mv",))
def mv(a, b):
    check(a.ndim == 2 and b.ndim == 1, lambda: "mv expects (2D, 1D)")
    return prims.matmul(a, b)


@torchsymbol(name="dot", method_names=("dot",))
def dot(a, b):
    check(a.ndim == 1 and b.ndim == 1, lambda: "dot expects 1D tensors")
    check(a.shape[0] == b.shape[0],
          lambda: f"dot: 1D tensors must have the same size, got {a.shape[0]} and {b.shape[0]}")
    return prims.matmul(a, b)


@torchsymbol(name="vdot", method_names=("vdot",))
def vdot(a, b):
    return prims.matmul(a, b)


@torchsymbol(name="kron", method_names=("kron",))
def kron(a, b):
    check(a.ndim == b.ndim, lambda: "kron: rank mismatch (pad with reshape first)")
    out = clang.mul(
        clang.reshape(a, tuple(x for s in a.shape for x in (s, 1))),
        clang.reshape(b, tuple(x for s in b.shape for x in (1, s))),
    )
    return clang.reshape(out, tuple(sa * sb for sa, sb in zip(a.shape, b.shape)))


@torchsymbol(name="tensordot", method_names=("tensordot",))
def tensordot(a, b, dims=2):
    if isinstance(dims, int):
        axes_a = list(builtins.range(a.ndim - dims, a.ndim))
        axes_b = list(builtins.range(dims))
    else:
        axes_a = [canonicalize_dim(a.ndim, pyval(d)) for d in dims[0]]
        axes_b = [canonicalize_dim(b.ndim, pyval(d)) for d in dims[1]]
    free_a = [i for i in builtins.range(a.ndim) if i not in axes_a]
    free_b = [i for i in builtins.range(b.ndim) if i not in axes_b]
    pa = clang.permute(a, free_a + axes_a)
    pb = clang.permute(b, axes_b + free_b)
    M = 1
    for i in free_a:
        M *= a.shape[i]
    K = 1
    for i in axes_a:
        K *= a.shape[i]
    N = 1
    for i in free_b:
        N *= b.shape[i]
    out = prims.matmul(clang.reshape(pa, (M, K)), clang.reshape(pb, (K, N)))
    return clang.reshape(out, tuple(a.shape[i] for i in free_a) + tuple(b.shape[i] for i in free_b))


@torchsymbol(name="cdist")
def cdist(x1, x2, p=2.0):
    """Pairwise distances (..., M, D) x (..., N, D) -> (..., M, N)."""
    p = pyval(p)
    if p == 2.0:
        # |x-y|^2 = |x|^2 + |y|^2 - 2 x·y — one MXU matmul instead of a broadcast blow-up
        x1n = clang.sum_(clang.mul(x1, x1), -1, True)
        x2n = clang.sum_(clang.mul(x2, x2), -1, True)
        cross = prims.matmul(x1, clang.matrix_transpose(x2))
        sq = clang.add(clang.sub(x1n, clang.mul(2.0, cross)), clang.matrix_transpose(x2n))
        return prims.sqrt(clang.maximum(sq, 0.0))
    d = clang.sub(clang.unsqueeze(x1, -2), clang.unsqueeze(x2, -3))
    return clang.pow_(clang.sum_(clang.pow_(prims.abs(d), p), -1, False), 1.0 / p)


@torchsymbol(name="addbmm", method_names=("addbmm",))
def addbmm(input, batch1, batch2, *, beta=1, alpha=1):
    out = clang.sum_(prims.matmul(batch1, batch2), 0, False)
    if pyval(alpha) != 1:
        out = clang.mul(out, alpha)
    if pyval(beta) != 0:
        out = clang.add(out, clang.mul(input, beta) if pyval(beta) != 1 else input)
    return out


@torchsymbol(name="addmv", method_names=("addmv",))
def addmv(input, mat, vec, *, beta=1, alpha=1):
    out = prims.matmul(mat, vec)
    if pyval(alpha) != 1:
        out = clang.mul(out, alpha)
    if pyval(beta) != 0:
        out = clang.add(out, clang.mul(input, beta) if pyval(beta) != 1 else input)
    return out


@torchsymbol(name="addr", method_names=("addr",))
def addr(input, vec1, vec2, *, beta=1, alpha=1):
    out = clang.mul(clang.unsqueeze(vec1, 1), clang.unsqueeze(vec2, 0))
    if pyval(alpha) != 1:
        out = clang.mul(out, alpha)
    if pyval(beta) != 0:
        out = clang.add(out, clang.mul(input, beta) if pyval(beta) != 1 else input)
    return out


# einsum ---------------------------------------------------------------------

from ..core.einsum_utils import expand_ellipsis as _einsum_expand_ellipsis_impl


def _einsum_expand_ellipsis(spec: str, operands):
    return _einsum_expand_ellipsis_impl(spec, [op.ndim for op in operands])


def _einsum_pair(s1, x, s2, y, keep):
    """Contract two einsum operands into one via a single MXU matmul.

    Size-1 dims broadcast against the other operand (ellipsis broadcasting):
    each shared index takes the max size and size-1 dims are expanded."""
    sizes = {}
    for ch, d in zip(s1, x.shape):
        sizes[ch] = d
    for ch, d in zip(s2, y.shape):
        sizes[ch] = builtins.max(sizes.get(ch, 1), d)
    set1, set2 = set(s1), set(s2)
    if builtins.any(x.shape[i] != sizes[ch] for i, ch in enumerate(s1)):
        x = clang.expand(x, tuple(sizes[ch] for ch in s1))
    if builtins.any(y.shape[i] != sizes[ch] for i, ch in enumerate(s2)):
        y = clang.expand(y, tuple(sizes[ch] for ch in s2))
    # pre-sum indices that appear in only one operand and are not needed later
    drop1 = [ch for ch in s1 if ch not in set2 and ch not in keep]
    if drop1:
        dims = tuple(s1.index(ch) for ch in drop1)
        x = clang.sum_(x, dims, False)
        s1 = "".join(ch for ch in s1 if ch not in drop1)
        set1 = set(s1)
    drop2 = [ch for ch in s2 if ch not in set1 and ch not in keep]
    if drop2:
        dims = tuple(s2.index(ch) for ch in drop2)
        y = clang.sum_(y, dims, False)
        s2 = "".join(ch for ch in s2 if ch not in drop2)
        set2 = set(s2)
    batch = [ch for ch in s1 if ch in set2 and ch in keep]
    contract = [ch for ch in s1 if ch in set2 and ch not in keep]
    mdims = [ch for ch in s1 if ch not in set2]
    ndims = [ch for ch in s2 if ch not in set1]
    # permute to (batch, m, contract) and (batch, contract, n)
    perm1 = [s1.index(ch) for ch in batch + mdims + contract]
    perm2 = [s2.index(ch) for ch in batch + contract + ndims]
    if perm1 != list(builtins.range(len(s1))):
        x = clang.permute(x, perm1)
    if perm2 != list(builtins.range(len(s2))):
        y = clang.permute(y, perm2)
    B = 1
    for ch in batch:
        B *= sizes[ch]
    M = 1
    for ch in mdims:
        M *= sizes[ch]
    K = 1
    for ch in contract:
        K *= sizes[ch]
    N = 1
    for ch in ndims:
        N *= sizes[ch]
    x2 = clang.reshape(x, (B, M, K))
    y2 = clang.reshape(y, (B, K, N))
    out = prims.matmul(x2, y2)
    out_spec = "".join(batch + mdims + ndims)
    out_shape = tuple(sizes[ch] for ch in out_spec)
    return out_spec, clang.reshape(out, out_shape)


@torchsymbol(name="einsum")
def einsum(equation, *operands):
    """General einsum, decomposed to transpose/reshape/matmul/sum prims so the
    MXU and existing grad rules are used (reference: thunder traces
    torch.einsum op-by-op; here decomposition is the TPU-native lowering).
    Falls back to the EINSUM prim for specs with repeated in-operand indices."""
    if len(operands) == 1 and isinstance(operands[0], (tuple, list)):
        operands = tuple(operands[0])
    equation = pyval(equation)
    in_specs, out_spec = _einsum_expand_ellipsis(equation, operands)
    # repeated index inside one operand (diagonal) -> prim fallback
    for sub in in_specs:
        if len(set(sub)) != len(sub):
            return prims.einsum(equation, *operands)
    if len(operands) == 1:
        s, x = in_specs[0], operands[0]
        drop = [ch for ch in s if ch not in out_spec]
        if drop:
            x = clang.sum_(x, tuple(s.index(ch) for ch in drop), False)
            s = "".join(ch for ch in s if ch in out_spec)
        perm = [s.index(ch) for ch in out_spec]
        return clang.permute(x, perm) if perm != list(builtins.range(len(s))) else x
    spec, acc = in_specs[0], operands[0]
    for i in builtins.range(1, len(operands)):
        keep = set(out_spec)
        for j in builtins.range(i + 1, len(operands)):
            keep |= set(in_specs[j])
        spec, acc = _einsum_pair(spec, acc, in_specs[i], operands[i], keep)
    drop = [ch for ch in spec if ch not in out_spec]
    if drop:
        acc = clang.sum_(acc, tuple(spec.index(ch) for ch in drop), False)
        spec = "".join(ch for ch in spec if ch in out_spec)
    perm = [spec.index(ch) for ch in out_spec]
    return clang.permute(acc, perm) if perm != list(builtins.range(len(spec))) else acc


# pooling (TPU-native: lowers to XLA ReduceWindow via the reduce_window prim) -


def _pool_args(kernel_size, stride, padding, n):
    ks = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(pyval(k) for k in kernel_size)
    st = ks if stride is None else ((stride,) * n if isinstance(stride, int) else tuple(pyval(s) for s in stride))
    pd = (padding,) * n if isinstance(padding, int) else tuple(pyval(p) for p in padding)
    return ks, st, pd


@torchsymbol(name="max_pool2d", id="torch.nn.functional.max_pool2d")
def max_pool2d(a, kernel_size, stride=None, padding=0):
    ks, st, pd = _pool_args(kernel_size, stride, padding, 2)
    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    return prims.reduce_window(a, window, strides, pads, op="max")


@torchsymbol(name="max_pool1d", id="torch.nn.functional.max_pool1d")
def max_pool1d(a, kernel_size, stride=None, padding=0):
    ks, st, pd = _pool_args(kernel_size, stride, padding, 1)
    return prims.reduce_window(a, (1, 1) + ks, (1, 1) + st, ((0, 0), (0, 0)) + tuple((p, p) for p in pd), op="max")


@torchsymbol(name="max_pool3d", id="torch.nn.functional.max_pool3d")
def max_pool3d(a, kernel_size, stride=None, padding=0):
    ks, st, pd = _pool_args(kernel_size, stride, padding, 3)
    return prims.reduce_window(a, (1, 1) + ks, (1, 1) + st, ((0, 0), (0, 0)) + tuple((p, p) for p in pd), op="max")


def _avg_pool(a, kernel_size, stride, padding, n, count_include_pad):
    ks, st, pd = _pool_args(kernel_size, stride, padding, n)
    check(builtins.all(k > 0 for k in ks),
          lambda: f"pooling kernel sizes must be positive, got {ks}")
    window = (1, 1) + ks
    strides = (1, 1) + st
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)
    s = prims.reduce_window(a, window, strides, pads, op="sum")
    if count_include_pad or builtins.all(p == 0 for p in pd):
        denom = 1.0
        for k in ks:
            denom *= k
        return clang.true_divide(s, float(denom))
    ones = clang.full_like(a, 1.0)
    counts = prims.reduce_window(ones, window, strides, pads, op="sum")
    return clang.true_divide(s, counts)


@torchsymbol(name="avg_pool2d", id="torch.nn.functional.avg_pool2d")
def avg_pool2d(a, kernel_size, stride=None, padding=0, count_include_pad=True):
    return _avg_pool(a, kernel_size, stride, padding, 2, count_include_pad)


@torchsymbol(name="avg_pool1d", id="torch.nn.functional.avg_pool1d")
def avg_pool1d(a, kernel_size, stride=None, padding=0, count_include_pad=True):
    return _avg_pool(a, kernel_size, stride, padding, 1, count_include_pad)


@torchsymbol(name="avg_pool3d", id="torch.nn.functional.avg_pool3d")
def avg_pool3d(a, kernel_size, stride=None, padding=0, count_include_pad=True):
    return _avg_pool(a, kernel_size, stride, padding, 3, count_include_pad)


@torchsymbol(name="adaptive_avg_pool2d", id="torch.nn.functional.adaptive_avg_pool2d")
def adaptive_avg_pool2d(a, output_size):
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(pyval(o) for o in output_size)
    H, W = a.shape[-2], a.shape[-1]
    check(H % oh == 0 and W % ow == 0, lambda: f"adaptive_avg_pool2d: {H}x{W} not divisible by {oh}x{ow}")
    return _avg_pool(a, (H // oh, W // ow), (H // oh, W // ow), 0, 2, True)


@torchsymbol(name="adaptive_max_pool2d", id="torch.nn.functional.adaptive_max_pool2d")
def adaptive_max_pool2d(a, output_size):
    oh, ow = (output_size, output_size) if isinstance(output_size, int) else tuple(pyval(o) for o in output_size)
    H, W = a.shape[-2], a.shape[-1]
    check(H % oh == 0 and W % ow == 0, lambda: f"adaptive_max_pool2d: {H}x{W} not divisible by {oh}x{ow}")
    return max_pool2d.meta(a, (H // oh, W // ow), (H // oh, W // ow), 0)


# convs (widened) ------------------------------------------------------------


@torchsymbol(name="conv3d", id="torch.nn.functional.conv3d")
def conv3d(a, weight, bias=None, stride=(1, 1, 1), padding=(0, 0, 0), dilation=(1, 1, 1), groups=1):
    stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    out = prims.convolution(a, weight, None, stride, padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0], 1, 1, 1)))
    return out


def _conv_transpose_nd(a, weight, bias, stride, padding, output_padding, dilation, groups, n):
    stride = (stride,) * n if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * n if isinstance(padding, int) else tuple(padding)
    output_padding = (output_padding,) * n if isinstance(output_padding, int) else tuple(output_padding)
    dilation = (dilation,) * n if isinstance(dilation, int) else tuple(dilation)
    out = prims.conv_transpose(a, weight, None, stride, padding, output_padding, dilation, groups)
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0]) + (1,) * n))
    return out


@torchsymbol(name="conv_transpose1d", id="torch.nn.functional.conv_transpose1d")
def conv_transpose1d(a, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1):
    return _conv_transpose_nd(a, weight, bias, stride, padding, output_padding, dilation, groups, 1)


@torchsymbol(name="conv_transpose2d", id="torch.nn.functional.conv_transpose2d")
def conv_transpose2d(a, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1):
    return _conv_transpose_nd(a, weight, bias, stride, padding, output_padding, dilation, groups, 2)


@torchsymbol(name="conv_transpose3d", id="torch.nn.functional.conv_transpose3d")
def conv_transpose3d(a, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1):
    return _conv_transpose_nd(a, weight, bias, stride, padding, output_padding, dilation, groups, 3)


# norms (widened) ------------------------------------------------------------


@torchsymbol(name="batch_norm", id="torch.nn.functional.batch_norm")
def batch_norm(a, running_mean=None, running_var=None, weight=None, bias=None,
               training=False, momentum=0.1, eps=1e-5):
    """Functional batch norm. In training mode batch statistics are used; the
    running-stat update is the caller's job (functional framework — the nn
    layer returns updated stats explicitly, unlike torch's in-place update)."""
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    if training or running_mean is None:
        dims = (0,) + tuple(builtins.range(2, a.ndim))
        m = clang.mean(compute, dims, keepdim=True)
        centered = clang.sub(compute, m)
        v = clang.mean(clang.mul(centered, centered), dims, keepdim=True)
    else:
        m = clang.reshape(running_mean, (1, running_mean.shape[0]) + (1,) * (a.ndim - 2))
        v = clang.reshape(running_var, (1, running_var.shape[0]) + (1,) * (a.ndim - 2))
        centered = clang.sub(compute, m)
    out = clang.mul(centered, prims.rsqrt(clang.add(v, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    if weight is not None:
        out = clang.mul(out, clang.reshape(weight, (1, weight.shape[0]) + (1,) * (a.ndim - 2)))
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, (1, bias.shape[0]) + (1,) * (a.ndim - 2)))
    return out


@torchsymbol(name="group_norm", id="torch.nn.functional.group_norm")
def group_norm(a, num_groups, weight=None, bias=None, eps=1e-5):
    N, C = a.shape[0], a.shape[1]
    G = pyval(num_groups)
    check(C % G == 0, lambda: f"group_norm: {C} channels not divisible by {G} groups")
    spatial = a.shape[2:]
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    grouped = clang.reshape(compute, (N, G, C // G) + spatial)
    dims = tuple(builtins.range(2, grouped.ndim))
    m = clang.mean(grouped, dims, keepdim=True)
    centered = clang.sub(grouped, m)
    v = clang.mean(clang.mul(centered, centered), dims, keepdim=True)
    out = clang.mul(centered, prims.rsqrt(clang.add(v, eps)))
    out = clang.reshape(out, a.shape)
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    view = (1, C) + (1,) * (a.ndim - 2)
    if weight is not None:
        out = clang.mul(out, clang.reshape(weight, view))
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, view))
    return out


@torchsymbol(name="instance_norm", id="torch.nn.functional.instance_norm")
def instance_norm(a, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.1, eps=1e-5):
    dims = tuple(builtins.range(2, a.ndim))
    compute = a if a.dtype == dtypes.float32 else clang.maybe_convert_to_dtype(a, dtypes.float32)
    m = clang.mean(compute, dims, keepdim=True)
    centered = clang.sub(compute, m)
    v = clang.mean(clang.mul(centered, centered), dims, keepdim=True)
    out = clang.mul(centered, prims.rsqrt(clang.add(v, eps)))
    out = clang.maybe_convert_to_dtype(out, a.dtype)
    view = (1, a.shape[1]) + (1,) * (a.ndim - 2)
    if weight is not None:
        out = clang.mul(out, clang.reshape(weight, view))
    if bias is not None:
        out = clang.add(out, clang.reshape(bias, view))
    return out


@torchsymbol(name="normalize", id="torch.nn.functional.normalize")
def normalize(a, p=2.0, dim=1, eps=1e-12):
    n = norm.meta(a, pyval(p), pyval(dim), True)
    return clang.true_divide(a, clang.maximum(n, eps))


@torchsymbol(name="local_response_norm", id="torch.nn.functional.local_response_norm")
def local_response_norm(a, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = clang.mul(a, a)
    n = pyval(size)
    pads = ((0, 0), ((n - 1) // 2, n // 2)) + ((0, 0),) * (a.ndim - 2)
    window = (1, n) + (1,) * (a.ndim - 2)
    strides = (1,) * a.ndim
    s = prims.reduce_window(sq, window, strides, pads, op="sum")
    div = clang.pow_(clang.add(k, clang.mul(alpha / n, s)), beta)
    return clang.true_divide(a, div)


# resampling -----------------------------------------------------------------


@torchsymbol(name="pixel_shuffle", id="torch.nn.functional.pixel_shuffle")
def pixel_shuffle(a, upscale_factor):
    r = pyval(upscale_factor)
    N, C, H, W = a.shape
    check(C % (r * r) == 0, lambda: f"pixel_shuffle: {C} % {r*r}")
    out = clang.reshape(a, (N, C // (r * r), r, r, H, W))
    out = clang.permute(out, (0, 1, 4, 2, 5, 3))
    return clang.reshape(out, (N, C // (r * r), H * r, W * r))


@torchsymbol(name="pixel_unshuffle", id="torch.nn.functional.pixel_unshuffle")
def pixel_unshuffle(a, downscale_factor):
    r = pyval(downscale_factor)
    N, C, H, W = a.shape
    if H % r != 0 or W % r != 0:
        raise RuntimeError(
            f"pixel_unshuffle: spatial dims ({H}, {W}) must be divisible by "
            f"downscale_factor {r}")
    out = clang.reshape(a, (N, C, H // r, r, W // r, r))
    out = clang.permute(out, (0, 1, 3, 5, 2, 4))
    return clang.reshape(out, (N, C * r * r, H // r, W // r))


@torchsymbol(name="interpolate", id="torch.nn.functional.interpolate")
def interpolate(a, size=None, scale_factor=None, mode="nearest"):
    """Static-shape interpolate: nearest / bilinear (align_corners=False)."""
    n_spatial = a.ndim - 2
    in_sp = a.shape[2:]
    if size is not None:
        out_sp = (size,) * n_spatial if isinstance(size, int) else tuple(pyval(s) for s in size)
    else:
        sf = (scale_factor,) * n_spatial if isinstance(scale_factor, (int, float)) else tuple(scale_factor)
        out_sp = tuple(int(s * f) for s, f in zip(in_sp, sf))
    if mode == "nearest":
        out = a
        for i, (si, so) in enumerate(zip(in_sp, out_sp)):
            dim = 2 + i
            idx_f = clang.mul(clang.add(prims.iota(so, dtype=dtypes.float32, device=a.device), 0.0), si / so)
            idx = clang.maybe_convert_to_dtype(prims.floor(idx_f), dtypes.int32)
            out = clang.take(out, idx, dim)
        return out
    check(mode in ("bilinear", "linear"), lambda: f"interpolate mode {mode} unsupported")
    out = a
    for i, (si, so) in enumerate(zip(in_sp, out_sp)):
        dim = 2 + i
        # align_corners=False source coordinates
        coord = clang.sub(clang.mul(clang.add(prims.iota(so, dtype=dtypes.float32, device=a.device), 0.5), si / so), 0.5)
        coord = clang.maximum(clang.minimum(coord, float(si - 1)), 0.0)
        lo_f = prims.floor(coord)
        w_hi = clang.sub(coord, lo_f)
        lo = clang.maybe_convert_to_dtype(lo_f, dtypes.int32)
        hi = clang.minimum(clang.add(lo, 1), si - 1)
        g_lo = clang.take(out, lo, dim)
        g_hi = clang.take(out, hi, dim)
        shape = [1] * out.ndim
        shape[dim] = so
        w = clang.reshape(w_hi, tuple(shape))
        out = clang.add(clang.mul(g_lo, clang.sub(1.0, w)), clang.mul(g_hi, w))
    return out


# distances ------------------------------------------------------------------


@torchsymbol(name="cosine_similarity", id="torch.nn.functional.cosine_similarity")
def cosine_similarity(x1, x2, dim=1, eps=1e-8):
    num = clang.sum_(clang.mul(x1, x2), dim, False)
    n1 = prims.sqrt(clang.sum_(clang.mul(x1, x1), dim, False))
    n2 = prims.sqrt(clang.sum_(clang.mul(x2, x2), dim, False))
    return clang.true_divide(num, clang.maximum(clang.mul(n1, n2), eps))


@torchsymbol(name="pairwise_distance", id="torch.nn.functional.pairwise_distance")
def pairwise_distance(x1, x2, p=2.0, eps=1e-6):
    d = clang.add(clang.sub(x1, x2), eps)
    return norm.meta(d, pyval(p), -1, False)


# losses (widened) -----------------------------------------------------------


def _apply_reduction(loss, reduction):
    if reduction == "none":
        return loss
    if reduction == "sum":
        return clang.sum_(loss)
    return clang.mean(loss)


@torchsymbol(name="l1_loss", id="torch.nn.functional.l1_loss")
def l1_loss(input, target, reduction="mean"):
    return _apply_reduction(prims.abs(clang.sub(input, target)), reduction)


@torchsymbol(name="smooth_l1_loss", id="torch.nn.functional.smooth_l1_loss")
def smooth_l1_loss(input, target, reduction="mean", beta=1.0):
    d = clang.sub(input, target)
    ad = prims.abs(d)
    quad = clang.true_divide(clang.mul(clang.mul(d, d), 0.5), beta)
    lin = clang.sub(ad, 0.5 * beta)
    return _apply_reduction(clang.where(clang.lt(ad, beta), quad, lin), reduction)


@torchsymbol(name="huber_loss", id="torch.nn.functional.huber_loss")
def huber_loss(input, target, reduction="mean", delta=1.0):
    d = clang.sub(input, target)
    ad = prims.abs(d)
    quad = clang.mul(clang.mul(d, d), 0.5)
    lin = clang.mul(delta, clang.sub(ad, 0.5 * delta))
    return _apply_reduction(clang.where(clang.lt(ad, delta), quad, lin), reduction)


@torchsymbol(name="binary_cross_entropy", id="torch.nn.functional.binary_cross_entropy")
def binary_cross_entropy(input, target, weight=None, reduction="mean"):
    eps = 1e-12
    loss = prims.neg(clang.add(
        clang.mul(target, prims.log(clang.maximum(input, eps))),
        clang.mul(clang.sub(1.0, target), prims.log(clang.maximum(clang.sub(1.0, input), eps))),
    ))
    if weight is not None:
        loss = clang.mul(loss, weight)
    return _apply_reduction(loss, reduction)


@torchsymbol(name="binary_cross_entropy_with_logits", id="torch.nn.functional.binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(input, target, weight=None, pos_weight=None, reduction="mean"):
    # max(x,0) - x*z + log(1 + exp(-|x|)) — numerically stable
    neg_abs = prims.neg(prims.abs(input))
    loss = clang.add(clang.sub(clang.maximum(input, 0.0), clang.mul(input, target)),
                     prims.log1p(prims.exp(neg_abs)))
    if pos_weight is not None:
        # general form: (1 + (p-1) z) * softplus(-x) + (1-z) x for x>0 branch — use direct formula
        log_sig = prims.neg(clang.add(clang.maximum(prims.neg(input), 0.0),
                                      prims.log1p(prims.exp(neg_abs))))
        log_sig_neg = clang.sub(log_sig, input)
        loss = prims.neg(clang.add(clang.mul(clang.mul(target, pos_weight), log_sig),
                                   clang.mul(clang.sub(1.0, target), log_sig_neg)))
    if weight is not None:
        loss = clang.mul(loss, weight)
    return _apply_reduction(loss, reduction)


@torchsymbol(name="kl_div", id="torch.nn.functional.kl_div")
def kl_div(input, target, reduction="mean", log_target=False):
    if log_target:
        loss = clang.mul(prims.exp(target), clang.sub(target, input))
    else:
        eps_t = clang.maximum(target, 1e-12)
        loss = clang.mul(target, clang.sub(prims.log(eps_t), input))
    if reduction == "batchmean":
        return clang.true_divide(clang.sum_(loss), input.shape[0])
    return _apply_reduction(loss, reduction)


@torchsymbol(name="soft_margin_loss", id="torch.nn.functional.soft_margin_loss")
def soft_margin_loss(input, target, reduction="mean"):
    return _apply_reduction(prims.log1p(prims.exp(prims.neg(clang.mul(input, target)))), reduction)


@torchsymbol(name="hinge_embedding_loss", id="torch.nn.functional.hinge_embedding_loss")
def hinge_embedding_loss(input, target, margin=1.0, reduction="mean"):
    pos = input
    neg = clang.maximum(clang.sub(margin, input), 0.0)
    loss = clang.where(clang.gt(target, 0), pos, neg)
    return _apply_reduction(loss, reduction)


@torchsymbol(name="margin_ranking_loss", id="torch.nn.functional.margin_ranking_loss")
def margin_ranking_loss(input1, input2, target, margin=0.0, reduction="mean"):
    loss = clang.maximum(clang.add(clang.mul(prims.neg(target), clang.sub(input1, input2)), margin), 0.0)
    return _apply_reduction(loss, reduction)


# im2col family --------------------------------------------------------------


def _pair(v):
    """int-or-(a, b) normalization shared by the im2col family."""
    if isinstance(v, (int, NumberProxy)):
        n = int(pyval(v))
        return n, n
    a, b = v
    return int(pyval(a)), int(pyval(b))


@torchsymbol(name="unfold", id="torch.nn.functional.unfold")
def unfold(a, kernel_size, dilation=1, padding=0, stride=1):
    """F.unfold (im2col): (N, C, H, W) -> (N, C*kh*kw, L). Decomposed into
    kh*kw strided slices (static unroll; XLA fuses into one gather)."""
    kh, kw = _pair(kernel_size)
    dh, dw = _pair(dilation)
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    N, C, H, W = a.shape
    if ph or pw:
        a = clang.pad(a, 0.0, [(0, 0, 0), (0, 0, 0), (ph, ph, 0), (pw, pw, 0)])
        H, W = H + 2 * ph, W + 2 * pw
    oh = (H - (kh - 1) * dh - 1) // sh + 1
    ow = (W - (kw - 1) * dw - 1) // sw + 1
    patches = []
    for i in builtins.range(kh):
        for j in builtins.range(kw):
            r0, c0 = i * dh, j * dw
            sl = prims.slice_prim(a, (0, 0, r0, c0),
                                  (N, C, r0 + (oh - 1) * sh + 1, c0 + (ow - 1) * sw + 1),
                                  (1, 1, sh, sw))
            patches.append(clang.reshape(sl, (N, C, 1, oh * ow)))
    out = clang.cat(patches, 2)  # (N, C, kh*kw, L)
    return clang.reshape(out, (N, C * kh * kw, oh * ow))


@torchsymbol(name="fold", id="torch.nn.functional.fold")
def fold(a, output_size, kernel_size, dilation=1, padding=0, stride=1):
    """F.fold (col2im): (N, C*kh*kw, L) -> (N, C, H, W), overlaps summed."""
    H, W = _pair(output_size)
    kh, kw = _pair(kernel_size)
    check(a.ndim == 3 and a.shape[1] % (kh * kw) == 0,
          lambda: f"fold expects (N, C*kh*kw, L) input; dim 1 of {tuple(a.shape)} "
                  f"is not divisible by the kernel block size {kh*kw}")
    dh, dw = _pair(dilation)
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    N = a.shape[0]
    C = a.shape[1] // (kh * kw)
    Hp, Wp = H + 2 * ph, W + 2 * pw
    oh = (Hp - (kh - 1) * dh - 1) // sh + 1
    ow = (Wp - (kw - 1) * dw - 1) // sw + 1
    cols = clang.reshape(a, (N, C, kh * kw, oh, ow))
    out = clang.full((N, C, Hp, Wp), 0.0, dtype=a.dtype, device=a.device)
    # scatter each kernel position back with stride-interior padding
    for i in builtins.range(kh):
        for j in builtins.range(kw):
            idx = i * kw + j
            piece = clang.squeeze(clang.slice_in_dim(cols, idx, idx + 1, 2), (2,))  # (N,C,oh,ow)
            r0, c0 = i * dh, j * dw
            expanded = clang.pad(piece, 0.0, [
                (0, 0, 0), (0, 0, 0),
                (r0, Hp - r0 - ((oh - 1) * sh + 1), sh - 1),
                (c0, Wp - c0 - ((ow - 1) * sw + 1), sw - 1),
            ])
            out = clang.add(out, expanded)
    if ph or pw:
        out = prims.slice_prim(out, (0, 0, ph, pw), (N, C, ph + H, pw + W), (1, 1, 1, 1))
    return out


@torchsymbol(name="tensor_unfold", method_names=("unfold",))
def tensor_unfold(a, dim, size, step):
    """Tensor.unfold: sliding windows of `size` every `step` along dim."""
    dim = canonicalize_dim(a.ndim, pyval(dim))
    size, step = pyval(size), pyval(step)
    n = (a.shape[dim] - size) // step + 1
    slices = []
    for w in builtins.range(n):
        sl = clang.slice_in_dim(a, w * step, w * step + size, dim)
        slices.append(clang.unsqueeze(sl, dim))
    out = clang.cat(slices, dim)  # windows at dim, window content at dim+1
    # torch puts the window content LAST
    return clang.movedim(out, dim + 1, out.ndim - 1) if dim + 1 != out.ndim - 1 else out


# attention / embedding ------------------------------------------------------


@torchsymbol(name="embedding_bag", id="torch.nn.functional.embedding_bag")
def embedding_bag(indices, weight, offsets=None, mode="mean"):
    """2D-input form: (B, L) indices -> (B, D) pooled embeddings."""
    check(indices.ndim == 2, lambda: "embedding_bag supports the 2D (B, L) input form")
    check(offsets is None, lambda: "offsets is only valid with 1D indices (torch semantics); "
                                   "the 2D form bags along dim 1")
    check(mode in ("sum", "max", "mean"), lambda: f"embedding_bag: unknown mode {mode!r}")
    emb = prims.embedding(indices, weight)  # (B, L, D)
    if mode == "sum":
        return clang.sum_(emb, 1, False)
    if mode == "max":
        return clang.amax(emb, 1, False)
    return clang.mean(emb, 1, False)


@torchsymbol(name="multi_head_attention_forward", id="thunder_tpu.multi_head_attention")
def multi_head_attention_forward(query, key, value, num_heads, in_proj_weight, in_proj_bias=None,
                                 out_proj_weight=None, out_proj_bias=None, is_causal=False):
    """Packed-projection MHA, batch-first (B, T, E) -> (B, T, E).

    Deliberately NOT registered under the torch.nn.functional id: torch's
    function is seq-first, takes embed_dim_to_check before num_heads, and
    returns (output, weights) — binding this simplified form there would
    silently misinterpret arguments."""
    B, Tq, E = query.shape
    H = pyval(num_heads)
    hd = E // H
    wq = clang.slice_in_dim(in_proj_weight, 0, E, 0)
    wk = clang.slice_in_dim(in_proj_weight, E, 2 * E, 0)
    wv = clang.slice_in_dim(in_proj_weight, 2 * E, 3 * E, 0)
    q = prims.linear(query, wq, None)
    k = prims.linear(key, wk, None)
    v = prims.linear(value, wv, None)
    if in_proj_bias is not None:
        q = clang.add(q, clang.slice_in_dim(in_proj_bias, 0, E, 0))
        k = clang.add(k, clang.slice_in_dim(in_proj_bias, E, 2 * E, 0))
        v = clang.add(v, clang.slice_in_dim(in_proj_bias, 2 * E, 3 * E, 0))

    def split_heads(t):
        Bt, Tt, _ = t.shape
        return clang.transpose(clang.reshape(t, (Bt, Tt, H, hd)), 1, 2)

    o = sdpa(split_heads(q), split_heads(k), split_heads(v), is_causal=is_causal)
    o = clang.reshape(clang.transpose(o, 1, 2), (B, Tq, E))
    if out_proj_weight is not None:
        o = prims.linear(o, out_proj_weight, None)
        if out_proj_bias is not None:
            o = clang.add(o, out_proj_bias)
    return o


@torchsymbol(name="gumbel_softmax", id="torch.nn.functional.gumbel_softmax")
def gumbel_softmax(logits, tau=1.0, hard=False, dim=-1, *, key=None):
    check(key is not None, lambda: "gumbel_softmax requires an rng key (key=)")
    u = prims.uniform(logits.shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=logits.device)
    eps = 1e-10
    g = prims.neg(prims.log(clang.add(prims.neg(prims.log(clang.add(u, eps))), eps)))
    y = softmax.meta(clang.true_divide(clang.add(logits, g), tau), dim)
    if hard:
        idx = clang.argmax(y, dim, True)
        # straight-through: hard one-hot forward, soft gradient
        oh = scatter(clang.full_like(y, 0.0), dim, idx, 1.0)
        return clang.add(clang.sub(oh, prims.stop_gradient(y)), y)
    return y


# pooling / shuffle ----------------------------------------------------------


@torchsymbol(name="lp_pool2d", id="torch.nn.functional.lp_pool2d")
def lp_pool2d(a, norm_type, kernel_size, stride=None):
    p = float(pyval(norm_type))
    ks, st, _ = _pool_args(kernel_size, stride, 0, 2)
    # torch semantics: sum(x^p)^(1/p) with NO abs — odd p on negative sums
    # yields NaN exactly like torch does
    powed = clang.pow_(a, p)
    s = prims.reduce_window(powed, (1, 1) + ks, (1, 1) + st, ((0, 0),) * 4, op="sum")
    return clang.pow_(s, 1.0 / p)


@torchsymbol(name="channel_shuffle", id="torch.nn.functional.channel_shuffle")
def channel_shuffle(a, groups):
    g = pyval(groups)
    N, C = a.shape[0], a.shape[1]
    rest = a.shape[2:]
    out = clang.reshape(a, (N, g, C // g) + rest)
    out = clang.transpose(out, 1, 2)
    return clang.reshape(out, (N, C) + rest)


@torchsymbol(name="dropout2d", id="torch.nn.functional.dropout2d")
def dropout2d(a, p=0.5, training=True, *, key=None):
    """Channel-wise dropout for (N, C, H, W)."""
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "dropout2d in training mode requires an rng key (key=)")
    keep = 1.0 - p
    mask_shape = a.shape[:2] + (1,) * (a.ndim - 2)
    mask = clang.lt(prims.uniform(mask_shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    mask = clang.expand_to(clang.maybe_convert_to_dtype(mask, a.dtype), a.shape)
    return clang.mul(clang.mul(a, mask), 1.0 / keep)


@torchsymbol(name="dropout1d", id="torch.nn.functional.dropout1d")
def dropout1d(a, p=0.5, training=True, *, key=None):
    """Channel-wise dropout for (N, C, L) / (C, L)."""
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "dropout1d in training mode requires an rng key (key=)")
    keep = 1.0 - p
    nch = 2 if a.ndim == 3 else 1
    mask_shape = a.shape[:nch] + (1,) * (a.ndim - nch)
    mask = clang.lt(prims.uniform(mask_shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    mask = clang.expand_to(clang.maybe_convert_to_dtype(mask, a.dtype), a.shape)
    return clang.mul(clang.mul(a, mask), 1.0 / keep)


@torchsymbol(name="dropout3d", id="torch.nn.functional.dropout3d")
def dropout3d(a, p=0.5, training=True, *, key=None):
    """Channel-wise dropout for (N, C, D, H, W) / unbatched (C, D, H, W)."""
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "dropout3d in training mode requires an rng key (key=)")
    keep = 1.0 - p
    nch = 2 if a.ndim == 5 else 1  # torch: 4-D input is unbatched (C, D, H, W)
    mask_shape = a.shape[:nch] + (1,) * (a.ndim - nch)
    mask = clang.lt(prims.uniform(mask_shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    mask = clang.expand_to(clang.maybe_convert_to_dtype(mask, a.dtype), a.shape)
    return clang.mul(clang.mul(a, mask), 1.0 / keep)


@torchsymbol(name="feature_dropout", id="torch.nn.functional.feature_dropout")
def feature_dropout(a, p=0.5, training=True, *, key=None):
    """Channel-wise for >=3-D input; element-wise for 2-D (torch semantics)."""
    if a.ndim >= 4:
        return dropout2d.meta(a, p, training, key=key)
    if a.ndim == 3:
        return dropout1d.meta(a, p, training, key=key)
    return dropout.meta(a, p, training, key=key)


@torchsymbol(name="alpha_dropout", id="torch.nn.functional.alpha_dropout")
def alpha_dropout(a, p=0.5, training=True, *, key=None):
    """SELU-preserving dropout (torch semantics: keeps self-normalizing stats)."""
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "alpha_dropout in training mode requires an rng key (key=)")
    alpha_prime = -1.7580993408473766
    keep = 1.0 - p
    mask = clang.lt(prims.uniform(a.shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    A = (keep + alpha_prime * alpha_prime * keep * (1 - keep)) ** -0.5
    Bc = -A * alpha_prime * (1 - keep)
    dropped = clang.where(mask, a, clang.full_like(a, alpha_prime))
    return clang.add(clang.mul(dropped, A), Bc)


@torchsymbol(name="feature_alpha_dropout", id="torch.nn.functional.feature_alpha_dropout")
def feature_alpha_dropout(a, p=0.5, training=True, *, key=None):
    """Alpha dropout with a per-channel mask (torch semantics)."""
    if not training or p == 0.0:
        return a
    check(key is not None, lambda: "feature_alpha_dropout in training mode requires an rng key (key=)")
    alpha_prime = -1.7580993408473766
    keep = 1.0 - p
    mask_shape = a.shape[:2] + (1,) * (a.ndim - 2)
    mask = clang.lt(prims.uniform(mask_shape, 0.0, 1.0, key=key, dtype=dtypes.float32, device=a.device), keep)
    mask = clang.expand_to(mask, a.shape)
    A = (keep + alpha_prime * alpha_prime * keep * (1 - keep)) ** -0.5
    Bc = -A * alpha_prime * (1 - keep)
    dropped = clang.where(mask, a, clang.full_like(a, alpha_prime))
    return clang.add(clang.mul(dropped, A), Bc)


# losses (second wave) -------------------------------------------------------


@torchsymbol(name="triplet_margin_loss", id="torch.nn.functional.triplet_margin_loss")
def triplet_margin_loss(anchor, positive, negative, margin=1.0, p=2.0, reduction="mean"):
    dp = norm.meta(clang.sub(anchor, positive), pyval(p), -1, False)
    dn = norm.meta(clang.sub(anchor, negative), pyval(p), -1, False)
    loss = clang.maximum(clang.add(clang.sub(dp, dn), margin), 0.0)
    return _apply_reduction(loss, reduction)


@torchsymbol(name="cosine_embedding_loss", id="torch.nn.functional.cosine_embedding_loss")
def cosine_embedding_loss(x1, x2, target, margin=0.0, reduction="mean"):
    cos = cosine_similarity.meta(x1, x2, -1)
    pos = clang.sub(1.0, cos)
    neg = clang.maximum(clang.sub(cos, margin), 0.0)
    loss = clang.where(clang.gt(target, 0), pos, neg)
    return _apply_reduction(loss, reduction)


@torchsymbol(name="multilabel_soft_margin_loss", id="torch.nn.functional.multilabel_soft_margin_loss")
def multilabel_soft_margin_loss(input, target, reduction="mean"):
    neg_abs = prims.neg(prims.abs(input))
    log_sig = prims.neg(clang.add(clang.maximum(prims.neg(input), 0.0), prims.log1p(prims.exp(neg_abs))))
    log_sig_neg = clang.sub(log_sig, input)
    loss = prims.neg(clang.add(clang.mul(target, log_sig), clang.mul(clang.sub(1.0, target), log_sig_neg)))
    loss = clang.mean(loss, -1, False)
    return _apply_reduction(loss, reduction)


# ---------------------------------------------------------------------------
# wave 4: reference-parity aliases & small composites
# (reference thunder/torch/__init__.py long tail)
# ---------------------------------------------------------------------------


@torchsymbol(name="addcmul", method_names=("addcmul",))
def addcmul(a, t1, t2, *, value=1.0):
    return clang.add(a, clang.mul(value, clang.mul(t1, t2)))


@torchsymbol(name="addcdiv", method_names=("addcdiv",))
def addcdiv(a, t1, t2, *, value=1.0):
    return clang.add(a, clang.mul(value, clang.true_divide(t1, t2)))


@torchsymbol(name="lerp", method_names=("lerp",))
def lerp(start, end, weight):
    return clang.lerp(start, end, weight)


@torchsymbol(name="ldexp", method_names=("ldexp",))
def ldexp(a, other):
    # a * 2**other, computed in float (torch promotes integer inputs)
    a = clang.ensure_proxy(a)
    if not a.dtype.is_float:
        a = clang.maybe_convert_to_dtype(a, dtypes.float32)
    other = clang.maybe_convert_to_dtype(clang.ensure_proxy(other), a.dtype) \
        if isinstance(other, TensorProxy) else other
    return clang.mul(a, clang.exp2(other))


@torchsymbol(name="multi_dot")
def multi_dot(tensors):
    out = tensors[0]
    for t in tensors[1:]:
        out = matmul(out, t)
    return out


@torchsymbol(name="view_as", method_names=("view_as",))
def view_as(a, other):
    return reshape(a, tuple(other.shape))


@torchsymbol(name="true_divide", method_names=("true_divide",))
def true_divide(a, b):
    return clang.true_divide(a, b)


@torchsymbol(name="real", method_names=("real",))
def real(a):
    return clang.real(a)


@torchsymbol(name="imag", method_names=("imag",))
def imag(a):
    return clang.imag(a)


@torchsymbol(name="polar")
def polar(r, theta):
    from .auto_register import get_auto_symbol

    return get_auto_symbol("polar")(r, theta)


@torchsymbol(name="view_as_real", method_names=("view_as_real",))
def view_as_real(a):
    from .auto_register import get_auto_symbol

    return get_auto_symbol("view_as_real")(a)


@torchsymbol(name="view_as_complex", method_names=("view_as_complex",))
def view_as_complex(a):
    from .auto_register import get_auto_symbol

    return get_auto_symbol("view_as_complex")(a)


@torchsymbol(name="polygamma", method_names=("polygamma",))
def polygamma(n, a):
    from .auto_register import get_auto_symbol

    return get_auto_symbol("polygamma")(n, a)


@torchsymbol(name="zeta")
def zeta(a, b):
    return clang.zeta(a, b)


@torchsymbol(name="frexp", method_names=("frexp",))
def frexp(a):
    from .auto_register import get_auto_symbol

    return get_auto_symbol("frexp")(a)


@torchsymbol(name="index_copy", method_names=("index_copy",))
def index_copy(a, dim, index, src):
    return clang.index_copy(a, dim, index, src)


@torchsymbol(name="index_put", method_names=("index_put",))
def index_put(a, indices, values, accumulate=False):
    return clang.index_put(a, tuple(indices), values, accumulate)


@torchsymbol(name="uniform")
def uniform(shape, minval=0.0, maxval=1.0, *, dtype=dtypes.float32, device=None, key=None):
    return clang.uniform(shape, minval, maxval, dtype=dtype, device=device, key=key)


@torchsymbol(name="uniform_like")
def uniform_like(a, minval=0.0, maxval=1.0, *, key=None):
    return clang.uniform_like(a, minval, maxval, key=key)


# metadata predicates (trace-time constants, reference torch/__init__.py
# is_floating_point/is_complex/numel/dim family)
def is_floating_point(a) -> bool:
    return a.dtype.is_float


def is_complex(a) -> bool:
    return a.dtype.is_complex


def is_cuda(a) -> bool:
    return False


def is_cpu(a) -> bool:
    return True


def is_nested(a) -> bool:
    return False


def numel(a) -> int:
    return a.numel


def dim(a) -> int:
    return a.ndim


def sym_max(a, b):
    return builtins.max(pyval(a) if isinstance(a, NumberProxy) else a,
                        pyval(b) if isinstance(b, NumberProxy) else b)


def sym_min(a, b):
    return builtins.min(pyval(a) if isinstance(a, NumberProxy) else a,
                        pyval(b) if isinstance(b, NumberProxy) else b)


@torchsymbol(name="long", method_names=("long",))
def long(a):
    return clang.maybe_convert_to_dtype(a, dtypes.int64)


@torchsymbol(name="tensor")
def tensor(seq, *, dtype=None, device=None):
    if isinstance(seq, (int, float, bool, NumberProxy)):
        seq = [seq]
        out = clang.tensor_from_sequence(seq, dtype=dtype, device=device)
        return clang.squeeze(out, 0)
    return clang.tensor_from_sequence(seq, dtype=dtype, device=device)


# ---------------------------------------------------------------------------
# reference @torchsymbol parity stragglers (LTORCH_COVERAGE.md maps every
# reference name; these close the genuinely-missing tail — reference
# thunder/torch/__init__.py:153)
# ---------------------------------------------------------------------------


@torchsymbol(name="view", id="torch.Tensor.view")
def view(a, *shape):
    """torch.Tensor.view — under XLA every array is logically contiguous and
    reshape is layout-free, so view IS reshape (also registered as the
    ``view`` tensor method via ``reshape``)."""
    return reshape(a, *shape)


@torchsymbol(name="item", method_names=("item",), id="torch.Tensor.item")
def item(a):
    """Tensor.item() -> NumberProxy (a DEVICE_SYNC_OP prim: forces a host
    read at execution, never fuses). The value is unbacked at trace time, so
    it can be RETURNED but not branched/computed on inside the traced
    program — same contract as the reference's data-dependent item."""
    return prims.item(a)


@torchsymbol(name="exponential", method_names=("exponential",))
def exponential(a, lambd=1.0, *, key=None):
    """Key-accepting exponential sampler (torch's Tensor.exponential_ is a
    stateful-RNG op; the stateless variant follows the dropout/bernoulli
    key= convention): inverse-CDF -log(1-u)/lambd."""
    check(key is not None, lambda: "exponential requires an rng key (key=)")
    check(pyval(lambd) > 0, lambda: f"exponential rate must be positive, got {lambd}")
    u = prims.uniform(a.shape, 0.0, 1.0, key=key, device=a.device, dtype=dtypes.float32)
    out = clang.true_divide(prims.neg(prims.log1p(prims.neg(u))), lambd)
    return clang.maybe_convert_to_dtype(out, a.dtype)


@torchsymbol(name="scaled_mm", id="torch._scaled_mm")
def scaled_mm(a, b, scale_a, scale_b, bias=None, out_dtype=None):
    """torch._scaled_mm: fp8 matmul with per-tensor dequant scales. The fp8
    executor claims this pattern when generated by the fp8 transform; this
    symbol is the direct user entry."""
    af = clang.mul(clang.maybe_convert_to_dtype(a, dtypes.float32), scale_a)
    bf = clang.mul(clang.maybe_convert_to_dtype(b, dtypes.float32), scale_b)
    out = prims.matmul(af, bf)
    if bias is not None:
        out = clang.add(out, bias)
    if out_dtype is not None:
        out = clang.maybe_convert_to_dtype(out, dtypes.to_dtype(out_dtype))
    return out


@torchsymbol(name="torch_type", method_names=("type",), id="torch.Tensor.type")
def torch_type(a, dtype=None):
    """Tensor.type(dtype): dtype cast. The zero-arg form returns a host
    string (metadata, resolved by the interop frontend, not traced)."""
    check(dtype is not None,
          lambda: "type() without arguments is host metadata; read .dtype instead")
    return clang.maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))


@torchsymbol(name="log_softmax_backward", id="torch.ops.aten._log_softmax_backward_data")
def log_softmax_backward(g, output, dim, input_dtype=None):
    """aten::_log_softmax_backward_data: dx = g - exp(out) * sum(g, dim)."""
    soft = prims.exp(clang.maybe_convert_to_dtype(output, dtypes.float32))
    gf = clang.maybe_convert_to_dtype(g, dtypes.float32)
    out = clang.sub(gf, clang.mul(soft, clang.sum_(gf, pyval(dim), keepdim=True)))
    return clang.maybe_convert_to_dtype(
        out, dtypes.to_dtype(input_dtype) if input_dtype is not None else g.dtype)


@torchsymbol(name="embedding_backward", id="torch.ops.aten.embedding_backward")
def embedding_backward(g, indices, num_weights, padding_idx=-1,
                       scale_grad_by_freq=False, sparse=False):
    """aten::embedding_backward: scatter-add of output grads into a
    (num_weights, D) zero table (dense; sparse grads have no XLA analog)."""
    check(not pyval(scale_grad_by_freq),
          lambda: "embedding_backward: scale_grad_by_freq is a host-side "
                  "frequency count; run it outside the traced region")
    D = g.shape[-1]
    n = 1
    for d in indices.shape:
        n *= pyval(d)
    gf = clang.reshape(g, (n, D))
    idx = clang.reshape(indices, (n,))
    pad = pyval(padding_idx)
    if pad >= 0:
        keep = clang.ne(idx, pad)
        gf = clang.mul(gf, clang.unsqueeze(clang.maybe_convert_to_dtype(keep, gf.dtype), 1))
    table = clang.full((pyval(num_weights), D), 0.0, dtype=gf.dtype, device=g.device)
    return clang.index_add(table, idx, gf, 0)


@torchsymbol(name="nll_loss_backward", id="torch.ops.aten.nll_loss_backward")
def nll_loss_backward(g, log_probs, target, weight=None, reduction="mean",
                      ignore_index=-100, total_weight=None):
    """aten::nll_loss_backward: d nll / d log_probs is -w one_hot(target),
    normalized per the reduction (mean divides by the valid-weight sum the
    forward used, passed back as total_weight)."""
    C = log_probs.shape[1]
    valid = clang.ne(target, ignore_index)
    safe_tgt = clang.where(valid, target, clang.full_like(target, 0))
    oh = clang.maybe_convert_to_dtype(one_hot(safe_tgt, C), log_probs.dtype)
    if weight is not None:
        w = clang.take(weight, safe_tgt, 0)
    else:
        w = clang.maybe_convert_to_dtype(valid, log_probs.dtype)
    wv = clang.mul(w, clang.maybe_convert_to_dtype(valid, log_probs.dtype))
    grad = prims.neg(clang.mul(oh, clang.unsqueeze(wv, 1)))
    if reduction == "none":
        return clang.mul(grad, clang.unsqueeze(g, 1))
    if reduction == "sum":
        return clang.mul(grad, g)
    denom = total_weight if total_weight is not None else clang.sum_(wv)
    return clang.true_divide(clang.mul(grad, g), denom)


@torchsymbol(name="adaptive_avg_pool2d_backward", id="torch.ops.aten._adaptive_avg_pool2d_backward")
def adaptive_avg_pool2d_backward(g, a):
    """aten::_adaptive_avg_pool2d_backward for the divisible-window case the
    forward supports: each output grad spreads evenly over its kh x kw
    window."""
    H, W = a.shape[-2], a.shape[-1]
    oh, ow = g.shape[-2], g.shape[-1]
    check(H % oh == 0 and W % ow == 0,
          lambda: f"adaptive_avg_pool2d_backward: {H}x{W} not divisible by {oh}x{ow}")
    kh, kw = H // oh, W // ow
    lead = tuple(g.shape[:-2])
    scaled = clang.true_divide(g, float(kh * kw))
    expanded = clang.reshape(scaled, lead + (oh, 1, ow, 1))
    nd = len(lead)
    bcast = prims.broadcast_in_dim(
        expanded, lead + (oh, kh, ow, kw),
        tuple(range(nd)) + (nd, nd + 1, nd + 2, nd + 3))
    return clang.reshape(bcast, lead + (H, W))


@torchsymbol(name="copy", method_names=("copy",))
def copy(a, b):
    """Out-of-place base of Tensor.copy_ (the interop frontend's generic
    in-place handling strips the underscore, runs this, and rebinds the
    receiver): b broadcast to a's shape and cast to a's dtype."""
    return clang.maybe_convert_to_dtype(clang.expand(b, a.shape), a.dtype)
