"""NumPy-flavored operation namespace: a demonstration second frontend language.

Counterpart of reference thunder/numpy/__init__.py:19 (npsymbol): the same
trace IR can host multiple user-facing op languages. Ops here follow numpy
naming/semantics (e.g. ``np.add(x, y)``, ``amax`` with ``axis=``/``keepdims=``)
but record the same clang/prims bsyms as ltorch, so every transform and
executor applies unchanged. Usage::

    import thunder_tpu as tt
    from thunder_tpu.ops import numpy_lang as tnp

    def f(x, y):
        return tnp.sum(tnp.multiply(x, y), axis=-1)

    cf = tt.jit(f)
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..core import dtypes, prims
from ..core.symbol import OpTags, Symbol
from . import clang

_np_symbols: dict[str, Symbol] = {}


def npsymbol(*, name: str, id: str | None = None, tags=()):
    """Create a numpy-language composite Symbol (reference thunder/numpy/__init__.py:19)."""

    def decorator(meta):
        sym = Symbol(name, meta, id=id or f"numpy.{name}", module="tnp", tags=tags)
        _np_symbols[sym.id] = sym
        return sym

    return decorator


def get_symbol(id: str) -> Symbol:
    return _np_symbols[id]


# -- elementwise binary --


@npsymbol(name="add")
def add(x1, x2):
    return clang.add(x1, x2)


@npsymbol(name="subtract")
def subtract(x1, x2):
    return clang.sub(x1, x2)


@npsymbol(name="multiply")
def multiply(x1, x2):
    return clang.mul(x1, x2)


@npsymbol(name="divide")
def divide(x1, x2):
    return clang.true_divide(x1, x2)


@npsymbol(name="power")
def power(x1, x2):
    return clang.pow_(x1, x2)


@npsymbol(name="maximum")
def maximum(x1, x2):
    return clang.maximum(x1, x2)


@npsymbol(name="minimum")
def minimum(x1, x2):
    return clang.minimum(x1, x2)


# -- elementwise unary --


@npsymbol(name="negative")
def negative(x):
    return prims.neg(x)


@npsymbol(name="absolute")
def absolute(x):
    return prims.abs(x)


@npsymbol(name="exp")
def exp(x):
    return prims.exp(x)


@npsymbol(name="log")
def log(x):
    return prims.log(x)


@npsymbol(name="sqrt")
def sqrt(x):
    return prims.sqrt(x)


@npsymbol(name="tanh")
def tanh(x):
    return prims.tanh(x)


@npsymbol(name="sin")
def sin(x):
    return prims.sin(x)


@npsymbol(name="cos")
def cos(x):
    return prims.cos(x)


# -- reductions (numpy calling convention: axis=, keepdims=) --


@npsymbol(name="sum", tags=(OpTags.REDUCTION_OP,))
def sum(a, axis=None, keepdims: bool = False):  # noqa: A001 — numpy name
    return clang.sum_(a, dim=axis, keepdim=keepdims)


@npsymbol(name="mean", tags=(OpTags.REDUCTION_OP,))
def mean(a, axis=None, keepdims: bool = False):
    return clang.mean(a, dim=axis, keepdim=keepdims)


@npsymbol(name="amax", tags=(OpTags.REDUCTION_OP,))
def amax(a, axis=None, keepdims: bool = False):
    return clang.amax(a, dim=axis, keepdim=keepdims)


@npsymbol(name="amin", tags=(OpTags.REDUCTION_OP,))
def amin(a, axis=None, keepdims: bool = False):
    return clang.amin(a, dim=axis, keepdim=keepdims)


# -- shape --


@npsymbol(name="reshape", tags=(OpTags.SHAPE_OP,))
def reshape(a, newshape):
    return clang.reshape(a, tuple(newshape))


@npsymbol(name="transpose", tags=(OpTags.SHAPE_OP,))
def transpose(a, axes: Optional[Sequence[int]] = None):
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    return clang.permute(a, tuple(axes))


@npsymbol(name="concatenate", tags=(OpTags.SHAPE_OP,))
def concatenate(arrays, axis: int = 0):
    return clang.cat(list(arrays), dim=axis)


@npsymbol(name="expand_dims", tags=(OpTags.SHAPE_OP,))
def expand_dims(a, axis: int):
    return clang.unsqueeze(a, axis)


@npsymbol(name="squeeze", tags=(OpTags.SHAPE_OP,))
def squeeze(a, axis: Optional[int] = None):
    return clang.squeeze(a, axis)


# -- linalg --


@npsymbol(name="matmul", tags=(OpTags.MATMUL_OP,))
def matmul(x1, x2):
    return prims.matmul(x1, x2)


@npsymbol(name="dot", tags=(OpTags.MATMUL_OP,))
def dot(a, b):
    return prims.matmul(a, b)


@npsymbol(name="where")
def where(condition, x, y):
    return clang.where(condition, x, y)
