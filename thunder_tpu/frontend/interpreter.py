"""A CPython bytecode interpreter with provenance tracking.

Re-design of reference thunder/core/interpreter.py (the reference's largest
single component, 7.8 kLoC): user callables are executed opcode-by-opcode on a
virtual stack so the framework sees *how* every value was obtained — function
arguments, globals, closure cells, attribute/item chains — instead of only
seeing the ops called on proxies. That provenance is what makes prologue
generation possible: captured tensors (globals, closures, attributes of
captured objects) become validated prologue inputs rather than baked-in
constants (reference jit_ext.py:2149 thunder_general_jit).

Design differences from the reference, deliberate for this stack:
  - Targets CPython 3.12 bytecode (the reference spans 3.10-3.13 with ~188
    handlers). Unknown opcodes raise loudly with the opcode name.
  - Values on the interpreter stack are ``WrappedValue``s carrying a
    ``Provenance`` tree; opaque calls unwrap arguments and re-wrap results
    (reference interpreter.py:129 WrappedValue, :945 ProvenanceRecord).
  - Python functions are interpreted recursively unless a *lookaside*
    substitutes them or they are opaque (C functions, skiplisted modules,
    generators); there are no graph breaks — anything opaque simply executes
    natively with proxies flowing through (reference `make_opaque`, :1338).
  - Callbacks fire on provenance-bearing loads (global/closure/attr/item) so
    the jit layer can proxify captured tensors and build prologue unpacks.
"""
from __future__ import annotations

import builtins
import dis
import types
from typing import Any, Callable, Optional, Sequence

__all__ = [
    "interpret",
    "InterpreterError",
    "Provenance",
    "WrappedValue",
    "register_lookaside",
    "default_lookasides",
]


class InterpreterError(RuntimeError):
    pass


class _Null:
    """The PUSH_NULL sentinel."""

    __slots__ = ()

    def __repr__(self):
        return "<NULL>"


NULL = _Null()


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------


class Provenance:
    """How a value was obtained (reference interpreter.py:945 ProvenanceRecord).

    kind: 'const' | 'arg' | 'global' | 'closure' | 'attr' | 'item' | 'opaque'
          | 'op'
    """

    __slots__ = ("kind", "key", "parent")

    def __init__(self, kind: str, key: Any = None, parent: "Provenance | None" = None):
        self.kind = kind
        self.key = key
        self.parent = parent

    def chain(self) -> list["Provenance"]:
        out: list[Provenance] = []
        p: Provenance | None = self
        while p is not None:
            out.append(p)
            p = p.parent
        return list(reversed(out))

    def root(self) -> "Provenance":
        p = self
        while p.parent is not None:
            p = p.parent
        return p

    def is_unpackable(self) -> bool:
        """True if the chain is a pure load chain from a stable root
        (global/closure/arg), i.e. the prologue can re-extract it."""
        for p in self.chain():
            if p.kind not in ("global", "closure", "attr", "item", "arg"):
                return False
        return True

    def __repr__(self):
        parts = []
        for p in self.chain():
            if p.kind in ("attr", "item"):
                parts.append(f".{p.key}" if p.kind == "attr" else f"[{p.key!r}]")
            else:
                parts.append(f"<{p.kind}:{p.key}>")
        return "".join(parts)


CONST_PROVENANCE = Provenance("const")
OPAQUE_PROVENANCE = Provenance("opaque")


class WrappedValue:
    __slots__ = ("value", "provenance")

    def __init__(self, value: Any, provenance: Provenance = CONST_PROVENANCE):
        self.value = value
        self.provenance = provenance

    def __repr__(self):
        return f"W({self.value!r})"


def wrap(value: Any, provenance: Provenance = CONST_PROVENANCE) -> WrappedValue:
    if isinstance(value, WrappedValue):
        return value
    return WrappedValue(value, provenance)


def unwrap(x: Any) -> Any:
    return x.value if isinstance(x, WrappedValue) else x


# ---------------------------------------------------------------------------
# lookasides & opacity
# ---------------------------------------------------------------------------

_global_lookasides: dict[Any, Callable] = {}


def register_lookaside(target: Callable):
    """Substitute ``target`` whenever interpreted code calls it."""

    def deco(fn: Callable) -> Callable:
        _global_lookasides[target] = fn
        return fn

    return deco


def default_lookasides() -> dict[Any, Callable]:
    return dict(_global_lookasides)


def _register_builtin_lookasides() -> None:
    """Tensor-aware diversions of builtins (reference general-jit lookaside
    table, thunder/core/jit_ext.py:411-1080): min/max over proxies cannot run
    natively (bool() of a tensor comparison is data-dependent), len() needs
    the static leading dim."""
    import builtins

    from ..core.proxies import TensorProxy

    def _lt():
        from ..ops import ltorch

        return ltorch

    def _has_multi_element(args):
        return builtins.any(
            isinstance(a, TensorProxy) and (a.ndim > 1 or (a.ndim == 1 and a.shape[0] > 1))
            for a in args)

    def _contains_tensor(x):
        return isinstance(x, (list, tuple)) and builtins.any(
            isinstance(e, TensorProxy) for e in x)

    def _minmax(name, reduce_name, args, kwargs):
        # torch semantics: min/max over a 1-D tensor reduces (each pairwise
        # comparison is scalar); multi-element comparisons are ambiguous and
        # must raise — NOT silently return an elementwise result
        if len(args) == 1 and isinstance(args[0], TensorProxy):
            t = args[0]
            if t.ndim == 0:
                raise TypeError(f"builtins.{name} of a 0-d tensor (not iterable, as in torch)")
            if t.ndim == 1:
                return getattr(_lt(), reduce_name)(t)
            raise InterpreterError(
                f"builtins.{name} over a {t.ndim}-D tensor compares whole "
                f"rows (data-dependent, ambiguous in torch too); use "
                f"ltorch.{reduce_name} for a reduction")
        if _has_multi_element(args) or builtins.any(_contains_tensor(a) for a in args):
            raise InterpreterError(
                f"builtins.{name} comparing multi-element tensors is "
                f"data-dependent (torch raises here too); use "
                f"ltorch.{'minimum' if name == 'min' else 'maximum'} for an "
                f"elementwise result or ltorch.{reduce_name} for a reduction")
        return getattr(builtins, name)(*args, **kwargs)

    @register_lookaside(builtins.min)
    def _min_la(*args, **kwargs):
        return _minmax("min", "amin", args, kwargs)

    @register_lookaside(builtins.max)
    def _max_la(*args, **kwargs):
        return _minmax("max", "amax", args, kwargs)

    @register_lookaside(builtins.len)
    def _len_la(x):
        if isinstance(x, TensorProxy):
            if x.ndim == 0:
                raise TypeError("len() of a 0-d tensor")
            return int(x.shape[0])
        return builtins.len(x)

    @register_lookaside(builtins.sorted)
    def _sorted_la(x, **kwargs):
        if isinstance(x, TensorProxy):
            if kwargs:
                raise NotImplementedError("sorted(tensor, key=/reverse=) is not supported")
            if x.ndim > 1:
                raise InterpreterError(
                    "sorted() over a >=2-D tensor compares whole rows "
                    "(data-dependent); use ltorch.sort")
            return _lt().sort(x, 0)[0]
        if _contains_tensor(x) and _has_multi_element(list(x)):
            raise InterpreterError(
                "sorted() over a sequence of multi-element tensors is "
                "data-dependent; use ltorch.sort on a stacked tensor")
        return builtins.sorted(x, **kwargs)

    def _anyall(name, reduce_name, x):
        # builtins.any/all iterate and bool() each element: over a tensor
        # that is per-element data-dependent control flow. A 1-D tensor has
        # a sound traced equivalent (the reduction); everything else raises
        # with the torch-matching guidance.
        if isinstance(x, TensorProxy):
            if x.ndim == 0:
                raise TypeError(f"builtins.{name} of a 0-d tensor (not iterable, as in torch)")
            if x.ndim == 1:
                return getattr(_lt(), reduce_name)(x)
            raise InterpreterError(
                f"builtins.{name} over a {x.ndim}-D tensor bool()s whole rows "
                f"(data-dependent); use ltorch.{reduce_name} for a reduction")
        if not isinstance(x, (list, tuple)):
            # generators are the common form (any(t > 0 for t in xs)):
            # materialize so tensor elements are caught, not silently
            # bool()'d truthy by builtins.any
            x = list(x)
        if _contains_tensor(x):
            raise InterpreterError(
                f"builtins.{name} over a sequence containing tensors is "
                f"data-dependent; reduce with ltorch.{reduce_name}")
        return getattr(builtins, name)(x)

    @register_lookaside(builtins.any)
    def _any_la(x):
        return _anyall("any", "any", x)

    @register_lookaside(builtins.all)
    def _all_la(x):
        return _anyall("all", "all", x)

    @register_lookaside(builtins.sum)
    def _sum_la(x, start=0):
        if isinstance(x, TensorProxy):
            if x.ndim == 0:
                raise TypeError("builtins.sum of a 0-d tensor (not iterable, as in torch)")
            # iterating would trace one add per element; the reduction over
            # the leading dim is the identical result in one op
            out = _lt().sum(x, 0)
            return out if start == 0 else _lt().add(out, start)
        if isinstance(x, (list, tuple)) and builtins.any(isinstance(e, TensorProxy) for e in x):
            out = start
            for e in x:
                out = _lt().add(out, e) if isinstance(out, TensorProxy) or isinstance(e, TensorProxy) else out + e
            return out
        return builtins.sum(x, start)

    @register_lookaside(builtins.isinstance)
    def _isinstance_la(obj, classinfo):
        # duck-typing escape hatch: user code checking isinstance(x, jax.Array)
        # (or np.ndarray) must see True for the proxy standing in for it
        if isinstance(obj, TensorProxy):
            import jax
            import numpy as np

            infos = classinfo if isinstance(classinfo, tuple) else (classinfo,)
            if builtins.any(c in (jax.Array, np.ndarray) for c in infos if isinstance(c, type)):
                return True
        return builtins.isinstance(obj, classinfo)


def _register_framework_lookasides() -> None:
    """Framework context managers run natively (their bodies only mutate
    host-side trace state; interpreting them would walk framework imports) —
    the autocast __enter__/__exit__ lookaside role of reference
    jit_ext.py:411-1080."""
    from ..transforms.autocast import autocast_ctx

    register_lookaside(autocast_ctx.__enter__)(autocast_ctx.__enter__)
    register_lookaside(autocast_ctx.__exit__)(autocast_ctx.__exit__)


_register_builtin_lookasides()
_register_framework_lookasides()


# modules whose functions run natively (opaque) rather than interpreted
_OPAQUE_MODULE_PREFIXES = (
    "jax", "numpy", "thunder_tpu", "builtins", "math", "operator", "functools",
    "itertools", "collections", "contextlib", "typing", "abc", "torch", "optree",
)


class _ProvenanceIter:
    """Iterator over a random-access sequence that yields items with
    `item` provenance (so captured tensors inside iterated containers build
    unpackable chains instead of opaque `op` roots)."""

    __slots__ = ("obj", "prov", "i")

    def __init__(self, obj, prov):
        self.obj = obj
        self.prov = prov
        self.i = 0


def _is_opaque_function(fn: Callable) -> bool:
    if not isinstance(fn, types.FunctionType):
        return True  # C functions, builtins, callables with __call__
    # the defining module's true name comes from the function's globals —
    # fn.__module__ lies under functools.wraps
    mod = (fn.__globals__.get("__name__") or "") if fn.__globals__ else ""
    # thunder_tpu.nn / thunder_tpu.models are USER-LEVEL model code: their
    # forward bodies must be interpreted so `self.<param>` loads get
    # provenance-proxified into captured runtime inputs (the framework's own
    # core/ops/executors stay opaque — proxies flow through them natively).
    # transforms.remat's checkpoint wrapper is interpreted for the same
    # reason: it calls back into module forwards.
    if mod.startswith(("thunder_tpu.nn", "thunder_tpu.models", "thunder_tpu.transforms.remat")):
        return bool(fn.__code__.co_flags & (0x80 | 0x200))
    if mod.partition(".")[0] in _OPAQUE_MODULE_PREFIXES:
        return True
    code = fn.__code__
    if code.co_flags & (0x80 | 0x200):  # coroutine/async-gen (generators ARE interpreted)
        return True
    return False


# ---------------------------------------------------------------------------
# binary-op table (3.12 NB_ codes; inplace variants fall back to the binary op
# — correct for immutable values; lists etc. are handled via the real inplace
# operator)
# ---------------------------------------------------------------------------

import operator as _op

_NB_OPS = [
    _op.add, _op.and_, _op.floordiv, _op.lshift, _op.matmul, _op.mul,
    _op.mod, _op.or_, _op.pow, _op.rshift, _op.sub, _op.truediv, _op.xor,
    _op.iadd, _op.iand, _op.ifloordiv, _op.ilshift, _op.imatmul, _op.imul,
    _op.imod, _op.ior, _op.ipow, _op.irshift, _op.isub, _op.itruediv, _op.ixor,
]

_CMP_OPS = {
    "<": _op.lt, "<=": _op.le, "==": _op.eq, "!=": _op.ne, ">": _op.gt, ">=": _op.ge,
}


# ---------------------------------------------------------------------------
# frames
# ---------------------------------------------------------------------------


class Frame:
    def __init__(self, code: types.CodeType, f_globals: dict, f_builtins: dict,
                 localsplus: dict[str, Any], cells: dict[str, types.CellType]):
        self.code = code
        self.f_globals = f_globals
        self.f_builtins = f_builtins
        self.locals = localsplus      # name -> WrappedValue (fast locals)
        self.cells = cells            # name -> CellType holding WrappedValue
        self.stack: list[Any] = []
        self.instrs = list(dis.get_instructions(code, adaptive=False))
        self.offset_to_idx = {ins.offset: i for i, ins in enumerate(self.instrs)}
        self.ip = 0
        self.exc_table = _parse_exception_table(code)
        self.block_depths: list[int] = []  # exception handler stack depths
        self.exc_stack: list[BaseException] = []  # live handlers' exceptions
        # applied to every pushed value: routes stale tensor aliases to their
        # functionalized replacements (in-place assignment support)
        self.resolver = None

    def push(self, v):
        if self.resolver is not None:
            v = self.resolver(v)
        self.stack.append(v)

    def pop(self):
        return self.stack.pop()

    def peek(self, i=1):
        return self.stack[-i]


def _parse_exception_table(code: types.CodeType):
    try:
        return list(dis._parse_exception_table(code))
    except Exception:
        return []


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


_GATE_WARNED = False


def _check_python_version() -> None:
    """Explicit version gate (reference spans 3.10-3.13 with per-version
    handler tables, thunder/core/interpreter.py:1257). Here: CPython 3.12 is
    the tested surface; 3.13 runs best-effort via the handlers for its new
    opcodes (TO_BOOL / CALL_KW / fused FAST pairs / FORMAT_* split); anything
    else is refused loudly — the direct-tracing frontend (the default
    ``interpretation=None``) has no version sensitivity at all."""
    import sys
    import warnings

    global _GATE_WARNED
    vi = sys.version_info[:2]
    if vi == (3, 12):
        return
    if vi == (3, 13):
        if not _GATE_WARNED:
            _GATE_WARNED = True
            warnings.warn(
                "thunder_tpu bytecode interpreter on CPython 3.13 is "
                "best-effort (CI runs 3.12); the direct-tracing frontend "
                "(interpretation=None) is version-independent")
        return
    raise InterpreterError(
        f"the thunder_tpu bytecode interpreter supports CPython 3.12 (tested) "
        f"and 3.13 (best-effort), not {vi[0]}.{vi[1]}; use the default "
        f"direct-tracing frontend (interpretation=None)")


class Interpreter:
    def __init__(self, *, lookasides: dict | None = None,
                 on_provenance_load: Callable[[Any, Provenance], Any] | None = None,
                 on_sharp_edge: Callable[[str], None] | None = None,
                 max_depth: int = 64, record_log: bool = False):
        _check_python_version()
        self.lookasides = {**default_lookasides(), **(lookasides or {})}
        self.on_provenance_load = on_provenance_load
        self.on_sharp_edge = on_sharp_edge or (lambda msg: None)
        self.max_depth = max_depth
        self.depth = 0
        # cells reachable from the ROOT callable's __closure__ (id -> root
        # freevar name): only these are prologue-re-derivable captures
        self._root_cells: dict[int, str] = {}
        # cells created while interpreting a closure-maker whose argument had
        # unpackable provenance: id(cell) -> (cell, provenance, value) — lets
        # LOAD_DEREF in the nested function re-attach the chain
        self._cell_prov: dict[int, tuple] = {}
        # instruction logging (reference interpreter.py:457 — every interpreted
        # instruction recorded; rendered by print_last_interpreter_log)
        self.log: list[str] = []
        self.record_log = record_log
        # proxy redirects: name of a functionally-updated tensor -> its
        # replacement. Consulted on every value push, so stale aliases in any
        # frame, container, or capture cache observe the update (the
        # acquisition-time form of reference update_aliases,
        # thunder/core/update_aliases.py:143)
        self.redirects: dict[str, Any] = {}

    def _resolve_pushed(self, v):
        if not self.redirects:
            return v
        from ..core.proxies import TensorProxy

        raw = unwrap(v)
        if not isinstance(raw, TensorProxy):
            return v
        cur = self.redirects.get(raw.name)
        if cur is None:
            return v
        while True:
            nxt = self.redirects.get(cur.name)
            if nxt is None:
                break
            cur = nxt
        # the updated value is computed, not a pure load — 'op' provenance
        return wrap(cur, Provenance("op"))

    # -- value wrapping with jit callback --
    def _loaded(self, value: Any, prov: Provenance) -> WrappedValue:
        if self.on_provenance_load is not None:
            value = self.on_provenance_load(value, prov)
        return WrappedValue(value, prov)

    # -- function call dispatch --
    def call(self, fn: Any, args: Sequence[Any], kwargs: dict[str, Any],
             fn_prov: Provenance = OPAQUE_PROVENANCE) -> WrappedValue:
        """args/kwargs are WrappedValues (or raw); returns a WrappedValue."""
        raw_fn = unwrap(fn)
        self._note_root_cells(raw_fn)
        la = self.lookasides.get(raw_fn)
        if la is not None:
            res = la(*[unwrap(a) for a in args], **{k: unwrap(v) for k, v in kwargs.items()})
            return wrap(res, Provenance("op"))
        if isinstance(raw_fn, types.MethodType) and not _is_opaque_function(raw_fn.__func__):
            # a bound method keeps the instance's provenance so attribute
            # loads off `self` chain back to the captured root
            self_prov = fn_prov.parent if fn_prov.kind == "attr" else OPAQUE_PROVENANCE
            return self.call(raw_fn.__func__, [wrap(raw_fn.__self__, self_prov)] + list(args),
                             kwargs, fn_prov)
        if not isinstance(raw_fn, types.FunctionType) and not isinstance(raw_fn, type):
            # instance call: interpret a user-defined __call__ (or forward,
            # when __call__ is the framework's trivial dispatcher) so
            # `self.<param>` loads are provenance-tracked
            call_m = getattr(type(raw_fn), "__call__", None)
            target = None
            if isinstance(call_m, types.FunctionType) and not _is_opaque_function(call_m):
                target = call_m
            else:
                fwd = getattr(type(raw_fn), "forward", None)
                if isinstance(fwd, types.FunctionType) and not _is_opaque_function(fwd):
                    target = fwd
            if target is not None:
                self_prov = fn_prov if fn_prov.is_unpackable() else OPAQUE_PROVENANCE
                return self.call(target, [wrap(raw_fn, self_prov)] + list(args), kwargs, fn_prov)
        if not _is_opaque_function(raw_fn):
            return self.interpret_function(raw_fn, args, kwargs, fn_prov)
        # opaque: execute natively with unwrapped values (proxies flow through)
        res = raw_fn(*[unwrap(a) for a in args], **{k: unwrap(v) for k, v in kwargs.items()})
        return wrap(res, Provenance("op"))

    def _note_root_cells(self, fn):
        if self.depth != 0:
            return
        self._root_cells = {}
        if isinstance(fn, types.FunctionType) and fn.__closure__:
            # hold the cell objects: identity must be checked against the
            # live cell (a bare id() could alias a collected cell's address)
            self._root_cells = {id(cell): (name, cell) for name, cell in
                                zip(fn.__code__.co_freevars, fn.__closure__)}

    def interpret_function(self, fn: types.FunctionType, args, kwargs,
                           fn_prov: Provenance = OPAQUE_PROVENANCE) -> WrappedValue:
        if self.depth >= self.max_depth:
            raise InterpreterError(f"interpreter recursion limit ({self.max_depth}) hit at {fn}")
        code = fn.__code__
        # bind the signature with raw values, keeping wrappers
        localsplus = _bind_args(fn, args, kwargs)
        cells: dict[str, types.CellType] = {}
        for name in code.co_cellvars:
            cell = types.CellType()
            if name in localsplus:  # argument that is also a cell (raw value)
                w = localsplus.pop(name)
                cell.cell_contents = unwrap(w)
                if isinstance(w, WrappedValue) and w.provenance.is_unpackable():
                    # remember the argument's provenance for later LOAD_DEREFs
                    # from nested interpreted functions (closure-makers)
                    self._cell_prov[id(cell)] = (cell, w.provenance, cell.cell_contents)
            cells[name] = cell
        if fn.__closure__:
            for name, cell in zip(code.co_freevars, fn.__closure__):
                cells[name] = cell
        frame = Frame(code, fn.__globals__, vars(builtins), localsplus, cells)
        frame.resolver = self._resolve_pushed
        self.depth += 1
        try:
            return self.run_frame(frame, fn)
        finally:
            self.depth -= 1

    # -- the opcode loop --
    def run_frame(self, frame: Frame, fn: types.FunctionType) -> WrappedValue:
        while True:
            ins = frame.instrs[frame.ip]
            try:
                result = self.step(frame, fn, ins)
            except _Return as r:
                return r.value
            except _Yield:
                raise  # generator suspension, not an exception to handle
            except InterpreterError:
                raise
            except Exception as e:
                handled = self._handle_exception(frame, e)
                if not handled:
                    raise
                continue
            if result is not None:  # jump target offset
                frame.ip = frame.offset_to_idx[result]
            else:
                frame.ip += 1

    def _handle_exception(self, frame: Frame, exc: BaseException) -> bool:
        offset = frame.instrs[frame.ip].offset
        for entry in frame.exc_table:
            if entry.start <= offset < entry.end:
                del frame.stack[entry.depth:]
                if entry.lasti:
                    frame.push(wrap(0))  # lasti placeholder (unsupported resume)
                frame.push(wrap(exc))
                frame.ip = frame.offset_to_idx[entry.target]
                return True
        return False

    def step(self, frame: Frame, fn, ins: dis.Instruction) -> Optional[int]:
        """Execute one instruction. Returns a jump target offset or None."""
        op = ins.opname
        if self.record_log:
            lineno = ins.positions.lineno if ins.positions else None
            self.log.append(f"{'  ' * self.depth}{fn.__qualname__}:{lineno} "
                            f"{op} {ins.argrepr or ins.argval if ins.arg is not None else ''}")
        handler = getattr(self, f"op_{op}", None)
        if handler is None:
            raise InterpreterError(
                f"unsupported opcode {op} at {fn.__qualname__}:{ins.positions.lineno if ins.positions else '?'} "
                f"(thunder_tpu interpreter targets CPython 3.12)")
        return handler(frame, fn, ins)

    # ---- trivial ----
    def op_RESUME(self, frame, fn, ins):
        return None

    def op_CACHE(self, frame, fn, ins):
        return None

    def op_NOP(self, frame, fn, ins):
        return None

    def op_POP_TOP(self, frame, fn, ins):
        frame.pop()
        return None

    def op_PUSH_NULL(self, frame, fn, ins):
        frame.push(NULL)
        return None

    def op_COPY(self, frame, fn, ins):
        frame.push(frame.peek(ins.arg))
        return None

    def op_SWAP(self, frame, fn, ins):
        i = ins.arg
        frame.stack[-i], frame.stack[-1] = frame.stack[-1], frame.stack[-i]
        return None

    # ---- loads/stores ----
    def op_LOAD_CONST(self, frame, fn, ins):
        frame.push(wrap(ins.argval, CONST_PROVENANCE))
        return None

    def op_RETURN_CONST(self, frame, fn, ins):
        raise _Return(wrap(ins.argval, CONST_PROVENANCE))

    def op_LOAD_FAST(self, frame, fn, ins):
        name = ins.argval
        if name not in frame.locals:
            raise UnboundLocalError(f"local variable '{name}' referenced before assignment")
        frame.push(frame.locals[name])
        return None

    op_LOAD_FAST_CHECK = op_LOAD_FAST

    def op_LOAD_FAST_AND_CLEAR(self, frame, fn, ins):
        name = ins.argval
        frame.push(frame.locals.get(name, _UNBOUND))
        frame.locals.pop(name, None)
        return None

    def op_STORE_FAST(self, frame, fn, ins):
        v = frame.pop()
        if v is _UNBOUND:
            frame.locals.pop(ins.argval, None)
        else:
            frame.locals[ins.argval] = v
        return None

    def op_DELETE_FAST(self, frame, fn, ins):
        frame.locals.pop(ins.argval, None)
        return None

    def op_DELETE_DEREF(self, frame, fn, ins):
        cell = frame.cells.get(ins.argval)
        try:
            if cell is None:
                raise ValueError
            del cell.cell_contents
        except ValueError:
            # match CPython: deleting a missing/empty cell raises NameError
            raise NameError(f"free variable '{ins.argval}' referenced before assignment")
        return None

    def op_LOAD_GLOBAL(self, frame, fn, ins):
        name = ins.argval
        if name in frame.f_globals:
            val = frame.f_globals[name]
            prov = Provenance("global", name)
        elif name in frame.f_builtins:
            val = frame.f_builtins[name]
            prov = Provenance("const", name)  # builtins are stable; no unpack
        else:
            raise NameError(f"name '{name}' is not defined")
        if ins.arg & 1:
            frame.push(NULL)
        frame.push(self._loaded(val, prov))
        return None

    def op_STORE_GLOBAL(self, frame, fn, ins):
        self.on_sharp_edge(f"STORE_GLOBAL '{ins.argval}' inside traced code "
                           f"(side effect is applied at trace time only)")
        frame.f_globals[ins.argval] = unwrap(frame.pop())
        return None

    def op_LOAD_NAME(self, frame, fn, ins):
        return self.op_LOAD_GLOBAL(frame, fn, ins._replace(arg=0))

    def op_LOAD_DEREF(self, frame, fn, ins):
        name = ins.argval
        cell = frame.cells.get(name)
        if cell is None:
            raise NameError(f"free variable '{name}' referenced before assignment")
        try:
            v = cell.cell_contents
        except ValueError:
            raise UnboundLocalError(f"cell variable '{name}' is empty")
        # cells hold RAW values (they are shared with natively-executing
        # closures); only cells reachable from the ROOT callable's __closure__
        # are unpackable — the prologue re-derives captures from
        # fn.__closure__, and nested interpreted functions (decorators,
        # dataclass-generated code) may carry cells the root cannot reach.
        # Identity (not depth) is the test: a helper at depth 2 reading the
        # root's own cell must still capture it, under the ROOT's name.
        entry = self._root_cells.get(id(cell))
        if entry is not None and entry[1] is cell:
            frame.push(self._loaded(v, Provenance("closure", entry[0])))
            return None
        # cells created while INTERPRETING a closure-maker (e.g. a decorator
        # like remat.checkpoint wrapping a provenance-tracked module) remember
        # the wrapped argument's provenance — the load re-attaches it so the
        # module's params still capture through the root chain
        rec = self._cell_prov.get(id(cell))
        if rec is not None and rec[0] is cell and rec[2] is v:
            frame.push(self._loaded(v, rec[1]))
        else:
            frame.push(wrap(v, Provenance("op")))
        return None

    def op_STORE_DEREF(self, frame, fn, ins):
        frame.cells[ins.argval].cell_contents = unwrap(frame.pop())
        return None

    def op_MAKE_CELL(self, frame, fn, ins):
        if ins.argval not in frame.cells:
            frame.cells[ins.argval] = types.CellType()
        return None

    def op_COPY_FREE_VARS(self, frame, fn, ins):
        return None  # cells were installed by interpret_function

    def op_LOAD_CLOSURE(self, frame, fn, ins):
        frame.push(frame.cells[ins.argval])
        return None

    def op_LOAD_ATTR(self, frame, fn, ins):
        obj_w = frame.pop()
        obj = unwrap(obj_w)
        name = ins.argval
        val = getattr(obj, name)
        prov = Provenance("attr", name, obj_w.provenance if isinstance(obj_w, WrappedValue) else OPAQUE_PROVENANCE)
        if ins.arg & 1:
            # method-call form: push callable then NULL (CALL handles either
            # slot order; bound methods already carry self)
            frame.push(self._loaded(val, prov))
            frame.push(NULL)
        else:
            frame.push(self._loaded(val, prov))
        return None

    def op_STORE_ATTR(self, frame, fn, ins):
        obj = unwrap(frame.pop())
        val = unwrap(frame.pop())
        self.on_sharp_edge(f"STORE_ATTR '.{ins.argval}' on traced object "
                           f"(side effect is applied at trace time only)")
        setattr(obj, ins.argval, val)
        return None

    def op_DELETE_ATTR(self, frame, fn, ins):
        delattr(unwrap(frame.pop()), ins.argval)
        return None

    def op_LOAD_SUPER_ATTR(self, frame, fn, ins):
        self_w = frame.pop()
        cls = unwrap(frame.pop())
        _sup = frame.pop()  # the super builtin
        obj = unwrap(self_w)
        val = getattr(super(cls, obj), ins.argval)
        if ins.arg & 1:
            frame.push(wrap(val, Provenance("op")))
            frame.push(NULL)
        else:
            frame.push(wrap(val, Provenance("op")))
        return None

    # ---- operators ----
    def op_BINARY_OP(self, frame, fn, ins):
        b, a = frame.pop(), frame.pop()
        frame.push(wrap(_NB_OPS[ins.arg](unwrap(a), unwrap(b)), Provenance("op")))
        return None

    def op_UNARY_NEGATIVE(self, frame, fn, ins):
        frame.push(wrap(-unwrap(frame.pop()), Provenance("op")))
        return None

    def op_UNARY_NOT(self, frame, fn, ins):
        frame.push(wrap(not unwrap(frame.pop()), Provenance("op")))
        return None

    def op_UNARY_INVERT(self, frame, fn, ins):
        frame.push(wrap(~unwrap(frame.pop()), Provenance("op")))
        return None

    def op_COMPARE_OP(self, frame, fn, ins):
        b, a = frame.pop(), frame.pop()
        frame.push(wrap(_CMP_OPS[ins.argval](unwrap(a), unwrap(b)), Provenance("op")))
        return None

    def op_IS_OP(self, frame, fn, ins):
        b, a = unwrap(frame.pop()), unwrap(frame.pop())
        res = a is b
        if ins.arg:
            res = not res
        frame.push(wrap(res, Provenance("op")))
        return None

    def op_CONTAINS_OP(self, frame, fn, ins):
        b, a = unwrap(frame.pop()), unwrap(frame.pop())
        res = a in b
        if ins.arg:
            res = not res
        frame.push(wrap(res, Provenance("op")))
        return None

    def op_BINARY_SUBSCR(self, frame, fn, ins):
        key_w, obj_w = frame.pop(), frame.pop()
        obj, key = unwrap(obj_w), unwrap(key_w)
        val = obj[key]
        parent_prov = obj_w.provenance if isinstance(obj_w, WrappedValue) else OPAQUE_PROVENANCE
        if isinstance(key, (str, int)) and parent_prov.is_unpackable():
            frame.push(self._loaded(val, Provenance("item", key, parent_prov)))
        else:
            frame.push(wrap(val, Provenance("op")))
        return None

    def op_STORE_SUBSCR(self, frame, fn, ins):
        key, obj, val = unwrap(frame.pop()), unwrap(frame.pop()), unwrap(frame.pop())
        from ..core.proxies import TensorProxy

        if isinstance(obj, TensorProxy):
            self._functionalize_setitem(frame, obj, key, val)
            return None
        obj[key] = val
        return None

    def _functionalize_setitem(self, frame, obj, key, val):
        """Rewrite `x[key] = v` to a functional copy_with_setitem (the
        acquisition-time form of reference update_aliases). The old proxy is
        redirected to the new one, so any alias — another frame's local, a
        container element, a re-loaded global — resolves to the updated
        tensor on its next load. Aliases already held inside opaque native
        state are the one remaining blind spot."""
        from ..core import prims as _prims

        new = _prims.copy_with_setitem(obj, key, val)
        self.redirects[obj.name] = new
        self._rebind_proxy(frame, obj, new)

    @staticmethod
    def _rebind_proxy(frame, old, new) -> bool:
        hit = False
        for name, w in list(frame.locals.items()):
            if unwrap(w) is old:
                frame.locals[name] = wrap(new, Provenance("op"))
                hit = True
        for name, cell in frame.cells.items():
            try:
                if unwrap(cell.cell_contents) is old:
                    cell.cell_contents = new
                    hit = True
            except ValueError:
                continue
        for i, w in enumerate(frame.stack):
            if unwrap(w) is old:
                frame.stack[i] = wrap(new, Provenance("op"))
                hit = True
        return hit

    def op_DELETE_SUBSCR(self, frame, fn, ins):
        key, obj = unwrap(frame.pop()), unwrap(frame.pop())
        del obj[key]
        return None

    def op_BINARY_SLICE(self, frame, fn, ins):
        end, start, obj = unwrap(frame.pop()), unwrap(frame.pop()), unwrap(frame.pop())
        frame.push(wrap(obj[start:end], Provenance("op")))
        return None

    def op_STORE_SLICE(self, frame, fn, ins):
        end, start, obj, val = (unwrap(frame.pop()), unwrap(frame.pop()),
                                unwrap(frame.pop()), unwrap(frame.pop()))
        from ..core.proxies import TensorProxy

        if isinstance(obj, TensorProxy):
            self._functionalize_setitem(frame, obj, slice(start, end), val)
            return None
        obj[start:end] = val
        return None

    def op_BUILD_SLICE(self, frame, fn, ins):
        if ins.arg == 3:
            step, stop, start = unwrap(frame.pop()), unwrap(frame.pop()), unwrap(frame.pop())
            frame.push(wrap(slice(start, stop, step), Provenance("op")))
        else:
            stop, start = unwrap(frame.pop()), unwrap(frame.pop())
            frame.push(wrap(slice(start, stop), Provenance("op")))
        return None

    # ---- collections ----
    def _popn(self, frame, n):
        if n == 0:
            return []
        vals = frame.stack[-n:]
        del frame.stack[-n:]
        return vals

    def op_BUILD_TUPLE(self, frame, fn, ins):
        frame.push(wrap(tuple(unwrap(v) for v in self._popn(frame, ins.arg)), Provenance("op")))
        return None

    def op_BUILD_LIST(self, frame, fn, ins):
        frame.push(wrap([unwrap(v) for v in self._popn(frame, ins.arg)], Provenance("op")))
        return None

    def op_BUILD_SET(self, frame, fn, ins):
        frame.push(wrap({unwrap(v) for v in self._popn(frame, ins.arg)}, Provenance("op")))
        return None

    def op_BUILD_MAP(self, frame, fn, ins):
        items = self._popn(frame, 2 * ins.arg)
        d = {unwrap(items[i]): unwrap(items[i + 1]) for i in range(0, len(items), 2)}
        frame.push(wrap(d, Provenance("op")))
        return None

    def op_BUILD_CONST_KEY_MAP(self, frame, fn, ins):
        keys = unwrap(frame.pop())
        vals = self._popn(frame, ins.arg)
        frame.push(wrap(dict(zip(keys, (unwrap(v) for v in vals))), Provenance("op")))
        return None

    def op_BUILD_STRING(self, frame, fn, ins):
        frame.push(wrap("".join(unwrap(v) for v in self._popn(frame, ins.arg)), Provenance("op")))
        return None

    def op_FORMAT_VALUE(self, frame, fn, ins):
        flags = ins.arg
        spec = unwrap(frame.pop()) if flags & 0x04 else ""
        val = unwrap(frame.pop())
        conv = flags & 0x03
        if conv == 1:
            val = str(val)
        elif conv == 2:
            val = repr(val)
        elif conv == 3:
            val = ascii(val)
        frame.push(wrap(format(val, spec), Provenance("op")))
        return None

    # ---- CPython 3.13 opcode surface (documented semantics; the CI image
    # ships 3.12, so these run under the best-effort gate) ----

    def op_TO_BOOL(self, frame, fn, ins):
        # _truthy, not bool(): a TensorProxy branch must raise the loud
        # data-dependent-control-flow error (3.12 jumps go through _truthy)
        v = frame.pop()
        frame.push(wrap(self._truthy(v), Provenance("op")))
        return None

    def op_CALL_KW(self, frame, fn, ins):
        # 3.13 folds KW_NAMES into the call: the names tuple rides the stack
        kwnames = unwrap(frame.pop())
        frame._kwnames = tuple(kwnames)
        return self.op_CALL(frame, fn, ins)

    def op_LOAD_FAST_LOAD_FAST(self, frame, fn, ins):
        for name in ins.argval:
            if name not in frame.locals:
                raise UnboundLocalError(f"local variable '{name}' referenced before assignment")
            frame.push(frame.locals[name])
        return None

    def op_STORE_FAST_STORE_FAST(self, frame, fn, ins):
        n1, n2 = ins.argval
        frame.locals[n1] = frame.pop()
        frame.locals[n2] = frame.pop()
        return None

    def op_STORE_FAST_LOAD_FAST(self, frame, fn, ins):
        n_store, n_load = ins.argval
        frame.locals[n_store] = frame.pop()
        if n_load not in frame.locals:
            raise UnboundLocalError(f"local variable '{n_load}' referenced before assignment")
        frame.push(frame.locals[n_load])
        return None

    def op_CONVERT_VALUE(self, frame, fn, ins):
        v = unwrap(frame.pop())
        conv = {1: str, 2: repr, 3: ascii}[ins.arg]
        frame.push(wrap(conv(v), Provenance("op")))
        return None

    def op_FORMAT_SIMPLE(self, frame, fn, ins):
        v = unwrap(frame.pop())
        frame.push(wrap(v if isinstance(v, str) else format(v), Provenance("op")))
        return None

    def op_FORMAT_WITH_SPEC(self, frame, fn, ins):
        spec = unwrap(frame.pop())
        v = unwrap(frame.pop())
        frame.push(wrap(format(v, spec), Provenance("op")))
        return None

    def op_LIST_EXTEND(self, frame, fn, ins):
        seq = unwrap(frame.pop())
        unwrap(frame.peek(ins.arg)).extend(seq)
        return None

    def op_SET_UPDATE(self, frame, fn, ins):
        seq = unwrap(frame.pop())
        unwrap(frame.peek(ins.arg)).update(seq)
        return None

    def op_DICT_UPDATE(self, frame, fn, ins):
        d = unwrap(frame.pop())
        unwrap(frame.peek(ins.arg)).update(d)
        return None

    op_DICT_MERGE = op_DICT_UPDATE

    def op_LIST_APPEND(self, frame, fn, ins):
        v = unwrap(frame.pop())
        unwrap(frame.peek(ins.arg)).append(v)
        return None

    def op_SET_ADD(self, frame, fn, ins):
        v = unwrap(frame.pop())
        unwrap(frame.peek(ins.arg)).add(v)
        return None

    def op_MAP_ADD(self, frame, fn, ins):
        v, k = unwrap(frame.pop()), unwrap(frame.pop())
        unwrap(frame.peek(ins.arg))[k] = v
        return None

    def op_UNPACK_SEQUENCE(self, frame, fn, ins):
        seq_w = frame.pop()
        seq = list(unwrap(seq_w))
        if len(seq) != ins.arg:
            raise ValueError(f"cannot unpack {len(seq)} values into {ins.arg}")
        prov = seq_w.provenance if isinstance(seq_w, WrappedValue) else OPAQUE_PROVENANCE
        for i in reversed(range(len(seq))):
            if prov.is_unpackable():
                frame.push(self._loaded(seq[i], Provenance("item", i, prov)))
            else:
                frame.push(wrap(seq[i], Provenance("op")))
        return None

    def op_UNPACK_EX(self, frame, fn, ins):
        before = ins.arg & 0xFF
        after = ins.arg >> 8
        seq = list(unwrap(frame.pop()))
        rest = seq[before:len(seq) - after if after else None]
        tail = seq[len(seq) - after:] if after else []
        for v in reversed(tail):
            frame.push(wrap(v, Provenance("op")))
        frame.push(wrap(rest, Provenance("op")))
        for v in reversed(seq[:before]):
            frame.push(wrap(v, Provenance("op")))
        return None

    # ---- control flow ----
    def op_GET_ITER(self, frame, fn, ins):
        obj_w = frame.pop()
        obj = unwrap(obj_w)
        prov = obj_w.provenance if isinstance(obj_w, WrappedValue) else OPAQUE_PROVENANCE
        # iterating a provenance-tracked random-access sequence (list/tuple/
        # ModuleList): keep per-item provenance so `for block in self.h` loads
        # proxify like `self.h[i]` would
        if (prov.is_unpackable() and not isinstance(obj, (str, bytes, dict))
                and hasattr(obj, "__len__") and hasattr(obj, "__getitem__")):
            frame.push(wrap(_ProvenanceIter(obj, prov), Provenance("op")))
        else:
            frame.push(wrap(iter(obj), Provenance("op")))
        return None

    def op_FOR_ITER(self, frame, fn, ins):
        it = unwrap(frame.peek(1))
        if isinstance(it, _ProvenanceIter):
            if it.i >= len(it.obj):
                frame.pop()
                idx = frame.offset_to_idx[ins.argval]
                nxt = frame.instrs[idx]
                return nxt.offset + 2 if nxt.opname == "END_FOR" else nxt.offset
            i = it.i
            it.i += 1
            frame.push(self._loaded(it.obj[i], Provenance("item", i, it.prov)))
            return None
        try:
            v = next(it)
        except StopIteration:
            frame.pop()  # the iterator; skip END_FOR at the target
            idx = frame.offset_to_idx[ins.argval]
            nxt = frame.instrs[idx]
            return nxt.offset + 2 if nxt.opname == "END_FOR" else nxt.offset
        frame.push(wrap(v, Provenance("op")))
        return None

    def op_END_FOR(self, frame, fn, ins):
        # only reached by a jump landing exactly here (we skip it after
        # exhaustion); pops the iterator remnants if present
        if frame.stack:
            frame.pop()
        return None

    def op_JUMP_FORWARD(self, frame, fn, ins):
        return ins.argval

    def op_JUMP_BACKWARD(self, frame, fn, ins):
        return ins.argval

    op_JUMP_BACKWARD_NO_INTERRUPT = op_JUMP_BACKWARD

    def _truthy(self, v) -> bool:
        raw = unwrap(v)
        from ..core.proxies import NumberProxy, Proxy, TensorProxy, pyval

        if isinstance(raw, TensorProxy):
            raise InterpreterError(
                "data-dependent control flow on a traced tensor (bool(TensorProxy)) — "
                "use jax.lax.cond / select, or lift the condition out of the jitted fn")
        if isinstance(raw, NumberProxy):
            self.on_sharp_edge("branch on a NumberProxy value specializes the trace to this value")
            return bool(pyval(raw))
        return bool(raw)

    def op_POP_JUMP_IF_TRUE(self, frame, fn, ins):
        return ins.argval if self._truthy(frame.pop()) else None

    def op_POP_JUMP_IF_FALSE(self, frame, fn, ins):
        return ins.argval if not self._truthy(frame.pop()) else None

    def op_POP_JUMP_IF_NONE(self, frame, fn, ins):
        return ins.argval if unwrap(frame.pop()) is None else None

    def op_POP_JUMP_IF_NOT_NONE(self, frame, fn, ins):
        return ins.argval if unwrap(frame.pop()) is not None else None

    def op_RETURN_VALUE(self, frame, fn, ins):
        raise _Return(frame.pop() if frame.stack else wrap(None))

    # ---- calls ----
    def op_KW_NAMES(self, frame, fn, ins):
        frame._kwnames = ins.argval
        return None

    def op_CALL(self, frame, fn, ins):
        argc = ins.arg
        kwnames = getattr(frame, "_kwnames", ())
        frame._kwnames = ()
        args = self._popn(frame, argc)
        s_upper = frame.pop()
        s_deeper = frame.pop()
        if s_deeper is NULL:
            callee, self_arg = s_upper, None
        elif s_upper is NULL:
            callee, self_arg = s_deeper, None
        else:
            callee, self_arg = s_deeper, s_upper
        if kwnames:
            n_kw = len(kwnames)
            kwargs = dict(zip(kwnames, args[argc - n_kw:]))
            args = args[: argc - n_kw]
        else:
            kwargs = {}
        if self_arg is not None:
            args = [self_arg] + list(args)
        prov = callee.provenance if isinstance(callee, WrappedValue) else OPAQUE_PROVENANCE
        frame.push(self.call(callee, args, kwargs, prov))
        return None

    def op_CALL_FUNCTION_EX(self, frame, fn, ins):
        kwargs = unwrap(frame.pop()) if ins.arg & 1 else {}
        args = list(unwrap(frame.pop()))
        callee = frame.pop()
        maybe_null = frame.pop()
        if maybe_null is not NULL:
            # stack had [callable, self?]: rare; push back
            frame.push(maybe_null)
        # keep the callee's provenance: a bound method's `self` chains back to
        # the captured root through it (same as op_CALL)
        prov = callee.provenance if isinstance(callee, WrappedValue) else OPAQUE_PROVENANCE
        frame.push(self.call(callee, [wrap(a, Provenance("op")) for a in args],
                             {k: wrap(v, Provenance("op")) for k, v in kwargs.items()},
                             prov))
        return None

    def op_CALL_INTRINSIC_1(self, frame, fn, ins):
        which = ins.arg
        v = frame.pop()
        if which == 5:  # UNARY_POSITIVE
            frame.push(wrap(+unwrap(v), Provenance("op")))
        elif which == 6:  # LIST_TO_TUPLE
            frame.push(wrap(tuple(unwrap(v)), Provenance("op")))
        elif which == 3:  # STOPITERATION_ERROR
            frame.push(v)
        else:
            raise InterpreterError(f"unsupported CALL_INTRINSIC_1 code {which}")
        return None

    def op_MAKE_FUNCTION(self, frame, fn, ins):
        code = unwrap(frame.pop())
        flags = ins.arg
        closure = tuple(unwrap(c) if isinstance(c, WrappedValue) else c
                        for c in (unwrap(frame.pop()) if flags & 0x08 else ()))
        annotations = unwrap(frame.pop()) if flags & 0x04 else None
        kwdefaults = unwrap(frame.pop()) if flags & 0x02 else None
        defaults = unwrap(frame.pop()) if flags & 0x01 else None
        new_fn = types.FunctionType(code, frame.f_globals, code.co_name,
                                    tuple(defaults) if defaults else None, closure or None)
        if kwdefaults:
            new_fn.__kwdefaults__ = kwdefaults
        if annotations:
            if isinstance(annotations, dict):
                new_fn.__annotations__ = annotations
            else:
                # 3.12 pushes a flat (name, value, name, value, ...) tuple
                new_fn.__annotations__ = dict(zip(annotations[::2], annotations[1::2]))
        frame.push(wrap(new_fn, Provenance("op")))
        return None

    def op_RETURN_GENERATOR(self, frame, fn, ins):
        # create the interpreter-backed generator; the frame resumes from the
        # next instruction on first send (reference interpreter.py handles
        # generators the same way: the frame object IS the generator state)
        gen = InterpGenerator(self, frame, fn)
        frame.ip += 1
        raise _Return(wrap(gen, Provenance("op")))

    def op_YIELD_VALUE(self, frame, fn, ins):
        value = frame.pop()
        frame.ip += 1  # resume continues after the yield
        raise _Yield(value)

    def op_GET_YIELD_FROM_ITER(self, frame, fn, ins):
        it = unwrap(frame.peek(1))
        if not (isinstance(it, InterpGenerator) or isinstance(it, types.GeneratorType)):
            frame.push(wrap(iter(unwrap(frame.pop())), Provenance("op")))
        return None

    def op_SEND(self, frame, fn, ins):
        # STACK: [receiver, value]; send value into receiver. On StopIteration
        # replace value with the result and jump by delta (receiver removed by
        # END_SEND at the jump target).
        value = unwrap(frame.pop())
        receiver = unwrap(frame.peek(1))
        try:
            if value is None:
                res = next(receiver) if hasattr(receiver, "__next__") else receiver.send(None)
            else:
                res = receiver.send(value)
        except StopIteration as e:
            frame.push(wrap(e.value, Provenance("op")))
            return ins.argval
        frame.push(wrap(res, Provenance("op")))
        return None

    def op_END_SEND(self, frame, fn, ins):
        value = frame.pop()
        frame.pop()  # receiver
        frame.push(value)
        return None

    def op_JUMP_BACKWARD_NO_INTERRUPT(self, frame, fn, ins):
        return ins.argval

    # ---- exceptions ----
    def op_PUSH_EXC_INFO(self, frame, fn, ins):
        exc = frame.pop()
        frame.push(wrap(None))  # previous exc_info placeholder
        frame.push(exc)
        frame.exc_stack.append(unwrap(exc))  # current exception, for bare raise
        return None

    def op_CHECK_EXC_MATCH(self, frame, fn, ins):
        typ = unwrap(frame.pop())
        exc = unwrap(frame.peek(1))
        frame.push(wrap(isinstance(exc, typ), Provenance("op")))
        return None

    def op_POP_EXCEPT(self, frame, fn, ins):
        frame.pop()
        if frame.exc_stack:
            frame.exc_stack.pop()
        return None

    def op_RERAISE(self, frame, fn, ins):
        exc = unwrap(frame.pop())
        if ins.arg:
            frame.pop()  # lasti
        raise exc

    def op_RAISE_VARARGS(self, frame, fn, ins):
        if ins.arg == 0:
            if frame.exc_stack:
                raise frame.exc_stack[-1]
            raise InterpreterError("bare raise outside exception handler is unsupported")
        if ins.arg == 2:
            cause = unwrap(frame.pop())
            exc = unwrap(frame.pop())
            raise exc from cause
        raise unwrap(frame.pop())

    def op_GET_LEN(self, frame, fn, ins):
        frame.push(wrap(len(unwrap(frame.peek(1))), Provenance("op")))
        return None

    # ---- with blocks ----
    def op_BEFORE_WITH(self, frame, fn, ins):
        mgr = unwrap(frame.pop())
        exit_fn = type(mgr).__exit__.__get__(mgr)
        frame.push(wrap(exit_fn, Provenance("op")))
        frame.push(wrap(type(mgr).__enter__(mgr), Provenance("op")))
        return None

    def op_WITH_EXCEPT_START(self, frame, fn, ins):
        exc = unwrap(frame.peek(1))
        exit_fn = unwrap(frame.peek(4))
        res = exit_fn(type(exc), exc, getattr(exc, "__traceback__", None))
        frame.push(wrap(res, Provenance("op")))
        return None

    # ---- imports (execute natively) ----
    def op_IMPORT_NAME(self, frame, fn, ins):
        fromlist = unwrap(frame.pop())
        level = unwrap(frame.pop())
        mod = __import__(ins.argval, frame.f_globals, None, fromlist, level)
        frame.push(wrap(mod, Provenance("op")))
        return None

    def op_IMPORT_FROM(self, frame, fn, ins):
        mod = unwrap(frame.peek(1))
        frame.push(wrap(getattr(mod, ins.argval), Provenance("op")))
        return None


class _Return(Exception):
    def __init__(self, value: WrappedValue):
        self.value = value


def _install_extra_opcodes(cls):
    """Name-space ops, match statements, asserts, class building — the long
    tail of the reference's 188 opcode handlers (interpreter.py:1257)."""

    def op_STORE_NAME(self, frame, fn, ins):
        frame.locals[ins.argval] = frame.pop()
        return None

    def op_DELETE_NAME(self, frame, fn, ins):
        frame.locals.pop(ins.argval, None)
        return None

    def op_DELETE_GLOBAL(self, frame, fn, ins):
        del frame.f_globals[ins.argval]
        return None

    def op_LOAD_ASSERTION_ERROR(self, frame, fn, ins):
        frame.push(wrap(AssertionError, Provenance("const")))
        return None

    def op_EXTENDED_ARG(self, frame, fn, ins):
        return None  # dis already folds the extended arg into the next instruction

    def op_DICT_MERGE(self, frame, fn, ins):
        other = unwrap(frame.pop())
        target = unwrap(frame.peek(ins.arg))
        for k in other:
            if k in target:
                raise TypeError(f"got multiple values for keyword argument {k!r}")
        target.update(other)
        return None

    def op_SETUP_ANNOTATIONS(self, frame, fn, ins):
        if "__annotations__" not in frame.locals:
            frame.locals["__annotations__"] = wrap({}, Provenance("const"))
        return None

    def op_LOAD_LOCALS(self, frame, fn, ins):
        frame.push(wrap({k: unwrap(v) for k, v in frame.locals.items()}, Provenance("op")))
        return None

    def op_LOAD_BUILD_CLASS(self, frame, fn, ins):
        frame.push(wrap(builtins.__build_class__, Provenance("const")))
        return None

    # -- match statements (PEP 634) --
    def op_MATCH_SEQUENCE(self, frame, fn, ins):
        import collections.abc as abc

        subject = unwrap(frame.peek(1))
        ok = isinstance(subject, abc.Sequence) and not isinstance(subject, (str, bytes, bytearray))
        frame.push(wrap(ok, Provenance("op")))
        return None

    def op_MATCH_MAPPING(self, frame, fn, ins):
        import collections.abc as abc

        frame.push(wrap(isinstance(unwrap(frame.peek(1)), abc.Mapping), Provenance("op")))
        return None

    def op_MATCH_KEYS(self, frame, fn, ins):
        keys = unwrap(frame.peek(1))
        subject = unwrap(frame.peek(2))
        if all(k in subject for k in keys):
            frame.push(wrap(tuple(subject[k] for k in keys), Provenance("op")))
        else:
            frame.push(wrap(None, Provenance("const")))
        return None

    # builtins where `case cls(x):` binds the subject itself (CPython MATCH_SELF)
    _MATCH_SELF_TYPES = (bool, bytearray, bytes, dict, float, frozenset, int,
                         list, set, str, tuple)

    def op_MATCH_CLASS(self, frame, fn, ins):
        kwd_attrs = unwrap(frame.pop())
        cls_ = unwrap(frame.pop())
        subject = unwrap(frame.pop())
        count = ins.arg
        if not isinstance(subject, cls_):
            frame.push(wrap(None, Provenance("const")))
            return None
        attrs = []
        try:
            if count:
                if cls_ in _MATCH_SELF_TYPES and not hasattr(cls_, "__match_args__"):
                    if count != 1:
                        raise TypeError(f"{cls_.__name__}() accepts 1 positional sub-pattern")
                    attrs.append(subject)
                else:
                    match_args = getattr(cls_, "__match_args__", ())
                    if len(match_args) < count:
                        raise TypeError(f"{cls_.__name__}() accepts {len(match_args)} positional sub-patterns")
                    for i in range(count):
                        attrs.append(getattr(subject, match_args[i]))
            for name in kwd_attrs:
                attrs.append(getattr(subject, name))
        except AttributeError:
            frame.push(wrap(None, Provenance("const")))
            return None
        frame.push(wrap(tuple(attrs), Provenance("op")))
        return None

    for name, impl in list(locals().items()):
        if name.startswith("op_"):
            setattr(cls, name, impl)
    return cls


class _Yield(Exception):
    def __init__(self, value: WrappedValue):
        self.value = value


class InterpGenerator:
    """Interpreter-backed generator: the suspended Frame IS the generator
    state (reference interpreter.py runs generator frames the same way).
    Supports iteration, send, throw (delivered through the frame's exception
    table), and close."""

    def __init__(self, interp: "Interpreter", frame: "Frame", fn):
        self._interp = interp
        self._frame = frame
        self._fn = fn
        self._started = False
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        return self.send(None)

    def _resume(self):
        interp, frame = self._interp, self._frame
        interp.depth += 1
        try:
            try:
                result = interp.run_frame(frame, self._fn)
            except _Yield as y:
                return unwrap(y.value)
            except BaseException:
                # body raised: the generator is finished (CPython: further
                # next() raises StopIteration, not a frame re-execution)
                self._done = True
                raise
            self._done = True
            raise StopIteration(unwrap(result))
        finally:
            interp.depth -= 1

    def send(self, value):
        if self._done:
            raise StopIteration
        if not self._started and value is not None:
            raise TypeError("can't send non-None value to a just-started generator")
        # CPython pushes the sent value on every resume (the generator body
        # pops or stores it — the first POP_TOP discards the initial None)
        self._frame.push(wrap(value, Provenance("op")))
        self._started = True
        return self._resume()

    def throw(self, exc, *rest):
        if isinstance(exc, type):
            exc = exc(*rest) if rest else exc()
        if self._done or not self._started:
            self._done = True
            raise exc
        handled = self._interp._handle_exception(self._frame, exc)
        if not handled:
            self._done = True
            raise exc
        return self._resume()

    def close(self):
        if self._done or not self._started:
            self._done = True
            return
        try:
            self.throw(GeneratorExit)
        except (GeneratorExit, StopIteration):
            self._done = True
            return
        # the generator caught GeneratorExit and yielded again
        self._done = True
        raise RuntimeError("generator ignored GeneratorExit")


_UNBOUND = WrappedValue(object(), Provenance("const"))  # LOAD_FAST_AND_CLEAR marker


def _bind_args(fn: types.FunctionType, args, kwargs) -> dict[str, Any]:
    """Bind call args to parameter names, keeping WrappedValues; wrap each
    bound arg with 'arg' provenance if it doesn't already carry one."""
    import inspect

    code = fn.__code__
    if any(n.startswith(".") for n in code.co_varnames[: code.co_argcount]):
        # genexpr/comprehension code objects take the implicit '.0' iterator
        # argument, which inspect refuses to name — bind positionally
        out = {}
        for name, val in zip(code.co_varnames[: code.co_argcount], args):
            out[name] = val if isinstance(val, WrappedValue) else wrap(val, Provenance("arg", name))
        return out

    # follow_wrapped=False: we are binding THIS code object's parameters, not
    # the signature functools.wraps advertises
    sig = inspect.Signature.from_callable(fn, follow_wrapped=False)
    raw_args = list(args)
    bound = sig.bind(*raw_args, **kwargs)
    bound.apply_defaults()
    out: dict[str, Any] = {}
    for i, (name, val) in enumerate(bound.arguments.items()):
        param = sig.parameters[name]
        if param.kind == inspect.Parameter.VAR_POSITIONAL:
            out[name] = wrap(tuple(unwrap(v) for v in val), Provenance("arg", name))
        elif param.kind == inspect.Parameter.VAR_KEYWORD:
            out[name] = wrap({k: unwrap(v) for k, v in val.items()}, Provenance("arg", name))
        elif isinstance(val, WrappedValue):
            out[name] = val
        else:
            out[name] = wrap(val, Provenance("arg", name))
    return out


_install_extra_opcodes(Interpreter)


def interpret(fn: Callable, *args, lookasides: dict | None = None,
              on_provenance_load=None, on_sharp_edge=None, **kwargs):
    """Interpret ``fn(*args, **kwargs)`` opcode-by-opcode; returns the raw
    result (reference interpreter.py:7599 interpret)."""
    interp = Interpreter(lookasides=lookasides, on_provenance_load=on_provenance_load,
                         on_sharp_edge=on_sharp_edge)
    if _is_opaque_function(fn) and not isinstance(fn, types.FunctionType):
        raise InterpreterError(f"cannot interpret non-Python callable {fn!r}")
    res = interp.call(wrap(fn), [wrap(a, Provenance("arg", i)) for i, a in enumerate(args)],
                      {k: wrap(v, Provenance("arg", k)) for k, v in kwargs.items()})
    return unwrap(res)
