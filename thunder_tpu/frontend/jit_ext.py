"""General jit: interpreter-based acquisition with prologue generation.

Re-design of reference thunder/core/jit_ext.py:2149 (thunder_general_jit).
Arbitrary Python callables are executed by the bytecode interpreter
(frontend/interpreter.py); tensors captured from the environment — module
globals, closure cells, attribute/item chains (e.g. ``self.fc1.weight`` of a
model held in a closure) — are proxified on first load and become *prologue
inputs*: the generated prologue trace re-extracts them with UNPACK_* prims
and validates their metadata with CHECK_* prims on every call, so a cache
hit is exactly "a prologue that runs without raising" (reference
thunder/__init__.py:711-743). Captured Python scalars are baked into the
computation as constants and guarded by value checks in the prologue
(CONSTANT_VALUES cache semantics).
"""
from __future__ import annotations

import types
import warnings
from typing import Any, Callable, NamedTuple, Optional, Sequence

from ..core import dtypes, prims
from ..core.proxies import AnyProxy, Proxy, TensorProxy, proxy_from_jax
from ..core.pytree import tree_flatten, tree_unflatten
from ..core.trace import TraceCtx, tracectx
from .interpreter import (
    Interpreter,
    InterpreterError,
    Provenance,
    WrappedValue,
    unwrap,
    wrap,
)


def _is_tensor_like(x) -> bool:
    from ..core.baseutils import is_tensor_like as _itl
    return _itl(x) and not isinstance(x, Proxy)


def _unwrap_param(x):
    data = getattr(x, "data", None)
    return data if data is not None and hasattr(x, "requires_grad") else x


def _prov_key(prov: Provenance) -> tuple:
    return tuple((p.kind, p.key) for p in prov.chain())


class CapturedTensor(NamedTuple):
    proxy: TensorProxy
    provenance: Provenance
    value: Any  # concrete array at trace time (for metadata)


class CapturedScalarCheck(NamedTuple):
    provenance: Provenance
    value: Any


class JitResults(NamedTuple):
    prologue_trc: TraceCtx
    computation_trc: TraceCtx
    captured: list
    sharp_edges: list
    log: tuple = ()


class GeneralJitCtx:
    """Per-trace state: proxification of captured values + sharp edge log
    (reference jit_ext.py:162 JitCtx)."""

    def __init__(self, trace: TraceCtx, *, sharp_edges: str = "allow"):
        self.trace = trace
        self.captured: list[CapturedTensor] = []
        self.scalar_checks: list[CapturedScalarCheck] = []
        self._by_key: dict[tuple, Any] = {}
        self.sharp_edges_mode = sharp_edges  # 'allow' | 'warn' | 'error'
        self.sharp_edges: list[str] = []

    def on_provenance_load(self, value: Any, prov: Provenance) -> Any:
        if not prov.is_unpackable():
            return value
        root = prov.root().kind
        if root not in ("global", "closure"):
            return value
        key = _prov_key(prov)
        if key in self._by_key:
            return self._by_key[key]
        out = self._proxify(value, prov, depth=0)
        if out is not value:
            self._by_key[key] = out
        return out

    _MAX_CONTAINER_DEPTH = 3

    def _proxify(self, value: Any, prov: Provenance, depth: int) -> Any:
        if isinstance(value, types.ModuleType):
            # modules are never tensors/containers-of-tensors; skipping them
            # keeps walks over e.g. sys.modules cheap and side-effect free
            return value
        raw = _unwrap_param(value)
        if _is_tensor_like(raw):
            key = _prov_key(prov)
            if key in self._by_key:
                return self._by_key[key]
            rg = bool(getattr(value, "requires_grad", False))
            p = proxy_from_jax(raw, requires_grad=rg)
            self.captured.append(CapturedTensor(p, prov, raw))
            self._by_key[key] = p
            return p
        if isinstance(value, (int, float, bool)) and not isinstance(value, Proxy):
            if depth == 0:
                # baked constant, guarded in the prologue; container entries
                # are guarded transitively by the tensor checks around them
                self.scalar_checks.append(CapturedScalarCheck(prov, value))
                self._by_key[_prov_key(prov)] = value
            return value
        # containers: return a copy with tensor entries proxified so native
        # iteration (for/enumerate/zip) yields proxies with item provenance
        if depth < self._MAX_CONTAINER_DEPTH:
            if isinstance(value, (list, tuple)):
                items = [self._proxify(v, Provenance("item", i, prov), depth + 1)
                         for i, v in enumerate(value)]
                if any(a is not b for a, b in zip(items, value)):
                    return type(value)(items)
            elif isinstance(value, dict):
                items = {k: self._proxify(v, Provenance("item", k, prov), depth + 1)
                         for k, v in value.items() if isinstance(k, (str, int))}
                if any(items.get(k) is not v for k, v in value.items()):
                    return {**value, **items}
        return value

    def on_sharp_edge(self, msg: str) -> None:
        self.sharp_edges.append(msg)
        if self.sharp_edges_mode == "error":
            raise InterpreterError(f"sharp edge: {msg}")
        if self.sharp_edges_mode == "warn":
            warnings.warn(f"thunder_tpu jit sharp edge: {msg}")


def general_jit(fn: Callable, args, kwargs, *, sharp_edges: str = "allow",
                lookasides: dict | None = None,
                symbolic_numbers: bool = False,
                record_log: bool = False,
                grad_mask: Sequence[bool] | None = None) -> tuple[JitResults, Any, list, list]:
    """Interpret fn over proxies, producing prologue + computation traces.

    Returns (JitResults, treedef, tensor_mask, leaves) — same surface as
    thunder_tpu.acquire_trace plus the prologue.

    symbolic_numbers: number arguments become NumberProxy runtime inputs
    (SYMBOLIC_VALUES cache semantics). A number whose concrete value the
    traced program *observes* (branching, arithmetic, pyval) is pinned and
    value-guarded in the prologue; unobserved numbers generalize across calls
    (reference thunder/core/options.py:45-49 + constraint propagation)."""
    import contextlib

    from ..core.proxies import NumberProxy, number_observation

    leaves, treedef = tree_flatten((args, kwargs))
    trc = TraceCtx(fn)
    ctx = GeneralJitCtx(trc, sharp_edges=sharp_edges)

    proxy_leaves = []
    tensor_mask = []
    number_proxies: list[NumberProxy] = []
    pinned: set[str] = set()
    with tracectx(trc):
        for li, leaf in enumerate(leaves):
            if _is_tensor_like(leaf):
                rg = bool(getattr(leaf, "requires_grad", False))
                if grad_mask is not None and li < len(grad_mask):
                    rg = rg or bool(grad_mask[li])
                p = proxy_from_jax(leaf, requires_grad=rg)
                proxy_leaves.append(p)
                tensor_mask.append(True)
            elif symbolic_numbers and isinstance(leaf, (int, float)) and not isinstance(leaf, bool):
                np_ = NumberProxy(leaf, type(leaf))
                np_.is_symbolic = True
                proxy_leaves.append(np_)
                number_proxies.append(np_)
                tensor_mask.append(False)
            else:
                proxy_leaves.append(leaf)
                tensor_mask.append(False)
        arg_proxies = tuple(p for p, m in zip(proxy_leaves, tensor_mask) if m)
        pargs, pkwargs = tree_unflatten(treedef, proxy_leaves)

        interp = Interpreter(lookasides=lookasides,
                             on_provenance_load=ctx.on_provenance_load,
                             on_sharp_edge=ctx.on_sharp_edge,
                             record_log=record_log)
        observe_ctx = (number_observation(lambda p: pinned.add(p.name))
                       if symbolic_numbers else contextlib.nullcontext())
        with observe_ctx:
            result = unwrap(interp.call(
                wrap(fn),
                [wrap(a, Provenance("arg", i)) for i, a in enumerate(pargs)],
                {k: wrap(v, Provenance("arg", k)) for k, v in pkwargs.items()},
            ))
        prims.python_return(result)
    trc.args = arg_proxies + tuple(number_proxies) + tuple(c.proxy for c in ctx.captured)

    pro = _build_prologue(fn, arg_proxies, ctx, number_proxies=number_proxies, pinned=pinned)
    res = JitResults(pro, trc, ctx.captured, ctx.sharp_edges, interp.log)
    return res, treedef, tensor_mask, leaves


def _build_prologue(fn: Callable, arg_proxies: Sequence[TensorProxy], ctx: GeneralJitCtx,
                    *, number_proxies: Sequence = (), pinned: frozenset = frozenset()) -> TraceCtx:
    """Prologue trace: validate args, re-extract + validate captured values.

    Signature: prologue(*tensor_args) -> (*tensor_args, *captured_tensors);
    the root callable is interned as a constant in the generated code."""
    pro = TraceCtx(None, prologue=True)
    pro._name = "prologue"
    unpack_syms = {
        "global": prims.unpack_global,
        "closure": prims.unpack_closure,
        "attr": prims.unpack_attr,
        "item": prims.unpack_item,
    }
    from ..core.proxies import NumberProxy

    with tracectx(pro):
        qargs = []
        for p in arg_proxies:
            q = TensorProxy(p.name, shape=p.shape, dtype=p.dtype, device=p.device)
            qargs.append(q)
            prims.check_tensor_shape_and_metadata(q, p.shape, p.dtype, str(p.device))
        qnums = []
        for np_ in number_proxies:
            qn = NumberProxy(np_.value, np_.python_type, name=np_.name)
            qn.is_symbolic = True
            pro.add_name(qn.name)
            qnums.append(qn)
            # pinned (observed) numbers guard the exact value; unobserved
            # numbers guard only the python type and generalize across calls
            prims.check_number_type_and_value(
                qn, np_.python_type, np_.value if np_.name in pinned else None)
        pro.args = tuple(qargs) + tuple(qnums)

        # emit unpack chains, sharing intermediate objects across captures
        emitted: dict[tuple, Proxy] = {}

        def emit_chain(prov: Provenance, final_proxy: Proxy | None):
            chain = prov.chain()
            parent: Any = fn
            parent_proxy: Any = fn  # printed interned for the root
            for depth, p in enumerate(chain):
                key = tuple((q.kind, q.key) for q in chain[: depth + 1])
                if key in emitted:
                    parent_proxy = emitted[key]
                    continue
                is_last = depth == len(chain) - 1
                out: Proxy = (final_proxy if (is_last and final_proxy is not None)
                              else AnyProxy(name=pro.make_name("obj")))
                sym = unpack_syms.get(p.kind)
                if sym is None:
                    raise InterpreterError(f"cannot build prologue for provenance {prov!r}")
                src = parent_proxy if depth > 0 else fn
                bsym = sym.bind(src, p.key, output=out)
                pro.add_bound_symbol(bsym)
                emitted[key] = out
                parent_proxy = out
            return parent_proxy

        cap_outs = []
        for cap in ctx.captured:
            q = TensorProxy(cap.proxy.name, shape=cap.proxy.shape, dtype=cap.proxy.dtype,
                            device=cap.proxy.device)
            pro.add_name(q.name)
            raw = emit_chain(cap.provenance, None)
            # Parameter/buffer wrappers (nn modules) -> raw array for the
            # computation; identity for plain captured arrays
            pro.add_bound_symbol(prims.unpack_tensor_data.bind(raw, output=q))
            prims.check_tensor_shape_and_metadata(q, cap.proxy.shape, cap.proxy.dtype,
                                                  str(cap.proxy.device))
            cap_outs.append(q)

        for chk in ctx.scalar_checks:
            v = emit_chain(chk.provenance, None)
            prims.check_number_type_and_value(v, type(chk.value), chk.value)

        prims.python_return(tuple(qargs) + tuple(qnums) + tuple(cap_outs))
    return pro
