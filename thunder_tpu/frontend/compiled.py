"""The callable produced by jit(..., interpretation="python interpreter").

Mirrors reference thunder/__init__.py:695-743 semantics: cache entries hold
(prologue, computation) callables, and a cache *hit is the first prologue that
runs without raising* — the prologue both re-extracts captured values (so
updated parameters flow in) and validates metadata/guarded scalars (so any
environment change that invalidates the trace forces recompilation).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Sequence

from ..common import CompileStats
from ..core.pytree import tree_flatten
from ..core.transform_common import dce
from ..observability import events as _obs
from ..observability import metrics as _obs_metrics
from ..observability import runtime as _obs_runtime
from ..observability.events import key_digest as _key_digest
from .jit_ext import _is_tensor_like, _unwrap_param, general_jit


class InterpretedEntry:
    __slots__ = ("prologue_fn", "computation_fn", "prologue_trc", "computation_trc", "shape_key")

    def __init__(self, prologue_fn, computation_fn, prologue_trc, computation_trc, shape_key):
        self.prologue_fn = prologue_fn
        self.computation_fn = computation_fn
        self.prologue_trc = prologue_trc
        self.computation_trc = computation_trc
        self.shape_key = shape_key


class ShapeKeyedMRU:
    """shape_key -> [entries], most-recently-hit first.

    The cache discipline shared by the interpreter frontend's specialization
    cache and the serving engine's bucketed prefill entries
    (thunder_tpu/serving/scheduler.py): lookup is one dict probe plus a scan
    of the bucket's snapshot, and the entry that served the call is promoted
    to the front so the steady-state probe order stays one-deep.

    Concurrency contract: bucket MUTATIONS (promotion, insertion) hold
    ``lock``; the steady-state hit (front entry) never locks. Readers scan
    an atomic ``snapshot`` (one C-level list copy under the GIL) and every
    mutation is a single atomic list op — ``insert`` is one insert-at-front,
    ``promote`` replaces the contents in ONE slice assignment — so a racing
    promotion can never hide an entry from a scan (which would cost a
    recompile and grow a duplicate specialization)."""

    __slots__ = ("buckets", "lock")

    def __init__(self):
        self.buckets: dict = {}
        self.lock = threading.Lock()

    def snapshot(self, key) -> list:
        """Atomic copy of the bucket for ``key`` (empty when absent); safe
        to scan without holding ``lock``."""
        bucket = self.buckets.get(key)
        return list(bucket) if bucket is not None else []

    def insert(self, key, entry) -> None:
        """Register ``entry`` at the FRONT of its bucket: the newest
        specialization probes first — its guards match the call that just
        built it, which steady state repeats."""
        with self.lock:
            self.buckets.setdefault(key, []).insert(0, entry)

    def promote(self, key, entry) -> None:
        """Move ``entry`` to the front of its bucket. The slice assignment
        replaces the contents in ONE atomic operation — unlocked snapshots
        never see the entry mid-flight (a remove+insert pair would have a
        window where the entry is in neither position)."""
        with self.lock:
            bucket = self.buckets.get(key)
            if bucket is not None:
                bucket[:] = [entry] + [e for e in bucket if e is not entry]

    def clear(self) -> None:
        with self.lock:
            self.buckets.clear()

    def __len__(self) -> int:
        return len(self.buckets)

    def __contains__(self, key) -> bool:
        return key in self.buckets


class InterpretedFunction:
    """jit-compiled via the bytecode interpreter frontend."""

    def __init__(self, fn: Callable, *, executors=None, sharp_edges: str = "allow",
                 transforms: Sequence = (), lookasides: dict | None = None,
                 cache: str = "constant values", disable_fusion: bool = False,
                 **compile_options):
        if cache not in ("constant values", "no caching", "symbolic values", "same input"):
            raise ValueError(
                f"cache={cache!r} is not supported by the interpreter frontend "
                f"(supported: 'constant values', 'no caching', 'symbolic values', 'same input')")
        self.fn = fn
        self.executors = executors
        self.sharp_edges = sharp_edges
        self.transforms = list(transforms or ())
        self.lookasides = lookasides
        self.cache_option = cache
        self.disable_fusion = disable_fusion
        dbg = compile_options.pop("debug_options", None)
        # per-function pass-interposed trace checking (analysis/manager.py);
        # TT_CHECK_TRACES covers every function without the option
        self._check_traces = bool(dbg is not None and getattr(dbg, "check_traces", False))
        self.record_interpreter_log = bool(
            compile_options.pop("record_interpreter_log", False)
            or (dbg is not None and (getattr(dbg, "show_interpreter_log", False)
                                     or getattr(dbg, "record_interpreter_history", False))))
        self._print_interpreter_log = bool(dbg is not None and getattr(dbg, "show_interpreter_log", False))
        self._entries: list[InterpretedEntry] = []
        # shape_key -> [entries], most-recently-hit first: cache lookup is
        # one dict probe + (usually) one prologue run instead of a linear
        # scan over every specialization ever compiled (concurrency
        # contract documented on ShapeKeyedMRU). _entries_by_key/_mru_lock
        # alias the MRU internals so existing introspection keeps working.
        self._mru = ShapeKeyedMRU()
        self._entries_by_key: dict = self._mru.buckets
        self._mru_lock = self._mru.lock
        # (treedef, leaf types) -> (mask, tensor_idx, number_idx): repeat
        # calls skip per-leaf _is_tensor_like re-masking. Keyed on the leaf
        # TYPES too because a treedef alone does not determine tensor-ness
        # (an int and an array flatten to the same treedef slot).
        self._leaf_plans: dict = {}
        self._cs = CompileStats()
        self.__name__ = getattr(fn, "__name__", type(fn).__name__)

    def _leaf_plan(self, leaves, treedef):
        key = (treedef, tuple(map(type, leaves)))
        plan = self._leaf_plans.get(key)
        if plan is None:
            mask = tuple(_is_tensor_like(l) for l in leaves)
            tensor_idx = tuple(i for i, m in enumerate(mask) if m)
            number_idx = tuple(
                i for i, (l, m) in enumerate(zip(leaves, mask))
                if not m and isinstance(l, (int, float)) and not isinstance(l, bool))
            plan = self._leaf_plans[key] = (mask, tensor_idx, number_idx)
        return plan

    def _shape_key(self, leaves, mask):
        symbolic = self.cache_option == "symbolic values"
        key = []
        for leaf, is_t in zip(leaves, mask):
            if is_t:
                key.append(("T", tuple(leaf.shape), str(leaf.dtype)))
            elif symbolic and isinstance(leaf, (int, float)) and not isinstance(leaf, bool):
                # symbolic numbers cache by type; the prologue value-guards
                # only the pinned (observed) ones
                key.append(("N", type(leaf).__name__))
            else:
                try:
                    hash(leaf)
                    # type name disambiguates 2 / 2.0 / True, which hash equal
                    key.append(("S", type(leaf).__name__, leaf))
                except TypeError:
                    key.append(("S", type(leaf).__name__, repr(leaf)))
        return tuple(key)

    def _compile(self, args, kwargs, shape_key) -> InterpretedEntry:
        from ..analysis import manager as _an
        from ..executors.passes import transform_for_execution
        from ..extend import resolve_executors

        chk = self._check_traces

        cs = self._cs
        key_digest = _key_digest(shape_key)
        phases: list = []
        root = _obs.span("compile", fn=self.__name__, cache_key=key_digest,
                         frontend="interpreter")
        with root:
            t0 = time.perf_counter_ns()
            with _obs.span("acquisition") as sp:
                res, treedef, mask, leaves = general_jit(self.fn, args, kwargs,
                                                         sharp_edges=self.sharp_edges,
                                                         lookasides=self.lookasides,
                                                         symbolic_numbers=self.cache_option == "symbolic values",
                                                         record_log=self.record_interpreter_log)
                sp.set(bsyms=len(res.computation_trc.bound_symbols))
            phases.append(sp)
            cs.last_interpreter_log = list(res.log)
            if self._print_interpreter_log and res.log:
                print("\n".join(res.log))
            cs.last_trace_tracing_time_ns = time.perf_counter_ns() - t0

            t1 = time.perf_counter_ns()
            pro, trc = res.prologue_trc, res.computation_trc
            _an.checkpoint("acquisition", trc, where=self.__name__, force=chk)
            _an.checkpoint("acquisition:prologue", pro, where=self.__name__, force=chk)
            traces = [trc]
            for tf in self.transforms:
                with _obs.span(f"transform:{type(tf).__name__}") as sp:
                    prev, prev_pro = trc, pro
                    pro, trc = tf.transform_traces_pre_autodiff(pro, trc, compile_data=None)
                    sp.set(bsyms=len(trc.bound_symbols))
                phases.append(sp)
                traces.append(trc)
                _an.checkpoint(f"transform:{type(tf).__name__}", trc, before=prev,
                               where=self.__name__, force=chk)
                if pro is not prev_pro:
                    # a rewritten prologue is verified too (see the driver in
                    # thunder_tpu/__init__.py) — prologue corruption must
                    # blame its pass, not fail guards at dispatch
                    _an.checkpoint(f"transform:{type(tf).__name__}:prologue", pro,
                                   where=self.__name__, force=chk)
            with _obs.span("transform:dce") as sp:
                prev = trc
                trc = dce(trc)
                sp.set(bsyms=len(trc.bound_symbols))
            phases.append(sp)
            traces.append(trc)
            _an.checkpoint("transform:dce", trc, before=prev, where=self.__name__,
                           force=chk)
            executors = resolve_executors(self.executors or None)
            if self.disable_fusion:
                executors = [e for e in executors if not e.is_fusion_executor()]
            with _obs.span("executor_dispatch", executors=[e.name for e in executors]) as sp:
                ex_trc = transform_for_execution(trc, executors, check_traces=chk)
                sp.set(bsyms=len(ex_trc.bound_symbols))
            phases.append(sp)
            traces.append(ex_trc)
            for tf in self.transforms:
                with _obs.span(f"transform_post:{type(tf).__name__}") as sp:
                    prev = ex_trc
                    ex_trc = tf.transform_trace_post_optimization(ex_trc, compile_data=None)
                phases.append(sp)
                traces.append(ex_trc)
                _an.checkpoint(f"transform_post:{type(tf).__name__}", ex_trc,
                               before=prev, where=self.__name__, force=chk)
            cs.last_trace_transform_time_ns = time.perf_counter_ns() - t1

            t2 = time.perf_counter_ns()
            with _obs.span("codegen") as sp:
                entry = InterpretedEntry(pro.python_callable(), ex_trc.python_callable(),
                                         pro, ex_trc, shape_key)
            phases.append(sp)
            cs.last_compile_time_ns = time.perf_counter_ns() - t2
        cs.last_compile_report = {
            "fn": self.__name__,
            "cache_key": key_digest,
            "total_ms": round(root.dur_ms, 3),
            "phases": [{"name": p.name, "dur_ms": round(p.dur_ms, 3), **p.attrs}
                       for p in phases],
        }
        cs.last_traces = traces
        cs.last_prologue_traces = [pro]
        self._entries.append(entry)
        self._mru.insert(shape_key, entry)
        return entry

    def __call__(self, *args, **kwargs):
        cs = self._cs
        cs.calls += 1
        # one enabled() read gates every observability touch on this path:
        # disabled mode (the default) must not even CALL into the bus
        obs_on = _obs.enabled()
        t_host = time.perf_counter_ns() if obs_on else 0
        leaves, treedef = tree_flatten((args, kwargs))
        mask, tensor_idx, number_idx = self._leaf_plan(leaves, treedef)
        tensor_leaves = [_unwrap_param(leaves[i]) for i in tensor_idx]
        if self.cache_option == "same input" and self._entries:
            # reuse the sole entry unconditionally (reference SAME_INPUT:
            # the caller asserts inputs never change shape/type)
            entry = self._entries[0]
            cs.cache_hits += 1
            # run the prologue BEFORE the host_overhead timestamp, exactly
            # like the keyed-hit path, so the metric is comparable across
            # cache modes
            flat_inputs = entry.prologue_fn(*tensor_leaves)
            if obs_on:
                _obs_metrics.record_cache("trace", "hit", fn=self.__name__)
                # host_overhead is per-dispatch; TT_OBS_SAMPLE bounds its
                # volume on serving hot loops (counters stay exact)
                if _obs_runtime.step_sampled(self.__name__):
                    _obs.event("host_overhead", fn=self.__name__,
                               us=round((time.perf_counter_ns() - t_host) / 1e3, 2))
            return entry.computation_fn(*flat_inputs)
        shape_key = self._shape_key(leaves, mask)
        if self.cache_option == "symbolic values":
            # the prologue takes the runtime numbers after the tensors
            tensor_leaves = tensor_leaves + [leaves[i] for i in number_idx]
        if self.cache_option == "no caching":
            entry = self._compile(args, kwargs, shape_key)
            self._entries.clear()
            self._mru.clear()
            # this mode retains NOTHING between calls; keeping leaf plans
            # would grow without bound under varying argument structures
            self._leaf_plans.clear()
            return entry.computation_fn(*entry.prologue_fn(*tensor_leaves))
        # a cache hit is the first prologue that runs without raising; the
        # scan runs over an atomic snapshot and the serving entry is
        # promoted to the bucket front (ShapeKeyedMRU's contract)
        guard_failed = False
        bucket = self._mru.snapshot(shape_key)
        if bucket:
            for i, entry in enumerate(bucket):
                try:
                    flat_inputs = entry.prologue_fn(*tensor_leaves)
                except Exception:
                    guard_failed = True
                    continue
                if i:
                    self._mru.promote(shape_key, entry)
                cs.cache_hits += 1
                if obs_on:
                    _obs_metrics.record_cache("trace", "hit", fn=self.__name__)
                    if _obs_runtime.step_sampled(self.__name__):
                        _obs.event("host_overhead", fn=self.__name__,
                                   us=round((time.perf_counter_ns() - t_host) / 1e3, 2))
                return entry.computation_fn(*flat_inputs)
        cs.cache_misses += 1
        if obs_on:
            _obs_metrics.record_cache("trace", "miss", fn=self.__name__)
            _obs_metrics.record_recompile(
                _obs_metrics.REASON_SHAPE_CHANGE if self._entries
                else _obs_metrics.REASON_CACHE_MISS,
                fn=self.__name__, cache_key=_key_digest(shape_key),
                guard_failed=guard_failed)
        entry = self._compile(args, kwargs, shape_key)
        flat_inputs = entry.prologue_fn(*tensor_leaves)
        return entry.computation_fn(*flat_inputs)

    def prewarm(self, *args, **kwargs) -> bool:
        """Compile the specialization for these args WITHOUT executing it —
        the compile service's pre-dispatch entry point (fusion regions
        lower/compile in parallel and are served from the artifact store
        when warm; compile_service/parallel_compile.py). Returns True when
        a new entry was compiled, False when one already matched."""
        leaves, treedef = tree_flatten((args, kwargs))
        mask, _, number_idx = self._leaf_plan(leaves, treedef)
        shape_key = self._shape_key(leaves, mask)
        tensor_leaves = [_unwrap_param(leaves[i])
                         for i, m in enumerate(mask) if m]
        if self.cache_option == "symbolic values":
            # symbolic prologues take the runtime numbers after the tensors
            # (same convention as __call__) — without them every probe would
            # TypeError and prewarm would compile a duplicate specialization
            tensor_leaves = tensor_leaves + [leaves[i] for i in number_idx]
        for entry in self._mru.snapshot(shape_key):
            try:
                entry.prologue_fn(*tensor_leaves)
                return False  # an existing entry already serves these args
            except Exception:
                continue
        self._compile(args, kwargs, shape_key)
        return True

    @property
    def cache_hits(self):
        return int(self._cs.cache_hits)

    @property
    def cache_misses(self):
        return int(self._cs.cache_misses)
