"""CompileData / CompileStats / CacheEntry for the jit driver.

Counterpart of reference thunder/common.py:65-180 and thunder/__init__.py:258.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence


class CompileStats:
    """Per-compile timings and cache counters (reference thunder/common.py:65)."""

    def __init__(self):
        from .observability.metrics import AtomicCounter

        # atomic: concurrent inference threads share one compiled function,
        # and `cs.cache_hits += 1` on a plain int is a lost-update race
        self.cache_hits = AtomicCounter()
        self.cache_misses = AtomicCounter()
        self.calls = AtomicCounter()
        self.last_trace_tracing_time_ns = 0
        self.last_trace_transform_time_ns = 0
        self.last_compile_time_ns = 0
        self.last_traces: list = []
        self.last_backward_traces: list = []
        self.last_prologue_traces: list = []
        # phase-by-phase record of the most recent compile, populated by the
        # jit drivers on every compile (observability.last_compile_report)
        self.last_compile_report: dict | None = None


class CompileData:
    """Per-compile configuration (reference thunder/common.py:180)."""

    def __init__(
        self,
        *,
        fn: Callable,
        executors: Sequence = (),
        cache_option: str = "constant values",
        transforms: Sequence = (),
        disable_fusion: bool = False,
        compile_options: dict | None = None,
    ):
        self.fn = fn
        self.executors = tuple(executors)
        self.cache_option = cache_option
        self.transforms = list(transforms)
        self.disable_fusion = disable_fusion
        self.compile_options = dict(compile_options or {})
        self.is_module = False
        self.module = None
        # distributed state set by parallel transforms
        self.mesh = None
        self.process_group = None
        self.use_fsdp = False
        self.use_ddp = False

    def get_compile_option(self, name: str, default=None):
        return self.compile_options.get(name, default)


class EpilogueMixin:
    """Shared epilogue: replay recorded buffer mutations onto their owners.
    Under an ambient jax trace the values are tracers — they are stashed for
    the enclosing program to consume via consume_pending_effects() (TrainStep
    does this for its vag); an enclosing program that does not consume them
    loses the updates."""

    def apply_effects(self, effect_keys, effects):
        import jax as _jax

        if any(isinstance(e, _jax.core.Tracer) for e in effects):
            # a known enclosing program (TrainStep, gspmd_step) will call
            # consume_pending_effects(); anything else — e.g. a user wrapping
            # the module call in their own jax.jit — silently loses the
            # buffer updates, so say so loudly
            if not getattr(self, "_effects_consumer_attached", False):
                import warnings

                warnings.warn(
                    "this function mutates module buffers (e.g. BatchNorm "
                    "running stats) and is being traced by an ambient jax "
                    "transformation (jax.jit/shard_map) that will not apply "
                    "them — the buffer updates will be LOST. Use "
                    "thunder_tpu.training.TrainStep, or call the compiled "
                    "module outside jax.jit.",
                    stacklevel=3,
                )
            self._pending_effects = (effect_keys, tuple(effects))
            return
        for (owner, name), value in zip(effect_keys, effects):
            owner._buffers[name] = value

    def consume_pending_effects(self):
        out = getattr(self, "_pending_effects", None)
        self._pending_effects = None
        return out


class CacheEntry:
    """One compiled specialization (reference thunder/__init__.py:258)."""

    __slots__ = (
        "prologue_fn",
        "computation_fn",
        "backward_fn",
        "prologue_trc",
        "computation_trc",
        "backward_trc",
        "treedef",
        "tensor_mask",
        "static_leaves",
        "key",
        "effect_keys",  # [(owner_module, buffer_name)] epilogue targets
    )

    def __init__(self, **kw):
        for s in self.__slots__:
            setattr(self, s, kw.get(s))
