"""Inference engine: KV-cached autoregressive generation.

Capability counterpart of the reference's inference stack
(thunder/benchmarks/benchmark_inference.py:1-11: throughput, ms/token, TTFT,
TBOT; HF generate via thunder.jit + CUDA graphs). TPU-native design:

  - static shapes: the KV cache is a fixed (B, H, max_seq, D) buffer updated
    with dynamic_update_slice; prefill and decode are two cached trace
    specializations (the role CUDA graphs play in the reference is played by
    XLA whole-program compilation — each decode step is ONE dispatch).
  - the decode step is compiled once and reused for every token.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Optional

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

_NULL = contextlib.nullcontext()

from . import nn
from .observability import events as _obs
from .observability import flight_recorder as _obs_flight
from .observability import runtime as _obs_runtime
from .observability import telemetry as _obs_tel
from .ops import clang, ltorch


@dataclass
class GenerationMetrics:
    """TTFT/TBOT/throughput, mirroring the reference harness metrics."""

    ttft_s: float = 0.0
    tbot_s: float = 0.0
    tokens_per_sec: float = 0.0
    ms_per_token: float = 0.0
    n_new_tokens: int = 0


class KVCache:
    """Per-layer static-shape KV cache."""

    def __init__(self, n_layer: int, batch: int, n_kv_heads: int, max_seq: int, head_dim: int, dtype=jnp.bfloat16):
        shape = (batch, n_kv_heads, max_seq, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(n_layer)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(n_layer)]

    def as_tuple(self):
        return tuple(self.k), tuple(self.v)


def cached_sdpa(q, k_cache, v_cache, pos, scale=None):
    """Attention against the cache prefix [0, pos+q_len); pos may be a traced
    scalar so the same compiled decode step serves every position."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    kt = clang.matrix_transpose(k_cache)
    scores = ltorch.matmul(q, kt) * scale
    Lq = q.shape[-2]
    Lk = k_cache.shape[-2]
    import jax.numpy as _jnp

    q_pos = clang.ensure_proxy(_jnp.arange(Lq, dtype=_jnp.int32))
    if isinstance(pos, int):
        q_pos = q_pos + pos
    else:
        q_pos = q_pos + ltorch.reshape(pos, (1,))
    k_pos = clang.ensure_proxy(_jnp.arange(Lk, dtype=_jnp.int32))
    mask = ltorch.le(clang.unsqueeze(k_pos, 0), clang.unsqueeze(q_pos, 1))
    scores = ltorch.where(mask, scores, float("-inf"))
    probs = ltorch.softmax(scores, -1)
    probs = clang.maybe_convert_to_dtype(probs, v_cache.dtype)
    return ltorch.matmul(probs, v_cache)


def split_qkv_rope(block, cfg, x_n, cos, sin):
    """Project + split + rope one block's q/k/v for T tokens: the per-block
    attention-input plumbing shared by the dense decode engine below and the
    paged serving runner (serving/runner.py) — ONE implementation, so block
    math can never drift between solo and continuously-batched decoding
    (the serving tests pin exact token equality between the two).
    Returns q (B, nh, T, hs), k/v (B, ng, T, hs)."""
    from .models.litgpt import _apply_rope

    B, T, _ = x_n.shape
    nh, ng, hs = cfg.n_head, cfg.n_query_groups, cfg.head_size
    q_per_kv = nh // ng
    qkv = block.attn.attn(x_n)
    qkv = ltorch.reshape(qkv, (B, T, ng, q_per_kv + 2, hs))
    q = ltorch.reshape(qkv[:, :, :, :q_per_kv, :], (B, T, nh, hs))
    k = ltorch.reshape(qkv[:, :, :, q_per_kv: q_per_kv + 1, :], (B, T, ng, hs))
    v = ltorch.reshape(qkv[:, :, :, q_per_kv + 1:, :], (B, T, ng, hs))
    q = ltorch.permute(q, (0, 2, 1, 3))
    k = ltorch.permute(k, (0, 2, 1, 3))
    v = ltorch.permute(v, (0, 2, 1, 3))
    q = _apply_rope(q, cos, sin, cfg.rope_n_elem)
    k = _apply_rope(k, cos, sin, cfg.rope_n_elem)
    return q, k, v


def block_mix(block, cfg, x, h):
    """Residual + MLP/MoE tail of one block (the other half of the shared
    plumbing; see split_qkv_rope)."""
    mlp = getattr(block, "mlp", None)
    is_moe = mlp is None
    if is_moe:
        mlp = block.moe  # MoE decoder blocks (models/moe.py MoEBlock)
    if cfg.parallel_residual and not is_moe:
        # MoEBlock.forward is always sequential (moe.py:92-93); only
        # litgpt Blocks honor parallel_residual
        return x + h + mlp(block.norm_2(x))
    x = x + h
    return x + mlp(block.norm_2(x))


class GPTInference:
    """Greedy/temperature generation over a models.litgpt.GPT or
    models.moe.MoEGPT (Mixtral-style MoE decoder).

    The model's sdpa path is swapped for cache-aware attention by running the
    blocks manually (the GPT module structure is reused; no retracing of the
    whole prefix per token)."""

    def __init__(self, gpt, *, max_seq: Optional[int] = None, dtype=jnp.bfloat16):
        from . import jit as _jit

        self.gpt = gpt
        cfg = gpt.cfg
        self.cfg = cfg
        self.max_seq = max_seq or cfg.block_size
        self.dtype = dtype
        self._decode_cfn = None
        self._prefill_cfn = None

    # --- functional single-step over the module tree ---
    def _forward_cached(self, idx, ks, vs, pos):
        """idx (B, T); ks/vs per-layer cache tuples; pos: start position —
        either a python int (prefill) or a scalar int32 tensor (decode, so one
        compiled decode step serves every position)."""
        from .core import prims

        cfg = self.cfg
        gpt = self.gpt
        B, T = idx.shape
        n_elem = cfg.rope_n_elem
        cos_full = clang.ensure_proxy(gpt.cos)
        sin_full = clang.ensure_proxy(gpt.sin)
        cos = prims.dynamic_slice(cos_full, (pos, 0), (T, n_elem))
        sin = prims.dynamic_slice(sin_full, (pos, 0), (T, n_elem))
        x = gpt.wte(idx)
        new_ks, new_vs = [], []
        nh, ng = cfg.n_head, cfg.n_query_groups
        q_per_kv = nh // ng
        for li, block in enumerate(gpt.h):
            from .models.litgpt import _repeat_kv

            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            # insert into cache at pos
            k_cache = prims.dynamic_update_slice(ks[li], k, (0, 0, pos, 0))
            v_cache = prims.dynamic_update_slice(vs[li], v, (0, 0, pos, 0))
            new_ks.append(k_cache)
            new_vs.append(v_cache)
            kq = _repeat_kv(k_cache, q_per_kv) if ng != nh else k_cache
            vq = _repeat_kv(v_cache, q_per_kv) if ng != nh else v_cache
            y = cached_sdpa(q, kq, vq, pos)
            y = ltorch.reshape(ltorch.permute(y, (0, 2, 1, 3)), (B, T, nh * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        x = gpt.ln_f(x)
        logits = gpt.lm_head(x[:, -1])  # only last position needed for generation
        return logits, tuple(new_ks), tuple(new_vs)

    def _build(self, B: int, prompt_len: int):
        from . import jit as _jit
        from .nn.module import functional_params

        gpt = self.gpt
        cfg = self.cfg

        def prefill(params, idx, ks, vs):
            with functional_params(gpt, params):
                return self._forward_cached(idx, ks, vs, 0)

        def decode(params, idx, ks, vs, pos):
            with functional_params(gpt, params):
                return self._forward_cached(idx, ks, vs, pos)

        prefill.__name__ = "prefill"
        decode.__name__ = "decode"
        self._prefill_cfn = _jit(prefill)
        self._decode_cfn = _jit(decode)

    def _build_scan_decode(self, n_steps: int):
        """Compile the WHOLE greedy decode loop into one XLA program via
        lax.scan over the compiled decode step (the role CUDA graphs play in
        the reference: per-token dispatch overhead drops to zero — one
        dispatch generates all n_steps tokens). The compiled decode entry is
        traceable because its generated prologue/computation are pure jax."""
        decode = self._decode_cfn

        def scan_decode(params, first_tok, ks, vs, start_pos):
            def step(carry, _):
                tok, ks, vs, pos = carry
                logits, ks, vs = decode(params, tok[:, None], ks, vs, pos)
                nxt = jnp.argmax(logits, -1).astype(tok.dtype)
                return (nxt, ks, vs, pos + 1), nxt

            (last, ks, vs, _), toks = jax.lax.scan(
                step, (first_tok, ks, vs, jnp.asarray(start_pos, jnp.int32)),
                None, length=n_steps)
            return toks, ks, vs  # toks: (n_steps, B)

        self._scan_jitted = jax.jit(scan_decode, static_argnames=())
        self._scan_steps = n_steps
        return self._scan_jitted

    _scan_jitted = None
    _scan_steps = None
    _scan_sig = None

    def generate(self, prompt, max_new_tokens: int = 32, *, temperature: float = 0.0,
                 seed: Optional[int] = None, collect_metrics: bool = False,
                 scan_decode: bool = True):
        """prompt: (B, T) int array. Returns (tokens (B, T+max_new), metrics).

        scan_decode=True (greedy only): all decode steps compile into one XLA
        program — one dispatch for the whole generation.

        seed keys temperature sampling: the token at position p draws from
        fold_in(PRNGKey(seed), p), so two generations with the same seed are
        identical and the stream matches the serving engine's
        (serving/scheduler.py) for the same request seed."""
        cfg = self.cfg
        B, T = prompt.shape
        if T + max_new_tokens > self.max_seq:
            # an overlong generation would let dynamic_update_slice clamp its
            # writes at the cache edge, silently corrupting the KV tail —
            # refuse up front instead
            raise ValueError(
                f"prompt_len={T} + max_new_tokens={max_new_tokens} exceeds "
                f"max_seq={self.max_seq}; build the engine with a larger "
                f"max_seq (or shorten the generation)")
        if self._decode_cfn is None:
            self._build(B, T)
        # seeds are canonicalized mod 2^32 so the stream matches the serving
        # engine's (whose packed seed array is uint32) for any Python int
        sample_key = jax.random.PRNGKey(
            (seed if seed is not None else 0) & 0xFFFFFFFF)
        # raw arrays: Parameter wrappers don't abstract under the jitted scan
        params = {k: p.data for k, p in self.gpt.named_parameters()}
        cache = KVCache(cfg.n_layer, B, cfg.n_query_groups, self.max_seq, cfg.head_size, self.dtype)
        ks, vs = cache.as_tuple()

        # one enabled() read gates the per-request observability (span +
        # flight-recorder records); disabled mode adds zero work here
        obs_on = _obs.enabled()
        t_start = time.perf_counter()
        with _obs_runtime.step_span("infer_prefill", B=B, T=T) if obs_on else _NULL:
            logits, ks, vs = self._prefill_cfn(params, prompt, ks, vs)
            if temperature > 0.0:
                next_tok = jax.random.categorical(
                    jax.random.fold_in(sample_key, T),
                    logits / temperature, -1).astype(prompt.dtype)
            else:
                next_tok = jnp.argmax(logits, -1).astype(prompt.dtype)
            jax.block_until_ready(next_tok)
        ttft = time.perf_counter() - t_start
        if obs_on:
            _obs_flight.record_step(ttft * 1e3, fn="infer_prefill", B=B, T=T)
            _obs_tel.observe("infer.ttft_ms", ttft * 1e3)

        n_steps = max_new_tokens - 1
        use_scan = scan_decode and temperature == 0.0 and n_steps > 0
        t_decode = time.perf_counter()
        if use_scan:
            sig = (n_steps, B, str(next_tok.dtype))
            if self._scan_jitted is None or self._scan_sig != sig:
                # warm-compile the decode entry with CONCRETE inputs first —
                # compiling it inside the scan trace would bake tracers into
                # the cached entry (outputs discarded; caches stay untouched).
                # Keyed on the full (steps, batch, dtype) signature: a new
                # batch size means a new decode cache entry to warm.
                self._decode_cfn(params, next_tok[:, None], ks, vs, jnp.asarray(T, jnp.int32))
                self._build_scan_decode(n_steps)
                self._scan_sig = sig
            with _obs_runtime.annotate_call("tt_decode") if obs_on else _NULL:
                toks_scan, ks, vs = self._scan_jitted(params, next_tok, ks, vs, T)
                jax.block_until_ready(toks_scan)
            dt = time.perf_counter() - t_decode
            if obs_on:
                # one record per generation: the scan is ONE dispatch, so
                # per-token wall time is the window divided by its length
                _obs_flight.record_step(dt * 1e3, fn="infer_decode",
                                        n_tokens=n_steps, scan=True)
                _obs_tel.observe("infer.tbot_ms", dt * 1e3 / max(1, n_steps))
            out = jnp.concatenate([prompt, next_tok[:, None], toks_scan.T.astype(prompt.dtype)], axis=1)
            metrics = GenerationMetrics(
                ttft_s=ttft,
                tbot_s=dt / max(1, n_steps),
                tokens_per_sec=B * max_new_tokens / (ttft + dt),
                ms_per_token=1e3 * (ttft + dt) / max_new_tokens,
                n_new_tokens=max_new_tokens,
            )
            return out, metrics
        else:
            toks = [next_tok]
            pos = T
            for _ in range(n_steps):
                logits, ks, vs = self._decode_cfn(params, next_tok[:, None], ks, vs,
                                                  jnp.asarray(pos, jnp.int32))
                if temperature > 0.0:
                    # position-keyed split of the per-request key: the OLD
                    # PRNGKey(pos) drew the SAME stream for every generation
                    # at the same position, whatever the request
                    key = jax.random.fold_in(sample_key, pos + 1)
                    next_tok = jax.random.categorical(key, logits / temperature, -1).astype(prompt.dtype)
                else:
                    next_tok = jnp.argmax(logits, -1).astype(prompt.dtype)
                toks.append(next_tok)
                pos += 1
            jax.block_until_ready(next_tok)
            dt = time.perf_counter() - t_decode
            if obs_on:
                _obs_flight.record_step(dt * 1e3, fn="infer_decode",
                                        n_tokens=n_steps, scan=False)
                _obs_tel.observe("infer.tbot_ms", dt * 1e3 / max(1, n_steps))

        out = jnp.concatenate([prompt] + [t[:, None] for t in toks], axis=1)
        metrics = GenerationMetrics(
            ttft_s=ttft,
            tbot_s=dt / max(1, n_steps),
            tokens_per_sec=B * max_new_tokens / (ttft + dt),
            ms_per_token=1e3 * (ttft + dt) / max_new_tokens,
            n_new_tokens=max_new_tokens,
        )
        return out, metrics
