"""Continuous-batching scheduler: admit, prefill, decode, retire — every step.

The Orca (OSDI '22) iteration-level scheduling loop over the paged KV pool:

* submit() enqueues a request and returns a concurrent.futures.Future.
* Each engine iteration ADMITS pending requests into free decode slots
  (FIFO; a request is admitted only when the page pool can cover its whole
  lifetime — prompt pages plus worst-case growth — so decode can never hit
  a mid-flight out-of-pages), runs one shape-BUCKETED prefill per admission
  (prompt padded to the next rung of the system-wide ``BucketLadder`` —
  compile_service/buckets.py, the SAME ladder the bucketed TrainStep and
  stored compile artifacts key on, so there is no separate per-engine
  bucket mechanism and the thunder trace cache serves every prompt length
  from a handful of specializations), then packs ALL active sequences into ONE compiled decode step
  over the page pool and retires finished sequences, returning their pages
  to the free-list immediately.

Per-request observability rides the existing bus: request-id-tagged spans,
``serve.*`` counters, and flight-recorder records per decode iteration
(docs/serving.md, docs/observability.md).

Sampling is position-keyed — token at position p draws from
``fold_in(PRNGKey(seed), p)`` — so a request's stream is identical whether
it runs solo (inference.GPTInference.generate) or continuously batched.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_service.buckets import BucketLadder
from ..observability import events as _obs
from ..observability import flight_recorder as _obs_flight
from ..observability import metrics as _obs_metrics
from ..observability import runtime as _obs_runtime
from ..observability import telemetry as _obs_tel
from ..observability.slo import SLOMonitor, SLOPolicy
from .kv_pages import PagedKVCache
from .runner import PagedGPTRunner

_NULL = contextlib.nullcontext()


@dataclass
class RequestResult:
    """What a request's Future resolves to."""

    request_id: int
    tokens: np.ndarray          # prompt + generated, (prompt_len + n_new,)
    new_tokens: np.ndarray      # generated only, (n_new,)
    ttft_s: float               # submit -> first token
    tbot_s: float               # mean time between output tokens
    n_new_tokens: int = 0
    finish_reason: str = "length"   # "length" | "eos" | "cancelled"
    # per-request SLO-met flag stamped at retirement when the engine has an
    # SLOPolicy attached (the goodput numerator); None without a policy
    slo_met: Optional[bool] = None


@dataclass
class _Request:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    seed: int
    eos_id: Optional[int]
    future: Future
    t_submit: float
    t_first: float = 0.0
    t_last: float = 0.0
    tokens: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    bucket: int = 0


def _sample_tokens(logits, seeds, pos, temps):
    """Position-keyed sampling: token at position p for request seed s draws
    from fold_in(PRNGKey(s), p). temps == 0 -> greedy argmax."""

    def one(l, s, p, t):
        key = jax.random.fold_in(jax.random.PRNGKey(s), p)
        safe_t = jnp.where(t > 0, t, 1.0)
        sampled = jax.random.categorical(key, l / safe_t)
        return jnp.where(t > 0, sampled, jnp.argmax(l, -1))

    return jax.vmap(one)(logits, seeds, pos, temps).astype(jnp.int32)


class ServingEngine:
    """Continuous-batching inference over a models.litgpt.GPT (or MoEGPT).

    max_batch   decode slots (sequences packed into one decode step)
    page_size   tokens per KV page
    n_pages     pool size per layer (default: full residency for max_batch
                sequences of max_seq tokens, plus the reserved null page)
    max_seq     per-sequence length cap (prompt + generated)
    """

    def __init__(self, gpt, *, max_batch: int = 8, page_size: int = 16,
                 n_pages: Optional[int] = None, max_seq: Optional[int] = None,
                 dtype=jnp.bfloat16, min_bucket: Optional[int] = None,
                 slo: Optional[SLOPolicy] = None):
        cfg = gpt.cfg
        self.gpt = gpt
        self.cfg = cfg
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_seq = max_seq or cfg.block_size
        rope_rows = gpt.cos.shape[0]
        if self.max_seq > rope_rows:
            raise ValueError(
                f"max_seq={self.max_seq} exceeds the model's rope cache "
                f"({rope_rows} positions); build the GPT with a larger block_size")
        if self.max_seq % page_size:
            raise ValueError(f"max_seq={self.max_seq} must be a multiple of "
                             f"page_size={page_size}")
        self.n_pages_max = self.max_seq // page_size  # page-table width
        if n_pages is None:
            n_pages = 1 + max_batch * self.n_pages_max
        self.min_bucket = max(page_size, min_bucket or page_size)
        # ONE bucket ladder (compile_service/buckets.py) owns the rounding
        # rule, page-alignment validation, and the per-rung traffic stats
        # that used to live in a separate ShapeKeyedMRU of _BucketEntry
        # records — prompt buckets, the bucketed TrainStep, and stored
        # artifact keys all route through the same object
        self.ladder = BucketLadder(self.min_bucket, self.max_seq,
                                   page_size=page_size)
        self.dtype = dtype

        self.cache = PagedKVCache(cfg.n_layer, n_pages, page_size,
                                  cfg.n_query_groups, cfg.head_size, dtype)
        self.runner = PagedGPTRunner(gpt, page_size=page_size)
        self.params = {k: p.data for k, p in gpt.named_parameters()}
        self._sampler = jax.jit(_sample_tokens)

        # host-side packed decode state; pos/toks change every step and are
        # re-uploaded, while seeds/temps/page tables only change at
        # (un)assignment — their device copies are cached under _pt_dirty
        self._page_tables = np.zeros((max_batch, self.n_pages_max), np.int32)
        self._pos = np.zeros((max_batch,), np.int32)
        self._toks = np.zeros((max_batch,), np.int32)
        self._seeds = np.zeros((max_batch,), np.uint32)
        self._temps = np.zeros((max_batch,), np.float32)
        self._pt_dev = None
        self._seeds_dev = None
        self._temps_dev = None
        self._pt_dirty = True
        self._slots: List[Optional[_Request]] = [None] * max_batch

        self._pending: deque = deque()
        self._lock = threading.Lock()
        self._next_id = 0
        # submitted-but-unresolved count: _has_work()/drain() key off this
        # rather than scanning pending+slots, which is momentarily EMPTY
        # between a pop from the queue and the slot assignment (a drain
        # racing the loop thread would return mid-prefill otherwise)
        self._outstanding = 0
        self._stopped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decode_steps = 0
        self.peak_pages_in_use = 0

        # SLO measurement substrate (observability/slo.py): a declarative
        # policy gets a sliding-window monitor (breach events/counters) and
        # per-request SLO-met accounting at retirement — the goodput gauge
        # ROADMAP #2's admission lanes will schedule against. Without a
        # policy the retirement path pays one `is None` test.
        self.slo_policy = slo
        self.slo_monitor = SLOMonitor(slo, source="serving") if slo is not None else None
        self.requests_retired = 0       # non-cancelled retirements
        self.requests_slo_met = 0

    # -- public API -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, *, temperature: float = 0.0,
               seed: Optional[int] = None, eos_id: Optional[int] = None) -> Future:
        """Enqueue one generation request; thread-safe. The Future resolves
        to a RequestResult (or a ValueError for an inadmissible request)."""
        fut: Future = Future()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        L = int(prompt.shape[0])
        worst = self._pages_needed(L, max_new_tokens)
        usable = self.cache.n_pages - 1
        if L < 1 or L + max_new_tokens > self.max_seq or max_new_tokens < 1:
            fut.set_exception(ValueError(
                f"request {rid}: prompt_len={L} + max_new_tokens={max_new_tokens} "
                f"must fit max_seq={self.max_seq} (and both be >= 1)"))
            return fut
        if worst > usable:
            fut.set_exception(ValueError(
                f"request {rid}: needs {worst} pages, pool has {usable}"))
            return fut
        # seeds canonicalized mod 2^32 (the packed sampler array is uint32);
        # inference.generate applies the same mask, keeping the documented
        # solo-vs-batched stream equivalence for any Python int seed
        req = _Request(rid, prompt, max_new_tokens, float(temperature),
                       int(seed if seed is not None else rid) & 0xFFFFFFFF,
                       eos_id, fut, time.perf_counter())
        with self._lock:
            if self._stopped:
                # stop() already flushed the queue; a late submit must fail
                # loudly rather than enqueue a Future nothing will resolve
                fut.set_exception(RuntimeError("serving engine stopped"))
                return fut
            self._pending.append(req)
            self._outstanding += 1
        if _obs.enabled():
            _obs_metrics.record_serve("requests")
        return fut

    def start(self) -> None:
        """Run the scheduling loop on a background thread."""
        if self._thread is not None:
            return
        with self._lock:
            self._stopped = False
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="tt-serving",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False) -> None:
        """Stop the loop thread. drain=True finishes outstanding requests
        first; otherwise every in-flight and pending Future is FAILED (with
        pages returned) — a stopped engine must never leave a waiter
        hanging on a Future that nothing will ever resolve."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            self._stop.clear()
            self.drain()
            self._stop.set()
        exc = RuntimeError("serving engine stopped")
        for i, req in enumerate(self._slots):
            if req is not None:
                self._fail(req, exc)
                self._clear_slot(i)
        with self._lock:
            # flag + flush under ONE lock section: a racing submit either
            # lands before the flush (failed here) or sees _stopped and
            # fails itself — no window leaves an unresolvable Future
            self._stopped = True
            pending, self._pending = list(self._pending), deque()
        for req in pending:
            self._fail(req, exc)

    def drain(self) -> None:
        """Block until every submitted request resolved. With the
        background thread running this only WAITS (stepping inline too
        would race the thread over slots and pool state); without it, the
        loop runs inline (deterministic test/benchmark driver)."""
        if self._thread is not None:
            while self._has_work():
                time.sleep(1e-3)
            return
        while self._has_work():
            self._step_once()

    def warmup(self, prompt_lens, max_new_tokens: int = 2) -> None:
        """Pre-compile the decode step and the prefill bucket for each
        prompt length (steady state then never recompiles)."""
        for L in prompt_lens:
            self.submit(np.zeros((L,), np.int32), max_new_tokens)
        self.drain()

    def stats(self) -> dict:
        usable = self.cache.n_pages - 1
        out = {
            "pages_in_use": self.cache.allocator.n_used,
            "page_pool_utilization": round(self.cache.utilization(), 4),
            "peak_page_pool_utilization": round(self.peak_pages_in_use / usable, 4)
            if usable else 0.0,
            "active": sum(1 for s in self._slots if s is not None),
            "pending": len(self._pending),
            "decode_steps": self.decode_steps,
            "prefill_buckets": self.ladder.mru(),
            "bucket_hits": self.ladder.hits(),
        }
        if self.slo_policy is not None:
            out["requests_retired"] = self.requests_retired
            out["requests_slo_met"] = self.requests_slo_met
            out["goodput"] = (round(self.requests_slo_met / self.requests_retired, 4)
                              if self.requests_retired else None)
            out["slo"] = self.slo_monitor.status()
        return out

    def goodput(self) -> Optional[float]:
        """Cumulative fraction of retired (non-cancelled) requests whose
        per-request SLO-met flag was True; None without a policy or before
        the first retirement. (The SLOMonitor additionally keeps a
        sliding-window goodput for burn-rate/breach evaluation.)"""
        if self.slo_policy is None or not self.requests_retired:
            return None
        return self.requests_slo_met / self.requests_retired

    def reset_slo_accounting(self) -> None:
        """Zero the goodput counters and restart the sliding-window monitor
        (same policy). Benchmarks call this after warmup() so roll-out
        traffic doesn't pollute goodput or the breach windows — the engine
        owns every field involved, so new accounting state added here can't
        silently desync external callers."""
        self.requests_retired = 0
        self.requests_slo_met = 0
        if self.slo_policy is not None:
            self.slo_monitor = SLOMonitor(self.slo_policy, source="serving")

    # -- scheduling loop --------------------------------------------------
    def _has_work(self) -> bool:
        return self._outstanding > 0

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._has_work():
                time.sleep(1e-3)
                continue
            try:
                self._step_once()
            except Exception as e:  # pragma: no cover - scheduler-bug net
                # per-request failures are contained in _prefill/_decode
                # (futures failed, pages freed); anything reaching here is a
                # scheduler bug — keep the thread alive for other requests
                # rather than silently hanging every future forever
                import warnings

                warnings.warn(f"serving loop error (contained): {e!r}")
                time.sleep(1e-2)

    def _pages_needed(self, L: int, max_new: int) -> int:
        """Worst-case pages over the request lifetime: the bucketed prefill
        writes bucket//page_size pages, growth extends to L+max_new tokens.
        Reserving the max at admission means decode can never hit a
        mid-flight out-of-pages (the admission policy; docs/serving.md)."""
        bucket = self.ladder.bucket_for(L)
        return max(bucket // self.page_size,
                   PagedKVCache.pages_for(L + max_new, self.page_size))

    def _step_once(self) -> None:
        self._admit()
        self._decode()

    def _admit(self) -> None:
        while True:
            free_slots = [i for i, s in enumerate(self._slots) if s is None]
            if not free_slots:
                return
            with self._lock:
                if not self._pending:
                    return
                req = self._pending[0]
                if req.future.cancelled():
                    # cancelled while queued: drop before allocating anything
                    self._pending.popleft()
                    self._outstanding -= 1
                    continue
                need = self._pages_needed(len(req.prompt), req.max_new_tokens)
                if not self.cache.allocator.can_alloc(need):
                    return  # FIFO head-of-line: wait for retirements
                self._pending.popleft()
            req.pages = self.cache.allocator.alloc(need)
            self.peak_pages_in_use = max(self.peak_pages_in_use,
                                         self.cache.allocator.n_used)
            self._prefill(req, free_slots[0])

    def _fail(self, req: _Request, exc: Exception) -> None:
        """Contain one request's failure: return its pages, fail its Future
        (waiters see the error instead of hanging), keep the engine alive."""
        if req.pages:
            self.cache.allocator.free(req.pages)
            req.pages = []
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass  # caller's cancel() raced the done() window — already dead
        with self._lock:
            self._outstanding -= 1
        if _obs.enabled():
            _obs_metrics.record_serve("failed", event=True,
                                      request=req.request_id,
                                      error=type(exc).__name__)

    def _prefill(self, req: _Request, slot: int) -> None:
        obs_on = _obs.enabled()
        L = len(req.prompt)
        bucket = self.ladder.touch(L)
        req.bucket = bucket
        n_prompt_pages = bucket // self.page_size
        idx = np.zeros((1, bucket), np.int32)
        idx[0, :L] = req.prompt
        page_ids = jnp.asarray(req.pages[:n_prompt_pages], jnp.int32)
        t0 = time.perf_counter()
        try:
            with (_obs_runtime.step_span("serve_prefill", request=req.request_id,
                                         bucket=bucket, prompt_len=L)
                  if obs_on else _NULL):
                logits, kps, vps = self.runner.prefill_cfn(
                    self.params, jnp.asarray(idx), page_ids,
                    self.cache.k_pages, self.cache.v_pages,
                    jnp.asarray(L - 1, jnp.int32))
                self.cache.rebind(kps, vps)
                tok0 = self._sampler(logits,
                                     jnp.asarray([req.seed], jnp.uint32),
                                     jnp.asarray([L], jnp.int32),
                                     jnp.asarray([req.temperature], jnp.float32))
                tok0 = int(np.asarray(tok0)[0])
        except Exception as e:
            self._fail(req, e)
            return
        req.t_first = req.t_last = time.perf_counter()
        req.tokens.append(tok0)
        if obs_on:
            util = round(self.cache.utilization(), 4)
            _obs_metrics.record_serve("prefills", event=True,
                                      request=req.request_id, bucket=bucket,
                                      prompt_len=L, ms=round((req.t_first - t0) * 1e3, 3),
                                      pool_utilization=util)
            _obs_metrics.record_serve("prefill_tokens", delta=L)
            _obs_tel.observe("serve.prefill_ms", (req.t_first - t0) * 1e3)
            _obs_tel.set_gauge("serve.pool_utilization", util)
            _obs_tel.set_gauge("serve.pages_in_use", self.cache.allocator.n_used)
        if self._finished(req, tok0):
            self._retire(req)
            return
        self._slots[slot] = req
        self._page_tables[slot] = self.cache.page_table_row(req.pages, self.n_pages_max)
        self._pos[slot] = L
        self._toks[slot] = tok0
        self._seeds[slot] = req.seed
        self._temps[slot] = req.temperature
        self._pt_dirty = True

    def _clear_slot(self, i: int) -> None:
        self._slots[i] = None
        self._page_tables[i] = 0
        self._pos[i] = 0
        self._toks[i] = 0
        self._seeds[i] = 0
        self._temps[i] = 0.0
        self._pt_dirty = True

    def _decode(self) -> None:
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return
        obs_on = _obs.enabled()
        t0 = time.perf_counter()
        if self._pt_dirty:
            # page tables / seeds / temps only change at slot (un)assignment;
            # re-upload them then, not per token (pos/toks change every step)
            self._pt_dev = jnp.asarray(self._page_tables)
            self._seeds_dev = jnp.asarray(self._seeds)
            self._temps_dev = jnp.asarray(self._temps)
            self._pt_dirty = False
        try:
            with (_obs_runtime.step_span("serve_decode", active=len(active))
                  if obs_on else _NULL):
                logits, kps, vps = self.runner.decode_cfn(
                    self.params, jnp.asarray(self._toks[:, None]),
                    self.cache.k_pages, self.cache.v_pages,
                    self._pt_dev, jnp.asarray(self._pos))
                self.cache.rebind(kps, vps)
                # the NEXT token's position is pos+1 (this step wrote pos)
                nxt = self._sampler(logits, self._seeds_dev,
                                    jnp.asarray(self._pos + 1),
                                    self._temps_dev)
                nxt = np.asarray(nxt)
        except Exception as e:
            # the packed step failed: every active sequence is implicated —
            # fail their futures and return their pages rather than hanging
            # the whole engine (pending requests still get admitted)
            for i in active:
                self._fail(self._slots[i], e)
                self._clear_slot(i)
            return
        t_now = time.perf_counter()
        self.decode_steps += 1
        if obs_on:
            _obs_metrics.record_serve("decode_steps")
            _obs_metrics.record_serve("tokens", delta=len(active))
            _obs_flight.record_step((t_now - t0) * 1e3, fn="serve_decode",
                                    active=len(active))
            # online decode-iteration latency percentiles (unsampled, like
            # the flight recorder — TT_OBS_SAMPLE only thins the spans)
            _obs_tel.observe("serve.decode_ms", (t_now - t0) * 1e3)
        for i in active:
            req = self._slots[i]
            tok = int(nxt[i])
            req.tokens.append(tok)
            req.t_last = t_now
            self._pos[i] += 1
            self._toks[i] = tok
            if self._finished(req, tok):
                self._retire(req)
                self._clear_slot(i)

    def _finished(self, req: _Request, tok: int) -> bool:
        if req.future.cancelled():
            # the caller gave up: stop decoding and free the pages now
            return True
        if req.eos_id is not None and tok == req.eos_id:
            return True
        return len(req.tokens) >= req.max_new_tokens

    def _retire(self, req: _Request) -> None:
        self.cache.allocator.free(req.pages)
        req.pages = []
        n_new = len(req.tokens)
        ttft = req.t_first - req.t_submit
        tbot = ((req.t_last - req.t_first) / (n_new - 1)) if n_new > 1 else 0.0
        if req.future.cancelled():
            # a client-side cancel is not a completion: tag it so latency
            # percentiles (obs_summary) aren't polluted by truncated samples
            reason = "cancelled"
        elif (req.eos_id is not None and req.tokens
              and req.tokens[-1] == req.eos_id):
            reason = "eos"
        else:
            reason = "length"
        obs_on = _obs.enabled()
        slo_met = None
        if reason != "cancelled":
            ttft_ms = ttft * 1e3
            # a one-token request has no between-token interval: exclude it
            # from the tbot population (online AND offline percentiles use
            # the same rule) rather than stream a 0.0 placeholder
            tbot_ms = tbot * 1e3 if n_new > 1 else None
            if self.slo_policy is not None:
                slo_met = self.slo_policy.request_met(ttft_ms, tbot_ms)
                self.requests_retired += 1
                self.requests_slo_met += int(slo_met)
            if obs_on:
                # streaming percentiles: the online mirror of the offline
                # serving section's TTFT/TBOT populations (cancelled
                # requests excluded from both)
                _obs_tel.observe("serve.ttft_ms", ttft_ms)
                if tbot_ms is not None:
                    _obs_tel.observe("serve.tbot_ms", tbot_ms)
            if self.slo_monitor is not None:
                self.slo_monitor.observe_request(
                    ttft_ms=ttft_ms, tbot_ms=tbot_ms, met=bool(slo_met),
                    tokens=n_new)
        if obs_on:
            util = round(self.cache.utilization(), 4)
            _obs_tel.set_gauge("serve.pool_utilization", util)
            _obs_tel.set_gauge("serve.pages_in_use", self.cache.allocator.n_used)
            if self.slo_policy is not None and self.requests_retired:
                _obs_tel.set_gauge(
                    "serve.goodput",
                    round(self.requests_slo_met / self.requests_retired, 4))
            _obs_metrics.record_serve(
                "cancelled" if reason == "cancelled" else "retired",
                event=True, request=req.request_id, n_new=n_new,
                ttft_ms=round(ttft * 1e3, 3), tbot_ms=round(tbot * 1e3, 3),
                finish=reason, pool_utilization=util)
        result = RequestResult(
            request_id=req.request_id,
            tokens=np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)]),
            new_tokens=np.asarray(req.tokens, np.int32),
            ttft_s=ttft,
            tbot_s=tbot,
            n_new_tokens=n_new,
            finish_reason=reason,
            slo_met=slo_met,
        )
        try:
            # a cancel() from the caller thread can land at ANY point, so a
            # done() pre-check would still race — set and swallow the loss
            # (pages are already freed above either way)
            req.future.set_result(result)
        except InvalidStateError:
            pass
        with self._lock:
            self._outstanding -= 1
