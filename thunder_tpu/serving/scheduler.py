"""Continuous-batching scheduler: admit, prefill, decode, retire — every step.

The Orca (OSDI '22) iteration-level scheduling loop over the paged KV pool:

* submit() enqueues a request and returns a concurrent.futures.Future.
* Each engine iteration ADMITS pending requests into free decode slots
  (FIFO; a request is admitted only when the page pool can cover its whole
  lifetime — prompt pages plus worst-case growth — so decode can never hit
  a mid-flight out-of-pages), runs one shape-BUCKETED prefill per admission
  (prompt padded to the next rung of the system-wide ``BucketLadder`` —
  compile_service/buckets.py, the SAME ladder the bucketed TrainStep and
  stored compile artifacts key on, so there is no separate per-engine
  bucket mechanism and the thunder trace cache serves every prompt length
  from a handful of specializations), then packs ALL active sequences into ONE compiled decode step
  over the page pool and retires finished sequences, returning their pages
  to the free-list immediately.

Four throughput stages compose on top of that loop, each OFF by default so
the baseline engine behaves exactly as before (docs/serving.md):

* prefix sharing (``prefix_sharing=True``) — admission consults a
  content-keyed ``PrefixCache`` and maps already-cached prompt pages into
  the new request's table (refcounted, copy-on-write on first divergence);
  prefill then runs only on the unshared suffix, and a fully covered
  prompt skips prefill entirely (one re-decoded token recovers the
  first-token logits bit-identically).
* chunked prefill (``chunk_tokens=N``) — prompts longer than N are split
  into page-aligned chunks interleaved into decode iterations under a
  ``prefill_budget`` tokens-per-iteration cap, bounding the decode-latency
  spike a long prompt used to inject.
* speculative decoding (``draft_gpt=...``) — a small draft model proposes
  ``spec_k`` tokens per iteration with the SAME position-keyed sampler;
  one packed target verify step scores all k+1 positions and the accepted
  prefix (capped at k — no bonus token, which keeps the draft KV valid)
  commits. Accepted tokens are bit-identical to plain decode.
* SLO-aware lanes (``submit(..., lane="batch")``) — interactive requests
  admit first; under page pressure or SLO burn the engine preempts batch
  sequences (pages spilled, request requeued at the front of the batch
  lane) and re-prefills them on resume — cheap when prefix sharing holds
  their pages in cache, and bit-identical thanks to position-keyed
  sampling.

Per-request observability rides the existing bus: request-id-tagged spans,
``serve.*`` counters, and flight-recorder records per decode iteration
(docs/serving.md, docs/observability.md).

Sampling is position-keyed — token at position p draws from
``fold_in(PRNGKey(seed), p)`` — so a request's stream is identical whether
it runs solo (inference.GPTInference.generate) or continuously batched.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..compile_service.buckets import BucketLadder
from ..observability import events as _obs
from ..observability import flight_recorder as _obs_flight
from ..observability import memory_watch as _obs_mem
from ..observability import metrics as _obs_metrics
from ..observability import runtime as _obs_runtime
from ..observability import telemetry as _obs_tel
from ..observability import tracing as _obs_trace
from ..observability.slo import SLOMonitor, SLOPolicy
from .kv_pages import PagedKVCache, PrefixCache
from .runner import PagedGPTRunner, quantize_for_serving

_NULL = contextlib.nullcontext()


@dataclass
class RequestResult:
    """What a request's Future resolves to."""

    request_id: int
    tokens: np.ndarray          # prompt + generated, (prompt_len + n_new,)
    new_tokens: np.ndarray      # generated only, (n_new,)
    ttft_s: float               # submit -> first token
    tbot_s: float               # mean time between output tokens
    n_new_tokens: int = 0
    finish_reason: str = "length"   # "length" | "eos" | "cancelled"
    # per-request SLO-met flag stamped at retirement when the engine has an
    # SLOPolicy attached (the goodput numerator); None without a policy
    slo_met: Optional[bool] = None


@dataclass
class _Request:
    request_id: int
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    seed: int
    eos_id: Optional[int]
    future: Future
    t_submit: float
    lane: str = "interactive"
    # end-to-end trace id (observability/tracing.py), minted at submit()
    # ONLY when the bus is enabled; None means every downstream trace site
    # exits on one attribute read (the zero-work-when-disabled contract)
    trace_id: Optional[str] = None
    t_first: float = 0.0
    t_last: float = 0.0
    tokens: List[int] = field(default_factory=list)
    pages: List[int] = field(default_factory=list)
    bucket: int = 0
    # admission-time routing state (set by _reserve_pages each admission —
    # a preempted request is re-routed from scratch on resume)
    admit_seq: int = -1          # monotone admission order (preemption victims)
    admit_mode: str = ""         # "prefill" | "chunk" | "hit"
    prompt_eff: Optional[np.ndarray] = None  # prompt (+ committed tokens on resume)
    covered: int = 0             # prefix-cache token coverage of prompt_eff
    n_shared: int = 0            # leading shared pages in .pages
    chunk_pos: int = -1          # next chunk start (chunk mode only)


def _sample_tokens(logits, seeds, pos, temps):
    """Position-keyed sampling: token at position p for request seed s draws
    from fold_in(PRNGKey(s), p). temps == 0 -> greedy argmax."""

    def one(l, s, p, t):
        key = jax.random.fold_in(jax.random.PRNGKey(s), p)
        safe_t = jnp.where(t > 0, t, 1.0)
        sampled = jax.random.categorical(key, l / safe_t)
        return jnp.where(t > 0, sampled, jnp.argmax(l, -1))

    return jax.vmap(one)(logits, seeds, pos, temps).astype(jnp.int32)


class ServingEngine:
    """Continuous-batching inference over a models.litgpt.GPT (or MoEGPT).

    max_batch   decode slots (sequences packed into one decode step)
    page_size   tokens per KV page
    n_pages     pool size per layer (default: full residency for max_batch
                sequences of max_seq tokens, plus the reserved null page)
    max_seq     per-sequence length cap (prompt + generated)

    Throughput stages (all off by default; see the module docstring):

    prefix_sharing  consult/populate a content-keyed PrefixCache at admission
    chunk_tokens    split prompts longer than this into page-aligned chunks
                    (default max_seq: whole-prompt prefill, never chunked
                    unless a prefix hit leaves an unaligned-free suffix)
    prefill_budget  chunk-prefill tokens per engine iteration (default
                    chunk_tokens: one chunk per iteration)
    draft_gpt       draft model for speculative decoding (same vocab; its
                    KV pool shares the target allocator page-for-page)
    spec_k          draft tokens proposed per iteration (default 4 with a
                    draft, 0 without)
    preemption      allow spilling batch-lane sequences for interactive
                    admission / SLO burn (on; only bites with lanes in use)
    quantize        weight-only quantization applied before tracing:
                    None/"none" or "int8" (int8 x bf16 decode compute via
                    the Pallas dequant-in-kernel linear on TPU)
    """

    def __init__(self, gpt, *, max_batch: int = 8, page_size: int = 16,
                 n_pages: Optional[int] = None, max_seq: Optional[int] = None,
                 dtype=jnp.bfloat16, min_bucket: Optional[int] = None,
                 slo: Optional[SLOPolicy] = None, prefix_sharing: bool = False,
                 chunk_tokens: Optional[int] = None,
                 prefill_budget: Optional[int] = None, draft_gpt=None,
                 spec_k: Optional[int] = None, preemption: bool = True,
                 quantize: Optional[str] = None):
        # weight-only quantization must precede BOTH the program tracing and
        # the named_parameters snapshot below (runner.quantize_for_serving)
        gpt = quantize_for_serving(gpt, quantize)
        cfg = gpt.cfg
        self.gpt = gpt
        self.cfg = cfg
        self.max_batch = max_batch
        self.page_size = page_size
        self.max_seq = max_seq or cfg.block_size
        rope_rows = gpt.cos.shape[0]
        if self.max_seq > rope_rows:
            raise ValueError(
                f"max_seq={self.max_seq} exceeds the model's rope cache "
                f"({rope_rows} positions); build the GPT with a larger block_size")
        if self.max_seq % page_size:
            raise ValueError(f"max_seq={self.max_seq} must be a multiple of "
                             f"page_size={page_size}")
        self.n_pages_max = self.max_seq // page_size  # page-table width
        if n_pages is None:
            n_pages = 1 + max_batch * self.n_pages_max
        self.min_bucket = max(page_size, min_bucket or page_size)
        # ONE bucket ladder (compile_service/buckets.py) owns the rounding
        # rule, page-alignment validation, and the per-rung traffic stats
        # that used to live in a separate ShapeKeyedMRU of _BucketEntry
        # records — prompt buckets, the bucketed TrainStep, and stored
        # artifact keys all route through the same object
        self.ladder = BucketLadder(self.min_bucket, self.max_seq,
                                   page_size=page_size)
        self.dtype = dtype

        if chunk_tokens is None:
            chunk_tokens = self.max_seq
        if chunk_tokens % page_size or not (self.min_bucket <= chunk_tokens
                                            <= self.max_seq):
            raise ValueError(
                f"chunk_tokens={chunk_tokens} must be a page-aligned length "
                f"in [{self.min_bucket}, {self.max_seq}]")
        self.chunk_tokens = chunk_tokens
        # final (short) chunks round on a capped child of the SAME ladder,
        # so chunk programs specialize over strictly fewer rungs
        self.chunk_ladder = self.ladder.subladder(chunk_tokens)
        self.prefill_budget = prefill_budget or chunk_tokens
        if self.prefill_budget < page_size:
            raise ValueError(f"prefill_budget={self.prefill_budget} must be "
                             f">= page_size={page_size}")
        self.preemption = preemption

        self.cache = PagedKVCache(cfg.n_layer, n_pages, page_size,
                                  cfg.n_query_groups, cfg.head_size, dtype)
        self.runner = PagedGPTRunner(gpt, page_size=page_size)
        self.params = {k: p.data for k, p in gpt.named_parameters()}
        self._sampler = jax.jit(_sample_tokens)

        self.prefix = (PrefixCache(self.cache.allocator, page_size)
                       if prefix_sharing else None)

        self.draft_gpt = draft_gpt
        self.spec_k = (int(spec_k) if spec_k is not None
                       else (4 if draft_gpt is not None else 0))
        if self.spec_k < 0:
            raise ValueError(f"spec_k={self.spec_k} must be >= 0")
        if self.spec_k and draft_gpt is None:
            raise ValueError("spec_k > 0 requires a draft_gpt")
        if draft_gpt is not None:
            dcfg = draft_gpt.cfg
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab_size={dcfg.vocab_size} != target "
                    f"vocab_size={cfg.vocab_size}")
            if draft_gpt.cos.shape[0] < self.max_seq:
                raise ValueError(
                    f"draft rope cache ({draft_gpt.cos.shape[0]} positions) "
                    f"shorter than max_seq={self.max_seq}")
            # the draft pool SHARES the target allocator: one allocation and
            # one page table cover both models, so sharing/CoW/preemption
            # bookkeeping never runs twice
            self.draft_cache = PagedKVCache(
                dcfg.n_layer, n_pages, page_size, dcfg.n_query_groups,
                dcfg.head_size, dtype, allocator=self.cache.allocator)
            self.draft_runner = PagedGPTRunner(draft_gpt, page_size=page_size)
            self.draft_params = {k: p.data
                                 for k, p in draft_gpt.named_parameters()}
        else:
            self.draft_cache = None
            self.draft_runner = None
            self.draft_params = None

        # host-side packed decode state; pos/toks change every step and are
        # re-uploaded, while seeds/temps/page tables only change at
        # (un)assignment — their device copies are cached under _pt_dirty
        self._page_tables = np.zeros((max_batch, self.n_pages_max), np.int32)
        self._pos = np.zeros((max_batch,), np.int32)
        self._toks = np.zeros((max_batch,), np.int32)
        self._seeds = np.zeros((max_batch,), np.uint32)
        self._temps = np.zeros((max_batch,), np.float32)
        self._pt_dev = None
        self._seeds_dev = None
        self._temps_dev = None
        self._pt_dirty = True
        self._slots: List[Optional[_Request]] = [None] * max_batch

        self._pending: deque = deque()        # interactive lane (admits first)
        self._pending_batch: deque = deque()  # batch lane (preemptible)
        self._chunking: Dict[int, _Request] = {}  # slot -> mid-chunk-prefill
        self._admit_counter = 0
        self._lock = threading.Lock()
        self._next_id = 0
        # submitted-but-unresolved count: _has_work()/drain() key off this
        # rather than scanning pending+slots, which is momentarily EMPTY
        # between a pop from the queue and the slot assignment (a drain
        # racing the loop thread would return mid-prefill otherwise)
        self._outstanding = 0
        self._stopped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.decode_steps = 0
        self.peak_pages_in_use = 0
        # stage counters (host truth; mirrored onto the serve.* bus when
        # observability is on — benchmark rates derive from the bus copies)
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.preempted = 0
        self.resumed = 0

        # SLO measurement substrate (observability/slo.py): a declarative
        # policy gets a sliding-window monitor (breach events/counters) and
        # per-request SLO-met accounting at retirement — the goodput gauge
        # ROADMAP #2's admission lanes will schedule against. Without a
        # policy the retirement path pays one `is None` test.
        self.slo_policy = slo
        self.slo_monitor = SLOMonitor(slo, source="serving") if slo is not None else None
        self.requests_retired = 0       # non-cancelled retirements
        self.requests_slo_met = 0

        # OOM forensics: hand the memory watcher a live view of the page
        # pool so a RESOURCE_EXHAUSTED bundle names pool pressure and
        # fragmentation, not just device bytes (last engine wins — one
        # engine per process is the deployed shape)
        _obs_mem.register_pool_state(self._pool_state)

    # -- public API -------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, *, temperature: float = 0.0,
               seed: Optional[int] = None, eos_id: Optional[int] = None,
               lane: str = "interactive") -> Future:
        """Enqueue one generation request; thread-safe. The Future resolves
        to a RequestResult (or a ValueError for an inadmissible request).
        lane="interactive" admits ahead of lane="batch"; batch sequences may
        be preempted (spilled and later resumed, stream unchanged) when an
        interactive request is page-starved or the SLO budget is burning."""
        fut: Future = Future()
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        if lane not in ("interactive", "batch"):
            fut.set_exception(ValueError(
                f"request {rid}: lane={lane!r} must be 'interactive' or 'batch'"))
            return fut
        L = int(prompt.shape[0])
        worst = self._pages_needed(L, max_new_tokens)
        usable = self.cache.n_pages - 1
        if L < 1 or L + max_new_tokens > self.max_seq or max_new_tokens < 1:
            fut.set_exception(ValueError(
                f"request {rid}: prompt_len={L} + max_new_tokens={max_new_tokens} "
                f"must fit max_seq={self.max_seq} (and both be >= 1)"))
            return fut
        if worst > usable:
            fut.set_exception(ValueError(
                f"request {rid}: needs {worst} pages, pool has {usable}"))
            return fut
        # seeds canonicalized mod 2^32 (the packed sampler array is uint32);
        # inference.generate applies the same mask, keeping the documented
        # solo-vs-batched stream equivalence for any Python int seed
        req = _Request(rid, prompt, max_new_tokens, float(temperature),
                       int(seed if seed is not None else rid) & 0xFFFFFFFF,
                       eos_id, fut, time.perf_counter(), lane=lane)
        if _obs.enabled():
            req.trace_id = _obs_trace.new_trace_id()
        with self._lock:
            if self._stopped:
                # stop() already flushed the queue; a late submit must fail
                # loudly rather than enqueue a Future nothing will resolve
                fut.set_exception(RuntimeError("serving engine stopped"))
                return fut
            (self._pending if lane == "interactive"
             else self._pending_batch).append(req)
            self._outstanding += 1
        if _obs.enabled():
            _obs_metrics.record_serve("requests")
            _obs_trace.trace_event(req.trace_id, "submitted",
                                   request=rid, lane=lane, prompt_len=L,
                                   max_new=max_new_tokens)
        return fut

    def start(self) -> None:
        """Run the scheduling loop on a background thread."""
        if self._thread is not None:
            return
        with self._lock:
            self._stopped = False
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, name="tt-serving",
                                        daemon=True)
        self._thread.start()

    def stop(self, drain: bool = False) -> None:
        """Stop the loop thread. drain=True finishes outstanding requests
        first; otherwise every in-flight and pending Future is FAILED (with
        pages returned) — a stopped engine must never leave a waiter
        hanging on a Future that nothing will ever resolve."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            self._stop.clear()
            self.drain()
            self._stop.set()
        exc = RuntimeError("serving engine stopped")
        for i, req in enumerate(self._slots):
            if req is not None:
                self._fail(req, exc)
                self._clear_slot(i)
        for req in list(self._chunking.values()):
            self._fail(req, exc)
        self._chunking.clear()
        with self._lock:
            # flag + flush under ONE lock section: a racing submit either
            # lands before the flush (failed here) or sees _stopped and
            # fails itself — no window leaves an unresolvable Future
            self._stopped = True
            pending = list(self._pending) + list(self._pending_batch)
            self._pending = deque()
            self._pending_batch = deque()
        for req in pending:
            self._fail(req, exc)

    def drain(self) -> None:
        """Block until every submitted request resolved. With the
        background thread running this only WAITS (stepping inline too
        would race the thread over slots and pool state); without it, the
        loop runs inline (deterministic test/benchmark driver)."""
        if self._thread is not None:
            while self._has_work():
                time.sleep(1e-3)
            return
        while self._has_work():
            self._step_once()

    def warmup(self, prompt_lens, max_new_tokens: int = 2) -> None:
        """Pre-compile the decode step and the prefill bucket for each
        prompt length (steady state then never recompiles)."""
        for L in prompt_lens:
            self.submit(np.zeros((L,), np.int32), max_new_tokens)
        self.drain()

    def stats(self) -> dict:
        usable = self.cache.n_pages - 1
        out = {
            "pages_in_use": self.cache.allocator.n_used,
            "page_pool_utilization": round(self.cache.utilization(), 4),
            "peak_page_pool_utilization": round(self.peak_pages_in_use / usable, 4)
            if usable else 0.0,
            "page_fragmentation": round(self.page_fragmentation(), 4),
            "active": sum(1 for s in self._slots if s is not None),
            "pending": len(self._pending) + len(self._pending_batch),
            "chunking": len(self._chunking),
            "decode_steps": self.decode_steps,
            "prefill_buckets": self.ladder.mru(),
            "bucket_hits": self.ladder.hits(),
            "prefix_hits": self.prefix_hits,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "preempted": self.preempted,
            "resumed": self.resumed,
        }
        if self.prefix is not None:
            out["prefix_cache_pages"] = len(self.prefix)
        if self.slo_policy is not None:
            out["requests_retired"] = self.requests_retired
            out["requests_slo_met"] = self.requests_slo_met
            out["goodput"] = (round(self.requests_slo_met / self.requests_retired, 4)
                              if self.requests_retired else None)
            out["slo"] = self.slo_monitor.status()
        return out

    def page_fragmentation(self) -> float:
        """Internal fragmentation of the page pool: the fraction of
        allocated page capacity NOT holding resident tokens. Worst-case
        lifetime reservation at admission means a request holds
        ``bucket + growth`` pages from its first prefill, so early in a
        long generation most of its reserved capacity is air — this gauge
        is the difference between "the pool is full" and "the pool is full
        of tokens", which picks between raising n_pages and tightening
        admission."""
        n_used = self.cache.allocator.n_used
        if not n_used:
            return 0.0
        resident = 0
        # lock-free slot scan: a torn read skews one gauge sample, while
        # taking self._lock here would deadlock callers that already hold
        # it (the post-mortem path can fire from anywhere)
        for req in list(self._slots):
            if req is None:
                continue
            prompt = req.prompt_eff if req.prompt_eff is not None else req.prompt
            resident += len(prompt) + len(req.tokens)
        frac = 1.0 - resident / (n_used * self.page_size)
        return max(0.0, min(1.0, frac))

    def _pool_state(self) -> dict:
        """Page-pool snapshot for OOM forensic bundles (memory_watch)."""
        usable = self.cache.n_pages - 1
        return {
            "pages_in_use": self.cache.allocator.n_used,
            "n_pages": self.cache.n_pages,
            "page_size": self.page_size,
            "utilization": round(self.cache.utilization(), 4),
            "peak_utilization": (round(self.peak_pages_in_use / usable, 4)
                                 if usable else 0.0),
            "fragmentation": round(self.page_fragmentation(), 4),
            "active": sum(1 for s in self._slots if s is not None),
            "pending": len(self._pending) + len(self._pending_batch),
        }

    def goodput(self) -> Optional[float]:
        """Cumulative fraction of retired (non-cancelled) requests whose
        per-request SLO-met flag was True; None without a policy or before
        the first retirement. (The SLOMonitor additionally keeps a
        sliding-window goodput for burn-rate/breach evaluation.)"""
        if self.slo_policy is None or not self.requests_retired:
            return None
        return self.requests_slo_met / self.requests_retired

    def reset_slo_accounting(self) -> None:
        """Zero the goodput counters and restart the sliding-window monitor
        (same policy). Benchmarks call this after warmup() so roll-out
        traffic doesn't pollute goodput or the breach windows — the engine
        owns every field involved, so new accounting state added here can't
        silently desync external callers."""
        self.requests_retired = 0
        self.requests_slo_met = 0
        if self.slo_policy is not None:
            self.slo_monitor = SLOMonitor(self.slo_policy, source="serving")

    # -- scheduling loop --------------------------------------------------
    def _has_work(self) -> bool:
        return self._outstanding > 0

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._has_work():
                time.sleep(1e-3)
                continue
            try:
                self._step_once()
            except Exception as e:  # pragma: no cover - scheduler-bug net
                # per-request failures are contained in _prefill/_decode
                # (futures failed, pages freed); anything reaching here is a
                # scheduler bug — keep the thread alive for other requests
                # rather than silently hanging every future forever
                import warnings

                warnings.warn(f"serving loop error (contained): {e!r}")
                time.sleep(1e-2)

    def _pages_needed(self, L: int, max_new: int) -> int:
        """Worst-case pages over the request lifetime: the bucketed prefill
        writes bucket//page_size pages, growth extends to L+max_new tokens.
        Reserving the max at admission means decode can never hit a
        mid-flight out-of-pages (the admission policy; docs/serving.md)."""
        bucket = self.ladder.bucket_for(L)
        return max(bucket // self.page_size,
                   PagedKVCache.pages_for(L + max_new, self.page_size))

    def _step_once(self) -> None:
        self._maybe_preempt_for_slo()
        self._admit()
        self._advance_prefills()
        self._decode()

    def _admit(self) -> None:
        while True:
            free_slots = [i for i, s in enumerate(self._slots)
                          if s is None and i not in self._chunking]
            if not free_slots:
                return
            req = queue = None
            with self._lock:
                for q in (self._pending, self._pending_batch):
                    while q and q[0].future.cancelled():
                        # cancelled while queued: drop before allocating
                        # anything (a preempted victim's pages were already
                        # spilled, so there is nothing to return either)
                        q.popleft()
                        self._outstanding -= 1
                    if req is None and q:
                        req, queue = q[0], q
            if req is None:
                return
            if not self._reserve_pages(req):
                # head-of-line within the lane pair: interactive starvation
                # may evict batch-lane victims; otherwise wait for retirements
                if (req.lane == "interactive" and self.preemption
                        and self._preempt_one()):
                    continue
                return
            with self._lock:
                queue.popleft()
            self.peak_pages_in_use = max(self.peak_pages_in_use,
                                         self.cache.allocator.n_used)
            req.admit_seq = self._admit_counter
            self._admit_counter += 1
            if req.admit_mode == "hit":
                self._admit_hit(req, free_slots[0])
            elif req.admit_mode == "chunk":
                self._start_chunk(req, free_slots[0])
            else:
                self._prefill(req, free_slots[0])

    def _reserve_pages(self, req: _Request) -> bool:
        """Route one request (prefix hit / chunked / whole-prompt prefill)
        and reserve its worst-case pages: shared pages come from the prefix
        cache (already incref'd by match), private ones from the free-list.
        On shortage every side effect is undone and False is returned — the
        request stays at its queue head."""
        ps = self.page_size
        resumed = bool(req.tokens)
        # a resumed victim re-prefills prompt + all-but-the-last committed
        # token: the last one re-enters decode exactly where the spill cut it
        prompt_eff = (req.prompt if not resumed else
                      np.concatenate([req.prompt,
                                      np.asarray(req.tokens[:-1], np.int32)]))
        L_eff = len(prompt_eff)
        lifetime = PagedKVCache.pages_for(
            len(req.prompt) + req.max_new_tokens, ps)
        shared: List[int] = []
        covered = 0
        if self.prefix is not None:
            shared, covered = self.prefix.match(prompt_eff)
            if req.trace_id is not None:
                _obs_trace.trace_event(req.trace_id, "prefix_lookup",
                                       request=req.request_id, covered=covered,
                                       shared_pages=len(shared),
                                       hit=bool(shared))
        n_shared = len(shared)
        if covered == L_eff and n_shared:
            # full coverage: no prefill at all. The first decode step
            # re-writes position L_eff-1 (the copy-on-write trigger) and
            # recovers the first-token logits bit-identically; a resumed
            # victim needs no logits, only a CoW fork if its next write
            # lands in the shared tail page.
            fork_n = 0 if (resumed and L_eff % ps == 0) else 1
            priv = lifetime - n_shared
            mode = "hit"
        elif covered > 0 or L_eff > self.chunk_tokens:
            end = self._final_chunk_end(L_eff, covered)
            priv = max(lifetime, end // ps) - n_shared
            fork_n = 0
            mode = "chunk"
        else:
            priv = max(lifetime, self.ladder.bucket_for(L_eff) // ps)
            fork_n = 0
            mode = "prefill"
        need = priv + fork_n
        if not self.cache.allocator.can_alloc(need):
            # cache-only pages are reclaimable: evicting them drops the
            # cache's reference, never a live sequence's (or ours — the
            # matched pages above hold our incref and survive eviction)
            if self.prefix is not None:
                self.prefix.evict_until(need)
            if not self.cache.allocator.can_alloc(need):
                if shared:
                    self.cache.allocator.free(shared)
                return False
        req.prompt_eff = prompt_eff
        req.covered = covered
        req.n_shared = n_shared
        req.admit_mode = mode
        req.pages = shared + (self.cache.allocator.alloc(priv) if priv else [])
        if req.trace_id is not None:
            _obs_trace.trace_event(
                req.trace_id, "admitted", request=req.request_id, mode=mode,
                covered=covered, shared_pages=n_shared, pages=len(req.pages),
                queued_ms=round((time.perf_counter() - req.t_submit) * 1e3, 3))
        return True

    def _final_chunk_end(self, L_eff: int, covered: int) -> int:
        """Absolute end of the final chunk's page write-out: intermediate
        chunks are exactly chunk_tokens, the final one rounds up on the
        capped chunk ladder — unless that rung would cross max_seq (and so
        the rope table), in which case it falls back to the exact page-
        aligned remainder."""
        C = self.chunk_tokens
        s = covered + ((L_eff - covered - 1) // C) * C
        rung = self.chunk_ladder.bucket_for(L_eff - s)
        if s + rung > self.max_seq:
            rung = PagedKVCache.pages_for(L_eff - s, self.page_size) * self.page_size
        return s + rung

    def _admit_hit(self, req: _Request, slot: int) -> None:
        """Admit a fully prefix-covered request without running prefill."""
        ps = self.page_size
        resumed = bool(req.tokens)
        L_eff = len(req.prompt_eff)
        if not (resumed and L_eff % ps == 0):
            # the first write (position L_eff-1 fresh, L_eff resumed) lands
            # in the last shared page: detach it now. fork() only pays the
            # device copy when other owners remain.
            old = req.pages[req.n_shared - 1]
            new = self.cache.allocator.fork(old)
            if new != old:
                self.cache.copy_page(old, new)
                if self.draft_cache is not None:
                    self.draft_cache.copy_page(old, new)
                req.pages[req.n_shared - 1] = new
        saved = L_eff if resumed else L_eff - 1
        self.prefix_hits += 1
        self.prefix_tokens_saved += saved
        if _obs.enabled():
            _obs_metrics.record_serve("prefix_hits")
            _obs_metrics.record_serve("prefix_tokens_saved", delta=saved)
        if resumed:
            self._on_resume(req)
            self._activate(req, slot, pos=L_eff, tok=req.tokens[-1])
        else:
            # t_first stays 0.0: TTFT is stamped when the first token
            # commits in decode (the re-decoded prompt token is not output)
            self._activate(req, slot, pos=L_eff - 1,
                           tok=int(req.prompt_eff[-1]))

    def _start_chunk(self, req: _Request, slot: int) -> None:
        """Reserve a slot for chunked prefill; chunks run under the
        per-iteration token budget in _advance_prefills."""
        req.chunk_pos = req.covered
        if req.covered:
            self.prefix_hits += 1
            self.prefix_tokens_saved += req.covered
            if _obs.enabled():
                _obs_metrics.record_serve("prefix_hits")
                _obs_metrics.record_serve("prefix_tokens_saved",
                                          delta=req.covered)
        self._chunking[slot] = req

    def _on_resume(self, req: _Request) -> None:
        self.resumed += 1
        if _obs.enabled():
            _obs_metrics.record_serve("resumed", event=True,
                                      request=req.request_id,
                                      n_tokens=len(req.tokens))
            _obs_trace.trace_event(req.trace_id, "resumed",
                                   request=req.request_id,
                                   n_tokens=len(req.tokens))

    def _preempt_one(self) -> bool:
        """Spill the most recently admitted batch-lane sequence: free its
        pages (shared ones just decref — the prefix cache keeps them warm)
        and requeue it at the FRONT of the batch lane for resume."""
        victim = None
        for i, r in enumerate(self._slots):
            if (r is not None and r.lane == "batch"
                    and (victim is None
                         or r.admit_seq > self._slots[victim].admit_seq)):
                victim = i
        if victim is None:
            return False
        req = self._slots[victim]
        self.cache.allocator.free(req.pages)
        req.pages = []
        self._clear_slot(victim)
        with self._lock:
            self._pending_batch.appendleft(req)
        self.preempted += 1
        if _obs.enabled():
            _obs_metrics.record_serve("preempted", event=True,
                                      request=req.request_id,
                                      n_tokens=len(req.tokens))
            _obs_trace.trace_event(req.trace_id, "preempted",
                                   request=req.request_id,
                                   n_tokens=len(req.tokens))
        return True

    def _maybe_preempt_for_slo(self) -> None:
        """Burn-rate-driven preemption: when the SLO monitor reports a
        breached or burning target while interactive requests queue, shed
        one batch sequence per iteration to shorten the interactive path."""
        if (not self.preemption or self.slo_monitor is None
                or not self._pending):
            return
        status = self.slo_monitor.status()
        burning = bool(status.get("breached")) or any(
            t.get("burn_rate") is not None and t["burn_rate"] >= 1.0
            for t in status.get("targets", {}).values())
        if burning:
            self._preempt_one()

    def _fail(self, req: _Request, exc: Exception) -> None:
        """Contain one request's failure: return its pages, fail its Future
        (waiters see the error instead of hanging), keep the engine alive."""
        # RESOURCE_EXHAUSTED through serving dispatch: dump the forensic
        # bundle (census + page-pool state) BEFORE freeing this request's
        # pages, so the bundle shows the pool as the allocator saw it
        _obs_mem.maybe_post_mortem(exc, step=self.decode_steps, source="serve")
        if req.pages:
            self.cache.allocator.free(req.pages)
            req.pages = []
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass  # caller's cancel() raced the done() window — already dead
        with self._lock:
            self._outstanding -= 1
        if _obs.enabled():
            _obs_metrics.record_serve("failed", event=True,
                                      request=req.request_id,
                                      error=type(exc).__name__)
            _obs_trace.trace_event(req.trace_id, "failed",
                                   request=req.request_id,
                                   error=type(exc).__name__)

    def _prefill(self, req: _Request, slot: int) -> None:
        obs_on = _obs.enabled()
        resumed = bool(req.tokens)
        L = len(req.prompt_eff) if req.prompt_eff is not None else len(req.prompt)
        prompt_eff = req.prompt_eff if req.prompt_eff is not None else req.prompt
        bucket = self.ladder.touch(L)
        req.bucket = bucket
        n_prompt_pages = bucket // self.page_size
        idx = np.zeros((1, bucket), np.int32)
        idx[0, :L] = prompt_eff
        page_ids = jnp.asarray(req.pages[:n_prompt_pages], jnp.int32)
        t0 = time.perf_counter()
        try:
            with (_obs_runtime.step_span("serve_prefill", request=req.request_id,
                                         bucket=bucket, prompt_len=L)
                  if obs_on else _NULL):
                logits, kps, vps = self.runner.prefill_cfn(
                    self.params, jnp.asarray(idx), page_ids,
                    self.cache.k_pages, self.cache.v_pages,
                    jnp.asarray(L - 1, jnp.int32))
                self.cache.rebind(kps, vps)
                if self.draft_cache is not None:
                    # the draft pool must hold the prompt too — same pages,
                    # same positions, draft weights (logits discarded)
                    _, dkps, dvps = self.draft_runner.prefill_cfn(
                        self.draft_params, jnp.asarray(idx), page_ids,
                        self.draft_cache.k_pages, self.draft_cache.v_pages,
                        jnp.asarray(L - 1, jnp.int32))
                    self.draft_cache.rebind(dkps, dvps)
                if not resumed:
                    tok0 = self._sampler(logits,
                                         jnp.asarray([req.seed], jnp.uint32),
                                         jnp.asarray([L], jnp.int32),
                                         jnp.asarray([req.temperature], jnp.float32))
                    tok0 = int(np.asarray(tok0)[0])
        except Exception as e:
            self._fail(req, e)
            return
        if self.prefix is not None:
            self.prefix.insert(prompt_eff, req.pages)
        t_done = time.perf_counter()
        if obs_on:
            util = round(self.cache.utilization(), 4)
            _obs_metrics.record_serve("prefills", event=True,
                                      request=req.request_id, bucket=bucket,
                                      prompt_len=L, ms=round((t_done - t0) * 1e3, 3),
                                      pool_utilization=util)
            _obs_metrics.record_serve("prefill_tokens", delta=L)
            _obs_tel.observe("serve.prefill_ms", (t_done - t0) * 1e3)
            _obs_tel.set_gauge("serve.pool_utilization", util)
            _obs_tel.set_gauge("serve.pages_in_use", self.cache.allocator.n_used)
            _obs_tel.set_gauge("serve.page_fragmentation",
                               round(self.page_fragmentation(), 4))
            _obs_trace.trace_event(req.trace_id, "prefill",
                                   request=req.request_id,
                                   dur_ms=(t_done - t0) * 1e3, bucket=bucket,
                                   prompt_len=L)
        if resumed:
            # the spilled stream already owns its next token; no sampling
            # (and t_first keeps the FIRST life's stamp — TTFT is end-to-end)
            self._on_resume(req)
            self._activate(req, slot, pos=L, tok=req.tokens[-1])
            return
        req.t_first = req.t_last = t_done
        req.tokens.append(tok0)
        if self._finished(req, tok0):
            self._retire(req)
            return
        self._activate(req, slot, pos=L, tok=tok0)

    def _advance_prefills(self) -> None:
        """Run queued prefill chunks under the per-iteration token budget.
        At least one chunk always runs when any is pending (progress even
        when a single chunk exceeds the budget); chunks from multiple
        requests share the budget in slot order."""
        if not self._chunking:
            return
        spent = 0
        for slot in sorted(self._chunking):
            req = self._chunking[slot]
            while spent < self.prefill_budget:
                try:
                    n_toks, logits = self._run_chunk(req)
                except Exception as e:
                    del self._chunking[slot]
                    self._fail(req, e)
                    break
                spent += n_toks
                if req.chunk_pos >= len(req.prompt_eff):
                    del self._chunking[slot]
                    self._finish_chunked(req, slot, logits)
                    break
            if spent >= self.prefill_budget:
                return

    def _run_chunk(self, req: _Request):
        """One page-aligned chunk of req's effective prompt: write K/V pages,
        attend everything written so far (shared prefix pages included).
        Returns (tokens_spent, logits) — logits only meaningful when this
        was the final chunk."""
        ps = self.page_size
        L_eff = len(req.prompt_eff)
        start = req.chunk_pos
        remaining = L_eff - start
        if remaining > self.chunk_tokens:
            cb = self.chunk_tokens
            last_rel = cb - 1  # logits discarded; any in-range index works
        else:
            cb = self.chunk_ladder.touch(remaining)
            if start + cb > self.max_seq:
                # the rounded rung would cross max_seq (and the rope table):
                # fall back to the exact page-aligned remainder
                cb = PagedKVCache.pages_for(remaining, ps) * ps
            last_rel = remaining - 1
        idx = np.zeros((1, cb), np.int32)
        n_real = min(cb, remaining)
        idx[0, :n_real] = req.prompt_eff[start:start + n_real]
        row = jnp.asarray(
            self.cache.page_table_row(req.pages, self.n_pages_max)[None, :])
        obs_on = _obs.enabled()
        t0 = time.perf_counter()
        with (_obs_runtime.step_span("serve_prefill", request=req.request_id,
                                     bucket=cb, prompt_len=L_eff, chunk=True,
                                     start=start)
              if obs_on else _NULL):
            logits, kps, vps = self.runner.chunk_cfn(
                self.params, jnp.asarray(idx), row, self.cache.k_pages,
                self.cache.v_pages, jnp.asarray(start, jnp.int32),
                jnp.asarray(last_rel, jnp.int32))
            self.cache.rebind(kps, vps)
            if self.draft_cache is not None:
                _, dkps, dvps = self.draft_runner.chunk_cfn(
                    self.draft_params, jnp.asarray(idx), row,
                    self.draft_cache.k_pages, self.draft_cache.v_pages,
                    jnp.asarray(start, jnp.int32),
                    jnp.asarray(last_rel, jnp.int32))
                self.draft_cache.rebind(dkps, dvps)
        req.chunk_pos = min(start + cb, L_eff)
        if obs_on:
            _obs_metrics.record_serve("prefill_tokens", delta=n_real)
            dur_ms = (time.perf_counter() - t0) * 1e3
            _obs_tel.observe("serve.prefill_ms", dur_ms)
            _obs_trace.trace_event(req.trace_id, "prefill_chunk",
                                   request=req.request_id, dur_ms=dur_ms,
                                   start=start, tokens=n_real)
        return cb, logits

    def _finish_chunked(self, req: _Request, slot: int, logits) -> None:
        """Final chunk done: register the prompt's full pages in the prefix
        cache, sample the first token (fresh requests), activate the slot."""
        obs_on = _obs.enabled()
        L_eff = len(req.prompt_eff)
        req.bucket = self.ladder.bucket_for(L_eff)
        if self.prefix is not None:
            self.prefix.insert(req.prompt_eff, req.pages)
        if obs_on:
            util = round(self.cache.utilization(), 4)
            _obs_metrics.record_serve("prefills", event=True,
                                      request=req.request_id,
                                      bucket=req.bucket, prompt_len=L_eff,
                                      chunked=True, pool_utilization=util)
            _obs_tel.set_gauge("serve.pool_utilization", util)
            _obs_tel.set_gauge("serve.pages_in_use",
                               self.cache.allocator.n_used)
            _obs_tel.set_gauge("serve.page_fragmentation",
                               round(self.page_fragmentation(), 4))
        if req.tokens:
            self._on_resume(req)
            self._activate(req, slot, pos=L_eff, tok=req.tokens[-1])
            return
        try:
            tok0 = self._sampler(logits, jnp.asarray([req.seed], jnp.uint32),
                                 jnp.asarray([L_eff], jnp.int32),
                                 jnp.asarray([req.temperature], jnp.float32))
            tok0 = int(np.asarray(tok0)[0])
        except Exception as e:
            self._fail(req, e)
            return
        req.t_first = req.t_last = time.perf_counter()
        req.tokens.append(tok0)
        if self._finished(req, tok0):
            self._retire(req)
            return
        self._activate(req, slot, pos=L_eff, tok=tok0)

    def _activate(self, req: _Request, slot: int, *, pos: int, tok: int) -> None:
        self._slots[slot] = req
        self._page_tables[slot] = self.cache.page_table_row(req.pages,
                                                            self.n_pages_max)
        self._pos[slot] = pos
        self._toks[slot] = tok
        self._seeds[slot] = req.seed
        self._temps[slot] = req.temperature
        self._pt_dirty = True

    def _clear_slot(self, i: int) -> None:
        self._slots[i] = None
        self._page_tables[i] = 0
        self._pos[i] = 0
        self._toks[i] = 0
        self._seeds[i] = 0
        self._temps[i] = 0.0
        self._pt_dirty = True

    def _upload_packed_state(self) -> None:
        # page tables / seeds / temps only change at slot (un)assignment;
        # re-upload them then, not per token (pos/toks change every step)
        if self._pt_dirty:
            self._pt_dev = jnp.asarray(self._page_tables)
            self._seeds_dev = jnp.asarray(self._seeds)
            self._temps_dev = jnp.asarray(self._temps)
            self._pt_dirty = False

    def _commit(self, i: int, req: _Request, tok: int, t_now: float) -> bool:
        """Commit one generated token to slot i; returns False when the
        request finished (retired, slot cleared)."""
        if req.t_first == 0.0:
            # prefix-hit admissions skip prefill: TTFT stamps at the first
            # committed token instead
            req.t_first = t_now
        req.tokens.append(tok)
        req.t_last = t_now
        self._pos[i] += 1
        self._toks[i] = tok
        if self._finished(req, tok):
            self._retire(req)
            self._clear_slot(i)
            return False
        return True

    def _decode(self) -> None:
        if self.draft_cache is not None and self.spec_k > 0:
            self._spec_decode()
            return
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return
        obs_on = _obs.enabled()
        t0 = time.perf_counter()
        self._upload_packed_state()
        try:
            with (_obs_runtime.step_span("serve_decode", active=len(active))
                  if obs_on else _NULL):
                logits, kps, vps = self.runner.decode_cfn(
                    self.params, jnp.asarray(self._toks[:, None]),
                    self.cache.k_pages, self.cache.v_pages,
                    self._pt_dev, jnp.asarray(self._pos))
                self.cache.rebind(kps, vps)
                # the NEXT token's position is pos+1 (this step wrote pos)
                nxt = self._sampler(logits, self._seeds_dev,
                                    jnp.asarray(self._pos + 1),
                                    self._temps_dev)
                nxt = np.asarray(nxt)
        except Exception as e:
            # the packed step failed: every active sequence is implicated —
            # fail their futures and return their pages rather than hanging
            # the whole engine (pending requests still get admitted)
            for i in active:
                self._fail(self._slots[i], e)
                self._clear_slot(i)
            return
        t_now = time.perf_counter()
        self.decode_steps += 1
        if obs_on:
            _obs_metrics.record_serve("decode_steps")
            _obs_metrics.record_serve("tokens", delta=len(active))
            _obs_flight.record_step((t_now - t0) * 1e3, fn="serve_decode",
                                    active=len(active))
            # online decode-iteration latency percentiles (unsampled, like
            # the flight recorder — TT_OBS_SAMPLE only thins the spans)
            _obs_tel.observe("serve.decode_ms", (t_now - t0) * 1e3)
            # ONE shared trace event per step carrying every participant
            # (volume scales with steps, not steps × batch width)
            _obs_trace.trace_step(
                [self._slots[i].trace_id for i in active], "decode",
                dur_ms=(t_now - t0) * 1e3, step=self.decode_steps,
                active=len(active))
        for i in active:
            self._commit(i, self._slots[i], int(nxt[i]), t_now)

    def _spec_decode(self) -> None:
        """Speculative decode iteration: k draft decode steps propose, one
        packed target verify step scores all k+1 positions, the accepted
        prefix commits (capped at k — NO bonus token, which is what keeps
        the draft pool valid through the new position without a catch-up
        pass). The draft proposes with the SAME position-keyed sampler, so
        a perfect draft accepts everything and every committed token is
        bit-identical to plain decode either way."""
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return
        obs_on = _obs.enabled()
        k = self.spec_k
        K1 = k + 1
        t0 = time.perf_counter()
        self._upload_packed_state()
        try:
            with (_obs_runtime.step_span("serve_decode", active=len(active),
                                         spec_k=k)
                  if obs_on else _NULL):
                base_pos = self._pos.copy()
                cand = [self._toks.copy()]
                cur = jnp.asarray(self._toks[:, None])
                for j in range(1, k + 1):
                    dlog, dkps, dvps = self.draft_runner.decode_cfn(
                        self.draft_params, cur, self.draft_cache.k_pages,
                        self.draft_cache.v_pages, self._pt_dev,
                        jnp.asarray(base_pos + (j - 1)))
                    self.draft_cache.rebind(dkps, dvps)
                    dj = np.asarray(self._sampler(
                        dlog, self._seeds_dev, jnp.asarray(base_pos + j),
                        self._temps_dev))
                    cand.append(dj)
                    cur = jnp.asarray(dj[:, None])
                toks_mat = np.stack(cand, axis=1)  # (max_batch, k+1)
                vlog, kps, vps = self.runner.verify_cfn(
                    self.params, jnp.asarray(toks_mat), self.cache.k_pages,
                    self.cache.v_pages, self._pt_dev, jnp.asarray(base_pos))
                self.cache.rebind(kps, vps)
                B = toks_mat.shape[0]
                pos_flat = (base_pos[:, None] + 1
                            + np.arange(K1, dtype=np.int32)[None, :]).reshape(-1)
                samples = np.asarray(self._sampler(
                    jnp.reshape(vlog, (B * K1, -1)),
                    jnp.asarray(np.repeat(self._seeds, K1)),
                    jnp.asarray(pos_flat),
                    jnp.asarray(np.repeat(self._temps, K1)))).reshape(B, K1)
        except Exception as e:
            for i in active:
                self._fail(self._slots[i], e)
                self._clear_slot(i)
            return
        t_now = time.perf_counter()
        self.decode_steps += 1
        # participant ids captured BEFORE commits (a finishing commit clears
        # its slot); only read when tracing is on
        trace_ids = ([self._slots[i].trace_id for i in active]
                     if obs_on else [])
        committed_total = 0
        accepted_total = 0
        for i in active:
            req = self._slots[i]
            m = 0
            while m < k and toks_mat[i, m + 1] == samples[i, m]:
                m += 1
            # commit the accepted samples; min(m+1, k) keeps the draft pool
            # valid (a bonus k+1th token would advance the target one
            # position past anything the draft ever wrote)
            n = min(m + 1, k)
            self.spec_proposed += k
            self.spec_accepted += m
            accepted_total += m
            for j in range(n):
                committed_total += 1
                if not self._commit(i, req, int(samples[i, j]), t_now):
                    break
        if obs_on:
            _obs_metrics.record_serve("decode_steps")
            _obs_metrics.record_serve("tokens", delta=committed_total)
            _obs_metrics.record_serve("spec_proposed", delta=k * len(active))
            _obs_metrics.record_serve("spec_accepted", delta=accepted_total)
            _obs_flight.record_step((t_now - t0) * 1e3, fn="serve_decode",
                                    active=len(active), spec_k=k,
                                    committed=committed_total)
            _obs_tel.observe("serve.decode_ms", (t_now - t0) * 1e3)
            _obs_trace.trace_step(trace_ids, "spec_verify",
                                  dur_ms=(t_now - t0) * 1e3,
                                  step=self.decode_steps, spec_k=k,
                                  accepted=accepted_total,
                                  committed=committed_total)

    def _finished(self, req: _Request, tok: int) -> bool:
        if req.future.cancelled():
            # the caller gave up: stop decoding and free the pages now
            return True
        if req.eos_id is not None and tok == req.eos_id:
            return True
        return len(req.tokens) >= req.max_new_tokens

    def _retire(self, req: _Request) -> None:
        self.cache.allocator.free(req.pages)
        req.pages = []
        n_new = len(req.tokens)
        # t_first == 0.0 only for a prefix-hit request cancelled before its
        # first committed token — report a zero TTFT rather than a negative
        ttft = (req.t_first - req.t_submit) if req.t_first else 0.0
        tbot = ((req.t_last - req.t_first) / (n_new - 1)) if n_new > 1 else 0.0
        if req.future.cancelled():
            # a client-side cancel is not a completion: tag it so latency
            # percentiles (obs_summary) aren't polluted by truncated samples
            reason = "cancelled"
        elif (req.eos_id is not None and req.tokens
              and req.tokens[-1] == req.eos_id):
            reason = "eos"
        else:
            reason = "length"
        obs_on = _obs.enabled()
        slo_met = None
        if reason != "cancelled":
            ttft_ms = ttft * 1e3
            # a one-token request has no between-token interval: exclude it
            # from the tbot population (online AND offline percentiles use
            # the same rule) rather than stream a 0.0 placeholder
            tbot_ms = tbot * 1e3 if n_new > 1 else None
            if self.slo_policy is not None:
                slo_met = self.slo_policy.request_met(ttft_ms, tbot_ms)
                self.requests_retired += 1
                self.requests_slo_met += int(slo_met)
            if obs_on:
                # streaming percentiles: the online mirror of the offline
                # serving section's TTFT/TBOT populations (cancelled
                # requests excluded from both); per-lane series alongside
                # the aggregate so SLO triage can split interactive vs batch
                _obs_tel.observe("serve.ttft_ms", ttft_ms)
                _obs_tel.observe(f"serve.ttft_ms.{req.lane}", ttft_ms)
                if tbot_ms is not None:
                    _obs_tel.observe("serve.tbot_ms", tbot_ms)
                    _obs_tel.observe(f"serve.tbot_ms.{req.lane}", tbot_ms)
            if self.slo_monitor is not None:
                self.slo_monitor.observe_request(
                    ttft_ms=ttft_ms, tbot_ms=tbot_ms, met=bool(slo_met),
                    tokens=n_new)
        if obs_on:
            util = round(self.cache.utilization(), 4)
            _obs_tel.set_gauge("serve.pool_utilization", util)
            _obs_tel.set_gauge("serve.pages_in_use", self.cache.allocator.n_used)
            _obs_tel.set_gauge("serve.page_fragmentation",
                               round(self.page_fragmentation(), 4))
            if self.slo_policy is not None and self.requests_retired:
                _obs_tel.set_gauge(
                    "serve.goodput",
                    round(self.requests_slo_met / self.requests_retired, 4))
            _obs_metrics.record_serve(
                "cancelled" if reason == "cancelled" else "retired",
                event=True, request=req.request_id, n_new=n_new,
                ttft_ms=round(ttft * 1e3, 3), tbot_ms=round(tbot * 1e3, 3),
                finish=reason, lane=req.lane, pool_utilization=util)
            _obs_trace.trace_event(req.trace_id, "retired",
                                   request=req.request_id, finish=reason,
                                   n_new=n_new, ttft_ms=round(ttft * 1e3, 3),
                                   lane=req.lane)
        result = RequestResult(
            request_id=req.request_id,
            tokens=np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)]),
            new_tokens=np.asarray(req.tokens, np.int32),
            ttft_s=ttft,
            tbot_s=tbot,
            n_new_tokens=n_new,
            finish_reason=reason,
            slo_met=slo_met,
        )
        try:
            # a cancel() from the caller thread can land at ANY point, so a
            # done() pre-check would still race — set and swallow the loss
            # (pages are already freed above either way)
            req.future.set_result(result)
        except InvalidStateError:
            pass
        with self._lock:
            self._outstanding -= 1
