"""Block-paged KV cache: a fixed page pool shared by all in-flight sequences.

The vLLM/PagedAttention (SOSP '23) memory design mapped onto the static-shape
XLA world: each layer owns one `(n_pages, page_size, n_kv_heads, head_dim)`
device array and every sequence owns an int32 row of page ids into it. The
pool shape never changes, so ONE compiled decode step serves every mix of
sequence lengths; allocation is pure host bookkeeping over a free-list, and
a finished request's pages return to the pool immediately at retirement.

Page 0 is reserved as the NULL page: unallocated page-table entries and idle
decode slots point at it, keeping every gather/DMA in-bounds (the attention
masks its values out via seq_lens; see executors/pallasex.py).
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

NULL_PAGE = 0


class OutOfPages(Exception):
    """The pool cannot satisfy an allocation; the scheduler queues the
    request until retirements return pages."""


class PageAllocator:
    """Free-list allocator over page ids [1, n_pages); page 0 is the
    reserved null page and is never handed out."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need at least 2 pages (1 usable + null), got {n_pages}")
        self.n_pages = n_pages
        # LIFO free-list: recently-freed pages are re-used first (their pool
        # slices are most likely still warm in cache hierarchies that care).
        # The mirror set makes free()'s double-free check O(1) — retirement
        # runs inside the decode iteration loop, so freeing k pages must not
        # scan a production-sized free list k times.
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._free_set = set(self._free)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise OutOfPages(f"requested {n} pages, {len(self._free)} free "
                             f"of {self.n_pages - 1} usable")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, pages: List[int]) -> None:
        seen = set()
        for p in pages:
            if not (0 < p < self.n_pages):
                raise ValueError(f"freeing invalid page id {p}")
            if p in self._free_set or p in seen:
                # a duplicate WITHIN the call is a double free too: letting
                # it through would hand the same page to two sequences later
                raise ValueError(f"double free of page {p}")
            seen.add(p)
        self._free.extend(pages)
        self._free_set.update(pages)

    def utilization(self) -> float:
        usable = self.n_pages - 1
        return self.n_used / usable if usable else 0.0


class PagedKVCache:
    """Per-layer paged K/V pools plus the allocator that parcels them out.

    The device arrays are FUNCTIONAL state: the decode/prefill programs
    return updated pools and the scheduler re-binds `k_pages`/`v_pages`
    each step (same discipline as the dense engine's KVCache tuples).
    """

    def __init__(self, n_layer: int, n_pages: int, page_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16):
        shape = (n_pages, page_size, n_kv_heads, head_dim)
        self.n_layer = n_layer
        self.n_pages = n_pages
        self.page_size = page_size
        self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.k_pages = tuple(jnp.zeros(shape, dtype) for _ in range(n_layer))
        self.v_pages = tuple(jnp.zeros(shape, dtype) for _ in range(n_layer))
        self.allocator = PageAllocator(n_pages)

    @staticmethod
    def pages_for(n_tokens: int, page_size: int) -> int:
        return max(1, math.ceil(n_tokens / page_size))

    def rebind(self, k_pages, v_pages) -> None:
        """Adopt the updated pools returned by a compiled step."""
        self.k_pages = tuple(k_pages)
        self.v_pages = tuple(v_pages)

    def utilization(self) -> float:
        return self.allocator.utilization()

    def page_table_row(self, pages: List[int], n_pages_max: int) -> np.ndarray:
        """A sequence's page-table row, padded with the null page."""
        row = np.full((n_pages_max,), NULL_PAGE, np.int32)
        row[: len(pages)] = np.asarray(pages, np.int32)
        return row
