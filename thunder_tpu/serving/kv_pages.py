"""Block-paged KV cache: a fixed page pool shared by all in-flight sequences.

The vLLM/PagedAttention (SOSP '23) memory design mapped onto the static-shape
XLA world: each layer owns one `(n_pages, page_size, n_kv_heads, head_dim)`
device array and every sequence owns an int32 row of page ids into it. The
pool shape never changes, so ONE compiled decode step serves every mix of
sequence lengths; allocation is pure host bookkeeping over a free-list, and
a finished request's pages return to the pool immediately at retirement.

Pages are REFCOUNTED: prefix sharing (PrefixCache below) maps the same
physical page into many sequences' tables, so `free` is a decref and a page
only returns to the free-list when its last owner lets go. A write into a
shared page goes through `PageAllocator.fork` + `PagedKVCache.copy_page`
(copy-on-write; docs/serving.md).

Page 0 is reserved as the NULL page: unallocated page-table entries and idle
decode slots point at it, keeping every gather/DMA in-bounds (the attention
masks its values out via seq_lens; see executors/pallasex.py).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

NULL_PAGE = 0


class OutOfPages(Exception):
    """The pool cannot satisfy an allocation; the scheduler queues the
    request until retirements return pages."""


class PageAllocator:
    """Refcounting free-list allocator over page ids [1, n_pages); page 0 is
    the reserved null page and is never handed out.

    alloc() hands out pages at refcount 1; incref() adds an owner (prefix
    sharing); free() is a DECREF — the page returns to the free-list only
    when the count reaches zero. The double-free check and the refcount
    bookkeeping live in one place (free), so a shared page freed by one
    owner can never re-enter the free list while other owners hold it."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need at least 2 pages (1 usable + null), got {n_pages}")
        self.n_pages = n_pages
        # LIFO free-list: recently-freed pages are re-used first (their pool
        # slices are most likely still warm in cache hierarchies that care).
        # The mirror set makes free()'s double-free check O(1) — retirement
        # runs inside the decode iteration loop, so freeing k pages must not
        # scan a production-sized free list k times.
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._rc: Dict[int, int] = {}  # page id -> live owner count

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise OutOfPages(f"requested {n} pages, {len(self._free)} free "
                             f"of {self.n_pages - 1} usable")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        for p in out:
            self._rc[p] = 1
        return out

    def incref(self, page: int) -> None:
        """Add an owner to an ALLOCATED page (prefix sharing: a new sequence
        or the prefix cache maps an existing physical page)."""
        if page in self._free_set or page not in self._rc:
            raise ValueError(f"incref of unallocated page {page}")
        self._rc[page] += 1

    def refcount(self, page: int) -> int:
        return self._rc.get(page, 0)

    def free(self, pages: List[int]) -> None:
        seen = set()
        for p in pages:
            if not (0 < p < self.n_pages):
                raise ValueError(f"freeing invalid page id {p}")
            if p in self._free_set or p in seen or p not in self._rc:
                # a duplicate WITHIN the call is a double free too: letting
                # it through would hand the same page to two sequences later.
                # (Callers hold at most one reference per page per free()
                # call; a multi-ref owner decrefs across separate calls.)
                raise ValueError(f"double free of page {p}")
            seen.add(p)
        released = []
        for p in pages:
            self._rc[p] -= 1
            if self._rc[p] == 0:
                del self._rc[p]
                released.append(p)
        self._free.extend(released)
        self._free_set.update(released)

    def fork(self, page: int) -> int:
        """Copy-on-write fork: detach THIS owner from a (possibly shared)
        page before writing into it. With other owners present, allocates a
        fresh page, drops this owner's reference on the old one, and returns
        the new id — the caller must then `PagedKVCache.copy_page(old, new)`
        and patch its page table. A sole owner gets the SAME id back (no
        other reader, writing in place is safe and no copy is paid)."""
        if page in self._free_set or page not in self._rc:
            raise ValueError(f"fork of unallocated page {page}")
        if self._rc[page] == 1:
            return page
        new = self.alloc(1)[0]
        self._rc[page] -= 1
        return new

    def utilization(self) -> float:
        usable = self.n_pages - 1
        return self.n_used / usable if usable else 0.0


class _PrefixNode:
    __slots__ = ("key", "page", "children", "parent")

    def __init__(self, key: Tuple[int, ...], page: int, parent):
        self.key = key          # the page's page_size prompt tokens
        self.page = page        # physical page id (cache holds one ref)
        self.children: Dict[Tuple[int, ...], "_PrefixNode"] = {}
        self.parent = parent


class PrefixCache:
    """Prefix -> page-id map: a trie over FULL prompt pages, keyed by each
    page's token tuple (content-keyed, so two prompts sharing a system
    prefix hit the same chain whatever request produced it).

    * `match(prompt)` walks the trie page by page, increfs every matched
      page on the caller's behalf, and additionally probes a PARTIAL tail:
      a prompt whose last (< page_size) tokens are a prefix of some cached
      page's tokens is fully covered — the scheduler then skips prefill
      entirely and re-decodes only the last prompt token (the write that
      triggers the copy-on-write fork).
    * `insert(prompt, pages)` registers a freshly prefilled request's full
      prompt pages; the cache holds its OWN reference on each registered
      page, so donors can retire without invalidating the chain.
    * Eviction is LRU over trie nodes (leaves first, so chains stay
      connected) and runs under pool pressure via `evict_until` — an evicted
      page is only decref'd, so sequences still sharing it are untouched.
    """

    def __init__(self, allocator: PageAllocator, page_size: int):
        self.allocator = allocator
        self.page_size = page_size
        self._root: Dict[Tuple[int, ...], _PrefixNode] = {}
        self._lru: Dict[_PrefixNode, None] = {}  # insertion-ordered; end = newest

    def __len__(self) -> int:
        return len(self._lru)

    def _touch(self, node: _PrefixNode) -> None:
        self._lru.pop(node, None)
        self._lru[node] = None

    def match(self, prompt: np.ndarray) -> Tuple[List[int], int]:
        """(shared_pages, covered_tokens) for a prompt; every returned page
        has been incref'd for the caller (who must free them like any other
        page it owns). covered_tokens == len(prompt) means full coverage
        (possibly via a partial-tail hit on the last page)."""
        ps = self.page_size
        L = len(prompt)
        pages: List[int] = []
        children = self._root
        node = None
        n_full = L // ps
        for i in range(n_full):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            nxt = children.get(key)
            if nxt is None:
                break
            node = nxt
            self._touch(node)
            self.allocator.incref(node.page)
            pages.append(node.page)
            children = node.children
        covered = len(pages) * ps
        if covered == L:
            return pages, covered
        if len(pages) == L // ps and L % ps:
            # partial tail: the remaining (< page_size) prompt tokens may be
            # the LEADING tokens of some cached full page — sharing it covers
            # the whole prompt; the first decode write CoW-forks it
            tail = tuple(int(t) for t in prompt[n_full * ps:])
            for key, child in children.items():
                if key[:len(tail)] == tail:
                    self._touch(child)
                    self.allocator.incref(child.page)
                    pages.append(child.page)
                    return pages, L
        return pages, covered

    def insert(self, prompt: np.ndarray, pages: List[int]) -> int:
        """Register the FULL prompt pages of a prefilled request (partial
        last pages are never registered — they would mix prompt and
        generated tokens). Existing nodes are touched, new ones incref
        their page. Returns the number of newly registered pages."""
        ps = self.page_size
        n_full = len(prompt) // ps
        children = self._root
        parent = None
        added = 0
        for i in range(min(n_full, len(pages))):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            node = children.get(key)
            if node is None:
                self.allocator.incref(pages[i])
                node = _PrefixNode(key, pages[i], parent)
                children[key] = node
                added += 1
            self._touch(node)
            children = node.children
            parent = node
        return added

    def _evict(self, node: _PrefixNode) -> None:
        siblings = node.parent.children if node.parent is not None else self._root
        siblings.pop(node.key, None)
        self._lru.pop(node, None)
        self.allocator.free([node.page])

    def evict_until(self, n_needed: int) -> bool:
        """Drop LRU leaf nodes until the allocator can serve `n_needed`
        pages (or nothing evictable remains). Only the cache's OWN reference
        is dropped: pages still mapped by live sequences survive; pages only
        the cache held return to the free-list."""
        while not self.allocator.can_alloc(n_needed):
            victim = next((n for n in self._lru if not n.children), None)
            if victim is None:
                return False
            self._evict(victim)
        return True

    def clear(self) -> None:
        while self._lru:
            victim = next(n for n in self._lru if not n.children)
            self._evict(victim)


class PagedKVCache:
    """Per-layer paged K/V pools plus the allocator that parcels them out.

    The device arrays are FUNCTIONAL state: the decode/prefill programs
    return updated pools and the scheduler re-binds `k_pages`/`v_pages`
    each step (same discipline as the dense engine's KVCache tuples).
    """

    def __init__(self, n_layer: int, n_pages: int, page_size: int,
                 n_kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 allocator: Optional[PageAllocator] = None):
        shape = (n_pages, page_size, n_kv_heads, head_dim)
        self.n_layer = n_layer
        self.n_pages = n_pages
        self.page_size = page_size
        self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.dtype = dtype
        self.k_pages = tuple(jnp.zeros(shape, dtype) for _ in range(n_layer))
        self.v_pages = tuple(jnp.zeros(shape, dtype) for _ in range(n_layer))
        # a draft-model cache (speculative decoding) shares the TARGET
        # cache's allocator: one allocation covers both pools, page ids and
        # page tables are identical across the two
        self.allocator = allocator if allocator is not None else PageAllocator(n_pages)
        self._copy_cfn = None

    @staticmethod
    def pages_for(n_tokens: int, page_size: int) -> int:
        return max(1, math.ceil(n_tokens / page_size))

    def rebind(self, k_pages, v_pages) -> None:
        """Adopt the updated pools returned by a compiled step."""
        self.k_pages = tuple(k_pages)
        self.v_pages = tuple(v_pages)

    def copy_page(self, src: int, dst: int) -> None:
        """Device-copy one page's K/V across every layer (the copy-on-write
        body after `PageAllocator.fork`). One cached jax.jit program — src
        and dst ride as traced scalars, so CoW never recompiles."""
        import jax

        if self._copy_cfn is None:
            def _copy(kps, vps, s, d):
                return (tuple(kp.at[d].set(kp[s]) for kp in kps),
                        tuple(vp.at[d].set(vp[s]) for vp in vps))

            self._copy_cfn = jax.jit(_copy)
        kps, vps = self._copy_cfn(self.k_pages, self.v_pages,
                                  jnp.asarray(src, jnp.int32),
                                  jnp.asarray(dst, jnp.int32))
        self.rebind(kps, vps)

    def utilization(self) -> float:
        return self.allocator.utilization()

    def page_table_row(self, pages: List[int], n_pages_max: int) -> np.ndarray:
        """A sequence's page-table row, padded with the null page."""
        row = np.full((n_pages_max,), NULL_PAGE, np.int32)
        row[: len(pages)] = np.asarray(pages, np.int32)
        return row
