"""thunder_tpu.serving — continuous-batching inference engine.

The production counterpart of the single-stream `inference.GPTInference`
(ROADMAP open item #2): a fixed page pool of KV memory shared by all
in-flight sequences (kv_pages.py), paged decode/prefill programs traced
through the thunder jit (runner.py), and a continuous-batching scheduler
that admits, decodes, and retires requests every iteration (scheduler.py).

    from thunder_tpu.serving import ServingEngine
    engine = ServingEngine(gpt, max_batch=8, page_size=16, max_seq=256)
    fut = engine.submit(prompt_ids, max_new_tokens=32)
    result = fut.result()      # result.tokens, result.ttft_s, result.tbot_s

Fleet-serving stages (docs/serving.md) layer on the same engine: refcounted
copy-on-write prefix sharing (PrefixCache), chunked prefill, speculative
decoding via a draft model, and SLO-aware interactive/batch lanes with
preemption.
"""
from .kv_pages import NULL_PAGE, OutOfPages, PageAllocator, PagedKVCache, PrefixCache
from .scheduler import RequestResult, ServingEngine

__all__ = [
    "NULL_PAGE",
    "OutOfPages",
    "PageAllocator",
    "PagedKVCache",
    "PrefixCache",
    "RequestResult",
    "ServingEngine",
]
