"""Paged model programs: bucketed prefill and the packed decode step.

The serving analog of `inference.GPTInference._forward_cached`: the GPT
module structure is reused and the blocks run manually under the thunder
jit, but the KV state is the shared page pool (kv_pages.py) instead of a
per-request dense cache.

Two compiled programs:

* prefill — per prompt-length BUCKET (power-of-two): dense causal attention
  over the padded prompt, page write-out of the prompt's K/V, logits at the
  true last token. One thunder specialization per bucket; buckets come from
  the system-wide BucketLadder (compile_service/buckets.py), which also
  keeps the steady-state MRU bookkeeping.
* decode — ONE program for the whole engine: every active sequence
  contributes one token; k/v land in the pool at (page_table[pos//ps],
  pos%ps) via a batched index_put and attention runs over the pages
  (ltorch.paged_attention — pallas kernel on TPU, jax gather on CPU).
* chunk_prefill — one CHUNK of a long (or prefix-shared) prompt: page-
  aligned writes starting at an arbitrary page boundary `start_pos`, with
  write-then-attend paged attention (ltorch.paged_chunk_attention) so the
  chunk's queries see both the previously written pages (including pages
  SHARED from the prefix cache) and their own chunk. The scheduler
  interleaves chunks into decode iterations under a token budget.
* verify — the speculative-decoding target step: k+1 tokens per packed
  sequence (the current token plus k draft proposals) processed in ONE
  program with logits at every position; the scheduler samples all k+1
  positions with the position-keyed sampler and commits the accepted
  prefix. Rolled-back positions are simply never committed — their page
  slots hold stale values that the next committed token overwrites.

All are pure functional: pools go in, updated pools come out.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..inference import block_mix, cached_sdpa, split_qkv_rope
from ..observability import runtime as _obs_runtime
from ..ops import clang, ltorch


def _annotated(cfn, name: str):
    """Wrap one compiled serving program so each dispatch runs under a
    host-side profiler annotation (``annotate_call`` — a shared no-op
    context when the bus is disabled, so the hot path pays one enabled()
    read). The wrapper keeps ``_cfn`` pointing at the real compiled
    function, which is the fallback attribute ``last_compile_report``
    already resolves through."""

    @functools.wraps(cfn)
    def dispatch(*args, **kwargs):
        with _obs_runtime.annotate_call(name):
            return cfn(*args, **kwargs)

    dispatch._cfn = cfn
    return dispatch


def quantize_for_serving(gpt, mode: Optional[str]):
    """Apply weight-only quantization to a GPT before its paged programs are
    traced. ``mode``: None/``"none"`` is a no-op; ``"int8"`` swaps every
    Linear's weights for symmetric per-output-channel int8 + f32 scales
    (transforms/quantization.py), so the packed decode step's matmuls run
    int8 x bf16 with the dequant in-register — the Pallas int8_linear kernel
    on TPU (executors/pallasex.py; weights stay int8-resident in HBM, which
    is the decode-bandwidth win), XLA's dequant-matmul elsewhere.

    Must run BEFORE PagedGPTRunner traces the programs and before the engine
    snapshots ``named_parameters`` — both see the quantized module."""
    if mode in (None, "none"):
        return gpt
    if mode != "int8":
        raise ValueError(f"unknown serving quantization mode: {mode!r}")
    from ..transforms.quantization import QuantizeInt8Transform

    QuantizeInt8Transform().transform_module(gpt)
    return gpt


def bucket_len(n: int, *, minimum: int, maximum: int) -> int:
    """Next power-of-two >= n, floored at `minimum` (>= page_size so every
    bucket is page-aligned) and capped at `maximum` (= max_seq).

    Compat shim: the rounding rule now lives in the system-wide
    ``compile_service.buckets.BucketLadder`` (one ladder shared by serving
    prompt buckets, the bucketed TrainStep, and artifact keys)."""
    return _ladder(minimum, maximum).bucket_for(n)


@functools.lru_cache(maxsize=64)
def _ladder(minimum: int, maximum: int):
    from ..compile_service.buckets import BucketLadder

    return BucketLadder(minimum, maximum)


class PagedGPTRunner:
    """Traces and caches the paged prefill/decode programs for one GPT."""

    def __init__(self, gpt, *, page_size: int):
        from .. import jit as _jit
        from ..nn.module import functional_params

        self.gpt = gpt
        self.cfg = gpt.cfg
        self.page_size = page_size

        def prefill(params, idx, page_ids, kps, vps, last_pos):
            with functional_params(gpt, params):
                return self._forward_prefill(idx, page_ids, kps, vps, last_pos)

        def decode(params, toks, kps, vps, page_table, pos):
            with functional_params(gpt, params):
                return self._forward_decode(toks, kps, vps, page_table, pos)

        def chunk_prefill(params, idx, page_table_row, kps, vps, start_pos, last_rel):
            with functional_params(gpt, params):
                return self._forward_chunk(idx, page_table_row, kps, vps,
                                           start_pos, last_rel)

        def verify(params, toks, kps, vps, page_table, pos):
            with functional_params(gpt, params):
                return self._forward_verify(toks, kps, vps, page_table, pos)

        prefill.__name__ = "serve_prefill"
        decode.__name__ = "serve_decode"
        chunk_prefill.__name__ = "serve_chunk_prefill"
        verify.__name__ = "serve_verify"
        self.prefill_cfn = _annotated(_jit(prefill), "serve_prefill")
        self.decode_cfn = _annotated(_jit(decode), "serve_decode")
        self.chunk_cfn = _annotated(_jit(chunk_prefill), "serve_chunk_prefill")
        self.verify_cfn = _annotated(_jit(verify), "serve_verify")

    # block plumbing (qkv split/rope, residual/MoE tail) is shared with the
    # dense engine: inference.split_qkv_rope / inference.block_mix — one
    # implementation, so solo and batched decode can never drift

    # -- prefill ----------------------------------------------------------
    def _forward_prefill(self, idx, page_ids, kps, vps, last_pos):
        """idx (1, Lb) bucketed prompt; page_ids (Lb/page_size,) pages to
        write; last_pos scalar int32 — the true last token. Returns
        (logits (1, V), new k pools, new v pools). Padding tokens beyond
        last_pos write garbage K/V into the tail pages — causality keeps
        them out of every real token's attention and seq_lens masks them
        out of later paged decode."""
        from ..core import prims
        from ..models.litgpt import _repeat_kv

        cfg = self.cfg
        gpt = self.gpt
        B, T = idx.shape
        ps = self.page_size
        n_elem = cfg.rope_n_elem
        cos = clang.ensure_proxy(gpt.cos)[:T]
        sin = clang.ensure_proxy(gpt.sin)[:T]
        q_per_kv = cfg.n_head // cfg.n_query_groups
        x = gpt.wte(idx)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            # page write-out: (1, Hkv, T, hs) -> (T//ps, ps, Hkv, hs) blocks
            k_blocks = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            v_blocks = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            new_kps.append(ltorch.index_put(kps[li], (page_ids,), k_blocks))
            new_vps.append(ltorch.index_put(vps[li], (page_ids,), v_blocks))
            kq = _repeat_kv(k, q_per_kv) if cfg.n_query_groups != cfg.n_head else k
            vq = _repeat_kv(v, q_per_kv) if cfg.n_query_groups != cfg.n_head else v
            y = cached_sdpa(q, kq, vq, 0)
            y = ltorch.reshape(ltorch.permute(y, (0, 2, 1, 3)),
                               (B, T, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        # logits at the TRUE last token (the bucket pads past it)
        x_last = prims.dynamic_slice(x, (0, last_pos, 0), (B, 1, cfg.n_embd))
        logits = gpt.lm_head(gpt.ln_f(x_last))[:, 0]
        return logits, tuple(new_kps), tuple(new_vps)

    # -- decode -----------------------------------------------------------
    def _forward_decode(self, toks, kps, vps, page_table, pos):
        """toks (Bcap, 1) current tokens; page_table (Bcap, n_pages_max)
        int32; pos (Bcap,) int32 — each sequence's write position (= tokens
        already cached; idle slots carry pos 0 and a null-page row).
        Returns (logits (Bcap, V), new k pools, new v pools).

        Positions at/past the table's coverage (draft proposal steps near
        the max_new/max_seq cap run the decode program up to spec_k - 1
        positions ahead) clamp the rope gather and redirect the k/v write to
        the null page — garbage logits for those slots are never committed
        (scheduler accept rule), and the null page is masked everywhere."""
        cfg = self.cfg
        gpt = self.gpt
        B, T = toks.shape  # T == 1
        ps = self.page_size
        rope_rows = gpt.cos.shape[0]
        pos_r = ltorch.clamp(pos, max=rope_rows - 1)
        # per-sequence rope rows: gather cos/sin at each slot's position
        cos = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.cos), pos_r, 0),
                             (B, 1, 1, cfg.rope_n_elem))
        sin = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.sin), pos_r, 0),
                             (B, 1, 1, cfg.rope_n_elem))
        npm = page_table.shape[1]
        in_bounds = ltorch.lt(pos, npm * ps)
        page_of = ltorch.gather(page_table, 1, ltorch.reshape(
            ltorch.floor_divide(ltorch.clamp(pos, max=npm * ps - 1), ps),
            (B, 1)))[:, 0]  # (B,) page id
        page_of = ltorch.where(in_bounds, page_of, 0)
        slot = ltorch.remainder(pos, ps)
        seq_lens = pos + 1  # attention covers the token being written
        x = gpt.wte(toks)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            k_tok = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                   (B, cfg.n_query_groups, cfg.head_size))
            v_tok = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                   (B, cfg.n_query_groups, cfg.head_size))
            kp = ltorch.index_put(kps[li], (page_of, slot), k_tok)
            vp = ltorch.index_put(vps[li], (page_of, slot), v_tok)
            new_kps.append(kp)
            new_vps.append(vp)
            q3 = ltorch.reshape(q, (B, cfg.n_head, cfg.head_size))
            y = ltorch.paged_attention(q3, kp, vp, page_table, seq_lens)
            y = ltorch.reshape(y, (B, 1, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        logits = gpt.lm_head(gpt.ln_f(x[:, -1]))
        return logits, tuple(new_kps), tuple(new_vps)

    # -- chunked prefill --------------------------------------------------
    def _forward_chunk(self, idx, page_table_row, kps, vps, start_pos, last_rel):
        """idx (1, Cb) one page-aligned chunk of a prompt (Cb a multiple of
        page_size); page_table_row (1, n_pages_max) the sequence's FULL page
        table; start_pos scalar int32 (multiple of page_size) — the chunk's
        absolute first position; last_rel scalar int32 — the true last
        prompt token RELATIVE to the chunk (only meaningful on the final
        chunk; earlier chunks' logits are discarded by the scheduler).
        Returns (logits (1, V), new k pools, new v pools).

        The chunk WRITES its pages first and then attends the whole table
        with per-query coverage k_pos <= start_pos + t, so it sees every
        previously written page — including pages shared from the prefix
        cache (copy-on-write sharing; the chunk itself only ever writes
        UNSHARED pages, because shared coverage always ends at or before
        the chunk start). Pad tokens past `last_rel` on the final chunk
        write garbage K/V into reserved-but-unused page slots; every real
        query masks them out by position, and decode overwrites each slot
        before seq_lens ever admits it."""
        cfg = self.cfg
        gpt = self.gpt
        B, T = idx.shape  # B == 1
        ps = self.page_size
        n_elem = cfg.rope_n_elem
        from ..core import dtypes, prims

        cos = prims.dynamic_slice(clang.ensure_proxy(gpt.cos), (start_pos, 0),
                                  (T, n_elem))
        sin = prims.dynamic_slice(clang.ensure_proxy(gpt.sin), (start_pos, 0),
                                  (T, n_elem))
        chunk_pages = ltorch.reshape(
            prims.dynamic_slice(page_table_row,
                                (0, ltorch.floor_divide(start_pos, ps)),
                                (1, T // ps)), (T // ps,))
        q_pos = ltorch.reshape(
            prims.iota(T, dtype=dtypes.int32, device=idx.device) + start_pos, (1, T))
        x = gpt.wte(idx)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            k_blocks = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            v_blocks = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            kp = ltorch.index_put(kps[li], (chunk_pages,), k_blocks)
            vp = ltorch.index_put(vps[li], (chunk_pages,), v_blocks)
            new_kps.append(kp)
            new_vps.append(vp)
            y = ltorch.paged_chunk_attention(q, kp, vp, page_table_row, q_pos)
            y = ltorch.reshape(ltorch.permute(y, (0, 2, 1, 3)),
                               (B, T, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        x_last = prims.dynamic_slice(x, (0, last_rel, 0), (B, 1, cfg.n_embd))
        logits = gpt.lm_head(gpt.ln_f(x_last))[:, 0]
        return logits, tuple(new_kps), tuple(new_vps)

    # -- speculative verify -----------------------------------------------
    def _forward_verify(self, toks, kps, vps, page_table, pos):
        """toks (Bcap, k+1): each sequence's current token followed by its k
        draft proposals; pos (Bcap,) int32 — the position of toks[:, 0].
        Writes k/v for ALL k+1 tokens at positions pos..pos+k and returns
        (logits (Bcap, k+1, V), new k pools, new v pools) — logits at every
        position, so ONE packed target step scores every proposal.

        Rollback is free: the scheduler commits only the accepted prefix;
        rejected positions hold stale k/v that the next committed token's
        write replaces before any mask admits it. Writes past the table's
        coverage (proposals past the max_seq cap) redirect to the null
        page; rope gathers clamp — those positions' logits are garbage and
        the accept rule never commits them."""
        cfg = self.cfg
        gpt = self.gpt
        B, K1 = toks.shape
        ps = self.page_size
        npm = page_table.shape[1]
        n_elem = cfg.rope_n_elem
        rope_rows = gpt.cos.shape[0]
        from ..core import dtypes, prims

        offs = prims.iota(K1, dtype=dtypes.int32, device=toks.device)
        pos_mat = ltorch.reshape(pos, (B, 1)) + ltorch.reshape(offs, (1, K1))  # (B, K1)
        flat_pos = ltorch.reshape(ltorch.clamp(pos_mat, max=rope_rows - 1),
                                  (B * K1,))
        cos = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.cos), flat_pos, 0),
                             (B, 1, K1, n_elem))
        sin = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.sin), flat_pos, 0),
                             (B, 1, K1, n_elem))
        in_bounds = ltorch.lt(pos_mat, npm * ps)
        page_of = ltorch.gather(page_table, 1,
                                ltorch.floor_divide(
                                    ltorch.clamp(pos_mat, max=npm * ps - 1), ps))
        page_of = ltorch.where(in_bounds, page_of, 0)
        page_flat = ltorch.reshape(page_of, (B * K1,))
        slot_flat = ltorch.reshape(ltorch.remainder(pos_mat, ps), (B * K1,))
        x = gpt.wte(toks)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            k_tok = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                   (B * K1, cfg.n_query_groups, cfg.head_size))
            v_tok = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                   (B * K1, cfg.n_query_groups, cfg.head_size))
            kp = ltorch.index_put(kps[li], (page_flat, slot_flat), k_tok)
            vp = ltorch.index_put(vps[li], (page_flat, slot_flat), v_tok)
            new_kps.append(kp)
            new_vps.append(vp)
            y = ltorch.paged_chunk_attention(q, kp, vp, page_table, pos_mat)
            y = ltorch.reshape(ltorch.permute(y, (0, 2, 1, 3)),
                               (B, K1, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        logits = gpt.lm_head(gpt.ln_f(x))  # (B, K1, V)
        return logits, tuple(new_kps), tuple(new_vps)
