"""Paged model programs: bucketed prefill and the packed decode step.

The serving analog of `inference.GPTInference._forward_cached`: the GPT
module structure is reused and the blocks run manually under the thunder
jit, but the KV state is the shared page pool (kv_pages.py) instead of a
per-request dense cache.

Two compiled programs:

* prefill — per prompt-length BUCKET (power-of-two): dense causal attention
  over the padded prompt, page write-out of the prompt's K/V, logits at the
  true last token. One thunder specialization per bucket; buckets come from
  the system-wide BucketLadder (compile_service/buckets.py), which also
  keeps the steady-state MRU bookkeeping.
* decode — ONE program for the whole engine: every active sequence
  contributes one token; k/v land in the pool at (page_table[pos//ps],
  pos%ps) via a batched index_put and attention runs over the pages
  (ltorch.paged_attention — pallas kernel on TPU, jax gather on CPU).

Both are pure functional: pools go in, updated pools come out.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..inference import block_mix, cached_sdpa, split_qkv_rope
from ..ops import clang, ltorch


def bucket_len(n: int, *, minimum: int, maximum: int) -> int:
    """Next power-of-two >= n, floored at `minimum` (>= page_size so every
    bucket is page-aligned) and capped at `maximum` (= max_seq).

    Compat shim: the rounding rule now lives in the system-wide
    ``compile_service.buckets.BucketLadder`` (one ladder shared by serving
    prompt buckets, the bucketed TrainStep, and artifact keys)."""
    return _ladder(minimum, maximum).bucket_for(n)


@functools.lru_cache(maxsize=64)
def _ladder(minimum: int, maximum: int):
    from ..compile_service.buckets import BucketLadder

    return BucketLadder(minimum, maximum)


class PagedGPTRunner:
    """Traces and caches the paged prefill/decode programs for one GPT."""

    def __init__(self, gpt, *, page_size: int):
        from .. import jit as _jit
        from ..nn.module import functional_params

        self.gpt = gpt
        self.cfg = gpt.cfg
        self.page_size = page_size

        def prefill(params, idx, page_ids, kps, vps, last_pos):
            with functional_params(gpt, params):
                return self._forward_prefill(idx, page_ids, kps, vps, last_pos)

        def decode(params, toks, kps, vps, page_table, pos):
            with functional_params(gpt, params):
                return self._forward_decode(toks, kps, vps, page_table, pos)

        prefill.__name__ = "serve_prefill"
        decode.__name__ = "serve_decode"
        self.prefill_cfn = _jit(prefill)
        self.decode_cfn = _jit(decode)

    # block plumbing (qkv split/rope, residual/MoE tail) is shared with the
    # dense engine: inference.split_qkv_rope / inference.block_mix — one
    # implementation, so solo and batched decode can never drift

    # -- prefill ----------------------------------------------------------
    def _forward_prefill(self, idx, page_ids, kps, vps, last_pos):
        """idx (1, Lb) bucketed prompt; page_ids (Lb/page_size,) pages to
        write; last_pos scalar int32 — the true last token. Returns
        (logits (1, V), new k pools, new v pools). Padding tokens beyond
        last_pos write garbage K/V into the tail pages — causality keeps
        them out of every real token's attention and seq_lens masks them
        out of later paged decode."""
        from ..core import prims
        from ..models.litgpt import _repeat_kv

        cfg = self.cfg
        gpt = self.gpt
        B, T = idx.shape
        ps = self.page_size
        n_elem = cfg.rope_n_elem
        cos = clang.ensure_proxy(gpt.cos)[:T]
        sin = clang.ensure_proxy(gpt.sin)[:T]
        q_per_kv = cfg.n_head // cfg.n_query_groups
        x = gpt.wte(idx)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            # page write-out: (1, Hkv, T, hs) -> (T//ps, ps, Hkv, hs) blocks
            k_blocks = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            v_blocks = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                      (T // ps, ps, cfg.n_query_groups, cfg.head_size))
            new_kps.append(ltorch.index_put(kps[li], (page_ids,), k_blocks))
            new_vps.append(ltorch.index_put(vps[li], (page_ids,), v_blocks))
            kq = _repeat_kv(k, q_per_kv) if cfg.n_query_groups != cfg.n_head else k
            vq = _repeat_kv(v, q_per_kv) if cfg.n_query_groups != cfg.n_head else v
            y = cached_sdpa(q, kq, vq, 0)
            y = ltorch.reshape(ltorch.permute(y, (0, 2, 1, 3)),
                               (B, T, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        # logits at the TRUE last token (the bucket pads past it)
        x_last = prims.dynamic_slice(x, (0, last_pos, 0), (B, 1, cfg.n_embd))
        logits = gpt.lm_head(gpt.ln_f(x_last))[:, 0]
        return logits, tuple(new_kps), tuple(new_vps)

    # -- decode -----------------------------------------------------------
    def _forward_decode(self, toks, kps, vps, page_table, pos):
        """toks (Bcap, 1) current tokens; page_table (Bcap, n_pages_max)
        int32; pos (Bcap,) int32 — each sequence's write position (= tokens
        already cached; idle slots carry pos 0 and a null-page row).
        Returns (logits (Bcap, V), new k pools, new v pools)."""
        cfg = self.cfg
        gpt = self.gpt
        B, T = toks.shape  # T == 1
        ps = self.page_size
        # per-sequence rope rows: gather cos/sin at each slot's position
        cos = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.cos), pos, 0),
                             (B, 1, 1, cfg.rope_n_elem))
        sin = ltorch.reshape(clang.take(clang.ensure_proxy(gpt.sin), pos, 0),
                             (B, 1, 1, cfg.rope_n_elem))
        page_of = ltorch.gather(page_table, 1, ltorch.reshape(
            ltorch.floor_divide(pos, ps), (B, 1)))[:, 0]  # (B,) page id
        slot = ltorch.remainder(pos, ps)
        seq_lens = pos + 1  # attention covers the token being written
        x = gpt.wte(toks)
        new_kps, new_vps = [], []
        for li, block in enumerate(gpt.h):
            q, k, v = split_qkv_rope(block, cfg, block.norm_1(x), cos, sin)
            k_tok = ltorch.reshape(ltorch.permute(k, (0, 2, 1, 3)),
                                   (B, cfg.n_query_groups, cfg.head_size))
            v_tok = ltorch.reshape(ltorch.permute(v, (0, 2, 1, 3)),
                                   (B, cfg.n_query_groups, cfg.head_size))
            kp = ltorch.index_put(kps[li], (page_of, slot), k_tok)
            vp = ltorch.index_put(vps[li], (page_of, slot), v_tok)
            new_kps.append(kp)
            new_vps.append(vp)
            q3 = ltorch.reshape(q, (B, cfg.n_head, cfg.head_size))
            y = ltorch.paged_attention(q3, kp, vp, page_table, seq_lens)
            y = ltorch.reshape(y, (B, 1, cfg.n_head * cfg.head_size))
            x = block_mix(block, cfg, x, block.attn.proj(y))
        logits = gpt.lm_head(gpt.ln_f(x[:, -1]))
        return logits, tuple(new_kps), tuple(new_vps)
