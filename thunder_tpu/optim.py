"""Optimizers: functional cores + stateful torch-style wrappers.

The reference delegates optimizers to torch.optim (used by its LitGPT
benchmark harness, thunder/benchmarks/benchmark_litgpt.py). TPU-native, the
optimizer must live inside the single XLA training-step program, so the cores
here are pure-jax functions over (params, grads, state) pytrees that the
train-step compiler fuses with forward+backward."""
from __future__ import annotations

import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


def global_norm(grads) -> jnp.ndarray:
    """L2 norm over every leaf of a gradient pytree, accumulated in f32
    (the step-guard NaN/Inf gate and grad-clip recipes share this so the
    in-program health metric matches what clipping would see)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    return jnp.sqrt(total)


class SGD:
    def __init__(self, lr: float = 0.01, momentum: float = 0.0, weight_decay: float = 0.0):
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay

    def init(self, params: dict) -> dict:
        if self.momentum == 0.0:
            return {"step": jnp.zeros((), jnp.int32)}
        return {
            "step": jnp.zeros((), jnp.int32),
            "momentum_buf": {k: jnp.zeros_like(v) for k, v in params.items()},
        }

    def update(self, params: dict, grads: dict, state: dict):
        new_params = {}
        new_state = {"step": state["step"] + 1}
        if self.momentum != 0.0:
            new_buf = {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k] = p
                if self.momentum != 0.0:
                    new_buf[k] = state["momentum_buf"][k]
                continue
            if self.weight_decay:
                g = g + self.weight_decay * p
            if self.momentum != 0.0:
                buf = self.momentum * state["momentum_buf"][k] + g
                new_buf[k] = buf
                g = buf
            new_params[k] = p - self.lr * g
        if self.momentum != 0.0:
            new_state["momentum_buf"] = new_buf
        return new_params, new_state


class AdamW:
    """Decoupled weight decay Adam; state in f32 regardless of param dtype."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.01):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params: dict) -> dict:
        return {
            "step": jnp.zeros((), jnp.int32),
            "m": {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()},
            "v": {k: jnp.zeros(v.shape, jnp.float32) for k, v in params.items()},
        }

    def update(self, params: dict, grads: dict, state: dict):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        bc1 = 1.0 - self.beta1**t
        bc2 = 1.0 - self.beta2**t
        new_params, new_m, new_v = {}, {}, {}
        for k, p in params.items():
            g = grads.get(k)
            if g is None:
                new_params[k], new_m[k], new_v[k] = p, state["m"][k], state["v"][k]
                continue
            g32 = g.astype(jnp.float32)
            m = self.beta1 * state["m"][k] + (1.0 - self.beta1) * g32
            v = self.beta2 * state["v"][k] + (1.0 - self.beta2) * (g32 * g32)
            mhat = m / bc1
            vhat = v / bc2
            upd = mhat / (jnp.sqrt(vhat) + self.eps)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                p32 = p32 - self.lr * self.weight_decay * p32
            p32 = p32 - self.lr * upd
            new_params[k] = p32.astype(p.dtype)
            new_m[k], new_v[k] = m, v
        return new_params, {"step": step, "m": new_m, "v": new_v}


class Adam(AdamW):
    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8):
        super().__init__(lr, betas, eps, weight_decay=0.0)
