"""xlaex: the XLA fusion executor — the TPU analog of nvFuser.

Reference counterpart: thunder/executors/nvfuserex_impl.py:301-836 (region
claiming + FusionDefinition translation + compilation cache). Here a claimed
region's subtrace is compiled once with ``jax.jit`` — XLA does the actual
kernel fusion, MXU tiling and latency hiding; the executor's job is region
formation and caching. On a typical trace the whole computation collapses
into one fusion, which is exactly the right shape for TPU (whole-program
XLA compilation; no CUDA-graph analog needed)."""
from __future__ import annotations

import time
from typing import Sequence

import jax

from ..core.prims import PrimIDs
from ..core.proxies import Proxy, TensorProxy, variableify
from ..core.symbol import BoundSymbol, OpTags, Symbol
from ..core.trace import TraceCtx, from_trace
from ..extend import FusionExecutor, register_executor
from ..observability import events as _obs
from ..observability import runtime as _obs_runtime

_STRUCTURAL = (PrimIDs.RETURN, PrimIDs.DEL, PrimIDs.COMMENT, PrimIDs.UNPACK_TRIVIAL)
_NOFUSE_IDS = (PrimIDs.ITEM, PrimIDs.PRINT, PrimIDs.DEVICE_PUT,
               PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE)


class XLAFusionExecutor(FusionExecutor):
    def __init__(self):
        super().__init__("xla")
        self._fusion_counter = 0
        self.fusion_cache: dict = {}

    def _fusible(self, bsym: BoundSymbol) -> bool:
        if bsym.sym.id in _STRUCTURAL or bsym.sym.id in _NOFUSE_IDS:
            return False
        if OpTags.DONT_FUSE in bsym.sym.tags or OpTags.DONT_FUSE in bsym.tags:
            return False
        if OpTags.DEVICE_SYNC_OP in bsym.sym.tags:
            return False
        return bsym.impl is not None or bsym.sym.python_impl is not None

    def fusion_pass(self, trace: TraceCtx) -> TraceCtx:
        start = time.perf_counter()
        bsyms = trace.bound_symbols

        # consumed-after map: for each position, proxies read at or after it
        consumed_after: list[set] = [set() for _ in range(len(bsyms) + 1)]
        acc: set = set()
        for i in range(len(bsyms) - 1, -1, -1):
            acc = acc | {variableify(p) for p in bsyms[i].flat_proxy_args()}
            consumed_after[i] = acc

        new_bsyms: list[BoundSymbol] = []
        region: list[BoundSymbol] = []

        def flush(next_idx: int):
            nonlocal region
            if not region:
                return
            if len(region) == 1 and not _worth_fusing_alone(region[0]):
                new_bsyms.extend(region)
                region = []
                return
            new_bsyms.append(self._make_fusion(region, consumed_after[next_idx], trace))
            region = []

        for i, bsym in enumerate(bsyms):
            if self._fusible(bsym):
                region.append(bsym)
            else:
                flush(i)
                new_bsyms.append(bsym)
        flush(len(bsyms))

        out = from_trace(trace)
        out.bound_symbols = new_bsyms
        out.set_provenance(f"XLA fusion pass (took {(time.perf_counter()-start)*1000:.2f} ms)")
        return out

    def _make_fusion(self, region: Sequence[BoundSymbol], consumed_later: set, trace: TraceCtx) -> BoundSymbol:
        produced: dict = {}
        inputs: list[Proxy] = []
        seen_in: set = set()
        for bsym in region:
            for p in bsym.flat_proxy_args():
                v = variableify(p)
                if v not in produced and v not in seen_in:
                    seen_in.add(v)
                    inputs.append(p)
            for p in bsym.flat_proxy_outs():
                produced[variableify(p)] = p

        outputs = [p for v, p in produced.items() if v in consumed_later]

        subtrace = TraceCtx(None)
        subtrace.args = tuple(inputs)
        subtrace.names = set(trace.names)
        subtrace.bound_symbols = list(region)
        from ..core import prims as _p

        subtrace.bound_symbols.append(_p.python_return.bind(tuple(outputs), output=None))
        self._fusion_counter += 1
        name = f"xla_fusion_{self._fusion_counter - 1}"
        subtrace._name = name

        raw_fn = subtrace.python_callable()

        def scoped_fn(*args):
            # the HLO traced under this scope carries the fusion name, so
            # device profiles (xprof) map rows back to trace symbols
            with _obs_runtime.fusion_scope(name):
                return raw_fn(*args)

        # the jitted module is named after the wrapped callable
        # ("jit_xla_fusion_N"): device trace events carry it in
        # args.hlo_module, which is the profiler's primary join back to
        # this region — it works even on backends (CPU) whose per-op
        # events drop the named_scope metadata
        scoped_fn.__name__ = name
        jfn = jax.jit(scoped_fn)

        fusion_sym = Symbol(name, None, id=f"xla.{name}", is_prim=True, executor=self, module="xla")

        first_call = [True]

        def impl(*args):
            # compile_service/parallel_compile.py installs an AOT-compiled
            # (or store-deserialized) executable here: dispatch uses it
            # directly — no lazy jit compile — and ANY mismatch (tracer
            # args under an ambient trace, aval/ABI drift) falls back to
            # the jfn path permanently; prewarming must never change
            # semantics, only when the compile happened.
            pw = impl._prewarmed
            if pw is not None:
                try:
                    # annotate like the steady-state jfn path: a STORE-served
                    # executable carries the PUBLISHING process's HLO module
                    # name, so this runtime annotation (and the named_scope
                    # inside the program) is what keeps device-time
                    # attribution joined to this process's region registry
                    if _obs._BUS.enabled:
                        with _obs_runtime.annotate_call(name):
                            return pw(*args)
                    return pw(*args)
                except Exception as e:
                    # the fallback is semantics-preserving but NOT free (a
                    # hidden lazy recompile follows) — record it so a fleet
                    # whose prewarmed regions silently disengage is
                    # distinguishable from one that never prewarmed
                    impl._prewarmed = None
                    if _obs._BUS.enabled:
                        _obs.inc("compile.prewarm_fallback")
                        _obs.event("prewarm_fallback", fusion=name,
                                   error=type(e).__name__)
            if first_call[0]:
                # jax.jit compiles lazily: the first dispatch pays jax
                # trace + StableHLO lowering + XLA backend compile
                first_call[0] = False
                with _obs.span("xla_compile", fusion=name, n_ops=len(region)):
                    return jfn(*args)
            if _obs._BUS.enabled:
                with _obs_runtime.annotate_call(name):
                    return jfn(*args)
            return jfn(*args)

        impl.__name__ = name
        impl.jitted = jfn
        impl.subtrace = subtrace
        impl._prewarmed = None
        bsym = BoundSymbol(fusion_sym, tuple(inputs), {}, tuple(outputs), subsymbols=tuple(region), impl=impl)
        return bsym


def _worth_fusing_alone(bsym: BoundSymbol) -> bool:
    # singleton regions still get jitted when they are matmul-class (MXU) ops;
    # trivial singletons stay op-by-op to avoid pointless dispatch
    return OpTags.MATMUL_OP in bsym.sym.tags


ex = XLAFusionExecutor()
register_executor(ex)
