"""pallasex: hand-written Pallas(Mosaic) TPU kernels for the hot ops.

The TPU analog of the reference's kernel executors — sdpaex/cudnnex/fa3ex
flash attention (thunder/executors/sdpaex.py:1, cudnn_sdpa.py:1, fa3ex.py:1),
apex/triton fused cross-entropy (apex_entropyex_impl.py:1,
triton_crossentropy_impl.py:1) and fused RMSNorm
(apex_fused_rms_norm_impl.py:1). Kernels follow the Pallas TPU playbook:
(8,128)+ tiles, f32 accumulation in VMEM scratch, online softmax for flash
attention.

The executor claims the composite ltorch symbols whole (`sdpa`,
`cross_entropy`, `rms_norm`) via checkers; autodiff uses the executor-claimed
grad path (flash fwd saves (o, lse); flash bwd recomputes blockwise) — the
reference's executor-claimed-grads mechanism (thunder/transforms/autodiff.py:28-40)."""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas namespace; absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from ..core import dtypes
from ..core.proxies import TensorProxy
from ..core.symbol import OpTags, Symbol
from ..extend import OperatorExecutor, register_executor

ex = OperatorExecutor("pallas")
register_executor(ex)

# swept on v5e (llama-350m, B=4, T=2048, D=64, fwd+bwd step): 512/1024 gave
# 39.4% MFU vs 23.8% at 128/128 — large q blocks amortize the k/v loop,
# k-major blocks keep the MXU fed during the online-softmax accumulation
DEFAULT_BLOCK_Q = int(os.environ.get("TT_FLASH_BLOCK_Q", "512"))
DEFAULT_BLOCK_K = int(os.environ.get("TT_FLASH_BLOCK_K", "1024"))
# k-block cap for the GQA streaming dkv backward (swept separately: its
# working set scales with block_k x block_q tiles plus the group's q/do)
_GQA_BLOCK_K = int(os.environ.get("TT_FLASH_GQA_BLOCK_K", "512"))
# single-pass fused backward blocks (swept on v5e across llama-350m/llama-1b/
# nanogpt shapes: 512/512 wins everywhere — 4.11/2.75/2.80 ms fwd+bwd vs
# 4.52/3.24/3.42 two-pass; 1024-row q blocks blow the 16 MB VMEM limit)
_FUSED_BLOCK_Q = int(os.environ.get("TT_FLASH_FUSED_BLOCK_Q", "512"))
_FUSED_BLOCK_K = int(os.environ.get("TT_FLASH_FUSED_BLOCK_K", "512"))


def _cap_blocks_for_dtype(q, block_q: int, block_k: int, T: int, Tk: int, *extra):
    """Block sizes are swept for bf16; 4-byte operands (f32 paths: a
    no-autocast train step, or mixed-precision rewrites that leave SOME of
    q/k/v/do f32) double the VMEM working set and blow the 16M scoped limit —
    cap both blocks at 256 there (gcd keeps divisibility). The decision
    lives in the unified budget API (analysis/memory.py flash_block_cap)."""
    from ..analysis import budget as _budget

    widest = max(jnp.dtype(t.dtype).itemsize for t in (q,) + tuple(extra))
    return _budget.flash_block_cap(widest, block_q, block_k, T, Tk)
NEG_INF = -1e30
LOG2E = 1.4426950408889634  # 1/ln 2: base-2 softmax folds this into the scale
LN2 = 0.6931471805599453


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def _interpret() -> bool:
    return not _on_tpu()


# ===========================================================================
# Flash attention — forward
# ===========================================================================


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool,
                      scale: float, q_offset_blocks: int):
    # q_ref: (block_q, D); k_ref/v_ref: (T, D); o_ref: (block_q, D); lse_ref: (block_q, 1)
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    qi = pl.program_id(2)

    # inputs stay low-precision so the dots ride the MXU's native bf16 path
    # (fp32 operands run the MXU at a fraction of peak); accumulation is
    # always f32 via preferred_element_type, scores/softmax stay f32
    q = q_ref[:]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    # base-2 softmax: fold log2(e) into the dot scale so the per-element
    # softmax uses the VPU's native exp2 with no premultiply pass — the
    # running max/sum track log2 units; lse converts back to natural log once
    scale2 = scale * LOG2E

    def body(j, carry):
        o_acc, m, l = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale2  # (bq, bk)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        o_new = o_acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_k = T // block_k
    if causal:
        # skip fully-masked k blocks: only blocks intersecting the causal
        # triangle ([0, (qi+1)*block_q)) contribute
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_k, body, (o0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = ((m + jnp.log2(l_safe)) * LN2)[:, None]


def flash_attention_forward(q, k, v, *, causal: bool = True, scale=None,
                            block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K):
    """q,k,v: (B, H, T, D) -> (o, lse). Head dims below the 128-lane tile
    (64 for llama-class models) are handled by Mosaic's implicit minor-dim
    padding in VMEM — no HBM-level zero-pad copies or doubled k/v traffic."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    B, H, T, D = q.shape
    Tk = k.shape[2]
    Hkv = k.shape[1]
    g = H // Hkv  # GQA group: kv head = q head // g (1 for MHA)
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, Tk, k, v)
    grid = (B, H, T // block_q)

    o, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k, causal=causal, scale=scale,
                          q_offset_blocks=0),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i: (b, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[..., 0]


# ===========================================================================
# Flash attention — backward (recompute blockwise; dq kernel + dkv kernel)
# ===========================================================================


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                         block_k: int, causal: bool, scale: float):
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:]
    do = do_ref[:]
    lse2 = lse_ref[:][:, 0] * LOG2E  # natural-log lse -> log2 units
    delta = delta_ref[:][:, 0]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq_acc):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (scale * LOG2E)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq_acc + jax.lax.dot_general(ds.astype(k_blk.dtype), k_blk,
                                            (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)

    n_k = T // block_k
    if causal:
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_tile(k_blk, v_blk, q, do, lse2, delta, k_pos_t, q_pos_t, causal,
              scale, dk_acc, dv_acc):
    """One (k-block x q-tile) contribution to dk/dv, transposed orientation
    (rows = k positions) in log2 units — the single source of truth for all
    four dkv kernels (MHA/GQA x plain/rope)."""
    s_t = jax.lax.dot_general(k_blk, q, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32) * (scale * LOG2E)  # (bk, bq)
    if causal:
        s_t = jnp.where(k_pos_t <= q_pos_t, s_t, NEG_INF)
    p_t = jnp.exp2(s_t - lse2[None, :])
    dv_acc = dv_acc + jax.lax.dot_general(p_t.astype(do.dtype), do,
                                          (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
    dp_t = jax.lax.dot_general(v_blk, do, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)  # (bk, bq)
    ds_t = (p_t * (dp_t - delta[None, :]) * scale).astype(q.dtype)
    dk_acc = dk_acc + jax.lax.dot_general(ds_t, q, (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
    return dk_acc, dv_acc


def _flash_bwd_dkv_kernel_mha(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                          block_q: int, causal: bool, scale: float):
    block_k, D = k_ref.shape
    T = q_ref.shape[0]
    ki = pl.program_id(2)
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    # work in the TRANSPOSED orientation (rows = k positions): every dot then
    # contracts lhs dim 1 against rhs dim 0/1 naturally — the straight
    # orientation needs pᵀ/dsᵀ for dv/dk, and those in-kernel transposes of
    # (block_q, block_k) tiles cost more than the matmuls themselves
    k_pos_t = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)

    def body(i, carry):
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse2 = lse_ref[pl.ds(i * block_q, block_q), :][:, 0] * LOG2E
        delta = delta_ref[pl.ds(i * block_q, block_q), :][:, 0]
        q_pos_t = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        return _dkv_tile(k_blk, v_blk, q, do, lse2, delta, k_pos_t, q_pos_t,
                         causal, scale, *carry)

    z = jnp.zeros((block_k, D), jnp.float32)
    i0 = (ki * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(i0, T // block_q, body, (z, z))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)




def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                          dk_scr, dv_scr, *, causal: bool, scale: float, g: int, n_i: int):
    # GQA-aware, VMEM-bounded: grid (B, Hkv, T//block_k, T//block_q) streams
    # q/do in (g, block_q, D) tiles (innermost-fastest on the TPU's
    # sequential grid); dk/dv accumulate in VMEM scratch across the i axis
    # and write ONCE at the last i — kv-grad HBM stays (B, Hkv, T, D), not
    # g× (advisor r3 finding), with working set independent of T and g.
    block_k, D = k_ref.shape
    block_q = q_ref.shape[1]
    ki = pl.program_id(2)
    ii = pl.program_id(3)

    @pl.when(ii == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # causal skip: the (j, i) tile contributes only when some q_pos >= k_pos
    live = (ki * block_k <= (ii + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = k_ref[:]
        v_blk = v_ref[:]
        # work in the TRANSPOSED orientation (rows = k positions): every dot
        # then contracts lhs dim 1 against rhs dim 0/1 naturally — the
        # straight orientation needs pᵀ/dsᵀ for dv/dk, and those in-kernel
        # transposes of (block_q, block_k) tiles cost more than the matmuls
        k_pos_t = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
        q_pos_t = ii * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        dk_acc = dk_scr[:]
        dv_acc = dv_scr[:]
        for h in range(g):  # static unroll over the q-head group
            dk_acc, dv_acc = _dkv_tile(
                k_blk, v_blk, q_ref[h], do_ref[h], lse_ref[h][:, 0] * LOG2E,
                delta_ref[h][:, 0], k_pos_t, q_pos_t, causal, scale,
                dk_acc, dv_acc)
        dk_scr[:] = dk_acc
        dv_scr[:] = dv_acc

    @pl.when(ii == n_i - 1)
    def _write():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _fused_bwd_tile(q, do, lse2, delta, k_blk, v_blk, sl, k_pos_t, q_pos_t,
                    causal, scale, dk_scr, dv_scr, dq_acc):
    """One (i, j) tile of the single-pass backward, shared by the plain and
    rope fused kernels (the _dkv_tile role for the fused design): computes
    s/p ONCE, accumulates dk/dv into the VMEM scratch slice and returns the
    updated dq accumulator. Transposed orientation (rows = k positions)."""
    s_t = jax.lax.dot_general(k_blk, q, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32) * (scale * LOG2E)
    if causal:
        s_t = jnp.where(k_pos_t <= q_pos_t, s_t, NEG_INF)
    p_t = jnp.exp2(s_t - lse2[None, :])
    dv_c = jax.lax.dot_general(p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dp_t = jax.lax.dot_general(v_blk, do, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    ds_t = (p_t * (dp_t - delta[None, :]) * scale).astype(q.dtype)
    dk_c = jax.lax.dot_general(ds_t, q, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    dk_scr[sl, :] += dk_c
    dv_scr[sl, :] += dv_c
    return dq_acc + jax.lax.dot_general(ds_t, k_blk, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                            dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                            block_k: int, causal: bool, scale: float,
                            g: int, n_i: int):
    """Single-pass backward (PROFILE_350M.md lever 2): grid (B, Hkv, T//block_q)
    with k/v full-T resident; each program computes s/p ONCE per (i, j) tile
    and emits BOTH its dq tile (written per program) and the dk/dv
    contributions (f32 VMEM scratch accumulated across the i axis, written at
    the last i) — vs the two-pass design this halves the backward exp and
    QK^T work (5 dots + 1 exp per tile instead of 7 + 2)."""
    Tk, D = k_ref.shape
    block_q = q_ref.shape[1]
    ii = pl.program_id(2)

    @pl.when(ii == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    n_j = Tk // block_k
    if causal:
        n_j = jnp.minimum(n_j, ((ii + 1) * block_q + block_k - 1) // block_k)

    for h in range(g):  # static unroll over the q-head group (1 for MHA)
        q = q_ref[h]
        do = do_ref[h]
        lse2 = lse_ref[h][:, 0] * LOG2E
        delta = delta_ref[h][:, 0]
        q_pos_t = ii * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)

        def body(j, dq_acc):
            sl = pl.ds(j * block_k, block_k)
            k_pos_t = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
            return _fused_bwd_tile(q, do, lse2, delta, k_ref[sl, :], v_ref[sl, :],
                                   sl, k_pos_t, q_pos_t, causal, scale,
                                   dk_scr, dv_scr, dq_acc)

        dq = jax.lax.fori_loop(0, n_j, body, jnp.zeros((block_q, D), jnp.float32))
        dq_ref[h] = dq.astype(dq_ref.dtype)

    @pl.when(ii == n_i - 1)
    def _write():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _fused_bwd_enabled() -> bool:
    return pltpu is not None and os.environ.get("TT_FLASH_TWO_PASS_BWD", "0") != "1"


def _flash_backward_fused(q, k, v, do, lse4, delta4, *, causal, scale,
                          block_q, block_k):
    B, H, T, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    g = H // Hkv
    block_q = math.gcd(min(block_q, _FUSED_BLOCK_Q), T)
    block_k = math.gcd(min(block_k, _FUSED_BLOCK_K), Tk)
    qg = q.reshape(B, Hkv, g, T, D)
    dog = do.reshape(B, Hkv, g, T, D)
    lseg = lse4.reshape(B, Hkv, g, T, 1)
    deltag = delta4.reshape(B, Hkv, g, T, 1)
    n_i = T // block_q
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, block_k=block_k,
                          causal=causal, scale=scale, g=g, n_i=n_i),
        grid=(B, Hkv, n_i),
        in_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, i: (b, hk, 0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, hk, i: (b, hk, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, g, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Tk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((Tk, D), jnp.float32),
                        pltpu.VMEM((Tk, D), jnp.float32)],
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag)
    return dq.reshape(B, H, T, D), dk, dv


def flash_attention_backward(q, k, v, o, lse, do, *, causal: bool = True, scale=None,
                             block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K):
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if jnp.dtype(do.dtype).itemsize > jnp.dtype(q.dtype).itemsize:
        # fp8/mixed rewrites can hand a f32 cotangent to a bf16 attention:
        # matching q's precision keeps the swept bf16 block sizes (delta is
        # accumulated in f32 regardless)
        do = do.astype(q.dtype)
    B, H, T, D = q.shape
    Tk = k.shape[2]
    Hkv = k.shape[1]
    g = H // Hkv  # GQA: dk/dv computed per q head, group-summed below
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, Tk, k, v, do)
    if g > 1:
        # grouped-kv vmem guard for the streaming dkv grid; gcd keeps
        # divisibility under overrides (a non-divisor block would silently
        # truncate the dkv grid). TT_FLASH_GQA_BLOCK_K tunes it.
        block_k = math.gcd(min(block_k, _GQA_BLOCK_K), Tk)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # (B,H,T)
    lse4 = lse[..., None]
    delta4 = delta[..., None]

    if _fused_bwd_enabled():
        return _flash_backward_fused(q, k, v, do, lse4, delta4, causal=causal,
                                     scale=scale, block_q=block_q, block_k=block_k)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale),
        grid=(B, H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta4)

    if g == 1 or pltpu is None:
        # MHA fast path: full-T q/do resident per program (measured faster
        # than the streaming grid at llama-350m shapes). Also the GQA route
        # when the TPU pallas namespace is unavailable (no VMEM scratch for
        # the streaming kernel): per-q-head dk/dv, group-summed below.
        dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_dkv_kernel_mha, block_q=block_q, causal=causal, scale=scale),
            grid=(B, H, Tk // block_k),
            in_specs=[
                pl.BlockSpec((None, None, T, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, T, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Tk, D), k.dtype),
                jax.ShapeDtypeStruct((B, H, Tk, D), v.dtype),
            ],
            interpret=_interpret(),
        )(q, k, v, do, lse4, delta4)
        if g > 1:
            dk = dk.reshape(B, Hkv, g, Tk, D).sum(2).astype(k.dtype)
            dv = dv.reshape(B, Hkv, g, Tk, D).sum(2).astype(v.dtype)
        return dq, dk, dv

    # GQA: q heads grouped per kv head — view q/do/lse/delta as (B, Hkv, g, T, ...)
    qg = q.reshape(B, Hkv, g, T, D)
    dog = do.reshape(B, Hkv, g, T, D)
    lseg = lse4.reshape(B, Hkv, g, T, 1)
    deltag = delta4.reshape(B, Hkv, g, T, 1)
    n_i = T // block_q
    scratch = []
    if pltpu is not None:
        scratch = [pltpu.VMEM((block_k, D), jnp.float32),
                   pltpu.VMEM((block_k, D), jnp.float32)]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale, g=g, n_i=n_i),
        grid=(B, Hkv, Tk // block_k, n_i),
        in_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, j, i: (b, hk, 0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, Tk, D), v.dtype),
        ],
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag)
    return dq, dk, dv


# ===========================================================================
# Fused RoPE + flash attention (rope applied in-kernel; pre-rope q/k are the
# saved-for-backward residuals and the rope VJP rotation happens in-kernel
# on the dq/dk accumulators — the separate rope slice/negate/cat fusions and
# their backward passes disappear from the XLA timeline)
# ===========================================================================


def _rot_matrix(D: int, dtype):
    """rotate_half as a constant matmul: rotate(x) = x @ R with
    R[i, j] = -1 at i == j + D/2, +1 at i == j - D/2. Lane-slicing halves of
    a bf16 tile in-kernel lowers to catastrophic VREG shuffles on Mosaic;
    one (N, D) @ (D, D) dot is MXU-trivial instead."""
    h = D // 2
    ii = jax.lax.broadcasted_iota(jnp.int32, (D, D), 0)
    jj = jax.lax.broadcasted_iota(jnp.int32, (D, D), 1)
    r = jnp.where(ii == jj + h, -1.0, 0.0) + jnp.where(ii + h == jj, 1.0, 0.0)
    return r.astype(dtype)


def _rope_block(x, c, s):
    """x (N, D) f32 -> rope'd (N, D); cos/sin (N, D) duplicated-half caches."""
    rot = jax.lax.dot_general(x, _rot_matrix(x.shape[-1], x.dtype),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return x * c + rot * s


def _rope_vjp_block(dxr, c, s):
    """VJP of _rope_block wrt x: dx = dxr*c + (dxr*s) @ R^T."""
    ds = dxr * s
    rot = jax.lax.dot_general(ds, _rot_matrix(dxr.shape[-1], ds.dtype),
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return dxr * c + rot


def _flash_rope_fwd_kernel(q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref,
                           o_ref, lse_ref, *, block_k: int, causal: bool, scale: float):
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    qi = pl.program_id(2)

    q = _rope_block(q_ref[:].astype(jnp.float32), cq_ref[:], sq_ref[:]).astype(q_ref.dtype)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        o_acc, m, l = carry
        k_blk = _rope_block(k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32),
                            ck_ref[pl.ds(j * block_k, block_k), :],
                            sk_ref[pl.ds(j * block_k, block_k), :]).astype(k_ref.dtype)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        ss = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * (scale * LOG2E)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            ss = jnp.where(k_pos <= q_pos, ss, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(ss, axis=1))
        pp = jnp.exp2(ss - m_new[:, None])
        corr = jnp.exp2(m - m_new)
        l_new = l * corr + jnp.sum(pp, axis=1)
        o_new = o_acc * corr[:, None] + jax.lax.dot_general(
            pp.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_k = T // block_k
    if causal:
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, n_k, body, (o0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = ((m + jnp.log2(l_safe)) * LN2)[:, None]


def flash_rope_attention_forward(q, k, v, cos, sin, *, causal: bool = True, scale=None,
                                 block_q: int = DEFAULT_BLOCK_Q,
                                 block_k: int = DEFAULT_BLOCK_K):
    """q,k,v PRE-rope (B, H, T, D); cos/sin (T, D) duplicated-half caches."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv  # GQA group (1 for MHA)
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, T, k, v)
    cos = cos.astype(jnp.float32)
    sin = sin.astype(jnp.float32)
    o, lse = pl.pallas_call(
        functools.partial(_flash_rope_fwd_kernel, block_k=block_k, causal=causal, scale=scale),
        grid=(B, H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((block_q, D), lambda b, h, i: (i, 0)),
            pl.BlockSpec((block_q, D), lambda b, h, i: (i, 0)),
            pl.BlockSpec((T, D), lambda b, h, i: (0, 0)),
            pl.BlockSpec((T, D), lambda b, h, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, cos, sin, cos, sin)
    return o, lse[..., 0]


def _flash_rope_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                              cq_ref, sq_ref, ck_ref, sk_ref, dq_ref, *,
                              block_k: int, causal: bool, scale: float):
    block_q, D = q_ref.shape
    T = k_ref.shape[0]
    qi = pl.program_id(2)
    q = _rope_block(q_ref[:].astype(jnp.float32), cq_ref[:], sq_ref[:]).astype(q_ref.dtype)
    do = do_ref[:]
    lse2 = lse_ref[:][:, 0] * LOG2E  # natural-log lse -> log2 units
    delta = delta_ref[:][:, 0]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq_acc):
        k_blk = _rope_block(k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32),
                            ck_ref[pl.ds(j * block_k, block_k), :],
                            sk_ref[pl.ds(j * block_k, block_k), :]).astype(k_ref.dtype)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        ss = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * (scale * LOG2E)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            ss = jnp.where(k_pos <= q_pos, ss, NEG_INF)
        pp = jnp.exp2(ss - lse2[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = pp * (dp - delta[:, None]) * scale
        return dq_acc + jax.lax.dot_general(ds.astype(k_blk.dtype), k_blk,
                                            (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)

    n_k = T // block_k
    if causal:
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    dq_r = jax.lax.fori_loop(0, n_k, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:] = _rope_vjp_block(dq_r, cq_ref[:], sq_ref[:]).astype(dq_ref.dtype)


def _flash_rope_bwd_dkv_kernel_mha(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                               cq_ref, sq_ref, ck_ref, sk_ref, dk_ref, dv_ref, *,
                               block_q: int, causal: bool, scale: float):
    block_k, D = k_ref.shape
    T = q_ref.shape[0]
    ki = pl.program_id(2)
    k_blk = _rope_block(k_ref[:].astype(jnp.float32), ck_ref[:], sk_ref[:]).astype(k_ref.dtype)
    v_blk = v_ref[:]
    k_pos_t = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)

    def body(i, carry):
        q = _rope_block(q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32),
                        cq_ref[pl.ds(i * block_q, block_q), :],
                        sq_ref[pl.ds(i * block_q, block_q), :]).astype(q_ref.dtype)
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse2 = lse_ref[pl.ds(i * block_q, block_q), :][:, 0] * LOG2E
        delta = delta_ref[pl.ds(i * block_q, block_q), :][:, 0]
        q_pos_t = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        return _dkv_tile(k_blk, v_blk, q, do, lse2, delta, k_pos_t, q_pos_t,
                         causal, scale, *carry)

    z = jnp.zeros((block_k, D), jnp.float32)
    i0 = (ki * block_k) // block_q if causal else 0
    dk_r, dv = jax.lax.fori_loop(i0, T // block_q, body, (z, z))
    dk_ref[:] = _rope_vjp_block(dk_r, ck_ref[:], sk_ref[:]).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)




def _flash_rope_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                               cq_ref, sq_ref, ck_ref, sk_ref, dk_ref, dv_ref,
                               dk_scr, dv_scr, *, causal: bool, scale: float,
                               g: int, n_i: int):
    # GQA-aware, VMEM-bounded (see _flash_bwd_dkv_kernel): 4-D grid streams
    # (g, block_q, D) q/do tiles, scratch accumulates dk/dv across i, the
    # rope VJP rotation applies once at the final write
    block_k, D = k_ref.shape
    block_q = q_ref.shape[1]
    ki = pl.program_id(2)
    ii = pl.program_id(3)

    @pl.when(ii == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (ki * block_k <= (ii + 1) * block_q - 1) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = _rope_block(k_ref[:].astype(jnp.float32), ck_ref[:], sk_ref[:]).astype(k_ref.dtype)
        v_blk = v_ref[:]
        k_pos_t = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
        q_pos_t = ii * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        dk_acc = dk_scr[:]
        dv_acc = dv_scr[:]
        for h in range(g):  # static unroll over the q-head group
            q = _rope_block(q_ref[h].astype(jnp.float32),
                            cq_ref[:], sq_ref[:]).astype(q_ref.dtype)
            dk_acc, dv_acc = _dkv_tile(
                k_blk, v_blk, q, do_ref[h], lse_ref[h][:, 0] * LOG2E,
                delta_ref[h][:, 0], k_pos_t, q_pos_t, causal, scale,
                dk_acc, dv_acc)
        dk_scr[:] = dk_acc
        dv_scr[:] = dv_acc

    @pl.when(ii == n_i - 1)
    def _write():
        dk_ref[:] = _rope_vjp_block(dk_scr[:], ck_ref[:], sk_ref[:]).astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_rope_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                                 cq_ref, sq_ref, ck_ref, sk_ref,
                                 dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                                 block_k: int, causal: bool, scale: float,
                                 g: int, n_i: int):
    """Single-pass rope backward (see _flash_bwd_fused_kernel): rope applied
    in-kernel on q/k loads, rope VJP on the dq carry at write and on the dk
    scratch at the final i."""
    Tk, D = k_ref.shape
    block_q = q_ref.shape[1]
    ii = pl.program_id(2)

    @pl.when(ii == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    n_j = Tk // block_k
    if causal:
        n_j = jnp.minimum(n_j, ((ii + 1) * block_q + block_k - 1) // block_k)

    for h in range(g):  # static unroll over the q-head group (1 for MHA)
        q = _rope_block(q_ref[h].astype(jnp.float32), cq_ref[:], sq_ref[:]).astype(q_ref.dtype)
        do = do_ref[h]
        lse2 = lse_ref[h][:, 0] * LOG2E
        delta = delta_ref[h][:, 0]
        q_pos_t = ii * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)

        def body(j, dq_acc):
            sl = pl.ds(j * block_k, block_k)
            k_blk = _rope_block(k_ref[sl, :].astype(jnp.float32),
                                ck_ref[sl, :], sk_ref[sl, :]).astype(k_ref.dtype)
            k_pos_t = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
            return _fused_bwd_tile(q, do, lse2, delta, k_blk, v_ref[sl, :],
                                   sl, k_pos_t, q_pos_t, causal, scale,
                                   dk_scr, dv_scr, dq_acc)

        dq = jax.lax.fori_loop(0, n_j, body, jnp.zeros((block_q, D), jnp.float32))
        dq_ref[h] = _rope_vjp_block(dq, cq_ref[:], sq_ref[:]).astype(dq_ref.dtype)

    @pl.when(ii == n_i - 1)
    def _write():
        dk_ref[:] = _rope_vjp_block(dk_scr[:], ck_ref[:], sk_ref[:]).astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_rope_backward_fused(q, k, v, do, lse4, delta4, cos, sin, *, causal,
                               scale, block_q, block_k):
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv
    block_q = math.gcd(min(block_q, _FUSED_BLOCK_Q), T)
    block_k = math.gcd(min(block_k, _FUSED_BLOCK_K), T)
    qg = q.reshape(B, Hkv, g, T, D)
    dog = do.reshape(B, Hkv, g, T, D)
    lseg = lse4.reshape(B, Hkv, g, T, 1)
    deltag = delta4.reshape(B, Hkv, g, T, 1)
    n_i = T // block_q
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_rope_bwd_fused_kernel, block_k=block_k,
                          causal=causal, scale=scale, g=g, n_i=n_i),
        grid=(B, Hkv, n_i),
        in_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((block_q, D), lambda b, hk, i: (i, 0)),
            pl.BlockSpec((block_q, D), lambda b, hk, i: (i, 0)),
            pl.BlockSpec((T, D), lambda b, hk, i: (0, 0)),
            pl.BlockSpec((T, D), lambda b, hk, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, hk, i: (b, hk, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, hk, i: (b, hk, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, g, T, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hkv, T, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, T, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((T, D), jnp.float32),
                        pltpu.VMEM((T, D), jnp.float32)],
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag, cos, sin, cos, sin)
    return dq.reshape(B, H, T, D), dk, dv


def flash_rope_attention_backward(q, k, v, o, lse, cos, sin, do, *, causal: bool = True,
                                  scale=None, block_q: int = DEFAULT_BLOCK_Q,
                                  block_k: int = DEFAULT_BLOCK_K):
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if jnp.dtype(do.dtype).itemsize > jnp.dtype(q.dtype).itemsize:
        # fp8/mixed rewrites can hand a f32 cotangent to a bf16 attention:
        # matching q's precision keeps the swept bf16 block sizes (delta is
        # accumulated in f32 regardless)
        do = do.astype(q.dtype)
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv  # GQA: dk/dv per-q-head partials group-summed at the end
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, T, k, v, do)
    if g > 1:
        # grouped-kv vmem guard (see flash_attention_backward)
        block_k = math.gcd(min(block_k, _GQA_BLOCK_K), T)
    cos = cos.astype(jnp.float32)
    sin = sin.astype(jnp.float32)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse4 = lse[..., None]
    delta4 = delta[..., None]

    if _fused_bwd_enabled():
        return _flash_rope_backward_fused(q, k, v, do, lse4, delta4, cos, sin,
                                          causal=causal, scale=scale,
                                          block_q=block_q, block_k=block_k)

    dq = pl.pallas_call(
        functools.partial(_flash_rope_bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale),
        grid=(B, H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, T, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((block_q, D), lambda b, h, i: (i, 0)),
            pl.BlockSpec((block_q, D), lambda b, h, i: (i, 0)),
            pl.BlockSpec((T, D), lambda b, h, i: (0, 0)),
            pl.BlockSpec((T, D), lambda b, h, i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta4, cos, sin, cos, sin)

    if g == 1 or pltpu is None:
        # MHA fast path (see flash_attention_backward); doubles as the GQA
        # no-pltpu fallback — per-q-head dk/dv, group-summed below
        dk, dv = pl.pallas_call(
            functools.partial(_flash_rope_bwd_dkv_kernel_mha, block_q=block_q, causal=causal, scale=scale),
            grid=(B, H, T // block_k),
            in_specs=[
                pl.BlockSpec((None, None, T, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, T, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((T, D), lambda b, h, j: (0, 0)),
                pl.BlockSpec((T, D), lambda b, h, j: (0, 0)),
                pl.BlockSpec((block_k, D), lambda b, h, j: (j, 0)),
                pl.BlockSpec((block_k, D), lambda b, h, j: (j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j: (b, h, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, T, D), k.dtype),
                jax.ShapeDtypeStruct((B, H, T, D), v.dtype),
            ],
            interpret=_interpret(),
        )(q, k, v, do, lse4, delta4, cos, sin, cos, sin)
        if g > 1:
            dk = dk.reshape(B, Hkv, g, T, D).sum(2).astype(k.dtype)
            dv = dv.reshape(B, Hkv, g, T, D).sum(2).astype(v.dtype)
        return dq, dk, dv

    qg = q.reshape(B, Hkv, g, T, D)
    dog = do.reshape(B, Hkv, g, T, D)
    lseg = lse4.reshape(B, Hkv, g, T, 1)
    deltag = delta4.reshape(B, Hkv, g, T, 1)
    n_i = T // block_q
    scratch = []
    if pltpu is not None:
        scratch = [pltpu.VMEM((block_k, D), jnp.float32),
                   pltpu.VMEM((block_k, D), jnp.float32)]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_rope_bwd_dkv_kernel, causal=causal,
                          scale=scale, g=g, n_i=n_i),
        grid=(B, Hkv, T // block_k, n_i),
        in_specs=[
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, g, block_q, D), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((None, None, g, block_q, 1), lambda b, hk, j, i: (b, hk, 0, i, 0)),
            pl.BlockSpec((block_q, D), lambda b, hk, j, i: (i, 0)),
            pl.BlockSpec((block_q, D), lambda b, hk, j, i: (i, 0)),
            pl.BlockSpec((block_k, D), lambda b, hk, j, i: (j, 0)),
            pl.BlockSpec((block_k, D), lambda b, hk, j, i: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
            pl.BlockSpec((None, None, block_k, D), lambda b, hk, j, i: (b, hk, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, T, D), k.dtype),
            jax.ShapeDtypeStruct((B, Hkv, T, D), v.dtype),
        ],
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(qg, k, v, dog, lseg, deltag, cos, sin, cos, sin)
    return dq, dk, dv


def rope_sdpa_supported(q, k, v, cos, sin, is_causal=True, scale=None) -> bool:
    """Claim fused rope+attention when the plain flash checker would claim
    the sdpa AND rope covers the full (even) head dim."""
    if getattr(q, "ndim", 0) != 4:
        return False
    D = q.shape[-1]
    T = q.shape[-2]
    return (
        flash_attention_supported(q, k, v, None, 0.0, is_causal, scale)
        and D % 2 == 0
        and getattr(cos, "shape", None) == (T, D)
        and getattr(sin, "shape", None) == (T, D)
    )


def _rope_sdpa_impl(q, k, v, cos, sin, is_causal=True, scale=None):
    o, _ = flash_rope_attention_forward(q, k, v, cos, sin, causal=is_causal, scale=scale)
    return o


def _jit_claimed(impl, static_argnames, normalize):
    """Shared jit wrapper for claimed ops dispatched standalone (outside a
    fusion region they would otherwise re-lower the pallas_call on every
    invocation). `normalize` maps the call args to hashable statics; any
    tracer-in-static slips through to the unjitted impl."""
    jitted = jax.jit(impl, static_argnames=static_argnames)

    def claimed(*args, **kwargs):
        try:
            a, kw = normalize(*args, **kwargs)
            return jitted(*a, **kw)
        except (TypeError, jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            return impl(*args, **kwargs)

    return claimed


_rope_sdpa_claimed = _jit_claimed(
    _rope_sdpa_impl, ("is_causal", "scale"),
    lambda q, k, v, cos, sin, is_causal=True, scale=None: (
        (q, k, v, cos, sin),
        {"is_causal": bool(is_causal), "scale": None if scale is None else float(scale)}))


def _register_rope_sdpa():
    from ..ops.ltorch import rope_sdpa as _rope_sdpa_sym

    ex.register_implementation(_rope_sdpa_sym.id, _rope_sdpa_claimed,
                               checker=rope_sdpa_supported)

    fwd_sym = ex.register_operator(
        "rope_flash_fwd",
        meta=lambda q, k, v, cos, sin, causal, scale: (
            TensorProxy(shape=q.shape, dtype=q.dtype, device=q.device),
            TensorProxy(shape=q.shape[:-1], dtype=dtypes.float32, device=q.device),
        ),
        fn=lambda q, k, v, cos, sin, causal, scale: flash_rope_attention_forward(
            q, k, v, cos, sin, causal=causal, scale=scale),
    )
    bwd_sym = ex.register_operator(
        "rope_flash_bwd",
        meta=lambda q, k, v, o, lse, cos, sin, causal, scale, do: (
            TensorProxy(shape=q.shape, dtype=q.dtype, device=q.device),
            TensorProxy(shape=k.shape, dtype=k.dtype, device=k.device),
            TensorProxy(shape=v.shape, dtype=v.dtype, device=v.device),
        ),
        fn=lambda q, k, v, o, lse, cos, sin, causal, scale, do: flash_rope_attention_backward(
            q, k, v, o, lse, cos, sin, do, causal=causal, scale=scale),
    )

    from ..transforms.autodiff import VJPResult, register_augmented_forward, register_backward

    @register_augmented_forward(_rope_sdpa_sym.id)
    def _rope_sdpa_aug(q, k, v, cos, sin, is_causal=True, scale=None):
        if not rope_sdpa_supported(q, k, v, cos, sin, is_causal, scale):
            return NotImplemented  # decompose: composite rope + sdpa rules apply
        o, lse = fwd_sym(q, k, v, cos, sin, bool(is_causal), scale)
        return VJPResult(o, (q, k, v, o, lse, cos, sin, bool(is_causal), scale))

    @register_backward(_rope_sdpa_sym.id)
    def _rope_sdpa_bwd(q, k, v, o, lse, cos, sin, causal, scale, g):
        dq, dk, dv = bwd_sym(q, k, v, o, lse, cos, sin, causal, scale, g)
        return dq, dk, dv, None, None, None, None


def flash_attention_supported(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False) -> bool:
    """Checker: pallas flash attention claims sdpa when shapes fit the tiling."""
    if attn_mask is not None or (dropout_p and dropout_p > 0.0):
        return False
    if getattr(q, "ndim", 0) != 4 or getattr(k, "ndim", 0) != 4 or getattr(v, "ndim", 0) != 4:
        return False
    # Claim whenever the tiling fits and the sequence is long enough to
    # amortize the kernel launch: with bf16 MXU dots and swept block sizes
    # the pallas kernels beat XLA's composite attention from T=1024 up
    # (measured v5e: nanogpt-124m B=8 T=1024 +20% step throughput; the
    # composite additionally OOMs at llama-350m B=4 T=2048 fwd+bwd).
    # TT_FLASH_SDPA overrides: "0" never claims (composite path), "1"
    # claims whenever the tiling fits (benchmark/profiling A/B)
    override = os.environ.get("TT_FLASH_SDPA")
    if override == "0":
        return False
    T = q.shape[-2]
    long_enough = (override == "1") or T >= 1024
    shapes_ok = (
        q.shape[-1] <= 512  # any head dim (Mosaic pads the minor dim in VMEM)
        and long_enough
        and q.shape[-2] % DEFAULT_BLOCK_Q == 0
        and k.shape[-2] % DEFAULT_BLOCK_K == 0
        and q.shape[-2] == k.shape[-2]
        # GQA/MQA: the k/v BlockSpecs index kv head = q head // group, and
        # the dkv backward computes per-q-head partials group-summed outside
        # (shared kv outputs written from grouped programs would race)
        and q.shape[0] == k.shape[0] == v.shape[0]
        and k.shape[1] == v.shape[1]
        and q.shape[1] % k.shape[1] == 0
        and q.shape[-1] == k.shape[-1] == v.shape[-1]
        and k.shape[-2] == v.shape[-2]
    )
    return bool(shapes_ok)


# symbol registration: claims ltorch.sdpa whole ------------------------------


def _sdpa_flash_impl(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False):
    o, _ = flash_attention_forward(q, k, v, causal=is_causal, scale=scale)
    return o


_sdpa_claimed = _jit_claimed(
    _sdpa_flash_impl, ("dropout_p", "is_causal", "scale"),
    lambda q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None,
    enable_gqa=False: (
        (q, k, v, attn_mask, float(dropout_p), bool(is_causal),
         None if scale is None else float(scale)), {}))


ex.register_implementation(
    "torch.nn.functional.scaled_dot_product_attention",
    _sdpa_claimed,
    checker=flash_attention_supported,
)


def _register_sdpa_grad_rule():
    """Executor-claimed grad: flash fwd saves (o, lse, q, k, v); flash bwd
    recomputes probabilities blockwise. Falls through to the composite
    decomposition when the kernel can't claim the shapes."""
    from ..transforms.autodiff import VJPResult, register_augmented_forward, register_backward

    def fwd_meta(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False):
        o = TensorProxy(shape=q.shape, dtype=q.dtype, device=q.device)
        lse = TensorProxy(shape=q.shape[:-1], dtype=dtypes.float32, device=q.device)
        return o, lse

    def fwd_impl(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False):
        return flash_attention_forward(q, k, v, causal=is_causal, scale=scale)

    flash_fwd_sym = Symbol("flash_attention_fwd", fwd_meta, id="pallas.flash_attention_fwd",
                           is_prim=True, module="pallas", executor=ex)
    ex.opmap[flash_fwd_sym.id] = fwd_impl

    def bwd_meta(q, k, v, o, lse, causal, scale, do):
        return (TensorProxy(shape=q.shape, dtype=q.dtype, device=q.device),
                TensorProxy(shape=k.shape, dtype=k.dtype, device=k.device),
                TensorProxy(shape=v.shape, dtype=v.dtype, device=v.device))

    def bwd_impl(q, k, v, o, lse, causal, scale, do):
        return flash_attention_backward(q, k, v, o, lse, do, causal=causal, scale=scale)

    flash_bwd_sym = Symbol("flash_attention_bwd", bwd_meta, id="pallas.flash_attention_bwd",
                           is_prim=True, module="pallas", executor=ex)
    ex.opmap[flash_bwd_sym.id] = bwd_impl

    @register_augmented_forward("torch.nn.functional.scaled_dot_product_attention")
    def _sdpa_aug(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False, scale=None, enable_gqa=False):
        if not flash_attention_supported(q, k, v, attn_mask, dropout_p, is_causal, scale):
            return NotImplemented
        o, lse = flash_fwd_sym(q, k, v, attn_mask, dropout_p, is_causal, scale)
        return VJPResult(o, (q, k, v, o, lse, bool(is_causal), scale))

    @register_backward("torch.nn.functional.scaled_dot_product_attention")
    def _sdpa_bwd(q, k, v, o, lse, causal, scale, do):
        return flash_bwd_sym(q, k, v, o, lse, causal, scale, do)


_register_sdpa_grad_rule()


# ===========================================================================
# Fused cross-entropy (mean reduction over valid targets)
# ===========================================================================


def _xent_kernel(logits_ref, tgt_ref, loss_ref, lse_ref):
    # logits (block_n, V), tgt (block_n, 1) int32
    logits = logits_ref[:].astype(jnp.float32)
    n, V = logits.shape
    m = jnp.max(logits, axis=1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=1))
    tgt = tgt_ref[:][:, 0]
    onehot = jax.lax.broadcasted_iota(jnp.int32, (n, V), 1) == tgt[:, None]
    picked = jnp.sum(jnp.where(onehot, logits, 0.0), axis=1)
    loss_ref[:] = (lse - picked)[:, None]
    lse_ref[:] = lse[:, None]


def fused_cross_entropy_forward(logits, targets, block_n: int = 8):
    N, V = logits.shape
    block_n = min(block_n, N)
    tgt2 = targets.astype(jnp.int32)[:, None]
    loss, lse = pl.pallas_call(
        _xent_kernel,
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, V), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(logits, tgt2)
    return loss[:, 0], lse[:, 0]


def _xent_supported(logits, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    return (
        weight is None and label_smoothing == 0.0 and reduction == "mean"
        and getattr(logits, "ndim", 0) == 2
        and logits.shape[0] % 8 == 0 and logits.shape[1] % 128 == 0
    )


def _xent_impl(logits, target, weight=None, ignore_index=-100, reduction="mean", label_smoothing=0.0):
    loss, _ = fused_cross_entropy_forward(logits, target)
    valid = (target != ignore_index)
    loss = jnp.where(valid, loss, 0.0)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)


_xent_claimed = _jit_claimed(
    _xent_impl, ("ignore_index", "reduction", "label_smoothing"),
    lambda logits, target, weight=None, ignore_index=-100, reduction="mean",
    label_smoothing=0.0: (
        (logits, target, weight, int(ignore_index), str(reduction),
         float(label_smoothing)), {}))


ex.register_implementation(
    "torch.nn.functional.cross_entropy",
    _xent_claimed,
    checker=_xent_supported,
)


# ===========================================================================
# Fused RMSNorm
# ===========================================================================


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=1, keepdims=True)
    w = w_ref[:].astype(jnp.float32)  # (1, D) broadcasts over rows
    o_ref[:] = ((x * jax.lax.rsqrt(ms + eps)) * w).astype(o_ref.dtype)


def fused_rms_norm(x2d, w, eps: float = 1e-6, block_n: int = 256):
    N, D = x2d.shape
    block_n = min(block_n, N)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, D), lambda i: (i, 0)),
            pl.BlockSpec((1, D), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x2d.dtype),
        interpret=_interpret(),
    )(x2d, w[None, :])


def _rms_supported(a, normalized_shape, weight=None, eps=1e-6):
    return (
        weight is not None and len(normalized_shape) == 1
        and getattr(a, "ndim", 0) >= 2 and a.shape[-1] % 128 == 0
    )


def _rms_impl(a, normalized_shape, weight=None, eps=1e-6):
    shape = a.shape
    x2d = a.reshape((-1, shape[-1]))
    out = fused_rms_norm(x2d, weight, eps)
    return out.reshape(shape)


_rms_claimed = _jit_claimed(
    _rms_impl, ("normalized_shape", "eps"),
    lambda a, normalized_shape, weight=None, eps=1e-6: (
        (a, tuple(int(d) for d in normalized_shape), weight, float(eps)), {}))


ex.register_implementation(
    "torch.nn.functional.rms_norm",
    _rms_claimed,
    checker=_rms_supported,
)


_register_rope_sdpa()


# ===========================================================================
# Fused int8 dequant-matmul (weight-only quantized linear)
# ===========================================================================
#
# XLA hoists a separate dequant out of loops/scans, materializing the full
# bf16 weight and defeating weight-only quantization's HBM saving (measured:
# the "int8" XLA path streams bf16 weights after the first step). This
# kernel keeps weights int8-resident in HBM: each program streams an int8
# (block_n, K) weight block into VMEM, dequantizes slice-wise, and feeds the
# MXU — the quantized analog of the reference's bnb linear executor.


def _int8_linear_kernel(x_ref, w_ref, s_ref, o_ref, *, block_k: int):
    M, K = x_ref.shape
    block_n = w_ref.shape[0]

    def body(j, acc):
        xs = x_ref[:, pl.ds(j * block_k, block_k)]
        ws = w_ref[:, pl.ds(j * block_k, block_k)].astype(xs.dtype)
        return acc + jax.lax.dot_general(xs, ws, (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(0, K // block_k, body,
                            jnp.zeros((M, block_n), jnp.float32))
    o_ref[:] = (acc * s_ref[:][:, 0][None, :]).astype(o_ref.dtype)


def int8_linear(x, qweight, scale, *, block_n: int = 256, block_k: int = 512):
    """x (..., K) @ dequant(qweight (N, K), scale (N,)).T -> (..., N)."""
    shape = x.shape
    K = shape[-1]
    N = qweight.shape[0]
    x2d = x.reshape((-1, K))
    M = x2d.shape[0]
    block_n = math.gcd(block_n, N)
    block_k = math.gcd(block_k, K)
    out = pl.pallas_call(
        functools.partial(_int8_linear_kernel, block_k=block_k),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((M, K), lambda n: (0, 0)),
            pl.BlockSpec((block_n, K), lambda n: (n, 0)),
            # scale rides as (N, 1): 1-D f32 operands hit XLA/Mosaic layout
            # tiling mismatches ({0:T(1024)} vs the block's {0:T(256)})
            pl.BlockSpec((block_n, 1), lambda n: (n, 0)),
        ],
        out_specs=pl.BlockSpec((M, block_n), lambda n: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, qweight, scale.astype(jnp.float32)[:, None])
    return out.reshape(shape[:-1] + (N,))


def _int8_linear_supported(x, qweight, scale, bias=None):
    if getattr(qweight, "ndim", 0) != 2 or getattr(x, "ndim", 0) < 2:
        return False
    N, K = qweight.shape
    M = 1
    for d in x.shape[:-1]:
        M *= int(d)
    # the kernel is a TPU HBM-residency play; on CPU the interpret-mode
    # pallas path is a per-call interpreter, far slower than XLA's
    # dequant-matmul — serving benchmarks must measure the XLA path there
    # (TT_INT8_PALLAS_CPU=1 re-enables the claim for kernel tests)
    if not (_on_tpu() or os.environ.get("TT_INT8_PALLAS_CPU") == "1"):
        return False
    # whole-M block (no M grid): claim the serving/decode regime; huge-M
    # prefill/training shapes stay on the XLA path (compute-bound there)
    return (
        # exact dtype name (proxy dtypes print as "dtypes.int8"): uint8 must
        # NOT claim the kernel — it would be reinterpreted as signed
        str(getattr(qweight, "dtype", "")).rpartition(".")[2] == "int8"
        and x.shape[-1] == K
        and K % 128 == 0 and K <= 8192
        and N % 128 == 0
        and M <= 512
    )


def _int8_linear_impl(x, qweight, scale, bias=None):
    out = int8_linear(x, qweight, scale)
    if bias is not None:
        out = out + bias
    return out


ex.register_implementation("quant.linear_int8", _int8_linear_impl,
                           checker=_int8_linear_supported)


# ===========================================================================
# Fused fp8 delayed-scaling matmul (quantize + amax + matmul, one VMEM pass)
# ===========================================================================
#
# The unfused delayed-scaling linear runs as FOUR device programs per call:
# quantize(x), quantize(w), the fp8 dot, and a separate abs-max reduction
# over each operand for the history roll — each streaming the operand
# through HBM again. The profiler tags the quantize/amax passes memory-bound
# (BENCH_FP8: the fp8 road measured 0.83x bf16 at 7B-shape width, i.e. the
# scaling overhead ATE the matmul win). This kernel folds all of it into the
# matmul's VMEM pass: each (block_m, block_k) x block and (block_n, block_k)
# w block is cast to f32 once, clipped/scaled to e4m3, max-reduced into the
# running amax, and fed to the MXU as bf16 (every e4m3 value is exactly
# representable in bf16, so the dot is exact in f32 accumulation). The
# quantized blocks are optionally written out as the saved-for-backward
# residuals — the same bytes the unfused path materializes anyway.


def _fp8_matmul_kernel(x_ref, w_ref, sx_ref, sw_ref, *refs,
                       n_k: int, fmt_max: float, save_q: bool):
    if save_q:
        o_ref, xq_ref, wq_ref, ax_ref, aw_ref, acc_ref = refs
    else:
        o_ref, ax_ref, aw_ref, acc_ref = refs
        xq_ref = wq_ref = None
    i, j, k = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    sx = sx_ref[0, 0]
    sw = sw_ref[0, 0]
    xq = jnp.clip(x * sx, -fmt_max, fmt_max).astype(jnp.float8_e4m3fn)
    wq = jnp.clip(w * sw, -fmt_max, fmt_max).astype(jnp.float8_e4m3fn)
    if save_q:
        # unconditional store: an x block is revisited once per j (w block
        # once per i) and rewriting the same value sidesteps any
        # leave-and-return output-revisit semantics
        xq_ref[:] = xq
        wq_ref[:] = wq

    # amax of the UNQUANTIZED operands (feeds the delayed-scaling history).
    # The (1, 1) output block is grid-resident (constant index map): init on
    # the first program, then max-accumulate — revisits re-apply the same
    # max, which is idempotent, so no j==0/i==0 gating is needed.
    @pl.when((i == 0) & (j == 0) & (k == 0))
    def _init_amax():
        # explicit f32 literals: under jax_enable_x64 a bare 0.0 stores f64
        ax_ref[0, 0] = jnp.float32(0.0)
        aw_ref[0, 0] = jnp.float32(0.0)

    ax_ref[0, 0] = jnp.maximum(ax_ref[0, 0], jnp.max(jnp.abs(x)))
    aw_ref[0, 0] = jnp.maximum(aw_ref[0, 0], jnp.max(jnp.abs(w)))

    @pl.when(k == 0)
    def _zero_acc():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        xq.astype(jnp.bfloat16), wq.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _write_out():
        o_ref[:] = (acc_ref[...] / (sx * sw)).astype(o_ref.dtype)


def fp8_linear_fused(x2d, w, sx, sw, *, fmt_max: float = 448.0,
                     save_quantized: bool = False,
                     block_m: int = 256, block_n: int = 256, block_k: int = 512):
    """Delayed-scaling fp8 linear: ``dequant(q(x2d) @ q(w).T)`` with the
    operand amaxes reduced in the same pass.

    Returns ``(y, amax_x, amax_w)`` — or ``(y, xq, wq, amax_x, amax_w)``
    with ``save_quantized`` (the e4m3 residuals for the backward). ``sx`` /
    ``sw`` are the precomputed delayed scales (scalars)."""
    M, K = x2d.shape
    N = w.shape[0]
    bm = math.gcd(block_m, M)
    bn = math.gcd(block_n, N)
    bk = math.gcd(block_k, K)
    n_k = K // bk
    scalar_spec = pl.BlockSpec((1, 1), lambda i, j, k: (0, 0))
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    out_shape = [jax.ShapeDtypeStruct((M, N), x2d.dtype)]
    if save_quantized:
        out_specs += [pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      pl.BlockSpec((bn, bk), lambda i, j, k: (j, k))]
        out_shape += [jax.ShapeDtypeStruct((M, K), jnp.float8_e4m3fn),
                      jax.ShapeDtypeStruct((N, K), jnp.float8_e4m3fn)]
    out_specs += [scalar_spec, scalar_spec]
    out_shape += [jax.ShapeDtypeStruct((1, 1), jnp.float32),
                  jax.ShapeDtypeStruct((1, 1), jnp.float32)]
    outs = pl.pallas_call(
        functools.partial(_fp8_matmul_kernel, n_k=n_k, fmt_max=fmt_max,
                          save_q=save_quantized),
        grid=(M // bm, N // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bn, bk), lambda i, j, k: (j, k)),
            scalar_spec,
            scalar_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)] if pltpu is not None else [],
        interpret=_interpret(),
    )(x2d, w,
      jnp.asarray(sx, jnp.float32).reshape(1, 1),
      jnp.asarray(sw, jnp.float32).reshape(1, 1))
    if save_quantized:
        y, xq, wq, ax, aw = outs
        return y, xq, wq, ax[0, 0], aw[0, 0]
    y, ax, aw = outs
    return y, ax[0, 0], aw[0, 0]


def fp8_linear_fused_supported(x2d, w) -> bool:
    """Dispatch gate for the fp8 training executor: TPU (or forced via
    TT_FP8_FUSED=force for interpret-mode testing), tile-aligned shapes.
    The CPU/jnp unfused reference stays the fallback everywhere else."""
    forced = os.environ.get("TT_FP8_FUSED", "") == "force"
    if not (_on_tpu() or forced):
        return False
    if pltpu is None or getattr(x2d, "ndim", 0) != 2 or getattr(w, "ndim", 0) != 2:
        return False
    M, K = x2d.shape
    N = w.shape[0]
    return K % 128 == 0 and N % 128 == 0 and M % 8 == 0


# ===========================================================================
# Fused NF4 dequant-matmul (4-bit weight-only linear, opt-in serving kernel)
# ===========================================================================
#
# Weights stay PACKED (0.5 byte/element) in HBM; the kernel unpacks nibbles,
# looks the 16-entry NF4 codebook up via a select tree (Mosaic has no
# small-table gather), applies per-64-block absmax via a 0/1 expander dot,
# and feeds the MXU. Measured at a decode GEMM (M=8, K=4096, N=11008):
# ~0.95x the bf16-weight matmul speed at 4x smaller weight footprint — the
# bitsandbytes trade (footprint over speed), TPU-native. Opt-in via
# nf4_linear + pack_nf4_kernel_layout; the canonical QuantizeNF4Transform
# path keeps its XLA dequant (which XLA may hoist/materialize).
#
# Kernel packing layout: within each block_k slice of a row, byte j holds
# the codes of columns j (hi nibble) and j + block_k/2 (lo nibble) — dequant
# is then a contiguous concat, avoiding Mosaic-unsupported lane interleaves.

NF4_KERNEL_BLOCK_K = 512


def nf4_kernel_block_k(K: int, block_size: int = 64):
    """Largest K-slice width the kernel layout supports for this K: a
    divisor of K, multiple of 2*block_size (nibble halves stay block-aligned)
    and of 256 (the (K/2) lane offsets stay 128-aligned), capped at 512.
    None when no such width exists (e.g. K=2816 -> 256; K=1000 -> None)."""
    for bk in (512, 384, 256, 128):
        if bk <= K and K % bk == 0 and bk % (2 * block_size) == 0 and (bk // 2) % 128 == 0:
            return bk
    return None


def pack_nf4_kernel_layout(packed, absmax, shape, block_size: int = 64):
    """Canonical NF4 (flat hi/lo interleave) -> kernel layout
    ((N, K/2) uint8 halves-per-slice + (N, K/block_size) absmax)."""
    N, K = shape
    bk = nf4_kernel_block_k(K, block_size)
    if bk is None:
        raise ValueError(f"no kernel block width for K={K} (see nf4_kernel_block_k)")
    hi = (packed >> 4) & 0xF
    lo = packed & 0xF
    codes = jnp.stack([hi, lo], axis=1).reshape(N, K)
    parts = []
    for j0 in range(0, K, bk):
        sl = codes[:, j0:j0 + bk]
        parts.append((sl[:, : bk // 2] << 4) | sl[:, bk // 2:])
    return jnp.concatenate(parts, axis=1).astype(jnp.uint8), absmax.reshape(N, K // block_size)


def _nf4_codebook_floats():
    # python-float codebook, resolved OUTSIDE kernel tracing (pallas kernels
    # can neither capture array constants nor concretize values mid-trace)
    import numpy as _np

    from ..transforms.quantization import NF4_CODE

    return [float(v) for v in _np.asarray(NF4_CODE)]


def _nf4_lookup(codes, vals):
    """16-way select tree over the NF4 codebook (Mosaic has no small-table
    gather)."""
    out = jnp.full(codes.shape, vals[0], jnp.float32)
    for idx in range(1, 16):
        out = jnp.where(codes == idx, vals[idx], out)
    return out


def _nf4_linear_kernel(x_ref, p_ref, a_ref, o_ref, *, block_k: int, block_size: int,
                       codebook: tuple):
    M, K = x_ref.shape
    bn = p_ref.shape[0]
    acc = jnp.zeros((M, bn), jnp.float32)
    for j in range(K // block_k):  # static unroll: lane offsets stay provable
        xs = x_ref[:, j * block_k:(j + 1) * block_k]
        byts = p_ref[:, j * (block_k // 2):(j + 1) * (block_k // 2)]
        b32 = byts.astype(jnp.int32)  # minor-dim ops need 32-bit types
        hi = (b32 >> 4) & 0xF
        lo = b32 & 0xF
        w = jnp.concatenate([_nf4_lookup(hi, codebook), _nf4_lookup(lo, codebook)], axis=-1)
        nb = block_k // block_size
        am = a_ref[:, j * nb:(j + 1) * nb]
        # repeat-along-lanes via a 0/1 expander dot (jnp.repeat's reshape is
        # an unsupported Mosaic shape cast)
        row = jax.lax.broadcasted_iota(jnp.int32, (nb, block_k), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (nb, block_k), 1) // block_size
        expander = (row == col).astype(jnp.float32)
        am_full = jax.lax.dot_general(am, expander, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ws = (w * am_full).astype(xs.dtype)
        acc = acc + jax.lax.dot_general(xs, ws, (((1,), (1,)), ((), ())),
                                        preferred_element_type=jnp.float32)
    o_ref[:] = acc.astype(o_ref.dtype)


def nf4_linear(x, packed_kl, absmax_kl, *, block_n: int = 256, block_size: int = 64):
    """x (..., K) against kernel-layout NF4 weights (see
    pack_nf4_kernel_layout) -> (..., N)."""
    shape = x.shape
    K = shape[-1]
    N = packed_kl.shape[0]
    x2d = x.reshape((-1, K))
    M = x2d.shape[0]
    block_n = math.gcd(block_n, N)
    block_k = nf4_kernel_block_k(K, block_size)
    out = pl.pallas_call(
        functools.partial(_nf4_linear_kernel, block_k=block_k, block_size=block_size,
                          codebook=tuple(_nf4_codebook_floats())),
        grid=(N // block_n,),
        in_specs=[
            pl.BlockSpec((M, K), lambda n: (0, 0)),
            pl.BlockSpec((block_n, K // 2), lambda n: (n, 0)),
            pl.BlockSpec((block_n, K // block_size), lambda n: (n, 0)),
        ],
        out_specs=pl.BlockSpec((M, block_n), lambda n: (0, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        interpret=_interpret(),
    )(x2d, packed_kl, absmax_kl.astype(jnp.float32))
    return out.reshape(shape[:-1] + (N,))


def _nf4_kl_supported(x, packed_kl, absmax_kl, out_features, in_features,
                      block_size=64, bias=None):
    try:
        N, K, bs = int(out_features), int(in_features), int(block_size)
    except Exception:
        return False
    M = 1
    for d in getattr(x, "shape", ())[:-1]:
        M *= int(d)
    return (
        getattr(x, "ndim", 0) >= 2 and x.shape[-1] == K
        and bs == 64 and nf4_kernel_block_k(K, bs) is not None
        and N % 128 == 0
        and M <= 512
    )


def _nf4_kl_impl(x, packed_kl, absmax_kl, out_features, in_features,
                 block_size=64, bias=None):
    out = nf4_linear(x, packed_kl, absmax_kl, block_size=int(block_size))
    if bias is not None:
        out = out + bias
    return out


ex.register_implementation("quant.linear_nf4_kl", _nf4_kl_impl,
                           checker=_nf4_kl_supported)


# ===========================================================================
# Paged attention — decode (serving engine, thunder_tpu/serving/)
# ===========================================================================
#
# Continuous-batching decode attends ONE new token per sequence against a
# block-paged KV pool (vLLM/PagedAttention, SOSP '23): k/v live in a fixed
# (n_pages, page_size, Hkv, D) pool per layer and each sequence owns a row
# of page ids. The kernel gathers a sequence's pages via the page table
# INSIDE the pallas grid — the table rides as a scalar-prefetch operand so
# the k/v BlockSpec index maps resolve page ids before each DMA — and runs
# the flash kernel's online-softmax body (base-2 exp, f32 accumulation)
# across the page axis in VMEM scratch. The ltorch.paged_attention
# decomposition (ops/ltorch.py) is the pure-jax gather reference path for
# CPU/interpret mode and for shapes the kernel declines.

# decode working set is small (one page pair + one q group per program), but
# absurd page_size x D configs must fall back, not fail-to-compile: estimate
# VMEM like _cap_blocks_for_dtype and decline the claim over the budget
# (ADVICE r5: estimate + automatic fallback instead of an env escape hatch).
# Both the estimate formula and the fit decision live in the unified budget
# API (analysis/memory.py) — this module keeps thin aliases.


def _paged_vmem_bytes(page_size: int, D: int, g: int, kv_itemsize: int, q_itemsize: int) -> int:
    from ..analysis import budget as _budget

    return _budget.paged_decode_vmem_bytes(page_size, D, g, kv_itemsize, q_itemsize)


def _paged_attn_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_scr, m_scr, l_scr, *, page_size: int, scale: float):
    # grid (B, Hkv, n_pages_max) with pages innermost: scratch carries the
    # online softmax across one sequence's pages; o is written ONCE at the
    # last page. q_ref: (g, D) — the kv head's q group; k_ref/v_ref:
    # (page_size, D) — the page the table mapped this grid step to.
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)
    g, D = q_ref.shape
    seq_len = sl_ref[b]

    @pl.when(p == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # pages entirely past the sequence are skipped: their table entries
    # point at the reserved null page, so the DMA is in-bounds but the
    # values are garbage — never let them into the accumulators
    @pl.when(p * page_size < seq_len)
    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (scale * LOG2E)
        # partially-filled last page: mask slots at/after seq_len
        k_pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, (g, page_size), 1)
        s = jnp.where(k_pos < seq_len, s, NEG_INF)
        m_prev = m_scr[:][:, 0]
        l_prev = l_scr[:][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        pexp = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m_prev - m_new)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new[:, None]
        l_scr[:] = (l_prev * corr + jnp.sum(pexp, axis=1))[:, None]

    @pl.when(p == n_p - 1)
    def _write():
        l = l_scr[:][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention_decode(q, k_pages, v_pages, page_table, seq_lens, scale=None,
                           *, interpret: bool | None = None):
    """q (B, H, D) against a paged pool (P, page_size, Hkv, D) through
    page_table (B, n_pages_max) int32 / seq_lens (B,) int32 -> (B, H, D).

    seq_lens counts valid tokens INCLUDING the current one (whose k/v must
    already be written to its page). interpret=True runs the kernel in
    pallas interpret mode (the CPU equivalence tests)."""
    B, H, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    npm = page_table.shape[1]
    g = H // Hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    qg = q.reshape(B, Hkv, g, D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, npm),
        in_specs=[
            pl.BlockSpec((None, None, g, D), lambda b, h, p, pt, sl: (b, h, 0, 0)),
            pl.BlockSpec((None, ps, None, D), lambda b, h, p, pt, sl: (pt[b, p], 0, h, 0)),
            pl.BlockSpec((None, ps, None, D), lambda b, h, p, pt, sl: (pt[b, p], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, g, D), lambda b, h, p, pt, sl: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((g, D), jnp.float32),
                        pltpu.VMEM((g, 1), jnp.float32),
                        pltpu.VMEM((g, 1), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, page_size=ps, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, g, D), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(B, H, D)


def paged_attention_supported(q, k_pages, v_pages, page_table, seq_lens, scale=None) -> bool:
    """Checker: the paged decode kernel claims thunder.paged_attention on
    TPU (TT_PAGED_KERNEL=1 forces the claim for interpret-mode A/B, =0
    never claims); shapes must fit the page tiling and the estimated VMEM
    working set must stay under budget — otherwise the pure-jax gather
    decomposition runs."""
    if pltpu is None:
        return False
    override = os.environ.get("TT_PAGED_KERNEL")
    if override == "0":
        return False
    if not (_on_tpu() or override == "1"):
        return False
    if getattr(q, "ndim", 0) != 3 or getattr(k_pages, "ndim", 0) != 4:
        return False
    B, H, D = q.shape
    P, ps, Hkv, Dk = k_pages.shape
    shapes_ok = (
        D == Dk and D <= 512
        and tuple(v_pages.shape) == tuple(k_pages.shape)
        and H % Hkv == 0
        and ps % 8 == 0  # sublane tile
        and getattr(page_table, "ndim", 0) == 2 and page_table.shape[0] == B
        and getattr(seq_lens, "ndim", 0) == 1 and seq_lens.shape[0] == B
    )
    if not shapes_ok:
        return False
    from ..analysis import budget as _budget

    kv_item = jnp.dtype(str(k_pages.dtype).rpartition(".")[2]).itemsize
    q_item = jnp.dtype(str(q.dtype).rpartition(".")[2]).itemsize
    return _budget.within_vmem(_paged_vmem_bytes(ps, D, H // Hkv, kv_item, q_item),
                               _budget.paged_vmem_limit())


def _paged_attention_impl(q, k_pages, v_pages, page_table, seq_lens, scale=None):
    return paged_attention_decode(q, k_pages, v_pages, page_table, seq_lens, scale)


ex.register_implementation("thunder.paged_attention", _paged_attention_impl,
                           checker=paged_attention_supported)


# ---------------------------------------------------------------------------
# Paged attention — multi-query (chunked prefill + speculative verify)
# ---------------------------------------------------------------------------
#
# The fleet-serving programs attend MORE than one new token per sequence
# against the same paged pool: a chunked-prefill chunk (B=1, T=chunk tokens)
# and the speculative-decoding verify step (T=k+1 proposals per packed
# sequence), both with PER-QUERY causal coverage k_pos <= q_pos[b, t]. The
# kernel is the decode kernel with the q group widened to (g*T, D) and the
# per-query positions riding as a third scalar-prefetch operand for the
# masking. Shared (copy-on-write) page tables are transparent: a physical
# page shared by N sequences simply appears in N table rows, and partial
# chunk tables (entries past the written prefix) point at the null page,
# which the q_pos mask keeps out of the accumulators either way.


def _paged_chunk_kernel(pt_ref, sl_ref, qp_ref, q_ref, k_ref, v_ref, o_ref,
                        acc_scr, m_scr, l_scr, *, page_size: int, n_q: int,
                        scale: float):
    # grid (B, Hkv, n_pages_max); q_ref (g*T, D) — T queries per kv head
    # group, flattened into rows; qp_ref carries each query's absolute
    # position ((B, T) prefetched), sl_ref the per-sequence page coverage
    # bound (max q_pos + 1) used to skip trailing never-attended pages.
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)
    gT, D = q_ref.shape
    g = gT // n_q

    @pl.when(p == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(p * page_size < sl_ref[b])
    def _compute():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (scale * LOG2E)
        k_pos = p * page_size + jax.lax.broadcasted_iota(jnp.int32, (gT, page_size), 1)
        # row r of the flattened q block is query t = r % n_q of its group
        t_of_row = jax.lax.broadcasted_iota(jnp.int32, (gT, page_size), 0) % n_q
        q_pos = qp_ref[b, t_of_row]
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_prev = m_scr[:][:, 0]
        l_prev = l_scr[:][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        pexp = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m_prev - m_new)
        acc_scr[:] = acc_scr[:] * corr[:, None] + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new[:, None]
        l_scr[:] = (l_prev * corr + jnp.sum(pexp, axis=1))[:, None]

    @pl.when(p == n_p - 1)
    def _write():
        l = l_scr[:][:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe[:, None]).astype(o_ref.dtype)


def paged_chunk_decode(q, k_pages, v_pages, page_table, q_pos, scale=None,
                       *, interpret: bool | None = None):
    """q (B, H, T, D) against a paged pool (P, page_size, Hkv, D) through
    page_table (B, n_pages_max) with per-query positions q_pos (B, T) int32
    -> (B, H, T, D). Each query attends key positions <= its own."""
    B, H, T, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    npm = page_table.shape[1]
    g = H // Hkv
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    # (B, Hkv, g*T, D): group rows of one kv head, T queries per group row set
    qg = q.reshape(B, Hkv, g, T, D).reshape(B, Hkv, g * T, D)
    seq_lens = jnp.max(q_pos, axis=1) + 1  # page coverage bound per sequence
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hkv, npm),
        in_specs=[
            pl.BlockSpec((None, None, g * T, D), lambda b, h, p, pt, sl, qp: (b, h, 0, 0)),
            pl.BlockSpec((None, ps, None, D), lambda b, h, p, pt, sl, qp: (pt[b, p], 0, h, 0)),
            pl.BlockSpec((None, ps, None, D), lambda b, h, p, pt, sl, qp: (pt[b, p], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, g * T, D),
                               lambda b, h, p, pt, sl, qp: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((g * T, D), jnp.float32),
                        pltpu.VMEM((g * T, 1), jnp.float32),
                        pltpu.VMEM((g * T, 1), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_paged_chunk_kernel, page_size=ps, n_q=T, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, g * T, D), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q_pos.astype(jnp.int32), qg, k_pages, v_pages)
    return out.reshape(B, Hkv, g, T, D).reshape(B, H, T, D)


def paged_chunk_attention_supported(q, k_pages, v_pages, page_table, q_pos,
                                    scale=None) -> bool:
    """Checker for thunder.paged_chunk_attention: same claim policy as the
    decode kernel (TT_PAGED_KERNEL override, page tiling, VMEM budget with
    the q/accumulator rows widened by T)."""
    if pltpu is None:
        return False
    override = os.environ.get("TT_PAGED_KERNEL")
    if override == "0":
        return False
    if not (_on_tpu() or override == "1"):
        return False
    if getattr(q, "ndim", 0) != 4 or getattr(k_pages, "ndim", 0) != 4:
        return False
    B, H, T, D = q.shape
    P, ps, Hkv, Dk = k_pages.shape
    shapes_ok = (
        D == Dk and D <= 512
        and tuple(v_pages.shape) == tuple(k_pages.shape)
        and H % Hkv == 0
        and ps % 8 == 0  # sublane tile
        and getattr(page_table, "ndim", 0) == 2 and page_table.shape[0] == B
        and getattr(q_pos, "ndim", 0) == 2 and tuple(q_pos.shape) == (B, T)
    )
    if not shapes_ok:
        return False
    from ..analysis import budget as _budget

    kv_item = jnp.dtype(str(k_pages.dtype).rpartition(".")[2]).itemsize
    q_item = jnp.dtype(str(q.dtype).rpartition(".")[2]).itemsize
    return _budget.within_vmem(
        _budget.paged_chunk_vmem_bytes(ps, D, H // Hkv, T, kv_item, q_item),
        _budget.paged_vmem_limit())


def _paged_chunk_attention_impl(q, k_pages, v_pages, page_table, q_pos, scale=None):
    return paged_chunk_decode(q, k_pages, v_pages, page_table, q_pos, scale)


ex.register_implementation("thunder.paged_chunk_attention", _paged_chunk_attention_impl,
                           checker=paged_chunk_attention_supported)


# ===========================================================================
# Grouped-expert MLP (MoE capacity-routed dispatch)
# ===========================================================================
#
# Tokens are packed into per-expert capacity bins (E, cap, D) by the routing
# scatter; the grid runs (expert, bin-block) so each expert's MXU matmuls
# touch ONLY its own bin — the dense one-hot einsum road multiplies every
# token through every expert (O(E*cap*D*H) regardless of routing). Bin rows
# at/after group_sizes[e] are zero-filled padding: wholly-padding blocks are
# skipped (zero write, no MXU work), partially-padding blocks compute them
# anyway — SwiGLU(0) = 0 exactly, so both roads agree bitwise on padding.

_GROUPED_BLOCK_C = int(os.environ.get("TT_GROUPED_BLOCK_C", "128"))


def _grouped_mlp_kernel(gs_ref, x_ref, wg_ref, wu_ref, wd_ref, o_ref, *, block_c: int):
    # grid (E, cap // block_c); x_ref (block_c, D) — one bin block of expert
    # e; wg/wu (D, H), wd (H, D) — expert e's panels; gs_ref (E,) prefetched
    e = pl.program_id(0)
    c = pl.program_id(1)
    live = c * block_c < gs_ref[e]

    @pl.when(jnp.logical_not(live))
    def _pad():
        o_ref[:] = jnp.zeros_like(o_ref)

    @pl.when(live)
    def _compute():
        x = x_ref[:]
        wd = wd_ref[:]
        # fused SwiGLU in one VMEM pass: f32 accumulation for the dots,
        # silu on the VPU, single down-projection write
        g = jax.lax.dot_general(x, wg_ref[:], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(x, wu_ref[:], (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        h = (g * (1.0 / (1.0 + jnp.exp(-g)))) * u
        o_ref[:] = jax.lax.dot_general(h.astype(wd.dtype), wd,
                                       (((1,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32).astype(o_ref.dtype)


def grouped_mlp_fused(bins, w_gate, w_up, w_down, group_sizes, *,
                      block_c: int | None = None, interpret: bool | None = None):
    """bins (E, cap, D) x per-expert panels (E, D, H)/(E, H, D) with
    group_sizes (E,) int32 -> (E, cap, D). Rows past group_sizes[e] must be
    zero-filled (the dispatch scatter's contract); whole padding blocks skip
    the MXU entirely."""
    E, cap, D = bins.shape
    H = w_gate.shape[-1]
    if block_c is None:
        block_c = math.gcd(cap, _GROUPED_BLOCK_C)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(E, cap // block_c),
        in_specs=[
            pl.BlockSpec((None, block_c, D), lambda e, c, gs: (e, c, 0)),
            pl.BlockSpec((None, D, H), lambda e, c, gs: (e, 0, 0)),
            pl.BlockSpec((None, D, H), lambda e, c, gs: (e, 0, 0)),
            pl.BlockSpec((None, H, D), lambda e, c, gs: (e, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_c, D), lambda e, c, gs: (e, c, 0)),
    )
    return pl.pallas_call(
        functools.partial(_grouped_mlp_kernel, block_c=block_c),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, cap, D), bins.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(group_sizes.astype(jnp.int32), bins, w_gate, w_up, w_down)


def grouped_mlp_supported(bins, w_gate, w_up, w_down, group_sizes) -> bool:
    """Checker: the grouped kernel claims thunder.grouped_mlp on TPU
    (TT_GROUPED_KERNEL=1 forces the claim for interpret-mode A/B, =0 never
    claims); the per-program working set — one expert's three weight panels
    plus a bin block and its f32 SwiGLU intermediates — must fit the VMEM
    budget, otherwise the batched-matmul decomposition runs (the ADVICE
    fallback pattern, unified via analysis/memory.py)."""
    if pltpu is None:
        return False
    override = os.environ.get("TT_GROUPED_KERNEL")
    if override == "0":
        return False
    if not (_on_tpu() or override == "1"):
        return False
    if getattr(bins, "ndim", 0) != 3 or getattr(w_gate, "ndim", 0) != 3:
        return False
    E, cap, D = bins.shape
    H = w_gate.shape[-1]
    shapes_ok = (
        tuple(w_gate.shape) == (E, D, H)
        and tuple(w_up.shape) == (E, D, H)
        and tuple(w_down.shape) == (E, H, D)
        and getattr(group_sizes, "ndim", 0) == 1 and group_sizes.shape[0] == E
        and cap % 8 == 0  # sublane tile
        and D <= 4096 and H <= 16384
    )
    if not shapes_ok:
        return False
    from ..analysis import budget as _budget

    block_c = math.gcd(cap, _GROUPED_BLOCK_C)
    w_item = jnp.dtype(str(w_gate.dtype).rpartition(".")[2]).itemsize
    x_item = jnp.dtype(str(bins.dtype).rpartition(".")[2]).itemsize
    return _budget.within_vmem(
        _budget.grouped_mlp_vmem_bytes(block_c, D, H, w_item, x_item))


_grouped_mlp_claimed = _jit_claimed(
    lambda bins, w_gate, w_up, w_down, group_sizes: grouped_mlp_fused(
        bins, w_gate, w_up, w_down, group_sizes),
    (), lambda *a: (a, {}))


ex.register_implementation("thunder.grouped_mlp", _grouped_mlp_claimed,
                           checker=grouped_mlp_supported)


def _register_grouped_mlp_grad_rule():
    """Executor-claimed grad for thunder.grouped_mlp: the fused kernel runs
    the forward; the backward is the straight SwiGLU chain rule over the
    SAME capacity bins (padding rows are zero, so their contributions to
    every weight grad vanish identically). Falls through to the composite
    decomposition when the kernel can't claim the shapes."""
    from ..transforms.autodiff import VJPResult, register_augmented_forward, register_backward

    def fwd_meta(bins, w_gate, w_up, w_down, group_sizes):
        return TensorProxy(shape=bins.shape, dtype=bins.dtype, device=bins.device)

    fwd_sym = Symbol("grouped_mlp_fwd", fwd_meta, id="pallas.grouped_mlp_fwd",
                     is_prim=True, module="pallas", executor=ex)
    ex.opmap[fwd_sym.id] = lambda bins, w_gate, w_up, w_down, group_sizes: (
        grouped_mlp_fused(bins, w_gate, w_up, w_down, group_sizes))

    def bwd_meta(bins, w_gate, w_up, w_down, group_sizes, do):
        return (TensorProxy(shape=bins.shape, dtype=bins.dtype, device=bins.device),
                TensorProxy(shape=w_gate.shape, dtype=w_gate.dtype, device=w_gate.device),
                TensorProxy(shape=w_up.shape, dtype=w_up.dtype, device=w_up.device),
                TensorProxy(shape=w_down.shape, dtype=w_down.dtype, device=w_down.device))

    def bwd_impl(bins, w_gate, w_up, w_down, group_sizes, do):
        g = jnp.einsum("ecd,edh->ech", bins, w_gate)
        u = jnp.einsum("ecd,edh->ech", bins, w_up)
        sg = jax.nn.sigmoid(g)
        h = g * sg * u
        dh = jnp.einsum("ecd,ehd->ech", do, w_down)
        dwd = jnp.einsum("ech,ecd->ehd", h, do)
        du = dh * (g * sg)
        dg = dh * u * (sg * (1.0 + g * (1.0 - sg)))
        dbins = (jnp.einsum("ech,edh->ecd", dg, w_gate)
                 + jnp.einsum("ech,edh->ecd", du, w_up))
        dwg = jnp.einsum("ecd,ech->edh", bins, dg)
        dwu = jnp.einsum("ecd,ech->edh", bins, du)
        return (dbins.astype(bins.dtype), dwg.astype(w_gate.dtype),
                dwu.astype(w_up.dtype), dwd.astype(w_down.dtype))

    bwd_sym = Symbol("grouped_mlp_bwd", bwd_meta, id="pallas.grouped_mlp_bwd",
                     is_prim=True, module="pallas", executor=ex)
    ex.opmap[bwd_sym.id] = bwd_impl

    @register_augmented_forward("thunder.grouped_mlp")
    def _grouped_mlp_aug(bins, w_gate, w_up, w_down, group_sizes):
        if not grouped_mlp_supported(bins, w_gate, w_up, w_down, group_sizes):
            return NotImplemented  # decompose: batched-matmul grad rules apply
        out = fwd_sym(bins, w_gate, w_up, w_down, group_sizes)
        return VJPResult(out, (bins, w_gate, w_up, w_down, group_sizes))

    @register_backward("thunder.grouped_mlp")
    def _grouped_mlp_bwd(bins, w_gate, w_up, w_down, group_sizes, do):
        dbins, dwg, dwu, dwd = bwd_sym(bins, w_gate, w_up, w_down, group_sizes, do)
        return dbins, dwg, dwu, dwd, None


_register_grouped_mlp_grad_rule()


# ===========================================================================
# Streaming ring-flash attention (context parallelism)
# ===========================================================================
#
# One ring step = one pallas_call: the ppermute'd K/V shard (T_blk rows, the
# per-device block — not the global sequence) is consumed by the flash
# online-softmax body with the (o, m, l) accumulators carried in HBM between
# steps, so the VMEM working set is O(block) however long the global context
# grows. GQA is native — the k/v BlockSpecs index kv head = q head // group,
# never materializing replicated heads. The causal mask uses GLOBAL
# positions (q_off/k_off ride as scalar prefetch): each device's q shard
# starts at my*T, each arriving k shard at src*T.
#
# Step-order contract: the jax-level ring MUST process src == my first (the
# diagonal block). Its first k sub-block gives every causal row at least one
# valid key, making the carried running max finite before any later
# fully-masked tile — NEG_INF is a finite sentinel, so a fully-masked tile
# against a still-NEG_INF max would contribute exp2(0) garbage.


def _ring_flash_step_kernel(off_ref, q_ref, k_ref, v_ref, oi_ref, mi_ref, li_ref,
                            oo_ref, mo_ref, lo_ref, *, block_k: int, causal: bool,
                            scale: float):
    # grid (B, H, T // block_q); q_ref (block_q, D); k_ref/v_ref (T_blk, D)
    # — this ring step's shard; (oi, mi, li) carried accumulators in, the
    # updated (oo, mo, lo) out. m/l ride in log2 units (flash convention).
    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:]
    q_off = off_ref[0]
    k_off = off_ref[1]
    scale2 = scale * LOG2E
    q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        o_acc, m, l = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale2
        if causal:
            k_pos = k_off + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1)
        o_new = o_acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    n_k = Tk // block_k
    if causal:
        # global causal skip: k sub-blocks starting past this q block's last
        # position contribute nothing (a whole future shard skips entirely)
        lim = (q_off - k_off) + (qi + 1) * block_q
        n_k = jnp.clip((lim + block_k - 1) // block_k, 0, n_k)
    o, m, l = jax.lax.fori_loop(
        0, n_k, body, (oi_ref[:], mi_ref[:][:, 0], li_ref[:][:, 0]))
    oo_ref[:] = o
    mo_ref[:] = m[:, None]
    lo_ref[:] = l[:, None]


def ring_flash_step(q, kb, vb, o, m, l, q_off, k_off, *, causal: bool,
                    scale: float, block_q: int, block_k: int,
                    interpret: bool | None = None):
    """One ring step: fold the arriving K/V shard kb/vb (B, Hkv, T_blk, D)
    into the carried accumulators o (B, H, T, D) f32 / m, l (B, H, T, 1)
    f32 for local queries q (B, H, T, D). q_off/k_off are the shards'
    global sequence offsets (traced: my*T and src*T)."""
    B, H, T, D = q.shape
    Tk = kb.shape[2]
    g = H // kb.shape[1]
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32), jnp.asarray(k_off, jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H, T // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i, off: (b, h, i, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i, off: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, Tk, D), lambda b, h, i, off: (b, h // g, 0, 0)),
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i, off: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, D), lambda b, h, i, off: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_ring_flash_step_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        interpret=_interpret() if interpret is None else interpret,
    )(offs, q, kb, vb, o, m, l)


def _ring_flash_bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, dq_ref, *, block_k: int, causal: bool,
                              scale: float):
    # the flash dq recompute (see _flash_bwd_dq_kernel) with GLOBAL causal
    # positions; lse is the GLOBAL log-sum-exp (all ring steps), so p for
    # this shard's keys is exact and dq contributions just add across steps
    block_q, D = q_ref.shape
    Tk = k_ref.shape[0]
    qi = pl.program_id(2)
    q = q_ref[:]
    do = do_ref[:]
    lse2 = lse_ref[:][:, 0] * LOG2E
    delta = delta_ref[:][:, 0]
    q_off = off_ref[0]
    k_off = off_ref[1]
    q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, dq_acc):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * (scale * LOG2E)
        if causal:
            k_pos = k_off + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq_acc + jax.lax.dot_general(ds.astype(k_blk.dtype), k_blk,
                                            (((1,), (0,)), ((), ())),
                                            preferred_element_type=jnp.float32)

    n_k = Tk // block_k
    if causal:
        lim = (q_off - k_off) + (qi + 1) * block_q
        n_k = jnp.clip((lim + block_k - 1) // block_k, 0, n_k)
    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[:] = dq


def _ring_flash_bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                               delta_ref, dk_ref, dv_ref, *, block_q: int,
                               causal: bool, scale: float):
    # transposed orientation (rows = k positions) per-q-head partials,
    # group-summed outside (the flash GQA backward convention here); global
    # positions via the off prefetch
    block_k, D = k_ref.shape
    Tq = q_ref.shape[0]
    ki = pl.program_id(2)
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    q_off = off_ref[0]
    k_off = off_ref[1]
    k_pos_t = k_off + ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, block_q), 0)

    def body(i, carry):
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse2 = lse_ref[pl.ds(i * block_q, block_q), :][:, 0] * LOG2E
        delta = delta_ref[pl.ds(i * block_q, block_q), :][:, 0]
        q_pos_t = q_off + i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, block_q), 1)
        return _dkv_tile(k_blk, v_blk, q, do, lse2, delta, k_pos_t, q_pos_t,
                         causal, scale, *carry)

    z = jnp.zeros((block_k, D), jnp.float32)
    n_i = Tq // block_q
    if causal:
        # first q tile whose last position reaches this k block
        i0 = jnp.clip((k_off + ki * block_k - q_off) // block_q, 0, n_i)
    else:
        i0 = 0
    dk, dv = jax.lax.fori_loop(i0, n_i, body, (z, z))
    dk_ref[:] = dk
    dv_ref[:] = dv


def ring_flash_bwd_step(q, kb, vb, do, lse, delta, q_off, k_off, *, causal: bool,
                        scale: float, block_q: int, block_k: int,
                        interpret: bool | None = None):
    """One backward ring step: local queries against the arriving shard.
    Returns (dq_contrib (B, H, T, D) f32, dk_contrib/dv_contrib
    (B, Hkv, T_blk, D) f32) — the kv grads are per-q-head partials
    group-summed here before the accumulators ride the ring onward."""
    B, H, T, D = q.shape
    Hkv, Tk = kb.shape[1], kb.shape[2]
    g = H // Hkv
    itp = _interpret() if interpret is None else interpret
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32), jnp.asarray(k_off, jnp.int32)])
    dq = pl.pallas_call(
        functools.partial(_ring_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, T // block_q),
            in_specs=[
                pl.BlockSpec((None, None, block_q, D), lambda b, h, i, off: (b, h, i, 0)),
                pl.BlockSpec((None, None, Tk, D), lambda b, h, i, off: (b, h // g, 0, 0)),
                pl.BlockSpec((None, None, Tk, D), lambda b, h, i, off: (b, h // g, 0, 0)),
                pl.BlockSpec((None, None, block_q, D), lambda b, h, i, off: (b, h, i, 0)),
                pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
                pl.BlockSpec((None, None, block_q, 1), lambda b, h, i, off: (b, h, i, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, block_q, D),
                                   lambda b, h, i, off: (b, h, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), jnp.float32),
        interpret=itp,
    )(offs, q, kb, vb, do, lse, delta)

    dk_p, dv_p = pl.pallas_call(
        functools.partial(_ring_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, Tk // block_k),
            in_specs=[
                pl.BlockSpec((None, None, T, D), lambda b, h, j, off: (b, h, 0, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j, off: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j, off: (b, h // g, j, 0)),
                pl.BlockSpec((None, None, T, D), lambda b, h, j, off: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j, off: (b, h, 0, 0)),
                pl.BlockSpec((None, None, T, 1), lambda b, h, j, off: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j, off: (b, h, j, 0)),
                pl.BlockSpec((None, None, block_k, D), lambda b, h, j, off: (b, h, j, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Tk, D), jnp.float32),
        ],
        interpret=itp,
    )(offs, q, kb, vb, do, lse, delta)
    dk = dk_p.reshape(B, Hkv, g, Tk, D).sum(axis=2)
    dv = dv_p.reshape(B, Hkv, g, Tk, D).sum(axis=2)
    return dq, dk, dv


def ring_flash_supported(q, k, v) -> bool:
    """Checker for the streaming ring path inside dist.ring_attention: TPU
    (TT_RING_KERNEL=1 forces for interpret-mode A/B, =0 never), equal-size
    shards on the flash tiling, and one step's working set — q block + this
    shard's K/V + the f32 carries — within the VMEM budget via the unified
    analysis/memory.py estimate; otherwise the pure-jax GQA-native
    reference ring runs."""
    if pltpu is None:
        return False
    override = os.environ.get("TT_RING_KERNEL")
    if override == "0":
        return False
    if not (_on_tpu() or override == "1"):
        return False
    if getattr(q, "ndim", 0) != 4 or getattr(k, "ndim", 0) != 4:
        return False
    B, H, T, D = q.shape
    Hkv, Tk = k.shape[1], k.shape[2]
    block_q = min(DEFAULT_BLOCK_Q, T)
    block_k = min(DEFAULT_BLOCK_K, Tk)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, Tk, k, v)
    shapes_ok = (
        D <= 512
        and tuple(v.shape) == tuple(k.shape)
        and k.shape[0] == B
        and T == Tk  # equal shards: every device holds T/n rows
        and H % Hkv == 0
        and T % block_q == 0 and Tk % block_k == 0
        and T % 8 == 0
    )
    if not shapes_ok:
        return False
    from ..analysis import budget as _budget

    q_item = jnp.dtype(str(q.dtype).rpartition(".")[2]).itemsize
    kv_item = jnp.dtype(str(k.dtype).rpartition(".")[2]).itemsize
    return _budget.within_vmem(
        _budget.ring_flash_vmem_bytes(block_q, Tk, D, q_item, kv_item))


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, block_q, block_k,
                         interpret):
    B, H, T, D = q.shape
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    o0 = jnp.zeros((B, H, T, D), jnp.float32)
    m0 = jnp.full((B, H, T, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, T, 1), jnp.float32)

    def step(carry, i):
        o, m, l, kb, vb = carry
        src = jax.lax.rem(my - i + n, n)  # device that produced this shard
        o, m, l = ring_flash_step(q, kb, vb, o, m, l, my * T, src * T,
                                  causal=causal, scale=scale, block_q=block_q,
                                  block_k=block_k, interpret=interpret)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, m, l, kb, vb), None

    # i=0 is src == my: the diagonal step that seeds finite running maxima
    # (see the step-order contract above); after n permutes k/v are home
    # again, which is what lets the backward reuse the SAME residency
    (o, m, l, _, _), _ = jax.lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
    l1 = l[..., 0]
    l_safe = jnp.where(l1 == 0.0, 1.0, l1)
    out = (o / l_safe[..., None]).astype(q.dtype)
    lse = (m[..., 0] + jnp.log2(l_safe)) * LN2  # (B, H, T), natural log
    return out, lse


def _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name, causal, scale,
                         block_q, block_k, interpret):
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
                    keepdims=True)  # (B, H, T, 1)
    lse1 = lse[..., None].astype(jnp.float32)
    dq0 = jnp.zeros((B, H, T, D), jnp.float32)
    dkv0 = jnp.zeros((B, Hkv, T, D), jnp.float32)

    def step(carry, i):
        dq, kb, vb, dkb, dvb = carry
        src = jax.lax.rem(my - i + n, n)
        dq_c, dk_c, dv_c = ring_flash_bwd_step(
            q, kb, vb, do, lse1, delta, my * T, src * T, causal=causal,
            scale=scale, block_q=block_q, block_k=block_k, interpret=interpret)
        dq = dq + dq_c
        # the kv-grad accumulators travel WITH their shard: after n permutes
        # both are home with every device's contribution folded in
        dkb = dkb + dk_c
        dvb = dvb + dv_c
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        dkb = jax.lax.ppermute(dkb, axis_name, perm)
        dvb = jax.lax.ppermute(dvb, axis_name, perm)
        return (dq, kb, vb, dkb, dvb), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq0, k, v, dkv0, dkv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.lru_cache(maxsize=None)
def _ring_flash_vjp(axis_name, causal, scale, block_q, block_k, interpret):
    @jax.custom_vjp
    def f(q, k, v):
        out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                      block_q, block_k, interpret)
        return out

    def fwd(q, k, v):
        out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                        block_q, block_k, interpret)
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        return _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name, causal,
                                    scale, block_q, block_k, interpret)

    f.defvjp(fwd, bwd)
    return f


def ring_flash_attention(q, k, v, *, axis_name: str, causal: bool = True,
                         scale=None, interpret: bool | None = None):
    """Streaming ring attention over the named mesh axis: q (B, H, T, D)
    local shard, k/v (B, Hkv, T, D) — GQA-native. Differentiable (custom
    VJP rides the flash backward recompute around the same ring), so
    jax.vjp — and thus the executor's JAX_VJP_FALLBACK — works through it."""
    B, H, T, D = q.shape
    Tk = k.shape[2]
    scale = float(scale) if scale is not None else 1.0 / math.sqrt(D)
    block_q = min(DEFAULT_BLOCK_Q, T)
    block_k = min(DEFAULT_BLOCK_K, Tk)
    block_q, block_k = _cap_blocks_for_dtype(q, block_q, block_k, T, Tk, k, v)
    itp = _interpret() if interpret is None else bool(interpret)
    return _ring_flash_vjp(str(axis_name), bool(causal), scale,
                           int(block_q), int(block_k), itp)(q, k, v)
