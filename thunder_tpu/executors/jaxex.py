"""jaxex: op-by-op JAX executor — every prim lowered 1:1 to jax.numpy/lax.

This is the TPU stack's "always" executor and numerics reference, the role
torchex plays in the reference (thunder/executors/torchex.py:1, ~180
register_implementation calls). All impls are pure jax functions, so any
contiguous region of them is XLA-fusible by the fusion executor."""
from __future__ import annotations

import builtins
import functools
import math
from numbers import Number

import jax
import jax.numpy as jnp
from jax import lax

from ..core import dtypes, prims
from ..core.dtypes import to_jax_dtype
from ..core.prims import PrimIDs
from ..core.proxies import TensorProxy
from ..extend import OperatorExecutor, register_executor, add_always_executor

ex = OperatorExecutor("jax")
register_executor(ex)
add_always_executor(ex)


def _jd(dtype):
    """framework dtype -> jnp dtype, downgrading 64-bit when x64 is disabled."""
    if dtype is None:
        return None
    jd = to_jax_dtype(dtype)
    if not jax.config.jax_enable_x64:
        jd = {jnp.int64: jnp.int32, jnp.uint32: jnp.uint32, jnp.float64: jnp.float32,
              jnp.complex128: jnp.complex64}.get(jd, jd)
    return jd


def _reg(pid, fn):
    ex.register_implementation(pid, fn)
    return fn


# ---- structure / checks ----
_reg(PrimIDs.PRINT, print)
_reg(PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA, prims.check_tensor_shape_and_metadata.python_impl)
_reg(PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE, prims.check_number_type_and_value.python_impl)

# ---- dtype/device ----
_reg(PrimIDs.CONVERT_ELEMENT_TYPE, lambda a, dtype: jnp.asarray(a).astype(_jd(dtype)))
_reg(PrimIDs.DEVICE_PUT, lambda a, device: jax.device_put(a, device.jax_device()))
_reg(PrimIDs.STOP_GRADIENT, lax.stop_gradient)
_reg(PrimIDs.BITCAST, lambda a, dtype: lax.bitcast_convert_type(a, _jd(dtype)))


# ---- factories ----
_reg(PrimIDs.TENSOR_CONSTANT, jnp.asarray)


def _full(shape, fill_value, *, device=None, dtype=None):
    return jnp.full(shape, fill_value, dtype=_jd(dtype))


_reg(PrimIDs.FULL, _full)


def _iota(length, *, start=0, step=1, device=None, dtype=None):
    return jnp.arange(start, start + length * step, step, dtype=_jd(dtype))[:length]


_reg(PrimIDs.IOTA, _iota)


def _uniform(shape, minval, maxval, *, key, device=None, dtype=None):
    return jax.random.uniform(key, tuple(shape), _jd(dtype) or jnp.float32, minval, maxval)


_reg(PrimIDs.UNIFORM, _uniform)


def _normal(shape, mean, std, *, key, device=None, dtype=None):
    return jax.random.normal(key, tuple(shape), _jd(dtype) or jnp.float32) * std + mean


_reg(PrimIDs.NORMAL, _normal)


def _randint(shape, low, high, *, key, device=None, dtype=None):
    return jax.random.randint(key, tuple(shape), low, high, _jd(dtype) or jnp.int32)


_reg(PrimIDs.RANDINT, _randint)


def _rng_split(key):
    k = jax.random.split(key, 2)
    return k[0], k[1]


_reg(PrimIDs.RNG_SPLIT, _rng_split)

# ---- shape ops ----
_reg(PrimIDs.RESHAPE, lambda a, shape: jnp.reshape(a, shape))
_reg(PrimIDs.TRANSPOSE, lambda a, permutation: jnp.transpose(a, permutation))
_reg(PrimIDs.BROADCAST_IN_DIM, lambda a, shape, broadcast_dimensions: lax.broadcast_in_dim(a, shape, broadcast_dimensions))
_reg(PrimIDs.SLICE, lambda a, start_indices, limit_indices, strides=None: lax.slice(a, start_indices, limit_indices, strides))
_reg(PrimIDs.SQUEEZE, lambda a, dims: lax.squeeze(a, dims))
_reg(PrimIDs.CAT, lambda tensors, dim: jnp.concatenate(tensors, axis=dim))


def _pad(a, padding_value, padding_config):
    pv = jnp.asarray(padding_value, dtype=a.dtype) if not hasattr(padding_value, "dtype") else padding_value.astype(a.dtype)
    return lax.pad(a, pv, tuple(tuple(int(x) for x in cfg) for cfg in padding_config))


_reg(PrimIDs.PAD, _pad)
_reg(PrimIDs.FLIP, lambda a, dims: jnp.flip(a, dims))
_reg(PrimIDs.TAKE, lambda a, indices, dim: jnp.take(a, indices, axis=dim))
_reg(PrimIDs.TAKE_ALONG_AXIS, lambda a, indices, dim: jnp.take_along_axis(a, indices, axis=dim))


def _index_add(a, indices, value, dim):
    idx = [builtins.slice(None)] * a.ndim
    idx[dim] = indices
    return a.at[tuple(idx)].add(value)


_reg(PrimIDs.INDEX_ADD, _index_add)


def _scatter_add(a, indices, value, dim):
    return a.at[indices].add(value) if dim == 0 else _scatter_add_general(a, indices, value, dim)


def _scatter_add_general(a, indices, value, dim):
    # torch.scatter_add semantics: indices same rank as a/value
    dnums = jnp.indices(indices.shape)
    gather_idx = list(dnums)
    gather_idx[dim] = indices
    return a.at[tuple(gather_idx)].add(value)


_reg(PrimIDs.SCATTER_ADD, _scatter_add_general)
def _norm_idx(start_indices):
    return tuple(jnp.asarray(i, jnp.int32) for i in start_indices)


_reg(PrimIDs.DYNAMIC_SLICE, lambda a, start_indices, slice_sizes: lax.dynamic_slice(a, _norm_idx(start_indices), slice_sizes))
_reg(PrimIDs.DYNAMIC_UPDATE_SLICE, lambda a, update, start_indices: lax.dynamic_update_slice(a, update, _norm_idx(start_indices)))

# ---- elementwise unary ----
_unary_impls = {
    PrimIDs.ABS: jnp.abs, PrimIDs.NEG: jnp.negative, PrimIDs.EXP: jnp.exp, PrimIDs.EXP2: jnp.exp2,
    PrimIDs.EXPM1: jnp.expm1, PrimIDs.LOG: jnp.log, PrimIDs.LOG1P: jnp.log1p, PrimIDs.LOG2: jnp.log2,
    PrimIDs.SQRT: jnp.sqrt, PrimIDs.RSQRT: lax.rsqrt, PrimIDs.SIN: jnp.sin, PrimIDs.COS: jnp.cos,
    PrimIDs.TAN: jnp.tan, PrimIDs.TANH: jnp.tanh, PrimIDs.ASIN: jnp.arcsin, PrimIDs.ACOS: jnp.arccos,
    PrimIDs.ATAN: jnp.arctan, PrimIDs.SINH: jnp.sinh, PrimIDs.COSH: jnp.cosh, PrimIDs.ASINH: jnp.arcsinh,
    PrimIDs.ACOSH: jnp.arccosh, PrimIDs.ATANH: jnp.arctanh, PrimIDs.ERF: lax.erf, PrimIDs.ERFC: lax.erfc,
    PrimIDs.ERFINV: lax.erf_inv, PrimIDs.FLOOR: jnp.floor, PrimIDs.CEIL: jnp.ceil,
    PrimIDs.ROUND: jnp.round, PrimIDs.TRUNC: jnp.trunc, PrimIDs.SIGN: jnp.sign,
    PrimIDs.ISFINITE: jnp.isfinite, PrimIDs.ISNAN: jnp.isnan, PrimIDs.ISINF: jnp.isinf,
    PrimIDs.RECIPROCAL: jnp.reciprocal, PrimIDs.LOGICAL_NOT: jnp.logical_not,
    PrimIDs.BITWISE_NOT: jnp.invert, PrimIDs.REAL: jnp.real, PrimIDs.IMAG: jnp.imag,
    PrimIDs.LOG10: jnp.log10, PrimIDs.LGAMMA: lax.lgamma, PrimIDs.DIGAMMA: lax.digamma,
    PrimIDs.SIGNBIT: jnp.signbit,
}
for pid, fn in _unary_impls.items():
    _reg(pid, fn)

# float unary on int inputs should produce f32 (framework semantics)
for pid in (PrimIDs.EXP, PrimIDs.LOG, PrimIDs.SQRT, PrimIDs.RSQRT, PrimIDs.SIN, PrimIDs.COS,
            PrimIDs.TANH, PrimIDs.ERF):
    base = ex.get_impl(pid)

    def _floatify(fn):
        def wrapped(a):
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.integer) or jnp.asarray(a).dtype == jnp.bool_:
                a = jnp.asarray(a).astype(jnp.float32)
            return fn(a)

        return wrapped

    _reg(pid, _floatify(base))

# ---- elementwise binary ----
_binary_impls = {
    PrimIDs.ADD: jnp.add, PrimIDs.SUB: jnp.subtract, PrimIDs.MUL: jnp.multiply,
    PrimIDs.DIV: jnp.true_divide, PrimIDs.POW: jnp.power, PrimIDs.FMOD: jnp.fmod,
    PrimIDs.REMAINDER: jnp.remainder, PrimIDs.MAXIMUM: jnp.maximum, PrimIDs.MINIMUM: jnp.minimum,
    PrimIDs.ATAN2: jnp.arctan2, PrimIDs.BITWISE_AND: jnp.bitwise_and,
    PrimIDs.BITWISE_OR: jnp.bitwise_or, PrimIDs.BITWISE_XOR: jnp.bitwise_xor,
    PrimIDs.SHIFT_LEFT: jnp.left_shift, PrimIDs.SHIFT_RIGHT: jnp.right_shift,
    PrimIDs.EQ: jnp.equal, PrimIDs.NE: jnp.not_equal, PrimIDs.LT: jnp.less,
    PrimIDs.LE: jnp.less_equal, PrimIDs.GT: jnp.greater, PrimIDs.GE: jnp.greater_equal,
    PrimIDs.NEXTAFTER: jnp.nextafter, PrimIDs.COPYSIGN: jnp.copysign, PrimIDs.HYPOT: jnp.hypot,
    PrimIDs.GCD: jnp.gcd, PrimIDs.LCM: jnp.lcm,
}
for pid, fn in _binary_impls.items():
    _reg(pid, fn)


def _div_torch(a, b):
    # torch true_divide on ints promotes to float, and clang.true_divide
    # pre-promotes (int_to_float=True) — so float operands take the plain
    # divide. Int operands reach DIV only via clang.floor_divide, whose
    # meta keeps the integer dtype: execute integer (floor) division so the
    # runtime dtype matches the trace (true_divide here returned f32 and
    # broke downstream integer consumers, e.g. gather indices).
    if jnp.issubdtype(jnp.result_type(a, b), jnp.integer):
        return jnp.floor_divide(a, b)
    return jnp.true_divide(a, b)


_reg(PrimIDs.DIV, _div_torch)
_reg(PrimIDs.WHERE, jnp.where)

# ---- reductions ----
_reg(PrimIDs.SUM, lambda a, dims, *, output_dtype=None: jnp.sum(a, axis=dims, dtype=_jd(output_dtype)))
_reg(PrimIDs.PROD, lambda a, dims, *, output_dtype=None: jnp.prod(a, axis=dims, dtype=_jd(output_dtype)))
_reg(PrimIDs.AMAX, lambda a, dims: jnp.max(a, axis=dims))
def _var_impl(a, dims, correction=1):
    n = 1
    for d in dims:
        n *= a.shape[d]
    m = jnp.mean(a, axis=dims, keepdims=True)
    centered = a - m
    sq = (centered * jnp.conj(centered)).real if jnp.iscomplexobj(a) else centered * centered
    # torch divides by max(0, n - correction): inf for over-corrected counts
    return jnp.sum(sq, axis=dims) / max(0, n - correction)


_reg(PrimIDs.VAR, _var_impl)
_reg(PrimIDs.AMIN, lambda a, dims: jnp.min(a, axis=dims))
_reg(PrimIDs.ARGMAX, lambda a, dim: jnp.argmax(a, axis=dim).astype(_jd(dtypes.int64)))
_reg(PrimIDs.ARGMIN, lambda a, dim: jnp.argmin(a, axis=dim).astype(_jd(dtypes.int64)))
_reg(PrimIDs.ANY, lambda a, dims: jnp.any(a, axis=dims))
_reg(PrimIDs.CUMSUM, lambda a, dim: jnp.cumsum(a, axis=dim))
_reg(PrimIDs.CUMPROD, lambda a, dim: jnp.cumprod(a, axis=dim))


def _cummax(a, dim):
    # joint (value, index) scan so indices stay correct through NaNs and ties
    # (torch: NaN propagates and carries its position; ties keep the latest).
    dim = dim % a.ndim
    idx = jnp.arange(a.shape[dim], dtype=jnp.int32)
    idx = jnp.broadcast_to(idx.reshape((-1,) + (1,) * (a.ndim - 1 - dim)), a.shape)
    is_float = jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)

    def combine(x, y):
        xv, xi = x
        yv, yi = y
        take_y = yv >= xv
        if is_float:
            # NaN absorbs (a NaN on the right always wins, incl. over an
            # earlier NaN); a non-NaN right never beats a NaN left
            take_y = jnp.logical_or(jnp.isnan(yv), jnp.logical_and(take_y, ~jnp.isnan(xv)))
        return jnp.where(take_y, yv, xv), jnp.where(take_y, yi, xi)

    values, indices = lax.associative_scan(combine, (a, idx), axis=dim)
    return values, indices


_reg(PrimIDs.CUMMAX, _cummax)


def _reduce_window(a, window_dims, strides, padding, *, op="max"):
    import numpy as np

    dt = jnp.asarray(a).dtype
    is_float = jnp.issubdtype(dt, jnp.floating)
    init, fn = {
        "max": (-np.inf if is_float else np.iinfo(dt).min, lax.max),
        "min": (np.inf if is_float else np.iinfo(dt).max, lax.min),
        "sum": (0, lax.add),
    }[op]
    # concrete numpy scalar init: required for jax's monoid fast-path, which
    # is what makes reduce_window reverse-mode differentiable
    init = np.array(init, dt)[()]
    return lax.reduce_window(a, init, fn, tuple(int(w) for w in window_dims),
                             tuple(int(s) for s in strides), tuple((int(l), int(h)) for l, h in padding))


_reg(PrimIDs.REDUCE_WINDOW, _reduce_window)
_reg(PrimIDs.TOPK, lambda a, k, dim: _topk(a, k, dim))


def _topk(a, k, dim):
    if dim != a.ndim - 1 and dim != -1:
        a_m = jnp.moveaxis(a, dim, -1)
        v, i = lax.top_k(a_m, k)
        return jnp.moveaxis(v, -1, dim), jnp.moveaxis(i, -1, dim).astype(jnp.int32)
    v, i = lax.top_k(a, k)
    return v, i.astype(jnp.int32)


_reg(PrimIDs.ARGSORT, lambda a, dim, descending=False: (
    jnp.argsort(-a if descending else a, axis=dim).astype(jnp.int32)))
_reg(PrimIDs.SORT, lambda a, dim, descending=False: (-jnp.sort(-a, axis=dim) if descending else jnp.sort(a, axis=dim)))


# ---- linear algebra / NN: MXU ops with bf16-friendly accumulation ----
def _matmul(a, b):
    # accumulate in f32 on the MXU regardless of input precision
    return jnp.matmul(a, b, preferred_element_type=_preferred_acc(a))


def _preferred_acc(a):
    d = jnp.asarray(a).dtype
    if d in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return None


def _matmul_cast(a, b):
    out = jnp.matmul(a, b, preferred_element_type=_preferred_acc(a))
    return out.astype(jnp.asarray(a).dtype)


_reg(PrimIDs.MATMUL, _matmul_cast)


def _linear(a, w, bias=None):
    out = jnp.matmul(a, w.T, preferred_element_type=_preferred_acc(a)).astype(jnp.asarray(a).dtype)
    return out


_reg(PrimIDs.LINEAR, _linear)


def _convolution(a, weight, bias, stride, padding, dilation, groups):
    n_spatial = a.ndim - 2
    dim_chars = "DHW"[-n_spatial:] if n_spatial <= 3 else None
    lhs_spec = "NC" + dim_chars
    rhs_spec = "OI" + dim_chars
    out = lax.conv_general_dilated(
        a, weight,
        window_strides=tuple(stride),
        padding=tuple((p, p) for p in padding),
        rhs_dilation=tuple(dilation),
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=groups,
        preferred_element_type=_preferred_acc(a),
    ).astype(jnp.asarray(a).dtype)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * n_spatial)
    return out


_reg(PrimIDs.CONVOLUTION, _convolution)


def _conv_transpose(a, weight, bias, stride, padding, output_padding, dilation, groups):
    # torch layout: a (N, Cin, *S), weight (Cin, Cout/groups, *K).
    # Implemented as the gradient of a forward conv (lhs-dilated conv), which
    # matches torch.nn.functional.conv_transpose semantics exactly.
    n_spatial = a.ndim - 2
    dim_chars = "DHW"[-n_spatial:]
    lhs_spec = "NC" + dim_chars
    rhs_spec = "IO" + dim_chars
    k_eff = [(weight.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n_spatial)]
    pads = tuple(
        (k_eff[i] - 1 - padding[i], k_eff[i] - 1 - padding[i] + output_padding[i])
        for i in range(n_spatial)
    )
    w = jnp.flip(weight, axis=tuple(range(2, 2 + n_spatial)))
    if groups > 1:
        # regroup (Cin, Cout/g, *K) -> feature groups over output channels
        cin, coutg = w.shape[0], w.shape[1]
        w = w.reshape((groups, cin // groups, coutg) + w.shape[2:])
        w = jnp.moveaxis(w, 2, 1).reshape((groups * coutg, cin // groups) + w.shape[3:])
        rhs_spec = "OI" + dim_chars
    out = lax.conv_general_dilated(
        a, w,
        window_strides=(1,) * n_spatial,
        padding=pads,
        lhs_dilation=tuple(stride),
        rhs_dilation=tuple(dilation),
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=groups,
        preferred_element_type=_preferred_acc(a),
    ).astype(jnp.asarray(a).dtype)
    if bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * n_spatial)
    return out


_reg(PrimIDs.CONV_TRANSPOSE, _conv_transpose)
_reg(PrimIDs.EMBEDDING, lambda indices, weight: jnp.take(weight, indices, axis=0))


def _einsum_impl(spec, *operands):
    return jnp.einsum(spec, *operands, preferred_element_type=_preferred_acc(operands[0])).astype(
        jnp.asarray(operands[0]).dtype)


_reg(PrimIDs.EINSUM, _einsum_impl)


def _scatter(a, indices, value, dim):
    return jnp.put_along_axis(a, indices, value, axis=dim, inplace=False)


_reg(PrimIDs.SCATTER, _scatter)


def _grouped_mm(a, b, group_sizes):
    return lax.ragged_dot(a, b, group_sizes.astype(jnp.int32),
                          preferred_element_type=_preferred_acc(a)).astype(jnp.asarray(a).dtype)


_reg(PrimIDs.GROUPED_MM, _grouped_mm)

# ---- memory / interop ----
_reg(PrimIDs.ITEM, lambda a: a.item())


def _copy_with_setitem(a, key, value):
    return a.at[key].set(value)


_reg(PrimIDs.COPY_WITH_SETITEM, _copy_with_setitem)
_reg(PrimIDs.UPDATE_ALIASES, lambda tensors: tuple(tensors))


# ---------------------------------------------------------------------------
# eager escape hatch: execute a symbol on concrete values by tracing it
# ---------------------------------------------------------------------------


def eager_execute(sym, *args, **kwargs):
    from ..core.proxies import proxy_from_jax, Proxy
    from ..core.trace import TraceCtx, tracectx
    from ..core import prims as _p

    trc = TraceCtx(None)
    flat_concrete = []
    with tracectx(trc):
        def proxify(x):
            if isinstance(x, (Number, str, type(None), tuple, list, dict, dtypes.dtype)):
                return x
            p = proxy_from_jax(x)
            if isinstance(p, Proxy) and not isinstance(x, Proxy):
                flat_concrete.append((p, x))
            return p

        pargs = [proxify(a) for a in args]
        pkwargs = {k: proxify(v) for k, v in kwargs.items()}
        out = sym(*pargs, **pkwargs)
        _p.python_return(out)
    trc.args = tuple(p for p, _ in flat_concrete)
    from .passes import transform_for_execution

    trc = transform_for_execution(trc, [ex])
    fn = trc.python_callable()
    return fn(*[v for _, v in flat_concrete])
