"""Execution passes: executor claiming and fusion.

Re-design of reference thunder/executors/passes.py:32-288. Priority-order
claiming: executor execution-transform → executor impl at the bsym's level →
descend into subsymbols → error on unclaimed prims. Then each FusionExecutor's
fusion_pass groups claimed ops into XLA-compiled regions."""
from __future__ import annotations

import time
from typing import Sequence

from ..analysis import manager as _an
from ..core.prims import PrimIDs
from ..core.symbol import BoundSymbol, OpTags
from ..core.trace import TraceCtx, from_trace, tracectx
from ..extend import Executor, FusionExecutor, get_always_executors
from ..observability import events as _obs
from ..observability import metrics as _obs_metrics

_STRUCTURAL = (PrimIDs.RETURN, PrimIDs.DEL, PrimIDs.COMMENT, PrimIDs.UNPACK_TRIVIAL)


def transform_for_execution(trace: TraceCtx, executors: Sequence[Executor],
                            *, check_traces: bool = False) -> TraceCtx:
    start = time.perf_counter()
    executors = list(executors)
    for al in get_always_executors():
        if al not in executors:
            executors.append(al)

    out_bsyms: list[BoundSymbol] = []

    def lower(bsym: BoundSymbol):
        if bsym.sym.id in _STRUCTURAL:
            out_bsyms.append(bsym)
            return
        if bsym.sym.python_impl is not None and bsym.impl is None and bsym.sym.executor is None:
            # pure-python symbols (prologue checks) execute directly
            out_bsyms.append(bsym.with_impl(bsym.sym.python_impl))
            return
        if bsym.sym.executor is not None:
            # already executor-bound (e.g. registered operator symbols)
            impl = bsym.sym.executor.get_impl(bsym.sym.id)
            if impl is not None:
                out_bsyms.append(bsym.with_impl(impl))
                return
        for ex in executors:
            if ex.is_fusion_executor():
                continue
            if ex.can_execute(bsym):
                info = ex.implmap.get(bsym.sym.id)
                if info is not None and info.execution_transform is not None:
                    # re-trace the replacement into prims/ops of the executor
                    new_trc = TraceCtx(None)
                    with tracectx(new_trc):
                        info.execution_transform(*bsym.args, **bsym.kwargs)
                    for sub in new_trc.bound_symbols:
                        lower(sub)
                    return
                impl = ex.get_impl(bsym.sym.id)
                if impl is not None:
                    out_bsyms.append(bsym.with_impl(impl))
                    return
        if bsym.subsymbols:
            for sub in bsym.subsymbols:
                lower(sub)
            return
        if not bsym.sym.is_prim:
            # composite that recorded nothing: a pure pass-through (e.g. a
            # full-range getitem) — outputs are existing proxies, nothing to run
            out_names = {o.name for o in bsym.flat_proxy_outs()}
            in_names = {a.name for a in bsym.flat_proxy_args()}
            if out_names <= in_names:
                return
        raise RuntimeError(
            f"no executor can run {bsym.sym.name} (id={bsym.sym.id}); "
            f"tried {[e.name for e in executors]}"
        )

    with _obs.span("claim", bsyms=len(trace.bound_symbols)) as sp:
        for bsym in trace.bound_symbols:
            lower(bsym)
        sp.set(claimed=len(out_bsyms))

    claimed = from_trace(trace)
    claimed.bound_symbols = out_bsyms
    claimed.set_provenance(
        f"Transform for execution (took {(time.perf_counter()-start)*1000:.2f} ms)"
    )
    # pass-interposed verification (TT_CHECK_TRACES=1 / debug_options): the
    # claim pass and every fusion pass verify their output, so a violation
    # is attributed to the exact pass that introduced it
    where = trace.name_of_fn()
    _an.checkpoint("executor:claim", claimed, before=trace, where=where,
                   force=check_traces)

    for ex in executors:
        if isinstance(ex, FusionExecutor) or ex.is_fusion_executor():
            with _obs.span(f"fusion:{ex.name}") as sp:
                pre_fusion = claimed
                claimed = ex.fusion_pass(claimed)
                regions = [b for b in claimed.bound_symbols if b.sym.executor is ex]
                sp.set(regions=len(regions))
            _obs_metrics.record_fusion(ex.name, len(regions),
                                       sum(len(b.subsymbols) for b in regions))
            _an.checkpoint(f"executor:fusion:{ex.name}", claimed,
                           before=pre_fusion, where=where, force=check_traces)

    # region-name <-> symbol registry: every fusion region formed above is
    # registered (name -> member bsym ids + flops/bytes cost) so device
    # profiles (observability/profiler.py) can join measured device time
    # back to the trace symbols the region was built from
    from ..observability import profiler as _obs_profiler

    _obs_profiler.register_trace_regions(claimed)
    # region handoff to the compile service: with the service enabled
    # (TT_PARALLEL_COMPILE=1 or an artifact store configured), independent
    # regions lower + XLA-compile concurrently NOW — on a worker pool, from
    # the store when warm — instead of serially at first dispatch
    # (compile_service/parallel_compile.py; a no-op by default on CPU)
    from ..compile_service import parallel_compile as _pc

    _pc.maybe_prewarm(claimed, where=where)
    # eager frees for op-by-op execution (reference passes.py:261); fused
    # regions don't need it but the DELs between them are harmless
    from ..core.transform_common import del_last_used

    final = del_last_used(claimed)
    _an.checkpoint("executor:del_last_used", final, before=claimed, where=where,
                   force=check_traces)
    return final
