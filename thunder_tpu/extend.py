"""Executor/extension system: the pluggable backend registry.

Re-design of reference thunder/extend/__init__.py:53-659. Executors claim
BoundSymbols at any level of the hierarchy: OperatorExecutors provide concrete
implementations per symbol id; FusionExecutors group claimed regions into
compiled fusions (here: ``jax.jit`` → XLA, the TPU analog of nvFuser).
``register_operator`` remains *the* extension point for custom kernels
(e.g. Pallas flash-attention registering against ``sdpa``)."""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from .core.symbol import BoundSymbol, Symbol
from .core.trace import TraceCtx


class ImplInfo:
    __slots__ = ("symbol", "execution_transform", "checker", "grad_transform")

    def __init__(self, symbol=None, execution_transform=None, checker=None, grad_transform=None):
        self.symbol = symbol
        self.execution_transform = execution_transform  # fn(*args, **kwargs) -> proxies, traced replacement
        self.checker = checker  # fn(*args, **kwargs) -> bool
        self.grad_transform = grad_transform  # executor-claimed grads (reference autodiff.py:28-40 priority)


class Executor:
    def __init__(self, name: str, *, version: str = "0.1"):
        self.name = name
        self.version = version
        self.implmap: dict[Any, ImplInfo] = {}
        # concrete callables per symbol id (what generated code invokes)
        self.opmap: dict[Any, Callable] = {}

    def __repr__(self) -> str:
        return f"<Executor {self.name}>"

    def can_execute(self, bsym: BoundSymbol) -> bool:
        info = self.implmap.get(bsym.sym.id)
        if info is None:
            return False
        if info.checker is not None:
            try:
                return bool(info.checker(*bsym.args, **bsym.kwargs))
            except Exception:
                return False
        return True

    def get_impl(self, sym_id) -> Optional[Callable]:
        return self.opmap.get(sym_id)

    def get_grad_transform(self, sym_id):
        info = self.implmap.get(sym_id)
        return info.grad_transform if info else None

    def is_fusion_executor(self) -> bool:
        return False


class OperatorExecutor(Executor):
    def register_operator(self, name: str, *, meta: Callable | None = None, fn: Callable,
                          replaces=None, tags=()) -> Symbol:
        """Create a Symbol backed by a concrete impl (reference extend/__init__.py:206
        OperatorExecutor.register_operator — the custom-kernel extension point)."""
        sym = Symbol(name, meta, id=f"{self.name}.{name}", is_prim=True, module=self.name,
                     executor=self, tags=tags)
        self.opmap[sym.id] = fn
        self.implmap[sym.id] = ImplInfo(symbol=sym)
        if replaces is not None:
            rep_ids = replaces if isinstance(replaces, (tuple, list)) else (replaces,)
            for rid in rep_ids:
                rid = rid.id if isinstance(rid, Symbol) else rid
                self.opmap[rid] = fn
                self.implmap[rid] = ImplInfo(symbol=sym)
        return sym

    def register_implementation(self, sym_or_id, fn: Callable, *, checker=None, grad_transform=None,
                                execution_transform=None) -> None:
        sym_id = sym_or_id.id if isinstance(sym_or_id, Symbol) else sym_or_id
        self.opmap[sym_id] = fn
        self.implmap[sym_id] = ImplInfo(checker=checker, grad_transform=grad_transform,
                                        execution_transform=execution_transform)


class FusionExecutor(Executor):
    def is_fusion_executor(self) -> bool:
        return True

    def fusion_pass(self, trace: TraceCtx) -> TraceCtx:
        raise NotImplementedError


class TemporaryExecutor(OperatorExecutor):
    """Per-jit ad-hoc ops for opaque callables (reference extend/__init__.py:356)."""

    _counter = 0

    def __init__(self):
        TemporaryExecutor._counter += 1
        super().__init__(f"__ad_hoc_{TemporaryExecutor._counter}")


class StatefulExecutor(OperatorExecutor):
    """Executor whose ops carry persistent state objects across calls
    (reference extend/__init__.py:284 — TransformerEngine's fp8 recipe state).
    `register_stateful_operator` binds a state factory; the state instance is
    created at claim time and threaded into every invocation."""

    def __init__(self, name: str):
        super().__init__(name)
        self._state_factories: dict = {}
        self._states: dict = {}

    def register_stateful_operator(self, name: str, state_factory, *, meta, fn, replaces=None) -> Symbol:
        sym = self.register_operator(name, meta=meta, fn=self._bind_state(name, fn), replaces=replaces)
        self._state_factories[sym.id] = state_factory
        return sym

    def _bind_state(self, name: str, fn):
        def wrapped(*args, **kwargs):
            sid = f"{self.name}.{name}"
            state = self._states.get(sid)
            if state is None:
                state = self._state_factories[sid]()
                self._states[sid] = state
            return fn(state, *args, **kwargs)

        return wrapped


def single_op_executor(name: str, sym_name: str, *, meta, fn, replaces=None) -> OperatorExecutor:
    """Create+register a one-op executor (reference extend/__init__.py:459)."""
    ex = OperatorExecutor(name)
    ex.register_operator(sym_name, meta=meta, fn=fn, replaces=replaces)
    register_executor(ex)
    return ex


def deregister_executor(name_or_ex) -> None:
    name = name_or_ex.name if isinstance(name_or_ex, Executor) else name_or_ex
    _executor_registry.pop(name, None)
    for lst in (_default_executors, _always_executors):
        for e in list(lst):
            if e.name == name:
                lst.remove(e)


# ---------------------------------------------------------------------------
# global registry (reference extend/__init__.py:525-659)
# ---------------------------------------------------------------------------

_executor_registry: dict[str, Executor] = {}
_default_executors: list[Executor] = []
_always_executors: list[Executor] = []


def register_executor(ex: Executor) -> Executor:
    _executor_registry[ex.name] = ex
    return ex


def get_executor(name: str) -> Executor:
    ex = _executor_registry.get(name)
    if ex is None:
        raise LookupError(f"unknown executor '{name}' (known: {sorted(_executor_registry)})")
    return ex


def get_all_executors() -> tuple[Executor, ...]:
    return tuple(_executor_registry.values())


def set_default_executors(exs: Sequence[Executor]) -> None:
    _default_executors.clear()
    _default_executors.extend(exs)


def get_default_executors() -> tuple[Executor, ...]:
    return tuple(_default_executors)


def set_always_executors(exs: Sequence[Executor]) -> None:
    _always_executors.clear()
    _always_executors.extend(exs)


def get_always_executors() -> tuple[Executor, ...]:
    return tuple(_always_executors)


def resolve_executors(executors) -> tuple[Executor, ...]:
    if executors is None:
        return get_default_executors()
    out = []
    for e in executors:
        if isinstance(e, Executor):
            out.append(e)
        elif isinstance(e, str):
            out.append(get_executor(e))
        else:
            raise TypeError(f"cannot resolve executor {e!r}")
    return tuple(out)


def add_always_executor(ex: Executor) -> None:
    if ex not in _always_executors:
        _always_executors.append(ex)
