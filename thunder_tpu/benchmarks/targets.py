"""Microbenchmark suite: per-op / per-block targets.

Counterpart of reference thunder/benchmarks/targets.py:190-1010 (LitGPT GELU /
SwiGLU / RMSNorm / SDPA / MLP / QKV+RoPE, nanoGPT blocks, full GPTs). Run as
pytest (`pytest thunder_tpu/benchmarks/targets.py --benchmark-only` style) or
directly: `python -m thunder_tpu.benchmarks.targets [pattern]`.

Every target derives its shapes through ``_d()`` and its model configs through
the ``_*_cfg`` helpers, so the CPU smoke test can clamp the whole suite to
tiny shapes (``_CLAMP``) and run all targets end-to-end — no hard-coded
literals that break under clamping."""
from __future__ import annotations

import math
import sys
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

import thunder_tpu as tt
from thunder_tpu import nn, optim
from thunder_tpu.ops import ltorch

# smoke mode: when set, every shape dimension is capped here and model
# configs collapse to their tiny "test" variants — the CPU suite runs all
# targets end-to-end in seconds (real timing happens on chip, unclamped)
_CLAMP: int | None = None


def _d(n: int) -> int:
    """A shape dimension, capped in smoke mode."""
    return n if _CLAMP is None else min(n, _CLAMP)


def _litgpt_cfg(name: str, **overrides):
    from thunder_tpu.models.litgpt import Config

    if _CLAMP is not None:
        return Config.from_name("tiny-llama2")
    return Config.from_name(name, **overrides)


def _nanogpt_cfg(name: str):
    from thunder_tpu.models.nanogpt import configs

    return configs["test" if _CLAMP is not None else name]


def _force(out):
    # a value READ is the only reliable device sync over the axon tunnel
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf)


def _timeit(fn, *args, iters=20, warmup=3) -> float:
    for _ in range(warmup):
        _force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _force(out)
    return (time.perf_counter() - t0) / iters


def _tensor(rng, shape, dtype=jnp.bfloat16):
    return jnp.asarray(rng.randn(*shape), dtype)


BENCHMARKS: dict[str, Callable] = {}

# executor mode for the current run: 'fused' (XLA regions, default) or
# 'opbyop' (per-prim jaxex dispatch) — the reference's per-executor benchmark
# matrix (thunder/benchmarks/targets.py:190-1010 runs each target under
# eager/torch.compile/thunder(+nvfuser...))
_MODE = "fused"


def _jit(fn, **kw):
    if _MODE == "opbyop":
        kw["disable_fusion"] = True
    return tt.jit(fn, **kw)


def register(name):
    def deco(fn):
        if name in BENCHMARKS:
            raise ValueError(f"benchmark target '{name}' is already registered")
        BENCHMARKS[name] = fn
        return fn

    return deco


@register("litgpt_gelu")
def bench_gelu(rng):
    x = _tensor(rng, (_d(16), _d(2048), _d(4096)))
    cf = _jit(lambda x: ltorch.gelu(x, approximate="tanh"))
    return _timeit(cf, x)


@register("litgpt_swiglu")
def bench_swiglu(rng):
    gate = _tensor(rng, (_d(8), _d(2048), _d(11008)))
    up = _tensor(rng, (_d(8), _d(2048), _d(11008)))
    cf = _jit(lambda g, u: ltorch.silu(g) * u)
    return _timeit(cf, gate, up)


@register("litgpt_rmsnorm")
def bench_rmsnorm(rng):
    D = _d(4096)
    x = _tensor(rng, (_d(16), _d(2048), D))
    w = jnp.ones((D,), jnp.bfloat16)
    cf = _jit(lambda x, w: ltorch.rms_norm(x, (D,), w))
    return _timeit(cf, x, w)


@register("litgpt_sdpa")
def bench_sdpa(rng):
    B, H, T, D = _d(8), _d(32), _d(2048), _d(128)
    q = _tensor(rng, (B, H, T, D))
    k = _tensor(rng, (B, H, T, D))
    v = _tensor(rng, (B, H, T, D))
    cf = _jit(lambda q, k, v: ltorch.sdpa(q, k, v, is_causal=True))
    return _timeit(cf, q, k, v, iters=10)


@register("litgpt_mlp")
def bench_mlp(rng):
    from thunder_tpu.models.litgpt import LLaMAMLP

    cfg = _litgpt_cfg("Llama-2-7b-hf")
    mlp = LLaMAMLP(cfg, dtype=jnp.bfloat16)
    tm = _jit(mlp)
    x = _tensor(rng, (_d(4), min(_d(2048), cfg.block_size), cfg.n_embd))
    return _timeit(tm, x, iters=10)


@register("nanogpt_block")
def bench_nanogpt_block(rng):
    from thunder_tpu.models.nanogpt import NanoBlock

    cfg = _nanogpt_cfg("gpt2")
    blk = NanoBlock(cfg, dtype=jnp.bfloat16)
    tm = _jit(blk)
    x = _tensor(rng, (_d(8), min(_d(1024), cfg.block_size), cfg.n_embd))
    return _timeit(tm, x, iters=10)


@register("nanogpt_gpt2_fwd")
def bench_gpt2_fwd(rng):
    from thunder_tpu.models.nanogpt import NanoGPT

    cfg = _nanogpt_cfg("gpt2")
    model = NanoGPT(cfg, dtype=jnp.bfloat16)
    tm = _jit(model)
    T = min(_d(1024), cfg.block_size)
    idx = jnp.asarray(rng.randint(0, cfg.vocab_size, (_d(4), T)), jnp.int32)
    return _timeit(tm, idx, iters=5)


@register("litgpt_qkv_rope")
def bench_qkv_rope(rng):
    """QKV projection + split + RoPE (reference targets.py litgpt qkv+rope)."""
    from thunder_tpu.models.litgpt import build_rope_cache, _apply_rope

    cfg = _litgpt_cfg("Llama-2-7b-hf")
    T = min(_d(2048), cfg.block_size)
    w = _tensor(rng, ((cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size, cfg.n_embd))
    x = _tensor(rng, (1, T, cfg.n_embd))
    cos, sin = build_rope_cache(T, cfg.rope_n_elem, cfg.rope_base, jnp.bfloat16)

    def qkv_rope(x, w, cos, sin):
        B = x.shape[0]
        nh, ng, hs = cfg.n_head, cfg.n_query_groups, cfg.head_size
        qkv = ltorch.reshape(ltorch.linear(x, w), (B, T, ng, nh // ng + 2, hs))
        q = ltorch.reshape(qkv[:, :, :, : nh // ng, :], (B, T, nh, hs))
        q = ltorch.permute(q, (0, 2, 1, 3))
        return _apply_rope(q, cos, sin, cfg.rope_n_elem)

    cf = _jit(qkv_rope)
    return _timeit(cf, x, w, cos, sin, iters=10)


@register("fused_cross_entropy")
def bench_cross_entropy(rng):
    N, V = _d(8192), _d(32000)
    logits = _tensor(rng, (N, V), jnp.float32)
    tgt = jnp.asarray(rng.randint(0, V, (N,)), jnp.int32)
    cf = _jit(lambda l, t: ltorch.cross_entropy(l, t))
    return _timeit(cf, logits, tgt, iters=10)


@register("train_step_tiny_gpt")
def bench_train_step(rng):
    from thunder_tpu.models.litgpt import GPTForCausalLM
    from thunder_tpu.training import TrainStep

    cfg = _litgpt_cfg("tiny-llama2")
    step = TrainStep(GPTForCausalLM(cfg), optim.AdamW(lr=1e-4))
    T = min(_d(128), cfg.block_size)
    idx = jnp.asarray(rng.randint(0, cfg.vocab_size, (_d(4), T)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, cfg.vocab_size, (_d(4), T)), jnp.int32)
    step(idx, tgt)  # compile

    def run(i, t):
        return step(i, t)

    return _timeit(run, idx, tgt, iters=10)


@register("resnet50_fwd")
def bench_resnet50(rng):
    from thunder_tpu.models.resnet import build

    model = build("test" if _CLAMP is not None else "resnet50", dtype=jnp.bfloat16)
    tm = _jit(model)
    x = _tensor(rng, (_d(8), 3, _d(224), _d(224)))
    return _timeit(tm, x, iters=5)


@register("moe_block")
def bench_moe_block(rng):
    from thunder_tpu.models.moe import MoEConfig, MoEMLP

    cfg = MoEConfig(n_embd=_d(1024), n_expert=8, n_expert_per_token=2)
    mlp = MoEMLP(cfg, dtype=jnp.bfloat16)
    tm = _jit(mlp)
    x = _tensor(rng, (_d(8), _d(512), cfg.n_embd))
    return _timeit(tm, x, iters=10)


@register("vit_b16_fwd")
def bench_vit(rng):
    from thunder_tpu.models.vit import ViT, configs

    cfg = configs["test" if _CLAMP is not None else "vit-b16"]
    model = ViT(cfg, dtype=jnp.bfloat16)
    tm = _jit(model)
    x = _tensor(rng, (_d(8), cfg.channels, cfg.image_size, cfg.image_size))
    return _timeit(tm, x, iters=5)


@register("llama2_7b_attention")
def bench_llama2_7b_attention(rng):
    """One Llama-2-7B attention layer at full dims (reference targets.py
    llama2 7B attention target)."""
    from thunder_tpu.models.litgpt import CausalSelfAttention, build_rope_cache

    cfg = _litgpt_cfg("Llama-2-7b-hf", block_size=2048)
    attn = CausalSelfAttention(cfg, dtype=jnp.bfloat16)
    tm = _jit(attn)
    T = min(_d(2048), cfg.block_size)
    x = _tensor(rng, (1, T, cfg.n_embd))
    cos, sin = build_rope_cache(T, cfg.rope_n_elem, cfg.rope_base, jnp.bfloat16)
    return _timeit(tm, x, cos, sin, iters=5)


@register("llama_mlp_7b")
def bench_llama_mlp_7b(rng):
    from thunder_tpu.models.litgpt import LLaMAMLP

    cfg = _litgpt_cfg("Llama-2-7b-hf")
    mlp = LLaMAMLP(cfg, dtype=jnp.bfloat16)
    tm = _jit(mlp)
    x = _tensor(rng, (1, min(_d(2048), cfg.block_size), cfg.n_embd))
    return _timeit(tm, x, iters=5)


@register("gpt2_xl_block")
def bench_gpt2_xl_block(rng):
    """GPT-2 XL dims block fwd (reference nanogpt/gpt2-xl family)."""
    from thunder_tpu.models.litgpt import Block, build_rope_cache

    cfg = _litgpt_cfg("nanogpt-124m", n_embd=1600, n_head=25, block_size=1024)
    blk = Block(cfg, dtype=jnp.bfloat16)
    tm = _jit(blk)
    T = min(_d(1024), cfg.block_size)
    x = _tensor(rng, (_d(4), T, cfg.n_embd))
    cos, sin = build_rope_cache(T, cfg.rope_n_elem, cfg.rope_base, jnp.bfloat16)
    return _timeit(tm, x, cos, sin, iters=5)


@register("hf_gpt2_module")
def bench_hf_gpt2(rng):
    """HF GPT-2 through the torch interop frontend (reference
    test_hf_transformers benchmark family)."""
    try:
        import torch
        from transformers import GPT2Config, GPT2LMHeadModel
    except Exception:
        return float("nan")
    V, T = _d(50257), _d(512)
    cfg = GPT2Config(n_layer=2 if _CLAMP else 4, n_head=8, n_embd=_d(512),
                     vocab_size=V, n_positions=T, use_cache=False)
    torch.manual_seed(0)
    model = GPT2LMHeadModel(cfg).eval()
    ctm = tt.jit(model)
    ids = jnp.asarray(rng.randint(0, V, (_d(4), T)), jnp.int32)

    def run(i):
        out = ctm(input_ids=i, use_cache=False)
        return out["logits"] if isinstance(out, dict) else out[0]

    return _timeit(run, ids, iters=5)


@register("hf_llama_module")
def bench_hf_llama(rng):
    try:
        import torch
        from transformers import LlamaConfig, LlamaForCausalLM
    except Exception:
        return float("nan")
    V, T = _d(32000), _d(512)
    cfg = LlamaConfig(vocab_size=V, hidden_size=_d(512),
                      intermediate_size=_d(1376),
                      num_hidden_layers=2 if _CLAMP else 4,
                      num_attention_heads=8, num_key_value_heads=8,
                      use_cache=False, max_position_embeddings=_d(1024))
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    ctm = tt.jit(model)
    ids = jnp.asarray(rng.randint(0, V, (_d(2), T)), jnp.int32)

    def run(i):
        out = ctm(input_ids=i)
        return out["logits"] if isinstance(out, dict) else out[0]

    return _timeit(run, ids, iters=5)


@register("adamw_update_124m")
def bench_adamw_update(rng):
    """Fused AdamW over a 124M-param tree — isolates the optimizer fusion
    cost seen in the llama-350m profile. Absolute numbers on the axon tunnel
    include per-call dispatch overhead (~50 ms); inside a TrainStep the
    update fuses into the one whole-step program."""
    from thunder_tpu import optim

    # few large tensors: per-arg dispatch marshaling on the tunnel would
    # otherwise dominate (the real step passes params as one fused program)
    shapes = [(_d(50304), _d(768)), (_d(12), _d(768), _d(3072)),
              (_d(12), _d(3072), _d(768)), (_d(48), _d(768), _d(768))]
    params = {f"p{i}": _tensor(rng, s, jnp.float32) for i, s in enumerate(shapes)}
    grads = {k: _tensor(rng, v.shape, jnp.float32) for k, v in params.items()}
    opt = optim.AdamW(lr=1e-4)
    state = opt.init(params)
    # no donation: the bench reuses the same buffers every iteration
    step = jax.jit(opt.update)

    def run(p, g, st):
        newp, newst = step(p, g, st)
        return newp["p0"]

    return _timeit(run, params, grads, state, iters=10)


@register("embedding_lmhead")
def bench_embedding_lmhead(rng):
    """Embedding gather + LM-head matmul + fused xent — the vocab-bound tail
    of every LM step."""
    V, D, N = _d(32000), _d(1024), _d(8192)
    wte = _tensor(rng, (V, D))
    ids = jnp.asarray(rng.randint(0, V, (N,)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, V, (N,)), jnp.int32)

    def fn(wte, ids, tgt):
        h = ltorch.embedding(ids, wte)
        logits = ltorch.matmul(h, ltorch.transpose(wte, 0, 1))
        return ltorch.cross_entropy(logits, tgt)

    cf = _jit(fn)
    return _timeit(cf, wte, ids, tgt, iters=5)


@register("layer_norm_bwd")
def bench_layer_norm_bwd(rng):
    N, D = _d(8192), _d(1024)
    x = _tensor(rng, (N, D), jnp.float32)
    w = _tensor(rng, (D,), jnp.float32)
    b = _tensor(rng, (D,), jnp.float32)

    def loss(x, w, b):
        return ltorch.sum(ltorch.layer_norm(x, (D,), w, b))

    vag = tt.value_and_grad(loss)
    vag(x, w, b)

    def run(x, w, b):
        return vag(x, w, b)[0]

    return _timeit(run, x, w, b, iters=10)


@register("rmsnorm_bwd")
def bench_rmsnorm_bwd(rng):
    N, D = _d(8192), _d(1024)
    x = _tensor(rng, (N, D), jnp.float32)
    w = _tensor(rng, (D,), jnp.float32)

    def loss(x, w):
        return ltorch.sum(ltorch.rms_norm(x, (D,), w))

    vag = tt.value_and_grad(loss)
    vag(x, w)
    return _timeit(lambda: vag(x, w)[0], iters=10)


@register("deepseek_moe_router")
def bench_deepseek_moe(rng):
    """Larger expert count + top-k routing (reference DeepSeek MoE target)."""
    from thunder_tpu.models.moe import MoEConfig, MoEMLP

    cfg = MoEConfig(n_embd=_d(1024), n_expert=32, n_expert_per_token=4)
    mlp = MoEMLP(cfg, dtype=jnp.bfloat16)
    tm = _jit(mlp)
    x = _tensor(rng, (_d(4), _d(512), cfg.n_embd))
    return _timeit(tm, x, iters=5)


def main(pattern: str = "", modes=("fused", "opbyop")):
    """Per-target x per-executor matrix with a winner column (reference
    targets.py benchmark CI table)."""
    global _MODE
    rng = np.random.RandomState(0)
    rows = []
    for name, fn in BENCHMARKS.items():
        if pattern and pattern not in name:
            continue
        row = {"target": name}
        for mode in modes:
            _MODE = mode
            try:
                row[mode] = fn(rng) * 1e3
            except Exception as e:
                row[mode] = None
                row.setdefault("errors", {})[mode] = str(e)[:80]
        rows.append(row)
    _MODE = "fused"
    hdr = f"{'target':28s}" + "".join(f"{m:>12s}" for m in modes) + f"{'winner':>10s}"
    print(hdr)
    print("-" * len(hdr))
    for row in rows:
        cells = ""
        best, best_t = "-", None
        for m in modes:
            v = row.get(m)
            cells += f"{v:12.3f}" if v is not None else f"{'FAIL':>12s}"
            if v is not None and (best_t is None or v < best_t):
                best, best_t = m, v
        print(f"{row['target']:28s}{cells}{best:>10s}")
        for m, err in row.get("errors", {}).items():
            print(f"    {m} error: {err}")
    return rows


if __name__ == "__main__":
    pat = sys.argv[1] if len(sys.argv) > 1 else ""
    modes = tuple(sys.argv[2].split(",")) if len(sys.argv) > 2 else ("fused", "opbyop")
    main(pat, modes)
