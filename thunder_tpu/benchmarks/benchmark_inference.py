"""Inference benchmark harness: throughput, ms/token, TTFT, TBOT.

Counterpart of reference thunder/benchmarks/benchmark_inference.py:1-11.

Usage:
    python -m thunder_tpu.benchmarks.benchmark_inference --model_name tiny-llama2 \
        --batch_size 1 --prompt_len 64 --max_new_tokens 64
"""
from __future__ import annotations

import argparse
import json

import jax.numpy as jnp
import numpy as np


def run(args) -> dict:
    from thunder_tpu.inference import GPTInference
    from thunder_tpu.models.litgpt import Config, GPT

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    cfg = Config.from_name(args.model_name, block_size=max(args.prompt_len + args.max_new_tokens, 128))
    if args.moe:
        from thunder_tpu.models.moe import MoEConfig, MoEGPT

        if args.moe_experts < 2:
            raise SystemExit("--moe_experts must be >= 2")
        moe_cfg = MoEConfig(n_embd=cfg.n_embd,
                            intermediate_size=max(128, cfg.intermediate_size // args.moe_experts),
                            n_expert=args.moe_experts,
                            n_expert_per_token=min(2, args.moe_experts))
        gpt = MoEGPT(cfg, moe_cfg, dtype=dtype)
    else:
        gpt = GPT(cfg, dtype=dtype)
    if args.quantize == "int8":
        # weight-only int8: decode-shape linears claim the fused
        # dequant-in-kernel Pallas matmul (weights stay int8 in HBM)
        from thunder_tpu.transforms.quantization import QuantizeInt8Transform

        QuantizeInt8Transform().transform_module(gpt)
    elif args.quantize == "nf4":
        from thunder_tpu.transforms.quantization import QuantizeNF4Transform

        QuantizeNF4Transform().transform_module(gpt)
    engine = GPTInference(gpt, dtype=dtype)

    rng = np.random.RandomState(0)
    prompt = jnp.asarray(rng.randint(0, cfg.vocab_size, (args.batch_size, args.prompt_len)))

    # warmup at the MEASURED step count: the scan-decode program is keyed on
    # (n_steps, batch, dtype) — warming with a different count would leave
    # the timed run paying the scan compile
    engine.generate(prompt, max_new_tokens=args.max_new_tokens)
    out, m = engine.generate(prompt, max_new_tokens=args.max_new_tokens, temperature=args.temperature)

    result = {
        "model": args.model_name + ("+moe" if args.moe else "")
                 + (f"+{args.quantize}" if args.quantize else ""),
        "batch_size": args.batch_size,
        "prompt_len": args.prompt_len,
        "new_tokens": m.n_new_tokens,
        "ttft_ms": m.ttft_s * 1e3,
        "tbot_ms": m.tbot_s * 1e3,
        "tokens_per_sec": m.tokens_per_sec,
        "ms_per_token": m.ms_per_token,
    }
    print(json.dumps(result, indent=2))
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quantize", choices=["int8", "nf4"], default=None,
                   help="weight-only quantization before compiling the engine")
    p.add_argument("--model_name", default="tiny-llama2")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--prompt_len", type=int, default=64)
    p.add_argument("--max_new_tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--precision", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--moe", action="store_true", help="Mixtral-style MoE decoder (models/moe.py)")
    p.add_argument("--moe_experts", type=int, default=8)
    run(p.parse_args())


if __name__ == "__main__":
    main()
