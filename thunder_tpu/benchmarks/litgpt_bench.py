"""LitGPT pretraining benchmark harness.

Counterpart of reference thunder/benchmarks/benchmark_litgpt.py:475-871:
reports tokens/sec (per-chip and global), model TFLOP/s, average iter time,
and peak memory. Distributed modes map to mesh axes instead of torchrun
process groups.

Usage:
    python -m thunder_tpu.benchmarks.litgpt_bench --model_name tiny-llama2 \
        --micro_batch_size 4 --seq_len 512 [--distributed_mode fsdp --n_devices 8]
"""
from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def model_flops_per_token(cfg) -> float:
    """6 * N params approximation + attention term (standard accounting)."""
    n_params = (
        cfg.padded_vocab_size * cfg.n_embd * 2
        + cfg.n_layer * (
            # attention
            cfg.n_embd * (cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size
            + cfg.n_head * cfg.head_size * cfg.n_embd
            # mlp (LLaMA 3-matrix or GptNeox 2-matrix)
            + (3 if cfg.mlp_class_name == "LLaMAMLP" else 2) * cfg.n_embd * cfg.intermediate_size
        )
    )
    return 6.0 * n_params


def run(args) -> dict:
    import thunder_tpu as tt
    from thunder_tpu import optim
    from thunder_tpu.models.litgpt import Config, GPTForCausalLM
    from thunder_tpu.training import TrainStep

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    cfg = Config.from_name(args.model_name, block_size=args.seq_len)
    model = GPTForCausalLM(cfg, dtype=dtype)
    tm = tt.jit(model)

    n_devices = 1
    if args.distributed_mode != "none":
        from thunder_tpu.parallel import ddp, fsdp, make_mesh

        n_devices = args.n_devices or len(jax.devices())
        if args.distributed_mode == "ddp":
            mesh = make_mesh({"dp": n_devices})
            ddp(tm, mesh)
        elif args.distributed_mode == "fsdp":
            mesh = make_mesh({"fsdp": n_devices})
            fsdp(tm, mesh)
        elif args.distributed_mode == "ddp_fsdp":
            mesh = make_mesh({"dp": 2, "fsdp": n_devices // 2})
            ddp(tm, mesh)
            fsdp(tm, mesh)
        else:
            raise ValueError(args.distributed_mode)

    step = TrainStep(tm, optim.AdamW(lr=args.lr))
    rng = np.random.RandomState(0)
    B = args.micro_batch_size * (n_devices if args.distributed_mode != "none" else 1)
    idx = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, args.seq_len)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, args.seq_len)), jnp.int32)

    t0 = time.perf_counter()
    loss = step(idx, tgt)
    jax.block_until_ready(loss)
    compile_time = time.perf_counter() - t0

    for _ in range(args.warmup_iters):
        step(idx, tgt)
    t0 = time.perf_counter()
    for _ in range(args.max_iters):
        loss = step(idx, tgt)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.max_iters

    tokens_per_iter = B * args.seq_len
    tokens_per_sec = tokens_per_iter / dt
    flops = model_flops_per_token(cfg) * tokens_per_iter
    result = {
        "model": args.model_name,
        "distributed_mode": args.distributed_mode,
        "n_devices": n_devices,
        "iter_time_ms": dt * 1e3,
        "tokens_per_sec_global": tokens_per_sec,
        "tokens_per_sec_per_chip": tokens_per_sec / n_devices,
        "model_tflops": flops / dt / 1e12,
        "compile_time_s": compile_time,
        "final_loss": float(loss),
    }
    for k, v in result.items():
        print(f"{k:26s} {v}")
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_name", default="tiny-llama2")
    p.add_argument("--micro_batch_size", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--max_iters", type=int, default=20)
    p.add_argument("--warmup_iters", type=int, default=3)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--precision", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--distributed_mode", default="none",
                   choices=["none", "ddp", "fsdp", "ddp_fsdp"])
    p.add_argument("--n_devices", type=int, default=0)
    run(p.parse_args())


if __name__ == "__main__":
    main()
