"""LitGPT pretraining benchmark harness.

Counterpart of reference thunder/benchmarks/benchmark_litgpt.py:475-871:
reports tokens/sec (per-chip and global), model TFLOP/s, MFU, average iter
time, peak memory, and saved-for-backward size. Distributed modes map to
mesh axes instead of torchrun process groups.

Usage:
    python -m thunder_tpu.benchmarks.litgpt_bench --model_name tiny-llama2 \
        --micro_batch_size 4 --seq_len 512 [--distributed_mode fsdp --n_devices 8]
"""
from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def model_flops_per_token(cfg) -> float:
    """6 * N params approximation + attention term (standard accounting)."""
    n_params = (
        cfg.padded_vocab_size * cfg.n_embd * 2
        + cfg.n_layer * (
            # attention
            cfg.n_embd * (cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size
            + cfg.n_head * cfg.head_size * cfg.n_embd
            # mlp (LLaMA 3-matrix or GptNeox 2-matrix)
            + (3 if cfg.mlp_class_name == "LLaMAMLP" else 2) * cfg.n_embd * cfg.intermediate_size
        )
    )
    return 6.0 * n_params


def peak_tflops_per_chip() -> float:
    """bf16 MXU peak for the local chip generation."""
    table = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6": 918.0}
    kind = jax.devices()[0].device_kind.lower()
    for k, v in table.items():
        if k in kind:
            return v
    return 197.0


def step_memory_gb(step) -> float | None:
    """Compiled-program memory estimate (args+temps+outputs-aliased)."""
    try:
        ma = step.memory_analysis()
        if ma is None:
            return None
        tot = (getattr(ma, "argument_size_in_bytes", 0)
               + getattr(ma, "temp_size_in_bytes", 0)
               + getattr(ma, "output_size_in_bytes", 0)
               - getattr(ma, "alias_size_in_bytes", 0))
        return round(tot / 2**30, 3)
    except Exception:
        return None


def saved_for_backward_mib(step) -> float | None:
    """Size of the residual tensors crossing the fwd/bwd split (reference
    benchmark_litgpt.py:867 saved-for-backward accounting)."""
    try:
        entry = next(iter(step._vag._cache.values()))
        ret = entry.fwd_trc.bound_symbols[-1]
        saved = ret.args[0][1]
        total = 0
        for p in saved:
            if hasattr(p, "shape") and hasattr(p, "dtype"):
                n = 1
                for d in p.shape:
                    n *= int(d)
                total += n * p.dtype.bytes
        return round(total / 2**20, 1)
    except Exception:
        return None


def run(args) -> dict:
    import thunder_tpu as tt
    from thunder_tpu import optim
    from thunder_tpu.models.litgpt import Config, GPTForCausalLM
    from thunder_tpu.training import TrainStep

    cfg = Config.from_name(args.model_name, block_size=args.seq_len,
                           activation_checkpoint=args.activation_checkpoint)
    transforms = []
    if args.autocast:
        # fp32 master weights + bf16 compute (the standard mixed recipe)
        from thunder_tpu.transforms.autocast import AutocastTransform

        transforms.append(AutocastTransform())
        dtype = jnp.float32
    else:
        dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    if getattr(args, "fp8", False):
        # delayed-scaling fp8 linears (amax-history buffers, fwd+bwd);
        # reference benchmark_litgpt.py TE fp8 role
        from thunder_tpu.transforms.fp8_training import FP8TrainingTransform

        transforms.append(FP8TrainingTransform())
    model = GPTForCausalLM(cfg, dtype=dtype)
    tm = tt.jit(model, transforms=transforms)

    n_devices = 1
    if args.distributed_mode != "none":
        from thunder_tpu.parallel import ddp, fsdp, make_mesh

        n_devices = args.n_devices or len(jax.devices())
        if args.distributed_mode == "ddp":
            mesh = make_mesh({"dp": n_devices})
            ddp(tm, mesh)
        elif args.distributed_mode == "fsdp":
            mesh = make_mesh({"fsdp": n_devices})
            fsdp(tm, mesh)
        elif args.distributed_mode == "ddp_fsdp":
            mesh = make_mesh({"dp": 2, "fsdp": n_devices // 2})
            ddp(tm, mesh)
            fsdp(tm, mesh)
        else:
            raise ValueError(args.distributed_mode)

    step = TrainStep(tm, optim.AdamW(lr=args.lr))
    rng = np.random.RandomState(0)
    B = args.micro_batch_size * (n_devices if args.distributed_mode != "none" else 1)
    idx = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, args.seq_len)), jnp.int32)
    tgt = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, args.seq_len)), jnp.int32)

    t0 = time.perf_counter()
    loss = step(idx, tgt)
    float(loss)
    compile_time = time.perf_counter() - t0

    for _ in range(args.warmup_iters):
        float(step(idx, tgt))  # value read: the only reliable sync over axon
    t0 = time.perf_counter()
    for _ in range(args.max_iters):
        loss = step(idx, tgt)
    float(loss)  # forces the chained steps
    dt = (time.perf_counter() - t0) / args.max_iters

    tokens_per_iter = B * args.seq_len
    tokens_per_sec = tokens_per_iter / dt
    flops = model_flops_per_token(cfg) * tokens_per_iter
    tflops = flops / dt / 1e12
    result = {
        "model": args.model_name,
        "distributed_mode": args.distributed_mode,
        "n_devices": n_devices,
        "iter_time_ms": dt * 1e3,
        "tokens_per_sec_global": tokens_per_sec,
        "tokens_per_sec_per_chip": tokens_per_sec / n_devices,
        "model_tflops": tflops,
        "mfu": tflops / (peak_tflops_per_chip() * n_devices),
        "peak_memory_gb": step_memory_gb(step),
        "saved_for_backward_mib": saved_for_backward_mib(step),
        "compile_time_s": compile_time,
        "final_loss": float(loss),
    }
    for k, v in result.items():
        print(f"{k:26s} {v}")
    return result


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_name", default="tiny-llama2")
    p.add_argument("--micro_batch_size", type=int, default=4)
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--max_iters", type=int, default=20)
    p.add_argument("--warmup_iters", type=int, default=3)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--precision", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--activation_checkpoint", action="store_true",
                   help="recompute each block in backward (remat.checkpoint)")
    p.add_argument("--fp8", action="store_true",
                   help="delayed-scaling fp8 linears (fwd+bwd)")
    p.add_argument("--autocast", action="store_true",
                   help="fp32 master weights + bf16 compute via AutocastTransform")
    p.add_argument("--distributed_mode", default="none",
                   choices=["none", "ddp", "fsdp", "ddp_fsdp"])
    p.add_argument("--n_devices", type=int, default=0)
    run(p.parse_args())


if __name__ == "__main__":
    main()
