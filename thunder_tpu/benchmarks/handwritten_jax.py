"""Hand-written plain-JAX GPT training step — the honest benchmark baseline.

The reference's headline compares thunder against PyTorch eager
(reference README.md:23); on TPU the competitor a user would actually write
is a straight ``jax.jit`` program. This module implements the same LitGPT
``Config`` model (models/litgpt.py) directly in jax.numpy — no thunder_tpu
IR, no executors, no transforms — with the standard mixed-precision recipe
(fp32 master weights, bf16 compute) and a fused AdamW step, jit-compiled
with donation. ``bench.py``'s ``vs_baseline`` is thunder_tpu ÷ this.
"""
from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------------------------
# parameter init (mirrors nn.Linear / nn.Embedding defaults in nn/module.py)
# --------------------------------------------------------------------------


def init_params(cfg, seed: int = 0, dtype=jnp.float32) -> dict:
    rng = np.random.RandomState(seed)

    def linear(key, fan_in, fan_out, bias):
        bound = 1.0 / math.sqrt(fan_in)
        p = {f"{key}.weight": jnp.asarray(
            rng.uniform(-bound, bound, (fan_out, fan_in)), dtype)}
        if bias:
            p[f"{key}.bias"] = jnp.asarray(rng.uniform(-bound, bound, (fan_out,)), dtype)
        return p

    def norm(key):
        p = {f"{key}.weight": jnp.ones((cfg.n_embd,), dtype)}
        if cfg.norm_class_name == "LayerNorm":
            p[f"{key}.bias"] = jnp.zeros((cfg.n_embd,), dtype)
        return p

    params: dict[str, Any] = {
        # N(0,1): the torch.nn.Embedding default, matching nn/module.py so
        # both bench phases train the same model
        "wte.weight": jnp.asarray(
            rng.randn(cfg.padded_vocab_size, cfg.n_embd), dtype),
    }
    qkv_out = (cfg.n_head + 2 * cfg.n_query_groups) * cfg.head_size
    for i in range(cfg.n_layer):
        b = f"h.{i}"
        params.update(norm(f"{b}.norm_1"))
        params.update(linear(f"{b}.attn.attn", cfg.n_embd, qkv_out, cfg.bias))
        params.update(linear(f"{b}.attn.proj", cfg.n_head * cfg.head_size, cfg.n_embd, cfg.bias))
        params.update(norm(f"{b}.norm_2"))
        if cfg.mlp_class_name == "LLaMAMLP":
            params.update(linear(f"{b}.mlp.fc_1", cfg.n_embd, cfg.intermediate_size, cfg.bias))
            params.update(linear(f"{b}.mlp.fc_2", cfg.n_embd, cfg.intermediate_size, cfg.bias))
            params.update(linear(f"{b}.mlp.proj", cfg.intermediate_size, cfg.n_embd, cfg.bias))
        else:
            params.update(linear(f"{b}.mlp.fc", cfg.n_embd, cfg.intermediate_size, cfg.bias))
            params.update(linear(f"{b}.mlp.proj", cfg.intermediate_size, cfg.n_embd, cfg.bias))
    params.update(norm("ln_f"))
    params.update(linear("lm_head", cfg.n_embd, cfg.padded_vocab_size, cfg.lm_head_bias))
    return params


def rope_cache(cfg, dtype=jnp.float32):
    n_elem = cfg.rope_n_elem
    if n_elem <= 0:
        z = jnp.zeros((cfg.block_size, 0), dtype)
        return z, z
    theta = 1.0 / (cfg.rope_base ** (jnp.arange(0, n_elem, 2, dtype=jnp.float32) / n_elem))
    idx = jnp.outer(jnp.arange(cfg.block_size, dtype=jnp.float32), theta)
    idx = jnp.concatenate([idx, idx], -1)
    return jnp.cos(idx).astype(dtype), jnp.sin(idx).astype(dtype)


# --------------------------------------------------------------------------
# forward (bf16 compute, f32 norms/softmax/loss — same policy as autocast)
# --------------------------------------------------------------------------


def _library_flash_attention():
    """jax's shipped TPU flash-attention kernel, if importable."""
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention
        return flash_attention
    except Exception:
        return None


def _norm_f(cfg, x, w, b, eps):
    x32 = x.astype(jnp.float32)
    if cfg.norm_class_name == "RMSNorm":
        out = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps) * w
    else:
        mu = jnp.mean(x32, -1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, -1, keepdims=True)
        out = (x32 - mu) * jax.lax.rsqrt(var + eps) * w + b
    return out


def _rope(x, cos, sin, n_elem):
    if n_elem <= 0:
        return x
    rot = x[..., :n_elem]
    x1, x2 = rot[..., : n_elem // 2], rot[..., n_elem // 2:]
    roped = rot * cos + jnp.concatenate([-x2, x1], -1) * sin
    if n_elem < x.shape[-1]:
        return jnp.concatenate([roped, x[..., n_elem:]], -1)
    return roped


def forward(cfg, params, idx, targets, cos, sin, compute_dtype=jnp.bfloat16):
    B, T = idx.shape
    nh, ng, hs = cfg.n_head, cfg.n_query_groups, cfg.head_size
    q_per_kv = nh // ng

    def w(k):
        return params[k].astype(compute_dtype)

    cos_t, sin_t = cos[:T], sin[:T]
    x = w("wte.weight")[idx]
    use_ckpt = bool(getattr(cfg, "activation_checkpoint", False))
    for i in range(cfg.n_layer):
        blk = f"h.{i}"
        body = functools.partial(_block_body, cfg, params, blk, w, cos_t, sin_t,
                                 compute_dtype, B, T)
        x = jax.checkpoint(body)(x) if use_ckpt else body(x)
    x = _norm_f(cfg, x, params["ln_f.weight"], params.get("ln_f.bias"),
                cfg.norm_eps).astype(compute_dtype)
    logits = x @ w("lm_head.weight").T
    if "lm_head.bias" in params:
        logits = logits + w("lm_head.bias")
    logits = logits.reshape(B * T, -1).astype(jnp.float32)
    tgt = targets.reshape(B * T)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, tgt[:, None], 1)[:, 0]
    return jnp.mean(lse - picked)


def _block_body(cfg, params, blk, w, cos_t, sin_t, compute_dtype, B, T, x):
    nh, ng, hs = cfg.n_head, cfg.n_query_groups, cfg.head_size
    q_per_kv = nh // ng
    h = _norm_f(cfg, x, params[f"{blk}.norm_1.weight"],
                params.get(f"{blk}.norm_1.bias"), cfg.norm_eps).astype(compute_dtype)
    qkv = h @ w(f"{blk}.attn.attn.weight").T
    if f"{blk}.attn.attn.bias" in params:
        qkv = qkv + w(f"{blk}.attn.attn.bias")
    qkv = qkv.reshape(B, T, ng, q_per_kv + 2, hs)
    q = qkv[:, :, :, :q_per_kv].reshape(B, T, nh, hs).transpose(0, 2, 1, 3)
    k = qkv[:, :, :, q_per_kv: q_per_kv + 1].reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
    v = qkv[:, :, :, q_per_kv + 1:].reshape(B, T, ng, hs).transpose(0, 2, 1, 3)
    q = _rope(q, cos_t, sin_t, cfg.rope_n_elem)
    k = _rope(k, cos_t, sin_t, cfg.rope_n_elem)
    if ng != nh:
        k = jnp.repeat(k, q_per_kv, axis=1)
        v = jnp.repeat(v, q_per_kv, axis=1)
    # the attention a jax user writes today, strongest available first:
    # jax's library pallas flash kernel (the composite materializes
    # B·H·T² probabilities for backward — OOM at llama-350m B=4 T=2048
    # on one 16 GB chip), then the fused composite, then manual softmax
    lib_flash = _library_flash_attention()
    score_bytes = B * nh * T * T * 2
    big_attention = T >= 4096 or (T >= 2048 and score_bytes >= 256 * 2**20)
    if lib_flash is not None and big_attention and T % 128 == 0 and hs >= 64:
        y = lib_flash(q.astype(compute_dtype), k.astype(compute_dtype),
                      v.astype(compute_dtype), causal=True,
                      sm_scale=1.0 / math.sqrt(hs))
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * hs)
    elif hasattr(jax.nn, "dot_product_attention"):
        # rope promotes q/k to f32 (f32 cos/sin); the composite requires
        # uniform dtypes
        y = jax.nn.dot_product_attention(
            q.astype(compute_dtype).transpose(0, 2, 1, 3),
            k.astype(compute_dtype).transpose(0, 2, 1, 3),
            v.astype(compute_dtype).transpose(0, 2, 1, 3),
            scale=1.0 / math.sqrt(hs), is_causal=True)
        y = y.reshape(B, T, nh * hs)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) / math.sqrt(hs)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(compute_dtype)
        y = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        y = y.transpose(0, 2, 1, 3).reshape(B, T, nh * hs)
    y = y @ w(f"{blk}.attn.proj.weight").T
    if f"{blk}.attn.proj.bias" in params:
        y = y + w(f"{blk}.attn.proj.bias")
    if cfg.parallel_residual:
        h2 = _norm_f(cfg, x, params[f"{blk}.norm_2.weight"],
                     params.get(f"{blk}.norm_2.bias"), cfg.norm_eps).astype(compute_dtype)
        x = x + y + _mlp(cfg, params, blk, h2, w)
    else:
        x = x + y
        h2 = _norm_f(cfg, x, params[f"{blk}.norm_2.weight"],
                     params.get(f"{blk}.norm_2.bias"), cfg.norm_eps).astype(compute_dtype)
        x = x + _mlp(cfg, params, blk, h2, w)
    return x


def _mlp(cfg, params, blk, h, w):
    if cfg.mlp_class_name == "LLaMAMLP":
        a = h @ w(f"{blk}.mlp.fc_1.weight").T
        b = h @ w(f"{blk}.mlp.fc_2.weight").T
        return (jax.nn.silu(a) * b) @ w(f"{blk}.mlp.proj.weight").T
    a = h @ w(f"{blk}.mlp.fc.weight").T
    if f"{blk}.mlp.fc.bias" in params:
        a = a + w(f"{blk}.mlp.fc.bias")
    out = jax.nn.gelu(a, approximate=True) @ w(f"{blk}.mlp.proj.weight").T
    if f"{blk}.mlp.proj.bias" in params:
        out = out + w(f"{blk}.mlp.proj.bias")
    return out


# --------------------------------------------------------------------------
# AdamW (same formula as thunder_tpu.optim.AdamW) + jitted step
# --------------------------------------------------------------------------


def adamw_init(params):
    return {
        "step": jnp.zeros((), jnp.int32),
        "m": jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
        "v": jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
    }


def adamw_update(params, grads, state, lr=1e-4, beta1=0.9, beta2=0.999,
                 eps=1e-8, weight_decay=0.01):
    step = state["step"] + 1
    t = step.astype(jnp.float32)
    bc1, bc2 = 1.0 - beta1**t, 1.0 - beta2**t

    def upd(p, g, m, v):
        g32 = g.astype(jnp.float32)
        m2 = beta1 * m + (1 - beta1) * g32
        v2 = beta2 * v + (1 - beta2) * g32 * g32
        p32 = p.astype(jnp.float32)
        p32 = p32 - lr * weight_decay * p32
        p32 = p32 - lr * (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
        return p32.astype(p.dtype), m2, v2

    out = {k: upd(params[k], grads[k], state["m"][k], state["v"][k]) for k in params}
    return ({k: o[0] for k, o in out.items()},
            {"step": step,
             "m": {k: o[1] for k, o in out.items()},
             "v": {k: o[2] for k, o in out.items()}})


def make_train_step(cfg, lr=1e-4, compute_dtype=jnp.bfloat16):
    cos, sin = rope_cache(cfg)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, idx, targets):
        loss, grads = jax.value_and_grad(
            lambda p: forward(cfg, p, idx, targets, cos, sin, compute_dtype))(params)
        params, opt_state = adamw_update(params, grads, opt_state, lr=lr)
        return loss, params, opt_state

    return step
