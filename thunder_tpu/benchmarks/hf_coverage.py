"""HF model coverage harness — the reference's jit-coverage job
(examples/coverage/jit_coverage_hf.py) rebuilt for the torch interop frontend.

Loads small randomly-initialized configs for N architectures, traces each
through ``interop.torch_frontend`` (forward AND backward), compares against
torch eager, and reports per-model status plus which torch ops fell back to
the host-eager path (the coverage signal: a fallback is correct but slow).

Usage:
    python -m thunder_tpu.benchmarks.hf_coverage [--models gpt2,llama,...]
    # writes HF_COVERAGE.md at the repo root with the report table
"""
from __future__ import annotations

import argparse
import json
import re
import time
import traceback

import numpy as np


def _configs():
    from transformers import (
        BertConfig,
        GemmaConfig,
        GPT2Config,
        LlamaConfig,
        MistralConfig,
        Qwen2Config,
    )

    common = dict(vocab_size=256, max_position_embeddings=128)
    return {
        "gpt2": (GPT2Config(n_layer=2, n_head=2, n_embd=64, vocab_size=256,
                            n_positions=128, use_cache=False), "causal"),
        "llama": (LlamaConfig(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2,
                              use_cache=False, **common), "causal"),
        "mistral": (MistralConfig(hidden_size=64, intermediate_size=128,
                                  num_hidden_layers=2, num_attention_heads=4,
                                  num_key_value_heads=2, sliding_window=None,
                                  use_cache=False, **common), "causal"),
        "qwen2": (Qwen2Config(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2,
                              use_cache=False, **common), "causal"),
        "gemma": (GemmaConfig(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                              num_attention_heads=4, num_key_value_heads=2, head_dim=16,
                              use_cache=False, **common), "causal"),
        # eager attention: transformers' sdpa path probes `0 in attention_mask`
        # (data-dependent host branch — untraceable by design)
        "bert": (BertConfig(hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                            num_attention_heads=4, vocab_size=256,
                            max_position_embeddings=128,
                            attn_implementation="eager"), "masked"),
    }


def _scrape_fallbacks(warning_list) -> list[str]:
    """Torch ops that hit the host-eager path, from the frontend's warning."""
    return sorted({
        m.group(1) for wi in warning_list
        for m in [re.search(r"no mapping for ([\w.]+)", str(wi.message))] if m})


def run_model(name: str, cfg, kind: str, *, check_backward: bool = True) -> dict:
    import warnings

    import jax.numpy as jnp
    import torch
    from transformers import AutoModelForCausalLM, AutoModelForMaskedLM

    import thunder_tpu as tt
    from thunder_tpu.interop import torch_frontend as tf

    torch.manual_seed(0)
    cls = AutoModelForCausalLM if kind == "causal" else AutoModelForMaskedLM
    model = cls.from_config(cfg).eval()
    ids = torch.randint(0, cfg.vocab_size, (2, 16))
    # masked-LM models get an explicit all-ones mask: without one,
    # transformers probes `pad_token_id in input_ids` just to warn (a
    # data-dependent host branch). Causal models take the opposite choice:
    # an explicit mask routes them into the `0 in attention_mask` sdpa
    # pruning probe — equally untraceable — so they pass none.
    mask = torch.ones_like(ids) if kind == "masked" else None
    mask_kw = {"attention_mask": mask} if mask is not None else {}

    rec: dict = {"model": name, "status": "ok", "fallbacks": [], "max_abs_err": None,
                 "bwd_max_rel_err": None}
    t0 = time.time()
    try:
        with torch.no_grad():
            ref = model(input_ids=ids, **mask_kw).logits
        tf._eager_warned.clear()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            ctm = tt.jit(model)
            out = ctm(input_ids=ids, **mask_kw)
        logits = out["logits"] if isinstance(out, dict) else getattr(out, "logits", out[0])
        err = float(np.max(np.abs(np.asarray(logits) - ref.numpy())))
        rec["max_abs_err"] = err
        rec["fallbacks"] = _scrape_fallbacks(w)
        if err > 1e-2:
            rec["status"] = f"numerics ({err:.2e})"

        if check_backward and rec["status"] == "ok":
            # fwd+bwd vs torch autograd: a torch wrapper computes the scalar
            # loss so the TorchModuleValueAndGrad path (grads per param name)
            # applies
            class LossWrap(torch.nn.Module):
                def __init__(self, inner):
                    super().__init__()
                    self.inner = inner

                def forward(self, input_ids, attention_mask=None):
                    kw = {"attention_mask": attention_mask} if attention_mask is not None else {}
                    return self.inner(input_ids=input_ids, **kw).logits.float().pow(2).mean()

            wrap = LossWrap(model)
            loss_t = wrap(ids, mask) if mask is not None else wrap(ids)
            loss_t.backward()
            named = {n: p for n, p in wrap.named_parameters() if p.grad is not None}
            tname, tparam = max(named.items(), key=lambda kv: float(kv[1].grad.abs().sum()))

            ctm_loss = tt.jit(wrap)
            vag_args = (ids, mask) if mask is not None else (ids,)
            tf._eager_warned.clear()  # fwd dedup must not hide bwd fallbacks
            with warnings.catch_warnings(record=True) as wb:
                warnings.simplefilter("always")
                lval, grads = tt.value_and_grad(ctm_loss)(*vag_args)
            rec["fallbacks"] = sorted(set(rec["fallbacks"]) | set(_scrape_fallbacks(wb)))
            g = grads.get(tname)
            if g is None:
                rec["status"] = f"bwd: no grad entry for {tname}"
            else:
                rel = float(np.max(np.abs(np.asarray(g) - tparam.grad.numpy()))
                            / (np.max(np.abs(tparam.grad.numpy())) + 1e-12))
                rec["bwd_max_rel_err"] = rel
                if not np.isclose(float(lval), float(loss_t), rtol=1e-3):
                    rec["status"] = f"bwd loss mismatch ({float(lval):.4f} vs {float(loss_t):.4f})"
                elif rel > 5e-2:
                    rec["status"] = f"bwd numerics ({rel:.2e})"
    except Exception as e:
        rec["status"] = f"error: {type(e).__name__}: {str(e)[:160]}"
        rec["traceback"] = traceback.format_exc()[-2000:]
    rec["seconds"] = round(time.time() - t0, 1)
    return rec


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--models", default=None, help="comma list; default all")
    p.add_argument("--out", default="HF_COVERAGE.md")
    p.add_argument("--no-backward", action="store_true")
    args = p.parse_args(argv)

    cfgs = _configs()
    names = args.models.split(",") if args.models else list(cfgs)
    rows = []
    for n in names:
        cfg, kind = cfgs[n]
        rec = run_model(n, cfg, kind, check_backward=not args.no_backward)
        print(json.dumps({k: v for k, v in rec.items() if k != "traceback"}))
        rows.append(rec)

    lines = [
        "# HF model coverage (torch interop frontend)",
        "",
        "Counterpart of the reference's jit-coverage job "
        "(`examples/coverage/jit_coverage_hf.py`): each architecture is traced "
        "fwd+bwd through `interop/torch_frontend.py` on randomly-initialized "
        "small configs and compared against torch eager. `fallbacks` lists "
        "torch ops that ran host-eager (correct but slow — lowering TODOs).",
        "",
        "| model | status | fwd max abs err | bwd max rel err | host-eager fallbacks |",
        "|---|---|---|---|---|",
    ]
    for r in rows:
        fb = ", ".join(r["fallbacks"]) if r["fallbacks"] else "none"
        lines.append(
            f"| {r['model']} | {r['status']} | "
            f"{r['max_abs_err'] if r['max_abs_err'] is not None else '—'} | "
            f"{r['bwd_max_rel_err'] if r['bwd_max_rel_err'] is not None else '—'} | {fb} |")
    # regenerate the table but carry over hand-measured sections appended
    # after it (e.g. the timed KV-cache generation artifact)
    extra = ""
    try:
        prev = open(args.out).read()
        cut = prev.find("\n## ")
        if cut != -1:
            extra = prev[cut:]
    except OSError:
        pass
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n" + extra)
    ok = sum(1 for r in rows if r["status"] == "ok")
    print(f"# {ok}/{len(rows)} architectures ok -> {args.out}")
    return rows


if __name__ == "__main__":
    main()
