"""Serving load benchmark: open- or closed-loop streams against the
continuous-batching engine (thunder_tpu/serving/), reporting aggregate
tokens/sec, TTFT/TBOT p50/p99, page-pool utilization, the steady-state
recompile count, and — with an SLO configured — goodput.

Two load modes:

* ``--mode open`` (default; Orca/vLLM evaluation style): request arrival
  times are drawn up front from an exponential inter-arrival process and
  requests are submitted on that schedule whatever the engine's backlog —
  so queueing delay shows up in TTFT instead of being hidden by a closed
  loop.
* ``--mode closed``: ``--concurrency`` requests stay in flight; each
  completion immediately submits the next until ``--streams`` total have
  run. With ``--slo_ttft_ms``/``--slo_tbot_ms`` set, the engine stamps a
  per-request SLO-met flag at retirement and the row reports **goodput**
  (the fraction meeting the SLO) and **requests/s meeting the SLO** — the
  ROADMAP #2 acceptance metric.

Requests that produced <= 1 token have no between-token interval; they are
excluded from the TBOT percentiles but still counted in aggregate tokens/s,
so the row reports ``n_truncated`` explicitly to keep goodput and latency
denominators honest.

Workloads:

* ``--workload uniform`` (default): every prompt drawn iid from
  ``[prompt_len_min, prompt_len_max]`` — the original BENCH_SERVE row.
* ``--workload mixed``: fleet traffic through every serving stage at once
  (docs/serving.md). A ``--shared_frac`` fraction of requests reuse one
  system prompt (``--shared_prefix_len`` tokens) plus a short tail —
  admitted through the copy-on-write prefix cache; a ``--long_frac``
  fraction carry long prompts on the ``batch`` lane, prefilled in
  ``--chunk_tokens`` chunks interleaved with decode; the rest are the
  uniform interactive background. ``--self_draft`` runs the target model
  as its own speculative draft (every proposal verifies, so the row's
  ``spec_accept_rate`` is the plumbing ceiling, not a model-quality
  number). The row adds ``prefix_hit_rate`` (serve.prefix_hits /
  serve.requests) and ``spec_accept_rate`` (serve.spec_accepted /
  serve.spec_proposed) from post-warmup counters; both gate
  higher-is-better in tools/perf_gate.py.

Every row also reports ``obs_overhead_us`` — the measured disabled-path
cost of per-request tracing (tracing.disabled_overhead_us(); gated
lower-is-better) — plus the ``trace_counters`` family and a ``fleet``
block (per-host step stats + straggler flags from
observability.fleet_snapshot(), single-host degenerate here but the same
merge path a multi-host run aggregates through).

Usage:
    python -m thunder_tpu.benchmarks.benchmark_serving --model_name tiny-llama2 \
        --streams 8 --page_size 16 --arrival_rate 16
    python -m thunder_tpu.benchmarks.benchmark_serving --mode closed \
        --concurrency 4 --slo_ttft_ms 50 --slo_tbot_ms 15
    BENCH_SERVE=1 python -m thunder_tpu.benchmarks.benchmark_serving ...
        # additionally writes the BENCH_SERVE.json artifact row
        # (gate fresh runs against it with tools/perf_gate.py)
    BENCH_SERVE=1 python -m thunder_tpu.benchmarks.benchmark_serving \
        --mode closed --workload mixed --self_draft --spec_k 2 \
        --streams 160 --concurrency 10 --precision f32 --n_pages 256 \
        --slo_ttft_ms 750 --slo_tbot_ms 100 --new_tokens_min 2 \
        --new_tokens_max 4 --long_frac 0.06 --artifact BENCH_SERVE_FLEET.json
        # regenerates the committed fleet baseline row
"""
from __future__ import annotations

import argparse
import json
import os
import time
from concurrent.futures import FIRST_COMPLETED, wait

import jax.numpy as jnp
import numpy as np


from thunder_tpu.observability.telemetry import percentile as _pct


def _submit(engine, rng, cfg, spec, temperature):
    prompt, n, lane = spec
    return engine.submit(prompt, max_new_tokens=n, temperature=temperature,
                         seed=int(rng.randint(1 << 30)), lane=lane)


def _mixed_specs(args, cfg, rng) -> list:
    """(prompt, max_new_tokens, lane) per stream: shared-prefix requests
    (interactive), long chunked prompts (batch lane), uniform background."""
    shared = rng.randint(0, cfg.vocab_size,
                         (args.shared_prefix_len,)).astype(np.int32)
    long_max = args.max_seq - args.new_tokens_max - 1
    specs = []
    for _ in range(args.streams):
        n = int(rng.randint(args.new_tokens_min, args.new_tokens_max + 1))
        u = rng.random_sample()
        if u < args.shared_frac:
            tail = rng.randint(0, cfg.vocab_size,
                               (int(rng.randint(1, 9)),)).astype(np.int32)
            specs.append((np.concatenate([shared, tail]), n, "interactive"))
        elif u < args.shared_frac + args.long_frac:
            L = int(rng.randint(max(args.chunk_tokens + 1, long_max // 2),
                                long_max + 1))
            specs.append((rng.randint(0, cfg.vocab_size, (L,)).astype(np.int32),
                          n, "batch"))
        else:
            L = int(rng.randint(args.prompt_len_min, args.prompt_len_max + 1))
            specs.append((rng.randint(0, cfg.vocab_size, (L,)).astype(np.int32),
                          n, "interactive"))
    return specs


def _uniform_specs(args, cfg, rng) -> list:
    return [(rng.randint(0, cfg.vocab_size,
                         (int(rng.randint(args.prompt_len_min,
                                          args.prompt_len_max + 1)),)
                         ).astype(np.int32),
             int(rng.randint(args.new_tokens_min, args.new_tokens_max + 1)),
             "interactive")
            for _ in range(args.streams)]


def run(args) -> dict:
    from thunder_tpu import observability
    from thunder_tpu.models.litgpt import Config, GPT
    from thunder_tpu.observability.slo import SLOPolicy
    from thunder_tpu.serving import ServingEngine

    slo = None
    if args.slo_ttft_ms or args.slo_tbot_ms:
        slo = SLOPolicy(p99_ttft_ms=args.slo_ttft_ms or None,
                        p99_tbot_ms=args.slo_tbot_ms or None,
                        min_samples=min(8, max(2, args.streams // 4)))

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    cfg = Config.from_name(args.model_name, block_size=max(args.max_seq, 128))
    gpt = GPT(cfg, dtype=dtype)
    fleet_kw = {}
    if args.workload == "mixed":
        fleet_kw = dict(prefix_sharing=True, chunk_tokens=args.chunk_tokens,
                        draft_gpt=gpt if args.self_draft else None,
                        spec_k=args.spec_k if args.self_draft else None)
    engine = ServingEngine(gpt, max_batch=args.max_batch, page_size=args.page_size,
                           max_seq=args.max_seq, dtype=dtype, slo=slo,
                           n_pages=args.n_pages or None,
                           quantize=None if args.quantize == "none" else args.quantize,
                           **fleet_kw)

    rng = np.random.RandomState(args.seed)
    if args.workload == "mixed":
        specs = _mixed_specs(args, cfg, rng)
    else:
        specs = _uniform_specs(args, cfg, rng)

    observability.enable()
    # warm every program the workload will touch plus the decode step, then
    # clear the counters: any recompile recorded after this point is a
    # steady-state failure
    if args.workload == "mixed":
        # replay the full spec list once so every prefill bucket, chunk
        # rung, and the verify program compile — AND the prefix cache ends
        # warm, which is the steady state the measured phase models
        for spec in specs:
            engine.submit(spec[0], 2, lane=spec[2])
        engine.drain()
    else:
        engine.warmup(sorted({len(p) for p, _, _ in specs}), max_new_tokens=2)
    observability.reset()
    engine.reset_slo_accounting()  # warmup must not pollute goodput/windows

    engine.start()
    t0 = time.perf_counter()
    futs = []
    try:
        if args.mode == "open":
            # exponential inter-arrivals -> open-loop schedule (s from t0)
            gaps = rng.exponential(1.0 / args.arrival_rate, size=args.streams)
            arrivals = np.cumsum(gaps) - gaps[0]
            for spec, at in zip(specs, arrivals):
                dt = t0 + float(at) - time.perf_counter()
                if dt > 0:
                    time.sleep(dt)
                futs.append(_submit(engine, rng, cfg, spec, args.temperature))
            results = [f.result(timeout=600) for f in futs]
        else:
            # closed loop: a fixed number of in-flight requests; every
            # completion immediately feeds the next submission
            todo = list(specs)
            inflight = set()
            while todo and len(inflight) < max(1, args.concurrency):
                inflight.add(_submit(engine, rng, cfg, todo.pop(0),
                                     args.temperature))
            futs = list(inflight)
            while inflight:
                done, inflight = wait(inflight, timeout=600,
                                      return_when=FIRST_COMPLETED)
                if not done:
                    raise TimeoutError("closed-loop benchmark stalled")
                for _ in done:
                    if todo:
                        f = _submit(engine, rng, cfg, todo.pop(0),
                                    args.temperature)
                        inflight.add(f)
                        futs.append(f)
            results = [f.result(timeout=600) for f in futs]
    finally:
        engine.stop()
    wall = time.perf_counter() - t0

    counters = observability.counters()
    # fleet view over this (single-host) run: merged counters + per-host step
    # stats from the same snapshot/merge path a multi-host aggregation uses
    fleet_snap = observability.fleet_snapshot()
    observability.disable()
    # disabled-path cost of request tracing, measured with the bus OFF (the
    # state the key gates): min-of-repeats microbench, see perf_gate.py
    from thunder_tpu.observability import tracing as _tracing
    obs_overhead_us = _tracing.disabled_overhead_us()
    recompiles = sum(v for k, v in counters.items() if k.startswith("recompile."))

    import jax

    total_new = sum(r.n_new_tokens for r in results)
    ttfts = [r.ttft_s * 1e3 for r in results]
    # <= 1 generated token -> no between-token interval: excluded from the
    # TBOT percentiles (but still in aggregate tokens/s); n_truncated below
    # reports the exclusion explicitly
    tbots = [r.tbot_s * 1e3 for r in results if r.n_new_tokens > 1]
    n_truncated = sum(1 for r in results if r.n_new_tokens <= 1)
    stats = engine.stats()
    workload_tag = "" if args.workload == "uniform" else f"{args.workload} workload, "
    if args.quantize != "none":
        workload_tag += f"{args.quantize} weight-quantized decode, "
    row = {
        "platform": jax.devices()[0].platform,
        "metric": (f"{args.model_name} serving aggregate new tokens/sec "
                   f"({args.streams} {args.mode}-loop streams, {workload_tag}"
                   f"max_batch={args.max_batch}, "
                   f"page_size={args.page_size}, "
                   f"prompts {args.prompt_len_min}-{args.prompt_len_max}, "
                   f"outputs {args.new_tokens_min}-{args.new_tokens_max})"),
        "value": round(total_new / wall, 2),
        "unit": "tokens/s",
        "mode": args.mode,
        "n_requests": len(results),
        "n_truncated": n_truncated,
        "total_new_tokens": total_new,
        "wall_s": round(wall, 3),
        "requests_per_s": round(len(results) / wall, 2),
        "ttft_ms_p50": round(_pct(ttfts, 0.50), 2),
        "ttft_ms_p99": round(_pct(ttfts, 0.99), 2),
        "tbot_ms_p50": round(_pct(tbots, 0.50), 2),
        "tbot_ms_p99": round(_pct(tbots, 0.99), 2),
        "decode_steps": stats["decode_steps"],
        "peak_page_pool_utilization": stats["peak_page_pool_utilization"],
        "recompiles_steady_state": int(recompiles),
        "obs_overhead_us": round(obs_overhead_us, 3),
        "serve_counters": {k: v for k, v in counters.items() if k.startswith("serve.")},
        # request-tracing traffic only: the specialization cache is ALSO
        # named "trace", so exclude its hit/miss/evict outcome counters
        "trace_counters": {k: v for k, v in counters.items()
                           if k.startswith("trace.")
                           and k.partition(".")[2] not in ("hit", "miss", "evict")},
        "fleet": {
            "n_hosts": fleet_snap.get("n_hosts"),
            "hosts": {str(h): info.get("steps")
                      for h, info in fleet_snap.get("hosts", {}).items()},
            "stragglers": fleet_snap.get("stragglers", []),
        },
    }
    if args.workload == "mixed":
        n_req = counters.get("serve.requests", 0)
        proposed = counters.get("serve.spec_proposed", 0)
        row["workload"] = {"shared_frac": args.shared_frac,
                           "long_frac": args.long_frac,
                           "shared_prefix_len": args.shared_prefix_len,
                           "chunk_tokens": args.chunk_tokens,
                           "self_draft": bool(args.self_draft),
                           "spec_k": args.spec_k if args.self_draft else 0}
        row["prefix_hit_rate"] = (round(counters.get("serve.prefix_hits", 0)
                                        / n_req, 4) if n_req else None)
        row["prefix_tokens_saved"] = counters.get("serve.prefix_tokens_saved", 0)
        row["spec_accept_rate"] = (round(counters.get("serve.spec_accepted", 0)
                                         / proposed, 4) if proposed else None)
        row["preempted"] = stats["preempted"]
        row["resumed"] = stats["resumed"]
    if slo is not None:
        n_met = sum(1 for r in results if r.slo_met)
        row["slo"] = {"ttft_ms": args.slo_ttft_ms or None,
                      "tbot_ms": args.slo_tbot_ms or None}
        row["goodput"] = round(n_met / len(results), 4) if results else None
        row["requests_per_s_slo_met"] = round(n_met / wall, 2)
        row["slo_breaches"] = {k: v for k, v in counters.items()
                               if k.startswith("slo.breach.")}
    print(json.dumps(row, indent=1))
    if os.environ.get("BENCH_SERVE") == "1":
        # merge-by-metric so variant runs (e.g. --quantize int8 next to the
        # bf16 baseline) accumulate into one multi-row artifact instead of
        # clobbering each other; perf_gate.load_rows handles both shapes
        rows = []
        if os.path.exists(args.artifact):
            try:
                with open(args.artifact) as f:
                    old = json.load(f)
                rows = old if isinstance(old, list) else [old]
            except Exception:
                rows = []
        rows = [r for r in rows if r.get("metric") != row["metric"]] + [row]
        with open(args.artifact, "w") as f:
            json.dump(rows if len(rows) > 1 else row, f, indent=1)
        print(f"wrote {args.artifact} ({len(rows)} row(s))")
    return row


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_name", default="tiny-llama2")
    p.add_argument("--mode", default="open", choices=["open", "closed"])
    p.add_argument("--streams", type=int, default=8)
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop in-flight request target")
    p.add_argument("--max_batch", type=int, default=8)
    p.add_argument("--page_size", type=int, default=16)
    p.add_argument("--max_seq", type=int, default=256)
    p.add_argument("--prompt_len_min", type=int, default=8)
    p.add_argument("--prompt_len_max", type=int, default=48)
    p.add_argument("--new_tokens_min", type=int, default=8)
    p.add_argument("--new_tokens_max", type=int, default=32)
    p.add_argument("--arrival_rate", type=float, default=8.0,
                   help="open-loop arrivals per second")
    p.add_argument("--slo_ttft_ms", type=float, default=0.0,
                   help="per-request TTFT target; enables goodput reporting")
    p.add_argument("--slo_tbot_ms", type=float, default=0.0,
                   help="per-request TBOT target; enables goodput reporting")
    p.add_argument("--workload", default="uniform", choices=["uniform", "mixed"])
    p.add_argument("--shared_frac", type=float, default=0.6,
                   help="mixed: fraction of requests sharing the system prompt")
    p.add_argument("--long_frac", type=float, default=0.15,
                   help="mixed: fraction with long (chunk-prefilled) prompts")
    p.add_argument("--shared_prefix_len", type=int, default=64,
                   help="mixed: shared system-prompt length (page-aligned)")
    p.add_argument("--chunk_tokens", type=int, default=64,
                   help="mixed: chunked-prefill chunk size")
    p.add_argument("--self_draft", action="store_true",
                   help="mixed: speculative decoding with the target as its "
                        "own draft (plumbing-ceiling accept rate)")
    p.add_argument("--spec_k", type=int, default=3)
    p.add_argument("--n_pages", type=int, default=0,
                   help="page-pool override (0 = engine default)")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--quantize", default="none", choices=["none", "int8"],
                   help="weight-only quantization for the serving model "
                        "(int8: dequant-in-kernel decode compute)")
    p.add_argument("--precision", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--artifact", default="BENCH_SERVE.json")
    run(p.parse_args())


if __name__ == "__main__":
    main()
