"""Serving load benchmark: open-loop streams against the continuous-batching
engine (thunder_tpu/serving/), reporting aggregate tokens/sec, TTFT/TBOT
p50/p99, page-pool utilization, and the steady-state recompile count.

The load generator is OPEN-LOOP (Orca/vLLM evaluation style): request
arrival times are drawn up front from an exponential inter-arrival process
and requests are submitted on that schedule whatever the engine's backlog —
so queueing delay shows up in TTFT instead of being hidden by a closed loop.
Prompt and output lengths are drawn uniformly from mixed ranges.

Usage:
    python -m thunder_tpu.benchmarks.benchmark_serving --model_name tiny-llama2 \
        --streams 8 --page_size 16 --arrival_rate 16
    BENCH_SERVE=1 python -m thunder_tpu.benchmarks.benchmark_serving ...
        # additionally writes the BENCH_SERVE.json artifact row
"""
from __future__ import annotations

import argparse
import json
import os
import time

import jax.numpy as jnp
import numpy as np


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(round(q * (len(xs) - 1))))]


def run(args) -> dict:
    from thunder_tpu import observability
    from thunder_tpu.models.litgpt import Config, GPT
    from thunder_tpu.serving import ServingEngine

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    cfg = Config.from_name(args.model_name, block_size=max(args.max_seq, 128))
    gpt = GPT(cfg, dtype=dtype)
    engine = ServingEngine(gpt, max_batch=args.max_batch, page_size=args.page_size,
                           max_seq=args.max_seq, dtype=dtype)

    rng = np.random.RandomState(args.seed)
    lens = [(int(rng.randint(args.prompt_len_min, args.prompt_len_max + 1)),
             int(rng.randint(args.new_tokens_min, args.new_tokens_max + 1)))
            for _ in range(args.streams)]
    # exponential inter-arrivals -> open-loop schedule (seconds from t0)
    gaps = rng.exponential(1.0 / args.arrival_rate, size=args.streams)
    arrivals = np.cumsum(gaps) - gaps[0]

    observability.enable()
    # warm every bucket the workload will touch plus the decode step, then
    # clear the counters: any recompile recorded after this point is a
    # steady-state failure
    engine.warmup(sorted({L for L, _ in lens}), max_new_tokens=2)
    observability.reset()

    engine.start()
    t0 = time.perf_counter()
    futs = []
    try:
        for (L, n), at in zip(lens, arrivals):
            dt = t0 + float(at) - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            prompt = rng.randint(0, cfg.vocab_size, (L,)).astype(np.int32)
            futs.append(engine.submit(prompt, max_new_tokens=n,
                                      temperature=args.temperature,
                                      seed=int(rng.randint(1 << 30))))
        results = [f.result(timeout=600) for f in futs]
    finally:
        engine.stop()
    wall = time.perf_counter() - t0

    counters = observability.counters()
    observability.disable()
    recompiles = sum(v for k, v in counters.items() if k.startswith("recompile."))

    import jax

    total_new = sum(r.n_new_tokens for r in results)
    ttfts = [r.ttft_s * 1e3 for r in results]
    tbots = [r.tbot_s * 1e3 for r in results if r.n_new_tokens > 1]
    stats = engine.stats()
    row = {
        "platform": jax.devices()[0].platform,
        "metric": (f"{args.model_name} serving aggregate new tokens/sec "
                   f"({args.streams} open-loop streams, max_batch={args.max_batch}, "
                   f"page_size={args.page_size}, "
                   f"prompts {args.prompt_len_min}-{args.prompt_len_max}, "
                   f"outputs {args.new_tokens_min}-{args.new_tokens_max})"),
        "value": round(total_new / wall, 2),
        "unit": "tokens/s",
        "n_requests": len(results),
        "total_new_tokens": total_new,
        "wall_s": round(wall, 3),
        "ttft_ms_p50": round(_pct(ttfts, 0.50), 2),
        "ttft_ms_p99": round(_pct(ttfts, 0.99), 2),
        "tbot_ms_p50": round(_pct(tbots, 0.50), 2),
        "tbot_ms_p99": round(_pct(tbots, 0.99), 2),
        "decode_steps": stats["decode_steps"],
        "peak_page_pool_utilization": stats["peak_page_pool_utilization"],
        "recompiles_steady_state": int(recompiles),
        "serve_counters": {k: v for k, v in counters.items() if k.startswith("serve.")},
    }
    print(json.dumps(row, indent=1))
    if os.environ.get("BENCH_SERVE") == "1":
        with open(args.artifact, "w") as f:
            json.dump(row, f, indent=1)
        print(f"wrote {args.artifact}")
    return row


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model_name", default="tiny-llama2")
    p.add_argument("--streams", type=int, default=8)
    p.add_argument("--max_batch", type=int, default=8)
    p.add_argument("--page_size", type=int, default=16)
    p.add_argument("--max_seq", type=int, default=256)
    p.add_argument("--prompt_len_min", type=int, default=8)
    p.add_argument("--prompt_len_max", type=int, default=48)
    p.add_argument("--new_tokens_min", type=int, default=8)
    p.add_argument("--new_tokens_max", type=int, default=32)
    p.add_argument("--arrival_rate", type=float, default=8.0,
                   help="open-loop arrivals per second")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--precision", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--artifact", default="BENCH_SERVE.json")
    run(p.parse_args())


if __name__ == "__main__":
    main()
