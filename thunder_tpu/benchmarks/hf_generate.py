"""Timed KV-cache generation for a HF model through the torch interop
frontend (reference README.md:310-316 — the headline interop artifact is a
timed HF ``generate()``).

Design: HF's ``model.generate()`` drives dynamic cache objects through
arbitrary python; the TPU-native equivalent compiles TWO static-shape
programs — prefill (B, T0) and decode (B, 1) — over a ``StaticCache`` whose
key/value buffers are *runtime inputs*: the traced forward constructs the
cache object and installs our trace tensors as its layer buffers, so HF's
``index_copy_`` cache update rides the interop in-place machinery and the
updated buffers flow out as outputs. One compile per phase, true KV-cache
reuse, no recompilation as the sequence grows.

Weights are random-init at the real gpt2-124M config (this environment has
zero egress — no checkpoint downloads); parity is checked greedy-token-exact
against torch eager on the same weights, which is weight-agnostic.
"""
from __future__ import annotations

import json
import time

import numpy as np


def build_static_step(model, config, max_cache_len: int):
    """A torch module computing one cached step (prefill or decode by input
    shape): (input_ids, cache_position, ks, vs) -> (logits, ks', vs')."""
    import torch
    from transformers.cache_utils import StaticCache

    class StaticStep(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.inner = model

        def forward(self, input_ids, cache_position, ks, vs):
            cache = StaticCache(config=config, max_batch_size=input_ids.shape[0],
                                max_cache_len=max_cache_len)
            for layer, k, v in zip(cache.layers, ks, vs):
                # install the traced buffers; update() then index_copy_'s
                # into them in-place (functionalized by the interop frontend)
                layer.keys = k
                layer.values = v
                layer.max_batch_size = input_ids.shape[0]
                layer.dtype = k.dtype
                layer.device = k.device
                layer.is_initialized = True
            # a ready 4-D additive mask: HF's own mask construction routes
            # through torch.vmap (functorch), which bypasses
            # __torch_function__ tracing; building it with plain ops keeps
            # the whole step traceable
            kv_idx = torch.arange(max_cache_len)
            visible = kv_idx[None, :] <= cache_position[:, None]  # (Tq, M)
            mask4d = torch.where(visible, 0.0, torch.finfo(torch.float32).min)
            mask4d = mask4d[None, None].expand(input_ids.shape[0], 1, -1, -1)
            out = self.inner(input_ids=input_ids, past_key_values=cache,
                             cache_position=cache_position,
                             attention_mask=mask4d, use_cache=True)
            return (out.logits[:, -1, :],
                    tuple(l.keys for l in cache.layers),
                    tuple(l.values for l in cache.layers))

    return StaticStep()


def generate_interop(model, config, prompt_ids: np.ndarray, new_tokens: int,
                     max_cache_len: int | None = None):
    """Greedy KV-cache generation through the compiled interop path.

    Returns (token list, prefill_seconds, decode_seconds_per_token)."""
    import jax
    import jax.numpy as jnp

    from ..interop.torch_frontend import compile_torch_module

    B, T0 = prompt_ids.shape
    M = max_cache_len or (T0 + new_tokens)
    H = config.n_head if hasattr(config, "n_head") else config.num_attention_heads
    D = (config.n_embd if hasattr(config, "n_embd") else config.hidden_size) // H
    L = config.n_layer if hasattr(config, "n_layer") else config.num_hidden_layers

    step = compile_torch_module(build_static_step(model, config, M))
    ks = tuple(jnp.zeros((B, H, M, D), jnp.float32) for _ in range(L))
    vs = tuple(jnp.zeros((B, H, M, D), jnp.float32) for _ in range(L))

    ids = jnp.asarray(prompt_ids, jnp.int64)
    # compile the prefill shape (fresh zero caches after; timing excludes it)
    jax.block_until_ready(step(ids, jnp.arange(T0, dtype=jnp.int64), ks, vs)[0])
    t0 = time.perf_counter()
    logits, ks, vs = step(ids, jnp.arange(T0, dtype=jnp.int64), ks, vs)
    nxt = jnp.argmax(logits, -1).astype(jnp.int64)
    float(logits[0, 0])  # sync
    prefill_s = time.perf_counter() - t0

    toks_dev = [nxt]
    # compile the decode shape once
    logits, ks, vs = step(nxt[:, None], jnp.asarray([T0], jnp.int64), ks, vs)
    nxt = jnp.argmax(logits, -1).astype(jnp.int64)
    toks_dev.append(nxt)

    # async decode: tokens stay on device so steps pipeline through the
    # dispatch queue (one host sync at the end, not per token)
    t1 = time.perf_counter()
    for i in range(1, new_tokens - 1):
        logits, ks, vs = step(nxt[:, None], jnp.asarray([T0 + i], jnp.int64), ks, vs)
        nxt = jnp.argmax(logits, -1).astype(jnp.int64)
        toks_dev.append(nxt)
    jax.block_until_ready(nxt)
    decode_s_per_tok = (time.perf_counter() - t1) / max(1, new_tokens - 2)
    return [int(t[0]) for t in toks_dev], prefill_s, decode_s_per_tok


def generate_torch_eager(model, prompt_ids: np.ndarray, new_tokens: int):
    """Greedy generation with torch eager + its own KV cache (the reference
    competitor), timed the same way."""
    import torch

    ids = torch.as_tensor(prompt_ids)
    with torch.no_grad():
        t0 = time.perf_counter()
        out = model(input_ids=ids, use_cache=True)
        past = out.past_key_values
        nxt = out.logits[:, -1, :].argmax(-1)
        prefill_s = time.perf_counter() - t0
        tokens = [int(nxt[0])]
        t1 = time.perf_counter()
        for _ in range(new_tokens - 1):
            out = model(input_ids=nxt[:, None], past_key_values=past, use_cache=True)
            past = out.past_key_values
            nxt = out.logits[:, -1, :].argmax(-1)
            tokens.append(int(nxt[0]))
        decode_s_per_tok = (time.perf_counter() - t1) / max(1, new_tokens - 1)
    return tokens, prefill_s, decode_s_per_tok


def logits_parity(model, config, prompt_ids: np.ndarray, steps: int = 8,
                  max_cache_len: int = 128) -> float:
    """Max-abs-err between interop and torch-eager *logits* along the decode
    path, both fed torch's greedy tokens (identical inputs at every step) —
    the decisive parity check, independent of argmax tie-breaking."""
    import jax.numpy as jnp
    import torch

    from ..interop.torch_frontend import compile_torch_module

    B, T0 = prompt_ids.shape
    M = max_cache_len
    H = config.n_head if hasattr(config, "n_head") else config.num_attention_heads
    D = (config.n_embd if hasattr(config, "n_embd") else config.hidden_size) // H
    L = config.n_layer if hasattr(config, "n_layer") else config.num_hidden_layers

    step = compile_torch_module(build_static_step(model, config, M))
    ks = tuple(jnp.zeros((B, H, M, D), jnp.float32) for _ in range(L))
    vs = tuple(jnp.zeros((B, H, M, D), jnp.float32) for _ in range(L))

    ids_t = torch.as_tensor(prompt_ids)
    errs = []
    with torch.no_grad():
        out_t = model(input_ids=ids_t, use_cache=True)
        past = out_t.past_key_values
        logits_t = out_t.logits[:, -1, :]
        logits_j, ks, vs = step(jnp.asarray(prompt_ids, jnp.int64),
                                jnp.arange(T0, dtype=jnp.int64), ks, vs)
        errs.append(float(jnp.max(jnp.abs(logits_j - jnp.asarray(logits_t.numpy())))))
        nxt_t = logits_t.argmax(-1)
        for i in range(steps):
            out_t = model(input_ids=nxt_t[:, None], past_key_values=past, use_cache=True)
            past = out_t.past_key_values
            logits_t = out_t.logits[:, -1, :]
            logits_j, ks, vs = step(jnp.asarray(nxt_t.numpy()[:, None], jnp.int64),
                                    jnp.asarray([T0 + i], jnp.int64), ks, vs)
            errs.append(float(jnp.max(jnp.abs(logits_j - jnp.asarray(logits_t.numpy())))))
            nxt_t = logits_t.argmax(-1)
    return max(errs)


def run_gpt2(new_tokens: int = 64, prompt_len: int = 32, tiny: bool = False) -> dict:
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = (GPT2Config(n_layer=2, n_embd=64, n_head=4) if tiny else GPT2Config())
    torch.manual_seed(0)
    model = GPT2LMHeadModel(cfg).eval()
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, (1, prompt_len))

    tok_i, pre_i, dec_i = generate_interop(model, cfg, prompt, new_tokens)
    tok_e, pre_e, dec_e = generate_torch_eager(model, prompt, new_tokens)
    n_match = sum(a == b for a, b in zip(tok_i, tok_e))
    # same max_cache_len as generate_interop so the parity probe reuses the
    # persistent-cache executables instead of compiling a third shape
    max_logit_err = logits_parity(model, cfg, prompt, steps=8,
                                  max_cache_len=prompt_len + new_tokens)
    return {
        "decode_logits_max_abs_err": round(max_logit_err, 6),
        "model": "gpt2-124M (real config, random init: zero-egress env)" if not tiny else "gpt2-tiny",
        "new_tokens": new_tokens,
        "prompt_len": prompt_len,
        "greedy_tokens_match": f"{n_match}/{min(len(tok_i), len(tok_e))}",
        "interop_decode_tok_per_s": round(1.0 / dec_i, 1),
        "torch_eager_decode_tok_per_s": round(1.0 / dec_e, 1),
        "speedup_vs_eager": round(dec_e / dec_i, 2),
        "interop_prefill_s": round(pre_i, 3),
        "eager_prefill_s": round(pre_e, 3),
    }


if __name__ == "__main__":
    import sys

    print(json.dumps(run_gpt2(tiny="--tiny" in sys.argv)))
