"""BENCH_7B.json: the 7B-shape evidence set on one chip.

The north star (BASELINE.json; reference benchmark_litgpt.py:475-479) is
Llama-2-7B tokens/sec — the full 32-layer model's AdamW state cannot fit one
16 GB v5e chip (1.07 GB params x 12 bytes f32 master+moments alone is
~13 GB x 8 = impossible at 32 layers), so the honest single-chip evidence is:

1. the 7B-shape microbench targets (one full-dims attention layer, one MLP,
   QKV+RoPE at width 4096 / head_dim 128), and
2. a 4-block 7B-dims stack (``llama-7b-block4``: everything per-layer is
   EXACTLY Llama-2-7B's shape; only depth is truncated) trained end-to-end —
   fwd+bwd+AdamW with activation checkpointing at B=1, T=2048 — through the
   same bench.py machinery as every other row, with MFU and the
   hand-written-jax vs_baseline column.

Run on chip:  python -m thunder_tpu.benchmarks.bench_7b
Writes BENCH_7B.json at the repo root (or $BENCH_7B_OUT).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def run_targets() -> list[dict]:
    import numpy as np

    from . import targets

    rows = []
    for name in ("llama2_7b_attention", "llama_mlp_7b", "litgpt_qkv_rope"):
        t0 = time.perf_counter()
        seconds = targets.BENCHMARKS[name](np.random.RandomState(0))
        rows.append({
            "target": name,
            "ms": round(seconds * 1e3, 2),
            "wall_s": round(time.perf_counter() - t0, 1),
        })
    return rows


def run_block_stack(B: int = 1, T: int = 2048, iters: int = 10) -> dict:
    """The 4-block 7B-dims train step through bench.py's row machinery."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env.update({
        "BENCH_MODEL": "llama-7b-block4",
        "BENCH_BATCH": str(B),
        "BENCH_SEQLEN": str(T),
        "BENCH_CKPT": "1",
        "BENCH_ITERS": str(iters),
    })
    out = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                         env=env, capture_output=True, text=True, timeout=3600)
    if out.returncode != 0:
        raise RuntimeError(f"block-stack bench failed: {out.stderr[-800:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    result = {
        "comment": ("7B-shape single-chip evidence: per-layer dims are exactly "
                    "Llama-2-7B's (width 4096, head_dim 128, MLP 11008, vocab 32k); "
                    "the stack row is a 4-block depth truncation (the deepest whose "
                    "f32 AdamW state fits 16 GB), fwd+bwd+adamw+ckpt"),
        "targets_ms": run_targets(),
        "block_stack": run_block_stack(),
    }
    out_path = os.environ.get("BENCH_7B_OUT", "BENCH_7B.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result["block_stack"]))


if __name__ == "__main__":
    main()
