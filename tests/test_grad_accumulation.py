"""no_sync gradient accumulation (reference ThunderModule.no_sync,
thunder/core/module.py:341 + skip_data_parallel_grad_sync)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import thunder_tpu as tt
from thunder_tpu import nn, optim
from thunder_tpu.ops import ltorch
from thunder_tpu.training import TrainStep


class _Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4, seed=0)

    def forward(self, x, y):
        return ltorch.mse_loss(self.fc(x), y)


def _batches(rng, n=3):
    return [(jnp.asarray(rng.rand(4, 8).astype(np.float32)),
             jnp.asarray(rng.rand(4, 4).astype(np.float32))) for _ in range(n)]


def test_no_sync_defers_update(rng):
    net = _Net()
    tm = tt.jit(net)
    step = TrainStep(tm, optim.AdamW(lr=0.1))
    batches = _batches(rng)
    w0 = np.asarray(net.fc.weight.data).copy()
    with tm.no_sync():
        step(*batches[0])
        step(*batches[1])
    # params untouched while accumulating
    np.testing.assert_array_equal(w0, np.asarray(net.fc.weight.data))
    step(*batches[2])
    assert not np.array_equal(w0, np.asarray(net.fc.weight.data))


def test_accumulated_equals_summed_grads(rng):
    """K micro steps + 1 sync step == one update with the summed grads."""
    batches = _batches(rng)

    net_a = _Net()
    tm_a = tt.jit(net_a)
    step_a = TrainStep(tm_a, optim.AdamW(lr=0.05))
    with tm_a.no_sync():
        step_a(*batches[0])
        step_a(*batches[1])
    step_a(*batches[2])

    # manual: sum the three grads, single AdamW update on identical init
    net_b = _Net()

    def loss_fn(w, b, x, y):
        return jnp.mean((x @ w.T + b - y) ** 2)

    w = jnp.asarray(net_b.fc.weight.data)
    b = jnp.asarray(net_b.fc.bias.data)
    gw = jnp.zeros_like(w)
    gb = jnp.zeros_like(b)
    for x, y in batches:
        dw, db = jax.grad(loss_fn, argnums=(0, 1))(w, b, x, y)
        gw += dw
        gb += db
    opt = optim.AdamW(lr=0.05)
    params = {"fc.weight": w, "fc.bias": b}
    state = opt.init(params)
    new_params, _ = opt.update(params, {"fc.weight": gw, "fc.bias": gb}, state)

    np.testing.assert_allclose(np.asarray(net_a.fc.weight.data),
                               np.asarray(new_params["fc.weight"]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(net_a.fc.bias.data),
                               np.asarray(new_params["fc.bias"]), atol=1e-5)


def test_ddp_no_sync_matches_single_device(rng):
    """Distributed (pure-DDP) no_sync accumulation == single-device result."""
    from thunder_tpu.parallel import ddp, make_mesh

    batches = _batches(rng)

    net_a = _Net()
    tm_a = tt.jit(net_a)
    ddp(tm_a, make_mesh({"dp": 4}))
    step_a = TrainStep(tm_a, optim.AdamW(lr=0.05))
    with tm_a.no_sync():
        step_a(*batches[0])
        step_a(*batches[1])
    step_a(*batches[2])

    net_b = _Net()
    step_b = TrainStep(tt.jit(net_b), optim.AdamW(lr=0.05))
    tm_b = step_b.tmodule
    with tm_b.no_sync():
        step_b(*batches[0])
        step_b(*batches[1])
    step_b(*batches[2])

    np.testing.assert_allclose(np.asarray(net_a.fc.weight.data),
                               np.asarray(net_b.fc.weight.data), atol=1e-5)


def test_fsdp_no_sync_matches_single_device(rng):
    """FSDP no_sync: params gathered once per window, micro-steps accumulate
    full local grads with no collectives, fold reduce-scatters once
    (reference FSDP no_sync + STASH_GRAD_FOR_FSDP,
    thunder/distributed/__init__.py:36,108-115)."""
    from thunder_tpu.parallel import fsdp, make_mesh

    batches = _batches(rng)

    net_a = _Net()
    tm_a = tt.jit(net_a)
    fsdp(tm_a, make_mesh({"fsdp": 4}), min_shard_numel=1)
    step_a = TrainStep(tm_a, optim.AdamW(lr=0.05))
    with tm_a.no_sync():
        step_a(*batches[0])
        step_a(*batches[1])
    step_a(*batches[2])

    net_b = _Net()
    step_b = TrainStep(tt.jit(net_b), optim.AdamW(lr=0.05))
    tm_b = step_b.tmodule
    with tm_b.no_sync():
        step_b(*batches[0])
        step_b(*batches[1])
    step_b(*batches[2])

    np.testing.assert_allclose(np.asarray(net_b.fc.weight.data),
                               np.asarray(net_a.fc.weight.data), atol=1e-5)


def test_fsdp_no_sync_micro_steps_do_not_communicate():
    """The compiled FSDP micro-step program must contain no gradient
    collectives (that is the point of no_sync) — only the scalar loss psum."""
    from thunder_tpu.parallel import fsdp, make_mesh

    rng = np.random.RandomState(1)
    net = _Net()
    tm = tt.jit(net)
    fsdp(tm, make_mesh({"fsdp": 4}), min_shard_numel=1)
    step = TrainStep(tm, optim.AdamW(lr=0.05))
    x = jnp.asarray(rng.rand(4, 8).astype(np.float32))
    y = jnp.asarray(rng.rand(4, 4).astype(np.float32))
    with tm.no_sync():
        step(x, y)
    # the micro vag traces must contain no collectives
    bwd_src = step._vag_full._cs.last_backward_traces[0].python()
    assert "reduce_scatter" not in bwd_src and "all_gather" not in bwd_src
    step(x, y)  # fold step closes the window


def test_2d_ddp_fsdp_no_sync_matches_single_device(rng):
    """Mixed dp x fsdp plan: the fold must sum grads over the dp axis AND
    reduce-scatter over the fsdp axis — missing either silently diverges the
    dp replicas (regression test for exactly that bug)."""
    from thunder_tpu.parallel import ddp, fsdp, make_mesh

    batches = _batches(rng)

    net_a = _Net()
    tm_a = tt.jit(net_a)
    mesh = make_mesh({"dp": 2, "fsdp": 2})
    ddp(tm_a, mesh)
    fsdp(tm_a, mesh, min_shard_numel=1)
    step_a = TrainStep(tm_a, optim.AdamW(lr=0.05))
    with tm_a.no_sync():
        step_a(*batches[0])
        step_a(*batches[1])
    step_a(*batches[2])

    net_b = _Net()
    step_b = TrainStep(tt.jit(net_b), optim.AdamW(lr=0.05))
    with step_b.tmodule.no_sync():
        step_b(*batches[0])
        step_b(*batches[1])
    step_b(*batches[2])

    np.testing.assert_allclose(np.asarray(net_b.fc.weight.data),
                               np.asarray(net_a.fc.weight.data), atol=1e-5)
