"""The three MFU levers (ROADMAP #5a, profiler-driven): collective overlap
scheduling, the fused fp8 scaling kernel, and int8 weight-quantized decode.

Each lever's safety property is held EXACTLY, not approximately:

* the overlap compiler-option config rides the AOT step key, so a config
  flip must MISS the executable cache (never silently reuse a
  non-overlapped program);
* bucketed grad all-reduce (``ddp(..., bucket_mb=)``) is pure data movement
  around the same reduction — bit-identical losses and parameters vs the
  unbucketed program;
* the fused fp8 kernel (quantize + amax + e4m3 dot in one VMEM pass) is
  bit-identical to the unfused four-program reference, because e4m3 values
  are exactly representable in bf16 and both roads accumulate in f32;
* int8 weight-quantized decode is token-identical to bf16 at temperature 0
  when the weights are exactly int8-representable (q * power-of-two scale
  roundtrips through quantize_int8 without error).

Runs entirely under JAX_PLATFORMS=cpu (conftest: 8 virtual devices); the
pallas kernels run in interpret mode.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import thunder_tpu as tt
from thunder_tpu import nn, optim
from thunder_tpu.ops import ltorch

pytestmark = [
    pytest.mark.perf,
    pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices"),
]


class LossMLP(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 64, seed=1)
        self.fc2 = nn.Linear(64, 8, seed=2)

    def forward(self, x, y):
        return ltorch.mse_loss(self.fc2(ltorch.gelu(self.fc1(x))), y)


def _batch(seed=0, n=16):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, 16), jnp.float32)
    y = jnp.asarray(rng.randn(n, 8), jnp.float32)
    return x, y


# ---------------------------------------------------------------------------
# lever (a): overlap scheduling — config must ride the AOT step key
# ---------------------------------------------------------------------------


class TestOverlapKey:
    def test_resolve_key_semantics(self):
        from thunder_tpu.parallel.overlap import resolve_overlap_options

        opts_off, key_off = resolve_overlap_options(False)
        assert key_off == "nooverlap" and opts_off == {}
        # probe=False: key semantics are backend-independent (the key
        # encodes the REQUESTED config, not the probed subset)
        _, key_on = resolve_overlap_options(True, probe=False)
        assert key_on.startswith("overlap[") and key_on != key_off
        _, key_extra = resolve_overlap_options(
            True, {"xla_something_else": 7}, probe=False)
        assert key_extra not in (key_on, key_off)
        # deterministic: same request, same key
        assert resolve_overlap_options(True, probe=False)[1] == key_on

    def test_probe_filters_unknown_options(self):
        from thunder_tpu.parallel.overlap import supported_compiler_options

        accepted = supported_compiler_options(
            {"xla_definitely_not_a_real_option_name": True})
        assert accepted == {}

    def test_overlap_flip_misses_aot_cache(self):
        """Two gspmd steps differing ONLY in overlap config must produce
        different AOT step keys — a flip is a cache miss, never a silent
        reuse of the other config's executable."""
        from thunder_tpu.parallel import (DistPlan, ParamStrategy, gspmd_step,
                                          make_mesh)

        mesh = make_mesh({"dp": 8})
        x, y = _batch()

        def build(overlap):
            tm = tt.jit(LossMLP())
            plan = DistPlan(mesh, {k: [ParamStrategy("replicate", "dp")]
                                   for k in tm.get_parameters()}, ("dp",))
            step = gspmd_step(tm, optim.AdamW(lr=0.05), plan, overlap=overlap)
            params = {k: p.data for k, p in tm.get_parameters().items()}
            step.opt_state = step.optimizer.init(params)
            return step, params

        step_on, params_on = build(True)
        step_off, params_off = build(False)
        assert step_on._overlap_key != step_off._overlap_key
        key_on = step_on._aot_key(params_on, {}, (x, y), {})
        key_off = step_off._aot_key(params_off, {}, (x, y), {})
        assert key_on != key_off


# ---------------------------------------------------------------------------
# lever (a), explicit road: bucketed grad-sync is bit-identical
# ---------------------------------------------------------------------------


class TestGradBucketing:
    def test_bucketed_bit_identical_to_unbucketed(self):
        """pack -> one all_reduce -> unpack is pure data movement around the
        same reduction: losses AND final params must be exactly equal."""
        from thunder_tpu.parallel import ddp, make_mesh

        x, y = _batch()
        m_ref = LossMLP()
        sd = {k: np.asarray(v).copy() for k, v in m_ref.state_dict().items()}

        def run(bucket_mb):
            m = LossMLP()
            m.load_state_dict(sd)
            tm = tt.jit(m)
            ddp(tm, make_mesh({"dp": 2}), bucket_mb=bucket_mb)
            from thunder_tpu.training import TrainStep

            step = TrainStep(tm, optim.AdamW(lr=1e-2))
            losses = [float(step(x, y)) for _ in range(3)]
            params = {k: np.asarray(v) for k, v in m.state_dict().items()}
            return losses, params

        losses_plain, params_plain = run(None)
        # tiny bucket cap so the pack actually splits into multiple buckets
        losses_bucketed, params_bucketed = run(0.001)
        assert losses_plain == losses_bucketed  # float-exact, not allclose
        for k in params_plain:
            np.testing.assert_array_equal(params_plain[k], params_bucketed[k])

    def test_bucketing_transform_in_repr(self):
        from thunder_tpu.parallel import ddp, make_mesh

        tm = tt.jit(LossMLP())
        ddp(tm, make_mesh({"dp": 2}), bucket_mb=25)
        reprs = [repr(t) for t in tm._cfn._transforms]
        assert any("GradBucketing" in r for r in reprs)


# ---------------------------------------------------------------------------
# lever (b): fused fp8 scaling kernel
# ---------------------------------------------------------------------------


class TestFusedFP8:
    def _ref_unfused(self, x, w, sx, sw, fmt_max):
        """The four-program reference the fusion replaces: quantize x,
        quantize w, e4m3 dot (f32 accumulation), amax reductions."""
        xq = jnp.clip(x.astype(jnp.float32) * sx, -fmt_max, fmt_max
                      ).astype(jnp.float8_e4m3fn)
        wq = jnp.clip(w.astype(jnp.float32) * sw, -fmt_max, fmt_max
                      ).astype(jnp.float8_e4m3fn)
        y = jax.lax.dot_general(
            xq.astype(jnp.bfloat16), wq.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        y = (y / (sx * sw)).astype(x.dtype)
        ax = jnp.max(jnp.abs(x)).astype(jnp.float32)
        aw = jnp.max(jnp.abs(w)).astype(jnp.float32)
        return y, xq, wq, ax, aw

    def test_kernel_bit_identical_to_unfused(self):
        from thunder_tpu.executors.pallasex import fp8_linear_fused
        from thunder_tpu.transforms.fp8_training import E4M3_MAX

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 256), jnp.float32)
        w = jnp.asarray(rng.randn(128, 256), jnp.float32)
        sx = float(E4M3_MAX / float(jnp.max(jnp.abs(x))))
        sw = float(E4M3_MAX / float(jnp.max(jnp.abs(w))))
        y_ref, xq_ref, wq_ref, ax_ref, aw_ref = self._ref_unfused(
            x, w, sx, sw, E4M3_MAX)
        y, xq, wq, ax, aw = fp8_linear_fused(
            x, w, sx, sw, fmt_max=E4M3_MAX, save_quantized=True)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_ref))
        np.testing.assert_array_equal(np.asarray(xq).view(np.uint8),
                                      np.asarray(xq_ref).view(np.uint8))
        np.testing.assert_array_equal(np.asarray(wq).view(np.uint8),
                                      np.asarray(wq_ref).view(np.uint8))
        assert float(ax) == float(ax_ref) and float(aw) == float(aw_ref)

    def test_kernel_multi_k_block_accumulation(self):
        """K larger than one block exercises the grid-resident accumulator
        and the idempotent amax accumulation across k revisits."""
        from thunder_tpu.executors.pallasex import fp8_linear_fused
        from thunder_tpu.transforms.fp8_training import E4M3_MAX

        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(16, 1024), jnp.float32)
        w = jnp.asarray(rng.randn(128, 1024), jnp.float32)
        sx, sw = 8.0, 4.0  # power-of-two scales: quantize/de-scale exact
        bk = 256
        y_one, _, _, ax_ref, aw_ref = self._ref_unfused(x, w, sx, sw, E4M3_MAX)
        y, ax, aw = fp8_linear_fused(x, w, sx, sw, fmt_max=E4M3_MAX,
                                     block_k=bk)
        # bit-identity holds against a reference that sums partial e4m3
        # dots in the kernel's k-block order (each block dot is exact; only
        # the f32 accumulation split differs from a single whole-K dot)
        acc = jnp.zeros((16, 128), jnp.float32)
        for k0 in range(0, 1024, bk):
            xq = jnp.clip(x[:, k0:k0 + bk] * sx, -E4M3_MAX, E4M3_MAX
                          ).astype(jnp.float8_e4m3fn)
            wq = jnp.clip(w[:, k0:k0 + bk] * sw, -E4M3_MAX, E4M3_MAX
                          ).astype(jnp.float8_e4m3fn)
            acc = acc + jax.lax.dot_general(
                xq.astype(jnp.bfloat16), wq.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        y_blocked = (acc / (sx * sw)).astype(x.dtype)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(y_blocked))
        # and the whole-K dot agrees to f32 rounding of the split
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_one),
                                   rtol=1e-5, atol=1e-4)
        assert float(ax) == float(ax_ref) and float(aw) == float(aw_ref)

    def test_checker_requires_tpu_or_force(self, monkeypatch):
        from thunder_tpu.executors.pallasex import fp8_linear_fused_supported

        x = jnp.zeros((64, 256), jnp.float32)
        w = jnp.zeros((128, 256), jnp.float32)
        monkeypatch.delenv("TT_FP8_FUSED", raising=False)
        assert not fp8_linear_fused_supported(x, w)  # CPU: off by default
        monkeypatch.setenv("TT_FP8_FUSED", "force")
        assert fp8_linear_fused_supported(x, w)
        # misaligned shapes never claim, even forced
        assert not fp8_linear_fused_supported(jnp.zeros((64, 250)), w)

    def test_forced_fused_training_matches_unfused(self, monkeypatch):
        """End-to-end: the fp8 training transform produces the same losses
        whether the linears dispatch to the fused kernel or the unfused
        four-program road."""
        from thunder_tpu.training import TrainStep
        from thunder_tpu.transforms.fp8_training import FP8TrainingTransform

        rng = np.random.RandomState(2)
        d = 256
        x = jnp.asarray(rng.randn(32, d), jnp.float32)
        y = jnp.asarray(rng.randn(32, d), jnp.float32)

        class Net(nn.Module):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(d, d, seed=3)
                self.fc2 = nn.Linear(d, d, seed=4)

            def forward(self, xx, yy):
                return ltorch.mse_loss(self.fc2(ltorch.relu(self.fc1(xx))), yy)

        def run(mode):
            monkeypatch.setenv("TT_FP8_FUSED", mode)
            tm = tt.jit(Net(), transforms=[FP8TrainingTransform()])
            step = TrainStep(tm, optim.AdamW(lr=1e-2))
            return [float(step(x, y)) for _ in range(3)]

        losses_unfused = run("0")
        losses_fused = run("force")
        np.testing.assert_allclose(losses_fused, losses_unfused, rtol=1e-6)


# ---------------------------------------------------------------------------
# lever (c): int8 weight-quantized decode
# ---------------------------------------------------------------------------


def _make_int8_exact(gpt, seed=0):
    """Overwrite every nn.Linear weight with values that roundtrip through
    quantize_int8 without error: w = q * s with integer q (per-row max
    |q| = 127) and a power-of-two scale s. quantize_int8 recovers q and s
    exactly, and q * s is exactly representable in bf16 (7-bit magnitudes
    fit bf16's 8-bit mantissa), so the dequantized matmul sees bitwise the
    original weights."""
    rng = np.random.RandomState(seed)
    for name, mod in gpt.named_modules():
        if isinstance(mod, nn.Linear):
            out_f, in_f = np.asarray(mod.weight.data).shape
            q = rng.randint(-126, 127, size=(out_f, in_f)).astype(np.float64)
            q[:, 0] = 127.0  # pin the per-row amax so scale == s exactly
            s = 2.0 ** -9  # power of two: amax/127 divides out exactly
            mod.weight.data = jnp.asarray(q * s, jnp.float32)


class TestInt8Decode:
    def _gpt(self):
        from thunder_tpu.models.litgpt import GPT, Config

        cfg = Config.from_name("tiny-llama2", block_size=64)
        return GPT(cfg, dtype=jnp.float32)

    def test_quantize_int8_exact_roundtrip(self):
        from thunder_tpu.transforms.quantization import quantize_int8

        gpt = self._gpt()
        _make_int8_exact(gpt)
        w = jnp.asarray(gpt.lm_head.weight.data)
        q, s = quantize_int8(w)
        deq = (q.astype(jnp.bfloat16) * s.astype(jnp.bfloat16)[:, None]
               ).astype(jnp.float32)
        np.testing.assert_array_equal(np.asarray(deq), np.asarray(w))

    def test_int8_decode_token_identical(self):
        """Greedy streams from a bf16-weights engine and an int8-quantized
        engine over the SAME (exactly-representable) weights must match
        token for token."""
        from thunder_tpu.serving import ServingEngine

        gpt_a = self._gpt()
        _make_int8_exact(gpt_a)
        sd = {k: np.asarray(v).copy() for k, v in gpt_a.state_dict().items()}
        gpt_b = self._gpt()
        gpt_b.load_state_dict(sd)

        kw = dict(max_batch=4, page_size=8, max_seq=64, dtype=jnp.float32)
        eng_a = ServingEngine(gpt_a, **kw)
        eng_b = ServingEngine(gpt_b, quantize="int8", **kw)

        rng = np.random.RandomState(7)
        prompts = [rng.randint(0, 320, (n,)).astype(np.int32)
                   for n in (5, 11, 17)]
        futs_a = [eng_a.submit(p, max_new_tokens=8) for p in prompts]
        futs_b = [eng_b.submit(p, max_new_tokens=8) for p in prompts]
        eng_a.drain()
        eng_b.drain()
        for fa, fb in zip(futs_a, futs_b):
            ra, rb = fa.result(), fb.result()
            assert ra.n_new_tokens == 8
            np.testing.assert_array_equal(ra.new_tokens, rb.new_tokens)

    def test_quantize_for_serving_modes(self):
        from thunder_tpu.serving.runner import quantize_for_serving

        gpt = self._gpt()
        assert quantize_for_serving(gpt, None) is gpt
        assert quantize_for_serving(gpt, "none") is gpt
        with pytest.raises(ValueError, match="quantization mode"):
            quantize_for_serving(gpt, "int4")

    def test_int8_kernel_checker_gated_off_tpu(self, monkeypatch):
        """Without TT_INT8_PALLAS_CPU the interpret-mode kernel must not
        claim the op on CPU — serving there measures the XLA dequant-matmul,
        not a per-call interpreter."""
        from thunder_tpu.executors.pallasex import _int8_linear_supported

        x = jnp.zeros((8, 256), jnp.bfloat16)
        q = jnp.zeros((128, 256), jnp.int8)
        s = jnp.zeros((128,), jnp.float32)
        monkeypatch.delenv("TT_INT8_PALLAS_CPU", raising=False)
        assert not _int8_linear_supported(x, q, s)
        monkeypatch.setenv("TT_INT8_PALLAS_CPU", "1")
        assert _int8_linear_supported(x, q, s)
