"""Test configuration: virtual 8-device CPU mesh, float64 oracle enabled.

Mirrors the reference's distributed test strategy (SURVEY.md §4): the
reference spawns real NCCL processes (thunder/tests/distributed/helper.py:146);
on the jax stack a virtual CPU mesh via --xla_force_host_platform_device_count
covers multi-device semantics in-process."""
import os

# TT_ONCHIP=1 keeps the ambient TPU platform for the on-chip smoke tests
# (tests/test_onchip.py); default is the virtual 8-device CPU mesh.
_ONCHIP = os.environ.get("TT_ONCHIP") == "1"

if not _ONCHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env may say "axon" (TPU tunnel)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = flags + " --xla_force_host_platform_device_count=8"
    if "xla_backend_optimization_level" not in flags:
        # tier-1 on CPU is compile-bound (thousands of tiny jits on one
        # core): backend opt level 1 cuts wall time ~20% with the failure
        # set byte-identical to the default level. Level 0 is NOT safe —
        # it breaks cross-program bit-equality (guarded-vs-unguarded step
        # trajectories). Subprocess tests (quickstarts, the multiprocess
        # harness) inherit this via os.environ.
        flags = flags + " --xla_backend_optimization_level=1"
    os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

if not _ONCHIP:
    # The ambient environment pre-imports jax (sitecustomize on PYTHONPATH)
    # with JAX_PLATFORMS=axon, so the env vars above are read too late.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(1234)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test (deselected by the tier-1 run)")
    config.addinivalue_line(
        "markers",
        "fault: fault-injection test (exercises TT_FAULT recovery paths; "
        "filter with -m fault / -m 'not fault')")
    config.addinivalue_line(
        "markers",
        "serve: serving-engine test (continuous batching + paged KV cache; "
        "runs under JAX_PLATFORMS=cpu interpret mode in tier-1; filter with "
        "-m serve / -m 'not serve')")
    config.addinivalue_line(
        "markers",
        "telemetry: live-telemetry test (streaming percentiles, metrics "
        "exporter, SLO monitors, perf gate; filter with -m telemetry / "
        "-m 'not telemetry')")
    config.addinivalue_line(
        "markers",
        "analysis: static-analysis test (trace verifier, pass-interposed "
        "checking, alias/donation safety, memory budgeting; filter with "
        "-m analysis / -m 'not analysis')")
    config.addinivalue_line(
        "markers",
        "compile: compile-service test (content-addressed artifact store, "
        "parallel region compilation, bucketed lowering, warm-start smoke; "
        "filter with -m compile / -m 'not compile')")
    config.addinivalue_line(
        "markers",
        "dist: multi-process distributed test (subprocess-spawned 2-process "
        "CPU cluster via jax.distributed + gloo; these also carry `slow` so "
        "tier-1 stays fast — run with -m dist)")
    config.addinivalue_line(
        "markers",
        "perf: performance-lever correctness test (overlap cache keys, "
        "bucketed grad-sync bit-identity, fused fp8 kernel parity, int8 "
        "decode token-identity, committed-artifact schema gates; filter "
        "with -m perf / -m 'not perf')")
    config.addinivalue_line(
        "markers",
        "moe: mixture-of-experts test (grouped-dispatch bit-identity, "
        "capacity/drop semantics, EP×DP mesh wiring, moe.* telemetry; "
        "filter with -m moe / -m 'not moe')")
    config.addinivalue_line(
        "markers",
        "longctx: long-context test (streaming ring-flash identity, GQA "
        "ring attention, 32k paged serving; the genuinely long-T runs also "
        "carry `slow`; filter with -m longctx / -m 'not longctx')")


def pytest_collection_modifyitems(config, items):
    # TT_TEST_ORDER_SEED=<int> runs the suite in a seeded random order to
    # flush out cross-test global-state leaks (registry/cache pollution).
    seed = os.environ.get("TT_TEST_ORDER_SEED")
    if seed:
        import random

        random.Random(int(seed)).shuffle(items)
