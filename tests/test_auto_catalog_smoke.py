"""Generated smoke tests: every auto-catalog entry runs once against the real
torch op (CPU reference), resolved by the same naming convention the frontend
uses (VERDICT r2 #3: 'a generated smoke test per entry').

SAMPLES maps catalog key -> lambda(rng) -> (args, kwargs) built with numpy;
each test converts to torch for the reference and to jax for our symbol,
then compares. Entries in NO_TORCH_REF have no 1:1 torch callable (helpers
or alias-only names) and get an execution-only check.
"""
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import torch

import thunder_tpu as tt
from thunder_tpu.ops import auto_register as ar

F = torch.nn.functional


def t32(x):
    return np.asarray(x, np.float32)


def _f(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.standard_normal(shape)) + 0.1).astype(np.float32)


def _spd(rng, n):
    a = rng.standard_normal((n, n)).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


# key -> sample builder. Returns (args, kwargs); tensors as numpy arrays.
SAMPLES = {
    # dtype casts
    "bfloat16": lambda r: ((_f(r, 3, 4),), {}),
    "half": lambda r: ((_f(r, 3, 4),), {}),
    "double": lambda r: ((_f(r, 3, 4),), {}),
    "cfloat": lambda r: ((_f(r, 3, 4),), {}),
    "bool": lambda r: ((np.array([0.0, 1.0, 2.0], np.float32),), {}),
    "byte": lambda r: ((np.array([0, 1, 250], np.int32),), {}),
    "char": lambda r: ((np.array([0, 1, 100], np.int32),), {}),
    "short": lambda r: ((np.array([0, 1, 1000], np.int32),), {}),
    "int": lambda r: ((np.array([0.5, 1.7, -2.3], np.float32),), {}),
    # comparisons / elementwise
    "greater": lambda r: ((_f(r, 4, 5), _f(r, 4, 5)), {}),
    "greater_equal": lambda r: ((_f(r, 4, 5), _f(r, 4, 5)), {}),
    "less": lambda r: ((_f(r, 4, 5), _f(r, 4, 5)), {}),
    "less_equal": lambda r: ((_f(r, 4, 5), _f(r, 4, 5)), {}),
    "not_equal": lambda r: ((_f(r, 4, 5), _f(r, 4, 5)), {}),
    "clip": lambda r: ((_f(r, 4, 5), -0.5, 0.5), {}),
    "sgn": lambda r: ((_f(r, 4, 5),), {}),
    "hypot": lambda r: ((_pos(r, 4), _pos(r, 4)), {}),
    "heaviside": lambda r: ((_f(r, 5), _f(r, 5)), {}),
    "logaddexp": lambda r: ((_f(r, 4), _f(r, 4)), {}),
    "logaddexp2": lambda r: ((_f(r, 4), _f(r, 4)), {}),
    "rsub": lambda r: ((_f(r, 4), _f(r, 4)), {}),
    "trapz": lambda r: ((_f(r, 6),), {}),
    "frac": lambda r: ((_f(r, 5) * 3,), {}),
    "nanmean": lambda r: ((np.array([1.0, np.nan, 3.0], np.float32),), {}),
    "nansum": lambda r: ((np.array([1.0, np.nan, 3.0], np.float32),), {}),
    "aminmax": lambda r: ((_f(r, 4, 5),), {}),
    "dist": lambda r: ((_f(r, 5), _f(r, 5)), {}),
    "absolute": lambda r: ((_f(r, 4),), {}),
    "negative": lambda r: ((_f(r, 4),), {}),
    "swapaxes": lambda r: ((_f(r, 3, 4, 5), 0, 2), {}),
    "ravel": lambda r: ((_f(r, 3, 4),), {}),
    "cummax": lambda r: ((_f(r, 3, 6), 1), {}),
    "cumprod": lambda r: ((_f(r, 3, 4), 1), {}),
    "median": lambda r: ((_f(r, 7),), {}),
    # linalg
    "dot": lambda r: ((_f(r, 5), _f(r, 5)), {}),
    "vdot": lambda r: ((_f(r, 5), _f(r, 5)), {}),
    "mv": lambda r: ((_f(r, 4, 5), _f(r, 5)), {}),
    "tensordot": lambda r: ((_f(r, 3, 4), _f(r, 4, 5)), {"dims": 1}),
    "kron": lambda r: ((_f(r, 2, 3), _f(r, 3, 2)), {}),
    "chain_matmul": lambda r: ((_f(r, 3, 4), _f(r, 4, 5), _f(r, 5, 2)), {}),
    "matrix_power": lambda r: ((_f(r, 3, 3), 3), {}),
    "pinverse": lambda r: ((_f(r, 4, 3),), {}),
    "inverse": lambda r: ((_spd(r, 4),), {}),
    "logdet": lambda r: ((_spd(r, 3),), {}),
    "det": lambda r: ((_spd(r, 3),), {}),
    "slogdet": lambda r: ((_spd(r, 3),), {}),
    "cholesky": lambda r: ((_spd(r, 4),), {}),
    "qr": lambda r: ((_f(r, 4, 3),), {}),
    "svd": lambda r: ((_f(r, 4, 3),), {}),
    "frobenius_norm": lambda r: ((_f(r, 3, 4), [0, 1]), {}),
    "nuclear_norm": lambda r: ((_f(r, 3, 4),), {}),
    "norm_except_dim": lambda r: ((_f(r, 4, 3, 2),), {}),
    "linalg_cholesky_ex": lambda r: ((_spd(r, 3),), {}),
    "linalg_inv_ex": lambda r: ((_spd(r, 3),), {}),
    "linalg_solve_ex": lambda r: ((_spd(r, 3), _f(r, 3, 2)), {}),
    "linalg_lu": lambda r: ((_f(r, 4, 4),), {}),
    "linalg_lu_factor": lambda r: ((_spd(r, 4),), {}),
    "linalg_lu_factor_ex": lambda r: ((_spd(r, 4),), {}),
    "lu_unpack": None,  # exercised via the composed test below
    "linalg_solve_triangular": lambda r: (
        (np.triu(_spd(r, 3)), _f(r, 3, 2)), {"upper": True}),
    "linalg_tensorinv": lambda r: ((_spd(r, 4).reshape(2, 2, 2, 2),), {}),
    "linalg_eig": lambda r: ((_spd(r, 3),), {}),
    "linalg_eigvals": lambda r: ((_spd(r, 3),), {}),
    # fft
    "fft_hfft": lambda r: ((_f(r, 8),), {}),
    "fft_ihfft": lambda r: ((_f(r, 8),), {}),
    "fft_rfftn": lambda r: ((_f(r, 4, 6),), {}),
    "fft_irfftn": lambda r: ((_f(r, 4, 6),), {}),
    "fft_fftfreq": lambda r: ((8,), {}),
    "fft_rfftfreq": lambda r: ((8,), {}),
    # special
    "special_modified_bessel_i0": lambda r: ((_pos(r, 5),), {}),
    "special_modified_bessel_i1": lambda r: ((_pos(r, 5),), {}),
    "special_modified_bessel_k0": lambda r: ((_pos(r, 5) + 0.2,), {}),
    "special_modified_bessel_k1": lambda r: ((_pos(r, 5) + 0.2,), {}),
    "special_scaled_modified_bessel_k0": lambda r: ((_pos(r, 5) + 0.2,), {}),
    "special_scaled_modified_bessel_k1": lambda r: ((_pos(r, 5) + 0.2,), {}),
    "special_bessel_j0": lambda r: ((_pos(r, 5),), {}),
    "special_bessel_j1": lambda r: ((_pos(r, 5),), {}),
    "special_spherical_bessel_j0": lambda r: ((_pos(r, 5),), {}),
    "special_chebyshev_polynomial_t": lambda r: ((_f(r, 5) * 0.9, 4), {}),
    "special_chebyshev_polynomial_u": lambda r: ((_f(r, 5) * 0.9, 4), {}),
    "special_chebyshev_polynomial_v": lambda r: ((_f(r, 5) * 0.9, 4), {}),
    "special_chebyshev_polynomial_w": lambda r: ((_f(r, 5) * 0.9, 4), {}),
    "special_shifted_chebyshev_polynomial_t": lambda r: ((_pos(r, 5) * 0.5, 3), {}),
    "special_shifted_chebyshev_polynomial_u": lambda r: ((_pos(r, 5) * 0.5, 3), {}),
    "special_shifted_chebyshev_polynomial_v": lambda r: ((_pos(r, 5) * 0.5, 3), {}),
    "special_shifted_chebyshev_polynomial_w": lambda r: ((_pos(r, 5) * 0.5, 3), {}),
    "special_hermite_polynomial_h": lambda r: ((_f(r, 5), 4), {}),
    "special_hermite_polynomial_he": lambda r: ((_f(r, 5), 4), {}),
    "special_laguerre_polynomial_l": lambda r: ((_f(r, 5), 4), {}),
    "special_legendre_polynomial_p": lambda r: ((_f(r, 5) * 0.9, 4), {}),
    # views / copies
    "expand_copy": lambda r: ((_f(r, 1, 4), (3, 4)), {}),
    "permute_copy": lambda r: ((_f(r, 2, 3, 4), (2, 0, 1)), {}),
    "squeeze_copy": lambda r: ((_f(r, 2, 1, 4),), {}),
    "unsqueeze_copy": lambda r: ((_f(r, 2, 4), 1), {}),
    "transpose_copy": lambda r: ((_f(r, 3, 4), 0, 1), {}),
    "t_copy": lambda r: ((_f(r, 3, 4),), {}),
    "view_copy": lambda r: ((_f(r, 3, 4), (4, 3)), {}),
    "detach_copy": lambda r: ((_f(r, 3),), {}),
    "diagonal_copy": lambda r: ((_f(r, 4, 4),), {}),
    "slice_copy": lambda r: ((_f(r, 6, 3),), {"dim": 0, "start": 1, "end": 5, "step": 2}),
    "select_copy": lambda r: ((_f(r, 4, 3), 0, 2), {}),
    "split_copy": lambda r: ((_f(r, 6, 2), 2), {}),
    "split_with_sizes": lambda r: ((_f(r, 6, 2), [2, 4]), {}),
    "split_with_sizes_copy": lambda r: ((_f(r, 6, 2), [2, 4]), {}),
    "unbind_copy": lambda r: ((_f(r, 3, 4),), {}),
    "unfold_copy": lambda r: ((_f(r, 8), 0, 3, 2), {}),
    "view_as_real_copy": lambda r: ((_f(r, 3) + 1j * _f(r, 3),), {}),
    "view_as_complex_copy": lambda r: ((_f(r, 3, 2),), {}),
    "as_strided": lambda r: ((_f(r, 12), (3, 3), (3, 1)), {}),
    "as_strided_copy": lambda r: ((_f(r, 12), (3, 3), (3, 1)), {}),
    "as_strided_scatter": lambda r: ((_f(r, 12), _f(r, 2, 2), (2, 2), (4, 1)), {}),
    "narrow": lambda r: ((_f(r, 6, 3), 0, 1, 4), {}),
    "dsplit": lambda r: ((_f(r, 2, 2, 4), 2), {}),
    "hsplit": lambda r: ((_f(r, 4, 4), 2), {}),
    "vsplit": lambda r: ((_f(r, 4, 4), 2), {}),
    "unsafe_chunk": lambda r: ((_f(r, 6, 2), 3), {}),
    "unsafe_split": lambda r: ((_f(r, 6, 2), 2), {}),
    "unsafe_split_with_sizes": lambda r: ((_f(r, 6, 2), [2, 4]), {}),
    # construction
    "block_diag": lambda r: ((_f(r, 2, 3), _f(r, 1, 2)), {}),
    "broadcast_tensors": lambda r: ((_f(r, 3, 1), _f(r, 1, 4)), {}),
    "cartesian_prod": lambda r: ((_f(r, 3), _f(r, 2)), {}),
    "combinations": lambda r: ((_f(r, 4),), {"r": 2}),
    "complex": lambda r: ((_f(r, 4), _f(r, 4)), {}),
    "constant_pad_nd": lambda r: ((_f(r, 2, 3), (1, 2)), {}),
    "diag": lambda r: ((_f(r, 4),), {}),
    "new_zeros": lambda r: ((_f(r, 2), (3, 2)), {}),
    "new_ones": lambda r: ((_f(r, 2), (3, 2)), {}),
    "new_full": lambda r: ((_f(r, 2), (2, 2), 7.0), {}),
    "new_tensor": lambda r: ((_f(r, 2), [[1.0, 2.0], [3.0, 4.0]]), {}),
    "reshape_as": lambda r: ((_f(r, 3, 4), _f(r, 4, 3)), {}),
    "sum_to_size": lambda r: ((_f(r, 3, 4), (1, 4)), {}),
    "scalar_tensor": lambda r: ((3.5,), {}),
    # scatter/index
    "index_fill": lambda r: ((_f(r, 4, 3), 0, np.array([0, 2]), 9.0), {}),
    "masked_scatter": lambda r: ((_f(r, 3, 3), _f(r, 3, 3) > 0, _f(r, 9)), {}),
    "put": lambda r: ((_f(r, 3, 3), np.array([0, 4]), t32([9.0, 8.0])), {}),
    "scatter_reduce": lambda r: ((_f(r, 3, 5), 1, r.randint(0, 5, (3, 4)), _f(r, 3, 4), "sum"), {}),
    "index_reduce": lambda r: ((_pos(r, 5, 3), 0, np.array([0, 2, 1]), _pos(r, 3, 3), "prod"), {}),
    "select_scatter": lambda r: ((_f(r, 4, 3), _f(r, 3), 0, 1), {}),
    "slice_scatter": lambda r: ((_f(r, 6, 3), _f(r, 2, 3)), {"dim": 0, "start": 1, "end": 5, "step": 2}),
    # nn.functional
    "adaptive_avg_pool1d": lambda r: ((_f(r, 2, 3, 10), 4), {}),
    "adaptive_max_pool1d": lambda r: ((_f(r, 2, 3, 10), 4), {}),
    "adaptive_avg_pool3d": lambda r: ((_f(r, 1, 2, 6, 6, 6), 2), {}),
    "adaptive_max_pool3d": lambda r: ((_f(r, 1, 2, 6, 6, 6), 2), {}),
    "max_pool2d_with_indices": lambda r: ((_f(r, 1, 2, 6, 6), 2), {}),
    "max_pool1d_with_indices": lambda r: ((_f(r, 1, 2, 8), 2), {}),
    "max_pool3d_with_indices": lambda r: ((_f(r, 1, 1, 4, 4, 4), 2), {}),
    "lp_pool1d": lambda r: ((_pos(r, 1, 2, 8), 2.0, 2), {}),
    "lp_pool3d": lambda r: ((_pos(r, 1, 1, 4, 4, 4), 2.0, 2), {}),
    "bilinear": lambda r: ((_f(r, 4, 3), _f(r, 4, 5), _f(r, 2, 3, 5), _f(r, 2)), {}),
    "pdist": lambda r: ((_f(r, 5, 3),), {}),
    "grid_sample": lambda r: ((_f(r, 1, 2, 5, 5), (r.uniform(-1, 1, (1, 4, 4, 2))).astype(np.float32)), {"align_corners": True}),
    "affine_grid": lambda r: ((_f(r, 1, 2, 3), (1, 1, 4, 4)), {"align_corners": True}),
    "poisson_nll_loss": lambda r: ((_f(r, 5), _pos(r, 5)), {}),
    "multi_margin_loss": lambda r: ((_f(r, 4, 5), r.randint(0, 5, (4,))), {}),
    "multilabel_margin_loss": lambda r: ((_f(r, 2, 4), np.array([[1, 2, -1, 0], [0, -1, 1, 2]])), {}),
    "triplet_margin_with_distance_loss": lambda r: ((_f(r, 4, 6), _f(r, 4, 6), _f(r, 4, 6)), {}),
    "ctc_loss": None,  # dedicated test below (arg marshalling)
    # rnn cells
    "gru_cell": lambda r: ((_f(r, 2, 3), _f(r, 2, 4), _f(r, 12, 3), _f(r, 12, 4),
                            _f(r, 12), _f(r, 12)), {}),
    "rnn_tanh_cell": lambda r: ((_f(r, 2, 3), _f(r, 2, 4), _f(r, 4, 3), _f(r, 4, 4),
                                 _f(r, 4), _f(r, 4)), {}),
    "rnn_relu_cell": lambda r: ((_f(r, 2, 3), _f(r, 2, 4), _f(r, 4, 3), _f(r, 4, 4),
                                 _f(r, 4), _f(r, 4)), {}),
    "lstm_cell": None,  # tuple hidden state: dedicated test below
    # norm internals
    "batch_norm_stats": None,  # CUDA-only aten op: dedicated manual-formula test
    "batch_norm_elemt": None,
    "native_layer_norm": lambda r: ((_f(r, 4, 6), (6,), _pos(r, 6), _f(r, 6), 1e-5), {}),
    "native_group_norm": lambda r: ((_f(r, 2, 6, 4), _pos(r, 6), _f(r, 6), 2, 6, 4, 3, 1e-5), {}),
    "native_channel_shuffle": lambda r: ((_f(r, 2, 6, 4), 3), {}),
    # signal
    "stft": lambda r: ((_f(r, 64),), {"n_fft": 16, "hop_length": 4, "return_complex": True}),
    "istft": None,  # round-trip test below
    # misc
    "conv_tbc": lambda r: ((_f(r, 7, 2, 3), _f(r, 3, 3, 4), _f(r, 4)), {}),
    "resolve_conj": lambda r: ((_f(r, 3),), {}),
    "resolve_neg": lambda r: ((_f(r, 3),), {}),
    # nondiff
    "count_nonzero": lambda r: ((np.array([0.0, 1.0, 0.0, 2.0], np.float32),), {}),
    "nonzero_static": lambda r: ((np.array([0.0, 1.0, 0.0, 2.0], np.float32),), {"size": 2}),
    "histogram": lambda r: ((_f(r, 20),), {"bins": 5}),
    "unravel_index": lambda r: ((np.array([3, 7]), (3, 4)), {}),
    "mode": lambda r: ((np.array([[1.0, 2.0, 2.0, 3.0], [0.0, 0.0, 1.0, 2.0]], np.float32),), {}),
    "is_same_size": None,  # returns a python bool; checked in dedicated test
    # --- wave 8 ---
    "convolution": lambda r: ((_f(r, 2, 3, 8, 8), _f(r, 4, 3, 3, 3), _f(r, 4),
                               (1, 1), (1, 1), (1, 1), False, (0, 0), 1), {}),
    "scaled_dot_product_attention": lambda r: ((_f(r, 2, 3, 6, 8), _f(r, 2, 3, 6, 8),
                                                _f(r, 2, 3, 6, 8)), {"is_causal": True}),
    "native_batch_norm": lambda r: ((_f(r, 4, 3, 5), _pos(r, 3), _f(r, 3),
                                     np.zeros(3, np.float32), np.ones(3, np.float32),
                                     True, 0.1, 1e-5), {}),
    "linalg_matmul": lambda r: ((_f(r, 3, 4), _f(r, 4, 5)), {}),
    "linalg_diagonal": lambda r: ((_f(r, 4, 5),), {}),
    "linalg_vander": lambda r: ((_f(r, 4),), {}),
    "special_logit": lambda r: ((np.clip(np.abs(_f(r, 3, 4)), 0.05, 0.95),), {}),
    "gradient": lambda r: ((_f(r, 6),), {}),
    "fill": lambda r: ((_f(r, 3, 4), 1.5), {}),
    "alias_copy": lambda r: ((_f(r, 3, 4),), {}),
    "upsample_nearest": lambda r: ((_f(r, 1, 2, 4, 4),), {"scale_factor": 2.0}),
    "upsample_bilinear": lambda r: ((_f(r, 1, 2, 4, 4),), {"scale_factor": 2}),
    "upsample": lambda r: ((_f(r, 1, 2, 4, 4),), {"scale_factor": 2.0, "mode": "nearest"}),
    "rrelu": lambda r: ((_f(r, 3, 4),), {"training": False}),
    "adaptive_max_pool3d": lambda r: ((_f(r, 1, 2, 6, 6, 6), (3, 3, 3)), {}),
    "adaptive_max_pool3d_with_indices": lambda r: ((_f(r, 1, 2, 6, 6, 6), (3, 3, 3)), {}),
    "fake_quantize_per_tensor_affine": lambda r: ((_f(r, 3, 4), 0.1, 2, -10, 10), {}),
    "fake_quantize_per_channel_affine": lambda r: ((_f(r, 3, 4), _pos(r, 3),
                                                    np.zeros(3, np.int32), 0, -10, 10), {}),
    "hann_window": lambda r: ((8,), {}),
    "hamming_window": lambda r: ((8,), {}),
    "blackman_window": lambda r: ((8,), {}),
    "bartlett_window": lambda r: ((8,), {}),
    "kaiser_window": lambda r: ((8,), {}),
    "histogramdd": lambda r: ((_f(r, 20, 2), 4), {}),
    "as_tensor": lambda r: ((_f(r, 3),), {}),
    "asarray": lambda r: ((_f(r, 3),), {}),
    "range": lambda r: ((0, 5, 1), {}),
    "native_norm": lambda r: ((_f(r, 5),), {}),
    "cpu": lambda r: ((_f(r, 3),), {}),
}

# entries whose torch reference has a different name or needs the
# nn.functional variant (the top-level aten overload differs)
TORCH_NAME = {
    "matrix_exp_": None,
    "lu_solve": lambda b, lu, piv: torch.lu_solve(
        torch.as_tensor(b), torch.as_tensor(lu), torch.as_tensor(piv)),
    "adaptive_max_pool1d": F.adaptive_max_pool1d,
    # aten::native_norm is sparse/CUDA-only on this CPU build; torch.norm is
    # the same p-norm contract for dense inputs
    "native_norm": lambda a: torch.norm(torch.as_tensor(a)),
    "poisson_nll_loss": F.poisson_nll_loss,
    "multilabel_margin_loss": F.multilabel_margin_loss,
    "multi_margin_loss": F.multi_margin_loss,
}


def _resolve_torch(key):
    for fam in ("fft", "linalg", "special"):
        if key.startswith(fam + "_"):
            return getattr(getattr(torch, fam), key[len(fam) + 1:], None)
    fn = getattr(torch, key, None)
    if fn is not None and callable(fn):
        return fn
    fn = getattr(F, key, None)
    if fn is not None and callable(fn):
        return fn
    m = getattr(torch.Tensor, key, None)
    if m is not None and callable(m):
        return lambda a, *args, **kw: m(torch.as_tensor(a), *args, **kw)
    return None


def _to_torch(x):
    if isinstance(x, np.ndarray):
        return torch.from_numpy(x.copy())
    return x


def _to_jax(x):
    if isinstance(x, np.ndarray):
        return jnp.asarray(x)
    return x


def _compare(got, want, key, atol=2e-2):
    got_l = jax.tree_util.tree_leaves(got)
    want_l = [w for w in (want if isinstance(want, (tuple, list)) else [want])]
    flat_want = []
    for w in want_l:
        if isinstance(w, torch.Tensor):
            flat_want.append(w)
        elif isinstance(w, (tuple, list)):
            flat_want.extend(x for x in w if isinstance(x, torch.Tensor))
    if not flat_want and isinstance(want, torch.Tensor):
        flat_want = [want]
    assert len(got_l) >= len(flat_want), f"{key}: arity {len(got_l)} vs {len(flat_want)}"
    for g, w in zip(got_l, flat_want):
        if w.dtype.is_complex:
            wn = w.detach().to(torch.complex128).numpy()
        elif w.dtype.is_floating_point:
            wn = w.detach().to(torch.float32).numpy()
        else:
            wn = w.detach().numpy()
        gn = np.asarray(g)
        if np.issubdtype(gn.dtype, np.floating) or np.issubdtype(gn.dtype, np.complexfloating):
            np.testing.assert_allclose(gn.astype(np.complex128 if np.iscomplexobj(gn) else np.float64),
                                       wn.astype(np.complex128 if np.iscomplexobj(wn) else np.float64),
                                       atol=atol, rtol=2e-2, err_msg=key)
        else:
            np.testing.assert_array_equal(gn, wn.astype(gn.dtype), err_msg=key)


_KEYS = sorted(k for k, v in SAMPLES.items() if v is not None)


@pytest.mark.parametrize("key", _KEYS)
def test_catalog_entry_matches_torch(key, rng):
    sym = ar.get_auto_symbol(key)
    assert sym is not None, f"{key} not in catalog"
    tfn = TORCH_NAME.get(key, _resolve_torch(key))
    assert tfn is not None, f"no torch reference for {key}"
    args, kwargs = SAMPLES[key](rng)
    want = tfn(*[_to_torch(a) for a in args], **{k: _to_torch(v) for k, v in kwargs.items()})
    got = tt.jit(lambda *a, **kw: sym(*a, **kw))(
        *[_to_jax(a) for a in args], **{k: _to_jax(v) for k, v in kwargs.items()})
    if key in ("bfloat16", "half", "cfloat", "double", "qr", "svd", "linalg_lu",
               "linalg_lu_factor", "linalg_lu_factor_ex", "linalg_eig", "linalg_eigvals"):
        # representation-dependent outputs: compare reconstruction/abs instead
        _compare_special(key, got, want)
        return
    _compare(got, want, key)


def _compare_special(key, got, want):
    if key in ("bfloat16", "half", "double", "cfloat"):
        g = jax.tree_util.tree_leaves(got)[0]
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   want.to(torch.float32).numpy(), atol=2e-2, err_msg=key)
    elif key in ("qr",):
        q, r = got
        np.testing.assert_allclose(np.asarray(q @ r), (want[0] @ want[1]).numpy(),
                                   atol=1e-3, err_msg=key)
    elif key == "svd":
        u, s, vt_or_v = got
        np.testing.assert_allclose(np.sort(np.asarray(s)), np.sort(want[1].numpy()),
                                   atol=1e-3, err_msg=key)
    elif key in ("linalg_lu", "linalg_lu_factor", "linalg_lu_factor_ex"):
        pass  # pivot conventions differ per backend; exercised by lu round-trip below
    elif key in ("linalg_eig", "linalg_eigvals"):
        leaves = jax.tree_util.tree_leaves(got)
        ev = leaves[0] if key == "linalg_eigvals" else leaves[0]
        w_ref = want if isinstance(want, torch.Tensor) else want[0]
        np.testing.assert_allclose(np.sort(np.abs(np.asarray(ev))),
                                   np.sort(np.abs(w_ref.numpy())), atol=1e-3, err_msg=key)


def test_lu_round_trip(rng):
    a = _spd(rng, 4)
    p, l, u = tt.jit(lambda x: ar.get_auto_symbol("linalg_lu")(x))(jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(p) @ np.asarray(l) @ np.asarray(u), a, atol=1e-3)
    lu, piv = tt.jit(lambda x: ar.get_auto_symbol("linalg_lu_factor")(x))(jnp.asarray(a))
    b = _f(rng, 4, 2)
    x = tt.jit(lambda b, lu, piv: ar.get_auto_symbol("lu_solve")(b, lu, piv))(
        jnp.asarray(b), lu, piv)
    np.testing.assert_allclose(a @ np.asarray(x), b, atol=1e-3)
    P, L, U = tt.jit(lambda lu, piv: ar.get_auto_symbol("lu_unpack")(lu, piv))(lu, piv)
    np.testing.assert_allclose(np.asarray(P) @ np.asarray(L) @ np.asarray(U), a, atol=1e-3)


def test_lstm_cell_matches_torch(rng):
    x, h, c = _f(rng, 2, 3), _f(rng, 2, 4), _f(rng, 2, 4)
    w_ih, w_hh = _f(rng, 16, 3), _f(rng, 16, 4)
    b_ih, b_hh = _f(rng, 16), _f(rng, 16)
    want_h, want_c = torch.lstm_cell(
        torch.as_tensor(x), (torch.as_tensor(h), torch.as_tensor(c)),
        torch.as_tensor(w_ih), torch.as_tensor(w_hh),
        torch.as_tensor(b_ih), torch.as_tensor(b_hh))
    sym = ar.get_auto_symbol("lstm_cell")
    got_h, got_c = tt.jit(lambda x, h, c, wi, wh, bi, bh: sym(x, (h, c), wi, wh, bi, bh))(
        *[jnp.asarray(v) for v in (x, h, c, w_ih, w_hh, b_ih, b_hh)])
    np.testing.assert_allclose(np.asarray(got_h), want_h.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_c), want_c.numpy(), atol=1e-4)


def test_ctc_loss_matches_torch(rng):
    T, N, C, S = 12, 3, 5, 4
    lp = np.log(np.abs(rng.standard_normal((T, N, C))) + 0.1).astype(np.float32)
    lp = lp - np.log(np.sum(np.exp(lp), -1, keepdims=True))
    targets = rng.randint(1, C, (N, S))
    in_len = np.array([12, 10, 8])
    tg_len = np.array([4, 3, 2])
    want = F.ctc_loss(torch.as_tensor(lp), torch.as_tensor(targets),
                      torch.as_tensor(in_len), torch.as_tensor(tg_len))
    sym = ar.get_auto_symbol("ctc_loss")
    got = tt.jit(lambda lp, tg, il, tl: sym(lp, tg, il, tl))(
        jnp.asarray(lp), jnp.asarray(targets), jnp.asarray(in_len), jnp.asarray(tg_len))
    np.testing.assert_allclose(float(got), float(want), atol=1e-3)


def test_stft_istft_round_trip(rng):
    x = _f(rng, 64)
    spec_sym = ar.get_auto_symbol("stft")
    istft_sym = ar.get_auto_symbol("istft")
    win = np.hanning(16).astype(np.float32)
    spec = tt.jit(lambda x, w: spec_sym(x, n_fft=16, hop_length=4, window=w,
                                        return_complex=True))(jnp.asarray(x), jnp.asarray(win))
    want = torch.stft(torch.as_tensor(x), n_fft=16, hop_length=4,
                      window=torch.as_tensor(win), return_complex=True)
    np.testing.assert_allclose(np.asarray(spec), want.numpy(), atol=1e-3)
    back = tt.jit(lambda s, w: istft_sym(s, n_fft=16, hop_length=4, window=w))(
        spec, jnp.asarray(win))
    wback = torch.istft(want, n_fft=16, hop_length=4, window=torch.as_tensor(win))
    np.testing.assert_allclose(np.asarray(back)[:wback.shape[0]], wback.numpy(), atol=1e-3)


def test_all_ext_entries_have_smoke_coverage():
    """Every wave-6 entry is either in SAMPLES or covered by a dedicated test."""
    from thunder_tpu.ops.auto_catalog_ext import EXT_DIFF, EXT_NONDIFF

    dedicated = {"lu_solve", "lu_unpack", "lstm_cell", "ctc_loss", "istft",
                 "is_same_size", "batch_norm_stats", "batch_norm_elemt",
                 # exercised through their sibling entries' samples
                 "max_unpool1d", "max_unpool2d", "max_unpool3d",
                 "adaptive_max_pool1d_with_indices", "grid_sampler", "grid_sampler_2d",
                 "affine_grid_generator", "matrix_exp_", "cdouble", "chalf",
                 "linalg_lu_solve"}
    missing = [k for k in list(EXT_DIFF) + list(EXT_NONDIFF)
               if k not in SAMPLES and k not in dedicated]
    assert not missing, f"wave-6 entries without smoke coverage: {missing}"


def test_max_unpool_round_trip(rng):
    x = _f(rng, 1, 2, 8)
    v, idx = tt.jit(lambda x: ar.get_auto_symbol("max_pool1d_with_indices")(x, 2))(jnp.asarray(x))
    back = tt.jit(lambda v, i: ar.get_auto_symbol("max_unpool1d")(v, i, 2))(v, idx)
    want = F.max_unpool1d(*F.max_pool1d(torch.as_tensor(x), 2, return_indices=True), 2)
    np.testing.assert_allclose(np.asarray(back), want.numpy(), atol=1e-6)

    x2 = _f(rng, 1, 2, 6, 6)
    v2, idx2 = tt.jit(lambda x: ar.get_auto_symbol("max_pool2d_with_indices")(x, 2))(jnp.asarray(x2))
    back2 = tt.jit(lambda v, i: ar.get_auto_symbol("max_unpool2d")(v, i, 2))(v2, idx2)
    want2 = F.max_unpool2d(*F.max_pool2d(torch.as_tensor(x2), 2, return_indices=True), 2)
    np.testing.assert_allclose(np.asarray(back2), want2.numpy(), atol=1e-6)


def test_batch_norm_internals_manual(rng):
    """batch_norm_stats/elemt vs the formula (the aten ops are CUDA-only)."""
    x = _f(rng, 4, 3, 5)
    mean, invstd = tt.jit(lambda x: ar.get_auto_symbol("batch_norm_stats")(x, 1e-5))(
        jnp.asarray(x))
    want_mean = x.mean(axis=(0, 2))
    want_invstd = 1.0 / np.sqrt(x.var(axis=(0, 2)) + 1e-5)
    np.testing.assert_allclose(np.asarray(mean), want_mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(invstd), want_invstd, atol=1e-4)

    w, b = _pos(rng, 3), _f(rng, 3)
    out = tt.jit(lambda x, w, b, m, i: ar.get_auto_symbol("batch_norm_elemt")(
        x, w, b, m, i, 1e-5))(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), mean, invstd)
    want = ((x - want_mean.reshape(1, 3, 1)) * want_invstd.reshape(1, 3, 1)
            * w.reshape(1, 3, 1) + b.reshape(1, 3, 1))
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)


def test_full_rnn_stacks_match_torch(rng):
    """gru / lstm / rnn_tanh full stacks vs torch.nn modules (2 layers, bidir)."""
    T, B, I, H, L = 5, 2, 3, 4, 2
    x = _f(rng, T, B, I)

    for kind in ("gru", "rnn_tanh", "lstm"):
        mod_cls = {"gru": torch.nn.GRU, "rnn_tanh": torch.nn.RNN, "lstm": torch.nn.LSTM}[kind]
        mod = mod_cls(I, H, num_layers=L, bidirectional=True)
        params = [p.detach().numpy() for p in mod._flat_weights]
        tx = torch.as_tensor(x)
        if kind == "lstm":
            h0 = np.zeros((L * 2, B, H), np.float32)
            c0 = np.zeros((L * 2, B, H), np.float32)
            want_out, (want_h, want_c) = mod(tx, (torch.as_tensor(h0), torch.as_tensor(c0)))
            sym = ar.get_auto_symbol("lstm")
            got_out, got_h, got_c = tt.jit(
                lambda x, h, c, *ps: sym(x, (h, c), list(ps), True, L, 0.0, False, True, False))(
                jnp.asarray(x), jnp.asarray(h0), jnp.asarray(c0),
                *[jnp.asarray(p) for p in params])
            np.testing.assert_allclose(np.asarray(got_c), want_c.detach().numpy(),
                                       atol=1e-4, err_msg=kind)
        else:
            h0 = np.zeros((L * 2, B, H), np.float32)
            want_out, want_h = mod(tx, torch.as_tensor(h0))
            sym = ar.get_auto_symbol(kind)
            got_out, got_h = tt.jit(
                lambda x, h, *ps: sym(x, h, list(ps), True, L, 0.0, False, True, False))(
                jnp.asarray(x), jnp.asarray(h0), *[jnp.asarray(p) for p in params])
        np.testing.assert_allclose(np.asarray(got_out), want_out.detach().numpy(),
                                   atol=1e-4, err_msg=kind)
        np.testing.assert_allclose(np.asarray(got_h), want_h.detach().numpy(),
                                   atol=1e-4, err_msg=kind)


def test_wave7_entries_match_torch(rng):
    # hermitian fft 2d
    x = _f(rng, 4, 5)
    got = tt.jit(lambda a: ar.get_auto_symbol("fft_hfft2")(a))(jnp.asarray(x))
    want = torch.fft.hfft2(torch.as_tensor(x))
    np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-3)
    got_i = tt.jit(lambda a: ar.get_auto_symbol("fft_ihfft2")(a))(jnp.asarray(x))
    want_i = torch.fft.ihfft2(torch.as_tensor(x))
    np.testing.assert_allclose(np.asarray(got_i), want_i.numpy(), atol=1e-4)

    # adaptive max pool 2d with indices
    a = _f(rng, 1, 2, 6, 7)
    gv, gi = tt.jit(lambda a: ar.get_auto_symbol("adaptive_max_pool2d_with_indices")(a, (3, 3)))(
        jnp.asarray(a))
    wv, wi = F.adaptive_max_pool2d(torch.as_tensor(a), (3, 3), return_indices=True)
    np.testing.assert_allclose(np.asarray(gv), wv.numpy(), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(gi), wi.numpy().astype(np.int32))

    # batch_norm_update_stats formula
    xb = _f(rng, 4, 3, 5)
    rm, rv = _f(rng, 3), _pos(rng, 3)
    nm, nv = tt.jit(lambda x, m, v: ar.get_auto_symbol("batch_norm_update_stats")(x, m, v, 0.1))(
        jnp.asarray(xb), jnp.asarray(rm), jnp.asarray(rv))
    np.testing.assert_allclose(np.asarray(nm), 0.9 * rm + 0.1 * xb.mean((0, 2)), atol=1e-5)
    np.testing.assert_allclose(np.asarray(nv), 0.9 * rv + 0.1 * xb.var((0, 2), ddof=1), atol=1e-4)

    # torch.lu alias
    aa = _spd(rng, 3)
    lu, piv = tt.jit(lambda a: ar.get_auto_symbol("lu")(a))(jnp.asarray(aa))
    assert lu.shape == (3, 3) and piv.shape == (3,)

    # new_empty: shape/dtype contract only (values unspecified)
    ne = tt.jit(lambda a: ar.get_auto_symbol("new_empty")(a, (2, 3)))(jnp.asarray(x))
    assert tuple(ne.shape) == (2, 3) and ne.dtype == jnp.float32


def test_ltorch_channel_dropouts(rng):
    from thunder_tpu.ops import ltorch as lt

    x = jnp.asarray(_f(rng, 2, 3, 8))
    # eval mode: identity
    out = tt.jit(lambda a: lt.dropout1d(a, 0.5, False))(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    # train mode: channels are zeroed whole, survivors scaled by 1/keep
    key = jax.random.PRNGKey(0)
    out_t = tt.jit(lambda a, k: lt.dropout1d(a, 0.5, True, key=k))(x, key)
    o = np.asarray(out_t)
    for n in range(2):
        for c in range(3):
            ch = o[n, c]
            assert np.all(ch == 0) or np.allclose(ch, np.asarray(x)[n, c] * 2.0)


def test_review_r3_edge_semantics(rng):
    """Regression pack for review findings: even-length median, torch.svd's V,
    batched lu_unpack, windowed normalized stft, rnn dropout guard."""
    # torch.median returns the LOWER middle element, not the average
    x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    got = tt.jit(lambda a: ar.get_auto_symbol("median")(a))(jnp.asarray(x))
    assert float(got) == float(torch.median(torch.as_tensor(x))) == 2.0
    x2 = _f(rng, 3, 6)
    gv, gi = tt.jit(lambda a: ar.get_auto_symbol("median")(a, 1))(jnp.asarray(x2))
    wv, wi = torch.median(torch.as_tensor(x2), 1)
    np.testing.assert_allclose(np.asarray(gv), wv.numpy(), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(x2)[np.arange(3), np.asarray(gi)],
                                  np.asarray(gv))  # value-at-index consistency

    # torch.svd third output is V (a == U S V^T), not Vh
    a = _f(rng, 4, 3)
    u, s, v = tt.jit(lambda a: ar.get_auto_symbol("svd")(a))(jnp.asarray(a))
    rec = np.asarray(u)[:, :3] @ np.diag(np.asarray(s)) @ np.asarray(v).T
    np.testing.assert_allclose(rec, a, atol=1e-4)

    # batched lu_unpack reconstructs each batch element
    ab = np.stack([_spd(rng, 4), _spd(rng, 4)])
    lu, piv = tt.jit(lambda m: ar.get_auto_symbol("linalg_lu_factor")(m))(jnp.asarray(ab))
    P, L, U = tt.jit(lambda lu, piv: ar.get_auto_symbol("lu_unpack")(lu, piv))(lu, piv)
    np.testing.assert_allclose(np.asarray(P) @ np.asarray(L) @ np.asarray(U), ab, atol=1e-3)

    # normalized stft with a non-rectangular window matches torch (1/sqrt(n_fft))
    sig = _f(rng, 64)
    win = np.hanning(16).astype(np.float32)
    got_s = tt.jit(lambda s, w: ar.get_auto_symbol("stft")(
        s, n_fft=16, hop_length=4, window=w, normalized=True, return_complex=True))(
        jnp.asarray(sig), jnp.asarray(win))
    want_s = torch.stft(torch.as_tensor(sig), n_fft=16, hop_length=4,
                        window=torch.as_tensor(win), normalized=True, return_complex=True)
    np.testing.assert_allclose(np.asarray(got_s), want_s.numpy(), atol=1e-4)

    # rnn stacks refuse silent dropout
    with pytest.raises(NotImplementedError, match="dropout"):
        tt.jit(lambda x, h, w1, w2: ar.get_auto_symbol("rnn_tanh")(
            x, h, [w1, w2], False, 1, 0.5, True, False, False))(
            jnp.ones((3, 2, 4)), jnp.ones((1, 2, 4)), jnp.ones((4, 4)), jnp.ones((4, 4)))


def test_dropout3d_unbatched_channel_mask(rng):
    """4-D dropout3d input is unbatched (C,D,H,W): whole channels drop."""
    from thunder_tpu.ops import ltorch as lt

    x = jnp.ones((6, 4, 3, 3), jnp.float32)
    key = jax.random.PRNGKey(1)
    out = np.asarray(tt.jit(lambda a, k: lt.dropout3d(a, 0.5, True, key=k))(x, key))
    for c in range(6):
        ch = out[c]
        assert np.all(ch == 0) or np.allclose(ch, 2.0)


def test_wave8_dedicated(rng):
    """wave-8 entries without a 1:1 CPU torch reference: geqrf/ormqr via
    reconstruction, low-rank factorizations via singular values, distributed
    batch-norm internals via the formula, shape-contract factories."""
    # geqrf + ormqr: Q @ R reconstructs A
    a = _f(rng, 5, 4)
    h, tau = tt.jit(lambda a: ar.get_auto_symbol("geqrf")(a))(jnp.asarray(a))
    r = np.triu(np.asarray(h))[:4, :]
    qr_full = tt.jit(lambda h, tau, o: ar.get_auto_symbol("ormqr")(h, tau, o))(
        h, tau, jnp.asarray(r))
    np.testing.assert_allclose(np.asarray(qr_full), a, atol=1e-4)

    # svd_lowrank / pca_lowrank: top singular values match full SVD
    m = _f(rng, 8, 6)
    u, s, v = tt.jit(lambda a: ar.get_auto_symbol("svd_lowrank")(a, 3))(jnp.asarray(m))
    want_s = np.linalg.svd(m, compute_uv=False)[:3]
    np.testing.assert_allclose(np.asarray(s), want_s, atol=1e-4)
    np.testing.assert_allclose(np.asarray(u @ jnp.diag(s) @ v.T),
                               np.asarray((u * s) @ v.T), atol=1e-5)
    _, s2, _ = tt.jit(lambda a: ar.get_auto_symbol("pca_lowrank")(a, 2))(jnp.asarray(m))
    want2 = np.linalg.svd(m - m.mean(0), compute_uv=False)[:2]
    np.testing.assert_allclose(np.asarray(s2), want2, atol=1e-4)

    # gather_stats: two replicas with equal counts == stats of the union
    x1, x2 = _f(rng, 10, 3), _f(rng, 10, 3)
    mean = np.stack([x1.mean(0), x2.mean(0)])
    invstd = np.stack([1 / np.sqrt(x1.var(0) + 1e-5), 1 / np.sqrt(x2.var(0) + 1e-5)])
    gm, gi = tt.jit(lambda m, i: ar.get_auto_symbol("batch_norm_gather_stats_with_counts")(
        None, m, i, None, None, 0.1, 1e-5, jnp.asarray([10.0, 10.0])))(
        jnp.asarray(mean), jnp.asarray(invstd))
    allx = np.concatenate([x1, x2], 0)
    np.testing.assert_allclose(np.asarray(gm), allx.mean(0), atol=1e-5)
    np.testing.assert_allclose(np.asarray(gi), 1 / np.sqrt(allx.var(0) + 1e-5), atol=1e-4)

    # backward_reduce / backward_elemt reproduce batch-norm input grads
    xb = _f(rng, 4, 3, 5)
    w = _pos(rng, 3)
    go = _f(rng, 4, 3, 5)
    mean_b = xb.mean((0, 2))
    invstd_b = (1 / np.sqrt(xb.var((0, 2)) + 1e-5)).astype(np.float32)
    sdy, sdyxmu, gw, gb = tt.jit(lambda g, x, m, i, w: ar.get_auto_symbol(
        "batch_norm_backward_reduce")(g, x, m, i, w, True, True, True))(
        jnp.asarray(go), jnp.asarray(xb), jnp.asarray(mean_b), jnp.asarray(invstd_b),
        jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(sdy), go.sum((0, 2)), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), go.sum((0, 2)), atol=1e-4)
    gi_el = tt.jit(lambda g, x, m, i, w, s1, s2: ar.get_auto_symbol(
        "batch_norm_backward_elemt")(g, x, m, i, w, s1, s2, jnp.asarray([20.0])))(
        jnp.asarray(go), jnp.asarray(xb), jnp.asarray(mean_b), jnp.asarray(invstd_b),
        jnp.asarray(w), sdy, sdyxmu)
    # reference grad via torch autograd on the normalization formula
    xt = torch.as_tensor(xb).requires_grad_(True)
    yt = ((xt - torch.as_tensor(mean_b).view(1, 3, 1))
          * torch.as_tensor(invstd_b).view(1, 3, 1) * torch.as_tensor(w).view(1, 3, 1))
    # batch-norm treats mean/invstd as functions of x; recompute them in torch
    xt2 = torch.as_tensor(xb).requires_grad_(True)
    mu = xt2.mean((0, 2), keepdim=True)
    var = xt2.var((0, 2), unbiased=False, keepdim=True)
    yt2 = (xt2 - mu) / torch.sqrt(var + 1e-5) * torch.as_tensor(w).view(1, 3, 1)
    yt2.backward(torch.as_tensor(go))
    np.testing.assert_allclose(np.asarray(gi_el), xt2.grad.numpy(), atol=1e-3)

    # transposed convolution path of the aten entry
    x = _f(rng, 2, 3, 6)
    wt = _f(rng, 3, 4, 3)
    got = tt.jit(lambda x, w: ar.get_auto_symbol("convolution")(
        x, w, None, (2,), (1,), (1,), True, (1,), 1))(jnp.asarray(x), jnp.asarray(wt))
    want = torch.convolution(torch.as_tensor(x), torch.as_tensor(wt), None,
                             (2,), (1,), (1,), True, (1,), 1)
    np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=1e-4)

    # grouped forward convolution
    xg = _f(rng, 2, 4, 8, 8)
    wg = _f(rng, 6, 2, 3, 3)
    got_g = tt.jit(lambda x, w: ar.get_auto_symbol("convolution")(
        x, w, None, (1, 1), (0, 0), (1, 1), False, (0, 0), 2))(jnp.asarray(xg), jnp.asarray(wg))
    want_g = torch.convolution(torch.as_tensor(xg), torch.as_tensor(wg), None,
                               (1, 1), (0, 0), (1, 1), False, (0, 0), 2)
    np.testing.assert_allclose(np.asarray(got_g), want_g.numpy(), atol=1e-4)

    # shape-contract-only factories + identities
    es = tt.jit(lambda: ar.get_auto_symbol("empty_strided")((2, 3), (3, 1)))()
    assert tuple(es.shape) == (2, 3)
    ep = tt.jit(lambda: ar.get_auto_symbol("empty_permuted")((2, 3), (1, 0)))()
    assert tuple(ep.shape) == (2, 3)
    ident = _f(rng, 3)
    pm = tt.jit(lambda a: ar.get_auto_symbol("pin_memory")(a))(jnp.asarray(ident))
    np.testing.assert_array_equal(np.asarray(pm), ident)

    # F.upsample_bilinear (align_corners=True semantics)
    xu = _f(rng, 1, 2, 4, 4)
    got_u = tt.jit(lambda a: ar.get_auto_symbol("upsample_bilinear")(a, None, 2))(jnp.asarray(xu))
    want_u = F.upsample_bilinear(torch.as_tensor(xu), scale_factor=2)
    np.testing.assert_allclose(np.asarray(got_u), want_u.numpy(), atol=1e-4)


def test_fallback_coverage_fully_accounted():
    """Every reference auto-registered name is either native here or carries a
    documented host-eager reason (FALLBACK_COVERAGE.md generator)."""
    import os
    from thunder_tpu.utils.fallback_coverage import coverage

    if not os.path.exists("/root/reference/thunder/torch/default_torch_ops.py"):
        pytest.skip("reference checkout not present")
    rows, counts = coverage()
    assert counts["unaccounted"] == 0, [k for k, v in rows.items() if v == "UNACCOUNTED"]
    assert counts["ltorch"] + counts["auto"] >= 400


def test_ltorch_coverage_fully_accounted():
    """Every @torchsymbol def name in the reference's curated torch namespace
    is native here, functionalized in-place, subsystem-covered, or excluded
    with a documented reason (LTORCH_COVERAGE.md generator)."""
    import os
    from thunder_tpu.utils.ltorch_coverage import coverage

    if not os.path.exists("/root/reference/thunder/torch/__init__.py"):
        pytest.skip("reference checkout not present")
    rows, counts = coverage()
    assert counts["unaccounted"] == 0, [k for k, v in rows.items() if v == "UNACCOUNTED"]
    assert counts["ltorch"] + counts["method"] + counts["auto"] >= 240
    # the runtime surface the artifact reports must stay >= the reference's
    from thunder_tpu.ops import ltorch
    n_runtime = sum(1 for n in dir(ltorch)
                    if not n.startswith("_") and callable(getattr(ltorch, n)))
    assert n_runtime >= 340
