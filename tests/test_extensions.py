"""Tests for the extension-surface parity components: pattern matching,
vjp_utils, the numpy language, custom ops, LoRA, gradient bucketing, and
recipes (counterparts of reference thunder/tests/test_patterns.py,
test_transforms.py LoRA cases, test_ddp.py bucketing cases, test_recipes)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import thunder_tpu as tt
from thunder_tpu import nn, optim
from thunder_tpu.ops import ltorch
from thunder_tpu.training import TrainStep


# ---------------------------------------------------------------------------
# patterns
# ---------------------------------------------------------------------------


class TestPatterns:
    def _trace(self, fn, *args):
        from thunder_tpu import acquire_trace
        from thunder_tpu.core.transform_common import flatten_to_prims

        trc, *_ = acquire_trace(fn, args, {})
        return flatten_to_prims(trc)

    def test_match_mul_add_chain(self):
        from thunder_tpu.core.patterns import Pattern, uses
        from thunder_tpu.core.prims import PrimIDs

        def f(a, b, c):
            return a * b + c

        trc = self._trace(f, jnp.ones((4,)), jnp.ones((4,)), jnp.ones((4,)))
        p = (Pattern()
             .match_op(PrimIDs.MUL, bind_out="prod")
             .match_op(PrimIDs.ADD, where=uses("prod")))
        matches = p.match(trc)
        assert len(matches) == 1
        state, indices = matches[0]
        assert [trc.bound_symbols[i].sym.id for i in indices] == [PrimIDs.MUL, PrimIDs.ADD]

    def test_no_match_when_disconnected(self):
        from thunder_tpu.core.patterns import Pattern, uses
        from thunder_tpu.core.prims import PrimIDs

        def f(a, b, c):
            return (a * b, c + c)  # add does not consume the mul

        trc = self._trace(f, jnp.ones((4,)), jnp.ones((4,)), jnp.ones((4,)))
        p = (Pattern()
             .match_op(PrimIDs.MUL, bind_out="prod")
             .match_op(PrimIDs.ADD, where=uses("prod")))
        assert p.match(trc) == []

    def test_replace_rewrites_and_preserves_numerics(self):
        from thunder_tpu.core import prims
        from thunder_tpu.core.patterns import Pattern, uses
        from thunder_tpu.core.prims import PrimIDs
        from thunder_tpu.core.transform_common import dce
        from thunder_tpu.executors.passes import transform_for_execution
        from thunder_tpu.extend import resolve_executors

        def f(a, b, c):
            return a * b + c

        x = jnp.asarray(np.random.RandomState(0).rand(4).astype(np.float32))
        y = jnp.asarray(np.random.RandomState(1).rand(4).astype(np.float32))
        z = jnp.asarray(np.random.RandomState(2).rand(4).astype(np.float32))
        trc = self._trace(f, x, y, z)

        p = (Pattern()
             .match_op(PrimIDs.MUL, bind_args=("a", "b"), bind_out="prod")
             .match_op(PrimIDs.ADD, where=uses("prod"), bind_args=(None, "c")))

        def fma(a, b, c, prod=None):
            return prims.add(prims.mul(prims.mul(a, b), 1.0), c)

        new_trc = p.replace(trc, fma)
        claimed = transform_for_execution(dce(new_trc), resolve_executors(None))
        out = claimed.python_callable()(x, y, z)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * np.asarray(y) + np.asarray(z), atol=1e-6)

    def test_intermediate_escape_blocks_match(self):
        from thunder_tpu.core.patterns import Pattern, uses
        from thunder_tpu.core.prims import PrimIDs

        def f(a, b, c):
            prod = a * b
            return prod + c, prod * 2.0  # prod escapes

        trc = self._trace(f, jnp.ones((4,)), jnp.ones((4,)), jnp.ones((4,)))
        p = (Pattern()
             .match_op(PrimIDs.MUL, bind_out="prod")
             .match_op(PrimIDs.ADD, where=uses("prod")))
        assert p.match(trc) == []


# ---------------------------------------------------------------------------
# vjp_utils
# ---------------------------------------------------------------------------


class TestVjpUtils:
    def test_make_aug_forward_and_backward(self):
        from thunder_tpu import acquire_trace
        from thunder_tpu.core.vjp_utils import make_aug_forward_and_backward

        from thunder_tpu.core.prims import PrimIDs
        from thunder_tpu.core.transform_common import flatten_to_prims

        def f(a, b):
            return ltorch.mul(a, b)

        x = jnp.asarray(np.random.RandomState(0).rand(3, 4).astype(np.float32))
        y = jnp.asarray(np.random.RandomState(1).rand(3, 4).astype(np.float32))
        trc, *_ = acquire_trace(f, (x, y), {})
        trc = flatten_to_prims(trc)
        mul_bsym = next(b for b in trc.bound_symbols if b.sym.id == PrimIDs.MUL)
        fwd_trc, bwd_trc = make_aug_forward_and_backward(mul_bsym)
        assert "augmented_forward" in fwd_trc.name_of_fn()
        assert "backward" in bwd_trc.name_of_fn()
        # traces print and contain at least one op each
        assert len(fwd_trc.bound_symbols) >= 1
        assert len(bwd_trc.bound_symbols) >= 1
        assert "def " in str(fwd_trc) and "def " in str(bwd_trc)

    def test_missing_rule_raises(self):
        from thunder_tpu.core.symbol import Symbol
        from thunder_tpu.core.vjp_utils import make_aug_forward_and_backward

        sym = Symbol("no_rule_op", lambda x: x, id="test.no_rule_op", is_prim=True)
        bsym = sym.bind(jnp.ones(()), output=jnp.ones(()))
        with pytest.raises(LookupError):
            make_aug_forward_and_backward(bsym)


# ---------------------------------------------------------------------------
# numpy language
# ---------------------------------------------------------------------------


class TestNumpyLang:
    def test_basic_ops(self, rng):
        from thunder_tpu.ops import numpy_lang as tnp

        x = jnp.asarray(rng.rand(4, 8).astype(np.float32))
        y = jnp.asarray(rng.rand(4, 8).astype(np.float32))

        def f(x, y):
            return tnp.sum(tnp.multiply(x, y), axis=-1)

        out = tt.jit(f)(x, y)
        np.testing.assert_allclose(np.asarray(out), np.sum(np.asarray(x) * np.asarray(y), axis=-1), atol=1e-5)

    def test_shape_and_linalg(self, rng):
        from thunder_tpu.ops import numpy_lang as tnp

        x = jnp.asarray(rng.rand(4, 8).astype(np.float32))

        def g(x):
            return tnp.matmul(tnp.transpose(x), tnp.exp(x))

        out = tt.jit(g)(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x).T @ np.exp(np.asarray(x)), rtol=2e-2)

    def test_reductions_keepdims(self, rng):
        from thunder_tpu.ops import numpy_lang as tnp

        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))

        def h(x):
            return tnp.amax(tnp.power(tnp.absolute(x), 2.0), axis=0, keepdims=True)

        out = tt.jit(h)(x)
        np.testing.assert_allclose(np.asarray(out), np.max(np.abs(np.asarray(x)) ** 2, axis=0, keepdims=True),
                                   atol=1e-5)


# ---------------------------------------------------------------------------
# custom ops
# ---------------------------------------------------------------------------


class TestCustomOp:
    def test_forward_and_vjp(self, rng):
        from thunder_tpu.transforms.autodiff import ThunderValueAndGrad

        @tt.custom_op("testlib.swish4", like=lambda x: x)
        def swish4(x):
            return x * jax.nn.sigmoid(4.0 * x)

        @swish4.register_vjp
        def swish4_vjp(x, g):
            s = jax.nn.sigmoid(4.0 * x)
            return g * (s + 4.0 * x * s * (1.0 - s))

        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        xn = np.asarray(x)
        out = tt.jit(lambda x: swish4(x))(x)
        np.testing.assert_allclose(np.asarray(out), xn / (1 + np.exp(-4 * xn)) * 1.0 * xn / xn, atol=1e-5)

        v = ThunderValueAndGrad(lambda x: ltorch.sum(swish4(x)), argnums=0)
        _, grads = v(x)
        s = 1 / (1 + np.exp(-4 * xn))
        np.testing.assert_allclose(np.asarray(grads[0][0]), s + 4 * xn * s * (1 - s), atol=1e-4)

    def test_requires_exactly_one_spec(self):
        with pytest.raises(TypeError):
            tt.custom_op("testlib.bad")(lambda x: x)
        with pytest.raises(TypeError):
            tt.custom_op("testlib.bad2", like=lambda x: x, meta=lambda x: x)(lambda x: x)


# ---------------------------------------------------------------------------
# LoRA
# ---------------------------------------------------------------------------


class _LoraNet(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32, seed=3)
        self.fc2 = nn.Linear(32, 4, seed=4)

    def forward(self, x, y):
        return ltorch.mse_loss(self.fc2(ltorch.relu(self.fc1(x))), y)


class TestLoRA:
    def test_adapters_train_base_frozen(self, rng):
        from thunder_tpu.transforms.lora import LORATransform

        net = _LoraNet()
        w1_before = np.asarray(net.fc1.weight.data).copy()
        tm = tt.jit(net, transforms=[LORATransform(r=4, lora_alpha=8, target_modules=("fc1",))])
        step = TrainStep(tm, optim.AdamW(lr=0.05))
        x = jnp.asarray(rng.rand(8, 16).astype(np.float32))
        y = jnp.asarray(rng.rand(8, 4).astype(np.float32))
        l0 = float(step(x, y))
        for _ in range(5):
            step(x, y)
        l1 = float(step(x, y))
        assert l1 < l0
        np.testing.assert_array_equal(w1_before, np.asarray(net.fc1.weight.data))
        assert np.abs(np.asarray(net.fc1._parameters["lora_B"].data)).max() > 0

    def test_no_match_raises(self):
        from thunder_tpu.transforms.lora import LORATransform

        with pytest.raises(ValueError):
            tt.jit(_LoraNet(), transforms=[LORATransform(target_modules=("nonexistent",))])


# ---------------------------------------------------------------------------
# gradient bucketing
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestGradBucketing:
    def test_ddp_bucketing_matches_reference(self):
        from thunder_tpu.parallel import ddp, make_mesh
        from thunder_tpu.parallel.bucketing import GradBucketingTransform

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(16, 16), jnp.float32)
        y = jnp.zeros((16, 4), jnp.float32)

        m0 = _LoraNet()
        sd = {k: np.asarray(v).copy() for k, v in m0.state_dict().items()}
        ref_step = TrainStep(m0, optim.AdamW(lr=1e-2))
        ref = [float(ref_step(x, y)) for _ in range(3)]

        m1 = _LoraNet()
        m1.load_state_dict(sd)
        tm = tt.jit(m1, transforms=[GradBucketingTransform(bucket_size_in_mb=25)])
        ddp(tm, make_mesh({"dp": 8}))
        step = TrainStep(tm, optim.AdamW(lr=1e-2))
        got = [float(step(x, y)) for _ in range(3)]
        np.testing.assert_allclose(got, ref, atol=1e-4)

        bwd = str(step._vag._cs.last_backward_traces[0])
        assert "dist.pack" in bwd and "dist.unpack" in bwd
        # 4 per-param all-reduces collapsed into 1
        assert bwd.count("dist.all_reduce") == 1


# ---------------------------------------------------------------------------
# recipes
# ---------------------------------------------------------------------------


class TestRecipes:
    def test_resolve_named(self):
        from thunder_tpu.recipes import BaseRecipe, HFTransformers, resolve_recipe

        assert isinstance(resolve_recipe("base", None), BaseRecipe)
        assert isinstance(resolve_recipe("hf-transformers", None), HFTransformers)
        with pytest.raises(ValueError):
            resolve_recipe("nope", None)

    def test_hf_validation_rejects_non_hf(self):
        from thunder_tpu.recipes import HFTransformers

        with pytest.raises(ValueError):
            HFTransformers().validate(_LoraNet())


class TestCustomOpArity:
    def test_optional_arg_two_arities(self, rng):
        from thunder_tpu.transforms.autodiff import ThunderValueAndGrad

        @tt.custom_op("testlib2.scale_shift", like=lambda x, s=None: x)
        def scale_shift(x, s=None):
            return x * 2.0 + (s if s is not None else 0.0)

        @scale_shift.register_vjp
        def scale_shift_vjp(*args):
            g = args[-1]
            if len(args) == 3:
                return g * 2.0, g
            return g * 2.0

        x = jnp.asarray(rng.rand(4).astype(np.float32))
        s = jnp.asarray(rng.rand(4).astype(np.float32))

        def f(x, s):
            return ltorch.sum(ltorch.add(scale_shift(x), scale_shift(x, s)))

        _, grads = ThunderValueAndGrad(f, argnums=(0, 1))(x, s)
        np.testing.assert_allclose(np.asarray(grads[0][0]), 4.0)
        np.testing.assert_allclose(np.asarray(grads[0][1]), 1.0)


class TestPatternChained:
    def test_chained_matches_rename_into_splices(self, rng):
        from thunder_tpu import acquire_trace
        from thunder_tpu.core import prims
        from thunder_tpu.core.patterns import Pattern, uses
        from thunder_tpu.core.prims import PrimIDs
        from thunder_tpu.core.transform_common import dce, flatten_to_prims
        from thunder_tpu.executors.passes import transform_for_execution
        from thunder_tpu.extend import resolve_executors

        def g2(a, b, c, d, e):
            return (a * b + c) * d + e

        args = [jnp.asarray(rng.rand(4).astype(np.float32)) for _ in range(5)]
        trc, *_ = acquire_trace(g2, tuple(args), {})
        trc = flatten_to_prims(trc)
        p = (Pattern()
             .match_op(PrimIDs.MUL, bind_args=("a", "b"), bind_out="prod")
             .match_op(PrimIDs.ADD, where=uses("prod"), bind_args=(None, "c")))

        def fma(a, b, c, prod=None):
            return prims.add(prims.mul(a, b), c)

        new_trc = p.replace(trc, fma)
        claimed = transform_for_execution(dce(new_trc), resolve_executors(None))
        out = claimed.python_callable()(*args)
        an = [np.asarray(a) for a in args]
        np.testing.assert_allclose(np.asarray(out), (an[0] * an[1] + an[2]) * an[3] + an[4], atol=1e-5)
