"""prefetch_to_device failure-mode contract (thunder_tpu/data/prefetch.py):
ordering, clean exhaustion, worker-exception propagation, and deadlock-free
early consumer exit."""
import gc

import numpy as np
import pytest

from thunder_tpu.data import TokenLoader, write_token_file
from thunder_tpu.data.prefetch import prefetch_to_device


def test_ordering_preserved():
    items = [np.full((2, 2), i, np.int32) for i in range(20)]
    out = list(prefetch_to_device(iter(items), size=3))
    assert len(out) == 20
    for i, x in enumerate(out):
        assert int(np.asarray(x)[0, 0]) == i


def test_default_transfer_lands_on_device():
    import jax

    out = list(prefetch_to_device(iter([np.arange(4, dtype=np.int32)]), size=2))
    assert len(out) == 1
    assert isinstance(out[0], jax.Array)
    np.testing.assert_array_equal(np.asarray(out[0]), np.arange(4))


def test_pytree_batches_transfer_whole():
    batches = [(np.zeros((2, 3)), np.ones((2, 3))) for _ in range(3)]
    for x, y in prefetch_to_device(iter(batches), size=2):
        assert np.asarray(x).shape == (2, 3)
        assert float(np.asarray(y).sum()) == 6.0


def test_exhaustion_terminates_cleanly():
    p = prefetch_to_device(iter([np.zeros(1)]), size=2)
    assert len(list(p)) == 1
    assert list(p) == []  # exhausted iterator stays exhausted
    p.close()


def test_worker_exception_propagates_in_order():
    def gen():
        yield np.zeros(2)
        yield np.ones(2)
        raise ValueError("boom")

    p = prefetch_to_device(gen(), size=2)
    assert float(np.asarray(next(p)).sum()) == 0.0
    assert float(np.asarray(next(p)).sum()) == 2.0
    with pytest.raises(ValueError, match="boom"):
        next(p)
    with pytest.raises(StopIteration):
        next(p)


def test_transfer_exception_propagates():
    def bad(x):
        raise RuntimeError("transfer failed")

    p = prefetch_to_device(iter([1, 2]), transfer=bad)
    with pytest.raises(RuntimeError, match="transfer failed"):
        next(p)


def test_early_consumer_exit_no_deadlock():
    def endless():
        i = 0
        while True:
            yield np.full((4,), i, np.int32)
            i += 1

    p = prefetch_to_device(endless(), size=2)
    for _ in range(3):
        next(p)
    t = p._thread
    p.close()
    t.join(timeout=5.0)
    assert not t.is_alive(), "producer thread survived close()"


def test_dropped_iterator_reaps_worker():
    # the worker must not hold the iterator alive: dropping the consumer
    # reference reaches __del__ -> close(), which stops the thread
    p = prefetch_to_device(iter(range(10_000)), size=2, transfer=lambda x: x)
    next(p)
    t = p._thread
    del p
    gc.collect()
    t.join(timeout=5.0)
    assert not t.is_alive(), "worker leaked after the consumer was dropped"


def test_context_manager_closes():
    with prefetch_to_device(iter(range(100)), size=2, transfer=lambda x: x) as p:
        assert next(p) == 0
        t = p._thread
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_invalid_size_rejected():
    with pytest.raises(ValueError):
        prefetch_to_device(iter(()), size=0)


def test_tokenloader_prefetched_stream(tmp_path):
    path = str(tmp_path / "tokens.bin")
    write_token_file(path, np.arange(5000) % 50000, token_bytes=2)
    loader = TokenLoader(path, batch_size=2, seq_len=16, native=False)
    stream = loader.prefetched(size=2)
    for _ in range(4):
        x, y = next(stream)
        np.testing.assert_array_equal(np.asarray(x)[:, 1:], np.asarray(y)[:, :-1])
    stream.close()
    loader.close()
