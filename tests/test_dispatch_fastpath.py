"""Steady-state dispatch fast paths (ISSUE 5): TrainStep's epoch-cached
param split, InterpretedFunction's leaf-plan + keyed MRU entry cache, the
hoisted observability gate, and the host_overhead metric.

The InterpretedFunction tests install a stub ``_compile`` so the dispatch
machinery (flatten, leaf plan, shape key, bucket probe, guards, reason
codes) is exercised without the bytecode-interpreter frontend — which keeps
them meaningful on interpreters the frontend gates out (CI runs 3.12; this
dispatch layer is version-independent).
"""
import importlib.util
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

import thunder_tpu as tt
from thunder_tpu import nn, observability, optim
from thunder_tpu.frontend import compiled as C
from thunder_tpu.frontend.compiled import InterpretedEntry, InterpretedFunction
from thunder_tpu.nn.module import structure_epoch
from thunder_tpu.ops import ltorch
from thunder_tpu.training import TrainStep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4, seed=0)

    def forward(self, x, y):
        return ltorch.mse_loss(self.fc(x), y)


def _step_and_batch(rng):
    net = _Net()
    step = TrainStep(tt.jit(net), optim.AdamW(lr=0.05))
    x = jnp.asarray(rng.rand(4, 8).astype(np.float32))
    y = jnp.asarray(rng.rand(4, 4).astype(np.float32))
    return net, step, x, y


# ---------------------------------------------------------------------------
# TrainStep: epoch-cached split
# ---------------------------------------------------------------------------


class TestTrainStepFastPath:
    def test_steady_state_does_not_walk_module_tree(self, rng, monkeypatch):
        net, step, x, y = _step_and_batch(rng)
        float(step(x, y))
        float(step(x, y))
        assert step._split_walks == 1, "steady-state step re-split the params"

        walks = {"n": 0}
        orig = nn.Module.named_modules

        def counting(self, prefix=""):
            walks["n"] += 1
            return orig(self, prefix)

        monkeypatch.setattr(nn.Module, "named_modules", counting)
        l3 = float(step(x, y))
        l4 = float(step(x, y))
        assert walks["n"] == 0, "steady-state step walked the module tree"
        assert step._split_walks == 1
        assert np.isfinite(l3) and np.isfinite(l4)

    def test_requires_grad_flip_invalidates_cached_split(self, rng):
        net, step, x, y = _step_and_batch(rng)
        t0, f0, _ = step._split_arrays()
        walks = step._split_walks
        assert "fc.weight" in t0 and "fc.weight" not in f0
        step._split_arrays()
        assert step._split_walks == walks  # epoch unchanged: cached

        net.fc.weight.requires_grad = False
        t1, f1, _ = step._split_arrays()
        assert step._split_walks == walks + 1
        assert "fc.weight" in f1 and "fc.weight" not in t1

        net.fc.weight.requires_grad = True
        t2, f2, _ = step._split_arrays()
        assert "fc.weight" in t2 and "fc.weight" not in f2

    def test_param_add_and_remove_invalidate_cached_split(self, rng):
        net, step, x, y = _step_and_batch(rng)
        step._split_arrays()
        walks = step._split_walks
        net.register_parameter("extra", nn.Parameter(jnp.zeros((2,))))
        t1, _, _ = step._split_arrays()
        assert step._split_walks == walks + 1
        assert "extra" in t1
        del net.extra
        t2, _, _ = step._split_arrays()
        assert "extra" not in t2

    def test_structure_epoch_moves_on_mutations(self):
        net = _Net()
        e0 = structure_epoch()
        net.fc.bias.requires_grad = False
        assert structure_epoch() > e0
        e1 = structure_epoch()
        net.register_buffer("scale", jnp.ones(()))
        assert structure_epoch() > e1
        e2 = structure_epoch()
        net.eval()
        assert structure_epoch() > e2
        # the stores themselves are instrumented: the direct dict writes
        # transforms use (bypassing __setattr__/register_*) bump too
        e3 = structure_epoch()
        net.fc._parameters["weight"] = nn.Parameter(jnp.zeros((4, 8)))
        assert structure_epoch() > e3
        e4 = structure_epoch()
        net._buffers["fresh"] = jnp.ones(())
        assert structure_epoch() > e4
        # ...but buffer VALUE rebinds (effect replay does one per step) do not
        e5 = structure_epoch()
        net._buffers["fresh"] = jnp.full((), 2.0)
        assert structure_epoch() == e5
        # `store |= {...}` goes through the C-level dict update unless
        # __ior__ is overridden — it must invalidate like any other write
        e6 = structure_epoch()
        net.fc._parameters |= {"weight": nn.Parameter(jnp.zeros((4, 8)))}
        assert structure_epoch() > e6

    def test_noop_mutations_do_not_bump(self):
        # the torch idioms of re-asserting train() / requires_grad every
        # iteration must not defeat the fast path with spurious epoch bumps
        net = _Net()
        net.train()  # already training: no-op
        e0 = structure_epoch()
        net.train()
        net.fc.weight.requires_grad = True  # already True
        net.training = True  # direct no-op mode write
        assert structure_epoch() == e0
        net.eval()  # a REAL flip still bumps
        assert structure_epoch() > e0

    def test_micro_step_uses_cached_split(self, rng):
        net, step, x, y = _step_and_batch(rng)
        float(step(x, y))
        float(step.micro_step(x, y))
        float(step.micro_step(x, y))
        assert step._split_walks == 1, "micro_step re-split the params"
        step._grad_acc = None  # discard the window: plain steps resume

    def test_direct_dict_param_replacement_invalidates(self, rng):
        # weight-tying / transform style: install an ALREADY-CONSTRUCTED
        # Parameter via the direct store write — the cached split must drop
        # its stale reference and serve (and write back through) the new one
        net, step, x, y = _step_and_batch(rng)
        step._split_arrays()
        walks = step._split_walks
        replacement = nn.Parameter(jnp.zeros_like(net.fc.weight.data))
        net.fc._parameters["weight"] = replacement
        t1, _, pairs = step._split_arrays()
        assert step._split_walks == walks + 1
        assert t1["fc.weight"] is replacement.data
        assert any(p is replacement for _, p in pairs)

    def test_mode_flip_during_no_sync_keeps_raising(self, rng):
        # the mode-flip-inside-accumulation-window error must fire on EVERY
        # step until the window ends — consuming the structure epoch before
        # raising would swallow the flip and silently run the stale program
        net, step, x, y = _step_and_batch(rng)
        float(step(x, y))
        mode0 = step._active_mode
        step._grad_acc = {}  # simulate an open no_sync accumulation window
        step.tmodule.eval()
        with pytest.raises(RuntimeError, match="no_sync"):
            step._sync_mode()
        with pytest.raises(RuntimeError, match="no_sync"):
            step._sync_mode()  # second call must still see the flip
        step._grad_acc = None  # window closed: the flip now takes effect
        step._sync_mode()
        assert step._active_mode != mode0

    def test_buffer_values_reread_without_walk(self, rng):
        net, step, x, y = _step_and_batch(rng)
        net.register_buffer("scale", jnp.ones(()))
        _, f0, _ = step._split_arrays()
        walks = step._split_walks
        assert float(f0["scale"]) == 1.0
        # value rebind (what effect replay does) must NOT need a re-walk,
        # yet the fresh value must flow into the next step's inputs
        net._buffers["scale"] = jnp.full((), 2.0)
        _, f1, _ = step._split_arrays()
        assert step._split_walks == walks
        assert float(f1["scale"]) == 2.0

    def test_mode_flip_still_selects_program(self, rng):
        net, step, x, y = _step_and_batch(rng)
        float(step(x, y))
        mode0 = step._active_mode
        step.tmodule.eval()
        float(step(x, y))
        assert step._active_mode != mode0, "eval() flip was not observed"
        step.tmodule.train()
        float(step(x, y))
        assert step._active_mode == mode0

    def test_write_back_updates_parameters(self, rng):
        net, step, x, y = _step_and_batch(rng)
        w0 = np.asarray(net.fc.weight.data).copy()
        float(step(x, y))
        float(step(x, y))
        assert not np.array_equal(w0, np.asarray(net.fc.weight.data))


# ---------------------------------------------------------------------------
# observability: opt-in on, zero bus work off
# ---------------------------------------------------------------------------


class TestDispatchObservability:
    def test_disabled_mode_zero_bus_calls(self, rng, monkeypatch):
        net, step, x, y = _step_and_batch(rng)
        float(step(x, y))
        float(step(x, y))
        assert not observability.enabled()

        def boom(*a, **k):
            raise AssertionError("event bus touched on the disabled hot path")

        from thunder_tpu import training as T
        from thunder_tpu.observability import events as ev

        monkeypatch.setattr(ev, "event", boom)
        monkeypatch.setattr(ev, "inc", boom)
        monkeypatch.setattr(T._obs_runtime, "step_span", boom)
        float(step(x, y))  # steady-state step: no bus calls, no span entry

    def test_host_overhead_event_emitted_and_summarized(self, rng):
        observability.reset()
        observability.enable()
        try:
            net, step, x, y = _step_and_batch(rng)
            float(step(x, y))  # build step: no host_overhead (compile skews it)
            float(step(x, y))
            float(step(x, y))
            evs = [r for r in observability.records()
                   if r["kind"] == "event" and r["name"] == "host_overhead"]
            assert len(evs) == 2
            assert all(r["attrs"]["fn"] == "train_step" for r in evs)
            assert all(r["attrs"]["us"] > 0 for r in evs)

            spec = importlib.util.spec_from_file_location(
                "obs_summary", os.path.join(REPO, "tools", "obs_summary.py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            out = mod.render(observability.records())
            assert "host dispatch overhead" in out
            assert "train_step" in out
        finally:
            observability.disable()
            observability.reset()


# ---------------------------------------------------------------------------
# InterpretedFunction dispatch (stubbed compile)
# ---------------------------------------------------------------------------


def _fake_interpreted(fn=None, cache="constant values", prologue=None):
    """InterpretedFunction whose _compile installs an identity entry — the
    dispatch path (the unit under test) runs unchanged."""
    cf = InterpretedFunction(fn or (lambda *a, **k: None), cache=cache)

    def fake_compile(args, kwargs, shape_key):
        entry = InterpretedEntry(prologue or (lambda *t: t), lambda *t: t,
                                 None, None, shape_key)
        cf._entries.append(entry)
        cf._entries_by_key.setdefault(shape_key, []).insert(0, entry)
        return entry

    cf._compile = fake_compile
    return cf


class TestInterpretedDispatchFastPath:
    def test_cache_hit_skips_remasking(self, monkeypatch):
        calls = {"n": 0}
        real = C._is_tensor_like

        def counting(x):
            calls["n"] += 1
            return real(x)

        monkeypatch.setattr(C, "_is_tensor_like", counting)
        cf = _fake_interpreted()
        x = jnp.ones((2, 3))
        cf(x, 2)
        first = calls["n"]
        assert first > 0
        cf(x, 2)
        assert calls["n"] == first, "cache hit re-ran per-leaf masking"
        assert cf.cache_hits == 1
        # a scalar VALUE change reuses the leaf plan (same types) but is a
        # distinct cache key -> new entry, still no re-masking
        cf(x, 3)
        assert calls["n"] == first
        assert cf.cache_misses == 2

    def test_keyed_bucket_mru_order(self):
        cf = _fake_interpreted()
        x = jnp.ones((2, 2))
        cf(x)
        key = cf._entries[0].shape_key
        gate = {"open": False}

        def guarded(*t):
            if not gate["open"]:
                raise RuntimeError("guard failed")
            return t

        picky = InterpretedEntry(guarded, lambda *t: t, None, None, key)
        cf._entries.append(picky)
        cf._entries_by_key[key].insert(0, picky)  # picky probes first

        cf(x)  # picky's guard raises; the permissive entry hits
        assert cf.cache_hits == 1
        assert cf._entries_by_key[key][0] is not picky, "MRU did not promote the hit"
        cf(x)  # steady state now probes the winner first
        assert cf.cache_hits == 2

    def test_all_guards_fail_recompiles_with_reason(self):
        observability.reset()
        observability.enable()
        try:
            attempts = {"n": 0}

            def flaky_prologue(*t):
                # passes on compile #1 (run 1), fails on the cache probe of
                # call #2 (run 2), passes for the freshly recompiled entry
                # (run 3) — a captured value changing between calls
                attempts["n"] += 1
                if attempts["n"] == 2:
                    raise RuntimeError("captured value changed")
                return t

            cf = _fake_interpreted(prologue=flaky_prologue)
            x = jnp.ones((3,))
            cf(x)  # compile #1 (prologue run #1 passes)
            cf(x)  # guard fails -> falls through to recompile
            assert cf.cache_misses == 2
            recs = [r for r in observability.records()
                    if r["kind"] == "event" and r["name"] == "recompile"]
            assert recs, "guard failure did not record a recompile"
            last = recs[-1]["attrs"]
            assert last["reason"] == "shape-change"
            assert last["guard_failed"] is True
        finally:
            observability.disable()
            observability.reset()

    def test_same_input_mode_uses_precomputed_extraction(self, monkeypatch):
        cf = _fake_interpreted(cache="same input")
        x = jnp.ones((2, 2))
        assert np.asarray(cf(x)[0]).shape == (2, 2)
        calls = {"n": 0}

        def counting(l):
            calls["n"] += 1
            return C._unwrap_param(l)

        monkeypatch.setattr(C, "_is_tensor_like", lambda l: (_ for _ in ()).throw(
            AssertionError("same-input hit re-masked leaves")))
        out = cf(x)
        assert cf.cache_hits == 1
        assert np.asarray(out[0]).shape == (2, 2)

    def test_disabled_mode_hit_path_zero_bus_calls(self, monkeypatch):
        cf = _fake_interpreted()
        x = jnp.ones((2,))
        cf(x)
        assert not observability.enabled()

        def boom(*a, **k):
            raise AssertionError("record_cache called with the bus disabled")

        monkeypatch.setattr(C._obs_metrics, "record_cache", boom)
        monkeypatch.setattr(C._obs, "event", boom)
        cf(x)
        assert cf.cache_hits == 1

    def test_mru_promotion_thread_safe(self):
        # two same-shape-key entries whose guards accept disjoint inputs,
        # hammered from threads that alternate between them: every hit on a
        # non-front entry promotes, so promotions race constantly. The
        # bucket must never corrupt (lost entries => wrong routing or
        # permanent recompiles) and no IndexError may escape.
        import threading as th

        cf = _fake_interpreted()
        x0 = jnp.zeros((4,))
        x1 = jnp.ones((4,))
        cf(x0)  # seed an entry to learn the shape key
        key = cf._entries[0].shape_key

        def make_guard(want):
            def prologue(*t):
                if float(np.asarray(t[0])[0]) != want:
                    raise RuntimeError("guard")
                return t
            return prologue

        e0 = InterpretedEntry(make_guard(0.0), lambda *t: ("e0",) + t, None, None, key)
        e1 = InterpretedEntry(make_guard(1.0), lambda *t: ("e1",) + t, None, None, key)
        cf._entries[:] = [e0, e1]
        cf._entries_by_key[key] = [e0, e1]

        def routed_compile(args, kwargs, shape_key):
            # a benignly-raced probe miss re-registers the right entry
            # instead of polluting the bucket with a catch-all
            e = e0 if float(np.asarray(args[0])[0]) == 0.0 else e1
            with cf._mru_lock:
                cf._entries_by_key.setdefault(shape_key, []).insert(0, e)
            return e

        cf._compile = routed_compile
        errors = []

        def worker(arr, tag):
            try:
                for _ in range(200):
                    out = cf(arr)
                    assert out[0] == tag, f"wrong entry routed: {out[0]} != {tag}"
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [th.Thread(target=worker, args=(x0, "e0")),
                   th.Thread(target=worker, args=(x1, "e1")),
                   th.Thread(target=worker, args=(x0, "e0")),
                   th.Thread(target=worker, args=(x1, "e1"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        assert set(cf._entries_by_key[key]) == {e0, e1}

    def test_cached_dispatch_python_work_bounded(self):
        """Microbench regression guard: the cached dispatch path (flatten,
        plan lookup, shape key, bucket probe) stays a handful of Python
        calls — a new per-leaf loop of function calls would blow the bound."""
        cf = _fake_interpreted()
        x = jnp.ones((4, 4))
        cf(x)   # compile
        cf(x)   # warm the leaf-plan cache
        counter = {"n": 0}

        def prof(frame, event, arg):
            if event == "call":
                counter["n"] += 1

        sys.setprofile(prof)
        try:
            cf(x)
        finally:
            sys.setprofile(None)
        assert cf.cache_hits >= 2
        assert counter["n"] <= 40, (
            f"cached dispatch ran {counter['n']} Python calls (bound 40); "
            f"host fast path regressed")
