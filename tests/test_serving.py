"""Serving engine: continuous batching + paged KV cache correctness.

The engine contract under test: every request decoded under continuous
batching produces EXACTLY the token stream it would produce running solo
through the dense GPTInference engine — whatever mix of lengths, slots, and
admission waits it experienced — and a finished request's pages return to
the pool immediately. Runs entirely under JAX_PLATFORMS=cpu (conftest);
the pallas paged kernel path is covered in interpret mode by
tests/test_inference.py's equivalence tests.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import thunder_tpu as tt
from thunder_tpu.inference import GPTInference
from thunder_tpu.models.litgpt import Config, GPT
from thunder_tpu.serving import (OutOfPages, PageAllocator, PagedKVCache,
                                 PrefixCache, ServingEngine)
from thunder_tpu.serving.runner import bucket_len

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def gpt():
    cfg = Config.from_name("tiny-llama2", block_size=64)
    return GPT(cfg, dtype=jnp.float32)


@pytest.fixture(scope="module")
def dense(gpt):
    return GPTInference(gpt, dtype=jnp.float32)


def _engine(gpt, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq", 64)
    kw.setdefault("dtype", jnp.float32)
    return ServingEngine(gpt, **kw)


# ---------------------------------------------------------------------------
# allocator / page-pool unit behavior
# ---------------------------------------------------------------------------


def test_allocator_freelist_roundtrip():
    a = PageAllocator(8)  # 7 usable + null
    assert a.n_free == 7
    got = a.alloc(5)
    assert len(set(got)) == 5 and 0 not in got
    assert a.n_used == 5
    with pytest.raises(OutOfPages):
        a.alloc(3)
    a.free(got[:2])
    assert a.n_free == 4
    with pytest.raises(ValueError):
        a.free([got[0]])  # double free
    with pytest.raises(ValueError):
        a.free([0])  # the null page is never allocatable


def test_page_table_row_pads_with_null():
    cache = PagedKVCache(1, 8, 4, 2, 8, jnp.float32)
    row = cache.page_table_row([3, 5], 4)
    assert row.tolist() == [3, 5, 0, 0]


def test_bucket_len_powers_of_two():
    assert bucket_len(1, minimum=8, maximum=64) == 8
    assert bucket_len(8, minimum=8, maximum=64) == 8
    assert bucket_len(9, minimum=8, maximum=64) == 16
    assert bucket_len(33, minimum=8, maximum=64) == 64
    assert bucket_len(200, minimum=8, maximum=64) == 64  # capped


# ---------------------------------------------------------------------------
# engine correctness vs the dense solo engine
# ---------------------------------------------------------------------------


def test_single_request_matches_dense(gpt, dense, rng):
    engine = _engine(gpt)
    prompt = rng.randint(0, gpt.cfg.vocab_size, (9,)).astype(np.int32)
    fut = engine.submit(prompt, max_new_tokens=6)
    engine.drain()
    res = fut.result()
    out, _ = dense.generate(jnp.asarray(prompt[None, :]), 6, scan_decode=False)
    np.testing.assert_array_equal(res.new_tokens, np.asarray(out)[0, 9:])
    assert res.tokens.shape == (15,)
    assert res.finish_reason == "length"
    assert res.ttft_s > 0 and res.tbot_s > 0


def test_concurrent_mixed_lengths_match_dense(gpt, dense, rng):
    """More requests than decode slots, mixed prompt/output lengths: every
    stream must equal its solo dense decode (slot reuse + admission waits
    must not perturb any sequence)."""
    engine = _engine(gpt)
    shapes = [(5, 7), (13, 4), (9, 10), (20, 3), (3, 8), (11, 5)]
    reqs = []
    for L, n in shapes:
        p = rng.randint(0, gpt.cfg.vocab_size, (L,)).astype(np.int32)
        reqs.append((p, n, engine.submit(p, max_new_tokens=n)))
    engine.drain()
    for p, n, fut in reqs:
        res = fut.result()
        out, _ = dense.generate(jnp.asarray(p[None, :]), n, scan_decode=False)
        np.testing.assert_array_equal(res.new_tokens, np.asarray(out)[0, len(p):])
    # all pages returned at retirement
    assert engine.cache.allocator.n_used == 0
    assert engine.stats()["page_pool_utilization"] == 0.0


def test_temperature_stream_matches_dense_seeded(gpt, dense, rng):
    """Position-keyed sampling: the same (seed, temperature) request draws
    the identical stream solo or continuously batched."""
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (7,)).astype(np.int32)
    fut = engine.submit(p, max_new_tokens=8, temperature=0.9, seed=42)
    # a concurrent greedy request keeps the batch genuinely mixed
    other = engine.submit(rng.randint(0, gpt.cfg.vocab_size, (12,)).astype(np.int32),
                          max_new_tokens=5)
    engine.drain()
    res = fut.result()
    other.result()
    out, _ = dense.generate(jnp.asarray(p[None, :]), 8, temperature=0.9,
                            seed=42, scan_decode=False)
    np.testing.assert_array_equal(res.new_tokens, np.asarray(out)[0, 7:])


def test_eos_retires_early_and_frees_pages(gpt, rng):
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    # find the greedy continuation's second token, then use it as eos
    probe = engine.submit(p, max_new_tokens=3)
    engine.drain()
    tok2 = int(probe.result().new_tokens[1])
    fut = engine.submit(p, max_new_tokens=30, eos_id=tok2)
    engine.drain()
    res = fut.result()
    assert res.finish_reason == "eos"
    assert res.n_new_tokens == 2  # stopped at eos, 28 tokens early
    assert engine.cache.allocator.n_used == 0


def test_admission_waits_for_pages_then_completes(gpt, dense, rng):
    """A pool sized for ~one sequence forces head-of-line waiting; both
    requests must still complete correctly (pages return at retirement)."""
    # 9 usable pages: one (L=9, n=7) request needs bucket 16/8=2 prefill
    # pages and ceil(16/8)=2 worst-case -> 2; three requests need 6; size
    # the pool so only one fits at a time
    engine = _engine(gpt, n_pages=4)
    reqs = []
    for _ in range(3):
        p = rng.randint(0, gpt.cfg.vocab_size, (9,)).astype(np.int32)
        reqs.append((p, engine.submit(p, max_new_tokens=7)))
    engine.drain()
    for p, fut in reqs:
        res = fut.result()
        out, _ = dense.generate(jnp.asarray(p[None, :]), 7, scan_decode=False)
        np.testing.assert_array_equal(res.new_tokens, np.asarray(out)[0, 9:])
    assert engine.cache.allocator.n_used == 0


def test_inadmissible_requests_fail_fast(gpt, rng):
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (60,)).astype(np.int32)
    with pytest.raises(ValueError, match="max_seq"):
        engine.submit(p, max_new_tokens=10).result()  # 60 + 10 > 64
    small = _engine(gpt, n_pages=3)  # 2 usable pages
    big = rng.randint(0, gpt.cfg.vocab_size, (40,)).astype(np.int32)
    with pytest.raises(ValueError, match="pages"):
        small.submit(big, max_new_tokens=8).result()


def test_background_thread_driver(gpt, dense, rng):
    """submit() from the caller thread while the loop runs in background."""
    engine = _engine(gpt)
    engine.start()
    try:
        p = rng.randint(0, gpt.cfg.vocab_size, (8,)).astype(np.int32)
        res = engine.submit(p, max_new_tokens=5).result(timeout=120)
        out, _ = dense.generate(jnp.asarray(p[None, :]), 5, scan_decode=False)
        np.testing.assert_array_equal(res.new_tokens, np.asarray(out)[0, 8:])
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# steady-state compile behavior + observability
# ---------------------------------------------------------------------------


def test_zero_steady_state_recompiles(gpt, rng):
    """After warming the decode step and each prompt bucket, a fresh wave of
    mixed-length requests must trigger ZERO reason-coded recompile events —
    the acceptance bar for shape-bucketed continuous batching."""
    from thunder_tpu import observability

    engine = _engine(gpt)
    engine.warmup([3, 9, 17], max_new_tokens=2)  # buckets 8, 16, 32
    observability.enable()
    observability.reset()
    try:
        reqs = []
        for L, n in [(4, 5), (10, 3), (18, 6), (7, 4), (15, 7)]:
            p = rng.randint(0, gpt.cfg.vocab_size, (L,)).astype(np.int32)
            reqs.append(engine.submit(p, max_new_tokens=n))
        engine.drain()
        for fut in reqs:
            fut.result()
        counters = observability.counters()
        recompiles = {k: v for k, v in counters.items() if k.startswith("recompile.")}
        assert not recompiles, f"steady state recompiled: {recompiles}"
        assert counters.get("serve.requests", 0) == 5
        assert counters.get("serve.retired", 0) == 5
        assert counters.get("serve.decode_steps", 0) > 0
        assert counters.get("serve.tokens", 0) == sum(n - 1 for _, n in
                                                      [(4, 5), (10, 3), (18, 6), (7, 4), (15, 7)])
    finally:
        observability.disable()
        observability.reset()


def test_request_spans_and_retire_events(gpt, rng):
    """Per-request observability: request-id-tagged prefill spans and
    serve_retired events with TTFT/TBOT land on the bus."""
    from thunder_tpu import observability

    engine = _engine(gpt)
    observability.enable()
    observability.reset()
    try:
        p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
        rid = None
        fut = engine.submit(p, max_new_tokens=4)
        engine.drain()
        rid = fut.result().request_id
        recs = observability.records()
        prefills = [r for r in recs if r["kind"] == "span" and r["name"] == "serve_prefill"]
        assert any(r["attrs"].get("request") == rid for r in prefills)
        retires = [r for r in recs if r["kind"] == "event" and r["name"] == "serve_retired"]
        assert len(retires) == 1
        attrs = retires[0]["attrs"]
        assert attrs["request"] == rid and attrs["n_new"] == 4
        assert attrs["ttft_ms"] > 0 and attrs["tbot_ms"] > 0
        decodes = [r for r in recs if r["kind"] == "span" and r["name"] == "serve_decode"]
        assert decodes and all(r["attrs"]["active"] >= 1 for r in decodes)
    finally:
        observability.disable()
        observability.reset()


def test_prefill_bucket_mru_promotes(gpt, rng):
    """The serving engine rides the interpreter frontend's ShapeKeyedMRU:
    the bucket that just served probes first."""
    engine = _engine(gpt)
    for L in (3, 20):  # buckets 8, 32
        engine.submit(rng.randint(0, gpt.cfg.vocab_size, (L,)).astype(np.int32), 2)
    engine.drain()
    assert engine.stats()["prefill_buckets"] == [32, 8]
    engine.submit(rng.randint(0, gpt.cfg.vocab_size, (4,)).astype(np.int32), 2)
    engine.drain()
    assert engine.stats()["prefill_buckets"] == [8, 32]


def test_prefill_failure_contained(gpt, dense, rng):
    """A request whose compiled step raises must fail ITS Future, return its
    pages, and leave the engine serving later requests — not kill the loop
    and hang every waiter."""
    engine = _engine(gpt)
    orig = engine.runner.prefill_cfn

    def boom(*a, **kw):
        raise RuntimeError("injected prefill failure")

    engine.runner.prefill_cfn = boom
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    fut = engine.submit(p, max_new_tokens=4)
    engine.drain()
    with pytest.raises(RuntimeError, match="injected"):
        fut.result(timeout=5)
    assert engine.cache.allocator.n_used == 0  # pages returned
    engine.runner.prefill_cfn = orig
    ok = engine.submit(p, max_new_tokens=4)
    engine.drain()
    out, _ = dense.generate(jnp.asarray(p[None, :]), 4, scan_decode=False)
    np.testing.assert_array_equal(ok.result().new_tokens, np.asarray(out)[0, 6:])


def test_decode_failure_fails_active_batch(gpt, rng):
    """A failing packed decode step fails every implicated Future and frees
    their pages; the engine stays usable."""
    engine = _engine(gpt)
    p1 = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    p2 = rng.randint(0, gpt.cfg.vocab_size, (10,)).astype(np.int32)
    f1 = engine.submit(p1, max_new_tokens=8)
    f2 = engine.submit(p2, max_new_tokens=8)
    orig = engine.runner.decode_cfn
    engine.runner.decode_cfn = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("injected decode failure"))
    engine.drain()
    for f in (f1, f2):
        with pytest.raises(RuntimeError, match="injected"):
            f.result(timeout=5)
    assert engine.cache.allocator.n_used == 0
    engine.runner.decode_cfn = orig
    ok = engine.submit(p1, max_new_tokens=3)
    engine.drain()
    assert ok.result().n_new_tokens == 3


def test_seed_canonicalized_mod_2_32(gpt, dense, rng):
    """Seeds outside [0, 2^32) draw the same stream as seed % 2^32 in BOTH
    engines (the packed sampler array is uint32)."""
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    engine = _engine(gpt)
    f_big = engine.submit(p, 6, temperature=1.0, seed=(1 << 32) + 5)
    f_small = engine.submit(p, 6, temperature=1.0, seed=5)
    engine.drain()
    np.testing.assert_array_equal(f_big.result().new_tokens,
                                  f_small.result().new_tokens)
    out_big, _ = dense.generate(jnp.asarray(p[None, :]), 6, temperature=1.0,
                                seed=(1 << 32) + 5, scan_decode=False)
    out_small, _ = dense.generate(jnp.asarray(p[None, :]), 6, temperature=1.0,
                                  seed=5, scan_decode=False)
    np.testing.assert_array_equal(np.asarray(out_big), np.asarray(out_small))
    np.testing.assert_array_equal(f_big.result().new_tokens,
                                  np.asarray(out_big)[0, 6:])


def test_cancelled_future_does_not_wedge_engine(gpt, dense, rng):
    """fut.cancel() must not blow up retirement or leave a slot stuck:
    queued cancellations are dropped before allocation, in-flight ones
    retire at the next step with pages freed, and later requests serve."""
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    queued = engine.submit(p, max_new_tokens=4)
    assert queued.cancel()  # still pending -> cancellable
    live = engine.submit(p, max_new_tokens=4)
    engine.drain()
    assert queued.cancelled()
    out, _ = dense.generate(jnp.asarray(p[None, :]), 4, scan_decode=False)
    np.testing.assert_array_equal(live.result().new_tokens, np.asarray(out)[0, 6:])
    # in-flight cancel: admit, then cancel mid-decode via inline stepping
    f = engine.submit(p, max_new_tokens=30)
    engine._step_once()  # admits + first decode step
    assert f.cancel()  # engine futures are never set_running
    engine.drain()
    assert engine.cache.allocator.n_used == 0  # pages freed either way
    again = engine.submit(p, max_new_tokens=3)
    engine.drain()
    assert again.result().n_new_tokens == 3


def test_misaligned_min_bucket_rejected(gpt):
    with pytest.raises(ValueError, match="min_bucket"):
        _engine(gpt, min_bucket=20)  # not a multiple of page_size=8


def test_intra_call_duplicate_free_rejected():
    a = PageAllocator(8)
    got = a.alloc(2)
    with pytest.raises(ValueError, match="double free"):
        a.free([got[0], got[0]])
    a.free(got)  # the failed call must not have mutated anything
    assert a.n_free == 7


def test_stop_fails_outstanding_futures(gpt, rng):
    """stop() must not strand waiters: whatever is still queued or
    in-flight fails with a clear error and its pages come back."""
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    inflight = engine.submit(p, max_new_tokens=30)
    engine._step_once()  # admit + one decode step
    queued = engine.submit(p, max_new_tokens=4)
    engine.stop()
    for f in (inflight, queued):
        with pytest.raises(RuntimeError, match="stopped"):
            f.result(timeout=5)
    assert engine.cache.allocator.n_used == 0


def test_submit_after_stop_fails_fast(gpt, rng):
    engine = _engine(gpt)
    engine.start()
    engine.stop()
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    with pytest.raises(RuntimeError, match="stopped"):
        engine.submit(p, max_new_tokens=3).result(timeout=5)
    engine.start()  # restartable
    try:
        assert engine.submit(p, max_new_tokens=3).result(timeout=120).n_new_tokens == 3
    finally:
        engine.stop()


def test_drain_with_running_thread_only_waits(gpt, dense, rng):
    """drain() alongside the background thread must wait, not step inline
    (inline stepping would race the thread over slots/pool state)."""
    engine = _engine(gpt)
    engine.start()
    try:
        p = rng.randint(0, gpt.cfg.vocab_size, (7,)).astype(np.int32)
        fut = engine.submit(p, max_new_tokens=5)
        engine.drain()
        assert fut.done()
        out, _ = dense.generate(jnp.asarray(p[None, :]), 5, scan_decode=False)
        np.testing.assert_array_equal(fut.result().new_tokens, np.asarray(out)[0, 7:])
    finally:
        engine.stop()


def test_index_put_negative_indices_normalized(rng):
    """The multi-index linearization canonicalizes numpy-style negative
    indices per-dim (a raw -1 would address the previous row's last slot)."""
    from thunder_tpu.ops import ltorch

    a = jnp.zeros((4, 8, 3), jnp.float32)
    vals = jnp.asarray(rng.randn(2, 3), jnp.float32)
    f = tt.jit(lambda a, i0, i1, v: ltorch.index_put(a, (i0, i1), v))
    out = f(a, jnp.asarray([1, 2], jnp.int32), jnp.asarray([-1, 0], jnp.int32), vals)
    ref = np.zeros((4, 8, 3), np.float32)
    ref[1, -1] = np.asarray(vals)[0]
    ref[2, 0] = np.asarray(vals)[1]
    np.testing.assert_array_equal(np.asarray(out), ref)


def test_moe_serving_matches_dense(rng):
    """The engine drives the MoE decoder too (block plumbing parity with
    inference._forward_cached)."""
    from thunder_tpu.models.moe import MoEConfig, MoEGPT

    cfg = Config.from_name("tiny-llama2", block_size=64)
    moe_cfg = MoEConfig(n_embd=cfg.n_embd, intermediate_size=160,
                        n_expert=4, n_expert_per_token=2)
    gpt = MoEGPT(cfg, moe_cfg, dtype=jnp.float32)
    engine = _engine(gpt)
    dense = GPTInference(gpt, dtype=jnp.float32)
    p = rng.randint(0, cfg.vocab_size, (8,)).astype(np.int32)
    fut = engine.submit(p, max_new_tokens=5)
    engine.drain()
    out, _ = dense.generate(jnp.asarray(p[None, :]), 5, scan_decode=False)
    np.testing.assert_array_equal(fut.result().new_tokens, np.asarray(out)[0, 8:])

# ---------------------------------------------------------------------------
# fleet serving: refcounts / CoW, prefix sharing, chunked prefill,
# speculative decoding, lanes + preemption
# ---------------------------------------------------------------------------


def test_allocator_refcounts():
    a = PageAllocator(8)
    (p,) = a.alloc(1)
    assert a.refcount(p) == 1
    a.incref(p)
    assert a.refcount(p) == 2
    a.free([p])            # decref: the page must NOT return to the free list
    assert a.refcount(p) == 1
    assert a.n_free == 6
    a.free([p])            # last owner lets go -> released
    assert a.refcount(p) == 0
    assert a.n_free == 7
    with pytest.raises(ValueError, match="double free"):
        a.free([p])
    with pytest.raises(ValueError, match="incref"):
        a.incref(p)        # incref of a free page is a use-after-free


def test_shared_page_free_does_not_reissue():
    """A shared page freed by ONE owner must never be handed to a new
    allocation while other owners hold it (the double-free-under-sharing
    hazard the refcount exists to kill)."""
    a = PageAllocator(4)   # 3 usable
    pages = a.alloc(3)
    a.incref(pages[0])     # second owner
    a.free([pages[0]])     # first owner retires
    with pytest.raises(OutOfPages):
        a.alloc(1)         # nothing is actually free
    a.free(pages)          # remaining owners let go of everything
    assert a.n_free == 3 and a.n_used == 0


def test_cow_fork():
    a = PageAllocator(8)
    (p,) = a.alloc(1)
    assert a.fork(p) == p  # sole owner: write-in-place, no copy
    a.incref(p)
    q = a.fork(p)          # shared: detach into a fresh page
    assert q != p
    assert a.refcount(p) == 1 and a.refcount(q) == 1
    with pytest.raises(ValueError, match="fork"):
        a.fork(7)          # never-allocated page


def test_prefix_cache_match_insert_evict():
    a = PageAllocator(16)
    c = PrefixCache(a, 4)
    prompt = np.arange(10, dtype=np.int32)   # 2 full pages + 2-token tail
    pages = a.alloc(3)
    assert c.insert(prompt, pages) == 2      # only FULL prompt pages register
    assert a.refcount(pages[0]) == 2 and a.refcount(pages[2]) == 1
    shared, covered = c.match(prompt[:8])
    assert covered == 8 and shared == pages[:2]
    assert a.refcount(pages[0]) == 3         # match increfs for the caller
    a.free(shared)
    # partial tail: a 6-token prompt whose tail is the LEADING tokens of a
    # cached page is fully covered by sharing that page
    shared, covered = c.match(prompt[:6])
    assert covered == 6 and shared == pages[:2]
    a.free(shared)
    a.free(pages)                            # original owner retires
    assert len(c) == 2 and a.n_used == 2     # cache refs keep 2 pages alive
    assert c.evict_until(15)                 # pool pressure: evict LRU leaves
    assert len(c) == 0 and a.n_used == 0 and a.n_free == 15


def test_prefix_sharing_suffix_prefill_matches_dense(gpt, dense, rng):
    """Requests sharing a system prompt map the donor's pages and prefill
    only the unshared suffix; every stream still equals its solo decode."""
    engine = _engine(gpt, prefix_sharing=True)
    sys_p = rng.randint(0, gpt.cfg.vocab_size, (16,)).astype(np.int32)  # 2 pages
    reqs = []
    for i in range(3):
        tail = rng.randint(0, gpt.cfg.vocab_size, (3,)).astype(np.int32)
        p = np.concatenate([sys_p, tail])
        reqs.append((p, engine.submit(p, max_new_tokens=5, temperature=0.7,
                                      seed=100 + i)))
    engine.drain()
    for p, fut in reqs:
        out, _ = dense.generate(jnp.asarray(p[None, :]), 5, temperature=0.7,
                                seed=int(fut.result().request_id) + 100,
                                scan_decode=False)
        np.testing.assert_array_equal(fut.result().new_tokens,
                                      np.asarray(out)[0, len(p):])
    assert engine.prefix_hits == 2                 # requests 2 and 3
    assert engine.prefix_tokens_saved == 2 * 16


def test_prefix_full_hit_skips_prefill(gpt, dense, rng):
    """Full coverage (including a partial-tail hit) admits with NO prefill:
    one re-decoded prompt token recovers the first-token logits."""
    engine = _engine(gpt, prefix_sharing=True)
    donor = rng.randint(0, gpt.cfg.vocab_size, (16,)).astype(np.int32)
    f1 = engine.submit(donor, max_new_tokens=4, seed=7)
    engine.drain()
    # exact repeat: both full pages hit
    f2 = engine.submit(donor, max_new_tokens=4, seed=7)
    engine.drain()
    np.testing.assert_array_equal(f1.result().new_tokens, f2.result().new_tokens)
    assert engine.prefix_hits == 1
    assert engine.prefix_tokens_saved == 15        # L - 1
    # partial-tail: an 11-token prefix of the donor is covered by page 2
    sub = donor[:11]
    f3 = engine.submit(sub, max_new_tokens=4, temperature=0.5, seed=9)
    engine.drain()
    out, _ = dense.generate(jnp.asarray(sub[None, :]), 4, temperature=0.5,
                            seed=9, scan_decode=False)
    np.testing.assert_array_equal(f3.result().new_tokens,
                                  np.asarray(out)[0, 11:])
    assert engine.prefix_hits == 2
    # donor pages stay intact (copy-on-write protected them from f2/f3 writes)
    f4 = engine.submit(donor, max_new_tokens=4, seed=7)
    engine.drain()
    np.testing.assert_array_equal(f4.result().new_tokens, f1.result().new_tokens)


def test_chunked_prefill_matches_dense(gpt, dense, rng):
    """Long prompts split into page-aligned chunks interleaved under the
    token budget produce streams identical to whole-prompt prefill."""
    engine = _engine(gpt, chunk_tokens=16, prefill_budget=16)
    shapes = [(40, 5), (23, 4)]   # 16+16+final rung, 16+final (mid-page end)
    reqs = []
    for L, n in shapes:
        p = rng.randint(0, gpt.cfg.vocab_size, (L,)).astype(np.int32)
        reqs.append((p, n, engine.submit(p, max_new_tokens=n)))
    engine.drain()
    for p, n, fut in reqs:
        out, _ = dense.generate(jnp.asarray(p[None, :]), n, scan_decode=False)
        np.testing.assert_array_equal(fut.result().new_tokens,
                                      np.asarray(out)[0, len(p):])
    assert engine.cache.allocator.n_used == 0      # no sharing -> all returned


def test_speculative_random_draft_matches_plain(gpt, dense, rng):
    """A draft with different weights proposes wrong tokens sometimes; the
    accept/rollback rule still commits exactly the plain-decode stream."""
    draft = GPT(Config.from_name("tiny-llama2", block_size=64), dtype=jnp.float32)
    engine = _engine(gpt, draft_gpt=draft, spec_k=2)
    p = rng.randint(0, gpt.cfg.vocab_size, (9,)).astype(np.int32)
    fut = engine.submit(p, max_new_tokens=6)
    engine.drain()
    out, _ = dense.generate(jnp.asarray(p[None, :]), 6, scan_decode=False)
    np.testing.assert_array_equal(fut.result().new_tokens,
                                  np.asarray(out)[0, 9:])
    assert engine.spec_proposed > 0
    assert engine.cache.allocator.n_used == 0


def test_all_stages_composed_match_dense(gpt, dense, rng):
    """Sharing + chunking + speculation all enabled at once: every request
    still decodes its exact solo stream (the tentpole equivalence bar).
    The draft IS the target, so this also pins the self-draft ceiling:
    every proposal must verify."""
    engine = _engine(gpt, prefix_sharing=True, chunk_tokens=16,
                     draft_gpt=gpt, spec_k=3)
    sys_p = rng.randint(0, gpt.cfg.vocab_size, (24,)).astype(np.int32)
    shapes = [(0, 6, 0.0, 11), (5, 7, 0.8, 12), (9, 4, 0.0, 13), (2, 5, 0.5, 14)]
    reqs = []
    for tail_len, n, temp, seed in shapes:
        tail = rng.randint(0, gpt.cfg.vocab_size, (tail_len,)).astype(np.int32)
        p = np.concatenate([sys_p, tail]) if tail_len else sys_p.copy()
        reqs.append((p, n, temp, seed,
                     engine.submit(p, max_new_tokens=n, temperature=temp,
                                   seed=seed)))
        if tail_len == 0:
            engine.drain()  # warm the prefix cache before the sharers arrive
    engine.drain()
    for p, n, temp, seed, fut in reqs:
        out, _ = dense.generate(jnp.asarray(p[None, :]), n, temperature=temp,
                                seed=seed, scan_decode=False)
        np.testing.assert_array_equal(fut.result().new_tokens,
                                      np.asarray(out)[0, len(p):])
    assert engine.prefix_hits > 0
    assert engine.spec_proposed > 0
    assert engine.spec_accepted == engine.spec_proposed  # perfect draft


def test_preemption_spill_resume_identity(gpt, dense, rng):
    """A batch-lane victim spilled for an interactive admission resumes and
    finishes with EXACTLY the stream it would have produced unpreempted."""
    engine = _engine(gpt, n_pages=9)               # 8 usable
    victim_p = rng.randint(0, gpt.cfg.vocab_size, (9,)).astype(np.int32)
    victim = engine.submit(victim_p, max_new_tokens=20, lane="batch")
    engine._step_once()                            # admit + a few tokens
    engine._step_once()
    # an interactive request needing the whole pool forces the spill
    inter_p = rng.randint(0, gpt.cfg.vocab_size, (33,)).astype(np.int32)
    inter = engine.submit(inter_p, max_new_tokens=5)
    engine.drain()
    assert engine.preempted == 1 and engine.resumed == 1
    out_v, _ = dense.generate(jnp.asarray(victim_p[None, :]), 20,
                              scan_decode=False)
    np.testing.assert_array_equal(victim.result().new_tokens,
                                  np.asarray(out_v)[0, 9:])
    out_i, _ = dense.generate(jnp.asarray(inter_p[None, :]), 5,
                              scan_decode=False)
    np.testing.assert_array_equal(inter.result().new_tokens,
                                  np.asarray(out_i)[0, 33:])
    assert engine.cache.allocator.n_used == 0


def test_no_leak_with_sharing_under_faults(gpt, rng):
    """Fault injection with sharing live: a failed suffix prefill must
    decref (not double-free) its shared pages, and after retirement only
    the prefix cache's own references remain."""
    engine = _engine(gpt, prefix_sharing=True)
    p_shared = rng.randint(0, gpt.cfg.vocab_size, (16,)).astype(np.int32)
    f1 = engine.submit(p_shared, max_new_tokens=4)
    engine.drain()
    f1.result()
    p2 = np.concatenate([p_shared,
                         rng.randint(0, gpt.cfg.vocab_size, (5,)).astype(np.int32)])
    orig = engine.runner.chunk_cfn
    engine.runner.chunk_cfn = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("injected chunk failure"))
    f2 = engine.submit(p2, max_new_tokens=4)
    engine.drain()
    with pytest.raises(RuntimeError, match="injected"):
        f2.result(timeout=5)
    engine.runner.chunk_cfn = orig
    # the shared pages survived the failure (cache refs intact): retry hits
    f3 = engine.submit(p2, max_new_tokens=4)
    engine.drain()
    f3.result()
    assert engine.prefix_hits == 2                 # f2 and f3 both matched
    # only cache-held references remain; eviction returns the pool to empty
    assert engine.cache.allocator.n_used == len(engine.prefix)
    engine.prefix.clear()
    assert engine.cache.allocator.n_used == 0
    pages = engine.cache.allocator.alloc(engine.cache.n_pages - 1)
    engine.cache.allocator.free(pages)             # free-list fully consistent


def test_lane_validation_and_batch_fifo(gpt, rng):
    engine = _engine(gpt)
    p = rng.randint(0, gpt.cfg.vocab_size, (6,)).astype(np.int32)
    with pytest.raises(ValueError, match="lane"):
        engine.submit(p, max_new_tokens=2, lane="bulk").result(timeout=5)
    fut = engine.submit(p, max_new_tokens=3, lane="batch")
    engine.drain()
    assert fut.result().n_new_tokens == 3
